from tools.raftlint.cli import main

if __name__ == "__main__":
    raise SystemExit(main())
