"""raftlint dataflow engine: abstract interpretation over the Project.

PR 12's rules are syntactic — they match spellings. This module gives
the semantic rules (R10–R13) a shared abstract interpreter that
propagates a small lattice value per bound name through assignments,
calls, and ``lax`` control flow, using the per-module symbol tables and
import maps :mod:`tools.raftlint.core` already builds.

The lattice value (:class:`AV`) tracks, per name:

- ``shape``  — a tuple of per-dim ints (``None`` per unknown dim), or
  ``None`` when the rank itself is unknown;
- ``dtype``  — a canonical dtype string (``"float32"``, ``"bfloat16"``,
  ``"float64"``, …) or ``None``;
- ``donated`` — whether the value aliases a buffer some call donated
  (``donate_argnums``) — the bit R10 chases through loop carries;
- ``const``  — a known python literal (int/str/float/tuple) for shape
  arithmetic and axis-name / op-string resolution;
- ``func``   — :class:`FuncFacts` when the value is callable (a
  ``jax.jit(f, donate_argnums=…)`` result, a ``shard_map``-wrapped
  body, a resolved def), carrying donation positions and bound axis
  names;
- ``tags``   — origin markers (``"axis_index"``, ``"padded"``) that
  survive arithmetic, for the rank-divergence and padding-helper
  checks.

Everything joins conservatively: conflicting facts become unknown, so
rules fire only where the code is genuinely analyzable — the same
over-report-nothing posture as the syntactic rules.

Interprocedural: each function gets a TOP-argument summary (memoized;
recursion breaks to TOP), and control-flow carriers
(``lax.while_loop`` / ``scan`` / ``fori_loop`` / ``cond``) re-interpret
their body callables with the *actual* carry values, so a donated
carry keeps its donation bit through the loop and a collective inside
a cond arm is seen under the enclosing ``shard_map``'s axis scope.
Loops host-side are interpreted twice with a join back into the entry
environment (one widening pass), which is enough for the
straight-line-plus-carries shapes this codebase writes.

Rules consume the recorded event streams (:class:`CallEvent`,
:class:`BinopEvent`, :class:`CollectiveEvent`) rather than re-walking
the AST; :func:`analyze` memoizes per Project.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, FrozenSet, List, Optional, Sequence, Tuple

from tools.raftlint.core import (FunctionInfo, ModuleInfo, Project,
                                 dotted_parts)

MAX_DEPTH = 6               # interprocedural recursion bound

JIT_FQS = {"jax.jit", "jax.pjit", "jax.experimental.pjit.pjit"}
SHARD_MAP_FQS = {"jax.shard_map", "jax.experimental.shard_map.shard_map"}
MESH_FQS = {"jax.sharding.Mesh", "jax.interpreters.pxla.Mesh", "Mesh"}
PARTIAL_FQS = {"functools.partial", "partial"}

#: collective primitive → index of the axis-name argument (after the
#: operand), ``0`` when the axis name is the first positional arg
COLLECTIVES = {
    "jax.lax.psum": 1, "jax.lax.pmean": 1, "jax.lax.pmax": 1,
    "jax.lax.pmin": 1, "jax.lax.psum_scatter": 1,
    "jax.lax.all_gather": 1, "jax.lax.all_to_all": 1,
    "jax.lax.ppermute": 1, "jax.lax.axis_index": 0,
}

CTRL_FLOW = {
    "jax.lax.while_loop": (1, 2),      # (body position, init position)
    "jax.lax.fori_loop": (2, 3),
    "jax.lax.scan": (0, 1),
}

#: dtype spellings → canonical string
_DTYPES = {
    "float32": "float32", "float64": "float64", "float16": "float16",
    "bfloat16": "bfloat16", "int32": "int32", "int64": "int64",
    "int16": "int16", "int8": "int8", "uint8": "uint8",
    "bool": "bool", "bool_": "bool", "complex64": "complex64",
}
FLOAT_WIDTH = {"bfloat16": 16, "float16": 16, "float32": 32,
               "float64": 64}

#: array constructors whose (shape, dtype) args we can often fold
_SHAPED_CTORS = {
    "jax.numpy.zeros", "jax.numpy.ones", "jax.numpy.empty",
    "jax.numpy.full", "numpy.zeros", "numpy.ones", "numpy.empty",
    "numpy.full",
}

#: the sanctioned padding/alignment helpers — values produced through
#: them carry the "padded" tag R12 honors
PADDING_HELPERS = {
    "raft_tpu.util.math.round_up_to_multiple",
    "raft_tpu.matrix.epilogue.resolve_tn_sw",
    "raft_tpu.matrix.epilogue.best_width",
    "raft_tpu.linalg.contractions._pad2",
    "raft_tpu.util.pallas_utils.pad_dim",
}


@dataclasses.dataclass(frozen=True)
class FuncFacts:
    """What we statically know about a callable value."""

    symbol: Optional[str] = None        # module:qual of the body def
    donate: Tuple[int, ...] = ()        # donated positional indices
    static_names: FrozenSet[str] = frozenset()
    axes: Optional[FrozenSet[str]] = None   # shard_map-bound axis names
    kind: str = "plain"                 # plain | jit | shard_map


@dataclasses.dataclass
class AV:
    """One abstract value (see module docstring)."""

    shape: Optional[Tuple] = None
    dtype: Optional[str] = None
    donated: bool = False
    const: object = None
    func: Optional[FuncFacts] = None
    tags: FrozenSet[str] = frozenset()

    @staticmethod
    def top() -> "AV":
        return AV()

    def with_tag(self, tag: str) -> "AV":
        return dataclasses.replace(self, tags=self.tags | {tag})


TOP = AV.top()


def join(a: AV, b: AV) -> AV:
    """Lattice join: agreement survives, conflict goes unknown, the
    donation bit and tags accumulate (may-analysis)."""
    if a is b:
        return a
    shape = a.shape if a.shape == b.shape else (
        _join_shapes(a.shape, b.shape))
    return AV(
        shape=shape,
        dtype=a.dtype if a.dtype == b.dtype else None,
        donated=a.donated or b.donated,
        const=a.const if _const_eq(a.const, b.const) else None,
        func=a.func if a.func == b.func else None,
        tags=a.tags | b.tags)


def _const_eq(x, y) -> bool:
    return type(x) is type(y) and x == y


def _join_shapes(sa, sb):
    if sa is None or sb is None or len(sa) != len(sb):
        return None
    return tuple(x if x == y else None for x, y in zip(sa, sb))


def promote_dtype(a: Optional[str], b: Optional[str]) -> Optional[str]:
    """NumPy-style result dtype for arithmetic between floats — only
    the float×float case matters here (the promotion-hazard check)."""
    if a is None or b is None:
        return None
    if a == b:
        return a
    wa, wb = FLOAT_WIDTH.get(a), FLOAT_WIDTH.get(b)
    if wa is None or wb is None:
        return None
    if wa == wb:                    # bfloat16 × float16 → float32
        return a if a == b else "float32"
    return a if wa > wb else b


# -- event records -----------------------------------------------------------


@dataclasses.dataclass
class CallEvent:
    """One call site with resolved facts + abstract arguments."""

    fn: FunctionInfo                    # enclosing function (caller)
    node: ast.Call
    fq: Optional[str]                   # resolved dotted callee name
    facts: Optional[FuncFacts]          # callable-value facts, if any
    args: List[AV]
    keywords: Dict[str, AV]
    axes_scope: Optional[FrozenSet[str]]    # shard_map axes in scope


@dataclasses.dataclass
class BinopEvent:
    fn: FunctionInfo
    node: ast.AST
    left: AV
    right: AV
    result: AV


@dataclasses.dataclass
class CollectiveEvent:
    fn: FunctionInfo
    node: ast.Call
    fq: str
    axis: AV                            # abstract axis-name argument
    axes_scope: Optional[FrozenSet[str]]


@dataclasses.dataclass
class Summary:
    """Per-function interpretation result under TOP arguments."""

    env: Dict[str, AV]
    returns: AV


class DataflowResult:
    def __init__(self) -> None:
        self.calls: List[CallEvent] = []
        self.binops: List[BinopEvent] = []
        self.collectives: List[CollectiveEvent] = []
        self.summaries: Dict[str, Summary] = {}
        #: symbol → donation positions, for defs decorated with a
        #: donating jit (``@partial(jax.jit, donate_argnums=…)``)
        self.donating_defs: Dict[str, Tuple[int, ...]] = {}

    def summary(self, symbol: str) -> Optional[Summary]:
        return self.summaries.get(symbol)


# -- the interpreter ---------------------------------------------------------


class _Interp:
    def __init__(self, project: Project, result: DataflowResult) -> None:
        self.project = project
        self.result = result
        self.table = project.symbol_table()
        self._memo: Dict[str, Summary] = {}
        self._module_envs: Dict[str, Dict[str, AV]] = {}
        self._in_flight: set = set()

    # -- entry points -------------------------------------------------------

    def run(self) -> None:
        for mod in self.project.modules.values():
            self._collect_decorated(mod)
        for mod in self.project.modules.values():
            self.module_env(mod)
        for fn in self.project.iter_functions():
            self.top_summary(fn)

    def _collect_decorated(self, mod: ModuleInfo) -> None:
        for fn in mod.functions.values():
            for deco in getattr(fn.node, "decorator_list", []):
                facts = self._jit_facts_from_deco(mod, deco, fn)
                if facts and facts.donate:
                    self.result.donating_defs[fn.symbol] = facts.donate

    def _jit_facts_from_deco(self, mod: ModuleInfo, deco: ast.AST,
                             fn: FunctionInfo) -> Optional[FuncFacts]:
        """FuncFacts for a @jax.jit / @partial(jax.jit, …) decoration."""
        if isinstance(deco, ast.Call):
            fq = mod.resolve(deco.func)
            if fq in JIT_FQS:
                return self._facts_from_jit_kwargs(
                    deco.keywords, fn.symbol)
            if (fq in PARTIAL_FQS and deco.args
                    and mod.resolve(deco.args[0]) in JIT_FQS):
                return self._facts_from_jit_kwargs(
                    deco.keywords, fn.symbol)
        elif mod.resolve(deco) in JIT_FQS:
            return FuncFacts(symbol=fn.symbol, kind="jit")
        return None

    @staticmethod
    def _facts_from_jit_kwargs(keywords, symbol,
                               inner: Optional[FuncFacts] = None
                               ) -> FuncFacts:
        donate: Tuple[int, ...] = ()
        static: FrozenSet[str] = frozenset()
        for kw in keywords:
            lit = _literal(kw.value)
            if kw.arg in ("donate_argnums", "donate_argnames"):
                if isinstance(lit, int):
                    donate = (lit,)
                elif isinstance(lit, tuple) and all(
                        isinstance(v, int) for v in lit):
                    donate = tuple(lit)
                # non-literal positions → unknown → no donation facts
            elif kw.arg == "static_argnames":
                if isinstance(lit, str):
                    static = frozenset((lit,))
                elif isinstance(lit, tuple):
                    static = frozenset(v for v in lit
                                       if isinstance(v, str))
        axes = inner.axes if inner else None
        return FuncFacts(symbol=symbol, donate=donate,
                         static_names=static, axes=axes, kind="jit")

    # -- environments -------------------------------------------------------

    def module_env(self, mod: ModuleInfo) -> Dict[str, AV]:
        env = self._module_envs.get(mod.modname)
        if env is None:
            env = {}
            self._module_envs[mod.modname] = env    # cycle guard
            pseudo = FunctionInfo(mod, "<module>", mod.tree, None)
            self._exec_block(mod.tree.body, env, pseudo, None, 0)
        return env

    def top_summary(self, fn: FunctionInfo) -> Summary:
        got = self._memo.get(fn.symbol)
        if got is not None:
            return got
        if fn.symbol in self._in_flight:        # recursion → TOP
            return Summary({}, TOP)
        self._in_flight.add(fn.symbol)
        try:
            summ = self._interpret(fn, None, None, 1)
        finally:
            self._in_flight.discard(fn.symbol)
        self._memo[fn.symbol] = summ
        self.result.summaries[fn.symbol] = summ
        return summ

    def _param_names(self, fn: FunctionInfo) -> List[str]:
        a = getattr(fn.node, "args", None)
        if a is None:
            return []
        return [p.arg for p in a.posonlyargs + a.args]

    def _interpret(self, fn: FunctionInfo,
                   args: Optional[Sequence[AV]],
                   axes_scope: Optional[FrozenSet[str]],
                   depth: int) -> Summary:
        """Interpret one function body; ``args`` positionally seeds the
        parameters (None → all TOP). Records events as it goes."""
        if depth > MAX_DEPTH:
            return Summary({}, TOP)
        env: Dict[str, AV] = {}
        names = self._param_names(fn)
        body = getattr(fn.node, "body", [])
        if isinstance(fn.node, ast.Lambda):
            body = [ast.Return(value=fn.node.body,
                               lineno=fn.node.lineno,
                               col_offset=fn.node.col_offset)]
        donate = self.result.donating_defs.get(fn.symbol, ())
        for i, name in enumerate(names):
            av = TOP
            if args is not None and i < len(args):
                av = args[i]
            if i in donate:
                av = dataclasses.replace(av, donated=True)
            env[name] = av
        ret = _Ret()
        self._exec_block(body, env, fn, axes_scope, depth, ret)
        return Summary(env, ret.value if ret.seen else TOP)

    # -- statements ---------------------------------------------------------

    def _exec_block(self, stmts, env, fn, axes, depth, ret=None) -> None:
        for st in stmts:
            self._exec_stmt(st, env, fn, axes, depth, ret)

    def _exec_stmt(self, st, env, fn, axes, depth, ret) -> None:
        if isinstance(st, ast.Assign):
            val = self._eval(st.value, env, fn, axes, depth)
            for tgt in st.targets:
                self._bind(tgt, val, env, fn, axes, depth)
        elif isinstance(st, ast.AnnAssign) and st.value is not None:
            val = self._eval(st.value, env, fn, axes, depth)
            self._bind(st.target, val, env, fn, axes, depth)
        elif isinstance(st, ast.AugAssign):
            left = self._eval(st.target, env, fn, axes, depth)
            right = self._eval(st.value, env, fn, axes, depth)
            out = self._binop_result(st, left, right, fn)
            if isinstance(st.target, ast.Name):
                env[st.target.id] = out
        elif isinstance(st, ast.Return):
            val = (self._eval(st.value, env, fn, axes, depth)
                   if st.value is not None else TOP)
            if ret is not None:
                ret.add(val)
        elif isinstance(st, ast.Expr):
            self._eval(st.value, env, fn, axes, depth)
        elif isinstance(st, ast.If):
            test = self._eval(st.test, env, fn, axes, depth)
            del test
            benv = dict(env)
            self._exec_block(st.body, benv, fn, axes, depth, ret)
            oenv = dict(env)
            self._exec_block(st.orelse, oenv, fn, axes, depth, ret)
            _merge_branches(env, benv, oenv)
        elif isinstance(st, (ast.For, ast.While)):
            if isinstance(st, ast.For):
                self._bind(st.target, TOP, env, fn, axes, depth)
            else:
                self._eval(st.test, env, fn, axes, depth)
            # two passes with a join back into the loop-entry env: the
            # second pass sees the carried (widened) values, so a
            # changing carry settles at the join instead of looping
            for _ in range(2):
                lenv = dict(env)
                self._exec_block(st.body, lenv, fn, axes, depth, ret)
                for name, av in lenv.items():
                    env[name] = join(env[name], av) if name in env \
                        else av
            self._exec_block(st.orelse, env, fn, axes, depth, ret)
        elif isinstance(st, ast.With):
            for item in st.items:
                self._eval(item.context_expr, env, fn, axes, depth)
                if item.optional_vars is not None:
                    self._bind(item.optional_vars, TOP, env, fn, axes,
                               depth)
            self._exec_block(st.body, env, fn, axes, depth, ret)
        elif isinstance(st, ast.Try):
            self._exec_block(st.body, env, fn, axes, depth, ret)
            for h in st.handlers:
                henv = dict(env)
                self._exec_block(h.body, henv, fn, axes, depth, ret)
                _merge_branches(env, env.copy(), henv)
            self._exec_block(st.orelse, env, fn, axes, depth, ret)
            self._exec_block(st.finalbody, env, fn, axes, depth, ret)
        elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            local = fn.module.functions.get(
                f"{fn.qual}.{st.name}" if fn.qual != "<module>"
                else st.name)
            env[st.name] = AV(func=FuncFacts(
                symbol=local.symbol if local else None))
        # class defs / imports / del / raise add no dataflow facts

    def _bind(self, tgt, val: AV, env, fn, axes, depth) -> None:
        if isinstance(tgt, ast.Name):
            env[tgt.id] = val
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            parts = None
            if isinstance(val.const, tuple) and \
                    len(val.const) == len(tgt.elts):
                parts = [AV(const=c) for c in val.const]
            for i, el in enumerate(tgt.elts):
                if isinstance(el, ast.Starred):
                    el = el.value
                item = parts[i] if parts else dataclasses.replace(
                    val, shape=None, const=None, func=None)
                self._bind(el, item, env, fn, axes, depth)
        elif isinstance(tgt, ast.Starred):
            self._bind(tgt.value, val, env, fn, axes, depth)
        # attribute/subscript stores tracked nowhere (conservative)

    # -- expressions --------------------------------------------------------

    def _eval(self, node, env, fn, axes, depth) -> AV:
        if node is None:
            return TOP
        if isinstance(node, ast.Constant):
            v = node.value
            av = AV(const=v if isinstance(
                v, (int, float, str, bool)) else None)
            if isinstance(v, bool):
                av = dataclasses.replace(av, dtype="bool")
            elif isinstance(v, int):
                av = dataclasses.replace(av, dtype="int")
            elif isinstance(v, float):
                av = dataclasses.replace(av, dtype="float")
            return av
        if isinstance(node, (ast.Tuple, ast.List)):
            items = [self._eval(e, env, fn, axes, depth)
                     for e in node.elts]
            consts = tuple(i.const for i in items)
            return AV(const=consts if all(
                c is not None for c in consts) else None)
        if isinstance(node, ast.Name):
            if node.id in env:
                return env[node.id]
            menv = self._module_envs.get(fn.module.modname)
            if menv is not None and node.id in menv and \
                    node.id not in fn.module.functions:
                return menv[node.id]
            return self._resolve_name(node, fn)
        if isinstance(node, ast.Attribute):
            base = node.value
            if isinstance(base, ast.Name) and base.id in env:
                return TOP          # attribute of a local: unknown
            return self._resolve_name(node, fn)
        if isinstance(node, ast.Call):
            return self._eval_call(node, env, fn, axes, depth)
        if isinstance(node, ast.BinOp):
            left = self._eval(node.left, env, fn, axes, depth)
            right = self._eval(node.right, env, fn, axes, depth)
            return self._binop_result(node, left, right, fn)
        if isinstance(node, ast.UnaryOp):
            val = self._eval(node.operand, env, fn, axes, depth)
            if isinstance(node.op, ast.USub) and isinstance(
                    val.const, (int, float)):
                return dataclasses.replace(val, const=-val.const)
            return dataclasses.replace(val, const=None)
        if isinstance(node, ast.Compare):
            avs = [self._eval(node.left, env, fn, axes, depth)]
            avs += [self._eval(c, env, fn, axes, depth)
                    for c in node.comparators]
            tags = frozenset().union(*(a.tags for a in avs))
            return AV(dtype="bool", tags=tags)
        if isinstance(node, ast.BoolOp):
            avs = [self._eval(v, env, fn, axes, depth)
                   for v in node.values]
            out = avs[0]
            for a in avs[1:]:
                out = join(out, a)
            return out
        if isinstance(node, ast.IfExp):
            self._eval(node.test, env, fn, axes, depth)
            return join(self._eval(node.body, env, fn, axes, depth),
                        self._eval(node.orelse, env, fn, axes, depth))
        if isinstance(node, ast.Subscript):
            base = self._eval(node.value, env, fn, axes, depth)
            idx = self._eval(node.slice, env, fn, axes, depth)
            if isinstance(base.const, tuple) and isinstance(
                    idx.const, int) and -len(base.const) <= idx.const \
                    < len(base.const):
                return AV(const=base.const[idx.const],
                          tags=base.tags)
            return dataclasses.replace(base, const=None, func=None,
                                       shape=None)
        if isinstance(node, ast.Starred):
            return self._eval(node.value, env, fn, axes, depth)
        if isinstance(node, ast.Lambda):
            return AV(func=FuncFacts(symbol=None))
        return TOP

    def _resolve_name(self, node, fn: FunctionInfo) -> AV:
        """A free Name/Attribute: dtype literals, resolved defs, module
        constants of other modules."""
        mod = fn.module
        parts = dotted_parts(node)
        fq = mod.resolve_local(node)
        if fq is not None:
            dt = _dtype_from_fq(fq)
            if dt is not None:
                return AV(const=dt, dtype=dt)
            target = self.project.function_by_fq(fq)
            if target is not None:
                donate = self.result.donating_defs.get(
                    target.symbol, ())
                return AV(func=FuncFacts(symbol=target.symbol,
                                         donate=donate,
                                         kind="jit" if donate
                                         else "plain"))
            # module-level constant in a scanned module?
            cut = fq.rfind(".")
            if cut > 0:
                other = self.project.modules.get(fq[:cut])
                if other is not None and other is not mod:
                    oenv = self._module_envs.get(other.modname)
                    if oenv is not None and fq[cut + 1:] in oenv:
                        return oenv[fq[cut + 1:]]
        if parts and len(parts) == 1:
            local = mod.functions.get(parts[0])
            if local is not None:
                donate = self.result.donating_defs.get(
                    local.symbol, ())
                return AV(func=FuncFacts(symbol=local.symbol,
                                         donate=donate))
        return TOP

    def _binop_result(self, node, left: AV, right: AV,
                      fn: FunctionInfo) -> AV:
        const = None
        if isinstance(left.const, (int, float)) and isinstance(
                right.const, (int, float)):
            try:
                const = _fold(node.op, left.const, right.const)
            except (ZeroDivisionError, TypeError, ValueError,
                    OverflowError):
                const = None
        dtype = promote_dtype(left.dtype, right.dtype)
        out = AV(dtype=dtype, const=const,
                 donated=left.donated or right.donated,
                 tags=left.tags | right.tags)
        self.result.binops.append(BinopEvent(fn, node, left, right,
                                             out))
        return out

    # -- calls --------------------------------------------------------------

    def _eval_call(self, node: ast.Call, env, fn, axes, depth) -> AV:
        mod = fn.module
        func_av = self._eval(node.func, env, fn, axes, depth)
        fq = mod.resolve_local(node.func)
        if fq is None and isinstance(node.func, ast.Name) and \
                node.func.id in env and env[node.func.id].func and \
                env[node.func.id].func.symbol:
            pass                            # facts carry the target
        args = [self._eval(a, env, fn, axes, depth)
                for a in node.args if not isinstance(a, ast.Starred)]
        starred = any(isinstance(a, ast.Starred) for a in node.args)
        for a in node.args:
            if isinstance(a, ast.Starred):
                self._eval(a.value, env, fn, axes, depth)
        kwargs = {kw.arg: self._eval(kw.value, env, fn, axes, depth)
                  for kw in node.keywords if kw.arg is not None}

        facts = func_av.func
        self.result.calls.append(CallEvent(
            fn, node, fq, facts, args if not starred else args,
            kwargs, axes))

        # -- special forms ---------------------------------------------
        if fq in JIT_FQS and node.args:
            inner = args[0].func if args else None
            out = self._facts_from_jit_kwargs(
                node.keywords,
                inner.symbol if inner else None, inner)
            return AV(func=out)
        if fq in PARTIAL_FQS and node.args:
            inner_fq = mod.resolve(node.args[0])
            if inner_fq in JIT_FQS:
                return AV(func=self._facts_from_jit_kwargs(
                    node.keywords, None))
            if args and args[0].func is not None:
                return args[0]          # partial(f, …) keeps f's facts
        if fq in SHARD_MAP_FQS:
            body_facts = args[0].func if args else None
            mesh_axes = None
            mesh_av = kwargs.get("mesh") or (args[1] if len(args) > 1
                                             else None)
            if mesh_av is not None and mesh_av.func and \
                    mesh_av.func.axes:
                mesh_axes = mesh_av.func.axes
            if mesh_av is not None and mesh_av.const is None and \
                    mesh_axes is None and isinstance(
                        mesh_av.tags, frozenset):
                for t in mesh_av.tags:
                    if t.startswith("mesh:"):
                        mesh_axes = frozenset(
                            t[len("mesh:"):].split(","))
            return AV(func=FuncFacts(
                symbol=body_facts.symbol if body_facts else None,
                axes=mesh_axes, kind="shard_map"))
        if fq in MESH_FQS or (fq or "").endswith(".Mesh"):
            ax = kwargs.get("axis_names") or (args[1] if len(args) > 1
                                              else None)
            names = None
            if ax is not None:
                if isinstance(ax.const, str):
                    names = frozenset((ax.const,))
                elif isinstance(ax.const, tuple) and all(
                        isinstance(v, str) for v in ax.const):
                    names = frozenset(ax.const)
            if names:
                return AV(tags=frozenset(
                    ("mesh:" + ",".join(sorted(names)),)))
            return TOP
        if fq in COLLECTIVES:
            pos = COLLECTIVES[fq]
            axis_av = kwargs.get("axis_name") or kwargs.get("axis") \
                or (args[pos] if len(args) > pos else TOP)
            self.result.collectives.append(CollectiveEvent(
                fn, node, fq, axis_av, axes))
            if fq == "jax.lax.axis_index":
                return AV(dtype="int32", tags=frozenset(
                    ("axis_index",)))
            return args[0] if args else TOP
        if fq in CTRL_FLOW:
            body_pos, init_pos = CTRL_FLOW[fq]
            body_av = args[body_pos] if len(args) > body_pos else TOP
            init_av = args[init_pos] if len(args) > init_pos else TOP
            return self._apply(body_av.func, [init_av], axes,
                               depth) or init_av
        if fq == "jax.lax.cond":
            outs = []
            for branch in args[1:3]:
                got = self._apply(branch.func,
                                  [a for a in args[3:]], axes, depth)
                if got is not None:
                    outs.append(got)
            if outs:
                out = outs[0]
                for o in outs[1:]:
                    out = join(out, o)
                return out
            return TOP
        if fq in PADDING_HELPERS or (
                fq or "").rsplit(".", 1)[-1] in (
                    "round_up_to_multiple", "resolve_tn_sw"):
            base = args[0] if args else TOP
            return dataclasses.replace(
                base, const=None, tags=base.tags | {"padded"})
        if fq in _SHAPED_CTORS:
            shape_av = args[0] if args else kwargs.get("shape", TOP)
            dtype_av = kwargs.get("dtype") or (
                args[1] if fq.endswith((".zeros", ".ones", ".empty"))
                and len(args) > 1 else
                args[2] if len(args) > 2 else None)
            shape = None
            if isinstance(shape_av.const, tuple) and all(
                    isinstance(v, int) for v in shape_av.const):
                shape = shape_av.const
            elif isinstance(shape_av.const, int):
                shape = (shape_av.const,)
            dt = _dtype_of_av(dtype_av)
            if dt is None and dtype_av is None and \
                    fq.startswith("jax."):
                dt = "float32"      # jnp default; an EXPLICIT but
                # unresolvable dtype arg must stay unknown, and numpy
                # ctors (host-side f64 world) never default
            return AV(shape=shape, dtype=dt)
        if fq in ("jax.numpy.asarray", "jax.numpy.array",
                  "numpy.asarray", "numpy.array"):
            base = args[0] if args else TOP
            dt = _dtype_of_av(kwargs.get("dtype") or (
                args[1] if len(args) > 1 else None))
            return AV(shape=base.shape, dtype=dt or base.dtype,
                      tags=base.tags)
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr == "astype":
            base = self._eval(node.func.value, env, fn, axes, depth)
            dt = _dtype_of_av(args[0] if args else None)
            return dataclasses.replace(base, dtype=dt, const=None)

        # -- resolved project function: interprocedural ------------------
        target_sym = None
        if facts is not None and facts.symbol:
            target_sym = facts.symbol
        elif fq is not None:
            t = self.project.function_by_fq(fq)
            if t is not None:
                target_sym = t.symbol
        if target_sym is not None:
            target = self.table.get(target_sym)
            if target is not None:
                inner_axes = axes
                if facts is not None and facts.axes is not None:
                    inner_axes = (facts.axes if axes is None
                                  else axes | facts.axes)
                donated_args = args
                if facts is not None and facts.donate:
                    donated_args = list(args)
                    for i in facts.donate:
                        if i < len(donated_args):
                            donated_args[i] = dataclasses.replace(
                                donated_args[i], donated=True)
                if args is not None and (
                        any(a is not TOP for a in donated_args)
                        or inner_axes is not None):
                    summ = self._interpret(target, donated_args,
                                           inner_axes, depth + 1)
                else:
                    summ = self.top_summary(target)
                return summ.returns
        return TOP

    def _apply(self, facts: Optional[FuncFacts], args: List[AV],
               axes, depth) -> Optional[AV]:
        """Interpret a callable value with explicit args (the lax
        control-flow body path). None when the target is unknown."""
        if facts is None or facts.symbol is None:
            return None
        target = self.table.get(facts.symbol)
        if target is None:
            return None
        inner_axes = axes
        if facts.axes is not None:
            inner_axes = (facts.axes if axes is None
                          else axes | facts.axes)
        return self._interpret(target, args, inner_axes,
                               depth + 1).returns


class _Ret:
    def __init__(self) -> None:
        self.seen = False
        self.value = TOP

    def add(self, av: AV) -> None:
        self.value = av if not self.seen else join(self.value, av)
        self.seen = True


def _merge_branches(env, a, b) -> None:
    for name in set(a) | set(b):
        if name in a and name in b:
            env[name] = join(a[name], b[name])
        else:
            present = a.get(name, b.get(name))
            env[name] = join(env[name], present) if name in env \
                else present


def _literal(node):
    """Fold a Constant / tuple-of-Constant AST node to python."""
    if isinstance(node, ast.Constant):
        return node.value
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = [_literal(e) for e in node.elts]
        if all(v is not None for v in vals):
            return tuple(vals)
    return None


def _fold(op, a, b):
    if isinstance(op, ast.Add):
        return a + b
    if isinstance(op, ast.Sub):
        return a - b
    if isinstance(op, ast.Mult):
        return a * b
    if isinstance(op, ast.FloorDiv):
        return a // b
    if isinstance(op, ast.Mod):
        return a % b
    if isinstance(op, ast.Div):
        return a / b
    raise ValueError("unfoldable")


def _dtype_from_fq(fq: str) -> Optional[str]:
    tail = fq.rsplit(".", 1)[-1]
    root = fq.split(".", 1)[0]
    if root in ("jax", "numpy") and tail in _DTYPES:
        return _DTYPES[tail]
    return None


def _dtype_of_av(av: Optional[AV]) -> Optional[str]:
    if av is None:
        return None
    if isinstance(av.const, str) and av.const in _DTYPES:
        return _DTYPES[av.const]
    if av.dtype in _DTYPES:
        return av.dtype
    return None


# -- utilities shared by the rules -------------------------------------------


def parent_map(fn: FunctionInfo) -> Dict[ast.AST, ast.AST]:
    parents: Dict[ast.AST, ast.AST] = {}
    for parent in ast.walk(fn.node):
        for child in ast.iter_child_nodes(parent):
            parents[child] = parent
    return parents


def _pos(node) -> Tuple[int, int]:
    return (getattr(node, "end_lineno", node.lineno),
            getattr(node, "end_col_offset", node.col_offset))


def reads_after(fn: FunctionInfo, call: ast.Call, name: str,
                ) -> Optional[ast.Name]:
    """First lexical READ of ``name`` after ``call`` inside ``fn`` that
    is not preceded by a rebind — the use-after-donate witness. Lexical
    order approximates execution order (good enough for the
    straight-line bodies the donation idiom lives in); the containing
    statement of the call itself is excluded, so ``x = f(x)`` stays
    clean."""
    cpos = _pos(call)
    own = {id(n) for n in ast.walk(call)}
    first_read = first_store = None
    for node in ast.walk(fn.node):
        if not isinstance(node, ast.Name) or node.id != name:
            continue
        if id(node) in own:
            continue
        npos = (node.lineno, node.col_offset)
        if npos <= cpos:
            continue
        if isinstance(node.ctx, ast.Store):
            if first_store is None or npos < _pos_key(first_store):
                first_store = node
        elif isinstance(node.ctx, ast.Load):
            if first_read is None or npos < _pos_key(first_read):
                first_read = node
    if first_read is None:
        return None
    if first_store is not None and \
            _pos_key(first_store) < _pos_key(first_read):
        return None
    return first_read


def _pos_key(node) -> Tuple[int, int]:
    return (node.lineno, node.col_offset)


def enclosing_loop(parents: Dict[ast.AST, ast.AST],
                   node: ast.AST) -> Optional[ast.AST]:
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.For, ast.While)):
            return cur
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            return None
        cur = parents.get(cur)
    return None


def stores_in(node: ast.AST, name: str) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id == name and \
                isinstance(sub.ctx, ast.Store):
            return True
    return False


# -- entry point -------------------------------------------------------------


def analyze(project: Project) -> DataflowResult:
    """Run (or fetch the memoized) dataflow analysis for a Project."""
    got = getattr(project, "_raftlint_dataflow", None)
    if got is not None:
        return got
    result = DataflowResult()
    _Interp(project, result).run()
    project._raftlint_dataflow = result
    return result
