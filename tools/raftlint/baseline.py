"""Baseline: waive pre-existing violations per (rule, file, symbol).

The key is deliberately the SYMBOL, never a line number: symbols
survive refactors that move code around inside a file, so the baseline
does not rot on every edit — and a waiver cannot silently start
covering a *new* violation of the same rule elsewhere in the file.

Every entry carries a one-line ``why``. Stale entries (the violation
they waive no longer exists) FAIL the run by default: a fixed debt must
be deleted from the baseline in the same change, keeping the file an
exact inventory of the remaining debt (--no-baseline prints it all).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Sequence, Set, Tuple

from tools.raftlint.core import Finding

DEFAULT_PATH = os.path.join(os.path.dirname(__file__), "baseline.json")

Key = Tuple[str, str, str]          # (rule, file, symbol)


class Baseline:
    def __init__(self, entries: List[dict]) -> None:
        self.entries = entries
        self.by_key: Dict[Key, dict] = {
            (e["rule"], e["file"], e["symbol"]): e for e in entries}

    @classmethod
    def load(cls, path: str) -> "Baseline":
        with open(path, "r", encoding="utf-8") as fh:
            doc = json.load(fh)
        entries = doc["entries"] if isinstance(doc, dict) else doc
        for e in entries:
            missing = {"rule", "file", "symbol", "why"} - set(e)
            if missing:
                raise ValueError(
                    f"baseline entry {e!r} missing {sorted(missing)}")
            if "line" in e:
                raise ValueError(
                    "baseline entries waive per (rule, file, symbol), "
                    f"never per line: {e!r}")
            why = str(e["why"]).strip()
            if not why or why.upper().startswith("TODO"):
                raise ValueError(
                    "baseline entry for "
                    f"({e['rule']}, {e['file']}, {e['symbol']}) still "
                    f"carries the --write-baseline placeholder why "
                    f"({e['why']!r}); a waiver ships with a real "
                    "justification or not at all")
        return cls(entries)

    @classmethod
    def empty(cls) -> "Baseline":
        return cls([])

    def split(self, findings: Sequence[Finding]):
        """(new, waived, stale_entries) for this run."""
        new: List[Finding] = []
        waived: List[Finding] = []
        hit: Set[Key] = set()
        for f in findings:
            if f.key() in self.by_key:
                waived.append(f)
                hit.add(f.key())
            else:
                new.append(f)
        stale = [e for k, e in self.by_key.items() if k not in hit]
        return new, waived, stale

    @staticmethod
    def render(findings: Sequence[Finding]) -> str:
        """A baseline JSON document waiving exactly these findings —
        what --write-baseline emits (the 'why' fields start empty and
        must be filled in by hand)."""
        seen: Set[Key] = set()
        entries = []
        for f in findings:
            if f.key() in seen:
                continue
            seen.add(f.key())
            entries.append({"rule": f.rule, "file": f.path,
                            "symbol": f.symbol,
                            "why": "TODO: justify this waiver"})
        return json.dumps({"version": 1, "entries": entries}, indent=2,
                          sort_keys=False) + "\n"
