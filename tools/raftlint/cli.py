"""raftlint command line.

    python -m tools.raftlint [paths...]        # or just: raftlint
    raftlint --no-baseline raft_tpu/           # full debt, ignore waivers
    raftlint --rules R4,R6 raft_tpu/comms/     # subset
    raftlint --write-baseline                  # regenerate waiver file

Exit codes: 0 clean, 1 new violations or stale baseline entries,
2 usage error (argparse). CI treats 1 as a gate failure; stale entries
fail so the baseline stays an exact inventory of the remaining debt.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List

from tools.raftlint.baseline import DEFAULT_PATH, Baseline
from tools.raftlint.cache import FileCache
from tools.raftlint.core import Finding, Project
from tools.raftlint.rules import ALL_RULES

DEFAULT_PATHS = ("raft_tpu",)


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="raftlint",
        description="AST-level invariant checker for the raft_tpu tree")
    ap.add_argument("paths", nargs="*", default=list(DEFAULT_PATHS),
                    help="files or directories to scan "
                         f"(default: {'/'.join(DEFAULT_PATHS)}/)")
    ap.add_argument("--root", default=os.getcwd(),
                    help="repo root paths are relative to "
                         "(default: cwd)")
    ap.add_argument("--baseline", default=DEFAULT_PATH,
                    help="baseline JSON waiving pre-existing "
                         "violations per (rule, file, symbol)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline and report the full "
                         "debt")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids to run "
                         "(default: all)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--write-baseline", nargs="?", const=DEFAULT_PATH,
                    default=None, metavar="PATH",
                    help="write a baseline waiving every current "
                         "finding, then exit 0 (fill in the why "
                         "fields)")
    ap.add_argument("--no-cache", action="store_true",
                    help="parse and analyze from scratch, ignoring "
                         "and not writing .raftlint_cache/")
    return ap


def run_rules(project: Project, rule_ids=None) -> List[Finding]:
    findings: List[Finding] = []
    for rule_cls in ALL_RULES:
        if rule_ids and rule_cls.id not in rule_ids:
            continue
        findings.extend(rule_cls().run(project))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        for rule in ALL_RULES:
            print(f"{rule.id}  {rule.summary}")
            print(f"    protects: {rule.rationale}")
        return 0

    rule_ids = None
    if args.rules:
        rule_ids = {r.strip().upper() for r in args.rules.split(",")
                    if r.strip()}
        known = {r.id for r in ALL_RULES}
        bad = rule_ids - known
        if bad:
            print(f"raftlint: unknown rule id(s): {sorted(bad)} "
                  f"(known: {sorted(known)})", file=sys.stderr)
            return 2

    cache = None if args.no_cache else FileCache(args.root)
    project = Project(args.root, cache=cache)
    project.scan(args.paths)
    if project.errors:
        for err in project.errors:
            print(f"raftlint: {err}", file=sys.stderr)
        return 2

    findings = None
    run_key = None
    if cache is not None:
        # warm clean run: replay the memoized findings for this exact
        # (file-contents, rule-selection) set without analyzing
        run_key = cache.run_key(sorted(rule_ids) if rule_ids else None)
        findings = cache.get_findings(run_key)
    if findings is None:
        findings = run_rules(project, rule_ids)
        if cache is not None and run_key is not None:
            cache.put_findings(run_key, findings)

    if args.write_baseline is not None:
        with open(args.write_baseline, "w", encoding="utf-8") as fh:
            fh.write(Baseline.render(findings))
        print(f"raftlint: wrote {len(findings)} waiver(s) to "
              f"{args.write_baseline}")
        return 0

    if args.no_baseline:
        for f in findings:
            print(f.render())
        print(f"raftlint: {len(findings)} finding(s) with no baseline "
              f"applied ({len(project.modules)} modules scanned)")
        return 1 if findings else 0

    try:
        baseline = (Baseline.load(args.baseline)
                    if os.path.exists(args.baseline) else
                    Baseline.empty())
    except (ValueError, KeyError, OSError) as e:
        print(f"raftlint: bad baseline {args.baseline}: {e}",
              file=sys.stderr)
        return 2

    new, waived, stale = baseline.split(findings)
    # a stale entry for a file outside this scan is not evidence the
    # debt was paid — only fail stale entries we could have re-observed
    scanned = {m.relpath for m in project.modules.values()}
    stale = [e for e in stale if e["file"] in scanned]

    for f in new:
        print(f.render())
    for e in stale:
        print(f"{e['file']}: stale baseline entry "
              f"({e['rule']}, {e['symbol']}): the violation it waives "
              "no longer exists — delete it from the baseline")
    status = (f"raftlint: {len(new)} new finding(s), "
              f"{len(waived)} waived by baseline, "
              f"{len(stale)} stale entr(ies) "
              f"({len(project.modules)} modules scanned)")
    print(status)
    return 1 if new or stale else 0


if __name__ == "__main__":
    sys.exit(main())
