"""raftlint on-disk cache: parsed modules + whole-run findings.

Two levels, both keyed by content so invalidation is automatic:

1. **Per-file** — a pickled :class:`~tools.raftlint.core.ModuleInfo`
   (AST + import map + symbol/lock tables) keyed by the sha256 of the
   file's source, so an edit to one module re-parses one module.
2. **Per-run** — the full findings list keyed by the sha256 of the
   sorted (relpath, source-hash) set plus the active rule ids, so the
   common CI case — warm cache, clean tree — skips analysis entirely
   and replays the memoized findings.

Both levels additionally key on a *tool version hash* folded from every
``tools/raftlint`` source file: changing a rule, the dataflow engine,
or the core indexes orphans every cached artifact at once. Entries are
written atomically (tmp + rename) and corrupt/unreadable entries read
as misses, so the cache can never make a run wrong — only faster. The
``--no-cache`` flag simply constructs no cache.

The cache lives under ``<root>/.raftlint_cache/`` (gitignored).
"""

from __future__ import annotations

import hashlib
import os
import pickle
import shutil
import tempfile
from typing import List, Optional, Sequence, Tuple

from tools.raftlint.core import Finding, ModuleInfo

CACHE_DIR_NAME = ".raftlint_cache"
_MAX_FILE_ENTRIES = 4096        # runaway backstop, not an LRU


def _tool_version_hash() -> str:
    """sha256 over every .py source in tools/raftlint — any change to
    the tool invalidates everything it previously produced."""
    here = os.path.dirname(os.path.abspath(__file__))
    h = hashlib.sha256()
    for dirpath, dirnames, filenames in sorted(os.walk(here)):
        dirnames[:] = sorted(d for d in dirnames
                             if d != "__pycache__")
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            fp = os.path.join(dirpath, name)
            h.update(os.path.relpath(fp, here).encode())
            try:
                with open(fp, "rb") as fh:
                    h.update(fh.read())
            except OSError:
                h.update(b"<unreadable>")
    return h.hexdigest()[:16]


def source_hash(source: str) -> str:
    return hashlib.sha256(source.encode("utf-8")).hexdigest()[:24]


class FileCache:
    """Content-addressed store for ModuleInfo pickles and run memos."""

    def __init__(self, root: str) -> None:
        self.dir = os.path.join(os.path.abspath(root), CACHE_DIR_NAME)
        self.version = _tool_version_hash()
        self.files_dir = os.path.join(self.dir, "files", self.version)
        self.runs_dir = os.path.join(self.dir, "runs", self.version)
        self.hits = 0
        self.misses = 0
        #: (relpath, source-hash) of everything seen this run — the
        #: run-memo key folds over it
        self.seen: List[Tuple[str, str]] = []
        self._gc_stale_versions()

    def _gc_stale_versions(self) -> None:
        """Drop artifacts from older tool versions — they can never hit
        again, so the cache dir stays bounded across upgrades."""
        for sub in ("files", "runs"):
            base = os.path.join(self.dir, sub)
            try:
                for v in os.listdir(base):
                    if v != self.version:
                        shutil.rmtree(os.path.join(base, v),
                                      ignore_errors=True)
            except OSError:
                pass

    # -- per-file level ------------------------------------------------------

    def _file_path(self, rel: str, shash: str) -> str:
        name = hashlib.sha256(rel.encode()).hexdigest()[:16]
        return os.path.join(self.files_dir, f"{name}-{shash}.pkl")

    def get(self, rel: str, source: str) -> Optional[ModuleInfo]:
        shash = source_hash(source)
        self.seen.append((rel, shash))
        try:
            with open(self._file_path(rel, shash), "rb") as fh:
                info = pickle.load(fh)
        except (OSError, pickle.PickleError, EOFError, AttributeError,
                ImportError, IndexError):
            self.misses += 1
            return None
        if not isinstance(info, ModuleInfo):
            self.misses += 1
            return None
        self.hits += 1
        return info

    def put(self, rel: str, source: str, info: ModuleInfo) -> None:
        self._atomic_dump(info, self._file_path(
            rel, source_hash(source)))

    # -- per-run level -------------------------------------------------------

    def run_key(self, rule_ids: Optional[Sequence[str]]) -> str:
        """Key for the findings memo: every scanned file's content hash
        plus the rule selection. Call after Project.scan()."""
        h = hashlib.sha256()
        for rel, shash in sorted(self.seen):
            h.update(f"{rel}={shash};".encode())
        rules = ",".join(sorted(rule_ids)) if rule_ids else "ALL"
        h.update(rules.encode())
        return h.hexdigest()[:24]

    def get_findings(self, key: str) -> Optional[List[Finding]]:
        try:
            with open(os.path.join(self.runs_dir, key + ".pkl"),
                      "rb") as fh:
                out = pickle.load(fh)
        except (OSError, pickle.PickleError, EOFError, AttributeError,
                ImportError, IndexError):
            return None
        if not isinstance(out, list) or not all(
                isinstance(f, Finding) for f in out):
            return None
        return out

    def put_findings(self, key: str, findings: List[Finding]) -> None:
        self._atomic_dump(findings,
                          os.path.join(self.runs_dir, key + ".pkl"))

    # -- plumbing ------------------------------------------------------------

    def _atomic_dump(self, obj, path: str) -> None:
        d = os.path.dirname(path)
        try:
            os.makedirs(d, exist_ok=True)
            if len(os.listdir(d)) >= _MAX_FILE_ENTRIES:
                return                      # full: stop growing
            fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
            try:
                with os.fdopen(fd, "wb") as fh:
                    pickle.dump(obj, fh, pickle.HIGHEST_PROTOCOL)
                os.replace(tmp, path)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
        except (OSError, pickle.PickleError):
            pass                            # cache is best-effort
