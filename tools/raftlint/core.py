"""raftlint shared visitor core.

One parse of the tree feeds every rule: a :class:`Project` holds per-module
ASTs plus the three cross-cutting indexes the rules share —

- an import map per module (alias → fully-qualified name), giving cheap
  qualified-name resolution for dotted expressions (``jnp.sqrt`` →
  ``jax.numpy.sqrt``) without executing anything;
- a function table keyed by ``module:Class.method`` symbols (the same
  symbol spelling the baseline waives on — symbols survive line churn,
  line numbers do not);
- per-class lock/field maps (which ``self.X`` attributes hold
  ``threading.Lock/RLock/Condition`` objects) for the lock-discipline
  rule;
- a best-effort call graph (same-module calls, ``self.`` method calls,
  and cross-module calls resolvable through the import map) that the
  jit-purity rule closes over from its seeds.

Everything is conservative-by-construction: unresolvable names resolve
to ``None`` and drop out, so rules over-report only where the code is
genuinely analyzable.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

LOCK_FACTORIES = (
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Semaphore", "threading.BoundedSemaphore",
)


@dataclasses.dataclass
class Finding:
    """One rule violation: location + the symbol key the baseline uses."""

    rule: str
    path: str                 # repo-relative posix path
    line: int
    col: int
    symbol: str               # "pkg.module:Class.method" | "pkg.module:<module>"
    message: str
    hint: str = ""

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.symbol)

    def render(self) -> str:
        text = (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"[{self.symbol}] {self.message}")
        if self.hint:
            text += f"\n    hint: {self.hint}"
        return text


@dataclasses.dataclass
class FunctionInfo:
    """A def (or method) with enough context to name and analyze it."""

    module: "ModuleInfo"
    qual: str                 # "Class.method", "func", "outer.inner"
    node: ast.AST             # FunctionDef | AsyncFunctionDef | Lambda
    class_name: Optional[str] = None

    @property
    def symbol(self) -> str:
        return f"{self.module.modname}:{self.qual}"

    @property
    def name(self) -> str:
        return self.qual.rsplit(".", 1)[-1]


@dataclasses.dataclass
class ClassInfo:
    module: "ModuleInfo"
    name: str
    node: ast.ClassDef
    lock_attrs: Set[str] = dataclasses.field(default_factory=set)
    methods: Dict[str, FunctionInfo] = dataclasses.field(
        default_factory=dict)


class ModuleInfo:
    """One parsed source file plus its local indexes."""

    def __init__(self, path: str, relpath: str, modname: str,
                 source: str, is_package: bool = False) -> None:
        self.path = path
        self.relpath = relpath
        self.modname = modname
        self.source = source
        self.is_package = is_package
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=relpath)
        self.imports: Dict[str, str] = {}
        self.functions: Dict[str, FunctionInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}
        self._index()

    # -- indexing -----------------------------------------------------------

    def _index(self) -> None:
        pkg = self.modname.rsplit(".", 1)[0] if "." in self.modname else ""
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    name = alias.asname or alias.name.split(".", 1)[0]
                    self.imports[name] = (alias.name if alias.asname
                                          else alias.name.split(".", 1)[0])
                    if alias.asname:
                        self.imports[alias.asname] = alias.name
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:          # relative import
                    parts = self.modname.split(".")
                    # level=1 → current package; each extra level pops
                    # one. For an __init__.py the modname IS its
                    # package, so level=1 keeps every part.
                    drop = node.level - 1 if self.is_package \
                        else node.level
                    anchor = parts[:len(parts) - drop] if drop \
                        else parts
                    base = ".".join(anchor + ([node.module]
                                              if node.module else []))
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    bound = alias.asname or alias.name
                    self.imports[bound] = (f"{base}.{alias.name}"
                                           if base else alias.name)
        self._walk_defs(self.tree, [], None)
        for cls in self.classes.values():
            self._find_locks(cls)
        del pkg

    def _walk_defs(self, node: ast.AST, stack: List[str],
                   cls: Optional[ClassInfo]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = ".".join(stack + [child.name])
                info = FunctionInfo(self, qual, child,
                                    cls.name if cls else None)
                self.functions[qual] = info
                if cls is not None and len(stack) == 1:
                    cls.methods[child.name] = info
                self._walk_defs(child, stack + [child.name], cls)
            elif isinstance(child, ast.ClassDef):
                cinfo = ClassInfo(self, child.name, child)
                self.classes[child.name] = cinfo
                self._walk_defs(child, stack + [child.name], cinfo)
            else:
                self._walk_defs(child, stack, cls)

    def _find_locks(self, cls: ClassInfo) -> None:
        """Attributes assigned a threading lock anywhere in the class, plus
        Condition aliases (``self._cond = threading.Condition(self._lock)``
        makes both names lock-like)."""
        for meth in cls.methods.values():
            for node in ast.walk(meth.node):
                if not isinstance(node, ast.Assign):
                    continue
                if not isinstance(node.value, ast.Call):
                    continue
                fq = self.resolve(node.value.func)
                if fq not in LOCK_FACTORIES:
                    continue
                for tgt in node.targets:
                    if (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"):
                        cls.lock_attrs.add(tgt.attr)

    # -- name resolution ----------------------------------------------------

    def resolve(self, node: ast.AST) -> Optional[str]:
        """Resolve a Name/Attribute chain to a dotted fully-qualified name
        through the import map; None when the chain is not static or the
        root is a plain local name."""
        parts = dotted_parts(node)
        if not parts:
            return None
        root = self.imports.get(parts[0])
        if root is None:
            return None
        return ".".join([root] + parts[1:])

    def resolve_local(self, node: ast.AST) -> Optional[str]:
        """Like resolve(), but a bare unimported root name maps to a
        module-level def in THIS module when one exists."""
        fq = self.resolve(node)
        if fq is not None:
            return fq
        parts = dotted_parts(node)
        if parts and len(parts) == 1 and parts[0] in self.functions:
            return f"{self.modname}.{parts[0]}"
        return None


def dotted_parts(node: ast.AST) -> Optional[List[str]]:
    """["jnp", "linalg", "norm"] for jnp.linalg.norm; None if the chain
    has a non-Name root (call results, subscripts, ...)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return list(reversed(parts))


def self_attr_chain(node: ast.AST) -> Optional[List[str]]:
    """["stats", "rejected"] for self.stats.rejected; None unless the
    chain is rooted at the name ``self``."""
    parts = dotted_parts(node)
    if parts and parts[0] == "self" and len(parts) > 1:
        return parts[1:]
    return None


def body_statements(fn: ast.AST) -> List[ast.stmt]:
    """The function body minus a leading docstring expression."""
    body = list(getattr(fn, "body", []))
    if (body and isinstance(body[0], ast.Expr)
            and isinstance(body[0].value, ast.Constant)
            and isinstance(body[0].value.value, str)):
        body = body[1:]
    return body


class Project:
    """Every parsed module under the scanned roots, plus shared lookups."""

    def __init__(self, root: str, cache=None) -> None:
        self.root = os.path.abspath(root)
        self.modules: Dict[str, ModuleInfo] = {}
        self.errors: List[str] = []
        self.cache = cache              # tools.raftlint.cache.FileCache

    # -- construction -------------------------------------------------------

    def scan(self, paths: Sequence[str]) -> None:
        files: List[str] = []
        for p in paths:
            ap = os.path.join(self.root, p) if not os.path.isabs(p) else p
            if os.path.isdir(ap):
                for dirpath, dirnames, filenames in os.walk(ap):
                    dirnames[:] = [d for d in dirnames
                                   if d != "__pycache__"
                                   and not d.startswith(".")]
                    files.extend(os.path.join(dirpath, f)
                                 for f in filenames if f.endswith(".py"))
            elif ap.endswith(".py") and os.path.isfile(ap):
                files.append(ap)
            else:
                self.errors.append(f"no such file or directory: {p}")
        for f in sorted(set(files)):
            self._load(f)

    def _load(self, path: str) -> None:
        rel = os.path.relpath(path, self.root).replace(os.sep, "/")
        mod = rel[:-3].replace("/", ".")
        is_pkg = mod.endswith(".__init__") or mod == "__init__"
        if mod.endswith(".__init__"):
            mod = mod[:-len(".__init__")]
        try:
            with open(path, "r", encoding="utf-8") as fh:
                source = fh.read()
            cached = self.cache.get(rel, source) if self.cache else None
            if cached is not None:
                cached.path = path      # tree may have moved on disk
                self.modules[mod] = cached
                return
            info = ModuleInfo(path, rel, mod, source, is_package=is_pkg)
            self.modules[mod] = info
            if self.cache:
                self.cache.put(rel, source, info)
        except (SyntaxError, UnicodeDecodeError) as e:
            self.errors.append(f"{rel}: parse error: {e}")

    # -- lookups ------------------------------------------------------------

    def iter_functions(self) -> Iterator[FunctionInfo]:
        for mod in self.modules.values():
            yield from mod.functions.values()

    def function_by_fq(self, fq: str) -> Optional[FunctionInfo]:
        """'pkg.mod.Class.method' or 'pkg.mod.func' → FunctionInfo."""
        for cut in range(len(fq), 0, -1):
            if fq[cut:cut + 1] not in ("", "."):
                continue
            modname, _, rest = fq[:cut], fq[cut:cut + 1], fq[cut + 1:]
            mod = self.modules.get(modname)
            if mod is not None and rest and rest in mod.functions:
                return mod.functions[rest]
        return None

    # -- call graph ---------------------------------------------------------

    def callees(self, fn: FunctionInfo) -> Set[str]:
        """Symbols (module:qual) this function may call, best-effort:
        bare/module-qualified calls through the import map, plus
        same-class ``self.method()`` calls."""
        out: Set[str] = set()
        mod = fn.module
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            chain = self_attr_chain(func)
            if chain is not None and len(chain) == 1 and fn.class_name:
                cls = mod.classes.get(fn.class_name)
                if cls and chain[0] in cls.methods:
                    out.add(cls.methods[chain[0]].symbol)
                continue
            fq = mod.resolve_local(func)
            if fq is None:
                continue
            target = self.function_by_fq(fq)
            if target is not None:
                out.add(target.symbol)
        return out

    def symbol_table(self) -> Dict[str, FunctionInfo]:
        return {fn.symbol: fn for fn in self.iter_functions()}
