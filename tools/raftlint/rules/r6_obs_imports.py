"""R6: the obs API boundary, as an import-graph rule.

Instrumented library code goes through the ``raft_tpu.obs`` facade
(``obs.inc`` / ``obs.span`` / ``obs.record_convergence`` ...). Importing
obs internals — or constructing ``MetricsRegistry``/``JsonlSink``
inline — bypasses the single on/off knob and the process-global
registry, so a module could emit metrics the exporter never sees or
allocate on the off path. The old smoke.sh grep enforced this with four
regexes; this is the same boundary on the import graph: any import
that resolves into ``raft_tpu.obs.<submodule>`` from a module outside
the obs package is a violation, as is a call whose terminal name is one
of the guarded constructors.
"""

from __future__ import annotations

import ast
from typing import List

from tools.raftlint.core import Finding, Project, dotted_parts
from tools.raftlint.rules.base import Rule

OBS_PKG = "raft_tpu.obs"
GUARDED_CTORS = {"MetricsRegistry", "JsonlSink"}


class ObsBoundaryRule(Rule):
    id = "R6"
    summary = "obs internals imported (or constructed) outside the facade"
    rationale = ("PR 4/10's single-knob observability: everything goes "
                 "through the raft_tpu.obs facade so one flag and one "
                 "process-global registry govern all emission")

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for mod in project.modules.values():
            if not mod.modname.startswith("raft_tpu"):
                continue
            if (mod.modname == OBS_PKG
                    or mod.modname.startswith(OBS_PKG + ".")):
                continue
            sym = f"{mod.modname}:<module>"
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.ImportFrom):
                    base = node.module or ""
                    if node.level:      # resolve relative imports
                        parts = mod.modname.split(".")
                        anchor = parts[:len(parts) - node.level]
                        base = ".".join(
                            anchor + ([node.module] if node.module
                                      else []))
                    if base.startswith(OBS_PKG + "."):
                        findings.append(self._imp(mod, sym, node,
                                                  base))
                    elif base == OBS_PKG:
                        for alias in node.names:
                            # only submodules are internals; facade
                            # helpers re-exported by obs/__init__ are
                            # the sanctioned surface
                            if (f"{OBS_PKG}.{alias.name}"
                                    in project.modules):
                                findings.append(self._imp(
                                    mod, sym, node,
                                    f"{OBS_PKG}.{alias.name}"))
                elif isinstance(node, ast.Import):
                    for alias in node.names:
                        if alias.name.startswith(OBS_PKG + "."):
                            findings.append(self._imp(mod, sym, node,
                                                      alias.name))
                elif isinstance(node, ast.Call):
                    parts = dotted_parts(node.func)
                    if parts and parts[-1] in GUARDED_CTORS:
                        findings.append(Finding(
                            self.id, mod.relpath, node.lineno,
                            node.col_offset, sym,
                            f"{parts[-1]}() constructed outside obs/ "
                            "bypasses the process-global registry",
                            "use the facade: obs.inc/observe emit to "
                            "the global registry; sinks attach via "
                            "obs.set_sink / RAFT_TPU_METRICS_JSONL"))
        return findings

    def _imp(self, mod, sym: str, node: ast.AST,
             target: str) -> Finding:
        return Finding(
            self.id, mod.relpath, node.lineno, node.col_offset, sym,
            f"import of obs internal {target} bypasses the facade",
            "import the facade instead: from raft_tpu import obs")
