"""R10: donation safety — donated buffers die at the call site.

``donate_argnums`` hands the argument's buffer to XLA for reuse; the
Python name still points at it, but the array is dead. The chaos suites
catch the resulting garbage reads dynamically; all three shapes of the
bug are statically decidable once the dataflow engine has resolved
which callables donate:

- **use-after-donate** — a name passed at a donated position and read
  again after the call (reads through the rebound result, ``x = f(x)``,
  are fine; reads of the stale operand are not);
- **stale loop carry** — a donated name fed to the call from outside a
  host loop and never rebound inside it: iteration 2 passes the buffer
  iteration 1 already donated;
- **vacuous donation** — ``donate_argnums`` naming a parameter the body
  never consumes: the donation frees nothing and documents an aliasing
  contract that does not exist.

The engine resolves donation facts through decorators
(``@partial(jax.jit, donate_argnums=…)``), direct ``jax.jit(f, …)``
wraps, and jit-of-``shard_map`` stacks; variable donate positions and
``*args`` call sites stay silent (conservative-by-construction).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from tools.raftlint import dataflow
from tools.raftlint.core import Finding, FunctionInfo, Project
from tools.raftlint.rules.base import Rule


def _enclosing_stmt(parents, node):
    cur = node
    while cur is not None and not isinstance(cur, ast.stmt):
        cur = parents.get(cur)
    return cur


def _rebinds_name(stmt, name: str) -> bool:
    """The call's own statement stores the name (``x = f(x)``,
    ``x, y = f(x)``, ``x += …``) — the donated operand is rebound the
    moment the call returns, so no stale read through it can follow."""
    if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
        targets = stmt.targets if isinstance(stmt, ast.Assign) \
            else [stmt.target]
        for tgt in targets:
            for sub in ast.walk(tgt):
                if isinstance(sub, ast.Name) and sub.id == name:
                    return True
    return False


class DonationSafetyRule(Rule):
    id = "R10"
    summary = ("buffer read after being donated, donated loop carry "
               "never rebound, or donation on an unconsumed argument")
    rationale = ("donate_argnums invalidates the operand buffer at the "
                 "call — a later read through the old name returns "
                 "whatever XLA wrote into the reused pages, the exact "
                 "garbage the double-buffer chaos suites hunt "
                 "dynamically")

    def run(self, project: Project) -> List[Finding]:
        df = dataflow.analyze(project)
        findings: List[Finding] = []
        seen: Set[Tuple] = set()
        pmaps: Dict[str, dict] = {}

        def emit(kind, path, line, col, sym, msg, hint):
            key = (kind, path, line, col, sym)
            if key in seen:
                return
            seen.add(key)
            findings.append(Finding(self.id, path, line, col, sym,
                                    msg, hint))

        for ev in df.calls:
            donate = ev.facts.donate if ev.facts else ()
            if not donate:
                continue
            if any(isinstance(a, ast.Starred) for a in ev.node.args):
                continue            # positions unknowable
            fn = ev.fn
            pm = pmaps.get(fn.symbol)
            if pm is None:
                pm = dataflow.parent_map(fn)
                pmaps[fn.symbol] = pm
            stmt = _enclosing_stmt(pm, ev.node)
            for pos in donate:
                if pos >= len(ev.node.args):
                    continue
                arg = ev.node.args[pos]
                if not isinstance(arg, ast.Name):
                    continue        # temporaries die anyway
                name = arg.id
                rebound = stmt is not None and _rebinds_name(stmt, name)
                if not rebound:
                    read = dataflow.reads_after(fn, ev.node, name)
                    if read is not None:
                        emit("use", fn.module.relpath, read.lineno,
                             read.col_offset, fn.symbol,
                             f"'{name}' is read after being donated "
                             f"at line {ev.node.lineno} "
                             f"(donate position {pos})",
                             "rebind the result over the operand "
                             "(x = f(x)) or stage a fresh buffer per "
                             "call (device_put before the donating "
                             "launch)")
                        continue
                loop = dataflow.enclosing_loop(pm, ev.node)
                if loop is not None and not dataflow.stores_in(
                        loop, name):
                    emit("loop", fn.module.relpath, ev.node.lineno,
                         ev.node.col_offset, fn.symbol,
                         f"'{name}' is donated inside a loop but "
                         "never rebound in the loop body — iteration "
                         "2 passes a buffer iteration 1 already gave "
                         "away",
                         "carry the call result back into the name "
                         "(x = f(x)) or allocate per iteration")

        # vacuous donation: donate positions naming params the body
        # never loads — both decorated defs and jit(f, donate_argnums=…)
        table = project.symbol_table()
        vacuous: Dict[str, Set[int]] = {}
        for sym, positions in df.donating_defs.items():
            vacuous.setdefault(sym, set()).update(positions)
        for ev in df.calls:
            if ev.fq not in dataflow.JIT_FQS or not ev.args:
                continue
            inner = ev.args[0].func
            if inner is None or inner.symbol is None:
                continue
            for kw in ev.node.keywords:
                if kw.arg in ("donate_argnums", "donate_argnames"):
                    lit = dataflow._literal(kw.value)
                    pos = (lit,) if isinstance(lit, int) else (
                        lit if isinstance(lit, tuple) else ())
                    vacuous.setdefault(inner.symbol, set()).update(
                        p for p in pos if isinstance(p, int))
        for sym, positions in sorted(vacuous.items()):
            fn = table.get(sym)
            if fn is None:
                continue
            params = self._params(fn)
            loads = {n.id for n in ast.walk(fn.node)
                     if isinstance(n, ast.Name)
                     and isinstance(n.ctx, ast.Load)}
            for pos in sorted(positions):
                if pos >= len(params):
                    continue
                pname = params[pos]
                if pname not in loads:
                    emit("vacuous", fn.module.relpath,
                         fn.node.lineno, fn.node.col_offset,
                         fn.symbol,
                         f"donate_argnums names '{pname}' (position "
                         f"{pos}) but the body never consumes it — "
                         "the donation frees nothing",
                         "drop the position from donate_argnums or "
                         "consume the buffer")
        findings.sort(key=lambda f: (f.path, f.line, f.col))
        return findings

    @staticmethod
    def _params(fn: FunctionInfo) -> List[str]:
        a = getattr(fn.node, "args", None)
        if a is None:
            return []
        return [p.arg for p in a.posonlyargs + a.args]
