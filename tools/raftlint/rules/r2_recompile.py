"""R2: recompile hazards at jit / aot_export call sites.

The serve layer's acceptance gate is ZERO post-warm recompiles; the two
statically-catchable ways to lose it are (a) constructing a fresh
jittable per call — ``jax.jit(lambda ...)`` or jit-of-a-local-``def``
inside a function body, where every invocation makes a new callable
identity and therefore a new trace-cache entry — and (b) closing a
jitted local over an array built in the enclosing scope, which
participates in the cache key by object identity and re-traces whenever
the enclosing function rebuilds it.

An enclosing function decorated with ``functools.lru_cache``/``cache``
is exempt from (a): the fresh callable is constructed once per cache
key and memoized, which is the repo's sanctioned spelling for
shape-keyed executable caches (serve/executor, ivf searchers). Sites
that memoize by hand into a dict are real but invisible to this rule —
they carry a baseline entry instead, with the cache named in the
justification.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from tools.raftlint.core import Finding, Project, dotted_parts
from tools.raftlint.rules.base import Rule

JIT_LIKE = {
    "jax.jit", "jax.pjit", "jax.experimental.pjit.pjit",
}
AOT_LIKE = {"aot_export"}       # matched on terminal name (repo helper)
CACHED_DECOS = {
    "functools.lru_cache", "functools.cache", "lru_cache", "cache",
}
ARRAY_CTORS_PREFIX = ("jax.numpy.", "numpy.")
ARRAY_CTOR_NAMES = {
    "array", "asarray", "zeros", "ones", "full", "arange", "linspace",
    "eye", "empty",
}


def _is_cached(mod, fn_node: ast.AST) -> bool:
    for deco in getattr(fn_node, "decorator_list", []):
        target = deco.func if isinstance(deco, ast.Call) else deco
        fq = mod.resolve(target)
        if fq in CACHED_DECOS:
            return True
        parts = dotted_parts(target)
        if parts and parts[-1] in ("lru_cache", "cache"):
            return True
    return False


class RecompileRule(Rule):
    id = "R2"
    summary = ("fresh jittable or closure-captured array at a "
               "jit/aot_export call site")
    rationale = ("the serve layer's zero-post-warm-recompile gate "
                 "(PR 6/9/11): a per-call callable identity or an "
                 "identity-keyed closure array re-traces on every "
                 "invocation")

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for fn in project.iter_functions():
            mod = fn.module
            if _is_cached(mod, fn.node):
                continue
            # names assigned an array constructor result in THIS function
            array_locals: Set[str] = set()
            for node in ast.walk(fn.node):
                if (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Call)):
                    fq = mod.resolve(node.value.func)
                    if fq and (fq.startswith(ARRAY_CTORS_PREFIX)
                               and fq.rsplit(".", 1)[-1]
                               in ARRAY_CTOR_NAMES):
                        for tgt in node.targets:
                            if isinstance(tgt, ast.Name):
                                array_locals.add(tgt.id)
            local_defs = {
                name.rsplit(".", 1)[-1]: info
                for name, info in mod.functions.items()
                if name.startswith(fn.qual + ".")
                and name.count(".") == fn.qual.count(".") + 1}

            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                fq = mod.resolve(node.func)
                parts = dotted_parts(node.func)
                terminal = parts[-1] if parts else None
                if not (fq in JIT_LIKE or terminal in AOT_LIKE):
                    continue
                if not node.args:
                    continue
                target = node.args[0]
                if isinstance(target, ast.Lambda):
                    findings.append(Finding(
                        self.id, mod.relpath, node.lineno,
                        node.col_offset, fn.symbol,
                        "jit of an inline lambda constructs a fresh "
                        "callable (new trace-cache entry) per call",
                        "hoist the lambda to module scope or memoize "
                        "the jitted result (functools.lru_cache)"))
                    continue
                if (isinstance(target, ast.Name)
                        and target.id in local_defs):
                    inner = local_defs[target.id]
                    captured = self._captured_arrays(
                        inner.node, array_locals)
                    if captured:
                        findings.append(Finding(
                            self.id, mod.relpath, node.lineno,
                            node.col_offset, fn.symbol,
                            "jitted local function closes over "
                            f"array(s) {sorted(captured)} built in the "
                            "enclosing scope (identity-keyed: every "
                            "rebuild re-traces)",
                            "pass the array as an argument instead of "
                            "capturing it"))
                    else:
                        findings.append(Finding(
                            self.id, mod.relpath, node.lineno,
                            node.col_offset, fn.symbol,
                            "jit of a local def constructs a fresh "
                            "callable (new trace-cache entry) per "
                            "call",
                            "hoist the def, or memoize the enclosing "
                            "builder with functools.lru_cache"))
        return findings

    @staticmethod
    def _captured_arrays(inner: ast.AST,
                         array_locals: Set[str]) -> Set[str]:
        args = inner.args
        bound = {a.arg for a in args.posonlyargs + args.args
                 + args.kwonlyargs}
        for node in ast.walk(inner):
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets
                           if isinstance(node, ast.Assign)
                           else [node.target])
                for tgt in targets:
                    if isinstance(tgt, ast.Name):
                        bound.add(tgt.id)
        used = {node.id for node in ast.walk(inner)
                if isinstance(node, ast.Name)
                and isinstance(node.ctx, ast.Load)}
        return (used - bound) & array_locals
