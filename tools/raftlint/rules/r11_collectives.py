"""R11: collective discipline — axis names, rank-uniform reachability,
and tag-matched mailbox traffic.

Three statically decidable shapes of the deadlock class
``tests/test_serve_chaos.py`` hunts dynamically:

- **unbound axis name** — ``lax.psum(x, "rows")`` under a
  ``shard_map`` whose mesh binds only ``("data",)``: the collective
  either crashes at trace time or, worse, resolves against an outer
  mesh nobody intended. The dataflow engine tracks the axis names each
  ``shard_map`` application brings into scope (through jit wrapping and
  nested maps) and checks every literal axis-name use against them;
  unknown scopes stay silent.
- **rank-divergent collective** — a ``lax.cond`` whose predicate is
  derived from ``lax.axis_index`` and whose arms do not agree on
  whether a collective runs: ranks take different arms and the
  collective's rendezvous never completes. The predicate's provenance
  rides the engine's ``axis_index`` origin tag through arithmetic and
  compares.
- **unmatched mailbox tag** — a literal-tag ``isend``/``mailbox.put``
  with no ``irecv``/``mailbox.get`` anywhere in the scanned tree using
  the same tag (or vice versa): the peer half of a
  ``search_local``/``merge_pool``-style pair is missing and the
  blocking side waits forever. Computed tags stay silent.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.raftlint import dataflow
from tools.raftlint.core import Finding, ModuleInfo, Project
from tools.raftlint.rules.base import Rule

SEND_ATTRS = {"isend": 2, "put": 2}         # attr → positional tag idx
RECV_ATTRS = {"irecv": 1, "get": 2, "get_nowait": 2}


def _mailboxish(func: ast.AST) -> Optional[str]:
    """'send'/'recv' when the call is mailbox traffic: ``isend``/
    ``irecv`` on anything, ``put``/``get*`` only on an attribute chain
    that names a mailbox (``self._mailbox.put``) — bare dict/queue
    put/get stay out."""
    if not isinstance(func, ast.Attribute):
        return None
    attr = func.attr
    if attr in ("isend",):
        return "send"
    if attr in ("irecv",):
        return "recv"
    if attr in SEND_ATTRS or attr in RECV_ATTRS:
        parts = dataflow.dotted_parts(func) or []
        if any("mailbox" in p.lower() for p in parts[:-1]):
            return "send" if attr in SEND_ATTRS else "recv"
    return None


def _literal_tag(call: ast.Call, attr: str) -> Optional[int]:
    for kw in call.keywords:
        if kw.arg == "tag" and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, int):
            return kw.value.value
        if kw.arg == "tag":
            return None
    pos = SEND_ATTRS.get(attr, RECV_ATTRS.get(attr))
    if pos is not None and len(call.args) > pos:
        node = call.args[pos]
        if isinstance(node, ast.Constant) and isinstance(
                node.value, int):
            return node.value
    return None


class CollectiveDisciplineRule(Rule):
    id = "R11"
    summary = ("collective axis name unbound by the enclosing "
               "shard_map, collective under a rank-dependent cond "
               "arm, or mailbox tag with no matching peer")
    rationale = ("every one of these is a distributed hang, not a "
                 "wrong answer: the static forms of the rendezvous "
                 "deadlocks the serve chaos suite can only catch when "
                 "the unlucky schedule actually fires")

    def run(self, project: Project) -> List[Finding]:
        df = dataflow.analyze(project)
        table = project.symbol_table()
        findings: List[Finding] = []
        seen: Set[Tuple] = set()

        def emit(path, line, col, sym, msg, hint):
            key = (path, line, col, msg)
            if key in seen:
                return
            seen.add(key)
            findings.append(Finding(self.id, path, line, col, sym,
                                    msg, hint))

        # -- (a) axis names vs the statically known scope ----------------
        # one syntactic site can be observed under several contexts
        # (a nested body is also interpreted standalone, where the
        # outer mesh is invisible), so flag a name only when NO
        # observed scope binds it — any binding context vindicates
        # the site
        sites: Dict[Tuple, list] = {}
        for ev in df.collectives:
            if ev.axes_scope is None:
                continue            # scope unknown: stay silent
            key = (ev.fn.module.relpath, ev.node.lineno,
                   ev.node.col_offset)
            sites.setdefault(key, [ev, set()])[1] |= ev.axes_scope
        for key in sorted(sites):
            ev, scope = sites[key]
            names = []
            if isinstance(ev.axis.const, str):
                names = [ev.axis.const]
            elif isinstance(ev.axis.const, tuple):
                names = [a for a in ev.axis.const
                         if isinstance(a, str)]
            for name in names:
                if name not in scope:
                    emit(ev.fn.module.relpath, ev.node.lineno,
                         ev.node.col_offset, ev.fn.symbol,
                         f"{ev.fq.rsplit('.', 1)[-1]} over axis "
                         f"'{name}' but the enclosing shard_map mesh "
                         f"binds only "
                         f"{sorted(scope) or ['<none>']}",
                         "use an axis name from the mesh spec, or "
                         "thread the axis through as a parameter")

        # -- (b) rank-divergent lax.cond arms ----------------------------
        for ev in df.calls:
            if ev.fq != "jax.lax.cond" or not ev.args:
                continue
            if "axis_index" not in ev.args[0].tags:
                continue
            counts = []
            for branch in ev.args[1:3]:
                sym = branch.func.symbol if branch.func else None
                fn = table.get(sym) if sym else None
                counts.append(self._collective_count(fn)
                              if fn is not None else None)
            if len(counts) == 2 and None not in counts and \
                    (counts[0] == 0) != (counts[1] == 0):
                emit(ev.fn.module.relpath, ev.node.lineno,
                     ev.node.col_offset, ev.fn.symbol,
                     "lax.cond predicate derives from lax.axis_index "
                     "and only one arm runs a collective — ranks "
                     "taking different arms deadlock the rendezvous",
                     "hoist the collective out of the cond, or make "
                     "both arms participate (reduce a zero "
                     "contribution on the idle arm)")

        # -- (c) mailbox tag pairing -------------------------------------
        sends: Dict[int, List] = {}
        recvs: Dict[int, List] = {}
        for mod in project.modules.values():
            for sym, node in _walk_with_symbols(mod):
                if not isinstance(node, ast.Call):
                    continue
                kind = _mailboxish(node.func)
                if kind is None:
                    continue
                tag = _literal_tag(node, node.func.attr)
                if tag is None:
                    continue
                (sends if kind == "send" else recvs).setdefault(
                    tag, []).append((mod, sym, node))
        for tag, sites in sorted(sends.items()):
            if tag in recvs:
                continue
            for mod, sym, node in sites:
                emit(mod.relpath, node.lineno, node.col_offset, sym,
                     f"mailbox send with literal tag {tag} has no "
                     "matching tagged recv anywhere in the scanned "
                     "tree",
                     "add the peer-half recv, or derive both tags "
                     "from one shared constant")
        for tag, sites in sorted(recvs.items()):
            if tag in sends:
                continue
            for mod, sym, node in sites:
                emit(mod.relpath, node.lineno, node.col_offset, sym,
                     f"mailbox recv with literal tag {tag} has no "
                     "matching tagged send anywhere in the scanned "
                     "tree — the blocking get waits forever",
                     "add the peer-half send, or derive both tags "
                     "from one shared constant")
        findings.sort(key=lambda f: (f.path, f.line, f.col))
        return findings

    @staticmethod
    def _collective_count(fn) -> int:
        mod = fn.module
        n = 0
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Call):
                fq = mod.resolve(node.func)
                if fq in dataflow.COLLECTIVES and \
                        fq != "jax.lax.axis_index":
                    n += 1
        return n


def _walk_with_symbols(mod: ModuleInfo):
    by_node = {info.node: f"{mod.modname}:{qual}"
               for qual, info in mod.functions.items()}

    def walk(node, sym):
        for child in ast.iter_child_nodes(node):
            child_sym = by_node.get(child, sym)
            yield child_sym, child
            yield from walk(child, child_sym)
    yield from walk(mod.tree, f"{mod.modname}:<module>")
