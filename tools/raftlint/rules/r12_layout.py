"""R12: layout and promotion hazards at the kernel boundary.

Two hazards the Mosaic lowering and the numerics ladder otherwise only
surface at run time:

- **misaligned tile parameters** — a statically known int flowing into
  a tile/strip parameter of a ``linalg/contractions.py`` or
  ``matrix/epilogue.py`` entry point that violates the hardware
  alignment the kernels assume: lane-dim parameters (``tn``, ``sw``,
  ``bw``) must divide by 128, sublane-dim parameters (``tm``) by 8.
  Values produced through the documented padding helpers
  (``round_up_to_multiple``, ``resolve_tn_sw``, ``best_width``,
  ``_pad2``) carry the engine's ``padded`` tag and are exempt — the
  rule polices the *bypass*, not the helpers. Unknown values stay
  silent; calls from inside the two kernel modules themselves are
  implementation plumbing and exempt.
- **silent f64 promotion** — mixed-dtype arithmetic whose NumPy-style
  result dtype is ``float64`` with a narrower float on the other side:
  ``util/numerics.py``'s precision ladder tops out at ``highest`` on
  device (f64 is host-only), so an f32×f64 product silently doubles
  bandwidth on CPU reference paths and fails to lower on TPU. Python
  float literals are weakly typed and never flag.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tools.raftlint import dataflow
from tools.raftlint.core import Finding, Project
from tools.raftlint.rules.base import Rule

POLICED_MODULES = ("raft_tpu.linalg.contractions",
                   "raft_tpu.matrix.epilogue")

#: tile parameter name → required divisor (lane dims 128, sublanes 8)
PARAM_MODULUS = {"tm": 8, "tn": 128, "sw": 128, "bw": 128}

#: positional signatures for the policed entry points, used when the
#: target module is outside the scan set (subset lints still resolve
#: keyword args either way)
FALLBACK_SIGS: Dict[str, Sequence[str]] = {
    "raft_tpu.matrix.epilogue.insert_drain":
        ("dist", "val_ref", "idx_ref", "j", "tn", "k", "n_valid",
         "sw"),
    "raft_tpu.matrix.epilogue.resolve_tn_sw": ("tn", "sw", "n"),
    "raft_tpu.linalg.contractions.pairwise_pallas":
        ("x", "y", "metric", "tm", "tn"),
}

#: the sanctioned alignment helpers never flag, even on literal args —
#: their whole job is taking unaligned values
HELPER_FQS = dataflow.PADDING_HELPERS | {
    "raft_tpu.matrix.epilogue.resolve_tn_sw"}

NARROW_FLOATS = ("float32", "bfloat16", "float16")


class LayoutPromotionRule(Rule):
    id = "R12"
    summary = ("tile parameter with lane dim not divisible by 128 / "
               "sublane not by 8 bypassing the padding helpers, or "
               "arithmetic silently promoting to float64")
    rationale = ("a misaligned tile either fails Mosaic legalization "
                 "at warm time or pads per-launch inside the kernel; "
                 "an accidental f64 operand doubles reference-path "
                 "bandwidth and cannot lower on TPU — both are "
                 "documented contracts with one sanctioned helper "
                 "spelling each")

    def run(self, project: Project) -> List[Finding]:
        df = dataflow.analyze(project)
        findings: List[Finding] = []
        seen: Set[Tuple] = set()

        def emit(path, line, col, sym, msg, hint):
            key = (path, line, col, msg)
            if key in seen:
                return
            seen.add(key)
            findings.append(Finding(self.id, path, line, col, sym,
                                    msg, hint))

        for ev in df.calls:
            fq = ev.fq
            if fq is None and ev.facts is not None and ev.facts.symbol:
                fq = ev.facts.symbol.replace(":", ".")
            if fq is None or fq in HELPER_FQS:
                continue
            owner = fq.rsplit(".", 1)[0]
            if owner not in POLICED_MODULES:
                continue
            if ev.fn.module.modname in POLICED_MODULES:
                continue            # internal plumbing
            if any(isinstance(a, ast.Starred) for a in ev.node.args):
                continue
            params = self._params_for(project, fq)
            named = dict(ev.keywords)
            if params is not None:
                for i, av in enumerate(ev.args):
                    if i < len(params):
                        named.setdefault(params[i], av)
            for pname, av in named.items():
                mod = PARAM_MODULUS.get(pname)
                if mod is None or not isinstance(av.const, int):
                    continue
                if pname == "sw" and av.const == 0:
                    continue        # 0 = whole-tile drain, legal
                if av.const % mod == 0 or "padded" in av.tags:
                    continue
                kind = "sublane" if mod == 8 else "lane"
                emit(ev.fn.module.relpath, ev.node.lineno,
                     ev.node.col_offset, ev.fn.symbol,
                     f"{fq.rsplit('.', 1)[-1]}({pname}={av.const}): "
                     f"{kind} tile parameter not divisible by {mod} "
                     "and not produced by a padding helper",
                     "route the value through "
                     "epilogue.resolve_tn_sw / "
                     "util.math.round_up_to_multiple before the "
                     "kernel boundary")

        for ev in df.binops:
            if ev.result.dtype != "float64":
                continue
            sides = (ev.left.dtype, ev.right.dtype)
            if not any(d in NARROW_FLOATS for d in sides):
                continue
            if ev.fn.module.modname == "raft_tpu.util.numerics":
                continue            # the ladder itself
            emit(ev.fn.module.relpath, ev.node.lineno,
                 ev.node.col_offset, ev.fn.symbol,
                 f"arithmetic between {sides[0]} and {sides[1]} "
                 "silently promotes to float64, past the numerics "
                 "precision ladder (f64 is host-only)",
                 "cast the f64 side down explicitly, or raise "
                 "precision through util.numerics' ladder instead "
                 "of dtype widening")
        findings.sort(key=lambda f: (f.path, f.line, f.col))
        return findings

    @staticmethod
    def _params_for(project: Project,
                    fq: str) -> Optional[Sequence[str]]:
        target = project.function_by_fq(fq)
        if target is not None:
            a = getattr(target.node, "args", None)
            if a is not None:
                return [p.arg for p in a.posonlyargs + a.args]
        return FALLBACK_SIGS.get(fq)
