"""R14: intra-package import resolution — dead imports fail loud.

The SURVEY's ``trustworthiness_score`` class of rot: a module importing
a path that no longer exists survives every syntactic lint and only
explodes when something finally imports *it*. For imports whose root
package lives under the scanned repo root, this rule checks:

- ``import a.b.c`` / ``from a.b import x`` — the target module/package
  file must exist on disk (``a/b.py`` or ``a/b/__init__.py``), so the
  check is robust under subset scans;
- ``from a.b import x`` where ``a.b`` was scanned — ``x`` must be a
  function, class, submodule, or module-level binding of ``a.b``.
  Modules that star-import or define ``__getattr__`` (lazy re-export)
  are exempt from the name-level check.

Relative imports resolve with the package-``__init__`` anchoring rule
(for an ``__init__.py`` the module *is* its package) — the same logic
``core.py`` uses, so a future regression there shows up as churn here.
External roots (jax, numpy, stdlib) are out of scope.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set

from tools.raftlint.core import Finding, ModuleInfo, Project
from tools.raftlint.rules.base import Rule


def _module_exists(root: str, dotted: str) -> bool:
    rel = dotted.replace(".", os.sep)
    return (os.path.isfile(os.path.join(root, rel + ".py"))
            or os.path.isfile(os.path.join(root, rel, "__init__.py")))


def _local_root(root: str, dotted: str) -> bool:
    """True when the import's first segment is a package/module that
    lives under the scanned repo root."""
    head = dotted.split(".", 1)[0]
    return _module_exists(root, head)


def _toplevel_bindings(mod: ModuleInfo) -> Set[str]:
    """Names bound at module scope, descending into top-level control
    flow but never into function/class bodies."""
    names: Set[str] = set()

    def visit(stmts):
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                names.add(st.name)
            elif isinstance(st, (ast.Import, ast.ImportFrom)):
                for alias in getattr(st, "names", []):
                    if alias.name == "*":
                        continue
                    bound = alias.asname or alias.name.split(".")[0]
                    names.add(bound)
            elif isinstance(st, (ast.Assign, ast.AnnAssign,
                                 ast.AugAssign)):
                targets = st.targets if isinstance(st, ast.Assign) \
                    else [st.target]
                for tgt in targets:
                    for sub in ast.walk(tgt):
                        if isinstance(sub, ast.Name):
                            names.add(sub.id)
            elif isinstance(st, (ast.If, ast.Try, ast.For, ast.While,
                                 ast.With)):
                for field in ("body", "orelse", "finalbody"):
                    visit(getattr(st, field, []) or [])
                for h in getattr(st, "handlers", []) or []:
                    visit(h.body)
                if isinstance(st, ast.For) and isinstance(
                        st.target, ast.Name):
                    names.add(st.target.id)
    visit(mod.tree.body)
    return names


def _is_opaque(mod: ModuleInfo) -> bool:
    """Star imports or a module __getattr__ make the exported-name set
    statically unknowable — skip name-level checks."""
    for st in mod.tree.body:
        if isinstance(st, ast.ImportFrom) and any(
                a.name == "*" for a in st.names):
            return True
        if isinstance(st, (ast.FunctionDef,)) and st.name in (
                "__getattr__", "__dir__"):
            return True
    return False


class ImportResolutionRule(Rule):
    id = "R14"
    summary = ("intra-package import of a module or name that no "
               "longer exists")
    rationale = ("a dead import is a landmine that only detonates "
                 "when something finally imports the module carrying "
                 "it — the vestigial-reference rot class the stats/ "
                 "header parity audit chases by hand")

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        bindings: Dict[str, Set[str]] = {}
        for mod in project.modules.values():
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Import):
                    for alias in node.names:
                        dotted = alias.name
                        if not _local_root(project.root, dotted):
                            continue
                        if not _module_exists(project.root, dotted):
                            findings.append(Finding(
                                self.id, mod.relpath, node.lineno,
                                node.col_offset,
                                f"{mod.modname}:<module>",
                                f"import of '{dotted}': no such "
                                "module under the repo root",
                                "delete the dead import or restore "
                                "the module"))
                elif isinstance(node, ast.ImportFrom):
                    self._check_importfrom(project, mod, node,
                                           bindings, findings)
        findings.sort(key=lambda f: (f.path, f.line, f.col))
        return findings

    def _check_importfrom(self, project: Project, mod: ModuleInfo,
                          node: ast.ImportFrom, bindings, findings
                          ) -> None:
        base = node.module or ""
        if node.level:
            parts = mod.modname.split(".")
            drop = node.level - 1 if mod.is_package else node.level
            if drop > len(parts):
                return
            anchor = parts[:len(parts) - drop] if drop else parts
            base = ".".join(anchor + ([node.module] if node.module
                                      else []))
        if not base or not _local_root(project.root, base):
            return
        if not _module_exists(project.root, base):
            findings.append(Finding(
                self.id, mod.relpath, node.lineno, node.col_offset,
                f"{mod.modname}:<module>",
                f"import from '{base}': no such module under the "
                "repo root",
                "delete the dead import or restore the module"))
            return
        target = project.modules.get(base)
        if target is None or _is_opaque(target):
            return                  # unscanned or dynamic exports
        names = bindings.get(base)
        if names is None:
            names = _toplevel_bindings(target)
            bindings[base] = names
        for alias in node.names:
            if alias.name == "*":
                continue
            if alias.name in names:
                continue
            if _module_exists(project.root,
                              f"{base}.{alias.name}"):
                continue            # submodule import
            findings.append(Finding(
                self.id, mod.relpath, node.lineno, node.col_offset,
                f"{mod.modname}:<module>",
                f"'{alias.name}' is not defined in '{base}' (no "
                "function, class, module-level binding, or "
                "submodule by that name)",
                "fix the name or restore the binding"))
