"""R1: no host-side effects reachable from a traced body.

Seeds are functions that jit compiles or a structured-control primitive
traces: ``@jax.jit`` decorations (bare or ``functools.partial``-wrapped),
and callables passed to ``jax.jit`` / ``shard_map`` / ``lax.while_loop``
/ ``lax.scan`` / ``lax.cond`` / ``lax.switch`` / ``lax.fori_loop`` /
``lax.map`` call sites. The rule closes over the best-effort call graph
from those seeds, then flags the unambiguous host-sync markers anywhere
reachable: ``.item()`` / ``.tolist()`` / ``.block_until_ready()``,
``print``, ``time.*`` clock reads, and ``np.*`` calls (a NumPy call on a
tracer either crashes or silently constant-folds host-side). Direct
seed bodies additionally get the coercion/branch checks — ``float(x)``
/ ``int(x)`` / ``bool(x)`` on a traced parameter and ``if``/``while``
tests that are a bare traced parameter — with parameters named in
``static_argnames``/``static_argnums`` excluded, since branching on a
static arg is exactly what static args are for.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.raftlint.core import (
    Finding, FunctionInfo, Project, dotted_parts)
from tools.raftlint.rules.base import Rule

JIT_NAMES = {
    "jax.jit", "jax.pjit", "jax.experimental.pjit.pjit",
}
TRACED_CALLERS = {
    "jax.jit", "jax.pjit", "jax.experimental.pjit.pjit",
    "jax.shard_map", "jax.experimental.shard_map.shard_map",
    "jax.lax.while_loop", "jax.lax.scan", "jax.lax.cond",
    "jax.lax.switch", "jax.lax.fori_loop", "jax.lax.map",
    "jax.lax.associative_scan", "jax.checkpoint", "jax.remat",
    "jax.vmap", "jax.grad", "jax.value_and_grad",
}
HOST_SYNC_ATTRS = {"item", "tolist", "block_until_ready"}
CLOCK_CALLS = {
    "time.time", "time.perf_counter", "time.monotonic",
    "time.process_time", "time.sleep",
}


def _partial_of_jit(mod, deco: ast.AST) -> bool:
    if not isinstance(deco, ast.Call):
        return False
    fq = mod.resolve(deco.func)
    if fq not in ("functools.partial", "partial"):
        return False
    return bool(deco.args) and mod.resolve(deco.args[0]) in JIT_NAMES


def _static_params(mod, fn: FunctionInfo) -> Set[str]:
    """Parameter names declared static at the decoration site."""
    static: Set[str] = set()
    node = fn.node
    args = node.args
    names = [a.arg for a in args.posonlyargs + args.args]
    for deco in getattr(node, "decorator_list", []):
        if not isinstance(deco, ast.Call):
            continue
        if (mod.resolve(deco.func) not in JIT_NAMES
                and not _partial_of_jit(mod, deco)):
            continue
        for kw in deco.keywords:
            if kw.arg == "static_argnames":
                for c in ast.walk(kw.value):
                    if isinstance(c, ast.Constant) and isinstance(
                            c.value, str):
                        static.add(c.value)
            elif kw.arg == "static_argnums":
                for c in ast.walk(kw.value):
                    if isinstance(c, ast.Constant) and isinstance(
                            c.value, int) and c.value < len(names):
                        static.add(names[c.value])
    return static


class JitPurityRule(Rule):
    id = "R1"
    summary = ("host sync / NumPy / host branching reachable from a "
               "jit-traced body")
    rationale = ("PR 6/9/11's zero-post-warm-recompile and "
                 "compiled-driver contracts: a .item()/np.* inside a "
                 "traced body either crashes under trace or forces a "
                 "silent host round-trip per step")

    def run(self, project: Project) -> List[Finding]:
        table = project.symbol_table()
        seeds: Dict[str, FunctionInfo] = {}
        lambda_seeds: List[Tuple[FunctionInfo, ast.Lambda]] = []

        for fn in project.iter_functions():
            mod = fn.module
            for deco in getattr(fn.node, "decorator_list", []):
                target = deco.func if isinstance(deco, ast.Call) else deco
                if (mod.resolve(target) in JIT_NAMES
                        or _partial_of_jit(mod, deco)):
                    seeds[fn.symbol] = fn
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                if mod.resolve(node.func) not in TRACED_CALLERS:
                    continue
                for arg in list(node.args) + [kw.value
                                              for kw in node.keywords]:
                    if isinstance(arg, ast.Lambda):
                        lambda_seeds.append((fn, arg))
                        continue
                    parts = dotted_parts(arg)
                    if parts is None:
                        continue
                    # local def in the enclosing function?
                    if len(parts) == 1:
                        local = mod.functions.get(
                            f"{fn.qual}.{parts[0]}")
                        if local is not None:
                            seeds[local.symbol] = local
                            continue
                    fq = mod.resolve_local(arg)
                    target_fn = (project.function_by_fq(fq)
                                 if fq else None)
                    if target_fn is not None:
                        seeds[target_fn.symbol] = target_fn

        # close over the call graph
        reachable: Dict[str, str] = {s: s for s in seeds}   # sym → seed
        frontier = list(seeds)
        while frontier:
            sym = frontier.pop()
            fn = table.get(sym)
            if fn is None:
                continue
            for callee in project.callees(fn):
                if callee not in reachable:
                    reachable[callee] = reachable[sym]
                    frontier.append(callee)

        findings: List[Finding] = []
        for sym in sorted(reachable):
            fn = table.get(sym)
            if fn is None:
                continue
            findings.extend(self._check_body(
                fn, direct=sym in seeds, via=reachable[sym]))
        for host_fn, lam in lambda_seeds:
            pseudo = FunctionInfo(host_fn.module, host_fn.qual, lam,
                                  host_fn.class_name)
            findings.extend(self._check_body(pseudo, direct=True,
                                             via=pseudo.symbol))
        return findings

    def _check_body(self, fn: FunctionInfo, direct: bool,
                    via: str) -> List[Finding]:
        mod = fn.module
        out: List[Finding] = []
        why = "" if direct else f" (reachable from traced {via})"

        def flag(node: ast.AST, message: str, hint: str) -> None:
            out.append(Finding(
                self.id, mod.relpath, node.lineno, node.col_offset,
                fn.symbol, message + why, hint))

        args = getattr(fn.node, "args", None)
        params = set()
        if args is not None:
            params = {a.arg for a in args.posonlyargs + args.args
                      + args.kwonlyargs} - {"self", "cls"}
        if direct and not isinstance(fn.node, ast.Lambda):
            params -= _static_params(mod, fn)

        for node in ast.walk(fn.node):
            if isinstance(node, ast.Call):
                func = node.func
                if (isinstance(func, ast.Attribute)
                        and func.attr in HOST_SYNC_ATTRS):
                    flag(node, f".{func.attr}() in a traced body",
                         "return the array and sync outside the jit "
                         "boundary")
                    continue
                fq = mod.resolve(func)
                if fq is None:
                    if (isinstance(func, ast.Name)
                            and func.id == "print"):
                        flag(node, "print() in a traced body",
                             "use jax.debug.print (traced) or log "
                             "outside the jit boundary")
                    elif (direct and isinstance(func, ast.Name)
                          and func.id in ("float", "int", "bool")
                          and node.args
                          and isinstance(node.args[0], ast.Name)
                          and node.args[0].id in params):
                        flag(node,
                             f"{func.id}() coerces traced parameter "
                             f"{node.args[0].id!r} to a host scalar",
                             "keep it an array, or declare the arg "
                             "static if it is genuinely host-side")
                    continue
                if fq in CLOCK_CALLS:
                    flag(node, f"host clock {fq}() in a traced body",
                         "time outside the jit boundary; traced code "
                         "must be replayable")
                elif fq.split(".", 1)[0] == "numpy":
                    flag(node, f"NumPy call {fq}() in a traced body",
                         "use jnp.* (traced) — np.* on a tracer "
                         "crashes or constant-folds on host")
            elif direct and isinstance(node, (ast.If, ast.While)):
                test = node.test
                if isinstance(test, ast.UnaryOp) and isinstance(
                        test.op, ast.Not):
                    test = test.operand
                if isinstance(test, ast.Name) and test.id in params:
                    flag(node,
                         f"host branch on traced parameter "
                         f"{test.id!r}",
                         "use jax.lax.cond/select, or declare the "
                         "arg static")
        return out
