"""R5: metrics/tracing-off fast paths stay allocation-free.

PR 4/10's bit-identity contract: with ``RAFT_TPU_METRICS=off`` and
``RAFT_TPU_TRACING=off`` the instrumented code paths must be a single
boolean test — no label-tuple construction, no f-string formatting, no
lock acquisition, no registry lookups. The emit helpers implement that
by gating on the enabled flag as their FIRST statement and returning
immediately.

The rule pins that shape for the configured helper set: the first
non-docstring statement must be ``if not <flag-or-call>: return ...``.
Anything before the gate — or a missing gate — is a violation, because
every instrumented call site in linalg/solvers pays it even when
observability is off.

``emit_event`` (error-path events) and ``record_failure`` (flight
recorder) are intentionally ALWAYS-ON — error-path observability is
not gated — so they are excluded by construction rather than
baselined.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Tuple

from tools.raftlint.core import Finding, Project, body_statements
from tools.raftlint.rules.base import Rule

# module → helper quals that must lead with an enabled-gate
GATED_HELPERS: Dict[str, Tuple[str, ...]] = {
    "raft_tpu.obs.metrics": (
        "inc", "set_gauge", "observe", "record_convergence",
        "Counter.inc", "Gauge.set", "Histogram.observe",
    ),
    "raft_tpu.obs.spans": ("span", "record_span"),
    "raft_tpu.obs.tracectx": ("mint",),
    # perf attribution (ISSUE 13) gates on its own RAFT_TPU_PERF bool —
    # same first-statement shape, independent switch
    "raft_tpu.obs.perf": (
        "profile_executable", "record_launch", "record_hbm_watermark",
        "profile_session",
    ),
}


def _is_enabled_gate(stmt: ast.stmt) -> bool:
    """``if not <name/attr/call>: return ...`` (optionally ``yield``/
    ``return <null-object>``) as the whole statement."""
    if not isinstance(stmt, ast.If):
        return False
    test = stmt.test
    # `if not _enabled or report is None:` — the leading short-circuit
    # term is the off-path cost, so only it must be the bare flag
    if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.Or):
        test = test.values[0]
    if not (isinstance(test, ast.UnaryOp)
            and isinstance(test.op, ast.Not)):
        return False
    flag = test.operand
    if not isinstance(flag, (ast.Name, ast.Attribute, ast.Call)):
        return False
    if isinstance(flag, ast.Call) and flag.args:
        return False            # enabled() takes no args; anything else
                                # is doing work inside the gate
    body = stmt.body
    return bool(body) and isinstance(body[0], (ast.Return, ast.Expr))


class OffPathPurityRule(Rule):
    id = "R5"
    summary = ("obs emit helper does work before (or without) its "
               "enabled-flag gate")
    rationale = ("PR 4/10's off-path bit-identity: with metrics/"
                 "tracing off the instrumented hot loops must pay one "
                 "boolean test, not allocation/formatting/locking")

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for modname, quals in GATED_HELPERS.items():
            mod = project.modules.get(modname)
            if mod is None:
                continue
            for qual in quals:
                fn = mod.functions.get(qual)
                if fn is None:
                    findings.append(Finding(
                        self.id, mod.relpath, 1, 0,
                        f"{modname}:<module>",
                        f"gated helper {qual} not found — update the "
                        "R5 helper table in "
                        "tools/raftlint/rules/r5_offpath.py",
                        "the off-path contract is only as good as "
                        "this list"))
                    continue
                body = body_statements(fn.node)
                if not body or not _is_enabled_gate(body[0]):
                    findings.append(Finding(
                        self.id, mod.relpath, fn.node.lineno,
                        fn.node.col_offset, fn.symbol,
                        "emit helper must gate on the enabled flag as "
                        "its first statement (single-bool no-op when "
                        "off)",
                        "make `if not <enabled>: return` the first "
                        "statement; allocate labels only after it"))
        return findings
