"""Rule registry. Order is the report order."""

from tools.raftlint.rules.r1_jit_purity import JitPurityRule
from tools.raftlint.rules.r2_recompile import RecompileRule
from tools.raftlint.rules.r3_locks import LockDisciplineRule
from tools.raftlint.rules.r4_errors import ErrorTaxonomyRule
from tools.raftlint.rules.r5_offpath import OffPathPurityRule
from tools.raftlint.rules.r6_obs_imports import ObsBoundaryRule
from tools.raftlint.rules.r7_env import EnvDisciplineRule
from tools.raftlint.rules.r8_numeric import NumericHygieneRule
from tools.raftlint.rules.r9_epilogue import EpilogueLayerRule
from tools.raftlint.rules.r10_donation import DonationSafetyRule
from tools.raftlint.rules.r11_collectives import \
    CollectiveDisciplineRule
from tools.raftlint.rules.r12_layout import LayoutPromotionRule
from tools.raftlint.rules.r13_costmodel import CostModelRule
from tools.raftlint.rules.r14_imports import ImportResolutionRule

ALL_RULES = (
    JitPurityRule,
    RecompileRule,
    LockDisciplineRule,
    ErrorTaxonomyRule,
    OffPathPurityRule,
    ObsBoundaryRule,
    EnvDisciplineRule,
    NumericHygieneRule,
    EpilogueLayerRule,
    DonationSafetyRule,
    CollectiveDisciplineRule,
    LayoutPromotionRule,
    CostModelRule,
    ImportResolutionRule,
)

__all__ = ["ALL_RULES"]
