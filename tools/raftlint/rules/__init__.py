"""Rule registry. Order is the report order."""

from tools.raftlint.rules.r1_jit_purity import JitPurityRule
from tools.raftlint.rules.r2_recompile import RecompileRule
from tools.raftlint.rules.r3_locks import LockDisciplineRule
from tools.raftlint.rules.r4_errors import ErrorTaxonomyRule
from tools.raftlint.rules.r5_offpath import OffPathPurityRule
from tools.raftlint.rules.r6_obs_imports import ObsBoundaryRule
from tools.raftlint.rules.r7_env import EnvDisciplineRule
from tools.raftlint.rules.r8_numeric import NumericHygieneRule
from tools.raftlint.rules.r9_epilogue import EpilogueLayerRule

ALL_RULES = (
    JitPurityRule,
    RecompileRule,
    LockDisciplineRule,
    ErrorTaxonomyRule,
    OffPathPurityRule,
    ObsBoundaryRule,
    EnvDisciplineRule,
    NumericHygieneRule,
    EpilogueLayerRule,
)

__all__ = ["ALL_RULES"]
