"""R13: cost-model coverage — the estimator tables cannot drift.

``runtime/limits.py`` prices ops twice: ``_ESTIMATORS`` (HBM footprint
for admission) and ``_SECONDS_ESTIMATORS`` (the ``(flops, bytes)``
models behind ``estimate_flops_bytes``/``estimate_seconds`` that seed
chunk admission AND the PR-13 roofline denominators). The serve
executor warms and quotes against these by string op name. Three drift
shapes, all statically decidable from the dict literals and the
estimator signatures:

- an op priced by ``estimate_bytes`` (and therefore warmable by the
  serve executor) with **no** ``estimate_flops_bytes`` model — its
  roofline attribution silently falls back or raises at runtime;
- an op present in both tables whose **required dim signatures
  disagree** — a call site satisfying one model crashes the other;
- a **call site** passing a literal op name that is missing from the
  table it targets, or kwargs that do not satisfy the estimator's
  required dims.

Keyword-only parameters with defaults are optional dims; ``**dims``
call sites and non-literal op names stay silent.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.raftlint.core import Finding, FunctionInfo, ModuleInfo, \
    Project
from tools.raftlint.rules.base import Rule

LIMITS_MODULE = "raft_tpu.runtime.limits"
BYTES_TABLE = "_ESTIMATORS"
FB_TABLE = "_SECONDS_ESTIMATORS"

#: public pricing entry point → which table serves it
ENTRY_TABLE = {
    "estimate_bytes": BYTES_TABLE,
    "estimate_flops_bytes": FB_TABLE,
    "estimate_seconds": FB_TABLE,
}
#: kwargs of the entry points that are not estimator dims
NON_DIM_KWARGS = {"backend"}


def _dict_literal(mod: ModuleInfo, name: str) -> Optional[Dict]:
    """{op: FunctionInfo|None} from a module-level ``name = {...}``
    dict literal with string keys and Name values."""
    for node in mod.tree.body:
        if not isinstance(node, ast.Assign):
            continue
        if not any(isinstance(t, ast.Name) and t.id == name
                   for t in node.targets):
            continue
        if not isinstance(node.value, ast.Dict):
            return None
        out: Dict[str, Tuple[Optional[FunctionInfo], ast.AST]] = {}
        for k, v in zip(node.value.keys, node.value.values):
            if not (isinstance(k, ast.Constant)
                    and isinstance(k.value, str)):
                continue
            fn = None
            if isinstance(v, ast.Name):
                fn = mod.functions.get(v.id)
            out[k.value] = (fn, k)
        return out
    return None


def _dims(fn: FunctionInfo) -> Tuple[Set[str], Set[str]]:
    """(required, all) keyword-only dim names of an estimator."""
    a = fn.node.args
    names = [p.arg for p in a.kwonlyargs]
    required = {p.arg for p, d in zip(a.kwonlyargs, a.kw_defaults)
                if d is None}
    # positional params count as required dims too (estimators are
    # conventionally kw-only, but a drifted def should still compare)
    pos = [p.arg for p in a.posonlyargs + a.args]
    required |= set(pos[:len(pos) - len(a.defaults or ())])
    return required, set(names) | set(pos)


class CostModelRule(Rule):
    id = "R13"
    summary = ("op priced for admission with no flops/bytes model, "
               "dim-signature drift between the estimator tables, or "
               "a call site off the table")
    rationale = ("the serve executor's warm quotes, the chunk "
                 "admission deadline checks, and the roofline "
                 "attribution denominators all index these tables by "
                 "op string — a missing or drifted entry turns a "
                 "static pre-launch decision into a runtime "
                 "ValueError on the serving path")

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        limits = None
        for mod in project.modules.values():
            if mod.modname == LIMITS_MODULE or (
                    mod.modname.endswith(".runtime.limits")):
                limits = mod
                break
        if limits is None:
            return findings         # subset scan: nothing to check
        sym = f"{limits.modname}:<module>"
        bytes_tab = _dict_literal(limits, BYTES_TABLE) or {}
        fb_tab = _dict_literal(limits, FB_TABLE) or {}

        for op, (bfn, knode) in sorted(bytes_tab.items()):
            if op not in fb_tab:
                findings.append(Finding(
                    self.id, limits.relpath, knode.lineno,
                    knode.col_offset, sym,
                    f"op '{op}' is priced by {BYTES_TABLE} but has no "
                    f"{FB_TABLE} entry — estimate_flops_bytes raises "
                    "for an op the executor warms and quotes",
                    "add a flops/bytes estimator with the same "
                    "required dims as the footprint estimator"))
                continue
            ffn = fb_tab[op][0]
            if bfn is None or ffn is None:
                continue
            breq, _ = _dims(bfn)
            freq, _ = _dims(ffn)
            if breq != freq:
                findings.append(Finding(
                    self.id, limits.relpath, knode.lineno,
                    knode.col_offset, sym,
                    f"op '{op}' dim signature drift: {BYTES_TABLE} "
                    f"requires {sorted(breq)} but {FB_TABLE} requires "
                    f"{sorted(freq)}",
                    "one op string, one dim vocabulary — mirror the "
                    "required keyword-only params"))

        # call sites across the scanned tree
        by_table = {BYTES_TABLE: bytes_tab, FB_TABLE: fb_tab}
        for mod in project.modules.values():
            for fsym, node in _walk_with_symbols(mod):
                if not isinstance(node, ast.Call):
                    continue
                fq = mod.resolve_local(node.func) or ""
                entry = fq.rsplit(".", 1)[-1]
                if entry not in ENTRY_TABLE or \
                        ".limits." not in f".{fq}" and not \
                        fq.startswith(f"{limits.modname}."):
                    continue
                table = by_table[ENTRY_TABLE[entry]]
                if not node.args or not (
                        isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)):
                    continue        # dynamic op name: silent
                op = node.args[0].value
                if op not in table:
                    findings.append(Finding(
                        self.id, mod.relpath, node.lineno,
                        node.col_offset, fsym,
                        f"{entry}({op!r}) but "
                        f"{ENTRY_TABLE[entry]} has no such op "
                        f"(known: {sorted(table)})",
                        "register the op's estimator or fix the "
                        "string"))
                    continue
                fn = table[op][0]
                if fn is None or any(kw.arg is None
                                     for kw in node.keywords):
                    continue        # **dims call site: silent
                required, allowed = _dims(fn)
                passed = {kw.arg for kw in node.keywords} \
                    - NON_DIM_KWARGS
                missing = required - passed
                unknown = passed - allowed
                if missing or unknown:
                    what = []
                    if missing:
                        what.append(f"missing dims {sorted(missing)}")
                    if unknown:
                        what.append(f"unknown dims {sorted(unknown)}")
                    findings.append(Finding(
                        self.id, mod.relpath, node.lineno,
                        node.col_offset, fsym,
                        f"{entry}({op!r}): " + " and ".join(what)
                        + f" for its estimator (requires "
                          f"{sorted(required)})",
                        "pass exactly the estimator's dim "
                        "vocabulary"))
        findings.sort(key=lambda f: (f.path, f.line, f.col))
        return findings


def _walk_with_symbols(mod: ModuleInfo):
    by_node = {info.node: f"{mod.modname}:{qual}"
               for qual, info in mod.functions.items()}

    def walk(node, sym):
        for child in ast.iter_child_nodes(node):
            child_sym = by_node.get(child, sym)
            yield child_sym, child
            yield from walk(child, child_sym)
    yield from walk(mod.tree, f"{mod.modname}:<module>")
