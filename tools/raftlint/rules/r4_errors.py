"""R4: the typed-error taxonomy.

Every operational failure in the library surfaces as one of the typed
RuntimeError subclasses grown across PRs 1/3/5/7 (CommsError kinds,
NumericalError kinds, DeadlineExceededError/RejectedError,
ArtifactCorruptError, ...), so callers can catch by meaning and the
flight recorder can classify. Three anti-patterns erode it:

- ``raise RuntimeError(...)`` / ``raise Exception(...)`` — an untyped
  operational error callers can only string-match;
- ``except Exception`` / ``except BaseException`` / bare ``except:`` —
  a blanket handler that flattens the taxonomy back into "something
  went wrong" (the old comms and numeric smoke greps, absorbed here
  tree-wide);
- a handler whose body is exactly ``pass`` — a silently swallowed
  error (``contextlib.suppress(SpecificError)`` is the sanctioned
  spelling at well-understood shutdown sites).

Intentional blanket handlers (crash-isolation at thread boundaries,
best-effort probes of optional native runtimes) carry baseline entries
whose ``why`` names the isolation boundary.
"""

from __future__ import annotations

import ast
from typing import List

from tools.raftlint.core import Finding, Project, dotted_parts
from tools.raftlint.rules.base import Rule

UNTYPED_RAISES = {"RuntimeError", "Exception", "BaseException"}
BLANKET = {"Exception", "BaseException"}


class ErrorTaxonomyRule(Rule):
    id = "R4"
    summary = ("untyped raise, blanket except, or silently swallowed "
               "error in library code")
    rationale = ("the typed-error taxonomy (PR 1/3/5/7): operational "
                 "failures must stay catchable by meaning — "
                 "CommsError kinds, NumericalError kinds, deadline/"
                 "admission errors — not by string-matching "
                 "RuntimeError")

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for mod in project.modules.values():
            if not mod.modname.startswith("raft_tpu"):
                continue
            for sym, node in self._walk_with_symbols(mod):
                if isinstance(node, ast.Raise):
                    exc = node.exc
                    name = None
                    if isinstance(exc, ast.Call):
                        parts = dotted_parts(exc.func)
                        name = parts[-1] if parts else None
                    elif exc is not None:
                        parts = dotted_parts(exc)
                        name = parts[-1] if parts else None
                    if name in UNTYPED_RAISES:
                        findings.append(Finding(
                            self.id, mod.relpath, node.lineno,
                            node.col_offset, sym,
                            f"raise {name} is outside the typed-error "
                            "taxonomy",
                            "raise the matching taxonomy type (a "
                            "RuntimeError subclass), so callers catch "
                            "by meaning"))
                elif isinstance(node, ast.ExceptHandler):
                    findings.extend(
                        self._check_handler(mod, sym, node))
        return findings

    def _check_handler(self, mod, sym: str,
                       node: ast.ExceptHandler) -> List[Finding]:
        out: List[Finding] = []
        names: List[str] = []
        if node.type is None:
            names = ["<bare>"]
        else:
            exprs = (node.type.elts
                     if isinstance(node.type, ast.Tuple)
                     else [node.type])
            for e in exprs:
                parts = dotted_parts(e)
                if parts:
                    names.append(parts[-1])
        if "<bare>" in names:
            out.append(Finding(
                self.id, mod.relpath, node.lineno, node.col_offset,
                sym, "bare 'except:' swallows everything including "
                "KeyboardInterrupt",
                "catch the typed taxonomy error this site expects"))
        elif any(n in BLANKET for n in names):
            bad = next(n for n in names if n in BLANKET)
            out.append(Finding(
                self.id, mod.relpath, node.lineno, node.col_offset,
                sym, f"blanket 'except {bad}' flattens the typed-error "
                "taxonomy",
                "catch the typed kinds (CommsError/NumericalError/"
                "...), or baseline this crash-isolation boundary"))
        if (len(node.body) == 1 and isinstance(node.body[0], ast.Pass)
                and "<bare>" not in names
                and not any(n in BLANKET for n in names)):
            out.append(Finding(
                self.id, mod.relpath, node.lineno, node.col_offset,
                sym, f"silent 'except {'/'.join(names) or '?'}: pass' "
                "swallows the error invisibly",
                "use contextlib.suppress(...) at a named shutdown "
                "site, or surface a typed error"))
        return out

    @staticmethod
    def _walk_with_symbols(mod):
        """(symbol, node) pairs with the enclosing def tracked."""
        def walk(node, sym):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    inner = None
                    for qual, info in mod.functions.items():
                        if info.node is child:
                            inner = f"{mod.modname}:{qual}"
                            break
                    yield from walk(child, inner or sym)
                else:
                    yield sym, child
                    yield from walk(child, sym)
        yield from walk(mod.tree, f"{mod.modname}:<module>")
