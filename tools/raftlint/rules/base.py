"""Rule plugin protocol: a rule sees the whole Project and returns
Findings. Rules carry their id/summary/hint as class attributes so the
CLI's --list-rules and docs stay generated from one source."""

from __future__ import annotations

from typing import List

from tools.raftlint.core import Finding, Project


class Rule:
    id = "R0"
    summary = ""
    # the PR-era guarantee this rule protects (docs/raftlint.md pulls it)
    rationale = ""

    def run(self, project: Project) -> List[Finding]:
        raise NotImplementedError
