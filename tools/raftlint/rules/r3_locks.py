"""R3: lock discipline for the threaded classes.

Any class that owns a lock attribute (``self._lock = threading.Lock()``
and friends, including a Condition wrapping the lock) promises that its
mutable fields are written under that lock. The rule flags writes to
``self``-rooted attribute chains (``self.stats.rejected += 1``,
``self._started = True``, ``self._q[k] = v``) in method bodies that are
not lexically inside a ``with self.<lock>`` block.

Two refinements keep it honest on real code:

- ``__init__``/``__new__``/``__enter__`` construct the object before it
  escapes to other threads, so they are exempt;
- a private helper whose every intra-class call site sits inside a
  locked context inherits that context (fixed point over the class's
  call sites) — the ``RequestQueue._drain`` pattern, where the lock is
  taken by the public entry points.

The same pass builds a lock-acquisition-order graph — edge A→B when a
``with B`` (or a call to a method that takes B) appears lexically inside
a ``with A`` — and reports any cycle: two threads entering the cycle
from different ends deadlock, which no dynamic test reliably catches.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.raftlint.core import (
    ClassInfo, Finding, FunctionInfo, Project, self_attr_chain)
from tools.raftlint.rules.base import Rule

CONSTRUCTORS = {"__init__", "__new__", "__post_init__"}


def _with_locks(node: ast.With, lock_attrs: Set[str]) -> Set[str]:
    """Lock attrs acquired by this with-statement (``with self._lock:``,
    ``with self._cv:``)."""
    out: Set[str] = set()
    for item in node.items:
        chain = self_attr_chain(item.context_expr)
        if chain and len(chain) == 1 and chain[0] in lock_attrs:
            out.add(chain[0])
        # with self._cv.acquire_timeout(...) style: root attr still names
        # the lock
        elif chain and chain[0] in lock_attrs:
            out.add(chain[0])
    return out


class _MethodScan(ast.NodeVisitor):
    """Collect, per method: unlocked self-field writes, self-method call
    sites with their lock context, and lock nesting edges."""

    def __init__(self, cls: ClassInfo) -> None:
        self.cls = cls
        self.lock_stack: List[str] = []
        self.unlocked_writes: List[Tuple[ast.AST, str]] = []
        self.calls: List[Tuple[str, bool]] = []   # (method, under_lock)
        self.edges: Set[Tuple[str, str]] = set()
        self.acquires_any = False

    # -- helpers ------------------------------------------------------------

    def _record_write(self, target: ast.AST) -> None:
        node = target
        while isinstance(node, ast.Subscript):
            node = node.value          # self._q[k] = v writes self._q
        if isinstance(node, (ast.Tuple, ast.List)):
            for elt in node.elts:
                self._record_write(elt)
            return
        if isinstance(node, ast.Starred):
            self._record_write(node.value)
            return
        chain = self_attr_chain(node)
        if chain is None:
            return
        if chain[0] in self.cls.lock_attrs:
            return                     # assigning the lock itself
        if not self.lock_stack:
            self.unlocked_writes.append((target, ".".join(chain)))

    # -- visitors -----------------------------------------------------------

    def visit_With(self, node: ast.With) -> None:
        got = _with_locks(node, self.cls.lock_attrs)
        if got:
            self.acquires_any = True
            for held in self.lock_stack:
                for new in got:
                    if held != new:
                        self.edges.add((held, new))
            self.lock_stack.extend(sorted(got))
            for child in node.body:
                self.visit(child)
            del self.lock_stack[len(self.lock_stack) - len(got):]
        else:
            self.generic_visit(node)

    visit_AsyncWith = visit_With

    def visit_Assign(self, node: ast.Assign) -> None:
        for tgt in node.targets:
            self._record_write(tgt)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._record_write(node.target)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._record_write(node.target)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        chain = self_attr_chain(node.func)
        if chain is not None:
            if len(chain) == 1 and chain[0] in self.cls.methods:
                self.calls.append((chain[0], bool(self.lock_stack)))
            elif (len(chain) == 2 and chain[0] in self.cls.lock_attrs
                    and chain[1] in ("acquire", "acquire_lock")):
                # manual acquire: treat the whole method as mixed-style
                # and skip flagging rather than misjudge scopes
                self.acquires_any = True
                self.lock_stack.append(chain[0])
        self.generic_visit(node)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        pass          # nested defs have their own discipline

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef


class LockDisciplineRule(Rule):
    id = "R3"
    summary = ("field write outside the owning lock, or a lock-order "
               "cycle")
    rationale = ("the threaded serve/comms/obs stack (PR 7/9/10): "
                 "RequestQueue, Replica, TagStore, and the metric "
                 "families are mutated from executor threads, router "
                 "threads, and timeout sweepers concurrently")

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        order_edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}

        for mod in project.modules.values():
            for cls in mod.classes.values():
                if not cls.lock_attrs:
                    continue
                scans: Dict[str, _MethodScan] = {}
                for name, meth in cls.methods.items():
                    scan = _MethodScan(cls)
                    for stmt in meth.node.body:
                        scan.visit(stmt)
                    scans[name] = scan

                # fixed point: a private method whose every intra-class
                # call site is under the lock is itself lock-guarded
                guarded: Set[str] = set()
                changed = True
                while changed:
                    changed = False
                    callers: Dict[str, List[Tuple[str, bool]]] = {}
                    for caller, scan in scans.items():
                        for callee, locked in scan.calls:
                            callers.setdefault(callee, []).append(
                                (caller,
                                 locked or caller in guarded))
                    for name in cls.methods:
                        if name in guarded or not name.startswith("_"):
                            continue
                        sites = callers.get(name, [])
                        if sites and all(lk for _, lk in sites):
                            guarded.add(name)
                            changed = True

                for name, meth in cls.methods.items():
                    if name in CONSTRUCTORS or name in guarded:
                        continue
                    scan = scans[name]
                    for node, field in scan.unlocked_writes:
                        findings.append(Finding(
                            self.id, mod.relpath, node.lineno,
                            node.col_offset, meth.symbol,
                            f"self.{field} written outside "
                            f"'with self.{sorted(cls.lock_attrs)[0]}' "
                            f"(class {cls.name} owns "
                            f"{sorted(cls.lock_attrs)})",
                            "move the write under the lock, or add a "
                            "baseline entry explaining why this field "
                            "is single-threaded"))
                    for a, b in scan.edges:
                        key = (f"{mod.modname}.{cls.name}.{a}",
                               f"{mod.modname}.{cls.name}.{b}")
                        order_edges.setdefault(
                            key, (mod.relpath,
                                  meth.node.lineno, meth.symbol))

        findings.extend(self._order_cycles(order_edges))
        return findings

    def _order_cycles(self, edges) -> List[Finding]:
        graph: Dict[str, Set[str]] = {}
        for (a, b) in edges:
            graph.setdefault(a, set()).add(b)
        seen: Set[str] = set()
        findings: List[Finding] = []
        reported: Set[frozenset] = set()

        def dfs(node: str, stack: List[str]) -> None:
            if node in stack:
                cycle = stack[stack.index(node):] + [node]
                key = frozenset(cycle)
                if key not in reported:
                    reported.add(key)
                    a, b = cycle[0], cycle[1]
                    rel, line, sym = edges[(a, b)]
                    findings.append(Finding(
                        self.id, rel, line, 0, sym,
                        "lock-acquisition-order cycle: "
                        + " -> ".join(cycle),
                        "pick one global order for these locks and "
                        "acquire in that order everywhere"))
                return
            if node in seen:
                return
            seen.add(node)
            for nxt in graph.get(node, ()):
                dfs(nxt, stack + [node])

        for start in sorted(graph):
            dfs(start, [])
        return findings
