"""R9: epilogue primitives live in matrix/epilogue.py — nowhere else.

ISSUE 14 deleted the hand-rolled copies of the iota-compare argmin and
the one-hot construction machinery (kmeans' mnmg block one-hot,
radix_select's histogram/emission one-hots, the fused-kNN drain's
argmin) and moved the single implementation into
``raft_tpu.matrix.epilogue``. This rule keeps that duplication deleted:
outside the epilogue module, raft_tpu code must not

- build a one-hot by wrapping an inline ``jax.lax.broadcasted_iota``
  equality compare in ``.astype(...)`` (the one-hot histogram /
  assignment spelling — use ``epilogue.assign_onehot`` /
  ``label_onehot`` / ``onehot_pair`` / ``onehot_histogram``);
- call ``jax.nn.one_hot`` (use ``epilogue.label_onehot`` — same 0/1
  output, one reviewed spelling, and the out-of-range-label contract
  is documented there);
- call ``jax.lax.argmin`` / ``jax.lax.argmax`` (use
  ``epilogue.argmin_ref`` on reference paths and
  ``epilogue.iota_argmin`` in kernels — lax.argmin's variadic-reduce
  lowering fails Mosaic legalization, so a stray call is either a
  future kernel bug or a reference path drifting off the shared tie
  contract).

Plain iota arithmetic (column masks, offsets, triangular masks,
ordered compares) stays legal everywhere — only the astype-wrapped
EQUALITY compare of an inline iota is the one-hot idiom this rule
polices.
"""

from __future__ import annotations

import ast
from typing import List

from tools.raftlint.core import Finding, ModuleInfo, Project
from tools.raftlint.rules.base import Rule

ALLOWED = ("raft_tpu.matrix.epilogue",)
BANNED_CALLS = {
    "jax.nn.one_hot": (
        "jax.nn.one_hot outside the epilogue layer",
        "use raft_tpu.matrix.epilogue.label_onehot"),
    "jax.lax.argmin": (
        "jax.lax.argmin outside the epilogue layer",
        "use epilogue.argmin_ref (reference) / epilogue.iota_argmin "
        "(kernels — lax.argmin fails Mosaic legalization)"),
    "jax.lax.argmax": (
        "jax.lax.argmax outside the epilogue layer",
        "use the epilogue argmin family on negated values"),
}


def _in_scope(modname: str) -> bool:
    return (modname.startswith("raft_tpu.")
            and modname not in ALLOWED)


def _has_inline_iota_eq(mod: ModuleInfo, node: ast.AST) -> bool:
    """An equality Compare anywhere under ``node`` with an inline
    jax.lax.broadcasted_iota call in its subtree — the hand-rolled
    one-hot construction."""
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Compare):
            continue
        if not any(isinstance(op, ast.Eq) for op in sub.ops):
            continue
        for part in ast.walk(sub):
            if (isinstance(part, ast.Call)
                    and mod.resolve(part.func)
                    == "jax.lax.broadcasted_iota"):
                return True
    return False


class EpilogueLayerRule(Rule):
    id = "R9"
    summary = ("argmin / one-hot epilogue machinery re-rolled outside "
               "matrix/epilogue.py")
    rationale = ("ISSUE 14 unified the iota-argmin, one-hot, and drain "
                 "epilogues into one measured module so levers land in "
                 "every consumer at once — a re-rolled copy silently "
                 "stops receiving them and re-opens the tie/NaN "
                 "contract drift the bit-identity gates closed")

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for mod in project.modules.values():
            if not _in_scope(mod.modname):
                continue
            for sym, node in self._walk(mod):
                if not isinstance(node, ast.Call):
                    continue
                fq = mod.resolve(node.func)
                if fq in BANNED_CALLS:
                    msg, hint = BANNED_CALLS[fq]
                    findings.append(Finding(
                        self.id, mod.relpath, node.lineno,
                        node.col_offset, sym, msg, hint))
                elif (isinstance(node.func, ast.Attribute)
                        and node.func.attr == "astype"
                        and _has_inline_iota_eq(mod, node.func.value)):
                    findings.append(Finding(
                        self.id, mod.relpath, node.lineno,
                        node.col_offset, sym,
                        "hand-rolled one-hot (astype of an inline "
                        "broadcasted_iota equality compare) outside "
                        "the epilogue layer",
                        "use epilogue.assign_onehot / label_onehot / "
                        "onehot_pair / onehot_histogram / slot_onehot"))
        return findings

    @staticmethod
    def _walk(mod: ModuleInfo):
        by_node = {info.node: f"{mod.modname}:{qual}"
                   for qual, info in mod.functions.items()}

        def walk(node, sym):
            for child in ast.iter_child_nodes(node):
                child_sym = by_node.get(child, sym)
                yield child_sym, child
                yield from walk(child, child_sym)
        yield from walk(mod.tree, f"{mod.modname}:<module>")
