"""R8: annotated numerical breakdown sites in the solver layers.

The AST port of the numeric error-hygiene lint (ISSUE 3): in
``raft_tpu/linalg/`` and ``raft_tpu/sparse/solver/``, a ``jnp.sqrt``
whose operand can silently go negative, or a division by a computed
``jnp.linalg.norm`` (zero vectors divide to NaN/inf), must either carry
a visible guard — ``maximum``/``abs``/``clip``/eps floor — or an
explanatory ``# guarded: <why>`` comment naming why the operand cannot
break. The guard/annotation vocabulary is unchanged from the grep so
every previously-clean line stays clean; the upgrade is that the check
now fires on the *call site* (AST node), not on raw line text, so
string literals and comments can no longer satisfy or dodge it by
accident.
"""

from __future__ import annotations

import ast
from typing import List

from tools.raftlint.core import Finding, ModuleInfo, Project
from tools.raftlint.rules.base import Rule

SCOPES = ("raft_tpu.linalg.", "raft_tpu.sparse.solver.")
GUARD_TOKENS = ("maximum", "abs", "clip", "eps", "finfo", "1.0 +",
                "guarded:")


def _in_scope(modname: str) -> bool:
    return any(modname.startswith(s) or modname == s.rstrip(".")
               for s in SCOPES)


def _guarded(mod: ModuleInfo, node: ast.AST) -> bool:
    """Guard token anywhere on the source lines the expression spans
    (same vocabulary as the original grep, including the `# guarded:`
    annotation escape hatch)."""
    start = node.lineno
    end = getattr(node, "end_lineno", start) or start
    text = "\n".join(mod.lines[start - 1:end])
    return any(tok in text for tok in GUARD_TOKENS)


class NumericHygieneRule(Rule):
    id = "R8"
    summary = ("unguarded sqrt / norm-divide breakdown site in the "
               "solver layers")
    rationale = ("ISSUE 3's numerical sentinels: a sqrt of a silently-"
                 "negative operand or a divide by a zero norm "
                 "manufactures NaN/inf that the guard machinery then "
                 "has to chase — annotate or clamp at the source")

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for mod in project.modules.values():
            if not _in_scope(mod.modname):
                continue
            for sym, node in self._walk(mod):
                if isinstance(node, ast.Call):
                    fq = mod.resolve(node.func)
                    if fq == "jax.numpy.sqrt" and not _guarded(mod,
                                                               node):
                        findings.append(Finding(
                            self.id, mod.relpath, node.lineno,
                            node.col_offset, sym,
                            "unguarded jnp.sqrt — the operand can "
                            "silently go negative",
                            "clamp it (jnp.maximum(x, 0)) or annotate "
                            "'# guarded: <why it cannot>'"))
                elif isinstance(node, ast.BinOp) and isinstance(
                        node.op, ast.Div):
                    right = node.right
                    if (isinstance(right, ast.Call)
                            and mod.resolve(right.func)
                            == "jax.numpy.linalg.norm"
                            and not _guarded(mod, node)):
                        findings.append(Finding(
                            self.id, mod.relpath, node.lineno,
                            node.col_offset, sym,
                            "unguarded divide by jnp.linalg.norm — "
                            "zero vectors divide to NaN/inf",
                            "floor the norm (jnp.maximum(n, eps)) or "
                            "annotate '# guarded: <why>'"))
        return findings

    @staticmethod
    def _walk(mod: ModuleInfo):
        by_node = {info.node: f"{mod.modname}:{qual}"
                   for qual, info in mod.functions.items()}

        def walk(node, sym):
            for child in ast.iter_child_nodes(node):
                child_sym = by_node.get(child, sym)
                yield child_sym, child
                yield from walk(child, child_sym)
        yield from walk(mod.tree, f"{mod.modname}:<module>")
