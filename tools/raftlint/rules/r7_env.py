"""R7: all RAFT_TPU_* environment reads go through core/env.py.

The knob registry (``raft_tpu/core/env.py``) is the single place where
a ``RAFT_TPU_*`` variable's parser, default, and malformed-value policy
live — that is what makes the fail-loud-vs-warn-fallback contract
testable and the docs' knob inventory complete. A direct
``os.environ[...]`` / ``os.environ.get(...)`` / ``os.getenv(...)`` read
with a ``RAFT_TPU_`` key anywhere else reintroduces an undeclared knob
with ad-hoc parsing.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from tools.raftlint.core import Finding, Project, dotted_parts
from tools.raftlint.rules.base import Rule

REGISTRY_MODULE = "raft_tpu.core.env"
PREFIX = "RAFT_TPU_"


def _literal_key(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


class EnvDisciplineRule(Rule):
    id = "R7"
    summary = "direct RAFT_TPU_* env read outside the core/env registry"
    rationale = ("the knob registry (this PR): one table of name -> "
                 "parser -> default -> malformed policy, so a typo'd "
                 "limit can never silently change behavior and the "
                 "docs' knob inventory stays complete")

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for mod in project.modules.values():
            if not mod.modname.startswith("raft_tpu"):
                continue
            if mod.modname == REGISTRY_MODULE:
                continue
            for node in ast.walk(mod.tree):
                key = None
                if isinstance(node, ast.Call):
                    fq = mod.resolve(node.func)
                    parts = dotted_parts(node.func)
                    is_get = (fq in ("os.getenv", "os.environ.get")
                              or (parts is not None and len(parts) >= 2
                                  and parts[-2:] in (["environ", "get"],)
                                  ))
                    if is_get and node.args:
                        key = _literal_key(node.args[0])
                elif isinstance(node, ast.Subscript):
                    parts = dotted_parts(node.value)
                    if parts and parts[-1] == "environ":
                        key = _literal_key(
                            node.slice if not isinstance(
                                node.slice, ast.Index)
                            else node.slice.value)  # py<3.9 compat
                if key and key.startswith(PREFIX):
                    findings.append(Finding(
                        self.id, mod.relpath, node.lineno,
                        node.col_offset,
                        f"{mod.modname}:<module>",
                        f"direct environment read of {key} bypasses "
                        "the knob registry",
                        "declare the knob in raft_tpu/core/env.py and "
                        "call env.read(name)"))
        return findings
