"""raftlint: AST-level invariant checker for the raft_tpu tree.

Static teeth for the disciplines the repo's PRs established at runtime:
jit purity, recompile hazards, lock discipline, the typed-error
taxonomy, off-path purity, the obs API boundary, env-knob registration,
and annotated numerical breakdown sites. See docs/raftlint.md for the
rule catalog and tools/raftlint/baseline.json for the waived debt.
"""

from tools.raftlint.core import Finding, Project  # noqa: F401
from tools.raftlint.rules import ALL_RULES        # noqa: F401
