"""Compiled-Pallas smoke tier on real TPU hardware (VERDICT #10): the CPU
suite exercises kernels through the interpreter only, so Mosaic layout
regressions (like the v5e (1, m) stats-layout constraints found manually in
round 1) could hide. This tier compiles every raft_tpu Pallas kernel on the
chip and checks numerics against oracles — including inside shard_map,
where it asserts the REAL kernel lowered (no fallback; VERDICT #3's
"fails if the fallback triggers" test).
"""

import os

import numpy as np
import pytest


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(11)


def _l2_oracle(x, y):
    return ((x[:, None, :] - y[None, :, :]) ** 2).sum(-1)


class TestCompiledKernels:
    def test_pairwise_l2(self, rng):
        from raft_tpu.linalg.contractions import pairwise_l2_pallas

        x = rng.normal(size=(300, 70)).astype(np.float32)
        y = rng.normal(size=(150, 70)).astype(np.float32)
        d = np.asarray(pairwise_l2_pallas(x, y))
        np.testing.assert_allclose(d, _l2_oracle(x, y), rtol=1e-3,
                                   atol=1e-3)

    @pytest.mark.parametrize("m,n,k", [(257, 31, 19), (2000, 700, 40)])
    def test_fused_argmin(self, rng, m, n, k):
        from raft_tpu.linalg.contractions import fused_l2_argmin_pallas

        x = rng.normal(size=(m, k)).astype(np.float32)
        y = rng.normal(size=(n, k)).astype(np.float32)
        ref = _l2_oracle(x, y)
        val, idx = fused_l2_argmin_pallas(x, y)
        # expansion-formula f32 noise flips near-ties: compare by achieved
        # distance, and demand near-total index agreement
        assert (np.asarray(idx) == ref.argmin(1)).mean() > 0.99
        np.testing.assert_allclose(np.asarray(val), ref.min(1), rtol=1e-2,
                                   atol=1e-2)

    def test_fused_argmin_tiled_path(self, rng):
        """Y past VMEM residency → the 2-axis running-min kernel compiles
        and agrees with the resident path's tie rule."""
        from raft_tpu.linalg.contractions import _pick_tm, \
            fused_l2_argmin_pallas

        x = rng.normal(size=(64, 24)).astype(np.float32)
        y = rng.normal(size=(20000, 24)).astype(np.float32)
        assert _pick_tm(128, 20096, mn_bufs=2,
                        const_bytes=20096 * 128 * 4) is None
        ref = _l2_oracle(x, y)
        val, idx = fused_l2_argmin_pallas(x, y)
        assert (np.asarray(idx) == ref.argmin(1)).mean() > 0.99

    def test_fused_lloyd(self, rng):
        from raft_tpu.linalg.contractions import fused_lloyd_pallas

        x = rng.normal(size=(1000, 33)).astype(np.float32)
        y = rng.normal(size=(37, 33)).astype(np.float32)
        sums, counts, val, idx = fused_lloyd_pallas(x, y)
        lab = np.asarray(idx)
        sums_ref = np.zeros_like(y)
        np.add.at(sums_ref, lab, x)
        np.testing.assert_allclose(np.asarray(sums), sums_ref, rtol=1e-3,
                                   atol=1e-3)
        np.testing.assert_array_equal(
            np.asarray(counts), np.bincount(lab, minlength=37))
        assert int(counts.sum()) == 1000

    def test_select_k(self, rng):
        from raft_tpu.matrix import SelectAlgo, select_k

        v = rng.normal(size=(8, 40000)).astype(np.float32)
        for k, algo in ((50, SelectAlgo.AUTO), (50, SelectAlgo.RADIX_11BITS),
                        (9000, SelectAlgo.RADIX_11BITS),
                        (50, SelectAlgo.WARPSORT_FILTERED)):  # stream path
            ov, oi = select_k(None, v, k, algo=algo)
            np.testing.assert_allclose(np.asarray(ov),
                                       np.sort(v, 1)[:, :k], rtol=1e-6)

    def test_pairwise_cosine_compiled(self, rng):
        from raft_tpu.linalg.contractions import pairwise_pallas

        x = rng.normal(size=(200, 40)).astype(np.float32)
        y = rng.normal(size=(90, 40)).astype(np.float32)
        d = np.asarray(pairwise_pallas(x, y, metric="cosine"))
        xn = np.linalg.norm(x, axis=1, keepdims=True)
        yn = np.linalg.norm(y, axis=1, keepdims=True)
        np.testing.assert_allclose(d, 1 - (x @ y.T) / (xn * yn.T),
                                   rtol=1e-3, atol=1e-3)

    def test_knn_compiled(self, rng):
        from raft_tpu.neighbors import knn

        db = rng.normal(size=(3000, 32)).astype(np.float32)
        q = rng.normal(size=(64, 32)).astype(np.float32)
        d, i = knn(None, db, q, k=10, metric="euclidean", tile=1024)
        ref = np.sqrt(((q[:, None, :] - db[None, :, :]) ** 2).sum(-1))
        order = np.argsort(ref, axis=1)[:, :10]
        assert (np.asarray(i) == order).mean() > 0.99

    def test_precision_tiers_on_mxu(self, rng):
        """The tier contract holds on real hardware: 'high' (bf16 hi/lo
        split) lands ~2^-17 of the f64 oracle, 500× tighter than one
        bf16 pass; 'highest' lands at f32 scale. Regression here means
        Mosaic changed dot lowering or the split was broken."""
        import raft_tpu
        from raft_tpu.linalg.contractions import pairwise_l2_pallas

        x = rng.normal(size=(512, 96)).astype(np.float32)
        y = rng.normal(size=(256, 96)).astype(np.float32)
        ref = ((x[:, None, :].astype(np.float64)
                - y[None, :, :].astype(np.float64)) ** 2).sum(-1)
        old = raft_tpu.get_matmul_precision()
        try:
            bounds = {"highest": 3e-6, "high": 3e-5, "default": 3e-2}
            for tier, bound in bounds.items():
                raft_tpu.set_matmul_precision(tier)
                d = np.asarray(pairwise_l2_pallas(x, y)).astype(np.float64)
                rel = (np.abs(d - ref)
                       / np.maximum(np.abs(ref), 1e-9)).max()
                assert rel < bound, (tier, rel)
        finally:
            raft_tpu.set_matmul_precision(old)

    def test_bitset_sorted_path_compiled(self, rng):
        """The no-scatter sort+cumsum set() path (large index sets) on
        real hardware, against numpy."""
        from raft_tpu.core.bitset import Bitset, _SORT_THRESHOLD

        n = 200_000
        ids = rng.integers(0, n, size=_SORT_THRESHOLD * 4)
        bs = Bitset(n, default_value=False).set(ids.astype(np.int32))
        want = np.zeros(n, dtype=bool)
        want[ids] = True
        assert int(bs.count()) == int(want.sum())
        np.testing.assert_array_equal(np.asarray(bs.to_bools()), want)

    def test_spmv_csr_and_ell(self, rng):
        import scipy.sparse as sp

        from raft_tpu.core.sparse_types import CSRMatrix
        from raft_tpu.sparse.ell import from_csr, spmv as ell_spmv
        from raft_tpu.sparse.linalg import spmv

        a = sp.random(500, 400, density=0.05, random_state=7,
                      dtype=np.float64).astype(np.float32).tocsr()
        x = rng.normal(size=400).astype(np.float32)
        csr = CSRMatrix.from_scipy(a)
        y1 = np.asarray(spmv(csr, x))
        y2 = np.asarray(ell_spmv(from_csr(csr), x))
        ref = a @ x
        np.testing.assert_allclose(y1, ref, rtol=1e-3, atol=1e-4)
        np.testing.assert_allclose(y2, ref, rtol=1e-3, atol=1e-4)


class TestShardMapCompiled:
    """The kernels must lower to Mosaic INSIDE shard_map with
    check_vma=True — bit-identical to the out-of-shard_map kernel, with a
    tpu_custom_call visibly present in the compiled HLO."""

    def test_lloyd_in_shard_map_is_real_kernel(self, rng):
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, PartitionSpec as P

        from raft_tpu.linalg.contractions import fused_lloyd_pallas

        x = rng.normal(size=(512, 40)).astype(np.float32)
        c = rng.normal(size=(24, 40)).astype(np.float32)
        s0, cnt0, v0, i0 = [np.asarray(a)
                            for a in fused_lloyd_pallas(x, c)]

        mesh = Mesh(np.asarray(jax.devices()[:1]), ("data",))

        def f(xs, cs):
            s, cnt, v, i = fused_lloyd_pallas(xs, cs)
            return (jax.lax.psum(s, "data"), jax.lax.psum(cnt, "data"),
                    v, i)

        g = jax.jit(jax.shard_map(
            f, mesh=mesh, in_specs=(P("data"), P()),
            out_specs=(P(), P(), P("data"), P("data"))))
        hlo = g.lower(x, c).compile().as_text()
        assert "tpu_custom_call" in hlo, \
            "fused kernel fell back to jnp inside shard_map"
        s, cnt, v, i = [np.asarray(a) for a in g(x, c)]
        np.testing.assert_array_equal(i, i0)
        np.testing.assert_array_equal(v, v0)
        np.testing.assert_array_equal(s, s0)
        np.testing.assert_array_equal(cnt, cnt0)

    def test_full_mnmg_step_hlo_contains_kernel(self, rng):
        import functools

        import jax
        from jax.sharding import Mesh, PartitionSpec as P

        from raft_tpu.cluster.kmeans import mnmg_lloyd_step

        x = rng.normal(size=(256, 32)).astype(np.float32)
        c = rng.normal(size=(16, 32)).astype(np.float32)
        mesh = Mesh(np.asarray(jax.devices()[:1]), ("data",))
        step = jax.jit(jax.shard_map(
            functools.partial(mnmg_lloyd_step, n_clusters=16,
                              data_axis="data"),
            mesh=mesh, in_specs=(P("data"), P()),
            out_specs=(P(), P(), P("data"))))
        hlo = step.lower(x, c).compile().as_text()
        assert "tpu_custom_call" in hlo
        new_c, inertia, labels = step(x, c)
        assert np.isfinite(float(inertia))


class TestAdversarialOnChip:
    """Promoted adversarial cases (round-3; full tier in
    tests/test_adversarial.py): NaN/inf total-order and duplicate ties
    must hold through the REAL XLA:TPU sort, not just the CPU emulator,
    and low-precision select_k must survive TPU layouts."""

    def test_select_k_nan_inf_total_order(self):
        from raft_tpu.matrix import select_k

        x = np.array([[4., np.nan, 1., 2., np.inf, -np.inf]], np.float32)
        v, i = select_k(None, x, k=3, select_min=True)
        assert np.asarray(v).tolist() == [[-np.inf, 1.0, 2.0]]
        v, i = select_k(None, x, k=2, select_min=False)
        out = np.asarray(v)[0]
        assert np.isnan(out[0]) and out[1] == np.inf

    def test_select_k_duplicate_ties_tiled(self):
        from raft_tpu.matrix import SelectAlgo, select_k

        wide = np.full((2, 20_000), 3.0, np.float32)
        wide[:, 777] = 1.0
        wide[:, 778] = 1.0
        v, i = select_k(None, wide, k=3, select_min=True,
                        algo=SelectAlgo.RADIX_11BITS)
        assert np.asarray(i).tolist() == [[777, 778, 0]] * 2

    def test_select_k_low_precision_dtypes(self, rng):
        from raft_tpu.matrix import select_k

        xh = rng.normal(size=(4, 600)).astype(np.float16)
        v, _ = select_k(None, xh, k=7, select_min=True)
        np.testing.assert_array_equal(np.asarray(v),
                                      np.sort(xh, 1)[:, :7])
        xi = rng.integers(-120, 120, size=(4, 600)).astype(np.int8)
        v, _ = select_k(None, xi, k=7, select_min=False)
        np.testing.assert_array_equal(np.asarray(v),
                                      np.sort(xi, 1)[:, ::-1][:, :7])

    def test_lloyd_prepared_bit_identical_on_chip(self, rng):
        """The hoisted-operand Lloyd path (what bench.py times at tier
        'high') must be bit-identical to the plain fused call ON THE
        CHIP — the shared tile plan guarantees it structurally; this
        gates it against Mosaic layout/lowering drift."""
        import raft_tpu
        from raft_tpu.linalg.contractions import (fused_lloyd_pallas,
                                                  fused_lloyd_prepared,
                                                  lloyd_prepare)

        old = raft_tpu.get_matmul_precision()
        try:
            raft_tpu.set_matmul_precision("high")
            x = rng.normal(size=(1500, 48)).astype(np.float32)
            c = rng.normal(size=(64, 48)).astype(np.float32)
            ops, meta = lloyd_prepare(x, 64)
            assert ops is not None
            ref = fused_lloyd_pallas(x, c)
            got = fused_lloyd_prepared(ops, c, **meta)
            for a, b, name in zip(ref, got,
                                  ("sums", "counts", "dist", "labels")):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                              err_msg=name)
        finally:
            raft_tpu.set_matmul_precision(old)

    def test_packed_split_equivalence_on_chip(self, rng):
        """The depth-packed bf16x3 spelling must Mosaic-COMPILE and agree
        with the 3-dot spelling on real hardware (CPU interpret already
        pins this; chip layouts are the remaining risk). Gate for ever
        flipping RAFT_TPU_SPLIT_PACKED on by default."""
        import raft_tpu
        from raft_tpu.linalg.contractions import fused_lloyd_pallas

        old = raft_tpu.get_matmul_precision()
        try:
            raft_tpu.set_matmul_precision("high")
            x = rng.normal(size=(512, 64)).astype(np.float32)
            c = rng.normal(size=(96, 64)).astype(np.float32)
            ref = fused_lloyd_pallas(x, c, packed=False)
            got = fused_lloyd_pallas(x, c, packed=True)
            for a, b, name in zip(ref, got,
                                  ("sums", "counts", "dist", "labels")):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=1e-5, atol=1e-5,
                                           err_msg=name)
        finally:
            raft_tpu.set_matmul_precision(old)


class TestChunkedRadixKnnOnChip:
    """The chunked-radix kNN path compiled on hardware: distance blocks
    via the Pallas pairwise kernel, per-chunk radix select (both Mosaic
    kernels), scan-merged — at a shape that actually crosses the
    dispatch gate AND spans multiple chunks."""

    def test_knn_chunked_matches_oracle(self):
        from raft_tpu.neighbors.brute_force import _knn_chunked

        rng = np.random.default_rng(31)
        db = rng.normal(size=(50000, 24)).astype(np.float32)
        q = rng.normal(size=(128, 24)).astype(np.float32)
        import jax.numpy as jnp
        v, i = _knn_chunked(jnp.asarray(q), jnp.asarray(db), 32, 16384,
                            "l2")
        d2 = ((q[:, None].astype(np.float64)
               - db[None].astype(np.float64)) ** 2).sum(-1)
        order = np.argsort(d2, axis=1, kind="stable")[:, :32]
        agree = (np.asarray(i) == order).mean()
        assert agree > 0.999, agree


class TestShardMapRadixSelect:
    """Radix-select kernels inside shard_map with check_vma=True on the
    chip: the vma plumbing (join_vma + vma out_shapes) must produce the
    same result as the out-of-shard_map kernel, with the tpu_custom_call
    present in the compiled HLO. Green here gates flipping knn_mnmg's
    shard body to the chunked-radix path."""

    def test_select_k_radix_in_shard_map(self, rng):
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, PartitionSpec as P

        from raft_tpu.matrix.radix_select import radix_select_k

        v = rng.normal(size=(16, 9000)).astype(np.float32)
        v0, i0 = [np.asarray(a) for a in radix_select_k(v, 64)]

        mesh = Mesh(np.asarray(jax.devices()[:1]), ("data",))
        g = jax.jit(jax.shard_map(
            lambda x: radix_select_k(x, 64), mesh=mesh,
            in_specs=P("data"), out_specs=(P("data"), P("data"))))
        hlo = g.lower(v).compile().as_text()
        assert "tpu_custom_call" in hlo, \
            "radix kernels fell back inside shard_map"
        vv, ii = [np.asarray(a) for a in g(v)]
        np.testing.assert_array_equal(ii, i0)
        np.testing.assert_array_equal(vv, v0)


class TestScatterToContractionOnChip:
    """The round-3 scatter->contraction formulations carry exactness
    claims (one-hot products, integer partials, f32 accumulation) that
    CPU cannot falsify for MXU execution — pin them on hardware."""

    def test_factored_histogram_bit_identical_to_scatter(self):
        import jax.numpy as jnp

        from raft_tpu.stats import histogram
        from raft_tpu.stats.histogram import HistType

        rng = np.random.default_rng(41)
        data = rng.integers(-9, 2060, size=(60000, 4)).astype(np.float32)
        h_fac = np.asarray(histogram(jnp.asarray(data), 2048))
        h_sct = np.asarray(histogram(jnp.asarray(data), 2048,
                                     hist_type=HistType.Gmem))
        np.testing.assert_array_equal(h_fac, h_sct)

    def test_keyed_rowsum_matches_segment_sum(self):
        import jax
        import jax.numpy as jnp

        from raft_tpu import linalg

        rng = np.random.default_rng(42)
        X = rng.normal(size=(60000, 8)).astype(np.float32)
        keys = rng.integers(0, 64, size=60000).astype(np.int32)
        got = np.asarray(linalg.reduce_rows_by_key(None, X, keys, 64))
        ref = np.asarray(jax.ops.segment_sum(
            jnp.asarray(X), jnp.asarray(keys), num_segments=64))
        # 'high'-floor contraction vs exact segment: 2^-17 data rounding
        np.testing.assert_allclose(got, ref, rtol=3e-5, atol=3e-3)


class TestUnexpandedMetricsOnChip:
    def test_unexpanded_tiles_match_reference(self):
        """The VPU reduction tile (k on the grid, (kc,tm,tn) broadcast,
        axis-0 reduce, max-accumulate for linf) vs the jnp reference on
        hardware — every metric, unaligned shapes."""
        import jax.numpy as jnp

        from raft_tpu.linalg.contractions import (
            pairwise_unexpanded_pallas, unexpanded_ref)

        rng = np.random.default_rng(45)
        x = rng.normal(size=(333, 70)).astype(np.float32)
        y = rng.normal(size=(217, 70)).astype(np.float32)
        for metric in ("l1", "linf", "canberra", "lp", "hamming", "l2un"):
            got = np.asarray(pairwise_unexpanded_pallas(
                jnp.asarray(x), jnp.asarray(y), metric, p=3.0))
            ref = np.asarray(unexpanded_ref(x, y, metric, p=3.0))
            np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-5,
                                       err_msg=metric)


class TestGridSpMVOnChip:
    def test_grid_spmv_matches_scipy(self):
        """All three slot-grid kernels compiled on hardware: the
        same-shape dynamic gather, the segmented-scan tile reduction
        (relayouts + flat emission gather), and the scalar-prefetch
        window accumulation. Skewed matrix: hub row + hub column +
        sparse tail, multi-shard."""
        import scipy.sparse as sp

        import jax.numpy as jnp

        from raft_tpu.core.sparse_types import CSRMatrix
        from raft_tpu.sparse.grid_spmv import prepare, spmv

        rng = np.random.default_rng(44)
        n = 200_000
        e = 400_000
        r = np.concatenate([rng.integers(0, n, e),
                            np.full(5000, 77),          # hub row
                            rng.integers(0, n, 5000)])
        c = np.concatenate([rng.integers(0, n, e),
                            rng.integers(0, n, 5000),
                            np.full(5000, 123_456)])    # hub column
        d = rng.normal(size=r.size).astype(np.float32)
        A = sp.csr_matrix((d, (r, c)), shape=(n, n))
        A.sum_duplicates()
        plan = prepare(CSRMatrix.from_scipy(A))
        assert plan.n_shards > 1
        x = rng.normal(size=n).astype(np.float32)
        y = np.asarray(spmv(plan, jnp.asarray(x)))
        ref = A @ x
        np.testing.assert_allclose(y, ref, rtol=3e-5, atol=3e-4)


class TestRadixSelectMaxKOnChip:
    def test_radix_select_at_max_k(self):
        """kh = 128 drives the emission tile to (8, 512) — the live-set
        gating added for the round-3 advisor finding; before it, this
        shape sized a ~14-15 MB working set and was never compiled on
        hardware."""
        import jax.numpy as jnp

        from raft_tpu.matrix.radix_select import MAX_K, radix_select_k

        rng = np.random.default_rng(43)
        v = rng.normal(size=(3, 2 * MAX_K)).astype(np.float32)
        gv, gi = radix_select_k(jnp.asarray(v), MAX_K)
        order = np.argsort(v, axis=1, kind="stable")[:, :MAX_K]
        np.testing.assert_array_equal(np.asarray(gi), order)
        np.testing.assert_array_equal(
            np.asarray(gv), np.take_along_axis(v, order, 1))


class TestTwoLevelRadixOnChip:
    def test_two_level_radix_past_chunk_bound(self):
        """Rows past CHUNK_LEN run the per-chunk + merge scheme (round
        5); exact agreement with the host oracle incl. cross-chunk
        duplicate minima."""
        import jax.numpy as jnp

        from raft_tpu.matrix.radix_select import CHUNK_LEN, radix_select_k

        rng = np.random.default_rng(47)
        L = CHUNK_LEN + 65536
        v = rng.normal(size=(4, L)).astype(np.float32)
        v[0, 3] = v[0, L - 2] = v[0].min() - 1.0   # cross-chunk dupes
        gv, gi = radix_select_k(jnp.asarray(v), 32)
        order = np.argsort(v, axis=1, kind="stable")[:, :32]
        np.testing.assert_array_equal(np.asarray(gi), order)
        np.testing.assert_array_equal(
            np.asarray(gv), np.take_along_axis(v, order, 1))


class TestFusedSpMMOnChip:
    def test_spmm_fused_matches_column_loop(self):
        """The KT-fused SpMM against the per-column SpMV loop and
        scipy, on the compiled kernels (round 5: both ride the tree
        gather; the fused pass additionally exercises the KT grid and
        the 5-D chunk view)."""
        import jax
        import jax.numpy as jnp
        import scipy.sparse as sp

        from raft_tpu.core.sparse_types import CSRMatrix
        from raft_tpu.sparse import grid_spmv

        rng = np.random.default_rng(48)
        n, e = 60_000, 300_000
        r = rng.integers(0, n, e)
        c = rng.integers(0, n, e)
        d = rng.normal(size=e).astype(np.float32)
        A = sp.csr_matrix((d, (r, c)), shape=(n, n))
        A.sum_duplicates()
        plan = grid_spmv.prepare(CSRMatrix.from_scipy(A))
        B = rng.normal(size=(n, 16)).astype(np.float32)
        fused = np.asarray(jax.jit(grid_spmv.spmm)(plan, jnp.asarray(B)))
        loop = np.stack([np.asarray(grid_spmv.spmv(plan, B[:, j]))
                         for j in range(16)], axis=1)
        np.testing.assert_allclose(fused, loop, rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(fused, A @ B, rtol=3e-4, atol=3e-4)


class TestMSTGridOnChip:
    def test_mst_grid_agrees_with_xla_and_scipy(self):
        """The Pallas Borůvka E-stage (forced RAFT_TPU_MST=grid) against
        the XLA cascade and scipy's MST total weight, on the compiled
        kernels."""
        import scipy.sparse as sp
        from scipy.sparse.csgraph import minimum_spanning_tree

        from raft_tpu.core.sparse_types import CSRMatrix
        from raft_tpu.sparse.solver import mst

        rng = np.random.default_rng(49)
        n, m = 30_000, 120_000
        r = rng.integers(0, n, m)
        c = rng.integers(0, n, m)
        keep = r != c
        r, c = r[keep], c[keep]
        w = (rng.random(len(r)) + 0.01).astype(np.float32)
        A = sp.csr_matrix(
            (np.concatenate([w, w]),
             (np.concatenate([r, c]), np.concatenate([c, r]))),
            shape=(n, n))
        A.sum_duplicates()
        want = minimum_spanning_tree(A).sum()
        totals = {}
        prev = os.environ.get("RAFT_TPU_MST")
        for method in ("grid", "xla"):
            os.environ["RAFT_TPU_MST"] = method
            try:
                csr = CSRMatrix.from_scipy(A)   # fresh: no cached plan
                out = mst(None, csr,
                          color=np.arange(n, dtype=np.int32))
                totals[method] = float(np.asarray(out.weights).sum()) / 2
            finally:
                if prev is None:
                    os.environ.pop("RAFT_TPU_MST", None)
                else:
                    os.environ["RAFT_TPU_MST"] = prev
        assert abs(totals["grid"] - totals["xla"]) <= 1e-3
        assert abs(totals["grid"] - want) <= 1e-3 * max(1.0, want)


class TestFusedTopKOnChip:
    def test_knn_fused_matches_oracle(self):
        """The fused distance+top-k kernel (round-5 kNN hot path): the
        bound-gated merge, lane-pointer two-pointer rounds, and the
        (tm, 128) lane-local gather of the sorted best — all on the
        compiled backend, both precision tiers, vs the host oracle."""
        import jax.numpy as jnp
        import raft_tpu
        from raft_tpu.neighbors.fused_topk import knn_fused

        rng = np.random.default_rng(53)
        q = rng.normal(size=(300, 40)).astype(np.float32)
        db = rng.normal(size=(5000, 40)).astype(np.float32)
        d = ((q[:, None, :].astype(np.float64)
              - db[None, :, :].astype(np.float64)) ** 2).sum(-1)
        oi = np.argsort(d, axis=1, kind="stable")[:, :64]
        ov = np.take_along_axis(d, oi, 1)
        old = raft_tpu.get_matmul_precision()
        try:
            # 'high': index agreement vs the f64 oracle is the ACCURACY
            # claim — neighbors whose gap sits below the tier's distance
            # error legitimately swap (0.04% observed 19:09; the
            # chunked-kNN case uses the same 0.999 bar).
            raft_tpu.set_matmul_precision("high")
            gv, gi = knn_fused(jnp.asarray(q), jnp.asarray(db), 64)
            agree = (np.asarray(gi) == oi).mean()
            assert agree > 0.999, agree
            np.testing.assert_allclose(np.asarray(gv), ov, rtol=1e-4,
                                       atol=1e-4)
            # 'default' (one bf16 pass): distance noise ~4e-3 swaps
            # ~20% of rank-64 indices vs an f64 oracle (measured 19:52)
            # — that is the TIER's accuracy, not the kernel's. The
            # merge-correctness claim is exactness on the computed
            # distances: the scan path evaluates the same _metric_tile
            # formulation element-independently at the same tier, so
            # fused and scan must agree EXACTLY, noise included.
            from raft_tpu.neighbors.brute_force import _knn_scan

            raft_tpu.set_matmul_precision("default")
            gv, gi = knn_fused(jnp.asarray(q), jnp.asarray(db), 64)
            sv, si = _knn_scan(jnp.asarray(q), jnp.asarray(db), 64,
                               1024, "l2")
            np.testing.assert_array_equal(np.asarray(gi),
                                          np.asarray(si))
        finally:
            raft_tpu.set_matmul_precision(old)

    def test_knn_fused_two_vreg_best_k200(self):
        """k in (128, 256]: the sorted best spans TWO vregs — the
        pltpu.roll lane shift, the lane==k-1 masked kth reduce, and the
        while-loop carries all run at 256-lane width on real Mosaic
        (AOT-probed before the dispatch widened; this pins it on chip).
        Exactness claim vs the scan path at the same tier, like the
        k=64 case; strip drain must agree bit-exactly too."""
        import jax.numpy as jnp
        import raft_tpu
        from raft_tpu.neighbors.brute_force import _knn_scan
        from raft_tpu.neighbors.fused_topk import knn_fused

        rng = np.random.default_rng(54)
        q = rng.normal(size=(300, 40)).astype(np.float32)
        db = rng.normal(size=(5000, 40)).astype(np.float32)
        old = raft_tpu.get_matmul_precision()
        try:
            raft_tpu.set_matmul_precision("default")
            gv, gi = knn_fused(jnp.asarray(q), jnp.asarray(db), 200)
            sv, si = _knn_scan(jnp.asarray(q), jnp.asarray(db), 200,
                               1024, "l2")
            np.testing.assert_array_equal(np.asarray(gi),
                                          np.asarray(si))
            wv, wi = knn_fused(jnp.asarray(q), jnp.asarray(db), 200,
                               sw=256)
            np.testing.assert_array_equal(np.asarray(wi),
                                          np.asarray(gi))
            np.testing.assert_array_equal(np.asarray(wv),
                                          np.asarray(gv))
        finally:
            raft_tpu.set_matmul_precision(old)


class TestFusedTopKMnmgOnChip:
    def test_knn_mnmg_fused_body_matches_single_device(self):
        """knn_mnmg's shard body rides the fused top-k kernel inside
        shard_map (vma plumbing + sentinel-padded shards) — must agree
        with the single-device fused path on the same data."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh

        from raft_tpu.neighbors import knn, knn_mnmg

        rng = np.random.default_rng(59)
        db = rng.normal(size=(4100, 24)).astype(np.float32)  # ragged
        q = rng.normal(size=(64, 24)).astype(np.float32)
        mesh = Mesh(np.asarray(jax.devices()[:1]), ("data",))
        sv, si = knn(None, db, q, 16)
        mv, mi = knn_mnmg(None, db, q, 16, mesh=mesh)
        np.testing.assert_array_equal(np.asarray(mi), np.asarray(si))
        np.testing.assert_allclose(np.asarray(mv), np.asarray(sv),
                                   rtol=1e-6, atol=1e-6)
