"""TPU smoke tier configuration (VERDICT #10).

Unlike tests/ (which pins an 8-virtual-device CPU mesh and runs Pallas in
interpreter mode), this tier runs COMPILED Mosaic kernels on the real chip:
no platform pinning here. The whole tier skips when no TPU is reachable,
so `pytest tpu_tests -q` is safe to run anywhere.

Run: python -m pytest tpu_tests -q        (~2-4 min incl. tunnel warmup)
"""

import pytest


def pytest_collection_modifyitems(config, items):
    import jax

    try:
        on_tpu = jax.default_backend() == "tpu"
    except Exception:
        on_tpu = False
    if not on_tpu:
        marker = pytest.mark.skip(reason="no TPU backend reachable")
        for item in items:
            item.add_marker(marker)
