"""HBM-traffic cost model for the selection kernels (CPU-measurable
proxy for the digit-histogram rebuild).

The radix threshold stage is bandwidth-bound: its cost is the number of
times the (R, L) key array streams through HBM. The retired binary
search held rows VMEM-resident but paid 32 serial VPU compare+reduce
sweeps over them — on hardware it measured 3.6-6.4 GB/s (~0.5-0.8% of
the v5e's 819 GB/s, ~25x off this model; VERDICT Weak #1) because the
sweeps serialized behind each other instead of overlapping with the
stream. The digit-histogram kernel makes the model's pass count real:
NPASS (=4) streamed passes, each narrowing one 8-bit digit.

Model (bytes READ per selection, itemsize-4 keys):

- binary search:  (1 + 32) . R.L.4      one stream in + 32 resident
                                        sweeps (counted as passes: each
                                        sweep touches every element)
- digit histogram: (NPASS + 1 + 1) . R.L.4   NPASS threshold passes
                                        + the XLA chunk-count maps
                                        + the emission stream

The ratio (33/6 = 5.5x at NPASS=4) is the ISSUE's >= 4x acceptance
floor; ci/smoke.sh asserts it so a pass-count regression (e.g. a
5th digit pass growing the model) trips CI before hardware does.
"""

from __future__ import annotations

from raft_tpu.matrix.radix_select import NPASS

# Element-touch counts per selection formulation. "Pass" = every key
# element is read (from HBM or swept in place — the retired kernel's
# sweeps serialized exactly like re-reads, which is what the hardware
# grid measured).
BINARY_SEARCH_PASSES = 1 + 32          # stream-in + 32 bit probes
DIGIT_HIST_PASSES = NPASS + 1 + 1      # threshold + chunk maps + emit


def selection_bytes(n_rows: int, n_cols: int, *, itemsize: int = 4,
                    algo: str = "digit") -> int:
    """Modeled bytes READ for one exact batched top-k threshold+emit."""
    passes = {"digit": DIGIT_HIST_PASSES,
              "binary": BINARY_SEARCH_PASSES}[algo]
    return passes * n_rows * n_cols * itemsize


def traffic_ratio() -> float:
    """binary-search bytes / digit-histogram bytes (the >= 4x bar)."""
    return BINARY_SEARCH_PASSES / DIGIT_HIST_PASSES


def bytes_per_s(n_rows: int, n_cols: int, ms: float, *,
                itemsize: int = 4, algo: str = "digit") -> float:
    """Achieved selection bandwidth against the model's byte count —
    the `select_k_bytes_per_s` gauge the serving loadgen report and
    the bench rows record."""
    return selection_bytes(n_rows, n_cols, itemsize=itemsize,
                           algo=algo) / (ms / 1e3)
