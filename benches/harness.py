"""Microbenchmark harness (ref: cpp/bench/prims/common/benchmark.hpp:34-60
— google-benchmark fixture with CUDA event timing + RMM pool setup).

TPU translation: wall-clock around `block_until_ready` after an untimed
warmup that triggers jit compilation (the analogue of the reference's
warmup kernel launch), median-of-repeats reporting, one JSON line per
case so the driver and CI can diff runs.
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

import jax

# Provenance era: bumped when a PR changes what a bench row MEANS
# (timing scheme, FLOP convention, workload shape). Readers treat a row
# whose era is below the newest era seen for that bench family — or one
# carrying a ``superseded_by`` marker — as historical, never current.
# Era 7: the radix threshold stage became the digit-histogram kernel —
# every matrix/select_k* radix row and chunked-kNN row measures a
# different kernel, so the 3.6-6.4 GB/s binary-search-era rows read as
# superseded the moment an era-7 row lands in their family.
# Era 8: MNMG solver rows split per-iteration wall time into device
# work vs host overhead (compiled inner loops with donated carries —
# sync_every chunks run as ONE program, host touched per chunk, not per
# iteration). Host-driven-era MULTICHIP rows bundled both costs into
# one number and read as superseded once an era-8 row lands.
# Era 9: neighbors rows gained the IVF-Flat probe-scan path — the
# neighbors/ivf_recall family stamps recall@k alongside latency (an
# approximate row without its recall column is not comparable to an
# exact one), and brute-force baselines re-measured next to it belong
# to the same era so speedup ratios never mix timing schemes.
# Era 11: the neighbors/ivf_mnmg_scaling family lands sharded-serving
# rows — qps + p99 per rank count plus a recovery-time row — measured
# through the one-program shard_map path; earlier single-rank IVF rows
# are not comparable to a sharded row's qps column.
# Era 14: the unified epilogue layer (matrix/epilogue.py) spends the
# shared-iota argmin/one-hot fusion and the widened drain strip in
# every consumer at once — north-star Lloyd, fused-kNN, IVF probe and
# select_k rows all measure the centralized epilogue, and the
# matrix/epilogue_levers family carries the armed lever bars
# (bar_iters_per_s / bar_ms / bar_mxu_frac with the cost-model cut).
# Pre-era-14 rows for those families measured the hand-rolled copies.
# Era 16: overload resilience lands in the serving layer — brownout
# degradation ladders, hedged fleet dispatch and the chaos harness.
# The serve/overload family's rows measure tail latency WITH those
# mechanisms armed (a brownout controller and a hedger in the loop),
# so they are not comparable to any earlier serve row's p99 column;
# the rows also carry the resilience witnesses (brownout_max_level,
# hedge_rate) the CI gates assert on.
# Era 17: the streaming index lifecycle (neighbors/streaming.py +
# serve/ingest.py) makes the IVF index a mutable, journaled object.
# The neighbors/streaming_ingest family's rows measure query tail
# latency WITH online mutation and background compaction running (a
# live ingest stream and snapshot swaps in the loop), so they are not
# comparable to any static ivf_search row; rows carry the lifecycle
# witnesses (swaps, recall floor, crc_match) the CI gates assert on.
# Era 18: the durable streaming fleet (neighbors/wal_ship.py +
# neighbors/scrub.py) adds WAL shipping, checkpointed replica restart
# and scrub/read-repair. The serve/durability family's rows measure
# follower catch-up latency vs WAL depth, scrub pass cost, and the
# time-to-accuracy tradeoff of streaming maybe_refit vs periodic full
# rebuild under distribution drift; rows carry the durability
# witnesses (crc_match, detect_repair_ok, recall floors) the CI gates
# assert on.
# Era 19: product quantization (neighbors/ivf_pq.py) shrinks the
# resident index to m uint8 codes/row + shared codebooks. The
# neighbors/ivf_pq_recall family's rows sweep (nprobe, refine) and
# stamp recall_at_k NEXT TO compression_ratio (flat index bytes / PQ
# index bytes, measured from the packed arrays) — a PQ row's recall
# is meaningless without the memory it was bought back with, and CI
# gates assert both witnesses.
# Era 20: leader failover (neighbors/election.py) makes the durable
# fleet self-coordinating — term-fenced election, quorum-acked writes,
# attach-only promotion. The serve/failover family's rows measure
# time-to-new-leader over a 3-node clique, the ingest gap a failover
# opens, and the per-write p99 cost of majority quorum acks vs async
# shipping; rows carry the failover witnesses (most-caught-up winner,
# post-heal crc_match, resumed acked writes) the CI gates assert on.
BENCH_ERA = 20


def is_current_row(d: dict, newest_era: int) -> bool:
    """Shared row-validity predicate for BENCH_r0*.json readers: a row
    is current iff nothing supersedes it and it belongs to the newest
    era present for its bench family (rows predating era stamping count
    as era 0)."""
    if d.get("superseded_by"):
        return False
    return int(d.get("era", 0) or 0) >= newest_era


@dataclass
class BenchResult:
    name: str
    median_ms: float
    best_ms: float
    repeats: int
    items_per_s: Optional[float] = None
    gbytes_per_s: Optional[float] = None
    gflops: Optional[float] = None
    params: dict = field(default_factory=dict)

    # v5e single-chip ceilings for roofline context: ~819 GB/s HBM,
    # 197 TFLOP/s bf16 MXU (logical f32 FLOPs run 2-6 hardware passes
    # depending on the precision tier — fractions use the bf16 ceiling,
    # so a tier-'high' matmul tops out near 1/3). Emitted only on the
    # tpu backend; other backends have different ceilings.
    HBM_GB_S = 819.0
    MXU_GFLOPS = 197_000.0

    def json_line(self) -> str:
        out = {"bench": self.name, "era": BENCH_ERA,
               "median_ms": round(self.median_ms, 4),
               "best_ms": round(self.best_ms, 4), "repeats": self.repeats}
        on_tpu = jax.default_backend() == "tpu"
        if self.items_per_s is not None:
            out["items_per_s"] = f"{self.items_per_s:.3e}"
        if self.gbytes_per_s is not None:
            out["GB_per_s"] = round(self.gbytes_per_s, 2)
            if on_tpu:
                out["hbm_frac"] = round(self.gbytes_per_s / self.HBM_GB_S,
                                        3)
        if self.gflops is not None:
            out["GFLOP_per_s"] = round(self.gflops, 2)
            if on_tpu:
                out["mxu_frac"] = round(self.gflops / self.MXU_GFLOPS, 3)
        out.update(self.params)
        return json.dumps(out)


def marginal_per_call(t_full: float, t_half: float, n_full: int,
                      n_half: int, floor_frac: float = 0.25):
    """Two-point marginal per-call time, with sanity clamps.

    ``(t_full - t_half) / (n_full - n_half)`` cancels every per-block
    fixed cost (tunnel RTT, the sync fetch, dispatch, result delivery)
    because both blocks pay it identically — no RTT model needed. The
    single spelling of the scheme, shared by run_case, bench.py and
    benches/tune_northstar.py so a future timing fix can't drift
    between harnesses (the probe-and-subtract predecessor had to be
    excised from three files in lockstep).

    Clamped into ``[floor_frac, 1.0] × (t_full / n_full)``: the ceiling
    because fixed overhead can't be negative, the floor because a
    correctly sized block is mostly work. Returns ``(per_call,
    floor_bound)`` — a binding floor means the sizing probe misfired
    and the caller should flag the row as suspect.
    """
    per = (t_full - t_half) / (n_full - n_half)
    lo = floor_frac * t_full / n_full
    return min(max(per, lo), t_full / n_full), per < lo


def _sync(out) -> None:
    """Synchronize by fetching one element to host.

    On the axon-tunneled TPU backend, `block_until_ready` can report
    chained small-output dispatches ready before the remote work finishes
    (measured: impossible 55×-peak throughputs); a device→host fetch is
    the only reliable completion barrier. Costs one tunnel RTT (~70 ms),
    which run_case amortizes by batching calls per timed repeat."""
    leaves = [x for x in jax.tree.leaves(out) if hasattr(x, "dtype")]
    if leaves:
        x = leaves[-1]
        jax.device_get(x if x.ndim == 0 else x.ravel()[0])


def run_case(name: str, fn: Callable, *args, repeats: int = 5,
             warmup: int = 2, items: Optional[int] = None,
             bytes_moved: Optional[int] = None,
             flops: Optional[int] = None, **params) -> BenchResult:
    """Time fn(*args) with warmup + median-of-repeats.

    Through the tunnel (tpu backend), each timed repeat batches
    back-to-back calls and the per-call cost comes from TWO-POINT
    MARGINAL timing (see marginal_per_call): a block of ``inner`` calls
    and a block of ``inner//2`` calls; per-block fixed costs cancel in
    the difference. The former probe-and-subtract scheme mismeasured as
    tunnel topology shifted between windows (a ready-buffer refetch
    probe read 493 ms in a window where the timed region's own sync
    paid ~0 — subtracting it fabricated >1.0-of-peak utilization in
    bench.py, same scheme). Three regimes by the raw single-call time:
    < 0.45 s → a 4-call marginal probe sizes inner (≥ 2, so the
    marginal always runs, even in a window where the RTT dwarfs the
    op); 0.45-2 s → inner pinned to 2 with the half block measured once
    and ≤3 repeats (keeps per-case wall time near the old budget);
    ≥ 2 s → "single-point-raw": raw block time, which includes ≤1
    fetch RTT — at that scale a ≤25% honest-in-the-slow-direction
    overhead."""
    for _ in range(warmup):
        out = fn(*args)
        _sync(out)

    def timed(n):
        t0 = time.perf_counter()
        out = None
        for _ in range(n):
            out = fn(*args)
        _sync(out)
        return time.perf_counter() - t0

    inner = 1
    reps = repeats
    if jax.default_backend() == "tpu":
        t1 = timed(1)
        if t1 >= 2.0:
            pass          # truly slow: single-shot raw (≤25% overhead)
        elif t1 >= 0.45:
            # mid-range op: a 4-call sizing probe would cost more than
            # the measurement. Pin inner=2 (half=1), measure the half
            # block ONCE and reuse it (fixed costs are per-block
            # constants), and cap repeats — total ≈ the old per-case
            # wall time instead of ~3x it.
            inner = 2
            reps = min(repeats, 3)
        else:
            # Size batches from a MARGINAL probe — (4 calls − 1 call)/3
            # is a work-per-call estimate with the per-block fixed costs
            # already cancelled: the same arithmetic as the measurement
            # itself. (Sizing from the raw single-call time collapses
            # inner toward 1 in a high-RTT window, starving the marginal
            # of work signal.) 0.45 s of work per full block keeps
            # full+half near the old 0.7 s per-repeat budget so family
            # timeouts don't shift.
            t4 = timed(4)
            per1 = max((t4 - t1) / 3, 2e-5)
            inner = max(2, min(20000, int(round(0.45 / per1))))
    half = inner // 2

    times = []
    floor_bound = False
    t_half_once = None
    for _ in range(reps):
        t_full = timed(inner)
        if inner >= 2:
            if inner == 2:
                if t_half_once is None:
                    t_half_once = timed(half)
                t_half = t_half_once
            else:
                t_half = timed(half)
            per, bound = marginal_per_call(t_full, t_half, inner, half)
            floor_bound |= bound
        else:
            per = t_full
        times.append(per)
    times.sort()
    med = times[len(times) // 2]
    params["timing"] = "marginal-2point" if inner >= 2 else "single-point-raw"
    if floor_bound:
        params["floor_bound"] = True
    res = BenchResult(
        name=name, median_ms=med * 1e3, best_ms=times[0] * 1e3,
        repeats=reps, params=params)
    if items is not None:
        res.items_per_s = items / med
    if bytes_moved is not None:
        res.gbytes_per_s = bytes_moved / med / 1e9
    if flops is not None:
        res.gflops = flops / med / 1e9
    return res


# global registry: name -> zero-arg callable returning list[BenchResult]
REGISTRY: dict = {}


def bench(name: str):
    def deco(f):
        REGISTRY[name] = f
        return f
    return deco
