"""Microbenchmark harness (ref: cpp/bench/prims/common/benchmark.hpp:34-60
— google-benchmark fixture with CUDA event timing + RMM pool setup).

TPU translation: wall-clock around `block_until_ready` after an untimed
warmup that triggers jit compilation (the analogue of the reference's
warmup kernel launch), median-of-repeats reporting, one JSON line per
case so the driver and CI can diff runs.
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

import jax


@dataclass
class BenchResult:
    name: str
    median_ms: float
    best_ms: float
    repeats: int
    items_per_s: Optional[float] = None
    gbytes_per_s: Optional[float] = None
    gflops: Optional[float] = None
    params: dict = field(default_factory=dict)

    # v5e single-chip ceilings for roofline context: ~819 GB/s HBM,
    # 197 TFLOP/s bf16 MXU (logical f32 FLOPs run 2-6 hardware passes
    # depending on the precision tier — fractions use the bf16 ceiling,
    # so a tier-'high' matmul tops out near 1/3). Emitted only on the
    # tpu backend; other backends have different ceilings.
    HBM_GB_S = 819.0
    MXU_GFLOPS = 197_000.0

    def json_line(self) -> str:
        out = {"bench": self.name, "median_ms": round(self.median_ms, 4),
               "best_ms": round(self.best_ms, 4), "repeats": self.repeats}
        on_tpu = jax.default_backend() == "tpu"
        if self.items_per_s is not None:
            out["items_per_s"] = f"{self.items_per_s:.3e}"
        if self.gbytes_per_s is not None:
            out["GB_per_s"] = round(self.gbytes_per_s, 2)
            if on_tpu:
                out["hbm_frac"] = round(self.gbytes_per_s / self.HBM_GB_S,
                                        3)
        if self.gflops is not None:
            out["GFLOP_per_s"] = round(self.gflops, 2)
            if on_tpu:
                out["mxu_frac"] = round(self.gflops / self.MXU_GFLOPS, 3)
        out.update(self.params)
        return json.dumps(out)


def _sync(out) -> None:
    """Synchronize by fetching one element to host.

    On the axon-tunneled TPU backend, `block_until_ready` can report
    chained small-output dispatches ready before the remote work finishes
    (measured: impossible 55×-peak throughputs); a device→host fetch is
    the only reliable completion barrier. Costs one tunnel RTT (~70 ms),
    which run_case amortizes by batching calls per timed repeat."""
    leaves = [x for x in jax.tree.leaves(out) if hasattr(x, "dtype")]
    if leaves:
        x = leaves[-1]
        jax.device_get(x if x.ndim == 0 else x.ravel()[0])


def run_case(name: str, fn: Callable, *args, repeats: int = 5,
             warmup: int = 2, items: Optional[int] = None,
             bytes_moved: Optional[int] = None,
             flops: Optional[int] = None, **params) -> BenchResult:
    """Time fn(*args) with warmup + median-of-repeats.

    Through the tunnel (tpu backend), each timed repeat batches enough
    back-to-back calls that the ~70 ms fetch RTT stays <10% of the
    measurement; per-call time is total/inner."""
    for _ in range(warmup):
        out = fn(*args)
        _sync(out)
    inner = 1
    rtt = 0.0
    if jax.default_backend() == "tpu":
        out = fn(*args)
        _sync(out)
        t0 = time.perf_counter()
        _sync(out)                       # ready buffer → pure fetch RTT
        rtt = time.perf_counter() - t0
        t0 = time.perf_counter()
        _sync(fn(*args))
        t_one = time.perf_counter() - t0
        t_est = max(t_one - rtt, 2e-5)
        inner = max(1, min(20000, int(round(0.7 / t_est))))
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = None
        for _ in range(inner):
            out = fn(*args)
        _sync(out)
        total = time.perf_counter() - t0
        # subtract the one fetch RTT the batch pays (keep half as a floor
        # against RTT variance underestimating real work)
        times.append(max(total - rtt, total * 0.5) / inner)
    times.sort()
    med = times[len(times) // 2]
    res = BenchResult(
        name=name, median_ms=med * 1e3, best_ms=times[0] * 1e3,
        repeats=repeats, params=params)
    if items is not None:
        res.items_per_s = items / med
    if bytes_moved is not None:
        res.gbytes_per_s = bytes_moved / med / 1e9
    if flops is not None:
        res.gflops = flops / med / 1e9
    return res


# global registry: name -> zero-arg callable returning list[BenchResult]
REGISTRY: dict = {}


def bench(name: str):
    def deco(f):
        REGISTRY[name] = f
        return f
    return deco
