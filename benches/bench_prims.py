"""Primitive microbenches (ref: cpp/bench/prims/ — one case family per
reference bench TU; SURVEY.md §2.13 lists the matrix).

Run: python benches/run_benches.py [--filter substr] [--size small|full]
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from benches.harness import bench, run_case

_SMALL = {"rows": 1 << 14, "cols": 256, "k": 64}
_FULL = {"rows": 1 << 20, "cols": 256, "k": 256}
SIZES = _SMALL


def _data(rows, cols, seed=0, dtype=np.float32):
    # Generate ON device: at full sizes, pushing ~1 GB of host data through
    # the remote TPU tunnel dominates the whole bench family's wall-clock;
    # jax.random costs nothing to ship.
    x = jax.random.normal(jax.random.key(seed), (rows, cols), jnp.float32)
    return x.astype(dtype)


# -- core (ref: bench/prims/core/bitset.cu, copy.cu, memory_tracking.cu) ----

@bench("core/bitset")
def bench_bitset():
    from raft_tpu.core.bitset import Bitset

    n = SIZES["rows"] * 8
    bs = Bitset(n)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, n, size=n // 16).astype(np.int32))

    def roundtrip(bs, ids):
        bs2 = bs.set(ids, True)
        return bs2.count()

    return [run_case("core/bitset_set_count", roundtrip, bs, ids,
                     items=int(ids.shape[0]), n=n)]


@bench("core/copy")
def bench_copy():
    x = _data(SIZES["rows"], SIZES["cols"])
    f = jax.jit(lambda a: a.T.copy())
    nbytes = x.size * 4 * 2
    return [run_case("core/copy_transpose", f, x, bytes_moved=nbytes,
                     shape=list(x.shape))]


@bench("core/memory_tracking")
def bench_memory_tracking():
    from raft_tpu.core.native_runtime import (TrackedHostPool,
                                              native_available)
    if not native_available():
        return []
    pool = TrackedHostPool()

    def cycle():
        arrs = [pool.allocate((4096,), np.float32) for _ in range(64)]
        for a in arrs:
            pool.release(a)
        return jnp.zeros(())

    out = [run_case("core/native_pool_alloc_free", cycle, items=128)]
    pool.close()
    return out


# -- linalg (ref: bench/prims/linalg/*.cu) ----------------------------------

@bench("linalg/add")
def bench_add():
    from raft_tpu.linalg import add

    x = _data(SIZES["rows"], SIZES["cols"])
    y = _data(SIZES["rows"], SIZES["cols"], seed=1)
    f = jax.jit(lambda a, b: add(None, a, b))
    return [run_case("linalg/add", f, x, y, bytes_moved=x.size * 4 * 3)]


@bench("linalg/reduce")
def bench_reduce():
    from raft_tpu.linalg import reduce as reduce_fn

    x = _data(SIZES["rows"], SIZES["cols"])
    out = []
    for apply, nm in (("along_columns", "strided"),
                      ("along_rows", "coalesced")):
        f = jax.jit(functools.partial(reduce_fn, None, apply=apply))
        out.append(run_case(f"linalg/reduce_{nm}", f, x,
                            bytes_moved=x.size * 4))
    return out


@bench("linalg/norm")
def bench_norm():
    from raft_tpu.linalg import normalize, row_norm

    x = _data(SIZES["rows"], SIZES["cols"])
    f = jax.jit(functools.partial(row_norm, None, norm_type="l2"))
    g = jax.jit(functools.partial(normalize, None))
    return [
        run_case("linalg/row_norm_l2", f, x, bytes_moved=x.size * 4),
        run_case("linalg/normalize", g, x, bytes_moved=x.size * 8),
    ]


@bench("linalg/reduce_cols_by_key")
def bench_rcbk():
    from raft_tpu.linalg import reduce_cols_by_key

    rng = np.random.default_rng(19)
    x = _data(1024, SIZES["cols"])
    keys = jnp.asarray(rng.integers(0, 32,
                                    size=SIZES["cols"]).astype(np.int32))
    f = jax.jit(lambda d, k: reduce_cols_by_key(None, d, k,
                                                n_unique_keys=32))
    return [run_case("linalg/reduce_cols_by_key", f, x, keys,
                     bytes_moved=x.size * 4, n_keys=32)]


@bench("sparse/sddmm_masked")
def bench_sddmm_masked():
    """sddmm + masked_matmul (ref: bench/prims/linalg/sddmm.cu,
    masked_matmul.cu)."""
    from raft_tpu.core.bitset import Bitmap
    from raft_tpu.sparse.convert import dense_to_csr
    from raft_tpu.sparse.linalg import masked_matmul, sddmm

    rng = np.random.default_rng(23)
    m, n, k = 2048, 2048, SIZES["cols"]
    a = _data(m, k, seed=24)
    b = _data(k, n, seed=25)
    pat = rng.uniform(size=(m, n)) < 0.01
    csr = dense_to_csr(jnp.asarray(pat.astype(np.float32)))
    nnz = int(csr.data.shape[0])
    f = jax.jit(lambda aa, bb: sddmm(aa, bb, csr).data)
    out = [run_case("sparse/sddmm", f, a, b, flops=2 * nnz * k, nnz=nnz)]
    # convert the bitmap pattern once outside the hot loop (the reference
    # bench also pre-builds its mask CSR)
    from raft_tpu.sparse.convert import bitmap_to_csr

    pattern = bitmap_to_csr(Bitmap.from_bool_matrix(pat))
    g = jax.jit(lambda aa, bb: masked_matmul(aa, bb.T, pattern).data)
    out.append(run_case("sparse/masked_matmul", g, a, b,
                        flops=2 * nnz * k, nnz=nnz))
    return out


@bench("sparse/convert_csr")
def bench_convert_csr():
    """adj→CSR + bitset→CSR conversions (ref: bench/prims/sparse/
    convert_csr.cu, bitset_to_csr.cu)."""
    from raft_tpu.core.bitset import Bitset
    from raft_tpu.sparse.convert import adj_to_csr, bitset_to_csr

    rng = np.random.default_rng(29)
    rows, cols = 4096, 4096
    adj = rng.uniform(size=(rows, cols)) < 0.05
    # host-side conversions (dynamic nnz → not jittable by design);
    # timed eagerly, matching what the reference bench measures
    out = [run_case("sparse/adj_to_csr", lambda: adj_to_csr(adj).indices,
                    items=rows * cols)]
    bs = Bitset.from_bools(adj[0])
    out.append(run_case("sparse/bitset_to_csr",
                        lambda: bitset_to_csr(bs, n_rows=rows).indices,
                        items=rows * cols))
    return out


@bench("linalg/matrix_vector_op")
def bench_mvo():
    from raft_tpu.linalg import matrix_vector_op

    x = _data(SIZES["rows"], SIZES["cols"])
    v = _data(1, SIZES["cols"], seed=2)[0]
    f = jax.jit(lambda m, vec: matrix_vector_op(None, m, vec,
                                                op=lambda a, b: a + b))
    return [run_case("linalg/matrix_vector_op", f, x, v,
                     bytes_moved=x.size * 4 * 2)]


@bench("linalg/map_then_reduce")
def bench_map_then_reduce():
    from raft_tpu.linalg import map_then_reduce

    x = _data(SIZES["rows"], SIZES["cols"])
    f = jax.jit(functools.partial(map_then_reduce, None, jnp.abs))
    return [run_case("linalg/map_then_reduce", f, x,
                     bytes_moved=x.size * 4)]


@bench("linalg/reduce_rows_by_key")
def bench_rrbk():
    from raft_tpu.linalg import reduce_rows_by_key

    x = _data(SIZES["rows"], SIZES["cols"])
    keys = jnp.asarray(
        np.random.default_rng(3).integers(0, 32, SIZES["rows"])
        .astype(np.int32))
    f = jax.jit(lambda d, k: reduce_rows_by_key(None, d, k, 32))
    return [run_case("linalg/reduce_rows_by_key", f, x, keys,
                     bytes_moved=x.size * 4)]


@bench("linalg/transpose")
def bench_transpose():
    from raft_tpu.linalg import transpose

    x = _data(SIZES["rows"], SIZES["cols"])
    f = jax.jit(functools.partial(transpose, None))
    return [run_case("linalg/transpose", f, x,
                     bytes_moved=x.size * 4 * 2)]


@bench("linalg/gemm")
def bench_gemm():
    from raft_tpu.linalg import gemm

    n = 2048
    a = _data(n, n)
    b = _data(n, n, seed=4)
    f = jax.jit(functools.partial(gemm, None))
    return [run_case("linalg/gemm_2048", f, a, b, flops=2 * n ** 3)]


@bench("linalg/svd")
def bench_svd():
    """BASELINE config 2's dense path: GEMM + row-norm + SVD on a tall
    16384×1024 f32 matrix (small sizes shrink to 2048×256)."""
    from raft_tpu.linalg import gemm, svd_eig, rsvd_fixed_rank
    from raft_tpu.linalg.norm import row_norm

    m, n = ((16384, 1024) if SIZES["rows"] >= (1 << 20) else (2048, 256))
    a = _data(m, n)

    def dense_path(a):
        g = gemm(None, a, a, trans_a=True)           # n×n gram GEMM
        norms = row_norm(None, a)
        u, s, v = svd_eig(None, a)
        return g[0, 0] + norms[0] + s[0] + u[0, 0] + v[0, 0]

    f = jax.jit(dense_path)
    r = jax.jit(functools.partial(rsvd_fixed_rank, None, k=64))
    return [
        run_case(f"linalg/svd_dense_path_{m}x{n}", f, a,
                 flops=2 * m * n * n),
        run_case(f"linalg/rsvd_k64_{m}x{n}", r, a),
    ]


# -- matrix (ref: bench/prims/matrix/*.cu) ----------------------------------

def _select_k_grid(lens_ks, *, batch_cap=8192, target_elems=None,
                   repeats=5, warmup=2):
    """Five-way direct/tiled/stream/radix/insert tournament over a
    (len, k) grid — the evidence base for select_k's dispatch (ref
    heuristic: matrix/detail/select_k-inl.cuh:38-63 picks radix vs
    warpsort from (len, k)). Implementations are invoked DIRECTLY (not
    through the algo enums) so a dispatch change can never silently
    relabel a row. Batch is scaled so every case streams ~the same
    element count — throughput comparisons are then apples-to-apples.

    Rows benched off-TPU carry ``partial: true``: they populate a
    tournament column structurally (ci/derive_select_k.py fails loudly
    on an armed-but-unmeasured contender) but never outvote a
    hardware row. Radix rows also record the model-relative
    ``select_k_bytes_per_s`` gauge (benches/select_model.py) through
    the obs registry — the serving loadgen report quotes the same
    gauge."""
    from benches import select_model
    from raft_tpu import obs
    from raft_tpu.matrix import radix_select, topk_insert
    from raft_tpu.matrix.select_k import (_direct_select, _stream_select,
                                          _tiled_select)

    if target_elems is None:
        target_elems = ((64 << 20) if SIZES["rows"] >= (1 << 20)
                        else (1 << 22))
    partial = jax.default_backend() != "tpu"
    for length, k in lens_ks:
        if k > length:
            continue
        batch = max(4, min(batch_cap, target_elems // length))
        x = _data(batch, length)
        algos = [("tiled", _tiled_select), ("direct", _direct_select)]
        if length > 8192:
            # below this the stream path dispatches to direct anyway —
            # benching it would record mislabeled duplicate rows
            algos.append(("stream", _stream_select))
        if radix_select.supports(x.dtype, length, k):
            algos.append(("radix", radix_select.radix_select_k))
        if topk_insert.supports(x.dtype, k):
            # the round-5 bound-gated insertion contender (k <= 256)
            algos.append(("insert", topk_insert.insert_select))
        for tag, impl in algos:
            f = jax.jit(functools.partial(impl, k=k, select_min=True))
            extra = {"partial": True} if partial else {}
            res = run_case(f"matrix/select_k_len{length}_k{k}_{tag}", f,
                           x, repeats=repeats, warmup=warmup,
                           items=batch * length, k=k, batch=batch,
                           length=length, algo=tag, **extra)
            if tag == "radix":
                obs.set_gauge(
                    "select_k_bytes_per_s",
                    select_model.bytes_per_s(batch, length,
                                             res.median_ms),
                    length=str(length), k=str(k))
            yield res


@bench("matrix/select_k")
def bench_select_k():
    """Small/medium-length half of the select_k tournament (the large-len
    half is its own family so each fits a battery per-family budget).
    Yields cases as they finish — a hung case can't hold results hostage."""
    lens = ((8192, 16), (8192, 256), (8192, 2048),
            (65536, 16), (65536, 256), (65536, 2048))
    yield from _select_k_grid(lens)

    # insertion worst case: rows sorted DESCENDING, so every tile
    # improves the bound (~k rounds per tile — the merge cost). The
    # AUTO adoption of "insert" needs this margin quantified, not just
    # the random-data cells.
    from raft_tpu.matrix import topk_insert
    from raft_tpu.matrix.select_k import _tiled_select

    full = SIZES["rows"] >= (1 << 20)
    # small tier keeps length > the 8192 tile so the "tiled" leg really
    # runs the tournament (at <= 8192 _tiled_select dispatches to
    # direct and the row label would lie)
    length, k, batch = (65536, 64, 1024) if full else (16384, 16, 8)
    x = jnp.sort(_data(batch, length), axis=1)[:, ::-1]
    jax.block_until_ready(x)
    for tag, impl in (("insert", topk_insert.insert_select),
                      ("tiled", _tiled_select)):
        f = jax.jit(functools.partial(impl, k=k, select_min=True))
        yield run_case(f"matrix/select_k_adversarial_{tag}", f, x,
                       items=batch * length, k=k, length=length, algo=tag)


@bench("matrix/select_k_large")
def bench_select_k_large():
    """Large-length (1M-row) half incl. the k=10^4 wide regime
    (MATRIX_SELECT_LARGE analogue; ref: cpp/tests/matrix/select_large_k.cu)
    and, at full size, one past-VMEM row length exercising the two-level
    chunked radix (ref: multi-block radix_topk, select_radix.cuh:877)."""
    n = SIZES["rows"]
    lens = [(n, 16), (n, 256), (n, 2048), (n, 10_000)]
    if n >= (1 << 20):
        lens.append((1 << 22, 256))
    yield from _select_k_grid(lens)


@bench("matrix/select_k_smoke")
def bench_select_k_smoke():
    """Smoke-scale five-way rows (CPU tier): tiny batches, one repeat,
    always stamped ``partial: true`` via _select_k_grid's backend
    check. Exists so ci/derive_select_k.py's adjudication is never
    structurally empty — in particular the insert column (k <= 256),
    which the round-5 battery dropped silently (rc=124 before the 65k
    grid landed) and which the derivation tool now fails loudly on.
    On TPU this family is a no-op: the real families own those rows."""
    if jax.default_backend() == "tpu":
        return
    # one k inside the insert band, one above it (insert un-armed
    # there — the derive tool's expected-contender set must agree)
    yield from _select_k_grid(((9000, 32), (9000, 300)),
                              batch_cap=8, target_elems=1,
                              repeats=1, warmup=1)


@bench("matrix/select_k_bars")
def bench_select_k_bars():
    """The VERDICT hardware bars for the digit-histogram rebuild,
    encoded as armed battery rows: (64 x 1M, k=2048) must land <= 12 ms
    at >= 20 GB/s of selection traffic, (64 x 1M, k=10^4) <= 20 ms.
    ``bar_ms``/``bar_gb_s`` ride the row so the next TPU window's
    artifact adjudicates pass/fail without cross-referencing the ISSUE;
    off-TPU the rows shrink and stamp ``partial: true`` (code-path
    smoke, no bar claim)."""
    from benches import select_model
    from raft_tpu.matrix import radix_select

    full = jax.default_backend() == "tpu"
    shapes = (((64, 1 << 20, 2048), 12.0), ((64, 1 << 20, 10_000), 20.0))
    if not full:
        shapes = (((4, 1 << 14, 2048), 12.0), ((4, 1 << 14, 10_000), 20.0))
    partial = {} if full else {"partial": True}
    for (batch, length, k), bar_ms in shapes:
        if k > length:
            continue
        x = _data(batch, length)
        f = jax.jit(functools.partial(radix_select.radix_select_k,
                                      k=k, select_min=True))
        res = run_case(f"matrix/select_k_bar_len{length}_k{k}_radix", f,
                       x, repeats=3 if full else 1, warmup=2 if full else 1,
                       items=batch * length, k=k, batch=batch,
                       length=length, algo="radix", bar_ms=bar_ms,
                       bar_gb_s=20.0,
                       model_bytes=select_model.selection_bytes(batch,
                                                                length),
                       **partial)
        yield res


@bench("matrix/epilogue_levers")
def bench_epilogue_levers():
    """ISSUE 14 armed lever rows: the unified epilogue layer's two spent
    levers, measured where they land.

    * ``epilogue/northstar_sharediota`` — the north-star Lloyd iteration
      through the shared-iota argmin/one-hot epilogue (VERDICT task 6;
      ``bar_iters_per_s=125`` against the 107.9 BASELINE capture).
    * ``epilogue/knn_drain_k64`` — fused kNN at the BASELINE drain shape
      with the strip-width lever armed (sw=None -> DRAIN_SW) next to the
      whole-tile contrast row (VERDICT task 5; ``bar_ms=50`` /
      ``bar_mxu_frac=0.15`` against the 97.65 ms / 0.057 capture).
    * ``epilogue/select_k_insert`` carry-over rows — the same drain
      under dense select_k's insertion path, strip vs whole tile.

    Off-TPU the rows shrink to code-path smoke shapes and stamp
    ``partial: true`` plus ``model_cut`` — the DRAIN_SW cost-model
    prediction ((12.6 + 85) / (12.6 + 85/4) ~ 2.9x, >= the 1.5x
    floor the ISSUE requires of a proxy row) — so the provenance trail
    shows an armed bar with a model-backed claim until a TPU window
    measures it."""
    from raft_tpu.matrix import epilogue
    from raft_tpu.matrix.topk_insert import insert_select
    from raft_tpu.neighbors.fused_topk import knn_fused
    from raft_tpu.util.precision import get_matmul_precision

    full = jax.default_backend() == "tpu"
    partial = {} if full else {"partial": True}
    reps, warm = (3, 2) if full else (1, 1)
    # DRAIN_SW cost model at the BASELINE kNN shape: ~12.6 ms distance
    # + ~85 ms drain; a 256-lane strip under tn=1024 cuts the dead-lane
    # extraction ~4x -> (12.6 + 85) / (12.6 + 85 / 4) per-kernel cut.
    model_cut = round((12.6 + 85.0) / (12.6 + 85.0 / 4.0), 2)

    # -- north-star shared-iota row (task 6) ---------------------------
    from raft_tpu.cluster.kmeans import lloyd_step

    rows, dim, k = ((1 << 20, 128, 1024) if full else (4096, 32, 64))
    x = _data(rows, dim, seed=50)
    c = _data(k, dim, seed=51)
    f = jax.jit(functools.partial(lloyd_step, n_clusters=k))
    r = run_case("epilogue/northstar_sharediota", f, x, c,
                 repeats=reps, warmup=warm,
                 flops=2 * rows * k * dim, rows=rows, k=k,
                 tier=get_matmul_precision(),
                 bar_iters_per_s=125.0, **partial)
    r.params["iters_per_s"] = round(1e3 / r.median_ms, 2)
    yield r

    # -- kNN drain rows (task 5): armed strip vs whole-tile contrast ---
    nq, ndb = ((4096, 1 << 20) if full else (64, 2048))
    kk = 64
    q = _data(nq, dim, seed=52)
    db = _data(ndb, dim, seed=53)
    for label, sw in (("strip", None), ("wholetile", 0)):
        g = jax.jit(functools.partial(knn_fused, k=kk, tn=1024, sw=sw))
        extra = dict(partial)
        if sw is None:          # the armed lever row carries the bars
            extra.update(bar_ms=50.0, bar_mxu_frac=0.15,
                         model_cut=model_cut)
        r = run_case(f"epilogue/knn_drain_k64_{label}", g, q, db,
                     repeats=reps, warmup=warm,
                     flops=2 * nq * ndb * dim, q=nq, n=ndb, k=kk,
                     sw=(epilogue.DRAIN_SW if sw is None else sw),
                     **extra)
        yield r

    # -- select_k carry-over rows: the same drain under insert_select --
    m, n = ((4096, 1 << 16) if full else (128, 4096))
    v = _data(m, n, seed=54)
    for label, sw in (("strip", epilogue.DRAIN_SW), ("wholetile", 0)):
        h = jax.jit(functools.partial(insert_select, k=kk, sw=sw))
        extra = dict(partial)
        if sw:
            extra["model_cut"] = model_cut
        yield run_case(f"epilogue/select_k_insert_{label}", h, v,
                       repeats=reps, warmup=warm,
                       items=m * n, m=m, n=n, k=kk, sw=sw, **extra)


@bench("matrix/argmin")
def bench_argmin():
    from raft_tpu.matrix import argmin

    x = _data(SIZES["rows"], SIZES["cols"])
    f = jax.jit(functools.partial(argmin, None))
    return [run_case("matrix/argmin", f, x, items=x.shape[0],
                     bytes_moved=x.size * 4)]


@bench("matrix/gather")
def bench_gather():
    from raft_tpu.matrix import gather

    x = _data(SIZES["rows"], SIZES["cols"])
    idx = jnp.asarray(np.random.default_rng(5).integers(
        0, SIZES["rows"], SIZES["rows"] // 2).astype(np.int32))
    f = jax.jit(functools.partial(gather, None))
    return [run_case("matrix/gather", f, x, idx,
                     bytes_moved=idx.shape[0] * SIZES["cols"] * 4 * 2)]


# -- random (ref: bench/prims/random/*.cu) ----------------------------------

@bench("random/rng")
def bench_rng():
    from raft_tpu.random import GeneratorType, RngState, uniform

    n = SIZES["rows"] * SIZES["cols"]

    def gen():
        return uniform(None, RngState(0), (n,))

    def gen_rbg():
        return uniform(None, RngState(0, type=GeneratorType.RBG), (n,))

    return [run_case("random/uniform", gen, items=n,
                     bytes_moved=n * 4),
            run_case("random/uniform_rbg", gen_rbg, items=n,
                     bytes_moved=n * 4)]


@bench("random/make_blobs")
def bench_make_blobs():
    from raft_tpu.random import RngState, make_blobs

    def gen():
        return make_blobs(None, RngState(1), SIZES["rows"], 64,
                          n_clusters=16)

    return [run_case("random/make_blobs", gen,
                     items=SIZES["rows"] * 64)]


@bench("random/permute")
def bench_permute():
    from raft_tpu.random import RngState, permute_rows

    x = _data(SIZES["rows"], SIZES["cols"])

    def gen(x):
        return permute_rows(None, RngState(2), x)

    return [run_case("random/permute_rows", gen, x,
                     bytes_moved=x.size * 4 * 2)]


@bench("random/subsample")
def bench_subsample():
    from raft_tpu.random import RngState, excess_subsample

    n = SIZES["rows"] * 4

    def gen():
        return excess_subsample(None, RngState(3), n // 8, n)

    return [run_case("random/excess_subsample", gen, items=n // 8)]


# -- sparse (ref: bench/prims/sparse/*.cu) ----------------------------------

@bench("sparse/bitmap_to_csr")
def bench_bitmap_to_csr():
    from raft_tpu.core.bitset import Bitmap
    from raft_tpu.sparse.convert import bitmap_to_csr

    rows, cols = 2048, 2048
    rng = np.random.default_rng(6)
    dense = rng.uniform(size=(rows, cols)) < 0.05
    bm = Bitmap.from_bool_matrix(jnp.asarray(dense))

    def conv(bm):
        return bitmap_to_csr(bm).indptr

    return [run_case("sparse/bitmap_to_csr", conv, bm,
                     items=int(dense.sum()), density=0.05)]


@bench("sparse/spmv")
def bench_spmv():
    from raft_tpu.sparse.convert import dense_to_csr

    rng = np.random.default_rng(7)
    n = 4096
    dense = rng.normal(size=(n, n)).astype(np.float32)
    dense[rng.uniform(size=(n, n)) > 0.02] = 0.0
    csr = dense_to_csr(jnp.asarray(dense))
    x = jnp.asarray(rng.normal(size=n).astype(np.float32))
    nnz = int(csr.data.shape[0])

    # pinned to the segment formulation: spmv()'s auto dispatch would
    # route this nnz to the grid plan (and un-jitted, rebuild it per
    # call); this row is the SEGMENT baseline, spmv_large carries the
    # three-way comparison
    from raft_tpu.sparse.linalg import _segment_spmv

    f = jax.jit(lambda v: _segment_spmv(
        csr.row_ids(), csr.indices, csr.data, v, csr.n_rows,
        limit=csr.indptr[-1]))

    return [run_case("sparse/spmv_4096_d02", f, x, flops=2 * nnz,
                     nnz=nnz, fmt="segment")]


@bench("sparse/spmv_large")
def bench_spmv_large():
    """CSR segment-sum vs ELL slab SpMV at scale (VERDICT #9: 10M nnz on
    chip; ref: cusparseSpMV, sparse/detail/cusparse_wrappers.h)."""
    import scipy.sparse as sp

    from raft_tpu.core.sparse_types import CSRMatrix
    from raft_tpu.sparse.ell import from_csr
    from raft_tpu.sparse.ell import spmv as ell_spmv

    full = SIZES["rows"] >= (1 << 20)
    n, nnz_target = (1 << 20, 10_000_000) if full else (1 << 14, 200_000)
    rng = np.random.default_rng(13)
    # uniform-degree graph → ELL-friendly; the skewed case is covered by
    # maybe_ell declining (tests); here we measure both formats' ceilings
    deg = nnz_target // n
    cols_h = rng.integers(0, n, size=(n, deg)).astype(np.int32)
    data_h = rng.random((n, deg)).astype(np.float32)
    indptr = np.arange(n + 1, dtype=np.int64) * deg
    a = sp.csr_matrix((data_h.ravel(), cols_h.ravel(), indptr),
                      shape=(n, n))
    csr = CSRMatrix.from_scipy(a)
    ell = from_csr(csr)
    x = jnp.asarray(rng.random(n).astype(np.float32))
    nnz = int(a.nnz)

    import time as _time

    from raft_tpu.sparse import grid_spmv

    t0 = _time.perf_counter()
    plan = grid_spmv.prepare(csr)
    build_ms = (_time.perf_counter() - t0) * 1e3

    # the segment baseline must stay the segment formulation — spmv()'s
    # auto dispatch would upgrade this nnz to the grid plan
    from raft_tpu.sparse.linalg import _segment_spmv

    f_csr = jax.jit(lambda v: _segment_spmv(
        csr.row_ids(), csr.indices, csr.data, v, csr.n_rows,
        limit=csr.indptr[-1]))
    f_ell = jax.jit(lambda v: ell_spmv(ell, v))
    # the plan rides as a jit ARGUMENT, never a closure: closed-over plan
    # arrays become HLO constants and the serialized compile request blows
    # the tunnel's size cap (round-5 capture: HTTP 413 at 10M nnz)
    f_grid = jax.jit(grid_spmv.spmv)
    return [
        run_case("sparse/spmv_csr_segment", f_csr, x, flops=2 * nnz,
                 nnz=nnz, fmt="csr"),
        run_case("sparse/spmv_ell_slab", f_ell, x, flops=2 * nnz,
                 nnz=nnz, fmt="ell", width=int(ell.width)),
        run_case("sparse/spmv_grid", f_grid, plan, x, flops=2 * nnz,
                 nnz=nnz, fmt="grid", pad_ratio=round(plan.pad_ratio, 3),
                 n_shards=plan.n_shards, build_ms=round(build_ms, 1)),
        *_spmm_k16_rows(plan, rng, n, nnz),
    ]


def _spmm_k16_rows(plan, rng, n, nnz):
    """k-batched fused SpMM vs the per-column loop at k=16 (VERDICT r4
    #4 bar: fused >= 4x the column loop on chip). Same plan, same B;
    the plan is a jit argument in both (see the HTTP-413 note above)."""
    from raft_tpu.sparse import grid_spmv

    k = 16
    b = jnp.asarray(rng.random((n, k)).astype(np.float32))
    f_fused = jax.jit(grid_spmv.spmm)
    f_loop = jax.jit(lambda p, bv: jax.lax.map(
        lambda col: grid_spmv._spmv_impl(p, col), bv.T).T)
    return [
        run_case("sparse/spmm_k16_fused", f_fused, plan, b,
                 flops=2 * nnz * k, nnz=nnz, k=k, fmt="grid-kt"),
        run_case("sparse/spmm_k16_colloop", f_loop, plan, b,
                 flops=2 * nnz * k, nnz=nnz, k=k, fmt="grid-colloop"),
    ]


@bench("sparse/prim_probe")
def bench_sparse_prim_probe():
    """On-chip throughput of the primitives a TPU SpMV redesign could
    be built from. Mosaic's `tpu.dynamic_gather` is LANE-LOCAL: at most
    one source vreg (width 128) along the gather dimension — the round-3
    same-shape "(rows, W)-from-(rows, W)" generalization was falsified
    on hardware in the round-5 capture ("Multiple source vregs along
    gather dimension" at W=16384), so the wide rowwise probe is gone.
    What remains: the legal lane-128 gather, the production tree-gather
    rate curve over shard widths (grid SpMV kernel 1's primitive), and
    the XLA gather / segment-sum / sort / scan rates that bound the
    non-Pallas alternatives; the redesign verdict gets written into
    sparse/ell.py from these rows."""
    full = SIZES["rows"] >= (1 << 20)
    n = (1 << 20) if full else (1 << 14)
    e = 16 * n
    rng = np.random.default_rng(17)
    x = jnp.asarray(rng.random(n).astype(np.float32))
    idx = jnp.asarray(rng.integers(0, n, size=e).astype(np.int32))
    seg = jnp.asarray(np.sort(rng.integers(0, n, size=e)).astype(np.int32))
    vals = jnp.asarray(rng.random(e).astype(np.float32))

    def _pallas_lane_gather(depth=64):
        # the Mosaic-LEGAL gather form: lane-local (width 128) — wider
        # sources are "Multiple source vregs along gather dimension"
        # (round-5 capture falsified the r3 same-shape generalization)
        from raft_tpu.sparse.grid_spmv import _lane_gather
        from raft_tpu.util.pallas_utils import pallas_call
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        def kern(x_ref, i_ref, o_ref):
            o_ref[:] = _lane_gather(x_ref[:], i_ref[:])

        def run(xv, iv):
            x2 = jnp.broadcast_to(xv[:128][None, :], (depth, 128))
            i2 = (iv % 128).reshape(-1, depth, 128)

            def one(i_blk):
                return pallas_call(
                    kern,
                    in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                              pl.BlockSpec(memory_space=pltpu.VMEM)],
                    out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
                    out_shape=jax.ShapeDtypeStruct((depth, 128),
                                                   jnp.float32),
                )(x2, i_blk)

            return jax.lax.map(one, i2)

        return jax.jit(run)

    def _pallas_tree_gather(shard_w, depth=64):
        # the production wide-range form: row-broadcast select tree over
        # a (shard_w/128, 128) source — grid SpMV kernel 1's primitive;
        # the rate curve over shard_w prices the tree depth
        from raft_tpu.sparse.grid_spmv import _tree_gather
        from raft_tpu.util.pallas_utils import pallas_call
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        def kern(x_ref, i_ref, o_ref):
            o_ref[:] = _tree_gather(x_ref[:], i_ref[:], i_ref.shape[0])

        def run(xv, iv):
            x2 = xv[:shard_w].reshape(shard_w // 128, 128)
            i2 = (iv % shard_w).reshape(-1, depth, 128)

            def one(i_blk):
                return pallas_call(
                    kern,
                    in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                              pl.BlockSpec(memory_space=pltpu.VMEM)],
                    out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
                    out_shape=jax.ShapeDtypeStruct((depth, 128),
                                                   jnp.float32),
                )(x2, i_blk)

            return jax.lax.map(one, i2)

        return jax.jit(run)

    n_probe = min(e, 1 << 22)
    probes_w = [
        run_case("sparse/probe_dg_width128", _pallas_lane_gather(),
                 x, idx[:n_probe], items=n_probe, width=128)
    ] + [
        run_case(f"sparse/probe_tree_gather{w}", _pallas_tree_gather(w),
                 x, idx[:n_probe], items=n_probe, width=w)
        for w in (1024, 8192, 65536) if w <= n
    ]

    f_gather = jax.jit(lambda v, i: v[i])
    f_take = jax.jit(lambda v, i: jnp.take(v, i, indices_are_sorted=False))
    f_gather_sorted = jax.jit(
        lambda v, i: jnp.take(v, i, indices_are_sorted=True))
    f_seg = jax.jit(functools.partial(
        jax.ops.segment_sum, num_segments=n, indices_are_sorted=True))
    f_sort = jax.jit(jnp.sort)
    f_cumsum = jax.jit(jnp.cumsum)

    return probes_w + [
        run_case("sparse/probe_gather", f_gather, x, idx, items=e),
        run_case("sparse/probe_take", f_take, x, idx, items=e),
        run_case("sparse/probe_take_sorted", f_gather_sorted, x, seg,
                 items=e),
        run_case("sparse/probe_segment_sum_sorted", f_seg, vals, seg,
                 items=e),
        run_case("sparse/probe_sort", f_sort, vals, items=e),
        run_case("sparse/probe_cumsum", f_cumsum, vals, items=e),
    ]


@bench("comms/collectives")
def bench_collectives():
    """Eager MeshComms collective throughput over the local device set
    (VERDICT weak #8: no bench showed collective throughput; ref: NCCL
    perf tests' role for std_comms)."""
    from raft_tpu.comms.comms import MeshComms
    from jax.sharding import Mesh

    devs = np.asarray(jax.devices())
    mesh = Mesh(devs, ("data",))
    comms = MeshComms(mesh, axis_name="data", rank=0)
    n = len(devs)
    rows = 1 << (20 if SIZES["rows"] >= (1 << 20) else 14)
    x = jnp.reshape(
        jax.random.normal(jax.random.key(0), (n * rows,), jnp.float32),
        (n, rows))
    nbytes = int(x.size * 4)

    # On a single device a psum moves no bytes over ICI — the number is
    # collective DISPATCH overhead, not link throughput, and is labeled as
    # such so it can't be read as an ICI measurement (round-2 verdict #6).
    suffix = "" if n > 1 else "_dispatch_overhead"
    out = []
    for name, fn in (("allreduce", lambda v: comms.allreduce(v)),
                     ("allgather", lambda v: comms.allgather(v)),
                     ("reducescatter", lambda v: comms.reducescatter(v))):
        out.append(run_case(
            f"comms/{name}{suffix}", fn, x, nranks=n, rows=rows,
            **({"bytes_moved": nbytes} if n > 1 else {})))
    return out


@bench("sparse/select_k_csr")
def bench_select_k_csr():
    from raft_tpu.sparse.convert import dense_to_csr
    from raft_tpu.sparse.matrix import select_k

    rng = np.random.default_rng(8)
    rows, cols = 1024, 4096
    dense = rng.normal(size=(rows, cols)).astype(np.float32)
    dense[rng.uniform(size=(rows, cols)) > 0.1] = 0.0
    csr = dense_to_csr(jnp.asarray(dense))

    def f():
        v, i = select_k(None, csr, k=32, select_min=False)
        return v

    return [run_case("sparse/select_k_csr", f, items=rows, k=32)]


@bench("sparse/lanczos")
def bench_lanczos():
    """Spectral embedding via thick-restart Lanczos (BASELINE config 4:
    1M-node/10M-edge graph; ref: detail/lanczos.cuh:537 restart loop)."""
    import time as _time

    import scipy.sparse as sp

    from benches.harness import BenchResult
    from raft_tpu.core.sparse_types import CSRMatrix
    from raft_tpu.random.rmat import rmat_rectangular_gen
    from raft_tpu.random.rng_state import RngState
    from raft_tpu.sparse.solver.lanczos import LanczosConfig, \
        lanczos_compute_eigenpairs

    full = SIZES["rows"] >= (1 << 20)
    scale, n_edges = (20, 10_000_000) if full else (13, 60_000)
    src, dst = rmat_rectangular_gen(None, RngState(11), r_scale=scale,
                                    c_scale=scale, n_edges=n_edges)
    src = np.asarray(src, dtype=np.int64)
    dst = np.asarray(dst, dtype=np.int64)
    keep = src != dst
    src, dst = src[keep], dst[keep]
    n = 1 << scale
    w = np.ones(src.shape[0], np.float32)
    adj = sp.coo_matrix((w, (src, dst)), shape=(n, n))
    adj = adj.maximum(adj.T).tocsr()
    # symmetric normalized laplacian-ish operator: A itself is fine for
    # timing the SpMV+ortho hot loop
    csr = CSRMatrix.from_scipy(adj)
    cfg = LanczosConfig(n_components=4, max_iterations=3, ncv=20,
                        tolerance=0.0)                 # fixed 3 restarts
    n_spmv = cfg.ncv + (cfg.max_iterations - 1) * (cfg.ncv
                                                   - cfg.n_components)

    # The auto dispatch picks the slot-grid plan at this nnz; if the grid
    # kernels fail on this backend (a Mosaic compile regression), fall
    # back to the segment formulation EXPLICITLY so the battery window
    # still records a lanczos number — tagged with which path ran.
    import os

    rows = []
    for forced in (None, "segment"):
        if forced is not None:
            os.environ["RAFT_TPU_SPMV"] = forced
        try:
            from raft_tpu.sparse.linalg import spmv_method

            method = spmv_method(csr) if forced is None else forced
            lanczos_compute_eigenpairs(None, csr, cfg)   # warmup/compile
            t0 = _time.perf_counter()
            lanczos_compute_eigenpairs(None, csr, cfg)
            dt = _time.perf_counter() - t0
            # one-restart run at the same ncv: the (t3 - t1) slope over
            # the 32 extra steps separates the per-step cost from the
            # fixed warmup/startup share that dividing the full solve by
            # n_spmv folds in (capture diagnosis, round 5: 124.8 ms/step
            # reported vs 57 ms standalone SpMV — which one is real?)
            cfg1 = dataclasses.replace(cfg, max_iterations=1)
            lanczos_compute_eigenpairs(None, csr, cfg1)  # warmup/compile
            t0 = _time.perf_counter()
            lanczos_compute_eigenpairs(None, csr, cfg1)
            dt1 = _time.perf_counter() - t0
            n_spmv1 = cfg.ncv
            from benches.harness import marginal_per_call

            marg_s, floor_bound = marginal_per_call(
                dt, dt1, n_spmv, n_spmv1)
            marginal = marg_s * 1e3
            rows.append(BenchResult(
                name="sparse/lanczos_rmat", median_ms=dt * 1e3,
                best_ms=dt * 1e3, repeats=1,
                params={"n_vertices": n, "nnz": int(adj.nnz),
                        "ncv": cfg.ncv, "restarts": 3,
                        "spmv": method,
                        "ms_per_lanczos_step":
                            round(dt * 1e3 / n_spmv, 3),
                        "one_restart_ms": round(dt1 * 1e3, 3),
                        "ms_per_step_marginal": round(marginal, 3),
                        **({"floor_bound": True} if floor_bound
                           else {})}))
            break
        except Exception as e:  # noqa: BLE001 — record, then fall back
            rows.append(BenchResult(
                name="sparse/lanczos_rmat", median_ms=0.0, best_ms=0.0,
                repeats=0,
                params={"error": f"{type(e).__name__}: {e}"[:200],
                        "spmv": "auto" if forced is None else forced}))
        finally:
            if forced is not None:
                os.environ.pop("RAFT_TPU_SPMV", None)
    return rows


@bench("sparse/mst")
def bench_mst():
    """Borůvka MSF on an R-MAT graph (ref: bench target for
    mst_solver_inl.cuh; VERDICT #5 asks for the 10M-edge point)."""
    import os
    import time as _time

    from benches.harness import BenchResult
    from raft_tpu.core.sparse_types import CSRMatrix
    from raft_tpu.random.rmat import rmat_rectangular_gen
    from raft_tpu.random.rng_state import RngState
    from raft_tpu.sparse.solver.mst import mst

    full = SIZES["rows"] >= (1 << 20)
    scale, n_edges = (20, 10_000_000) if full else (14, 100_000)
    src, dst = rmat_rectangular_gen(None, RngState(3), r_scale=scale,
                                    c_scale=scale, n_edges=n_edges)
    src = np.asarray(src, dtype=np.int64)
    dst = np.asarray(dst, dtype=np.int64)
    keep = src != dst                       # drop self-loops
    src, dst = src[keep], dst[keep]
    n = 1 << scale
    rng = np.random.default_rng(5)
    w = rng.random(src.shape[0]).astype(np.float32) + 0.01
    import scipy.sparse as sp
    adj = sp.coo_matrix((w, (src, dst)), shape=(n, n))
    adj = adj.maximum(adj.T).tocsr()        # symmetric, deduped
    csr = CSRMatrix.from_scipy(adj)

    # A/B the Borůvka E-stage: the round-5 slot-grid Pallas path (auto on
    # the compiled backend at this size) vs the XLA scatter-min cascade.
    # Forced via RAFT_TPU_MST so both rows always appear; the grid row
    # carries the plan-build (pack) time separately — it amortizes over
    # reuse the way the SpMV plan does.
    rows = []
    for method in ("grid", "xla"):
        os.environ["RAFT_TPU_MST"] = method
        try:
            if method == "grid":
                from raft_tpu.sparse.solver.mst import _cached_mst_plan

                t0 = _time.perf_counter()
                _cached_mst_plan(csr)            # pack once, timed apart
                pack_ms = (_time.perf_counter() - t0) * 1e3
            else:
                pack_ms = 0.0
            mst(None, csr)                       # warmup/compile
            t0 = _time.perf_counter()
            forest = mst(None, csr)
            dt = _time.perf_counter() - t0
            rows.append(BenchResult(
                name=f"sparse/mst_rmat_{method}", median_ms=dt * 1e3,
                best_ms=dt * 1e3, repeats=1,
                items_per_s=int(adj.nnz) / dt,
                params={"n_vertices": n, "n_edges": int(adj.nnz),
                        "forest_edges": int(forest.n_edges) // 2,
                        "pack_ms": round(pack_ms, 1)}))
        except Exception as e:   # noqa: BLE001 — record, keep sweeping
            rows.append(BenchResult(
                name=f"sparse/mst_rmat_{method}", median_ms=-1.0,
                best_ms=-1.0, repeats=0, items_per_s=0.0,
                params={"error": f"{type(e).__name__}: {e}"[:200]}))
        finally:
            os.environ.pop("RAFT_TPU_MST", None)
    return rows


# -- distance / cluster (BASELINE north-star rebuild layer) -----------------

@bench("distance/pairwise_l2")
def bench_pairwise():
    from raft_tpu.distance.pairwise import pairwise_distance, DistanceType

    x = _data(4096, 256)
    y = _data(1024, 256, seed=9)
    f = jax.jit(functools.partial(pairwise_distance, None,
                                  metric=DistanceType.L2Expanded))
    flops = 2 * x.shape[0] * y.shape[0] * x.shape[1]
    return [run_case("distance/pairwise_l2_4096x1024x256", f, x, y,
                     flops=flops)]


@bench("distance/unexpanded")
def bench_unexpanded():
    """Unexpanded metrics: the Pallas VPU reduction tile vs the blocked
    jnp broadcast it replaced (round-4, VERDICT #5 — done = >=10x at
    4096x1024x256; ref: every metric on Contractions_NT,
    linalg/detail/contractions.cuh:16)."""
    from raft_tpu.linalg.contractions import (pairwise_unexpanded_pallas,
                                              unexpanded_ref)

    x = _data(4096, 256)
    y = _data(1024, 256, seed=9)
    items = x.shape[0] * y.shape[0] * x.shape[1]
    rows = []
    for metric in ("l1", "linf", "canberra"):
        f_pal = jax.jit(functools.partial(pairwise_unexpanded_pallas,
                                          metric=metric))
        f_ref = jax.jit(lambda a, b, _m=metric: unexpanded_ref(a, b, _m))
        rows.append(run_case(f"distance/unexp_{metric}_pallas", f_pal,
                             x, y, items=items, metric=metric))
        rows.append(run_case(f"distance/unexp_{metric}_broadcast", f_ref,
                             x, y, items=items, metric=metric))
    return rows


@bench("cluster/kmeans_iter")
def bench_kmeans():
    from raft_tpu.cluster.kmeans import lloyd_step
    from raft_tpu.util.precision import get_matmul_precision

    x = _data(SIZES["rows"], 64)
    c = _data(256, 64, seed=10)
    f = jax.jit(functools.partial(lloyd_step, n_clusters=256))
    flops = 2 * x.shape[0] * 256 * 64
    tier = get_matmul_precision()
    yield run_case("cluster/lloyd_iter", f, x, c, flops=flops,
                   rows=x.shape[0], k=256, tier=tier)
    # the north-star shape itself (BASELINE config 3) so the sweep JSONL
    # carries the headline row, not only bench_northstar.json
    if SIZES["rows"] >= (1 << 20):
        xn = _data(1 << 20, 128, seed=30)
        cn = _data(1024, 128, seed=31)
        g = jax.jit(functools.partial(lloyd_step, n_clusters=1024))
        yield run_case("cluster/lloyd_iter_northstar_1Mx128_k1024", g,
                       xn, cn, flops=2 * (1 << 20) * 1024 * 128,
                       rows=1 << 20, k=1024, tier=tier)
        # prepared-loop variant (what kmeans_fit/bench.py actually run
        # at tier 'high': X split+norms hoisted out of the iteration)
        from raft_tpu.cluster.kmeans import lloyd_step_prepared
        from raft_tpu.linalg.contractions import lloyd_prepare

        ops, meta = lloyd_prepare(xn, 1024)
        if ops is not None:
            jax.block_until_ready(ops)
            h = functools.partial(lloyd_step_prepared, **meta)
            yield run_case("cluster/lloyd_iter_northstar_prepared", h,
                           ops, cn, flops=2 * (1 << 20) * 1024 * 128,
                           rows=1 << 20, k=1024, tier=tier)


@bench("cluster/mnmg_lloyd_sync")
def bench_mnmg_lloyd_sync():
    """MULTICHIP Lloyd per-iteration wall time, host-driven
    (sync_every=1, one shard_map launch + convergence fetch per
    iteration) vs compiled chunks (sync_every=8, one program per 8
    iterations with the psum epilogues and convergence test fused
    in-graph). The sync=8 row approximates pure device time per
    iteration; the row-pair difference is the host overhead (dispatch +
    sync fetch) the compiled inner loop removes."""
    from jax.sharding import Mesh
    from raft_tpu.cluster.kmeans import KMeansParams, kmeans_fit_mnmg

    devs = np.asarray(jax.devices())
    mesh = Mesh(devs, ("data",))
    n = len(devs)
    rows = 1 << (18 if SIZES["rows"] >= (1 << 20) else 12)
    iters = 16
    x = _data(rows, 64, seed=40)
    p = KMeansParams(n_clusters=SIZES["k"], seed=0, max_iter=iters,
                     tol=-1.0)  # tol<0: never converges → exactly iters

    out = []
    per_iter_ms = {}
    for sync in (1, 8):
        f = functools.partial(kmeans_fit_mnmg, None, p, x, mesh=mesh,
                              sync_every=sync)
        r = run_case(f"cluster/mnmg_lloyd_sync{sync}", f,
                     items=rows * iters, rows=rows, k=SIZES["k"],
                     nranks=n, iters=iters, sync_every=sync,
                     host_syncs=-(-iters // sync))
        per_iter_ms[sync] = r.median_ms / iters
        out.append(r)
    # Device/host split, stamped on BOTH rows so either alone tells the
    # story: device_ms/iter ≈ the chunked per-iter time, host overhead
    # ≈ what sync_every=1 pays on top of it (clamped ≥0: on a fast host
    # the two medians can cross within noise).
    dev = per_iter_ms[8]
    host = max(per_iter_ms[1] - per_iter_ms[8], 0.0)
    for r in out:
        r.params["device_ms_per_iter"] = round(dev, 4)
        r.params["host_overhead_ms_per_iter"] = round(host, 4)
    return out


@bench("neighbors/brute_force")
def bench_knn():
    """Brute-force k-NN (the cuVS consumer workload rebuilt from the
    primitives; tiled fused-metric distances + running top-k)."""
    from raft_tpu.neighbors import knn

    full = SIZES["rows"] >= (1 << 20)
    n, q, d, k = ((1 << 20, 4096, 128, 64) if full
                  else (1 << 14, 512, 64, 32))
    db = _data(n, d, seed=21)
    queries = _data(q, d, seed=22)
    f = jax.jit(functools.partial(knn, None, k=k))
    flops = 2 * q * n * d
    out = [run_case("neighbors/knn_l2", f, db, queries, flops=flops,
                    n=n, q=q, d=d, k=k)]
    if full:
        # the two-vreg fused path (k in (128, 256] rode chunked-radix
        # until round 5 widened MAX_K)
        g = jax.jit(functools.partial(knn, None, k=256))
        out.append(run_case("neighbors/knn_l2_k256", g, db, queries,
                            flops=flops, n=n, q=q, d=d, k=256))
    return out


@bench("neighbors/ivf_recall")
def bench_ivf_recall():
    """IVF-Flat recall-vs-latency against brute force (the claim an ANN
    row has to make: queries/sec at a stated recall@k, never latency
    alone). One blobs database, one era-9 brute baseline row, then a
    probe sweep at nprobe ∈ {1, 4, 16, n_lists} — every sweep row
    stamps recall_at_k (vs the brute ground truth), scanned_frac and
    speedup_vs_brute so the trade-off curve is readable from the rows
    themselves."""
    import raft_tpu
    from raft_tpu.neighbors import ivf_flat, knn
    from raft_tpu.random import RngState, make_blobs

    full = SIZES["rows"] >= (1 << 20)
    # full = the acceptance shape (1M×64, k=10); small = CPU-proxy
    n, q, d, n_lists, k = ((1 << 20, 256, 64, 1024, 10) if full
                           else (1 << 14, 128, 32, 64, 10))
    res = raft_tpu.device_resources(seed=0)
    X, _, _ = make_blobs(res, RngState(11), n, d, n_clusters=n_lists)
    queries = X[:q]
    brute = jax.jit(functools.partial(knn, None, k=k))
    gd, gi = brute(X, queries)
    ground = np.asarray(gi)
    out = [run_case("neighbors/ivf_brute_baseline", brute, X, queries,
                    items=q, n=n, d=d, k=k)]
    idx = ivf_flat.build(res, X, n_lists, seed=0,
                         max_iter=10 if full else 25)
    base_ms = out[0].median_ms
    for nprobe in (1, 4, 16, n_lists):
        f = functools.partial(ivf_flat.search, None, idx, queries, k,
                              nprobe)
        _, ai = f()
        hits = np.asarray([len(set(a) & set(b)) for a, b in
                           zip(ground, np.asarray(ai))])
        r = run_case(f"neighbors/ivf_search_np{nprobe}", f, items=q,
                     n=n, d=d, k=k, n_lists=n_lists, nprobe=nprobe,
                     recall_at_k=round(float(hits.mean()) / k, 4),
                     scanned_frac=round(
                         idx.scanned_fraction(nprobe), 4))
        r.params["speedup_vs_brute"] = round(base_ms / r.median_ms, 2)
        out.append(r)
    return out


@bench("neighbors/ivf_pq_recall")
def bench_ivf_pq_recall():
    """IVF-PQ recall-vs-latency-vs-memory (era 19): the claim a
    product-quantized row has to make is three-sided — queries/sec at
    a stated recall@k at a stated compression. One blobs database, one
    brute baseline row, a flat index built ONLY to measure the bytes
    PQ saves, then a (nprobe, refine) sweep ending at the full-scan
    delegation point. Every sweep row stamps recall_at_k AND
    compression_ratio (flat index bytes / PQ index bytes, read off the
    packed arrays actually resident — not estimated) next to
    scanned_frac and speedup_vs_brute."""
    import raft_tpu
    from raft_tpu.neighbors import ivf_flat, ivf_pq, knn
    from raft_tpu.random import RngState, make_blobs

    full = SIZES["rows"] >= (1 << 20)
    # full = the acceptance shape (1M×128, m=16); small = CPU-proxy
    n, q, d, n_lists, k, m = ((1 << 20, 256, 128, 1024, 10, 16) if full
                              else (1 << 14, 128, 32, 64, 10, 8))
    res = raft_tpu.device_resources(seed=0)
    X, _, _ = make_blobs(res, RngState(19), n, d, n_clusters=n_lists)
    queries = X[:q]
    brute = jax.jit(functools.partial(knn, None, k=k))
    gd, gi = brute(X, queries)
    ground = np.asarray(gi)
    out = [run_case("neighbors/ivf_pq_brute_baseline", brute, X,
                    queries, items=q, n=n, d=d, k=k)]
    flat = ivf_flat.build(res, X, n_lists, seed=0,
                          max_iter=10 if full else 25)
    flat_bytes = int(flat.packed_db.nbytes + flat.packed_ids.nbytes
                     + flat.centroids.nbytes + flat.starts.nbytes
                     + flat.sizes.nbytes)
    idx = ivf_pq.build(res, X, n_lists, m=m, nbits=8,
                       centroids=flat.centroids,
                       pq_max_iter=10 if full else 6, seed=0)
    del flat
    compr = round(flat_bytes / idx.device_bytes(), 2)
    base_ms = out[0].median_ms
    for nprobe, refine in ((1, 0), (4, 0), (16, 0), (16, 4 * k),
                           (n_lists, 4 * k)):
        f = functools.partial(ivf_pq.search, None, idx, queries, k,
                              nprobe, refine=refine)
        _, ai = f()
        hits = np.asarray([len(set(a) & set(b)) for a, b in
                           zip(ground, np.asarray(ai))])
        r = run_case(
            f"neighbors/ivf_pq_search_np{nprobe}_rf{refine}", f,
            items=q, n=n, d=d, k=k, n_lists=n_lists, nprobe=nprobe,
            refine=refine, m=m, nbits=idx.nbits,
            recall_at_k=round(float(hits.mean()) / k, 4),
            compression_ratio=compr,
            scanned_frac=round(idx.scanned_fraction(nprobe), 4))
        r.params["speedup_vs_brute"] = round(base_ms / r.median_ms, 2)
        out.append(r)
    return out


@bench("neighbors/ivf_mnmg_scaling")
def bench_ivf_mnmg_scaling():
    """Sharded IVF serving scaling (era 11): one database, one rank
    sweep 1/2/4/8 over the one-program ``shard_map`` search. Each rank
    row stamps serving qps and p99 from a short closed-loop run against
    a warmed :class:`~raft_tpu.serve.IvfMnmgKnnService` executor (the
    queue/QoS path real traffic takes) next to the raw eager search
    latency run_case measures; a final recovery row kills one of two
    replicas mid-run and stamps ``recovery_time_to_slo_s`` — the
    serving claim a fault-tolerant ANN row has to make."""
    import raft_tpu
    from raft_tpu.neighbors import ivf_flat
    from raft_tpu.neighbors.ivf_mnmg import build_mnmg, search_mnmg
    from raft_tpu.random import RngState, make_blobs
    from raft_tpu.serve import (BatchPolicy, Executor,
                                IvfMnmgKnnService, QosPolicy,
                                ReplicaGroup, TenantPolicy,
                                closed_loop, fleet_closed_loop)

    full = SIZES["rows"] >= (1 << 20)
    n, q, d, n_lists, k, nprobe = ((1 << 18, 256, 64, 256, 10, 16)
                                   if full
                                   else (1 << 13, 64, 32, 32, 10, 4))
    res = raft_tpu.device_resources(seed=0)
    X, _, _ = make_blobs(res, RngState(13), n, d, n_clusters=n_lists)
    X = np.asarray(X)
    queries = X[:q] + 0.01
    flat = ivf_flat.build(res, X, n_lists, seed=0,
                          max_iter=10 if full else 25)

    def make_executor(idx):
        ex = Executor(
            [IvfMnmgKnnService(idx, k=k, nprobe=nprobe)],
            policy=BatchPolicy(max_batch=q, max_wait_ms=2.0),
            qos=QosPolicy({"default": TenantPolicy(slo_latency_s=5.0)}))
        ex.warm([8, q])
        return ex

    out = []
    rank_counts = [r for r in (1, 2, 4, 8) if r <= len(jax.devices())]
    for n_ranks in rank_counts:
        idx = build_mnmg(res, X, n_lists, n_ranks, flat=flat)
        f = functools.partial(search_mnmg, None, idx, queries, k,
                              nprobe)
        r = run_case(f"neighbors/ivf_mnmg_search_r{n_ranks}", f,
                     items=q, n=n, d=d, k=k, n_lists=n_lists,
                     nprobe=nprobe, n_ranks=n_ranks)
        ex = make_executor(idx)
        op = f"ivf_mnmg_k{k}_np{nprobe}_r{n_ranks}_{idx.metric}"
        with ex:
            rep = closed_loop(ex, op, clients=4, rows=8,
                              duration_s=1.0)
        r.params["serve_qps"] = round(rep.qps, 2)
        r.params["serve_p50_ms"] = round(rep.p50_ms, 3)
        r.params["serve_p99_ms"] = round(rep.p99_ms, 3)
        out.append(r)

    # recovery row: two replicas of the widest index, one killed mid-run
    idx = build_mnmg(res, X, n_lists, rank_counts[-1], flat=flat)
    op = (f"ivf_mnmg_k{k}_np{nprobe}_r{rank_counts[-1]}_{idx.metric}")
    group = ReplicaGroup([make_executor(idx) for _ in range(2)])
    with group:
        rep = fleet_closed_loop(group, op, clients=4, rows=8,
                                duration_s=1.5, kill_after_s=0.5)
    from benches.harness import BenchResult

    rec = rep.recovery_time_to_slo_s
    out.append(BenchResult(
        name="neighbors/ivf_mnmg_recovery", repeats=1,
        median_ms=(rec if rec not in (None, float("inf")) else 0.0)
        * 1e3,
        best_ms=(rec if rec not in (None, float("inf")) else 0.0) * 1e3,
        params={"n_ranks": rank_counts[-1], "replicas": 2,
                "killed": rep.killed,
                "recovery_time_to_slo_s":
                    (round(rec, 4) if rec not in (None, float("inf"))
                     else "inf"),
                "fleet_qps": round(rep.fleet.qps, 2),
                "fleet_p99_ms": round(rep.fleet.p99_ms, 3)}))
    return out


# -- serve overload (ISSUE 16; no cpp/bench analogue — the rows witness
#    the serving layer's overload-resilience stack under chaos) ------------

@bench("serve/overload")
def bench_serve_overload():
    """BENCH_ERA=16 overload-resilience rows, measured through the
    chaos harness (serve/loadgen.py) with the resilience stack ARMED.

    * ``serve/overload_step_p99`` — open-loop 4x traffic step against a
      brownout-armed Executor (capacity throttled by a constant
      FaultInjector stall so the step genuinely overloads); median_ms
      is the STEP-phase p99 and the row carries the witnesses the
      smoke gate asserts on (brownout_max_level, retraces, recovered).
    * ``serve/overload_slowreplica_p99`` — closed loop against a
      hedged 4-replica group with one replica straggling on a duty
      cycle (the GC-pause profile hedging is built for); median_ms is
      the STALLED-phase p99 next to the healthy baseline and the hedge
      spend.

    Brownout engagement needs the SLO meter, which only runs with obs
    metrics enabled — the family arms obs for its own duration. Rows
    stamp ``partial: true`` off-TPU: CPU wall-clock smoke of the full
    code path, not an accelerator claim."""
    from benches.harness import BenchResult
    from raft_tpu import obs, serve
    from raft_tpu.comms.faults import FaultInjector
    from raft_tpu.serve import loadgen

    full = jax.default_backend() == "tpu"
    partial = {} if full else {"partial": True}
    rng = np.random.default_rng(16)
    db = rng.standard_normal((2048, 32)).astype(np.float32)
    was_enabled = obs.enabled()
    obs.set_enabled(True)
    out = []
    try:
        # -- traffic-step row (brownout) -------------------------------
        ladder = serve.knn_ladder(db, [32, 16, 8])
        qos = serve.QosPolicy({
            "default": serve.TenantPolicy(slo_latency_s=0.25)})
        qos.SLO_WINDOW_S = 1.5          # bench-speed burn window
        ctl = serve.BrownoutController(
            [ladder], qos=qos, queue_high=0.5, step_interval_s=0.1,
            window_s=0.2, clean_windows=2)
        inj = FaultInjector(seed=0)
        ex = serve.Executor(
            [], policy=serve.BatchPolicy(max_batch=8, max_wait_ms=2.0,
                                         max_queue=64),
            qos=qos, brownout=ctl, faults=inj)
        ex.warm([4, 8])
        inj.stall(0.02)                 # throttle so the 4x step overloads
        with ex:
            rep = loadgen.chaos_traffic_step(
                ex, "knn_k32_l2", base_qps=40.0, step_factor=4.0,
                rows=4, phase_s=1.2, recovery_s=2.5, seed=16)
        step = rep.phases["step"]
        out.append(BenchResult(
            name="serve/overload_step_p99", repeats=1,
            median_ms=step["p99_ms"], best_ms=step["p99_ms"],
            params=dict(partial, scenario="traffic_step",
                        qps=step["qps"],
                        base_p99_ms=rep.phases["base"]["p99_ms"],
                        recovery_p99_ms=rep.phases["recovery"]["p99_ms"],
                        brownout_max_level=rep.brownout_max_level,
                        brownout_recovered=rep.brownout_recovered,
                        retraces=rep.retraces_during,
                        rejected=rep.rejected_total)))

        # -- slow-replica row (hedging) --------------------------------
        injs = [FaultInjector(seed=i) for i in range(4)]
        execs = []
        for i in range(4):
            rex = serve.Executor(
                [serve.KnnService(db, k=8)],
                policy=serve.BatchPolicy(max_batch=16, max_wait_ms=2.0,
                                         max_queue=32),
                faults=injs[i])
            rex.warm()
            execs.append(rex)
        # 0.045: the fractional budget's base window also counts the
        # priming phase's submits, so an exact 0.05 can land a hair
        # over the gate's 5% hedge-rate ceiling
        group = serve.ReplicaGroup(
            execs, hedge=serve.HedgePolicy(delay_floor_s=0.005,
                                           min_samples=16,
                                           budget_fraction=0.045))
        with group:
            # prime the hedger's per-bucket delay estimate (and the
            # fractional budget's base window) before measuring
            loadgen._group_closed_loop(group, "knn_k8_l2", clients=8,
                                       rows=4, duration_s=1.0, seed=3)
            rep = loadgen.chaos_slow_replica(
                group, "knn_k8_l2", stall_s=0.08, victim=0, clients=8,
                rows=4, phase_s=1.5, stall_duty=0.07,
                stall_period_s=0.5, seed=17)
        stalled = rep.phases["stalled"]
        out.append(BenchResult(
            name="serve/overload_slowreplica_p99", repeats=1,
            median_ms=stalled["p99_ms"], best_ms=stalled["p99_ms"],
            params=dict(partial, scenario="slow_replica", replicas=4,
                        qps=stalled["qps"],
                        healthy_p99_ms=rep.phases["healthy"]["p99_ms"],
                        healed_p99_ms=rep.phases["healed"]["p99_ms"],
                        hedge_rate=round(rep.hedge_rate, 4),
                        hedges_issued=rep.hedges_issued,
                        hedges_won=rep.hedges_won)))
    finally:
        obs.set_enabled(was_enabled)
    return out


# -- streaming lifecycle (ISSUE 17; no cpp/bench analogue — the rows
#    witness online mutation + zero-pause compaction + crash recovery) -----

@bench("neighbors/streaming_ingest")
def bench_streaming_ingest():
    """BENCH_ERA=17 streaming-lifecycle rows, measured through the
    serving trio (serve/ingest.py) and the journaled index.

    * ``neighbors/streaming_ingest_p99`` — query p99 while a sustained
      insert+delete stream drives background compaction; the row
      carries the lifecycle witnesses the smoke gate asserts on
      (ingest rate, swaps crossed, per-query recall floor against an
      exact reference over the snapshot window each query was served
      from, zero failures).
    * ``neighbors/streaming_recovery`` — wall-clock to recover a
      journaled index (newest intact epoch + WAL replay) after a
      mutation history, with the content-CRC bit-equality witness.

    Rows stamp ``partial: true`` off-TPU: CPU wall-clock smoke of the
    full code path, not an accelerator claim."""
    import tempfile
    import time

    from benches.harness import BenchResult
    from raft_tpu import serve
    from raft_tpu.neighbors.streaming import StreamingIndex, stream_build

    full = jax.default_backend() == "tpu"
    partial = {} if full else {"partial": True}
    rng = np.random.default_rng(17)
    db = rng.standard_normal((2048, 16)).astype(np.float32)
    out = []

    # -- sustained-ingest row (queries racing compaction swaps) --------
    idx = stream_build(None, db, 16, seed=0, max_iter=8,
                       repack_slack=96)
    idx.compact(reason="provision")
    svc = serve.StreamingKnnService(idx, k=10, nprobe=12)
    ctl = serve.IngestController(
        idx, [svc],
        policy=serve.BatchPolicy(max_batch=16, max_wait_ms=2.0),
        compact_interval=0.05, refit=False, warm_buckets=[8, 16])
    with ctl:
        rep = serve.streaming_loop(
            ctl, svc.name, clients=4, rows=8, duration_s=2.5,
            ingest_rows=64, ingest_interval_s=0.02, delete_frac=0.3,
            seed=17)
    out.append(BenchResult(
        name="neighbors/streaming_ingest_p99", repeats=1,
        median_ms=rep.p99_ms, best_ms=rep.p50_ms,
        params=dict(partial, qps=round(rep.qps, 2),
                    ingest_rate=round(rep.ingest_rate, 1),
                    ingest_rows=rep.ingest_rows,
                    deleted_rows=rep.deleted_rows,
                    swaps=rep.swaps, compactions=rep.compactions,
                    min_recall=round(rep.min_recall, 4),
                    mean_recall=round(rep.mean_recall, 4),
                    failed=rep.failed)))

    # -- recovery row (epoch load + WAL replay after a "crash") --------
    with tempfile.TemporaryDirectory() as d:
        jidx = stream_build(None, db, 16, seed=0, max_iter=8,
                            directory=d, repack_slack=128)
        jidx.insert(rng.standard_normal((256, 16)).astype(np.float32))
        jidx.delete(np.arange(0, 512, 3))          # WAL: delete record
        for s in range(3):                         # WAL: fitting inserts
            jidx.insert(rng.standard_normal((64, 16)).astype(np.float32))
        crc = jidx.content_crc()
        t0 = time.perf_counter()
        rec = StreamingIndex.recover(None, d)
        wall_ms = (time.perf_counter() - t0) * 1e3
        out.append(BenchResult(
            name="neighbors/streaming_recovery", repeats=1,
            median_ms=wall_ms, best_ms=wall_ms,
            params=dict(partial, n_live=rec.n_live, epoch=rec.epoch,
                        crc_match=rec.content_crc() == crc)))
    return out


# -- durable streaming fleet (ISSUE 18; no cpp/bench analogue — the rows
#    witness WAL shipping, scrub/read-repair and drift maintenance) -------

@bench("serve/durability")
def bench_durability():
    """BENCH_ERA=18 durability rows for the replicated streaming fleet.

    * ``serve/durability_catchup_d{64,256}`` — wall-clock for a
      restarted follower to fold a WAL backlog of that depth through
      :meth:`WalFollower.catch_up` (the restart-to-converged time the
      mid-stream SIGKILL witness measures end-to-end), with the
      content-CRC bit-equality witness and ``snapshot: false`` proving
      the records path (not a resync) was measured.
    * ``serve/durability_scrub`` — one clean scrub pass over a
      journaled directory (the steady-state background cost), plus the
      ``detect_repair_ok`` witness: a seeded bit-flip in the newest
      epoch is quarantined + repaired and the next pass is clean.
    * ``serve/durability_drift_{stream,rebuild}`` — time-to-accuracy
      under distribution drift: maintenance wall-clock (streaming
      ``maybe_refit`` per batch vs one full rebuild at the end) against
      the recall@k each strategy holds mid-stream and finally, at an
      nprobe where quantizer quality matters.

    Rows stamp ``partial: true`` off-TPU: CPU wall-clock smoke of the
    full code path, not an accelerator claim."""
    import tempfile
    import time

    from benches.harness import BenchResult
    from raft_tpu.comms.comms import _Mailbox
    from raft_tpu.comms.faults import FaultInjector
    from raft_tpu.neighbors.scrub import Scrubber
    from raft_tpu.neighbors.streaming import stream_build
    from raft_tpu.neighbors.wal_ship import (WalFollower, WalShipper,
                                             bootstrap_follower)

    full = jax.default_backend() == "tpu"
    partial = {} if full else {"partial": True}
    rng = np.random.default_rng(18)
    dim, n_lists = 16, 16
    db = rng.standard_normal((2048, dim)).astype(np.float32)
    out = []

    # -- catch-up vs WAL depth (deletes: in-place records, never
    #    folded into an epoch mid-bench, so the backlog depth holds) --
    with tempfile.TemporaryDirectory() as d:
        leader = stream_build(None, db, n_lists, seed=0, max_iter=8,
                              directory=d)
        mbx = _Mailbox()
        shipper = WalShipper(leader, mbx, 0, [1],
                             poll_interval=0.005).attach()
        shipper.start()
        # one seeded mutation: a fresh build sits at cursor −1, and a
        # follower asking "from 0" is indistinguishable from a blank
        # bootstrap — it would snapshot-resync instead of exercising
        # the records path this row is supposed to measure
        leader.delete(leader.live_rows()[1][:1])
        try:
            for depth in (64, 256):
                wf = WalFollower(bootstrap_follower(
                    None, dim=dim, n_lists=n_lists), mbx, 1, 0)
                wf.catch_up(timeout=60.0)          # baseline resync
                live = leader.live_rows()[1]
                for i in range(depth):             # the WAL backlog
                    leader.delete(live[i:i + 1])
                t0 = time.perf_counter()
                rpt = wf.catch_up(timeout=60.0)
                wall_ms = (time.perf_counter() - t0) * 1e3
                out.append(BenchResult(
                    name=f"serve/durability_catchup_d{depth}",
                    repeats=1, median_ms=wall_ms, best_ms=wall_ms,
                    params=dict(partial, wal_depth=depth,
                                records=rpt.records,
                                snapshot=rpt.snapshot,
                                crc_match=wf.index.content_crc()
                                == leader.content_crc())))
                # undo the tombstones so the next depth has live rows
                leader.compact(reason="bench_reset")
                wf.catch_up(timeout=60.0)
        finally:
            shipper.stop()
            shipper.detach()

    # -- scrub pass cost + detect/repair witness ----------------------
    with tempfile.TemporaryDirectory() as d:
        idx = stream_build(None, db, n_lists, seed=0, max_iter=8,
                           directory=d)
        ids = idx.insert(rng.standard_normal(
            (256, dim)).astype(np.float32))
        idx.delete(ids[::5])
        sc = Scrubber(idx, interval=60.0)
        t0 = time.perf_counter()
        clean = sc.run_once()
        wall_ms = (time.perf_counter() - t0) * 1e3
        newest = idx.log.epoch_path(max(idx.log.epoch_steps()))
        FaultInjector().corrupt_bytes(newest)
        hit = sc.run_once()
        ok = (bool(hit.quarantined) and bool(hit.repaired)
              and not sc.run_once().corrupt)
        out.append(BenchResult(
            name="serve/durability_scrub", repeats=1,
            median_ms=wall_ms, best_ms=wall_ms,
            params=dict(partial, files_checked=clean.files_checked,
                        detect_repair_ok=ok)))

    # -- time-to-accuracy under drift: streaming refit vs rebuild -----
    def _recall(idx, q, k, nprobe):
        _, exact = idx.search(q, k, idx.flat.n_lists)   # exact path
        _, got = idx.search(q, k, nprobe)
        hits = sum(len(np.intersect1d(got[i], exact[i]))
                   for i in range(q.shape[0]))
        return hits / float(q.shape[0] * k)

    k, nprobe, n_batches = 10, 3, 6
    base = rng.standard_normal((1024, dim)).astype(np.float32)
    shift = np.full((dim,), 4.0, np.float32)           # the drift
    batches = [(rng.standard_normal((128, dim)) + shift * (b + 1)
                / n_batches).astype(np.float32)
               for b in range(n_batches)]
    queries = (rng.standard_normal((32, dim))
               + shift).astype(np.float32)             # post-drift load

    for mode in ("stream", "rebuild"):
        idx = stream_build(None, base, n_lists, seed=0, max_iter=8)
        maintain_s, refits, recall_mid = 0.0, 0, 1.0
        for b, batch in enumerate(batches):
            idx.insert(batch)
            if mode == "stream":
                t0 = time.perf_counter()
                refits += bool(idx.maybe_refit(force=True))
                maintain_s += time.perf_counter() - t0
            if b == n_batches - 1:                     # mid = pre-fix
                recall_mid = _recall(idx, queries, k, nprobe)
        if mode == "rebuild":
            rows, _ = idx.live_rows()
            t0 = time.perf_counter()
            idx = stream_build(None, np.asarray(rows), n_lists,
                               seed=0, max_iter=8)
            maintain_s += time.perf_counter() - t0
            refits = 1
        out.append(BenchResult(
            name=f"serve/durability_drift_{mode}", repeats=1,
            median_ms=maintain_s * 1e3, best_ms=maintain_s * 1e3,
            params=dict(partial, refits=refits,
                        recall_mid=round(recall_mid, 4),
                        recall_final=round(_recall(idx, queries, k,
                                                   nprobe), 4))))
    return out


@bench("serve/failover")
def bench_failover():
    """BENCH_ERA=20 failover rows for the term-fenced fleet.

    * ``serve/failover_election_n3`` — kill-to-new-leader wall-clock
      over an in-proc 3-node clique (``median_ms``: kill through both
      survivors' elections settled; ``best_ms``: the winner's own
      detection-free ballot), with the determinism witnesses: the
      most-caught-up survivor won and the loser converged
      ``content_crc``-bit-equal after the heal.
    * ``serve/failover_ingest_gap`` — the write-unavailability window
      a failover opens: leader kill through the FIRST mutation applied
      on the promoted successor.
    * ``serve/failover_ack_{async,majority}`` — per-insert latency
      under each shipper ack mode against two live followers
      (``median_ms`` = p50; params carry p99); the majority row stamps
      ``p99_overhead_vs_async``, the price of the zero-acked-loss
      guarantee the chaos witness asserts.

    Rows stamp ``partial: true`` off-TPU: CPU wall-clock smoke of the
    full code path, not an accelerator claim."""
    import os
    import tempfile
    import threading
    import time

    from benches.harness import BenchResult
    from raft_tpu.comms.comms import _Mailbox
    from raft_tpu.neighbors.election import ElectionNode
    from raft_tpu.neighbors.streaming import stream_build
    from raft_tpu.neighbors.wal_ship import (WalFollower, WalShipper,
                                             bootstrap_follower)

    full = jax.default_backend() == "tpu"
    partial = {} if full else {"partial": True}
    rng = np.random.default_rng(20)
    dim, n_lists = 16, 16
    db = rng.standard_normal((2048, dim)).astype(np.float32)
    out = []

    def batch(m=8):
        return rng.standard_normal((m, dim)).astype(np.float32)

    # -- election + ingest gap over a 3-node clique -------------------
    with tempfile.TemporaryDirectory() as d:
        idx0 = stream_build(None, db, n_lists, seed=0, max_iter=8,
                            directory=os.path.join(d, "n0"))
        mbx = _Mailbox()
        n0 = ElectionNode(idx0, mbx, 0, [0, 1, 2], role="leader",
                          leader=0, acks="async", election_timeout=2.0,
                          heartbeat_interval=0.05)
        n0.shipper.attach()
        n0.shipper.start()
        followers = []
        for r in (1, 2):
            fidx = bootstrap_follower(
                None, dim=dim, n_lists=n_lists,
                directory=os.path.join(d, f"n{r}"))
            wf = WalFollower(fidx, mbx, r, 0)
            wf.catch_up(timeout=60.0)
            followers.append(ElectionNode(
                fidx, mbx, r, [0, 1, 2], role="follower", leader=0,
                acks="async", election_timeout=2.0, follower=wf))
        n1, n2 = followers
        for _ in range(4):
            idx0.insert(batch())
        n1.follower.drain()
        n2.follower.drain()

        n0.shipper.stop()
        n0.shipper.detach()
        t_kill = time.perf_counter()
        mbx.fail_peer(0, "bench kill")
        recs = {}

        def run(node):
            recs[node.rank] = node.run_election()

        threads = [threading.Thread(target=run, args=(n,))
                   for n in (n1, n2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60.0)
        elect_ms = (time.perf_counter() - t_kill) * 1e3
        winner = recs[1].winner
        lead = n1 if winner == 1 else n2
        lose = n2 if winner == 1 else n1
        # the first mutation applied on the successor closes the gap
        lead.index.insert(batch())
        gap_ms = (time.perf_counter() - t_kill) * 1e3
        lose.follower.drain()
        crc_ok = lead.index.content_crc() == lose.index.content_crc()
        out.append(BenchResult(
            name="serve/failover_election_n3", repeats=1,
            median_ms=elect_ms,
            best_ms=recs[winner].seconds * 1e3,
            params=dict(partial, fleet=3, term=recs[1].term,
                        winner_most_caught_up=recs[1].votes[winner]
                        == max(recs[1].votes.values()),
                        crc_match=crc_ok)))
        out.append(BenchResult(
            name="serve/failover_ingest_gap", repeats=1,
            median_ms=gap_ms, best_ms=gap_ms,
            params=dict(partial, fleet=3,
                        writes_resumed=lead.index.applied_seq
                        > recs[1].votes[winner][1])))
        lead.shipper.stop()
        lead.shipper.detach()

    # -- quorum-ack p99 overhead vs async shipping --------------------
    p99_by_mode = {}
    for mode in ("async", "majority"):
        with tempfile.TemporaryDirectory() as d:
            # provision per-list tail slack so the timed op stream
            # never shape-changes: a mid-loop repack recompile would
            # put a ~300 ms spike into whichever mode it lands on and
            # drown the ack overhead being measured
            leader = stream_build(None, db, n_lists, seed=0,
                                  max_iter=8,
                                  directory=os.path.join(d, "n0"),
                                  repack_slack=64)
            leader.compact(reason="provision")
            mbx = _Mailbox()
            sh = WalShipper(leader, mbx, 0, [1, 2], acks=mode,
                            ack_timeout=60.0,
                            poll_interval=0.005).attach()
            sh.start()
            stop = threading.Event()
            pumps = []
            for r in (1, 2):
                fidx = bootstrap_follower(
                    None, dim=dim, n_lists=n_lists,
                    directory=os.path.join(d, f"n{r}"))
                wf = WalFollower(fidx, mbx, r, 0)
                wf.catch_up(timeout=60.0)

                def pump(follower=wf):
                    while not stop.is_set():
                        follower.drain()
                        time.sleep(0.002)

                t = threading.Thread(target=pump, daemon=True)
                t.start()
                pumps.append(t)
            lat_ms = []
            try:
                for _ in range(4):          # first-touch compiles
                    leader.insert(batch())
                for _ in range(64):
                    t0 = time.perf_counter()
                    leader.insert(batch())
                    lat_ms.append((time.perf_counter() - t0) * 1e3)
            finally:
                stop.set()
                for t in pumps:
                    t.join(timeout=10.0)
                sh.stop()
                sh.detach()
            p99_by_mode[mode] = float(np.percentile(lat_ms, 99))
            p99_by_mode[f"{mode}_p50"] = float(np.median(lat_ms))
            extra = {}
            if mode == "majority":
                extra["p99_overhead_vs_async"] = round(
                    p99_by_mode["majority"] / p99_by_mode["async"], 3)
                # the stable comparator: single-sample p99 on a busy
                # CPU container is tail-noise-dominated, the median
                # isolates the per-write ack wait itself
                extra["p50_overhead_vs_async"] = round(
                    p99_by_mode["majority_p50"]
                    / p99_by_mode["async_p50"], 3)
                extra["quorum_waits"] = sh.quorum_waits
            out.append(BenchResult(
                name=f"serve/failover_ack_{mode}", repeats=len(lat_ms),
                median_ms=float(np.median(lat_ms)),
                best_ms=float(np.min(lat_ms)),
                params=dict(partial, followers=2,
                            p99_ms=round(p99_by_mode[mode], 3),
                            **extra)))
    return out


# -- stats (ref: bench/prims/stats/*.cu — the domain had no bench family
#    until round 3; the round-2 verdict flagged zero on-TPU stats numbers) --

@bench("stats/moments")
def bench_stats_moments():
    from raft_tpu.stats import mean, meanvar, minmax

    x = _data(SIZES["rows"], SIZES["cols"])
    return [
        run_case("stats/mean", jax.jit(lambda a: mean(a)), x,
                 bytes_moved=x.size * 4),
        run_case("stats/meanvar", jax.jit(lambda a: meanvar(a)), x,
                 bytes_moved=x.size * 4),
        run_case("stats/minmax", jax.jit(lambda a: minmax(a)), x,
                 bytes_moved=x.size * 4),
    ]


@bench("stats/metrics")
def bench_stats_metrics():
    """Histogram (both strategies) + label-pair clustering metrics at
    full-scale sample counts (ref: bench/prims/stats/ — contingency feeds
    rand_index the way detail/contingency_matrix.cuh feeds the metrics)."""
    from raft_tpu.stats import adjusted_rand_index, entropy, histogram
    from raft_tpu.stats.histogram import HistType

    n = SIZES["rows"]
    rng = np.random.default_rng(17)
    data = jnp.asarray(rng.uniform(size=(n, 8)).astype(np.float32))
    ya = jnp.asarray(rng.integers(0, 32, n).astype(np.int32))
    yb = jnp.asarray(rng.integers(0, 32, n).astype(np.int32))
    h_onehot = jax.jit(functools.partial(
        histogram, n_bins=64, binner=lambda v, r, c: v * 64,
        hist_type=HistType.Smem))
    h_scatter = jax.jit(functools.partial(
        histogram, n_bins=2048, binner=lambda v, r, c: v * 2048,
        hist_type=HistType.Gmem))
    h_factored = jax.jit(functools.partial(
        histogram, n_bins=2048, binner=lambda v, r, c: v * 2048))
    ari = jax.jit(functools.partial(adjusted_rand_index, n_classes=32))
    ent = jax.jit(functools.partial(entropy, lower=0, upper=32))
    return [
        run_case("stats/histogram_64bins_onehot", h_onehot, data,
                 items=data.size),
        run_case("stats/histogram_2048bins_scatter", h_scatter, data,
                 items=data.size),
        run_case("stats/histogram_2048bins_factored", h_factored, data,
                 items=data.size),
        run_case("stats/adjusted_rand_index", ari, ya, yb, items=n),
        run_case("stats/entropy", ent, ya, items=n),
    ]


# -- util (ref: bench/prims/util/popc.cu) -----------------------------------

@bench("util/cache")
def bench_device_cache():
    """Device-resident functional cache (ref: util/cache.cuh:102 Cache;
    the in-kernel lookup/assign of cache_util.cuh). One steady-state
    cycle = batched lookup + insert-the-batch (the get_or_compute shape)
    as ONE jitted program threading the cache state."""
    from raft_tpu.util.cache import (device_cache_init, device_cache_insert,
                                     device_cache_lookup)

    n_vec, cap, batch = 128, 8192, 4096
    st = device_cache_init(n_vec=n_vec, capacity=cap, associativity=32)
    rng = np.random.default_rng(5)
    # distinct keys: device_cache_insert's batch contract (duplicate
    # same-set keys race for one victim way, XLA-unspecified winner)
    keys = jnp.asarray(rng.choice(cap * 2, batch,
                                  replace=False).astype(np.int32))
    vecs = _data(batch, n_vec, seed=6)
    # Warm to ~50% occupancy.  Deterministic insert needs at most one NEW
    # key per set per batch — distinctness is not enough: new same-set keys
    # elect the same argmin victim way, so one 4096-key insert into 256
    # empty sets would retain only ~1 entry per set (~3% occupancy).
    # Round-robin keys into per-set rounds (round j = each set's j-th key),
    # padded with the negative drop sentinel to keep one jit shape.
    keys_np, n_sets = np.asarray(keys), int(st.n_sets)
    sets = keys_np % n_sets
    order = np.lexsort((np.arange(batch), sets))
    start = np.r_[0, np.flatnonzero(np.diff(sets[order])) + 1]
    rounds = np.empty(batch, np.int64)
    rounds[order] = np.arange(batch) - np.repeat(start, np.diff(
        np.r_[start, batch]))
    n_rounds = int(rounds.max()) + 1
    pad_keys = np.full((n_rounds, n_sets), -1, np.int32)
    pad_rows = np.zeros((n_rounds, n_sets), np.int64)
    pad_keys[rounds, sets] = keys_np
    pad_rows[rounds, sets] = np.arange(batch)
    warm = jax.jit(device_cache_insert)
    for j in range(n_rounds):
        st = warm(st, jnp.asarray(pad_keys[j]), vecs[pad_rows[j]])

    @jax.jit
    def cycle(st, keys, vecs):
        out, hit, st = device_cache_lookup(st, keys)
        st = device_cache_insert(st, keys, vecs)
        return out, hit, st

    return [run_case("util/device_cache_cycle", cycle, st, keys, vecs,
                     items=batch, n_vec=n_vec, capacity=cap)]


@bench("util/popc")
def bench_popc():
    from raft_tpu.core.bitset import popc

    n = SIZES["rows"] * 32
    words = jnp.asarray(np.random.default_rng(11).integers(
        0, 2 ** 31, n // 32, dtype=np.int64).astype(np.int32))
    f = jax.jit(popc)
    return [run_case("util/popc", f, words, bytes_moved=n // 8)]
