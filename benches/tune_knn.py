"""Fused-kNN tuning sweep (round 5): find where the 97.7 ms goes.

The captured headline (neighbors/knn_l2, 1M x 128, q=4096, k=64:
97.65 ms, mxu_frac 0.057) runs the insertion-epilogue fused kernel at
its default tiles (tm=256, tn=1024) and the session precision tier.
The distance contraction alone is ~1.07 logical TFLOP -> ~16 ms at the
'high' tier's effective rate, so the epilogue + gate overhead plausibly
holds 4-5x headroom. This sweep prices each component separately, at
the headline shape, with the same two-point marginal timing as every
other harness:

- tm x tn grid (pool geometry: per-round cost scales with tm*tn, round
  COUNT falls with wider tn only via fewer gate evaluations);
- epilogue share: the same grid/tiles with the insertion drain replaced
  by a single running min-fold (matmul + 1-pass epilogue floor);
- tier: 'high' (bf16x3 split) vs 'default' (single bf16 pass) prices
  the MXU passes — 'default' changes ACCURACY (~1e-3 rel distances),
  recorded for the dispatch table, not proposed as the default;
- k sensitivity at the best tiles.

One JSON line per case -> tpu_battery_out/knn_tune.jsonl (appended by
ci/tpu_battery.sh or run standalone). Ref anchor: the reference tunes
its fusedL2NN Policy<> tiles per arch offline the same way
(distance/detail/fused_distance_nn/custom_policies: tile templates).
"""

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))


def main():
    import jax
    import jax.numpy as jnp

    from benches.harness import marginal_per_call

    on_tpu = jax.default_backend() == "tpu"
    n, q, d, k = ((1 << 20, 4096, 128, 64) if on_tpu
                  else (1 << 14, 512, 64, 32))
    kd, kq = jax.random.split(jax.random.key(21))
    db = jax.random.normal(kd, (n, d), jnp.float32)
    queries = jax.random.normal(kq, (q, d), jnp.float32)
    jax.block_until_ready((db, queries))
    flops = 2 * q * n * d

    def emit(**kw):
        print(json.dumps({"bench": "neighbors/knn_tune", **kw}),
              flush=True)

    def sync(v):
        jax.device_get(jnp.ravel(v)[0])

    def time_marginal(fn, n_full=4):
        """Two-point marginal ms per call (block of n_full vs n_full//2)."""
        out = fn()
        sync(out[0])                      # compile + warm
        n_half = max(1, n_full // 2)

        def block(nb):
            t0 = time.perf_counter()
            o = None
            for _ in range(nb):
                o = fn()
            sync(o[0])
            return (time.perf_counter() - t0) * 1e3

        tf, th = block(n_full), block(n_half)
        per, fb = marginal_per_call(tf, th, n_full, n_half)
        return per, fb

    from raft_tpu.neighbors.fused_topk import knn_fused

    # -- tm x tn grid at the session tier --------------------------------
    best = (None, float("inf"))
    for tm in (128, 256, 512):
        for tn in (512, 1024, 2048, 4096):
            f = jax.jit(functools.partial(knn_fused, k=k, tm=tm, tn=tn))
            try:
                ms, fb = time_marginal(lambda: f(queries, db))
                emit(case="tile_sweep", tm=tm, tn=tn,
                     ms=round(ms, 2),
                     GFLOP_per_s=round(flops / ms / 1e6, 1),
                     **({"floor_bound": True} if fb else {}))
                # floor-bound rows are flagged-suspect measurements —
                # they must not steer the downstream sweeps
                if not fb and ms < best[1]:
                    best = ((tm, tn), ms)
            except Exception as e:   # noqa: BLE001 — record, keep sweeping
                emit(case="tile_sweep", tm=tm, tn=tn,
                     error=f"{type(e).__name__}: {e}"[:200])
    if best[0] is not None:
        emit(case="tile_best", tiles=best[0], ms=round(best[1], 2))
    else:
        emit(case="tile_best", error="no clean tile_sweep row")
    btm, btn = best[0] if best[0] else (256, 1024)

    # -- epilogue share: insertion drain replaced by a 1-pass min fold ----
    # (the floor of ANY fused formulation at these tiles: distance tiles
    # at matmul rate + one vector pass over each; the gap to the full
    # kernel is the insertion epilogue's price)
    from raft_tpu.neighbors.fused_topk import _minonly_probe

    for tm, tn in {(256, 1024), (btm, btn)}:
        f = jax.jit(functools.partial(_minonly_probe, tm=tm, tn=tn))
        try:
            ms, fb = time_marginal(lambda: f(queries, db))
            emit(case="minonly_floor", tm=tm, tn=tn, ms=round(ms, 2),
                 GFLOP_per_s=round(flops / ms / 1e6, 1),
                 **({"floor_bound": True} if fb else {}))
        except Exception as e:   # noqa: BLE001
            emit(case="minonly_floor", tm=tm, tn=tn,
                 error=f"{type(e).__name__}: {e}"[:200])

    # -- tier: single-pass bf16 distances (accuracy trade recorded) ------
    from raft_tpu.util import precision as prec

    old = prec.get_matmul_precision()
    try:
        for tier in ("default", "high"):
            prec.set_matmul_precision(tier)
            f = jax.jit(functools.partial(knn_fused, k=k, tm=btm, tn=btn))
            try:
                ms, fb = time_marginal(lambda: f(queries, db))
                emit(case="tier", tier=tier, tm=btm, tn=btn,
                     ms=round(ms, 2),
                     GFLOP_per_s=round(flops / ms / 1e6, 1),
                     **({"floor_bound": True} if fb else {}))
            except Exception as e:   # noqa: BLE001
                emit(case="tier", tier=tier,
                     error=f"{type(e).__name__}: {e}"[:200])
    finally:
        prec.set_matmul_precision(old)

    # -- drain-strip width at wide matmul tiles --------------------------
    # (sw decouples the per-round vector width from the distance tile's
    # MXU width — the round-5 strip-drain lever; sw=0 is the whole tile)
    for tm, tn in ((256, 1024), (256, 4096), (512, 4096)):
        for sw in (0, 128, 256, 512):
            if sw and tn % sw:
                continue
            f = jax.jit(functools.partial(knn_fused, k=k, tm=tm, tn=tn,
                                          sw=sw))
            try:
                ms, fb = time_marginal(lambda: f(queries, db))
                emit(case="strip_sweep", tm=tm, tn=tn, sw=sw,
                     ms=round(ms, 2),
                     GFLOP_per_s=round(flops / ms / 1e6, 1),
                     **({"floor_bound": True} if fb else {}))
            except Exception as e:   # noqa: BLE001
                emit(case="strip_sweep", tm=tm, tn=tn, sw=sw,
                     error=f"{type(e).__name__}: {e}"[:200])

    # -- adversarial db ordering: rows sorted so EVERY tile improves the
    # bound (best candidates last) — the drain's worst case (~k rounds
    # per tile, the merge cost). Quantifies the safety margin the AUTO
    # adoption of insertion needs for a general primitive.
    try:
        norms = jnp.sum(db * db, axis=1)
        db_adv = db[jnp.argsort(-norms)]
        jax.block_until_ready(db_adv)
        f = jax.jit(functools.partial(knn_fused, k=k, tm=btm, tn=btn))
        ms, fb = time_marginal(lambda: f(queries, db_adv))
        emit(case="adversarial_sorted", tm=btm, tn=btn, ms=round(ms, 2),
             **({"floor_bound": True} if fb else {}))
    except Exception as e:   # noqa: BLE001
        emit(case="adversarial_sorted",
             error=f"{type(e).__name__}: {e}"[:200])

    # -- k sensitivity at the best tiles ---------------------------------
    for kk in (16, 64, 128, 256):
        f = jax.jit(functools.partial(knn_fused, k=kk, tm=btm, tn=btn))
        try:
            ms, fb = time_marginal(lambda: f(queries, db))
            emit(case="k_sweep", k=kk, ms=round(ms, 2),
                 **({"floor_bound": True} if fb else {}))
        except Exception as e:   # noqa: BLE001
            emit(case="k_sweep", k=kk,
                 error=f"{type(e).__name__}: {e}"[:200])


if __name__ == "__main__":
    main()
