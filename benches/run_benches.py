"""Bench runner (ref: cpp/bench/prims/ executables via `./build.sh
bench-prims`; docs/source/build.md:171-183).

Usage:
    python benches/run_benches.py                 # all, small sizes
    python benches/run_benches.py --filter linalg # substring filter
    python benches/run_benches.py --size full     # production sizes
Prints one JSON line per case.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--filter", default="", help="substring filter")
    ap.add_argument("--family", default=None,
                    help="exact family name (the battery's per-family "
                         "isolation needs exact match: a substring filter "
                         "would drag matrix/select_k_large into "
                         "matrix/select_k's time budget)")
    ap.add_argument("--size", choices=("small", "full"), default="small")
    ap.add_argument("--list", action="store_true")
    args = ap.parse_args()

    from benches import bench_prims
    from benches.harness import REGISTRY

    if args.size == "full":
        bench_prims.SIZES = bench_prims._FULL

    if args.family is not None:
        if args.family not in REGISTRY:
            sys.exit(f"unknown family {args.family!r}; see --list")
        names = [args.family]
    else:
        names = sorted(n for n in REGISTRY if args.filter in n)
    if args.list:
        print("\n".join(names))
        return

    import jax
    print(f"# devices: {[d.device_kind for d in jax.devices()]}",
          file=sys.stderr)
    import json

    failed = False
    for name in names:
        try:
            for result in REGISTRY[name]():
                print(result.json_line(), flush=True)
        except Exception as e:   # keep the sweep going, report the failure
            # the error row goes to STDOUT as data and the exit code goes
            # nonzero: the battery must never stamp family_done for a
            # family that died (round 5: three Mosaic-crash families were
            # silently skipped this way)
            print(json.dumps({"bench": name, "error":
                              f"{type(e).__name__}: {e}"[:500]}),
                  flush=True)
            print(f"# {name} FAILED: {type(e).__name__}: {e}",
                  file=sys.stderr)
            failed = True
    if failed:
        sys.exit(3)


if __name__ == "__main__":
    main()
