"""North-star tuning sweep (round-3 verdict item 3): run the fused Lloyd
kernel at its measured frontier and record WHERE the time goes.

Sweeps, at the BASELINE config-3 shape (1M×128 f32, k=1024, one chip):
- tm ∈ {128, 256, 512, 1024} at the default tier (round-2 sweep measured
  tm=256 fastest at the single-pass tier; this pins it at tier 'high');
- precision tiers at the chosen tm (MXU-pass scaling: 2/5/2+ passes per
  iteration — if 'default'≈'high' the kernel is epilogue/VPU-bound, not
  MXU-bound);
- host-loop vs lax.scan iteration (the round-2 3× scan regression), and a
  single-step sync time so tunnel dispatch overhead is separable.

One JSON line per case → ci/tpu_battery.sh redirects to
tpu_battery_out/northstar_tune.jsonl. Ref anchor for the exercise:
linalg/detail/contractions.cuh:16-309 (the reference tunes its
Policy<> tile templates per arch the same way, offline).
"""

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))


def main():
    import jax
    import jax.numpy as jnp

    from raft_tpu.cluster.kmeans import lloyd_step
    from raft_tpu.linalg.contractions import fused_lloyd_pallas
    from raft_tpu.util import precision as prec

    on_tpu = jax.default_backend() == "tpu"
    m, k, n_clusters = (1_000_000, 128, 1024) if on_tpu else (20_000, 64,
                                                              256)
    iters = 30 if on_tpu else 3
    kx, kc = jax.random.split(jax.random.key(0))
    x = jax.random.normal(kx, (m, k), jnp.float32)
    c = jax.random.normal(kc, (n_clusters, k), jnp.float32)
    jax.block_until_ready((x, c))

    def sync(v):
        jax.device_get(jnp.ravel(v)[0])

    def emit(**kw):
        print(json.dumps({"bench": "cluster/northstar_tune", **kw}),
              flush=True)

    def time_loop(fn, n_iter):
        out = fn()
        sync(out[0])                       # compile + warm
        t0 = time.perf_counter()
        for _ in range(n_iter):
            out = fn()
        sync(out[0])
        return (time.perf_counter() - t0) / n_iter * 1e3

    # -- tm sweep at the default tier ------------------------------------
    for tm in (128, 256, 512, 1024):
        f = jax.jit(functools.partial(fused_lloyd_pallas, tm=tm))
        try:
            ms = time_loop(lambda: f(x, c), iters)
            emit(case="tm_sweep", tm=tm, tier=prec.get_matmul_precision(),
                 ms_per_iter=round(ms, 3))
        except Exception as e:   # noqa: BLE001 — record, keep sweeping
            emit(case="tm_sweep", tm=tm, error=f"{type(e).__name__}: {e}"[:200])

    # -- packed vs 3-dot bf16x3 spelling, PINNED to tier 'high' ----------
    # (the packed knob only exists on the split kernels — at any other
    # tier fused_lloyd_pallas ignores it and this would be an A/A run)
    old = prec.get_matmul_precision()
    try:
        prec.set_matmul_precision("high")
        for packed in (False, True):
            f = jax.jit(functools.partial(fused_lloyd_pallas,
                                          packed=packed))
            try:
                ms = time_loop(lambda: f(x, c), iters)
                emit(case="packed_split", packed=packed, tier="high",
                     ms_per_iter=round(ms, 3))
            except Exception as e:   # noqa: BLE001
                emit(case="packed_split", packed=packed,
                     error=f"{type(e).__name__}: {e}"[:200])
    finally:
        prec.set_matmul_precision(old)

    # -- prepared loop: X split+norms hoisted out of the iteration
    # (lloyd_prepare) vs recomputed every step — the measured value of
    # ~1.3 GB/iter of avoided HBM traffic at tier 'high'
    old = prec.get_matmul_precision()
    try:
        prec.set_matmul_precision("high")    # prepare only applies at 'high'
        from raft_tpu.cluster.kmeans import lloyd_step_prepared
        from raft_tpu.linalg.contractions import lloyd_prepare

        ops_prep, meta = lloyd_prepare(x, n_clusters)
        if ops_prep is None:
            emit(case="prepared_loop", error="prepare declined")
        else:
            jax.block_until_ready(ops_prep)
            ms = time_loop(lambda: lloyd_step_prepared(ops_prep, c, **meta),
                           iters)
            emit(case="prepared_loop", tier="high",
                 ms_per_iter=round(ms, 3),
                 iters_per_s=round(1e3 / ms, 2))
            # counts on the MXU (ones @ one-hot) vs the VPU reduce — the
            # round-5 epilogue lever candidate: the epilogue is VPU-bound
            # (BASELINE roofline), this trades its counts pass onto the
            # matrix unit (raw kernel, not the full step: the delta is
            # what matters)
            from raft_tpu.linalg.contractions import fused_lloyd_prepared

            for cm in (False, True):
                try:
                    ms2 = time_loop(
                        lambda: fused_lloyd_prepared(ops_prep, c, **meta,
                                                     counts_mxu=cm),
                        iters)
                    emit(case="counts_mxu", counts_mxu=cm, tier="high",
                         ms_per_iter=round(ms2, 3),
                         iters_per_s=round(1e3 / ms2, 2))
                except Exception as e:   # noqa: BLE001
                    emit(case="counts_mxu", counts_mxu=cm,
                         error=f"{type(e).__name__}: {e}"[:200])
            # scanned block (lloyd_iterate_prepared): the whole chain in
            # ONE launch — prices what per-launch overhead + lost cross-
            # launch overlap cost the per-step loop above. Per-iter cost
            # is TWO-POINT MARGINAL (full-length block minus half-length
            # block, like bench.py and benches/harness.py): every fixed
            # cost of a block — tunnel RTT, dispatch, the sync fetch —
            # cancels in the difference, so no RTT probe is needed (the
            # former ready-buffer refetch probe read 493 ms in a window
            # where the region's own sync paid ~0; subtracting it
            # fabricated impossible speeds).
            try:
                from raft_tpu.cluster.kmeans import lloyd_iterate_prepared

                halfn = max(1, iters // 2)
                blk_f = jax.jit(functools.partial(
                    lloyd_iterate_prepared, n_steps=iters, **meta))
                blk_h = jax.jit(functools.partial(
                    lloyd_iterate_prepared, n_steps=halfn, **meta))
                sync(blk_f(ops_prep, c)[1])      # warm both executables
                sync(blk_h(ops_prep, c)[1])
                t0 = time.perf_counter()
                sync(blk_f(ops_prep, c)[1])
                total_ms = (time.perf_counter() - t0) * 1e3
                t0 = time.perf_counter()
                sync(blk_h(ops_prep, c)[1])
                half_ms = (time.perf_counter() - t0) * 1e3
                from benches.harness import marginal_per_call

                marg, fb = marginal_per_call(total_ms, half_ms, iters,
                                             halfn, floor_frac=0.5)
                emit(case="scan_prepared", tier="high", n_steps=iters,
                     ms_per_iter=round(total_ms / iters, 3),
                     ms_per_iter_marginal=round(marg, 3),
                     **({"floor_bound": True} if fb else {}))
            except Exception as e:   # noqa: BLE001
                emit(case="scan_prepared",
                     error=f"{type(e).__name__}: {e}"[:200])
    except Exception as e:   # noqa: BLE001
        emit(case="prepared_loop", error=f"{type(e).__name__}: {e}"[:200])
    finally:
        prec.set_matmul_precision(old)

    # -- bf16 END-TO-END inputs (VERDICT #3's "bf16-input end-to-end"
    # lever): when the caller's data is ALREADY bf16, every dot is one
    # exact MXU pass (bf16×bf16 accumulates in f32 — no split needed, no
    # accuracy tier in play) and X tiles move half the HBM bytes. This is
    # the honest fast path: full accuracy RELATIVE TO THE DATA's own
    # precision, unlike tier 'default' which silently rounds f32 data.
    try:
        xb, cb = x.astype(jnp.bfloat16), c.astype(jnp.bfloat16)
        jax.block_until_ready((xb, cb))
        fb = jax.jit(functools.partial(lloyd_step, n_clusters=n_clusters))
        ms = time_loop(lambda: fb(xb, cb), iters)
        emit(case="bf16_inputs", ms_per_iter=round(ms, 3),
             iters_per_s=round(1e3 / ms, 2))
    except Exception as e:   # noqa: BLE001
        emit(case="bf16_inputs", error=f"{type(e).__name__}: {e}"[:200])

    # -- tier sweep at auto tm -------------------------------------------
    old = prec.get_matmul_precision()
    step = functools.partial(lloyd_step, n_clusters=n_clusters)
    try:
        for tier in ("default", "high", "highest"):
            try:
                prec.set_matmul_precision(tier)
                g = jax.jit(step)
                ms = time_loop(lambda: g(x, c), iters)
                emit(case="tier_sweep", tier=tier,
                     ms_per_iter=round(ms, 3),
                     iters_per_s=round(1e3 / ms, 2))
            except Exception as e:   # noqa: BLE001 — keep sweeping
                emit(case="tier_sweep", tier=tier,
                     error=f"{type(e).__name__}: {e}"[:200])
    finally:
        prec.set_matmul_precision(old)

    # -- dispatch overhead: 1-step sync vs amortized loop ----------------
    g = jax.jit(step)
    try:
        out = g(x, c)
        sync(out[0])
        t0 = time.perf_counter()
        out = g(x, c)
        sync(out[0])
        single = (time.perf_counter() - t0) * 1e3
        amort = time_loop(lambda: g(x, c), iters)
        emit(case="dispatch_overhead", single_step_ms=round(single, 3),
             amortized_ms=round(amort, 3),
             overhead_ms=round(max(single - amort, 0.0), 3))
    except Exception as e:   # noqa: BLE001
        amort = float("nan")
        emit(case="dispatch_overhead",
             error=f"{type(e).__name__}: {e}"[:200])

    # -- host loop vs lax.scan (the 3x restaging regression) -------------
    def scan_iters(x, c, n_iter):
        def body(cc, _):
            nc, inertia, _ = step(x, cc)
            return nc, inertia
        cc, inertias = jax.lax.scan(body, c, None, length=n_iter)
        return cc, inertias

    s = jax.jit(functools.partial(scan_iters, n_iter=iters))
    try:
        cc, _ = s(x, c)
        sync(cc)
        t0 = time.perf_counter()
        cc, _ = s(x, c)
        sync(cc)
        scan_ms = (time.perf_counter() - t0) / iters * 1e3
        emit(case="scan_vs_loop", scan_ms_per_iter=round(scan_ms, 3),
             loop_ms_per_iter=round(amort, 3))
    except Exception as e:   # noqa: BLE001
        emit(case="scan_vs_loop", error=f"{type(e).__name__}: {e}"[:200])


if __name__ == "__main__":
    main()
