"""Adaptive quality brownout tests (ISSUE 16 tentpole, control half):
ladder construction/validation, deterministic hysteresis via the
injectable clock, the per-tenant min_quality floor, admission-time
resolution through the executor with the zero-recompile contract
across level changes, and the floor-violation flight bundle.
"""

import time

import numpy as np
import pytest

from raft_tpu import obs, serve
from raft_tpu.obs import metrics as obs_metrics
from raft_tpu.serve.brownout import (BrownoutController,
                                     BrownoutFloorError,
                                     DegradationLadder, ivf_ladder,
                                     knn_ladder)

DIM = 16
OP = "knn_k8_l2"                       # level-0 op of the test ladder


@pytest.fixture
def live_obs():
    was_enabled = obs.enabled()
    old_reg = obs_metrics.set_registry(obs.MetricsRegistry())
    old_sink = obs.set_sink(None)
    obs.set_enabled(True)
    try:
        yield obs_metrics.get_registry()
    finally:
        obs.set_enabled(was_enabled)
        obs_metrics.set_registry(old_reg)
        obs.set_sink(old_sink)


@pytest.fixture(scope="module")
def db():
    rng = np.random.default_rng(11)
    return rng.standard_normal((256, DIM)).astype(np.float32)


def _ladder(db):
    return knn_ladder(db, [8, 4, 2])


def _counter_value(reg, name, **labels):
    fam = reg.snapshot().get(name)
    if not fam:
        return 0.0
    return sum(s["value"] for s in fam["series"]
               if all(s["labels"].get(k) == v for k, v in labels.items()))


def _gauge_value(reg, name, **labels):
    fam = reg.snapshot().get(name)
    if not fam:
        return None
    for s in fam["series"]:
        if all(s["labels"].get(k) == v for k, v in labels.items()):
            return s["value"]
    return None


class TestLadder:
    def test_knn_ladder_shape(self, db):
        lad = _ladder(db)
        assert lad.depth == 3
        assert lad.op == OP
        names = [s.name for s in lad.services]
        assert len(set(names)) == 3
        # clamping at both ends
        assert lad.service(-3).name == names[0]
        assert lad.service(99).name == names[-1]
        assert lad.service(1).name == names[1]

    def test_knn_ladder_rejects_non_descending(self, db):
        with pytest.raises(ValueError, match="descending"):
            knn_ladder(db, [4, 8])
        with pytest.raises(ValueError, match="descending"):
            knn_ladder(db, [8, 8, 4])

    def test_empty_ladder_rejected(self):
        with pytest.raises(ValueError, match="at least one"):
            DegradationLadder([])

    def test_non_monotone_cost_rejected(self, db):
        # a "degraded" level that costs MORE than its predecessor is a
        # configuration bug caught at construction
        cheap = serve.KnnService(db, k=2)
        costly = serve.KnnService(db, k=8)
        with pytest.raises(ValueError, match="not monotone"):
            DegradationLadder([cheap, costly])

    def test_dim_mismatch_rejected(self, db):
        rng = np.random.default_rng(0)
        other = rng.standard_normal((64, DIM * 2)).astype(np.float32)
        with pytest.raises(ValueError, match="dim"):
            DegradationLadder([serve.KnnService(db, k=4),
                               serve.KnnService(other, k=2)])

    def test_ivf_ladder_filters_and_validates(self, res, db):
        from raft_tpu.neighbors import ivf_flat

        idx = ivf_flat.build(res, db, 8, seed=0, max_iter=4)
        lad = ivf_ladder(idx, k=4, nprobes=(6, 4, 2))
        assert lad.depth == 3
        # nprobes at/above n_lists are clamped out, not served
        assert ivf_ladder(idx, k=4, nprobes=(32, 16, 6, 3)).depth == 2
        with pytest.raises(ValueError, match="descending"):
            ivf_ladder(idx, k=4, nprobes=(2, 6))
        with pytest.raises(ValueError, match="no valid nprobe"):
            ivf_ladder(idx, k=4, nprobes=(64, 32))


class TestHysteresis:
    """Pure controller dynamics, driven through tick()'s injectable
    clock — no executor, no wall-clock sleeps."""

    def _ctl(self, db, **over):
        kw = dict(engage_burn=1.0, queue_high=0.8, step_interval_s=1.0,
                  window_s=1.0, clean_windows=3, enabled=True)
        kw.update(over)
        return BrownoutController([_ladder(db)], **kw)

    def test_engages_one_step_per_interval(self, db):
        ctl = self._ctl(db)
        ctl.tick(queue_frac=0.0, burn_by_tenant={"t": 2.0}, now=100.0)
        assert ctl.level(OP, "t") == 1
        # inside the step interval: no further deepening
        ctl.tick(queue_frac=0.0, burn_by_tenant={"t": 5.0}, now=100.5)
        assert ctl.level(OP, "t") == 1
        ctl.tick(queue_frac=0.0, burn_by_tenant={"t": 5.0}, now=101.1)
        assert ctl.level(OP, "t") == 2
        # depth-1 is the ladder cap
        ctl.tick(queue_frac=0.0, burn_by_tenant={"t": 9.0}, now=103.0)
        assert ctl.level(OP, "t") == 2

    def test_queue_pressure_engages_without_burn(self, db):
        ctl = self._ctl(db)
        ctl.tick(queue_frac=0.95, burn_by_tenant={"t": 0.0}, now=10.0)
        assert ctl.level(OP, "t") == 1

    def test_recovery_needs_clean_streak_and_restarts(self, db):
        ctl = self._ctl(db)
        ctl.tick(queue_frac=0.0, burn_by_tenant={"t": 2.0}, now=100.0)
        ctl.tick(queue_frac=0.0, burn_by_tenant={"t": 2.0}, now=101.1)
        assert ctl.level(OP, "t") == 2
        # clean ticks: no up-step until clean_windows * window_s elapse
        ctl.tick(queue_frac=0.0, burn_by_tenant={"t": 0.0}, now=102.0)
        ctl.tick(queue_frac=0.0, burn_by_tenant={"t": 0.0}, now=104.0)
        assert ctl.level(OP, "t") == 2
        ctl.tick(queue_frac=0.0, burn_by_tenant={"t": 0.0}, now=105.1)
        assert ctl.level(OP, "t") == 1
        # the streak restarts after each up-step: walking, not snapping
        ctl.tick(queue_frac=0.0, burn_by_tenant={"t": 0.0}, now=106.0)
        assert ctl.level(OP, "t") == 1
        ctl.tick(queue_frac=0.0, burn_by_tenant={"t": 0.0}, now=108.2)
        assert ctl.level(OP, "t") == 0

    def test_hot_tick_resets_clean_streak(self, db):
        ctl = self._ctl(db)
        # drive to the ladder cap (level 2) so a later hot tick cannot
        # deepen further — isolating the streak-reset effect
        ctl.tick(queue_frac=0.0, burn_by_tenant={"t": 2.0}, now=100.0)
        ctl.tick(queue_frac=0.0, burn_by_tenant={"t": 2.0}, now=101.1)
        assert ctl.level(OP, "t") == 2
        ctl.tick(queue_frac=0.0, burn_by_tenant={"t": 0.0}, now=102.0)
        ctl.tick(queue_frac=0.0, burn_by_tenant={"t": 0.0}, now=104.0)
        # burn returns mid-streak: the streak restarts from scratch
        ctl.tick(queue_frac=0.0, burn_by_tenant={"t": 3.0}, now=104.5)
        ctl.tick(queue_frac=0.0, burn_by_tenant={"t": 0.0}, now=105.0)
        ctl.tick(queue_frac=0.0, burn_by_tenant={"t": 0.0}, now=107.5)
        assert ctl.level(OP, "t") == 2, \
            "clean streak must restart after a hot tick"
        ctl.tick(queue_frac=0.0, burn_by_tenant={"t": 0.0}, now=108.1)
        assert ctl.level(OP, "t") == 1

    def test_min_quality_floor_caps_depth(self, db):
        qos = serve.QosPolicy({
            "gold": serve.TenantPolicy(min_quality=0),
            "std": serve.TenantPolicy(min_quality=1),
            "batch": serve.TenantPolicy()})
        ctl = self._ctl(db, qos=qos)
        for i in range(4):
            ctl.tick(queue_frac=0.95,
                     burn_by_tenant={"gold": 9.0, "std": 9.0,
                                     "batch": 9.0},
                     now=100.0 + 1.1 * i)
        lad = _ladder(db)
        assert ctl.resolve(OP, "gold") == (OP, 0)
        assert ctl.resolve(OP, "std") == (lad.services[1].name, 1)
        assert ctl.resolve(OP, "batch") == (lad.services[2].name, 2)

    def test_min_quality_validation(self):
        with pytest.raises(ValueError, match="min_quality"):
            serve.TenantPolicy(min_quality=-1)

    def test_unknown_op_passes_through(self, db):
        ctl = self._ctl(db)
        assert ctl.resolve("pairwise_l2_expanded", "t") == \
            ("pairwise_l2_expanded", 0)

    def test_disabled_controller_serves_full_quality(self, db):
        ctl = self._ctl(db, enabled=False)
        ctl.tick(queue_frac=0.95, burn_by_tenant={"t": 9.0}, now=50.0)
        # state still tracks the signal (flipping the switch back on
        # engages immediately) but resolution pins level 0
        assert ctl.level(OP, "t") == 1
        assert ctl.resolve(OP, "t") == (OP, 0)

    def test_env_kill_switch(self, db, monkeypatch):
        monkeypatch.setenv("RAFT_TPU_BROWNOUT", "off")
        ctl = BrownoutController([_ladder(db)])
        assert not ctl.enabled

    def test_snapshot_nonzero_only(self, db):
        ctl = self._ctl(db)
        assert ctl.snapshot() == {}
        ctl.tick(queue_frac=0.0, burn_by_tenant={"t": 2.0}, now=100.0)
        assert ctl.snapshot() == {OP: {"t": 1}}


class TestExecutorIntegration:
    def test_degraded_serving_zero_recompiles(self, db, live_obs):
        """The acceptance core: every ladder level pre-warms, the
        controller's level changes re-route admission, and the retrace
        counter stays flat across ALL transitions."""
        ctl = BrownoutController([_ladder(db)], enabled=True,
                                 step_interval_s=0.01)
        ex = serve.Executor(
            [], policy=serve.BatchPolicy(max_batch=32, max_wait_ms=1.0),
            brownout=ctl)
        assert set(ex.services) == {s.name for s in _ladder(db).services}
        ex.warm([8])
        traces_at_warm = ex.stats.traces
        rng = np.random.default_rng(3)
        q = rng.standard_normal((4, DIM)).astype(np.float32)
        with ex:
            outs = {}
            for i, lvl in enumerate([0, 1, 2, 1, 0]):
                # drive the level directly (deterministic), then serve
                ctl.tick(queue_frac=0.0,
                         burn_by_tenant={"default":
                                         9.0 if lvl > ctl.level(
                                             OP, "default") else 0.0},
                         now=1000.0 + i)
                # force the exact level for determinism
                with ctl._lock:
                    from raft_tpu.serve.brownout import _TenantState
                    st = ctl._state.setdefault((OP, "default"),
                                               _TenantState())
                    st.level = lvl
                req = ex.submit_request(OP, q)
                assert req.level == lvl
                out = req.future.result(timeout=60.0)
                outs[lvl] = out
        # degraded levels return fewer neighbors (the k-cap ladder)
        assert np.asarray(outs[0][1]).shape == (4, 8)
        assert np.asarray(outs[1][1]).shape == (4, 4)
        assert np.asarray(outs[2][1]).shape == (4, 2)
        assert ex.stats.traces == traces_at_warm, \
            "stepping the ladder must never compile"
        assert set(ex.stats.brownout_levels) == {0, 1, 2}
        assert ex.stats.brownout_levels[0] == 2

    def test_brownout_level_gauge_and_event(self, db, live_obs):
        ctl = BrownoutController([_ladder(db)], enabled=True)
        ctl.tick(queue_frac=0.0, burn_by_tenant={"gold": 2.0},
                 now=10.0)
        assert _gauge_value(live_obs, "serve_brownout_level",
                            service=OP, tenant="gold") == 1.0
        ctl.tick(queue_frac=0.0, burn_by_tenant={"gold": 0.0},
                 now=20.0)
        ctl.tick(queue_frac=0.0, burn_by_tenant={"gold": 0.0},
                 now=30.0)
        assert _gauge_value(live_obs, "serve_brownout_level",
                            service=OP, tenant="gold") == 0.0

    def test_maybe_tick_is_rate_limited(self, db):
        ctl = BrownoutController([_ladder(db)], enabled=True,
                                 step_interval_s=3600.0)
        ex = serve.Executor(
            [], policy=serve.BatchPolicy(max_batch=8, max_wait_ms=1.0),
            brownout=ctl)
        ctl.maybe_tick(ex)
        t1 = ctl._last_tick
        ctl.maybe_tick(ex)              # inside the half-interval
        assert ctl._last_tick == t1

    def test_floor_violation_flight_recorded(self, db, live_obs):
        """A response stamped below min_quality is a controller bug:
        metered AND flight-recorded, never silently shipped."""
        qos = serve.QosPolicy({"gold": serve.TenantPolicy(
            min_quality=0)})
        ex = serve.Executor(
            [serve.KnnService(db, k=8)],
            policy=serve.BatchPolicy(max_batch=8, max_wait_ms=1.0),
            qos=qos)
        rng = np.random.default_rng(5)
        q = rng.standard_normal((2, DIM)).astype(np.float32)
        obs.clear_flight_bundles()
        # bypass admission (which would clamp) to forge the violation
        r = ex.queue.submit_request(OP, q, tenant="gold", level=2)
        ex._check_floor(r)
        assert _counter_value(live_obs,
                              "serve_brownout_floor_violations_total",
                              tenant="gold") == 1.0
        bundles = obs.flight_bundles("BrownoutFloorError")
        assert bundles, "floor violation must flight-record"
        assert "min_quality floor" in bundles[-1]["header"]["error"]

    def test_floor_error_carries_context(self):
        e = BrownoutFloorError("x", op="op", tenant="t", level=2,
                               floor=1)
        assert (e.op, e.tenant, e.level, e.floor) == ("op", "t", 2, 1)
