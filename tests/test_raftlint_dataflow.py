"""tools/raftlint/dataflow.py lattice semantics, unit-level.

The rule-facing behavior (R10–R14 firing and staying silent) lives in
test_raftlint.py; this file pins the engine itself: the AV join,
host-loop widening, the donation bit riding ``lax.while_loop`` carries,
axis-name scoping through nested ``shard_map`` applications, and
interprocedural constant/dtype propagation through the call closure.
"""

from __future__ import annotations

import textwrap
from pathlib import Path

from tools.raftlint import dataflow
from tools.raftlint.core import Project
from tools.raftlint.dataflow import AV, TOP, join


def analyze(root: Path, files: dict):
    for rel, src in files.items():
        path = root / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(src), encoding="utf-8")
        d = path.parent
        while d != root:
            init = d / "__init__.py"
            if not init.exists():
                init.write_text("", encoding="utf-8")
            d = d.parent
    project = Project(str(root))
    project.scan(["raft_tpu"])
    assert not project.errors, project.errors
    return project, dataflow.analyze(project)


def env_of(df, symbol: str):
    summ = df.summary(symbol)
    assert summ is not None, symbol
    return summ.env


# ---------------------------------------------------------------------------
# the lattice itself


def test_join_keeps_agreement_and_drops_conflict():
    a = AV(shape=(8, 128), dtype="float32", const=4)
    b = AV(shape=(8, 128), dtype="float32", const=4)
    j = join(a, b)
    assert j.shape == (8, 128) and j.dtype == "float32" and j.const == 4

    c = AV(shape=(8, 64), dtype="bfloat16", const=5)
    j = join(a, c)
    assert j.shape == (8, None)        # per-dim join, rank preserved
    assert j.dtype is None and j.const is None


def test_join_accumulates_donation_and_tags():
    a = AV(donated=True, tags=frozenset({"axis_index"}))
    b = AV(donated=False, tags=frozenset({"padded"}))
    j = join(a, b)
    assert j.donated                   # may-analysis: either path donates
    assert j.tags == {"axis_index", "padded"}


def test_join_mismatched_rank_loses_shape():
    assert join(AV(shape=(8,)), AV(shape=(8, 128))).shape is None


def test_const_join_is_type_strict():
    # 1 == True in python; the lattice must not conflate them
    assert join(AV(const=1), AV(const=True)).const is None


def test_promote_dtype_follows_float_widths():
    assert dataflow.promote_dtype("float32", "float64") == "float64"
    assert dataflow.promote_dtype("bfloat16", "float32") == "float32"
    assert dataflow.promote_dtype("float32", "float32") == "float32"
    assert dataflow.promote_dtype("float32", None) is None


# ---------------------------------------------------------------------------
# host-loop widening


def test_loop_carry_join_widens_changing_const(tmp_path):
    _, df = analyze(tmp_path, {"raft_tpu/a.py": """
        def f(xs):
            n = 0
            for x in xs:
                n = n + 1
            return n
    """})
    env = env_of(df, "raft_tpu.a:f")
    # n is 0 on entry, 1 after one pass: the fixed point is unknown,
    # never a wrongly-pinned literal
    assert env["n"].const is None


def test_loop_invariant_const_survives_widening(tmp_path):
    _, df = analyze(tmp_path, {"raft_tpu/a.py": """
        def f(xs):
            tile = 256
            for x in xs:
                use = tile
            return tile
    """})
    env = env_of(df, "raft_tpu.a:f")
    assert env["tile"].const == 256


def test_branch_join_merges_environments(tmp_path):
    _, df = analyze(tmp_path, {"raft_tpu/a.py": """
        def f(flag):
            if flag:
                n = 128
            else:
                n = 128
            return n

        def g(flag):
            if flag:
                n = 128
            else:
                n = 100
            return n
    """})
    assert env_of(df, "raft_tpu.a:f")["n"].const == 128
    assert env_of(df, "raft_tpu.a:g")["n"].const is None


# ---------------------------------------------------------------------------
# the donation bit through lax control-flow carries


def test_donation_bit_rides_while_loop_carry(tmp_path):
    _, df = analyze(tmp_path, {"raft_tpu/a.py": """
        import functools

        import jax

        @functools.partial(jax.jit, donate_argnums=(0,))
        def outer(buf):
            def body(carry):
                return carry
            out = jax.lax.while_loop(lambda c: True, body, buf)
            return out
    """})
    env = env_of(df, "raft_tpu.a:outer")
    assert env["buf"].donated          # the decorator marks the param
    assert env["out"].donated          # ...and the carry keeps the bit


def test_undonated_carry_stays_clean(tmp_path):
    _, df = analyze(tmp_path, {"raft_tpu/a.py": """
        import jax

        def outer(buf):
            def body(carry):
                return carry
            out = jax.lax.while_loop(lambda c: True, body, buf)
            return out
    """})
    assert not env_of(df, "raft_tpu.a:outer")["out"].donated


def test_donating_defs_registry_sees_decorators(tmp_path):
    _, df = analyze(tmp_path, {"raft_tpu/a.py": """
        import functools

        import jax

        @functools.partial(jax.jit, static_argnames=("n",),
                           donate_argnums=(1,))
        def chunk(x, scratch, n):
            return scratch

        @jax.jit
        def plain(x):
            return x
    """})
    assert df.donating_defs == {"raft_tpu.a:chunk": (1,)}


def test_jit_wrap_facts_resolve_through_variables(tmp_path):
    _, df = analyze(tmp_path, {"raft_tpu/a.py": """
        import jax

        def body(a, b):
            return a + b

        run = jax.jit(body, donate_argnums=(0, 1))

        def use(a, b):
            return run(a, b)
    """})
    ev = [e for e in df.calls
          if e.fn.symbol == "raft_tpu.a:use" and e.facts][0]
    assert ev.facts.donate == (0, 1)
    assert ev.facts.symbol == "raft_tpu.a:body"


# ---------------------------------------------------------------------------
# axis-name scoping through nested shard_map


def test_axes_scope_reaches_the_mapped_body(tmp_path):
    _, df = analyze(tmp_path, {"raft_tpu/a.py": """
        import jax

        def body(x):
            return jax.lax.psum(x, "data")

        def run(x, devs):
            mesh = jax.sharding.Mesh(devs, axis_names=("data",))
            return jax.shard_map(body, mesh=mesh, in_specs=None,
                                 out_specs=None)(x)
    """})
    scoped = [e for e in df.collectives if e.axes_scope is not None]
    assert scoped and scoped[0].axes_scope == frozenset({"data"})


def test_nested_shard_map_unions_axis_scopes(tmp_path):
    _, df = analyze(tmp_path, {"raft_tpu/a.py": """
        import jax

        def inner(x):
            return jax.lax.psum(x, "model")

        def body(x, devs):
            sub = jax.sharding.Mesh(devs, axis_names=("model",))
            return jax.shard_map(inner, mesh=sub, in_specs=None,
                                 out_specs=None)(x)

        def run(x, devs):
            mesh = jax.sharding.Mesh(devs, axis_names=("data",))
            return jax.shard_map(body, mesh=mesh, in_specs=None,
                                 out_specs=None)(x, devs)
    """})
    scopes = {e.axes_scope for e in df.collectives
              if e.fn.symbol == "raft_tpu.a:inner"
              and e.axes_scope is not None}
    # the contextual pass sees both meshes; the standalone pass of
    # `body` (outer mesh invisible) may also record the inner-only view
    assert frozenset({"data", "model"}) in scopes


def test_jit_of_shard_map_keeps_axes(tmp_path):
    _, df = analyze(tmp_path, {"raft_tpu/a.py": """
        import jax

        def body(x):
            return jax.lax.psum(x, "data")

        def run(x, devs):
            mesh = jax.sharding.Mesh(devs, axis_names=("data",))
            chunk = jax.jit(jax.shard_map(body, mesh=mesh,
                                          in_specs=None,
                                          out_specs=None),
                            donate_argnums=(0,))
            return chunk(x)
    """})
    scoped = [e for e in df.collectives if e.axes_scope is not None]
    assert scoped and scoped[0].axes_scope == frozenset({"data"})


def test_unresolvable_mesh_leaves_scope_unknown(tmp_path):
    _, df = analyze(tmp_path, {"raft_tpu/a.py": """
        import jax

        def body(x):
            return jax.lax.psum(x, "data")

        def run(x, mesh):
            return jax.shard_map(body, mesh=mesh, in_specs=None,
                                 out_specs=None)(x)
    """})
    assert all(e.axes_scope is None for e in df.collectives)


# ---------------------------------------------------------------------------
# interprocedural propagation through the closure


def test_consts_flow_through_calls_and_returns(tmp_path):
    _, df = analyze(tmp_path, {"raft_tpu/a.py": """
        def double(v):
            return v * 2

        def use():
            got = double(64)
            return got
    """})
    assert env_of(df, "raft_tpu.a:use")["got"].const == 128


def test_module_constants_resolve_across_modules(tmp_path):
    _, df = analyze(tmp_path, {
        "raft_tpu/consts.py": "LANES = 128\n",
        "raft_tpu/a.py": """
            from raft_tpu.consts import LANES

            def f():
                tile = LANES * 2
                return tile
        """})
    assert env_of(df, "raft_tpu.a:f")["tile"].const == 256


def test_ctor_shapes_and_dtypes_propagate(tmp_path):
    _, df = analyze(tmp_path, {"raft_tpu/a.py": """
        import jax.numpy as jnp

        def f():
            a = jnp.zeros((8, 128), dtype=jnp.bfloat16)
            b = jnp.ones((4,))
            return a, b
    """})
    env = env_of(df, "raft_tpu.a:f")
    assert env["a"].shape == (8, 128)
    assert env["a"].dtype == "bfloat16"
    assert env["b"].dtype == "float32"     # jnp default


def test_padding_helper_output_carries_the_tag(tmp_path):
    _, df = analyze(tmp_path, {"raft_tpu/a.py": """
        from raft_tpu.util.math import round_up_to_multiple

        def f(n):
            tile = round_up_to_multiple(n, 128)
            return tile
    """})
    assert "padded" in env_of(df, "raft_tpu.a:f")["tile"].tags


def test_recursion_terminates_at_top(tmp_path):
    _, df = analyze(tmp_path, {"raft_tpu/a.py": """
        def ping(n):
            return pong(n)

        def pong(n):
            return ping(n)
    """})
    summ = df.summary("raft_tpu.a:ping")
    assert summ is not None and summ.returns is not None


def test_analyze_memoizes_per_project(tmp_path):
    project, df = analyze(tmp_path, {
        "raft_tpu/a.py": "def f():\n    return 1\n"})
    assert dataflow.analyze(project) is df
