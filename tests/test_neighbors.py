"""Brute-force k-NN tests vs sklearn/numpy oracles (ref lineage:
cuvs::neighbors::brute_force built from this primitives layer)."""

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.neighbors import knn


@pytest.fixture
def rng():
    return np.random.default_rng(17)


class TestBruteForceKnn:
    @pytest.mark.parametrize("n,q,d,k", [(100, 10, 8, 5), (3000, 64, 16, 20)])
    def test_l2_vs_sklearn(self, rng, n, q, d, k):
        from sklearn.neighbors import NearestNeighbors

        db = rng.normal(size=(n, d)).astype(np.float32)
        queries = rng.normal(size=(q, d)).astype(np.float32)
        dist, idx = knn(None, db, queries, k=k, metric="euclidean",
                        tile=1024)
        ref = NearestNeighbors(n_neighbors=k).fit(db)
        rd, ri = ref.kneighbors(queries)
        # f32 near-ties can swap orders; compare achieved distances
        np.testing.assert_allclose(np.asarray(dist), rd, rtol=1e-3,
                                   atol=1e-3)
        assert (np.asarray(idx) == ri).mean() > 0.99

    def test_multi_tile_matches_single(self, rng):
        db = rng.normal(size=(5000, 12)).astype(np.float32)
        queries = rng.normal(size=(33, 12)).astype(np.float32)
        d1, i1 = knn(None, db, queries, k=7, tile=512)
        d2, i2 = knn(None, db, queries, k=7, tile=8192)
        np.testing.assert_allclose(np.asarray(d1), np.asarray(d2),
                                   rtol=1e-5)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))

    def test_cosine(self, rng):
        db = rng.normal(size=(400, 9)).astype(np.float32)
        queries = rng.normal(size=(15, 9)).astype(np.float32)
        dist, idx = knn(None, db, queries, k=6, metric="cosine", tile=128)
        dbn = db / np.linalg.norm(db, axis=1, keepdims=True)
        qn = queries / np.linalg.norm(queries, axis=1, keepdims=True)
        ref = 1.0 - qn @ dbn.T
        order = np.argsort(ref, axis=1)[:, :6]
        np.testing.assert_allclose(
            np.asarray(dist),
            np.take_along_axis(ref, order, axis=1), rtol=1e-3, atol=1e-4)
        assert (np.asarray(idx) == order).mean() > 0.98

    def test_inner_product_descending(self, rng):
        db = rng.normal(size=(200, 5)).astype(np.float32)
        queries = rng.normal(size=(9, 5)).astype(np.float32)
        sim, idx = knn(None, db, queries, k=4, metric="inner", tile=128)
        ref = queries @ db.T
        order = np.argsort(-ref, axis=1)[:, :4]
        np.testing.assert_array_equal(np.asarray(idx), order)
        np.testing.assert_allclose(
            np.asarray(sim), np.take_along_axis(ref, order, axis=1),
            rtol=1e-4, atol=1e-4)

    def test_validation(self, rng):
        db = rng.normal(size=(10, 3)).astype(np.float32)
        with pytest.raises(ValueError):
            knn(None, db, db[:, :2], k=2)
        with pytest.raises(ValueError):
            knn(None, db, db, k=11)
        with pytest.raises(ValueError):
            knn(None, db, db, k=2, metric="mahalanobis")
        # round 4: manhattan IS now in the vocabulary (unexpanded tile)
        d, _ = knn(None, db, db, k=1, metric="manhattan")
        np.testing.assert_allclose(np.asarray(d), 0.0, atol=1e-5)

    def test_mnmg_matches_single(self, rng, mesh8):
        """Row-sharded MNMG k-NN (uneven last shard) must reproduce the
        single-device result in global indices."""
        from raft_tpu.neighbors import knn_mnmg

        db = rng.normal(size=(1000, 10)).astype(np.float32)  # 1000 % 8 != 0
        queries = rng.normal(size=(21, 10)).astype(np.float32)
        d1, i1 = knn(None, db, queries, k=9, tile=256)
        d2, i2 = knn_mnmg(None, db, queries, k=9, tile=256, mesh=mesh8)
        np.testing.assert_allclose(np.asarray(d2), np.asarray(d1),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_array_equal(np.asarray(i2), np.asarray(i1))

    def test_mnmg_k_exceeds_shard_falls_back(self, rng, mesh8):
        from raft_tpu.neighbors import knn_mnmg

        db = rng.normal(size=(64, 4)).astype(np.float32)   # 8 rows/shard
        queries = rng.normal(size=(3, 4)).astype(np.float32)
        d, i = knn_mnmg(None, db, queries, k=20, mesh=mesh8)
        dref, iref = knn(None, db, queries, k=20)
        np.testing.assert_array_equal(np.asarray(i), np.asarray(iref))
        np.testing.assert_allclose(np.asarray(d), np.asarray(dref),
                                   rtol=1e-6)

    def test_k_exceeds_tile_width(self, rng):
        """k > requested tile: the tile must be raised to hold k (the
        per-tile top_k needs k <= tile)."""
        db = rng.normal(size=(600, 4)).astype(np.float32)
        queries = rng.normal(size=(5, 4)).astype(np.float32)
        d, i = knn(None, db, queries, k=300, tile=128)
        ref = ((queries[:, None, :] - db[None, :, :]) ** 2).sum(-1)
        np.testing.assert_allclose(
            np.asarray(d), np.sort(ref, 1)[:, :300], rtol=1e-3, atol=1e-3)

    def test_exact_recall_on_blobs(self, rng):
        """On separated blobs, each query's neighbors come from its own
        blob — an end-to-end recall check."""
        centers = rng.normal(size=(5, 6)).astype(np.float32) * 50
        db = np.concatenate([c + rng.normal(size=(50, 6)).astype(np.float32)
                             for c in centers])
        queries = centers + 0.1
        _, idx = knn(None, db, queries.astype(np.float32), k=10, tile=128)
        blob_of = np.asarray(idx) // 50
        assert (blob_of == np.arange(5)[:, None]).all()


class TestKnnAdversarial:
    """Edge cases for the streaming brute-force path (round-3 depth:
    k == n_db, single query/row, duplicate points — tie rule, bf16
    inputs, non-tile-multiple database sizes). Order comparisons follow
    the file convention: compare achieved DISTANCES, not exact index
    order (f32 near-ties swap across precision tiers/backends)."""

    def test_k_equals_db_size(self, rng):
        db = rng.normal(size=(37, 8)).astype(np.float32)
        q = rng.normal(size=(3, 8)).astype(np.float32)
        d, i = knn(None, db, q, k=37)
        ref = np.sort(((q[:, None].astype(np.float64)
                        - db[None].astype(np.float64)) ** 2).sum(-1), 1)
        # every db row present exactly once, distances sorted + correct
        assert all(sorted(r) == list(range(37))
                   for r in np.asarray(i).tolist())
        np.testing.assert_allclose(np.asarray(d), ref, rtol=1e-3,
                                   atol=1e-3)

    def test_single_query_single_db_row(self, rng):
        db = np.array([[1., 2., 3.]], np.float32)
        q = np.array([[1., 2., 3.]], np.float32)
        d, i = knn(None, db, q, k=1)
        assert np.asarray(i).tolist() == [[0]]
        assert float(np.asarray(d)[0, 0]) < 1e-5

    def test_duplicate_points_tie_to_lower_index(self, rng):
        row = rng.normal(size=(1, 16)).astype(np.float32)
        db = np.concatenate([row] * 5 + [row + 10.0], axis=0)
        d, i = knn(None, db, row, k=5)
        # five BIT-IDENTICAL distances -> ascending db indices (KVP rule)
        assert np.asarray(i).tolist() == [[0, 1, 2, 3, 4]]

    def test_bf16_database(self, rng):
        db = rng.normal(size=(256, 32)).astype(np.float32)
        q = db[:8] + 1e-3
        d32, i32 = knn(None, db, q, k=5)
        d16, i16 = knn(None, jnp.asarray(db, jnp.bfloat16), q, k=5)
        # bf16 storage: nearest-neighbor agreement stays high (the true
        # NN is ~4 orders of magnitude closer than the runner-up)
        agree = (np.asarray(i16)[:, 0] == np.asarray(i32)[:, 0]).mean()
        assert agree == 1.0

    def test_odd_db_size_vs_tile(self, rng):
        db = rng.normal(size=(1003, 8)).astype(np.float32)
        q = rng.normal(size=(9, 8)).astype(np.float32)
        d, i = knn(None, db, q, k=7, tile=256)    # 1003 = 3*256 + 235
        ref = np.sort(((q[:, None].astype(np.float64)
                        - db[None].astype(np.float64)) ** 2).sum(-1),
                      1)[:, :7]
        np.testing.assert_allclose(np.asarray(d), ref, rtol=1e-3,
                                   atol=1e-3)
        # indices address rows whose true distance matches the claimed one
        true_d = np.take_along_axis(
            ((q[:, None].astype(np.float64)
              - db[None].astype(np.float64)) ** 2).sum(-1),
            np.asarray(i), axis=1)
        np.testing.assert_allclose(true_d, ref, rtol=1e-3, atol=1e-3)


class TestChunkedRadixPath:
    """The chunked-radix kNN path (dispatched at long databases for
    16 < k <= radix_select.MAX_K — CPU suite shapes are below the
    dispatch gate, so these call the internals directly plus one
    through-the-gate case)."""

    def test_multi_chunk_matches_oracle(self):
        from raft_tpu.neighbors.brute_force import _knn_chunked

        rng = np.random.default_rng(21)
        db = rng.normal(size=(20000, 16)).astype(np.float32)
        q = rng.normal(size=(4, 16)).astype(np.float32)
        v, i = _knn_chunked(jnp.asarray(q), jnp.asarray(db), 20, 8192,
                            "l2")
        d2 = ((q[:, None].astype(np.float64)
               - db[None].astype(np.float64)) ** 2).sum(-1)
        order = np.argsort(d2, axis=1, kind="stable")[:, :20]
        np.testing.assert_array_equal(np.asarray(i), order)

    def test_chunked_agrees_with_scan_path(self):
        from raft_tpu.neighbors.brute_force import _knn_chunked, _knn_scan

        rng = np.random.default_rng(22)
        db = rng.normal(size=(9000, 8)).astype(np.float32)
        q = rng.normal(size=(6, 8)).astype(np.float32)
        cv, ci = _knn_chunked(jnp.asarray(q), jnp.asarray(db), 18, 4096,
                              "l2")
        sv, si = _knn_scan(jnp.asarray(q), jnp.asarray(db), 18, 4096,
                           "l2")
        np.testing.assert_array_equal(np.asarray(ci), np.asarray(si))
        np.testing.assert_allclose(np.asarray(cv), np.asarray(sv),
                                   rtol=1e-5, atol=1e-5)

    def test_dispatch_gate_end_to_end(self):
        # n and k inside the gate -> public knn runs the chunked path
        rng = np.random.default_rng(23)
        db = rng.normal(size=(16500, 8)).astype(np.float32)
        q = rng.normal(size=(3, 8)).astype(np.float32)
        d, i = knn(None, db, q, k=17)
        d2 = ((q[:, None].astype(np.float64)
               - db[None].astype(np.float64)) ** 2).sum(-1)
        order = np.argsort(d2, axis=1, kind="stable")[:, :17]
        np.testing.assert_array_equal(np.asarray(i), order)

    def test_duplicate_ties_keep_lowest_index(self):
        from raft_tpu.neighbors.brute_force import _knn_chunked

        row = np.ones((1, 8), np.float32)
        db = np.concatenate([np.tile(row, (30, 1)),
                             np.zeros((9000, 8), np.float32)], axis=0)
        q = np.ones((1, 8), np.float32)
        v, i = _knn_chunked(jnp.asarray(q), jnp.asarray(db), 20, 4096,
                            "l2")
        assert np.asarray(i)[0].tolist() == list(range(20))


class TestLargeKEpilogue:
    """Era-7 large-k epilogue: knn_plan is the single dispatch
    predicate, k > 256 chains the digit-histogram radix select, and the
    routed path is bit-identical to the scan reference."""

    def test_knn_plan_bands(self):
        from raft_tpu.neighbors.brute_force import knn_plan

        # small k on a clean metric -> fused insert path
        assert knn_plan(8, 20000, 64)[0] == "fused"
        assert knn_plan(8, 20000, 256)[0] == "fused"
        # above the insert capacity the radix epilogue takes over
        path, chunk = knn_plan(8, 20000, 257)
        assert path == "radix" and chunk > 0
        path, chunk = knn_plan(4, 16384, 512)
        assert path == "radix"
        # vma-blocked (interpreter replay) falls off the pallas paths
        assert knn_plan(8, 20000, 64, vma_blocked=True)[0] == "scan"
        # tiny databases have nothing to chunk
        assert knn_plan(8, 500, 300)[0] == "scan"

    def test_fused_topk_epilogue_band(self):
        from raft_tpu.neighbors import fused_topk

        assert fused_topk.epilogue(256) == "insert"
        assert fused_topk.epilogue(257) == "radix"
        assert fused_topk.epilogue(1) == "insert"

    def test_k512_dispatches_radix_and_matches_scan(self):
        from raft_tpu.core import trace
        from raft_tpu.neighbors.brute_force import _knn_scan

        rng = np.random.default_rng(24)
        db = rng.normal(size=(16384, 12)).astype(np.float32)
        q = rng.normal(size=(3, 12)).astype(np.float32)
        trace.clear_events()
        d, i = knn(None, db, q, k=512)
        evs = trace.events("knn.dispatch")
        assert evs and evs[-1]["path"] == "radix"
        assert evs[-1]["k"] == 512
        sv, si = _knn_scan(jnp.asarray(q), jnp.asarray(db), 512,
                           evs[-1]["chunk"], "l2")
        np.testing.assert_array_equal(np.asarray(i), np.asarray(si))
        np.testing.assert_array_equal(np.asarray(d), np.asarray(sv))

    def test_small_k_dispatch_event_says_fused(self):
        from raft_tpu.core import trace

        rng = np.random.default_rng(25)
        db = rng.normal(size=(700, 8)).astype(np.float32)
        q = rng.normal(size=(2, 8)).astype(np.float32)
        trace.clear_events()
        knn(None, db, q, k=5)
        assert trace.events("knn.dispatch")[-1]["path"] == "fused"


class TestUnexpandedMetricsKnn:
    @pytest.mark.parametrize("metric,sname", [
        ("l1", "cityblock"), ("chebyshev", "chebyshev"),
        ("canberra", "canberra")])
    def test_vs_scipy(self, metric, sname):
        from scipy.spatial.distance import cdist

        rng = np.random.default_rng(40)
        db = rng.normal(size=(400, 24)).astype(np.float32)
        q = rng.normal(size=(29, 24)).astype(np.float32)
        d, i = knn(None, db, q, 5, metric=metric)
        ref = cdist(q, db, sname)
        ri = np.argsort(ref, axis=1, kind="stable")[:, :5]
        np.testing.assert_allclose(
            np.asarray(d), np.take_along_axis(ref, ri, 1),
            rtol=2e-4, atol=2e-4)
        np.testing.assert_array_equal(np.asarray(i), ri)


class TestFusedTopK:
    """The fused distance+top-k kernel (neighbors/fused_topk.py) — the
    k <= 256 kNN hot path. Oracle: numpy stable argsort."""

    def _oracle(self, q, db, k):
        d = ((q[:, None, :].astype(np.float64)
              - db[None, :, :].astype(np.float64)) ** 2).sum(-1)
        oi = np.argsort(d, axis=1, kind="stable")[:, :k]
        return np.take_along_axis(d, oi, 1), oi

    @pytest.mark.parametrize("tier", ["default", "high", "highest"])
    def test_vs_oracle_all_tiers(self, tier):
        import raft_tpu
        from raft_tpu.neighbors.fused_topk import knn_fused

        rng = np.random.default_rng(7)
        q = rng.normal(size=(43, 21)).astype(np.float32)
        db = rng.normal(size=(2333, 21)).astype(np.float32)
        old = raft_tpu.get_matmul_precision()
        try:
            raft_tpu.set_matmul_precision(tier)
            v, i = knn_fused(jnp.asarray(q), jnp.asarray(db), 11, tn=512)
        finally:
            raft_tpu.set_matmul_precision(old)
        ov, oi = self._oracle(q, db, 11)
        np.testing.assert_array_equal(np.asarray(i), oi)
        np.testing.assert_allclose(np.asarray(v), ov, rtol=2e-3,
                                   atol=2e-3)

    def test_adversarial_descending_quality(self):
        """Rows sorted so every later tile IMPROVES the bound — the
        bound gate never skips and every tile merges; correctness must
        not depend on the gate firing."""
        from raft_tpu.neighbors.fused_topk import knn_fused

        rng = np.random.default_rng(8)
        q = np.zeros((9, 6), np.float32)
        db = rng.normal(size=(1500, 6)).astype(np.float32)
        norms = (db ** 2).sum(1)
        db = db[np.argsort(-norms)]         # best candidates LAST
        v, i = knn_fused(jnp.asarray(q), jnp.asarray(db), 13, tn=256)
        ov, oi = self._oracle(q, db, 13)
        np.testing.assert_array_equal(np.asarray(i), oi)

    def test_ties_smallest_global_index(self):
        from raft_tpu.neighbors.fused_topk import knn_fused

        q = np.ones((3, 8), np.float32)
        base = np.arange(40, dtype=np.float32).reshape(5, 8)
        db = np.tile(base, (60, 1))          # 300 rows, 60 exact copies
        v, i = knn_fused(jnp.asarray(q), jnp.asarray(db), 7, tn=128)
        d = ((q[:1, None, :] - db[None, :, :]) ** 2).sum(-1)[0]
        oi = np.argsort(d, kind="stable")[:7]
        np.testing.assert_array_equal(np.asarray(i)[0], oi)

    def test_k_equals_max_and_short_db(self):
        from raft_tpu.neighbors.fused_topk import MAX_K, knn_fused

        rng = np.random.default_rng(9)
        # integer grid data: expanded-form f32 distances are exact, so
        # index equality is well-defined even at rank depth ~ n
        q = rng.integers(-5, 6, size=(5, 12)).astype(np.float32)
        db = rng.integers(-5, 6, size=(MAX_K + 144, 12)).astype(np.float32)
        v, i = knn_fused(jnp.asarray(q), jnp.asarray(db), MAX_K)
        ov, oi = self._oracle(q, db, MAX_K)
        np.testing.assert_array_equal(np.asarray(i), oi)

    @pytest.mark.parametrize("k", [129, 256])
    def test_two_vreg_best_k_beyond_128(self, k):
        """k in (128, 256] widens the sorted best to two vregs; integer
        data makes the expanded-form f32 distances exact, so the index
        compare is valid through near-rank ties."""
        from raft_tpu.neighbors.fused_topk import knn_fused

        rng = np.random.default_rng(15)
        q = rng.integers(-6, 7, size=(9, 16)).astype(np.float32)
        db = rng.integers(-6, 7, size=(1100, 16)).astype(np.float32)
        d = ((q[:, None, :] - db[None, :, :]) ** 2).sum(-1)
        oi = np.argsort(d, axis=1, kind="stable")[:, :k]
        for sw in (0, 128):
            v, i = knn_fused(jnp.asarray(q), jnp.asarray(db), k, tn=512,
                             sw=sw)
            np.testing.assert_array_equal(np.asarray(i), oi)

    def test_dispatch_prefers_fused(self):
        """knn() routes k <= 256 through the fused kernel; results match
        the chunked/scan paths it replaced."""
        from raft_tpu.neighbors.brute_force import _knn_scan

        rng = np.random.default_rng(10)
        q = rng.normal(size=(17, 16)).astype(np.float32)
        db = rng.normal(size=(900, 16)).astype(np.float32)
        v, i = knn(None, db, q, 6)
        sv, si = _knn_scan(jnp.asarray(q), jnp.asarray(db), 6, 512, "l2")
        np.testing.assert_array_equal(np.asarray(i), np.asarray(si))
        np.testing.assert_allclose(np.asarray(v), np.asarray(sv),
                                   rtol=1e-5, atol=1e-6)

    def test_ragged_db_and_single_query(self):
        """n not a multiple of tn (padding masked by n_valid) and q=1
        (row padding sliced off)."""
        from raft_tpu.neighbors.fused_topk import knn_fused

        rng = np.random.default_rng(11)
        q = rng.normal(size=(1, 7)).astype(np.float32)
        db = rng.normal(size=(1337, 7)).astype(np.float32)
        v, i = knn_fused(jnp.asarray(q), jnp.asarray(db), 21, tn=512)
        ov, oi = self._oracle(q, db, 21)
        np.testing.assert_array_equal(np.asarray(i), oi)

    @pytest.mark.parametrize("sw", [128, 256])
    def test_strip_drain_matches_whole_tile(self, sw):
        """sw splits the drain into static strips (matmul width and
        drain width decoupled); results must be bit-identical to the
        whole-tile drain, including the global tie contract and on the
        adversarial best-candidates-last ordering."""
        from raft_tpu.neighbors.fused_topk import knn_fused

        rng = np.random.default_rng(13)
        q = rng.normal(size=(11, 9)).astype(np.float32)
        db = rng.normal(size=(1100, 9)).astype(np.float32)
        norms = (db ** 2).sum(1)
        db = db[np.argsort(-norms)]          # best candidates LAST
        v0, i0 = knn_fused(jnp.asarray(q), jnp.asarray(db), 9, tn=512)
        v1, i1 = knn_fused(jnp.asarray(q), jnp.asarray(db), 9, tn=512,
                           sw=sw)
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        np.testing.assert_array_equal(np.asarray(v0), np.asarray(v1))
        ov, oi = self._oracle(q, db, 9)
        np.testing.assert_array_equal(np.asarray(i1), oi)

    def test_strip_width_validation_and_clamp(self):
        """Malformed sw raises; an sw made indivisible only by the
        small-db tn clamp degrades to the whole-tile drain (perf knob,
        not a correctness contract)."""
        from raft_tpu.neighbors.fused_topk import knn_fused

        rng = np.random.default_rng(16)
        q = jnp.asarray(rng.normal(size=(5, 8)).astype(np.float32))
        db = jnp.asarray(rng.normal(size=(300, 8)).astype(np.float32))
        for bad in (-128, 100):
            with pytest.raises(ValueError):
                knn_fused(q, db, 5, sw=bad)
        # tn clamps to 384 here; sw=256 no longer divides it -> falls
        # back to sw=0 and must still be correct
        v, i = knn_fused(q, db, 5, tn=1024, sw=256)
        v0, i0 = knn_fused(q, db, 5, tn=1024)
        np.testing.assert_array_equal(np.asarray(i), np.asarray(i0))

    def test_strip_drain_tie_contract(self):
        from raft_tpu.neighbors.fused_topk import knn_fused

        q = np.ones((3, 8), np.float32)
        base = np.arange(40, dtype=np.float32).reshape(5, 8)
        db = np.tile(base, (60, 1))          # 300 rows, 60 exact copies
        v, i = knn_fused(jnp.asarray(q), jnp.asarray(db), 7, tn=256,
                         sw=128)
        d = ((q[:1, None, :] - db[None, :, :]) ** 2).sum(-1)[0]
        oi = np.argsort(d, kind="stable")[:7]
        np.testing.assert_array_equal(np.asarray(i)[0], oi)

    @pytest.mark.parametrize("tier", ["default", "high"])
    def test_minonly_probe_both_dispatch_paths(self, tier):
        """The tune-only 1-NN floor probe must stay oracle-correct on
        both the plain and pre-split operand pipelines (it exists to
        price the SAME distance path the fused kernel runs)."""
        import raft_tpu
        from raft_tpu.neighbors.fused_topk import _minonly_probe

        rng = np.random.default_rng(14)
        q = rng.normal(size=(21, 10)).astype(np.float32)
        db = rng.normal(size=(900, 10)).astype(np.float32)
        old = raft_tpu.get_matmul_precision()
        try:
            raft_tpu.set_matmul_precision(tier)
            v, i = _minonly_probe(jnp.asarray(q), jnp.asarray(db),
                                  tm=128, tn=256)
        finally:
            raft_tpu.set_matmul_precision(old)
        _, oi = self._oracle(q, db, 1)
        np.testing.assert_array_equal(np.asarray(i), oi[:, 0])

    def test_metrics_through_dispatch(self):
        """cosine and inner ride the fused path with the right ordering
        (inner: largest first via the negated kernel metric)."""
        rng = np.random.default_rng(12)
        q = rng.normal(size=(9, 15)).astype(np.float32)
        db = rng.normal(size=(700, 15)).astype(np.float32)
        for metric in ("cosine", "inner"):
            d, i = knn(None, db, q, 5, metric=metric)
            if metric == "cosine":
                qn = q / np.linalg.norm(q, axis=1, keepdims=True)
                dn = db / np.linalg.norm(db, axis=1, keepdims=True)
                ref = 1.0 - qn @ dn.T
                oi = np.argsort(ref, axis=1, kind="stable")[:, :5]
            else:
                ref = q.astype(np.float64) @ db.T.astype(np.float64)
                oi = np.argsort(-ref, axis=1, kind="stable")[:, :5]
            np.testing.assert_array_equal(np.asarray(i), oi)
