"""Worker + orchestrator for the leader-failover chaos witness
(ISSUE 20 acceptance: SIGKILL the leader of a real-TCP 3-node fleet
mid-stream in quorum-ack mode; the survivors elect the most-caught-up
follower, writes resume through the promoted controller, every
client-acked seq is present on the new leader with ``content_crc``
bit-equal to a never-killed twin's replay of the same durable prefix,
and the stale old leader rejoins, truncates its unreplicated suffix
behind a typed :class:`TermFencedError`, and converges bit-equal).

Roles (``python tests/_failover_worker.py <role> ...``):

``leader --dir D --addrs A0 A1 A2``
    Rank 0: builds the journaled index, runs an
    :class:`~raft_tpu.neighbors.election.ElectionNode` as leader with
    ``acks="majority"``, waits for both followers' READY, then streams
    the deterministic op sequence — each op blocks until
    quorum-acked and prints ``ACKED seq=<s>`` — and SIGKILLs itself
    after ``KILL_AT_ACKS`` acked ops (``LEADER_SUICIDE wall=<t>``).

``follower --dir D --addrs A0 A1 A2 --rank R``
    Ranks 1 and 2: bootstrap over the wire (snapshot resync), then run
    a full serving stack — :class:`StreamingKnnService` +
    :class:`IngestController` wired to an election node — so the
    promotion's zero-recompile contract is witnessed on a live
    executor. After the leader dies, exactly one follower prints
    ``PROMOTED rank=<r> ... ballot_applied=<a> crc=<c>
    traces_pre=<n> traces_post=<n>`` (crc is captured before any
    resumed write, so it is the durable-prefix CRC the clean twin must
    match); the other prints ``REDIRECT leader=<r>`` (the typed
    NotLeaderError redirect) and ``LOSER_OK crc=<c>``. The winner then
    resumes quorum-acked writes, shepherds the stale leader's rejoin
    (HELLO/GO handshake), and prints ``WINNER_FINAL crc=<c>``.

``rejoin --dir D --addrs A0 A1 A2``
    Rank 0 restarted: recovers the killed leader's journal, appends a
    deliberately unreplicated term-0 suffix (the partitioned-leader
    writes), waits for the new leader's GO (a term-0 heartbeat
    mid-election would read as the old leader returning), then starts
    a stale election node that still believes it leads — and gets
    fenced, truncates, demotes, and heals. Prints ``REJOIN_OK
    fenced=TermFencedError divergence=<s> truncated=<n> crc=<c>``.

``clean --dir D --records N``
    The never-killed twin: replays the first N ops of the identical
    deterministic sequence in-process and prints ``CLEAN_OK crc=<c>``.

``orchestrate``
    Runs the whole dance in subprocesses and asserts: leader rc is
    −9; election lands inside 2x the transport heartbeat timeout; the
    winner carried the max ballot (most-caught-up); every acked seq
    is within the winner's ballot prefix (zero acked-write loss); the
    promotion CRC equals the clean twin's replay (bit-equal durable
    prefix); zero post-promotion retraces; the loser's redirect names
    the winner; final CRCs converge three ways; and the rejoiner's
    divergence equals ``ballot_applied + 1`` with a non-empty
    truncation. Prints ``FAILOVER_CHAOS_OK ...`` — ci/smoke.sh gates
    on it.
"""

import argparse
import os
import signal
import socket
import subprocess
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

N_DB, DIM, N_LISTS = 160, 8, 8
B_ROWS = 6
KILL_AT_ACKS = 12       # acked ops before the leader SIGKILLs itself
RESUME_OPS = 3          # post-promotion quorum-acked writes
K, NPROBE = 5, 4
HB_INTERVAL, HB_TIMEOUT = 0.3, 2.0      # transport failure detector
ELECTION_TIMEOUT = 1.0                  # app-level silence threshold
TAG_READY, TAG_DONE = 7400, 7401
TAG_FINAL, TAG_HELLO, TAG_GO = 7402, 7403, 7404


def _op_stream():
    """The deterministic op sequence both twins run. Each op is
    exactly ONE WAL record, so a replay of the first N ops reproduces
    the content of any N-record durable prefix bit-for-bit."""
    import itertools

    import numpy as np

    rng = np.random.default_rng(7)
    next_id = N_DB
    for n in itertools.count():
        if n % 4 == 3:
            prev = list(range(next_id - B_ROWS, next_id))
            yield n, ("delete", np.asarray(prev[::3], np.int64))
        else:
            yield n, ("insert",
                      rng.normal(size=(B_ROWS, DIM)).astype(np.float32))
            next_id += B_ROWS


def _apply_op(idx, n, op):
    kind, payload = op
    if kind == "insert":
        idx.insert(payload, write_id=n)
    else:
        idx.delete(payload)


def _build(directory):
    import numpy as np

    from raft_tpu.neighbors import streaming

    rng = np.random.default_rng(7)
    db = rng.normal(size=(N_DB, DIM)).astype(np.float32)
    idx = streaming.stream_build(None, db, N_LISTS, seed=0, max_iter=4,
                                 directory=directory, repack_slack=64)
    # provision tail slack up front: the whole op stream then fits
    # without a shape-changing repack, so the promotion's snapshot
    # roll is content-only and the zero-recompile witness is strict
    idx.compact(reason="provision")
    return idx


def _node_kw():
    return dict(acks="majority", ack_timeout=30.0,
                heartbeat_interval=0.25,
                election_timeout=ELECTION_TIMEOUT, poll_interval=0.02)


def run_clean(directory, records):
    idx = _build(directory)
    for n, op in _op_stream():
        if n >= records:
            break
        _apply_op(idx, n, op)
    print(f"CLEAN_OK crc={idx.content_crc()} applied={idx.applied_seq}",
          flush=True)


def run_leader(directory, addrs):
    import numpy as np

    from raft_tpu.comms.tcp_mailbox import TcpMailbox
    from raft_tpu.neighbors.election import ElectionNode

    box = TcpMailbox(0, addrs, heartbeat_interval=HB_INTERVAL,
                     heartbeat_timeout=HB_TIMEOUT)
    idx = _build(directory)
    node = ElectionNode(idx, box, 0, [0, 1, 2], role="leader", leader=0,
                        **_node_kw())
    node.start()
    for r in (1, 2):
        np.asarray(box.get(r, 0, TAG_READY, timeout=240.0))
    for n, op in _op_stream():
        if n >= KILL_AT_ACKS:
            break
        _apply_op(idx, n, op)       # blocks until quorum-acked
        print(f"ACKED seq={idx.applied_seq} op={n}", flush=True)
        time.sleep(0.02)
    print(f"LEADER_SUICIDE wall={time.time():.6f} seq={idx.applied_seq}",
          flush=True)
    sys.stdout.flush()
    os.kill(os.getpid(), signal.SIGKILL)


def run_follower(directory, addrs, rank):
    import numpy as np

    from raft_tpu import serve
    from raft_tpu.comms.errors import CommsTimeoutError, PeerFailedError
    from raft_tpu.comms.tcp_mailbox import TcpMailbox
    from raft_tpu.neighbors.election import ElectionNode
    from raft_tpu.neighbors.wal_ship import WalFollower, bootstrap_follower
    from raft_tpu.serve.ingest import NotLeaderError

    box = TcpMailbox(rank, addrs, heartbeat_interval=HB_INTERVAL,
                     heartbeat_timeout=HB_TIMEOUT)
    idx = bootstrap_follower(None, dim=DIM, n_lists=N_LISTS,
                             directory=directory)
    wf = WalFollower(idx, box, rank, 0)
    wf.catch_up(timeout=120.0)      # snapshot resync: the base build
    svc = serve.StreamingKnnService(idx, k=K, nprobe=NPROBE)
    node = ElectionNode(idx, box, rank, [0, 1, 2], role="follower",
                        leader=0, follower=wf, **_node_kw())
    ctl = serve.IngestController(
        idx, [svc], policy=serve.BatchPolicy(max_batch=8, max_wait_ms=2.0),
        compact_interval=30.0, refit=False, warm_buckets=[4],
        election=node)
    ctl.start()
    q = np.random.default_rng(40 + rank).normal(
        size=(4, DIM)).astype(np.float32)
    ctl.submit(svc.name, q).result(timeout=120.0)   # flush first-touch
    box.put(rank, 0, TAG_READY, np.asarray([rank], np.int64))

    deadline = time.monotonic() + 240.0
    while True:
        assert time.monotonic() < deadline, (node.role, node._error)
        if node._error is not None:
            raise node._error
        if node.role == "leader" and node.last_election is not None:
            won = True
            break
        # role flips before last_election lands on the winner: only a
        # settled FOLLOWER pointing away from rank 0 is the loser
        if node.role == "follower" and node.leader != 0:
            won = False
            break
        time.sleep(0.02)

    if won:
        # last_election is stored after _promote returns, so the
        # promotion hook (and any off-path rewarm it paid) is done:
        # from here on the serving path must be compile-free
        rec = node.last_election
        t_pre = ctl.executor.stats.traces
        ctl.submit(svc.name, q).result(timeout=120.0)
        t_post = ctl.executor.stats.traces
        ballot_applied = rec.votes[rec.winner][1]
        votes_s = ";".join(f"{r}:{a}" for r, (_t, a)
                           in sorted(rec.votes.items()))
        print(f"PROMOTED rank={rank} wall={time.time():.6f} "
              f"term={idx.term} ballot_applied={ballot_applied} "
              f"applied={idx.applied_seq} crc={idx.content_crc()} "
              f"votes={votes_s} seconds={rec.seconds:.3f} "
              f"traces_pre={t_pre} traces_post={t_post}", flush=True)
        rng2 = np.random.default_rng(100)
        loser = 3 - rank
        try:
            for j in range(RESUME_OPS):     # quorum-acked by the loser
                ctl.insert(rng2.normal(size=(4, DIM)).astype(np.float32),
                           write_id=1000 + j)
        except Exception:
            sh = node.shipper
            print(f"WINNER_STUCK followers={sh.followers} "
                  f"shipped={sh.shipped} acked={sh.acked_seq(loser)} "
                  f"applied={idx.applied_seq}", flush=True)
            raise
        final_applied = idx.applied_seq
        box.put(rank, loser, TAG_FINAL,
                np.asarray([final_applied], np.int64))
        # shepherd the stale leader's rejoin: wait for its HELLO, then
        # GO (carrying the convergence target) once our writes are in
        while True:
            assert time.monotonic() < deadline, "no HELLO from rank 0"
            box.revive_peer(0)
            if box.get_nowait(0, rank, TAG_HELLO) is not None:
                break
            time.sleep(0.1)
        box.put(rank, 0, TAG_GO, np.asarray([final_applied], np.int64))
        while True:
            try:
                np.asarray(box.get(0, rank, TAG_DONE, timeout=5.0))
                break
            except (PeerFailedError, CommsTimeoutError):
                assert time.monotonic() < deadline, "no DONE from rank 0"
                box.revive_peer(0)
        box.put(rank, loser, TAG_DONE, np.asarray([1], np.int64))
        print(f"WINNER_FINAL crc={idx.content_crc()} "
              f"applied={idx.applied_seq}", flush=True)
        time.sleep(0.2)             # let the shutdown frame flush
    else:
        # the typed redirect: a write on a follower names the leader
        # and invites an idempotent same-write_id replay there
        try:
            ctl.insert(np.zeros((2, DIM), np.float32), write_id=9999)
            print("REDIRECT_FAIL no NotLeaderError", flush=True)
        except NotLeaderError as exc:
            print(f"REDIRECT leader={exc.leader}", flush=True)
        winner = node.leader
        fin = None
        last_report = time.monotonic()
        while fin is None:
            assert time.monotonic() < deadline, "no FINAL from winner"
            fin = box.get_nowait(winner, rank, TAG_FINAL)
            if time.monotonic() - last_report > 5.0:
                last_report = time.monotonic()
                print(f"LOSER_STATE applied={idx.applied_seq} "
                      f"term={idx.term} leader={node.leader} "
                      f"role={node.role} err={node._error!r}",
                      flush=True)
            time.sleep(0.02)
        target = int(np.asarray(fin)[0])
        while idx.applied_seq < target:
            assert time.monotonic() < deadline, \
                (idx.applied_seq, target, node._error)
            time.sleep(0.02)
        time.sleep(0.5)             # let the last apply's swap settle
        print(f"LOSER_OK rank={rank} crc={idx.content_crc()} "
              f"applied={idx.applied_seq}", flush=True)
        # stay up through the stale leader's rejoin — an early exit
        # would leave its HELLO puts blocking on a dead-peer reconnect
        np.asarray(box.get(winner, rank, TAG_DONE, timeout=180.0))
    ctl.stop()
    box.close()


def run_rejoin(directory, addrs):
    import numpy as np

    from raft_tpu.comms.tcp_mailbox import TcpMailbox
    from raft_tpu.neighbors.election import ElectionNode
    from raft_tpu.neighbors.streaming import StreamingIndex

    box = TcpMailbox(0, addrs, heartbeat_interval=HB_INTERVAL,
                     heartbeat_timeout=HB_TIMEOUT)
    idx = StreamingIndex.recover(None, directory)
    resumed = idx.applied_seq
    # the partitioned-leader writes: a term-0 suffix the fleet never saw
    rng = np.random.default_rng(55)
    idx.insert(rng.normal(size=(5, DIM)).astype(np.float32))
    stale_applied = idx.applied_seq
    print(f"REJOIN_RECOVERED resumed={resumed} "
          f"stale_applied={stale_applied} term={idx.term}", flush=True)
    hello = np.asarray([0], np.int64)
    winner = target = None
    deadline = time.monotonic() + 240.0
    while target is None:
        assert time.monotonic() < deadline, "no GO from the new leader"
        for p in (1, 2):
            try:
                box.put(0, p, TAG_HELLO, hello)
            except Exception:       # noqa: BLE001 — peer may be gone
                pass
            got = box.get_nowait(p, 0, TAG_GO)
            if got is not None:
                winner, target = p, int(np.asarray(got)[0])
                break
        time.sleep(0.1)
    # start a node that still believes it leads at term 0: the fleet
    # fences it, it truncates the suffix, demotes, and heals
    node = ElectionNode(idx, box, 0, [0, 1, 2], role="leader", leader=0,
                        **_node_kw())
    node.start()
    while not (node.role == "follower" and node.last_fence is not None):
        assert time.monotonic() < deadline, (node.role, node._error)
        if node._error is not None:
            raise node._error
        time.sleep(0.02)
    while idx.applied_seq < target:
        assert time.monotonic() < deadline, \
            (idx.applied_seq, target, node._error)
        time.sleep(0.02)
    time.sleep(0.5)
    fence = node.last_fence
    truncated = stale_applied - fence.divergence + 1
    print(f"REJOIN_OK fenced={type(fence).__name__} "
          f"divergence={fence.divergence} truncated={truncated} "
          f"term={idx.term} crc={idx.content_crc()} "
          f"applied={idx.applied_seq}", flush=True)
    box.put(0, winner, TAG_DONE, np.asarray([1], np.int64))
    time.sleep(0.2)                 # let the DONE frame flush
    node.stop()
    box.close()


# -- orchestrator ------------------------------------------------------


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _field(out, marker, key):
    import re

    m = re.search(rf"{marker}\b.*\b{key}=([\w.:;+-]+)", out)
    assert m, f"missing {marker} {key}= in:\n{out}"
    return m.group(1)


def orchestrate():
    import re
    import tempfile

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    me = os.path.abspath(__file__)

    def launch(args):
        return subprocess.Popen([sys.executable, me] + args, cwd=_REPO,
                                env=env, stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True)

    with tempfile.TemporaryDirectory() as tmp:
        d_lead = os.path.join(tmp, "leader")
        d_f1 = os.path.join(tmp, "f1")
        d_f2 = os.path.join(tmp, "f2")
        d_clean = os.path.join(tmp, "clean")
        addrs = [f"127.0.0.1:{p}" for p in _free_ports(3)]
        leader = launch(["leader", "--dir", d_lead, "--addrs"] + addrs)
        f1 = launch(["follower", "--dir", d_f1, "--rank", "1",
                     "--addrs"] + addrs)
        f2 = launch(["follower", "--dir", d_f2, "--rank", "2",
                     "--addrs"] + addrs)
        out0 = leader.communicate(timeout=300)[0]
        assert leader.returncode == -9, \
            f"leader was not SIGKILLed (rc={leader.returncode}):\n{out0}"
        assert "LEADER_SUICIDE" in out0, out0
        # the restarted leader binds the dead process's port; it holds
        # off its stale node until the winner's GO, so launching now
        # (mid-election) is safe
        rejoin = launch(["rejoin", "--dir", d_lead, "--addrs"] + addrs)
        out_r = rejoin.communicate(timeout=300)[0]
        assert rejoin.returncode == 0, f"rejoin failed:\n{out_r}"
        out1 = f1.communicate(timeout=300)[0]
        assert f1.returncode == 0, f"follower 1 failed:\n{out1}"
        out2 = f2.communicate(timeout=300)[0]
        assert f2.returncode == 0, f"follower 2 failed:\n{out2}"

        w_out, l_out = (out1, out2) if "PROMOTED" in out1 else (out2, out1)
        assert "PROMOTED" in w_out and "PROMOTED" not in l_out, \
            f"expected exactly one promotion:\n{out1}\n{out2}"
        ballot_applied = int(_field(w_out, "PROMOTED", "ballot_applied"))
        clean = launch(["clean", "--dir", d_clean, "--records",
                        str(ballot_applied + 1)])
        out_c = clean.communicate(timeout=300)[0]
        assert clean.returncode == 0, f"clean twin failed:\n{out_c}"

    # zero acked-write loss: every seq the client saw acked is inside
    # the winner's ballot prefix (quorum intersection: some survivor
    # acked it, and the election picked the max-applied survivor)
    acked = [int(s) for s in re.findall(r"ACKED seq=(\d+)", out0)]
    assert len(acked) == KILL_AT_ACKS and max(acked) <= ballot_applied, \
        f"acked={acked} ballot_applied={ballot_applied}\n{out0}\n{w_out}"
    # the election landed inside 2x the transport heartbeat timeout
    elected_in = (float(_field(w_out, "PROMOTED", "wall"))
                  - float(_field(out0, "LEADER_SUICIDE", "wall")))
    assert elected_in < 2 * HB_TIMEOUT, \
        f"election took {elected_in:.2f}s >= {2 * HB_TIMEOUT}s\n{w_out}"
    # most-caught-up follower won
    winner = int(_field(w_out, "PROMOTED", "rank"))
    votes = dict(pair.split(":") for pair
                 in _field(w_out, "PROMOTED", "votes").split(";"))
    assert int(votes[str(winner)]) == max(int(a) for a in votes.values())
    assert int(_field(w_out, "PROMOTED", "term")) == 1, w_out
    # durable prefix bit-equal to the never-killed twin's replay
    crc_prom = _field(w_out, "PROMOTED", "crc")
    crc_clean = _field(out_c, "CLEAN_OK", "crc")
    assert crc_prom == crc_clean, \
        f"promoted prefix diverged from clean twin: {crc_prom} != " \
        f"{crc_clean}"
    # zero post-promotion retraces on the serving path
    t_pre = int(_field(w_out, "PROMOTED", "traces_pre"))
    t_post = int(_field(w_out, "PROMOTED", "traces_post"))
    assert t_post == t_pre, f"post-promotion retrace: {t_pre}->{t_post}"
    # the loser's typed redirect names the winner
    assert int(_field(l_out, "REDIRECT", "leader")) == winner, l_out
    # final three-way convergence
    crc_final = _field(w_out, "WINNER_FINAL", "crc")
    assert _field(l_out, "LOSER_OK", "crc") == crc_final, \
        f"loser diverged\n{l_out}\n{w_out}"
    assert _field(out_r, "REJOIN_OK", "crc") == crc_final, \
        f"rejoined leader diverged\n{out_r}\n{w_out}"
    # the stale leader truncated a non-empty suffix at exactly the
    # fence's divergence point (the winner's term boundary)
    assert _field(out_r, "REJOIN_OK", "fenced") == "TermFencedError"
    divergence = int(_field(out_r, "REJOIN_OK", "divergence"))
    assert divergence == ballot_applied + 1, out_r
    truncated = int(_field(out_r, "REJOIN_OK", "truncated"))
    assert truncated >= 1, out_r
    assert int(_field(out_r, "REJOIN_OK", "term")) == 1, out_r
    print(f"FAILOVER_CHAOS_OK winner={winner} elected_in={elected_in:.2f} "
          f"acked={len(acked)} ballot_applied={ballot_applied} "
          f"truncated={truncated} crc={crc_final}", flush=True)


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("role", choices=["leader", "follower", "rejoin",
                                    "clean", "orchestrate"])
    p.add_argument("--dir")
    p.add_argument("--addrs", nargs="*", default=[])
    p.add_argument("--rank", type=int, default=None)
    p.add_argument("--records", type=int, default=None)
    a = p.parse_args(argv)
    if a.role == "orchestrate":
        orchestrate()
    elif a.role == "clean":
        run_clean(a.dir, a.records)
    elif a.role == "leader":
        run_leader(a.dir, a.addrs)
    elif a.role == "rejoin":
        run_rejoin(a.dir, a.addrs)
    else:
        assert a.rank in (1, 2)
        run_follower(a.dir, a.addrs, a.rank)
    return 0


if __name__ == "__main__":
    sys.exit(main())
