"""Cross-platform Mosaic lowering tier: every Pallas kernel, every
precision tier, lowered FOR TPU on a machine with no TPU.

`jax.export(platforms=("tpu",))` runs the full Pallas→Mosaic module
generation at lowering time — the phase that rejects unsupported kernel
constructs (e.g. Precision.HIGH on dots, int64 reduce indices). The
hardware smoke tier (tpu_tests/) still owns Mosaic-compile and numerics
on a real chip; this tier catches the lowering class of regression in
every CPU test run, which matters because the chip tunnel can be
unreachable for hours at a time.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import raft_tpu

def _mosaic_tier_available() -> bool:
    """Probe the actual capability, not the API surface. ``jax.export``
    is a lazy submodule — ``hasattr(jax, "export")`` flips with import
    order elsewhere in the suite — and builds that HAVE it may still
    lack Mosaic lowerings for the tier's baseline constructs (this
    container's build rejects integer reductions with
    NotImplementedError). Lower one minimal Pallas kernel containing an
    integer reduce for TPU; any failure means the whole tier would only
    report the build gap, not regressions."""
    try:
        from jax import export as jax_export
        from jax.experimental import pallas as pl
    except ImportError:
        return False

    def kern(x_ref, o_ref):
        m = jnp.min(x_ref[...], axis=1, keepdims=True)
        o_ref[...] = jnp.broadcast_to(m, o_ref.shape)

    def fn():
        return pl.pallas_call(
            kern,
            out_shape=jax.ShapeDtypeStruct((8, 128), jnp.int32),
        )(jnp.zeros((8, 128), jnp.int32))

    try:
        jax_export.export(jax.jit(fn), platforms=("tpu",))()
        return True
    except Exception:
        return False


if not _mosaic_tier_available():
    pytest.skip("this jax build cannot run the Mosaic lowering tier "
                "(jax.export missing, or the Pallas→Mosaic TPU "
                "lowering lacks the tier's baseline constructs) — "
                "hardware smoke in tpu_tests/ still covers these "
                "kernels",
                allow_module_level=True)

pytestmark = pytest.mark.filterwarnings("ignore")


@pytest.fixture(autouse=True)
def _compiled_pallas(monkeypatch):
    # force the compiled (non-interpret) kernel path during lowering
    monkeypatch.setenv("RAFT_TPU_PALLAS_INTERPRET", "0")
    from raft_tpu.util import pallas_utils

    pallas_utils.use_interpret.cache_clear()
    yield
    pallas_utils.use_interpret.cache_clear()


@pytest.fixture
def xy():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(512, 64)), jnp.float32)
    y = jnp.asarray(rng.normal(size=(96, 64)), jnp.float32)
    return x, y


def _lowers_with_mosaic(fn):
    exp = jax.export.export(jax.jit(fn), platforms=("tpu",))()
    assert "tpu_custom_call" in exp.mlir_module(), \
        "kernel fell back to plain XLA during TPU lowering"


@pytest.mark.parametrize("tier", ["default", "high", "highest"])
def test_knn_scan_lowers_for_tpu(tier, xy):
    """Pallas kernel inside lax.scan (the knn database streaming loop).

    tile=64 pins the SCAN path: at tile >= 128 knn dispatches to the
    fused top-k kernel, whose 32 gated merge regions overflow
    jax.export's recursive jaxpr walk (RecursionError in
    util.weakrefs_to_sentinel — a serialization-path limit, not a
    Mosaic one). The fused kernel's TPU lowering is proven the stronger
    way: ci/aot_preflight.py knn_bench compiles it against the real
    libtpu toolchain at the 1M-row bench shape."""
    from raft_tpu.neighbors import knn

    x, y = xy
    old = raft_tpu.get_matmul_precision()
    try:
        raft_tpu.set_matmul_precision(tier)
        _lowers_with_mosaic(lambda: knn(None, x, y, k=5, tile=64)[0])
    finally:
        raft_tpu.set_matmul_precision(old)
        jax.config.update("jax_default_matmul_precision", None)


@pytest.mark.parametrize("tier", ["default", "high", "highest"])
@pytest.mark.parametrize("kernel", ["pairwise", "argmin", "lloyd",
                                    "argmin_tiled"])
def test_kernels_lower_for_tpu(tier, kernel, xy):
    from raft_tpu.linalg.contractions import (fused_l2_argmin_pallas,
                                              fused_lloyd_pallas,
                                              pairwise_l2_pallas)

    x, y = xy
    old = raft_tpu.get_matmul_precision()
    try:
        raft_tpu.set_matmul_precision(tier)
        if kernel == "pairwise":
            _lowers_with_mosaic(lambda: pairwise_l2_pallas(x, y))
        elif kernel == "argmin":
            _lowers_with_mosaic(lambda: fused_l2_argmin_pallas(x, y))
        elif kernel == "lloyd":
            _lowers_with_mosaic(lambda: fused_lloyd_pallas(x, y))
        else:
            # wide Y forces the 2-axis running-min kernel
            rng = np.random.default_rng(2)
            ywide = jnp.asarray(rng.normal(size=(20000, 24)), jnp.float32)
            xs = jnp.asarray(rng.normal(size=(64, 24)), jnp.float32)
            _lowers_with_mosaic(lambda: fused_l2_argmin_pallas(xs, ywide))
    finally:
        raft_tpu.set_matmul_precision(old)
        jax.config.update("jax_default_matmul_precision", None)


def test_packed_split_lowers_for_tpu(xy):
    """The depth-packed bf16x3 Lloyd variant concatenates operands along
    the contraction dim INSIDE the kernel — that concat must have a
    Mosaic lowering (the whole point of this tier: no chip needed to
    catch it)."""
    import functools

    from raft_tpu.linalg.contractions import fused_lloyd_pallas

    x, y = xy
    old = raft_tpu.get_matmul_precision()
    try:
        raft_tpu.set_matmul_precision("high")
        _lowers_with_mosaic(functools.partial(fused_lloyd_pallas, x, y,
                                              packed=True))
    finally:
        raft_tpu.set_matmul_precision(old)
        jax.config.update("jax_default_matmul_precision", None)


@pytest.mark.parametrize("kcase", [(9000, 64), (1000, 7), (600, 5),
                                   (32768, 16384)])
def test_radix_select_lowers_for_tpu(kcase):
    """Both radix-select kernels: the digit-histogram threshold (grid-
    axis passes, factorized 16x16 one-hot MXU histogram in scratch,
    triangular cumsum narrowing) and the triangular-matmul cumsum +
    factorized one-hot contraction with scratch carry (emission).

    This tier runs under jax_enable_x64 (conftest), which is exactly the
    configuration where referencing a fori_loop index inside a
    pallas_call body recurses in jax.export lowering — the threshold
    kernel drives its passes from a grid axis (pl.program_id) instead
    of a fori index, and that avoidance is pinned here."""
    from raft_tpu.matrix.radix_select import radix_select_k

    n_cols, k = kcase
    rng = np.random.default_rng(n_cols)
    v = jnp.asarray(rng.normal(size=(16, n_cols)), jnp.float32)
    _lowers_with_mosaic(lambda: radix_select_k(v, k))


def test_knn_chunked_radix_lowers_for_tpu():
    """The chunked-radix kNN path: radix-select kernels inside lax.scan
    behind the distance kernel (the dispatch regime the CPU suite's
    small shapes never reach)."""
    from raft_tpu.neighbors.brute_force import _knn_chunked

    rng = np.random.default_rng(5)
    db = jnp.asarray(rng.normal(size=(20000, 16)), jnp.float32)
    q = jnp.asarray(rng.normal(size=(64, 16)), jnp.float32)
    _lowers_with_mosaic(lambda: _knn_chunked(q, db, 20, 8192, "l2")[0])


@pytest.mark.parametrize("metric", ["l1", "linf", "canberra", "lp",
                                    "hamming", "l2un"])
def test_unexpanded_pairwise_lowers_for_tpu(metric, xy):
    """The VPU reduction tile for unexpanded metrics: 3-D broadcast +
    axis-1 reduction per k-chunk with output accumulation over the k grid
    dimension (max-accumulate for linf)."""
    from raft_tpu.linalg.contractions import pairwise_unexpanded_pallas

    x, y = xy
    _lowers_with_mosaic(
        lambda: pairwise_unexpanded_pallas(x, y, metric, p=3.0))


def test_grid_spmv_lowers_for_tpu():
    """All three slot-grid SpMV kernels: the same-shape dynamic gather
    (tpu.dynamic_gather via take_along_axis), the segmented-scan tile
    reduction with its (8,128)<->(1,1024) relayouts and flat emission
    gather, and the scalar-prefetch window reduction with 8 accumulating
    output planes."""
    import scipy.sparse as sp

    from raft_tpu.core.sparse_types import CSRMatrix
    from raft_tpu.sparse.grid_spmv import prepare, spmv

    rng = np.random.default_rng(6)
    dense = rng.normal(size=(512, 700)).astype(np.float32)
    dense[rng.uniform(size=dense.shape) > 0.03] = 0.0
    fmt = prepare(CSRMatrix.from_scipy(sp.csr_matrix(dense)), shard_w=256)
    assert fmt.n_shards == 3
    x = jnp.asarray(rng.normal(size=700), jnp.float32)
    exp = jax.export.export(jax.jit(lambda: spmv(fmt, x)),
                            platforms=("tpu",))()
    mod = exp.mlir_module()
    assert mod.count("tpu_custom_call") >= 3, \
        "expected all three grid-SpMV kernels to lower via Mosaic"


def test_mst_grid_lowers_for_tpu():
    """The Borůvka E-stage kernels (sparse/solver/mst_grid.py): the i32
    replicated-shard gather, the segmented lexicographic (w, rank, eid)
    min-scan with the own-window color gather, and the 24-plane KVP
    window accumulation."""
    import scipy.sparse as sp

    from raft_tpu.core.sparse_types import CSRMatrix
    from raft_tpu.sparse.solver.mst_grid import (per_vertex_min_edge,
                                                 prepare_mst)

    rng = np.random.default_rng(8)
    dense = np.abs(rng.normal(size=(512, 512))).astype(np.float32)
    dense[rng.uniform(size=dense.shape) > 0.03] = 0.0
    adj = sp.csr_matrix(np.minimum(dense, dense.T))
    adj.eliminate_zeros()
    mp = prepare_mst(CSRMatrix.from_scipy(adj))
    colors = jnp.arange(512, dtype=jnp.int32)
    exp = jax.export.export(jax.jit(
        lambda: per_vertex_min_edge(mp, colors)), platforms=("tpu",))()
    mod = exp.mlir_module()
    assert mod.count("tpu_custom_call") >= 3, \
        "expected all three MST E-stage kernels to lower via Mosaic"


def test_spmm_kt_lowers_for_tpu():
    """The k-batched SpMM kernels (grid_spmv.py KT group): the KT-column
    chunk gather, the (ntile, KT)-grid scan reading the 5-D chunk view,
    and the (nwp, KT, 128) plane accumulation."""
    import scipy.sparse as sp

    from raft_tpu.core.sparse_types import CSRMatrix
    from raft_tpu.sparse.grid_spmv import prepare, spmm

    rng = np.random.default_rng(9)
    dense = rng.normal(size=(512, 700)).astype(np.float32)
    dense[rng.uniform(size=dense.shape) > 0.03] = 0.0
    fmt = prepare(CSRMatrix.from_scipy(sp.csr_matrix(dense)), shard_w=256)
    b = jnp.asarray(rng.normal(size=(700, 12)), jnp.float32)
    exp = jax.export.export(jax.jit(lambda: spmm(fmt, b)),
                            platforms=("tpu",))()
    mod = exp.mlir_module()
    assert mod.count("tpu_custom_call") >= 3, \
        "expected all three k-batched SpMM kernels to lower via Mosaic"
