"""Victim process for the peer-death chaos test (test_comms_faults.py).

Binds a TcpMailbox at the given rank, announces readiness to rank 0
(which also attributes its TCP stream to this rank via the HELLO/DATA
frames), then blocks until killed — modelling a peer dying mid-exchange.

Usage: python _fault_worker.py <rank> <addr0> <addr1> ...
"""

import sys
import time


def main():
    rank = int(sys.argv[1])
    addrs = sys.argv[2:]

    import numpy as np

    from raft_tpu.comms.tcp_mailbox import TcpMailbox

    box = TcpMailbox(rank, addrs)
    box.put(rank, 0, 0, np.int32(rank))     # ready signal
    print(f"FAULT_WORKER_READY {rank}", flush=True)
    time.sleep(300)                          # hold the link until killed


if __name__ == "__main__":
    main()
