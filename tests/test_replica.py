"""Replica-group serving tests (ISSUE 11 tentpole, serve half):
weighted-fair routing, rejection spill, health-gated membership, the
comms-wired heal cycle, and the fleet load generator's recovery clock.
"""

import threading
import time

import numpy as np
import pytest

from raft_tpu.neighbors import ivf_flat
from raft_tpu.neighbors.ivf_mnmg import build_mnmg, shrink_mnmg
from raft_tpu.runtime import limits
from raft_tpu.serve import (BatchPolicy, Executor, IvfMnmgKnnService,
                            QosPolicy, ReplicaGroup, TenantPolicy,
                            fleet_closed_loop)


@pytest.fixture(scope="module")
def small_index(res):
    rng = np.random.default_rng(2)
    X = rng.standard_normal((512, 12)).astype(np.float32)
    flat = ivf_flat.build(res, X, 8, seed=0, max_iter=4)
    return X, flat, build_mnmg(res, X, 8, 2, flat=flat)


def _make_ex(idx, *, slo_s=None, max_queue=1024):
    qos = None
    if slo_s is not None:
        qos = QosPolicy({"default": TenantPolicy(slo_latency_s=slo_s)})
    ex = Executor([IvfMnmgKnnService(idx, k=4, nprobe=3)],
                  policy=BatchPolicy(max_batch=32, max_wait_ms=1.0,
                                     max_queue=max_queue),
                  qos=qos)
    ex.warm([8, 32])
    return ex


def _op(idx):
    return f"ivf_mnmg_k4_np3_r{idx.n_ranks}_{idx.metric}"


class TestRouting:
    def test_weighted_fair_spread(self, small_index):
        X, _, idx = small_index
        group = ReplicaGroup([_make_ex(idx) for _ in range(3)],
                             weights=[2.0, 1.0, 1.0])
        q = X[:4]
        with group:
            futs = [group.route(_op(idx), q)[1] for _ in range(20)]
            for f in futs:
                f.result(timeout=60.0)
        routed = [r.routed for r in group.replicas]
        assert sum(routed) == 20
        # weight 2 replica gets ~2x the requests of each weight 1
        assert routed[0] == 10 and routed[1] == routed[2] == 5

    def test_route_reports_serving_replica(self, small_index):
        X, _, idx = small_index
        group = ReplicaGroup([_make_ex(idx), _make_ex(idx)])
        with group:
            rep, fut = group.route(_op(idx), X[:4])
            fut.result(timeout=60.0)
        assert rep.name in {r.name for r in group.replicas}
        assert group.stats.routed == 1

    def test_spill_on_queue_full(self, small_index):
        X, _, idx = small_index
        # one-slot queues, no drain threads running: the first two
        # submits fill both replicas; the third sees the preferred
        # replica's queue_full rejection, spills to the other, and only
        # when BOTH refuse does the typed rejection reach the caller
        a = _make_ex(idx, max_queue=1)
        b = _make_ex(idx, max_queue=1)
        group = ReplicaGroup([a, b])
        op = _op(idx)
        group.submit(op, X[:4])             # fills one queue
        group.submit(op, X[:4])             # router prefers the idle one
        assert group.stats.spills == 0
        assert [r.routed for r in group.replicas] == [1, 1]
        with pytest.raises(limits.RejectedError) as ei:
            group.submit(op, X[:4])
        assert ei.value.reason == "queue_full"
        assert group.stats.spills == 2      # both replicas were tried
        assert group.stats.rejected == 1
        a.start()
        b.start()
        group.stop()

    def test_no_healthy_replica_raises_typed(self, small_index):
        X, _, idx = small_index
        group = ReplicaGroup([_make_ex(idx)])
        group.mark_failed(0, "down")
        with pytest.raises(limits.RejectedError) as ei:
            group.submit(_op(idx), X[:4])
        assert ei.value.reason == "no_replica"
        assert group.stats.rejected == 1


class TestMembership:
    def test_mark_failed_routes_around(self, small_index):
        X, _, idx = small_index
        group = ReplicaGroup([_make_ex(idx), _make_ex(idx)])
        with group:
            group.mark_failed("replica0", "test")
            for _ in range(5):
                rep, fut = group.route(_op(idx), X[:4])
                assert rep.name == "replica1"
                fut.result(timeout=60.0)
        assert group.stats.failures == 1
        assert group.replicas[0].failed_reason == "test"

    def test_fail_replica_fails_pending_typed(self, small_index):
        X, _, idx = small_index
        group = ReplicaGroup([_make_ex(idx)])
        fut = group.submit(_op(idx), X[:4])   # queued, no drain thread
        group.fail_replica(0, "killed")
        with pytest.raises(limits.RejectedError) as ei:
            fut.result(timeout=5.0)
        assert ei.value.reason == "replica_failed"
        # and new submits find no healthy replica
        with pytest.raises(limits.RejectedError):
            group.submit(_op(idx), X[:4])


class TestRejoin:
    def test_rejoin_vtime_snaps_to_fleet_floor(self, small_index):
        """ISSUE 16 satellite: a replica rejoining far behind in
        virtual time gets its FAIR share immediately — not the
        catch-up flood a stale clock would attract."""
        X, _, idx = small_index
        group = ReplicaGroup([_make_ex(idx), _make_ex(idx)])
        op = _op(idx)
        with group:
            for _ in range(10):
                group.route(op, X[:4])[1].result(timeout=60.0)
            group.mark_failed(0, "down")
            for _ in range(30):         # replica1's clock runs ahead
                group.route(op, X[:4])[1].result(timeout=60.0)
            assert group.replicas[0].routed == 5
            before = group.replicas[0].routed
            group.rejoin(0)
            assert group.replicas[0].healthy
            assert group.replicas[0].failed_reason is None
            for _ in range(20):
                group.route(op, X[:4])[1].result(timeout=60.0)
        post0 = group.replicas[0].routed - before
        assert 8 <= post0 <= 12, (
            f"rejoined replica took {post0}/20 — expected ~fair share, "
            f"not a catch-up flood")

    def test_rejoin_under_submit_storm_loses_no_future(self, small_index):
        """8 submitter threads race a mark_failed/rejoin flapper: every
        accepted future resolves (served, or typed rejection) — none
        hang, none are lost."""
        X, _, idx = small_index
        group = ReplicaGroup([_make_ex(idx) for _ in range(2)])
        op = _op(idx)
        stop = threading.Event()
        accepted, rejected = [], []
        acc_lock = threading.Lock()

        def flapper():
            while not stop.is_set():
                group.mark_failed(0, "flap")
                time.sleep(0.0005)
                group.rejoin(0)
                time.sleep(0.0005)

        def submitter():
            for _ in range(25):
                try:
                    fut = group.submit(op, X[:4])
                except limits.RejectedError:
                    with acc_lock:
                        rejected.append(1)
                    continue
                with acc_lock:
                    accepted.append(fut)

        with group:
            flap = threading.Thread(target=flapper)
            subs = [threading.Thread(target=submitter)
                    for _ in range(8)]
            flap.start()
            for s in subs:
                s.start()
            for s in subs:
                s.join()
            stop.set()
            flap.join()
            if not group.replicas[0].healthy:
                group.rejoin(0)         # leave the fleet whole
            for fut in accepted:
                fut.result(timeout=60.0)
        assert len(accepted) + len(rejected) == 200
        assert len(accepted) > 0


class TestHeal:
    def test_heal_healthy_clique_is_noop(self, small_index):
        from raft_tpu.comms.comms import MeshComms, _Mailbox

        import jax
        from jax.sharding import Mesh

        _, _, idx = small_index
        mesh = Mesh(np.asarray(jax.devices()[:2]), ("data",))
        comms = MeshComms(mesh, "data", 0, _mailbox=_Mailbox())
        group = ReplicaGroup([_make_ex(idx), _make_ex(idx)],
                             comms=comms)
        assert group.heal(timeout=2.0) is None
        assert group.stats.recoveries == 0

    def test_heal_requires_comms(self, small_index):
        _, _, idx = small_index
        group = ReplicaGroup([_make_ex(idx)])
        with pytest.raises(ValueError, match="comms"):
            group.heal()

    def test_heal_shrinks_and_repacks(self, res, small_index):
        """The in-process chaos cycle: rank 2 fault-disconnects, heal()
        detects the typed failure, reaches survivor consensus, shrinks
        the clique, and the on_shrink repack equals a fresh build on
        the survivor count — survivors answer afterwards."""
        from raft_tpu.comms.comms import MeshComms, _Mailbox
        from raft_tpu.comms.faults import FaultInjector

        import jax
        from jax.sharding import Mesh

        X, flat, _ = small_index
        idx3 = build_mnmg(res, X, 8, 3, flat=flat)
        mesh = Mesh(np.asarray(jax.devices()[:3]), ("data",))
        inj = FaultInjector(seed=0, disconnect=1.0, source_ranks={2})
        comms = MeshComms(mesh, "data", 0, _mailbox=_Mailbox(faults=inj))

        repacked = {}

        def on_shrink(new_comms, survivors):
            idx_s = shrink_mnmg(idx3, survivors)
            repacked["idx"] = idx_s
            return [_make_ex(idx_s) for _ in survivors]

        group = ReplicaGroup([_make_ex(idx3) for _ in range(3)],
                             comms=comms, on_shrink=on_shrink)
        group.start()
        report = group.heal(timeout=5.0)
        assert report is not None
        assert report.dead == (2,)
        assert report.survivors == (0, 1)
        assert report.repacked
        assert report.recovery_s > 0
        assert group.comms.get_size() == 2
        assert len(group.healthy()) == 2
        assert group.stats.recoveries == 1

        fresh = build_mnmg(res, X, 8, 2, flat=flat)
        idx_s = repacked["idx"]
        for a, b in ((idx_s.packed_db_sh, fresh.packed_db_sh),
                     (idx_s.packed_ids_sh, fresh.packed_ids_sh),
                     (idx_s.starts_sh, fresh.starts_sh),
                     (idx_s.sizes_sh, fresh.sizes_sh)):
            assert np.array_equal(np.asarray(a), np.asarray(b))

        # survivors keep serving (on the repacked 2-rank op)
        fut = group.submit(_op(idx_s), X[:4])
        d, i = fut.result(timeout=60.0)
        from raft_tpu.neighbors.ivf_mnmg import search_mnmg

        ed, ei = search_mnmg(res, idx_s, X[:4], k=4, nprobe=3)
        assert np.array_equal(np.asarray(d), np.asarray(ed))
        assert np.array_equal(np.asarray(i), np.asarray(ei))
        group.stop()


class TestFleetLoadgen:
    def test_per_replica_rows_and_merged(self, small_index):
        X, _, idx = small_index
        group = ReplicaGroup([_make_ex(idx, slo_s=5.0)
                              for _ in range(2)])
        with group:
            rep = fleet_closed_loop(group, _op(idx), clients=3, rows=4,
                                    duration_s=0.5)
        d = rep.as_dict()
        assert set(d["replicas"]) == {"replica0", "replica1"}
        fleet_completed = d["fleet"]["completed"]
        assert fleet_completed > 0
        assert sum(r["completed"] for r in d["replicas"].values()) \
            == fleet_completed
        assert d["fleet"]["p99_ms"] >= d["fleet"]["p50_ms"]
        assert "killed" not in d
        assert rep.recovery_time_to_slo_s is None

    def test_kill_mid_run_reports_recovery(self, small_index):
        X, _, idx = small_index
        group = ReplicaGroup([_make_ex(idx, slo_s=5.0)
                              for _ in range(3)])
        with group:
            rep = fleet_closed_loop(group, _op(idx), clients=4, rows=4,
                                    duration_s=1.0, kill_after_s=0.3)
        assert rep.killed is not None
        assert rep.kill_at_s == pytest.approx(0.3, abs=0.4)
        # survivors kept answering within the (generous) SLO
        assert rep.recovery_time_to_slo_s is not None
        assert rep.recovery_time_to_slo_s < 1.0
        d = rep.as_dict()
        assert d["recovery_time_to_slo_s"] == pytest.approx(
            rep.recovery_time_to_slo_s, abs=1e-3)
        # the killed replica served strictly less than the survivors
        killed_row = d["replicas"][rep.killed]
        others = [r for n, r in d["replicas"].items() if n != rep.killed]
        assert all(killed_row["completed"] < o["completed"]
                   for o in others)
