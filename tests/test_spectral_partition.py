"""Spectral clustering driver tests (rebuilt-from-primitives pipeline:
laplacian -> Lanczos -> k-means -> analyzers; the reference's fixture for
this layer is the karate-club graph, tests/linalg/eigen_solvers.cu:50-67)."""

import numpy as np
import pytest
import scipy.sparse as sp

from raft_tpu.core.sparse_types import CSRMatrix
from raft_tpu.spectral import (analyze_modularity, analyze_partition,
                               modularity_maximization, partition)

# Zachary's karate club (standard 34-node edge list, 0-based).
_KARATE_EDGES = [
    (0,1),(0,2),(0,3),(0,4),(0,5),(0,6),(0,7),(0,8),(0,10),(0,11),(0,12),
    (0,13),(0,17),(0,19),(0,21),(0,31),(1,2),(1,3),(1,7),(1,13),(1,17),
    (1,19),(1,21),(1,30),(2,3),(2,7),(2,8),(2,9),(2,13),(2,27),(2,28),
    (2,32),(3,7),(3,12),(3,13),(4,6),(4,10),(5,6),(5,10),(5,16),(6,16),
    (8,30),(8,32),(8,33),(9,33),(13,33),(14,32),(14,33),(15,32),(15,33),
    (18,32),(18,33),(19,33),(20,32),(20,33),(22,32),(22,33),(23,25),
    (23,27),(23,29),(23,32),(23,33),(24,25),(24,27),(24,31),(25,31),
    (26,29),(26,33),(27,33),(28,31),(28,33),(29,32),(29,33),(30,32),
    (30,33),(31,32),(31,33),(32,33),
]
# Ground truth: the two factions (Mr. Hi vs Officer)
_FACTION = np.array([0,0,0,0,0,0,0,0,1,1,0,0,0,0,1,1,0,0,1,0,1,0,1,1,1,1,
                     1,1,1,1,1,1,1,1])


def _karate_csr():
    src, dst = zip(*_KARATE_EDGES)
    src, dst = np.asarray(src), np.asarray(dst)
    w = np.ones(len(src), np.float32)
    a = sp.coo_matrix((w, (src, dst)), shape=(34, 34))
    return CSRMatrix.from_scipy((a + a.T).tocsr())


from tests.conftest import ring_of_cliques as _ring_of_cliques  # shared fixture


class TestSpectralDrivers:
    def test_partition_ring_of_cliques(self):
        csr = _ring_of_cliques()
        labels, vals, vecs = partition(None, csr, n_clusters=4, seed=1)
        labels = np.asarray(labels)
        # every clique uniformly labeled, 4 distinct labels
        blocks = labels.reshape(4, 8)
        assert all(len(set(b.tolist())) == 1 for b in blocks)
        assert len(set(labels.tolist())) == 4
        # analyzer: cut cost of this partition is tiny (4 bridge edges)
        cut = float(np.asarray(
            analyze_partition(None, csr, 4, labels)[0]))
        assert cut <= 8.0 + 1e-3            # 4 bridges × 2 (symmetrized)

    def test_partition_karate_two_way(self):
        csr = _karate_csr()
        labels, _, _ = partition(None, csr, n_clusters=2, seed=3)
        labels = np.asarray(labels)
        agree = (labels == _FACTION).mean()
        agree = max(agree, 1 - agree)       # label permutation
        assert agree >= 0.85, agree         # classic result: ~1-2 errors

    def test_modularity_maximization_karate(self):
        csr = _karate_csr()
        labels, vals, _ = modularity_maximization(None, csr, n_clusters=2,
                                                  seed=5)
        labels = np.asarray(labels)
        q = float(np.asarray(analyze_modularity(None, csr, 2, labels)))
        assert q > 0.3, q                   # known 2-way modularity ≈ 0.37
        agree = (labels == _FACTION).mean()
        assert max(agree, 1 - agree) >= 0.8


class TestMNMGPartition:
    def test_mesh_pipeline_finds_planted_cut(self, mesh8):
        from raft_tpu.spectral import analyze_partition, partition

        rng = np.random.default_rng(3)
        n, half = 400, 200
        dense = np.zeros((n, n), np.float32)
        for blk in (slice(0, half), slice(half, n)):
            w = (rng.uniform(size=(half, half)) < 0.08).astype(np.float32)
            dense[blk, blk] = np.triu(w, 1)
        for _ in range(6):
            dense[rng.integers(0, half), rng.integers(half, n)] = 1.0
        dense = dense + dense.T
        csr = CSRMatrix.from_scipy(sp.csr_matrix(dense))
        labels, vals, vecs = partition(None, csr, n_clusters=2,
                                       n_eig_vects=2, mesh=mesh8)
        edge_cut, _ = analyze_partition(None, csr, 2, labels)
        assert int(edge_cut) <= 24
        l1, _, _ = partition(None, csr, n_clusters=2, n_eig_vects=2)
        a = (np.asarray(l1) == np.asarray(labels)).mean()
        assert max(a, 1 - a) > 0.97    # identical up to label swap
