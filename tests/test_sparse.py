"""Sparse layer tests vs scipy references (mirrors the reference's SPARSE_TEST
suite, cpp/tests/CMakeLists.txt:249-286 — convert, linalg, ops, matrix,
solvers)."""

import numpy as np
import pytest
import scipy.sparse as sp
import scipy.sparse.csgraph as csgraph
import scipy.sparse.linalg as spla

from raft_tpu.core.bitset import Bitmap, Bitset
from raft_tpu.core.sparse_types import COOMatrix, CSRMatrix
from raft_tpu.sparse import convert, linalg, matrix, op
from raft_tpu.sparse.solver import GraphCOO, eigsh, mst


def _rand_csr(rng, m, n, density=0.2, dtype=np.float32):
    mat = sp.random(m, n, density=density, random_state=rng,
                    dtype=np.float64).astype(dtype)
    mat.sum_duplicates()
    return mat.tocsr()


class TestConvert:
    def test_csr_coo_roundtrip(self):
        rng = np.random.RandomState(0)
        ref = _rand_csr(rng, 23, 17)
        csr = CSRMatrix.from_scipy(ref)
        coo = convert.csr_to_coo(csr)
        back = convert.sorted_coo_to_csr(coo)
        assert (back.to_scipy() != ref).nnz == 0

    def test_csr_to_dense(self):
        rng = np.random.RandomState(1)
        ref = _rand_csr(rng, 9, 13)
        dense = convert.csr_to_dense(CSRMatrix.from_scipy(ref))
        np.testing.assert_allclose(np.asarray(dense), ref.toarray(),
                                   rtol=1e-6)

    def test_dense_to_csr(self):
        rng = np.random.RandomState(2)
        d = rng.randn(8, 11) * (rng.rand(8, 11) > 0.6)
        csr = convert.dense_to_csr(d.astype(np.float32))
        np.testing.assert_allclose(csr.to_scipy().toarray(),
                                   d.astype(np.float32), rtol=1e-6)

    def test_adj_to_csr(self):
        rng = np.random.RandomState(3)
        adj = rng.rand(7, 7) > 0.5
        csr = convert.adj_to_csr(adj)
        np.testing.assert_array_equal(
            csr.to_scipy().toarray() != 0, adj)

    def test_bitmap_bitset_to_csr(self):
        rng = np.random.RandomState(4)
        m = rng.rand(5, 40) > 0.5
        csr = convert.bitmap_to_csr(Bitmap.from_bool_matrix(m))
        np.testing.assert_array_equal(csr.to_scipy().toarray() != 0, m)

        row = rng.rand(40) > 0.5
        csr2 = convert.bitset_to_csr(Bitset.from_bools(row), n_rows=3)
        np.testing.assert_array_equal(
            csr2.to_scipy().toarray() != 0, np.tile(row, (3, 1)))


class TestOps:
    def test_coo_sort_and_dedup(self):
        rows = np.array([2, 0, 1, 0, 2], dtype=np.int32)
        cols = np.array([1, 3, 0, 3, 1], dtype=np.int32)
        data = np.array([5., 1., 2., 4., 7.], dtype=np.float32)
        coo = COOMatrix(rows, cols, data, (3, 4))
        merged = op.sum_duplicates(coo)
        ref = sp.coo_matrix((data, (rows, cols)), shape=(3, 4)).tocsr()
        got = convert.sorted_coo_to_csr(merged).to_scipy()
        assert (got != ref).nnz == 0
        maxed = op.max_duplicates(coo)
        got_max = convert.sorted_coo_to_csr(maxed).to_scipy().toarray()
        assert got_max[0, 3] == 4.0 and got_max[2, 1] == 7.0

    def test_remove_scalar(self):
        coo = COOMatrix(np.array([0, 1]), np.array([1, 0]),
                        np.array([0.0, 3.0], dtype=np.float32), (2, 2))
        out = op.coo_remove_zeros(coo)
        assert out.nnz == 1 and float(out.data[0]) == 3.0

    def test_row_slice(self):
        rng = np.random.RandomState(5)
        ref = _rand_csr(rng, 12, 9)
        sliced = op.csr_row_slice(CSRMatrix.from_scipy(ref), 3, 8)
        assert (sliced.to_scipy() != ref[3:8]).nnz == 0


class TestLinalg:
    def test_spmv(self):
        rng = np.random.RandomState(6)
        ref = _rand_csr(rng, 33, 21)
        x = rng.randn(21).astype(np.float32)
        y = linalg.spmv(CSRMatrix.from_scipy(ref), x)
        np.testing.assert_allclose(np.asarray(y), ref @ x, rtol=1e-4,
                                   atol=1e-5)

    def test_spmm(self):
        rng = np.random.RandomState(7)
        ref = _rand_csr(rng, 19, 15)
        b = rng.randn(15, 6).astype(np.float32)
        c = linalg.spmm(CSRMatrix.from_scipy(ref), b)
        np.testing.assert_allclose(np.asarray(c), ref @ b, rtol=1e-4,
                                   atol=1e-5)

    def test_sddmm(self):
        rng = np.random.RandomState(8)
        a = rng.randn(10, 5).astype(np.float32)
        b = rng.randn(5, 12).astype(np.float32)
        pat = _rand_csr(rng, 10, 12, density=0.3)
        out = linalg.sddmm(a, b, CSRMatrix.from_scipy(pat),
                           alpha=2.0, beta=0.5)
        dense = 2.0 * (a @ b) * (pat.toarray() != 0) \
            + 0.5 * pat.toarray()
        got = out.to_scipy().toarray()
        mask = pat.toarray() != 0
        np.testing.assert_allclose(got[mask], dense[mask], rtol=1e-4,
                                   atol=1e-5)

    def test_masked_matmul_bitmap(self):
        rng = np.random.RandomState(9)
        a = rng.randn(6, 4).astype(np.float32)
        b = rng.randn(8, 4).astype(np.float32)
        mask = rng.rand(6, 8) > 0.4
        out = linalg.masked_matmul(a, b, Bitmap.from_bool_matrix(mask))
        ref = (a @ b.T) * mask
        np.testing.assert_allclose(out.to_scipy().toarray(), ref,
                                   rtol=1e-4, atol=1e-5)

    def test_masked_matmul_bitset(self):
        rng = np.random.RandomState(10)
        a = rng.randn(5, 3).astype(np.float32)
        b = rng.randn(7, 3).astype(np.float32)
        row = rng.rand(7) > 0.3
        out = linalg.masked_matmul(a, b, Bitset.from_bools(row))
        ref = (a @ b.T) * np.tile(row, (5, 1))
        np.testing.assert_allclose(out.to_scipy().toarray(), ref,
                                   rtol=1e-4, atol=1e-5)

    def test_csr_add(self):
        rng = np.random.RandomState(11)
        a = _rand_csr(rng, 9, 9)
        b = _rand_csr(rng, 9, 9)
        out = linalg.csr_add(CSRMatrix.from_scipy(a),
                             CSRMatrix.from_scipy(b))
        np.testing.assert_allclose(out.to_scipy().toarray(),
                                   (a + b).toarray(), rtol=1e-5,
                                   atol=1e-6)

    def test_transpose(self):
        rng = np.random.RandomState(12)
        a = _rand_csr(rng, 7, 13)
        out = linalg.transpose(CSRMatrix.from_scipy(a))
        assert (out.to_scipy() != a.T.tocsr()).nnz == 0

    def test_row_normalize(self):
        rng = np.random.RandomState(13)
        a = _rand_csr(rng, 11, 8, density=0.5)
        a.data = np.abs(a.data)
        out = linalg.csr_row_normalize_l1(CSRMatrix.from_scipy(a))
        sums = np.asarray(out.to_scipy().sum(axis=1)).ravel()
        nz = np.diff(a.indptr) > 0
        np.testing.assert_allclose(sums[nz], 1.0, rtol=1e-5)

    def test_laplacian(self):
        rng = np.random.RandomState(14)
        adj = _rand_csr(rng, 16, 16, density=0.2)
        adj = adj + adj.T   # symmetric, no self loops guaranteed removed
        adj.setdiag(0)
        adj.eliminate_zeros()
        lap = linalg.laplacian(CSRMatrix.from_scipy(adj))
        ref = csgraph.laplacian(adj.astype(np.float64))
        np.testing.assert_allclose(lap.to_scipy().toarray(),
                                   ref.toarray(), rtol=1e-4, atol=1e-5)

    def test_laplacian_normalized(self):
        rng = np.random.RandomState(15)
        adj = _rand_csr(rng, 12, 12, density=0.3)
        adj = adj + adj.T
        adj.setdiag(0)
        adj.eliminate_zeros()
        adj.data = np.abs(adj.data)
        lap = linalg.laplacian_normalized(CSRMatrix.from_scipy(adj))
        ref = csgraph.laplacian(adj.astype(np.float64), normed=True)
        np.testing.assert_allclose(lap.to_scipy().toarray(),
                                   ref.toarray(), rtol=1e-4, atol=1e-4)

    def test_symmetrize(self):
        rng = np.random.RandomState(16)
        a = _rand_csr(rng, 10, 10)
        coo = convert.csr_to_coo(CSRMatrix.from_scipy(a))
        out = linalg.coo_symmetrize(coo)
        ref = (a + a.T).toarray()
        got = convert.sorted_coo_to_csr(op.coo_sort(out)) \
            .to_scipy().toarray()
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)

    def test_degree(self):
        rng = np.random.RandomState(17)
        a = _rand_csr(rng, 9, 9)
        coo = convert.csr_to_coo(CSRMatrix.from_scipy(a))
        deg = linalg.coo_degree(coo)
        np.testing.assert_array_equal(np.asarray(deg),
                                      np.diff(a.indptr))


class TestMatrix:
    def test_select_k_csr(self, res):
        rng = np.random.RandomState(18)
        ref = _rand_csr(rng, 14, 30, density=0.4)
        vals, idx = matrix.select_k(res, CSRMatrix.from_scipy(ref), k=3,
                                    select_min=True)
        dense = ref.toarray()
        dense[dense == 0] = np.inf
        order = np.argsort(dense, axis=1)[:, :3]
        expect = np.take_along_axis(dense, order, axis=1)
        got = np.asarray(vals)
        finite = np.isfinite(expect)
        np.testing.assert_allclose(got[finite], expect[finite],
                                   rtol=1e-5)
        gi = np.asarray(idx)
        np.testing.assert_array_equal(gi[finite], order[finite])

    def test_select_k_csr_radix_band_bit_exact(self, res):
        """A CSR whose max row length lands in radix_select.preferred's
        short-row band: the CSR path must return bit-identically what
        dense select_k returns over the same materialized rows, both
        under AUTO and with the radix enum passed through ``algo``."""
        import jax.numpy as jnp
        from raft_tpu.matrix import radix_select
        from raft_tpu.matrix.select_k import SelectAlgo
        from raft_tpu.matrix.select_k import select_k as dense_select_k

        rng = np.random.RandomState(27)
        n_rows, n_cols = 16, 12000
        ref = _rand_csr(rng, n_rows, n_cols, density=0.8)
        max_len = int(np.diff(ref.indptr).max())
        assert radix_select.preferred(max_len, 32), \
            "fixture must land in the radix dispatch band"
        dense = ref.toarray().astype(np.float32)
        dense[dense == 0] = np.inf        # pad sentinel, sorts last
        dense = np.sort(dense, axis=1)[:, :max_len]
        for algo in (SelectAlgo.AUTO, SelectAlgo.RADIX_8BITS):
            vals, idx = matrix.select_k(res, CSRMatrix.from_scipy(ref),
                                        k=32, select_min=True, algo=algo)
            dv, _ = dense_select_k(res, jnp.asarray(dense), 32,
                                   select_min=True, algo=algo)
            np.testing.assert_array_equal(np.asarray(vals),
                                          np.asarray(dv))
            # selected positions map back to real columns with the
            # selected values (index order can differ from the sorted
            # dense fixture; values pin the selection)
            gi = np.asarray(idx)
            full = ref.toarray().astype(np.float32)
            full[full == 0] = np.inf
            picked = np.take_along_axis(full, np.maximum(gi, 0), axis=1)
            finite = np.isfinite(np.asarray(vals))
            np.testing.assert_array_equal(picked[finite],
                                          np.asarray(vals)[finite])
            assert (gi[~finite] == -1).all()

    def test_diagonal(self):
        rng = np.random.RandomState(19)
        a = _rand_csr(rng, 8, 8, density=0.5)
        d = matrix.diagonal(CSRMatrix.from_scipy(a))
        np.testing.assert_allclose(np.asarray(d), a.diagonal(),
                                   rtol=1e-6)

    def test_set_diagonal(self):
        rng = np.random.RandomState(20)
        a = _rand_csr(rng, 8, 8, density=0.6)
        out = matrix.set_diagonal(CSRMatrix.from_scipy(a), 9.0)
        got = out.to_scipy().toarray()
        refd = a.toarray()
        mask = np.eye(8, dtype=bool) & (refd != 0)
        assert np.all(got[mask] == 9.0)

    def test_tfidf(self):
        # ref formula: tf = log(v), idf = log(n_rows/featCount + 1)
        rows = np.array([0, 0, 1, 2], dtype=np.int32)
        cols = np.array([0, 1, 0, 2], dtype=np.int32)
        vals = np.array([2., 3., 1., 5.], dtype=np.float32)
        coo = COOMatrix(rows, cols, vals, (3, 3))
        out = np.asarray(matrix.encode_tfidf(coo))
        feat = np.array([2, 1, 1])
        expect = np.log(vals) * np.log(3 / feat[cols] + 1)
        np.testing.assert_allclose(out, expect, rtol=1e-5)

    def test_bm25(self):
        rows = np.array([0, 0, 1, 2], dtype=np.int32)
        cols = np.array([0, 1, 0, 2], dtype=np.int32)
        vals = np.array([2., 3., 1., 5.], dtype=np.float32)
        coo = COOMatrix(rows, cols, vals, (3, 3))
        k1, b = 1.6, 0.75
        out = np.asarray(matrix.encode_bm25(coo, k1, b))
        feat = np.array([2, 1, 1])
        row_len = np.array([5., 1., 5.])
        avg = 11.0 / 3
        tf = np.log(vals)
        idf = np.log(3 / feat[cols] + 1)
        bm = ((k1 + 1) * tf) / (
            k1 * ((1 - b) + b * row_len[rows] / avg) + tf)
        np.testing.assert_allclose(out, idf * bm, rtol=1e-5)


class TestSolvers:
    def _sym_psd(self, rng, n, density=0.15):
        a = sp.random(n, n, density=density, random_state=rng,
                      dtype=np.float64)
        a = a + a.T + sp.eye(n) * 5.0
        return a.tocsr().astype(np.float32)

    @pytest.mark.parametrize("which", ["SA", "LA", "LM", "SM"])
    def test_eigsh_vs_scipy(self, which):
        rng = np.random.RandomState(21)
        a = self._sym_psd(rng, 120)
        k = 4
        vals, vecs = eigsh(CSRMatrix.from_scipy(a), k=k, which=which,
                           tol=1e-6, seed=7)
        ref_vals = spla.eigsh(a.astype(np.float64), k=k, which=which,
                              return_eigenvectors=False)
        np.testing.assert_allclose(np.sort(np.asarray(vals)),
                                   np.sort(ref_vals), rtol=2e-3,
                                   atol=2e-3)
        # residual check ‖Av − λv‖
        av = a @ np.asarray(vecs)
        lv = np.asarray(vecs) * np.asarray(vals)[None, :]
        assert np.linalg.norm(av - lv) < 5e-2

    def test_mst_total_weight(self, res):
        rng = np.random.RandomState(22)
        n = 40
        dense = rng.rand(n, n)
        dense = np.triu(dense, 1)
        dense = dense + dense.T
        adj = sp.csr_matrix(dense * (dense < 0.3))
        # ensure connectivity via a ring
        ring = sp.coo_matrix(
            (np.full(n, 0.5), (np.arange(n), (np.arange(n) + 1) % n)),
            shape=(n, n))
        adj = (adj + ring + ring.T).tocsr().astype(np.float32)
        colors = np.zeros(n, dtype=np.int32)
        out = mst(res, CSRMatrix.from_scipy(adj), color=np.arange(n, dtype=np.int32))
        assert isinstance(out, GraphCOO)
        got_w = float(np.sum(np.asarray(out.weights))) / 2.0
        ref = csgraph.minimum_spanning_tree(adj.astype(np.float64))
        np.testing.assert_allclose(got_w, ref.sum(), rtol=1e-5)
        assert out.n_edges == 2 * (n - 1)

    def test_eigsh_invariant_subspace_stability(self, res):
        """Highly symmetric graph (few distinct eigenvalues) with ncv near
        n: betas decay to ~1e-5 mid-extension; the RELATIVE breakdown
        threshold must catch it or noise amplification corrupts the basis
        (regression: Ritz values exploded to ±435 on a matrix with
        ||A|| <= 2)."""
        from tests.conftest import ring_of_cliques
        L = csgraph.laplacian(
            ring_of_cliques().to_scipy().astype(np.float64), normed=True)
        Lc = CSRMatrix.from_scipy(sp.csr_matrix(L.astype(np.float32)))
        for ncv in (12, 20, 31):
            vals, vecs = eigsh(Lc, k=4, which="SA", ncv=ncv, seed=1)
            ref = spla.eigsh(L, k=4, which="SA")[0]
            np.testing.assert_allclose(np.sort(np.asarray(vals)),
                                       np.sort(ref), atol=1e-3,
                                       err_msg=f"ncv={ncv}")

    def test_eigsh_scale_invariance(self, res):
        """A 1e-4-scaled matrix must solve exactly like its unit-scale
        version (regression: a constant floor in the breakdown threshold
        made every step on a tiny-norm operator look like breakdown)."""
        rng = np.random.RandomState(3)
        d = rng.rand(40, 40)
        d = np.triu(d, 1) * (np.triu(d, 1) < 0.2)
        A = sp.csr_matrix(d + d.T).astype(np.float32)
        L = csgraph.laplacian(A.astype(np.float64))
        for scale in (1.0, 1e-4):
            Ls = sp.csr_matrix(L * scale).astype(np.float32)
            vals, _ = eigsh(CSRMatrix.from_scipy(Ls), k=3, which="SA",
                            seed=0)
            ref = np.sort(np.linalg.eigvalsh((L * scale).toarray()))[:3]
            np.testing.assert_allclose(np.sort(np.asarray(vals)), ref,
                                       atol=1e-3 * scale + 1e-7,
                                       err_msg=f"scale={scale}")

    def test_eigsh_ell_auto_selection(self, res):
        """Regular sparsity → maybe_ell picks the slab SpMV inside the
        Lanczos device loop; results must match scipy either way."""
        from raft_tpu.sparse.ell import maybe_ell
        from raft_tpu.sparse.solver.lanczos import eigsh

        n = 300
        diags = [np.full(n, 4.0), np.full(n - 1, -1.0), np.full(n - 3, -.5)]
        A = sp.diags(diags, [0, 1, 3])
        A = (A + A.T).tocsr().astype(np.float32)
        csr = CSRMatrix.from_scipy(A)
        assert maybe_ell(csr) is not None           # the regular case
        vals, vecs = eigsh(csr, k=4, which="SA", seed=0)
        ref = spla.eigsh(A.astype(np.float64), k=4, which="SA")[0]
        np.testing.assert_allclose(np.sort(np.asarray(vals)),
                                   np.sort(ref), rtol=1e-3, atol=1e-4)

        # skewed rows (one dense row) → ELL declined, segment path used
        B = A.tolil()
        B[0, :] = 1.0
        B[:, 0] = 1.0
        csr_skew = CSRMatrix.from_scipy(B.tocsr().astype(np.float32))
        assert maybe_ell(csr_skew) is None

    @pytest.mark.parametrize("seed", [0, 1, 2, 3])
    def test_mst_random_vs_scipy(self, res, seed):
        """Randomized forests (possibly disconnected) against scipy,
        including duplicate weights (exercises the canonical-undirected-key
        tie-break and mutual-pair dedup of the device Borůvka rounds)."""
        rng = np.random.RandomState(seed)
        n = 120
        dense = np.round(rng.rand(n, n), 2)      # many exact weight ties
        dense = np.triu(dense, 1)
        dense = dense * (dense < 0.08)           # sparse → likely a forest
        adj = sp.csr_matrix(dense + dense.T).astype(np.float32)
        colors = np.arange(n, dtype=np.int32)
        out = mst(res, CSRMatrix.from_scipy(adj), color=colors)
        got_w = float(np.sum(np.asarray(out.weights))) / 2.0
        ref = csgraph.minimum_spanning_tree(adj.astype(np.float64))
        np.testing.assert_allclose(got_w, ref.sum(), rtol=1e-5)
        # component count from MSF size and from colors must agree
        n_comp = csgraph.connected_components(adj, directed=False)[0]
        assert out.n_edges // 2 == n - n_comp
        assert len(np.unique(colors)) == n_comp

    def test_mst_with_edge_compaction(self, res, monkeypatch):
        # a weighted path needs ~log2(n) Boruvka rounds, so with a tiny
        # size floor the driver MUST run the round-4 edge compaction
        # (asserted via a spy — this test caught the original-id output
        # extraction bug) and still match scipy exactly
        import importlib

        mst_mod = importlib.import_module("raft_tpu.sparse.solver.mst")
        monkeypatch.setattr(mst_mod, "_COMPACT_MIN", 8)
        calls = []
        orig = mst_mod._compact

        def spy(colors, src, dst, w, eids, out_size):
            calls.append(out_size)
            return orig(colors, src, dst, w, eids, out_size)

        monkeypatch.setattr(mst_mod, "_compact", spy)
        rng = np.random.RandomState(7)
        n = 3000
        i = np.arange(n - 1)
        w = rng.rand(n - 1).astype(np.float32) + 0.1
        adj = sp.coo_matrix((w, (i, i + 1)), shape=(n, n))
        adj = (adj + adj.T).tocsr()
        out = mst_mod.mst(res, CSRMatrix.from_scipy(adj))
        assert calls and calls == sorted(calls, reverse=True)
        got_w = float(np.sum(np.asarray(out.weights))) / 2.0
        ref = csgraph.minimum_spanning_tree(adj.astype(np.float64))
        np.testing.assert_allclose(got_w, ref.sum(), rtol=1e-5)
        assert out.n_edges // 2 == n - 1


class TestELL:
    """ELL slab format (raft_tpu.sparse.ell — the TPU-preferred layout)."""

    def _random_csr(self, rng, rows=60, cols=40, density=0.1):
        import numpy as np
        from raft_tpu.sparse import convert

        d = rng.normal(size=(rows, cols)).astype(np.float32)
        d[rng.uniform(size=(rows, cols)) > density] = 0.0
        return convert.dense_to_csr(d), d

    def test_from_csr_roundtrip_spmv(self):
        import numpy as np
        from raft_tpu.sparse import ell
        from raft_tpu.sparse.linalg import spmv

        rng = np.random.default_rng(0)
        csr, dense = self._random_csr(rng)
        e = ell.from_csr(csr)
        assert e.nnz == int(np.asarray(csr.indptr)[-1])
        assert e.width % 8 == 0
        x = rng.normal(size=dense.shape[1]).astype(np.float32)
        np.testing.assert_allclose(np.asarray(spmv(e, x)), dense @ x,
                                   rtol=1e-4, atol=1e-4)
        # dispatch equivalence with the CSR path
        np.testing.assert_allclose(np.asarray(spmv(e, x)),
                                   np.asarray(spmv(csr, x)),
                                   rtol=1e-5, atol=1e-5)

    def test_spmm(self):
        import numpy as np
        from raft_tpu.sparse import ell
        from raft_tpu.sparse.linalg import spmm

        rng = np.random.default_rng(1)
        csr, dense = self._random_csr(rng)
        e = ell.from_csr(csr)
        b = rng.normal(size=(dense.shape[1], 7)).astype(np.float32)
        np.testing.assert_allclose(np.asarray(spmm(e, b)), dense @ b,
                                   rtol=1e-4, atol=1e-4)

    def test_maybe_ell_padding_policy(self):
        import numpy as np
        from raft_tpu.sparse import convert, ell

        # uniform rows: favorable
        d = np.eye(32, dtype=np.float32)
        assert ell.maybe_ell(convert.dense_to_csr(d)) is not None
        # one huge row among empty ones: unfavorable
        d = np.zeros((64, 64), np.float32)
        d[0, :] = 1.0
        assert ell.maybe_ell(convert.dense_to_csr(d)) is None

    def test_empty_and_zero_rows(self):
        import numpy as np
        from raft_tpu.sparse import convert, ell
        from raft_tpu.sparse.linalg import spmv

        d = np.zeros((8, 8), np.float32)
        d[3, 2] = 5.0
        e = ell.from_csr(convert.dense_to_csr(d))
        y = np.asarray(spmv(e, np.ones(8, np.float32)))
        np.testing.assert_array_equal(y, d.sum(1))


class TestWeakCC:
    """Weakly-connected components (ref: sparse/csr.hpp weak_cc)."""

    def test_vs_scipy(self):
        from raft_tpu.sparse.csr import weak_cc

        rng = np.random.RandomState(5)
        for trial in range(4):
            d = rng.rand(60, 60)
            d = np.triu(d, 1) * (np.triu(d, 1) < 0.03)
            a = sp.csr_matrix(d).astype(np.float32)   # directed edges
            labels = np.asarray(weak_cc(None, CSRMatrix.from_scipy(a)))
            ncomp, ref = csgraph.connected_components(a, directed=True,
                                                      connection="weak")
            assert len(np.unique(labels)) == ncomp
            # same partition: our label == 1 + min vertex per component
            for c in range(ncomp):
                ours = labels[ref == c]
                assert len(set(ours.tolist())) == 1
                assert ours[0] == np.nonzero(ref == c)[0].min() + 1

    def test_mask_barriers(self):
        from raft_tpu.label.merge_labels import MAX_LABEL
        from raft_tpu.sparse.csr import weak_cc, weak_cc_batched

        # path 0-1-2-3; masking vertex 1 splits {0} | {2,3}
        rows = np.array([0, 1, 2], np.int64)
        cols = np.array([1, 2, 3], np.int64)
        a = sp.csr_matrix((np.ones(3, np.float32), (rows, cols)),
                          shape=(4, 4))
        mask = np.array([True, False, True, True])
        labels = np.asarray(weak_cc(None, CSRMatrix.from_scipy(a),
                                    mask=mask))
        assert labels[1] == MAX_LABEL
        assert labels[0] == 1 and labels[2] == labels[3] == 3
        # batched spelling agrees
        lb = np.asarray(weak_cc_batched(None, CSRMatrix.from_scipy(a),
                                        0, 2, mask=mask))
        np.testing.assert_array_equal(lb, labels)

    def test_adversarial_path_diameter(self):
        """The reviewer's counterexample: path 0-(n-1)-(n-2)-...-1, a
        single weak component whose min label spreads only one hop per
        round — the iteration cap must be diameter-safe, not log-bounded
        (regression: log cap silently returned 2 components)."""
        from raft_tpu.sparse.csr import weak_cc

        for n in (64, 256, 1024):
            src = np.array([0] + list(range(n - 1, 1, -1)), np.int64)
            dst = np.array([n - 1] + list(range(n - 2, 0, -1)), np.int64)
            a = sp.csr_matrix((np.ones(len(src), np.float32), (src, dst)),
                              shape=(n, n))
            labels = np.asarray(weak_cc(None, CSRMatrix.from_scipy(a)))
            assert len(np.unique(labels)) == 1, \
                f"n={n}: {len(np.unique(labels))} labels"
            assert labels[0] == 1

    def test_mst_adversarial_path(self, res):
        """Path graph with reversed vertex numbering: color-merge chains
        propagate one hop per round; forest must still be exact."""
        n = 512
        src = np.array(list(range(n - 1, 0, -1)), np.int64)
        dst = src - 1
        w = np.linspace(1, 2, n - 1).astype(np.float32)
        adj = sp.coo_matrix((w, (src, dst)), shape=(n, n))
        adj = (adj + adj.T).tocsr()
        out = mst(res, CSRMatrix.from_scipy(adj))
        assert out.n_edges // 2 == n - 1           # spanning tree
        got = float(np.sum(np.asarray(out.weights))) / 2
        np.testing.assert_allclose(got, w.sum(), rtol=1e-5)


class TestMSTFuzz:
    @pytest.mark.parametrize("seed", range(8))
    def test_compaction_schedule_vs_scipy(self, res, seed, monkeypatch):
        """Seeded fuzz over graph shapes with the compaction floor forced
        low: paths (log rounds), random forests, cliques with ties —
        total MSF weight must match scipy exactly through every
        compaction step."""
        import importlib

        mst_mod = importlib.import_module("raft_tpu.sparse.solver.mst")
        monkeypatch.setattr(mst_mod, "_COMPACT_MIN", 8)
        rng = np.random.RandomState(200 + seed)
        n = int(rng.randint(20, 800))
        kind = seed % 3
        if kind == 0:        # path + chords
            i = np.arange(n - 1)
            w = rng.rand(n - 1).astype(np.float32) + 0.1
            A = sp.coo_matrix((w, (i, i + 1)), shape=(n, n))
        elif kind == 1:      # sparse random (often a forest)
            dense = np.triu(np.round(rng.rand(n, n), 2), 1)
            dense = dense * (dense < 0.04)
            A = sp.coo_matrix(dense)
        else:                # denser with many exact ties
            dense = np.triu(np.round(rng.rand(n, n), 1), 1)
            dense = dense * (dense < 0.3)
            A = sp.coo_matrix(dense)
        A = (A + A.T).tocsr().astype(np.float32)
        if A.nnz == 0:
            return
        out = mst_mod.mst(res, CSRMatrix.from_scipy(A))
        got = float(np.asarray(out.weights).sum()) / 2.0
        ref = csgraph.minimum_spanning_tree(A.astype(np.float64)).sum()
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
        n_comp = csgraph.connected_components(A, directed=False)[0]
        assert out.n_edges // 2 == n - n_comp


class TestMSTGrid:
    """The Pallas Borůvka E-stage (sparse/solver/mst_grid.py) against
    scipy, forced via RAFT_TPU_MST=grid (the auto gate requires the
    compiled backend + 2^18 nnz; the kernels run interpreted here)."""

    @pytest.fixture(autouse=True)
    def _force_grid(self, monkeypatch):
        monkeypatch.setenv("RAFT_TPU_MST", "grid")

    def _check(self, A, res=None):
        from raft_tpu.sparse.solver.mst import mst as mst_fn

        ref = csgraph.minimum_spanning_tree(A.astype(np.float64))
        out = mst_fn(res, CSRMatrix.from_scipy(A),
                     symmetrize_output=False)
        got = float(np.asarray(out.weights).sum())
        np.testing.assert_allclose(got, ref.sum(), rtol=1e-5, atol=1e-5)
        assert ref.nnz == out.n_edges
        return out

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_random_forest_vs_scipy(self, seed):
        rng = np.random.default_rng(seed)
        n = 250
        d = np.abs(rng.normal(size=(n, n))).astype(np.float32) + 0.01
        d[rng.uniform(size=(n, n)) > 0.03] = 0     # sparse → forest-y
        A = sp.csr_matrix(np.minimum(d, d.T))
        A.eliminate_zeros()
        self._check(A)

    def test_weight_ties_rank_order(self):
        # every edge weight 1: the (w, rank, eid) order decides every
        # pick — mutual pairs must dedup by rank equality exactly
        rng = np.random.default_rng(3)
        d = (rng.uniform(size=(200, 200)) < 0.05).astype(np.float32)
        A = sp.csr_matrix(np.maximum(d, d.T))
        A.setdiag(0)
        A.eliminate_zeros()
        self._check(A)

    def test_path_graph_chain_depth(self):
        # a long path maximizes Borůvka round count AND the pointer-
        # doubling chain length; also exercises the cross-sub-row carry
        # of the lexicographic scan (single-row runs span tiles)
        rng = np.random.default_rng(4)
        n = 900
        i = np.arange(n - 1)
        w = rng.uniform(1, 2, n - 1).astype(np.float32)
        A = sp.csr_matrix(
            (np.concatenate([w, w]),
             (np.concatenate([i, i + 1]), np.concatenate([i + 1, i]))),
            shape=(n, n))
        self._check(A)

    def test_hub_star(self):
        # hub vertex: one long run chaining across many sub-rows/tiles
        rng = np.random.default_rng(5)
        n = 600
        s = np.zeros(n - 1, np.int64)
        t = np.arange(1, n)
        w = rng.uniform(1, 2, n - 1).astype(np.float32)
        A = sp.csr_matrix(
            (np.concatenate([w, w]),
             (np.concatenate([s, t]), np.concatenate([t, s]))),
            shape=(n, n))
        self._check(A)

    def test_colors_output_and_components(self):
        from raft_tpu.sparse.solver.mst import mst as mst_fn

        rng = np.random.default_rng(6)
        n = 150
        d = np.abs(rng.normal(size=(n, n))).astype(np.float32) + 0.01
        d[rng.uniform(size=(n, n)) > 0.04] = 0
        A = sp.csr_matrix(np.minimum(d, d.T))
        A.eliminate_zeros()
        colors = np.arange(n, dtype=np.int32)
        out = mst_fn(None, CSRMatrix.from_scipy(A), color=colors)
        n_comp = csgraph.connected_components(A, directed=False)[0]
        assert out.n_edges // 2 == n - n_comp
        assert len(np.unique(colors)) == n_comp

    def test_auto_dispatch_gate(self, monkeypatch):
        # auto: interpret mode (CPU suite) must stay on the XLA path;
        # forcing is what tests the kernels above
        monkeypatch.setenv("RAFT_TPU_MST", "auto")
        from raft_tpu.sparse.solver.mst import _mst_method

        rng = np.random.default_rng(8)
        d = np.abs(rng.normal(size=(64, 64))).astype(np.float32)
        d[rng.uniform(size=(64, 64)) > 0.2] = 0
        A = sp.csr_matrix(np.minimum(d, d.T))
        A.eliminate_zeros()
        assert _mst_method(CSRMatrix.from_scipy(A)) == "xla"
        monkeypatch.setenv("RAFT_TPU_MST", "bogus")
        with pytest.raises(ValueError):
            _mst_method(CSRMatrix.from_scipy(A))
