"""Adversarial / stress tier (round-2 verdict item 7).

NaN/inf and duplicate-value semantics through select_k and argmin, k≈m and
empty-cluster k-means, MATRIX_SELECT_LARGE-style shapes, and low-precision
select_k dtypes (ref: cpp/tests/matrix/select_large_k.cu and the NaN/tie
handling contracts of detail/select_radix.cuh + test_utils.cuh:45-141).

Documented contracts pinned here:
- NaN ordering is the IEEE total order the reference's radix bit-twiddle
  also induces (select_radix.cuh maps float→sortable uint): +NaN sorts
  above +inf, -NaN below -inf. So +NaN is selected LAST by select_min and
  FIRST by select_max; non-NaN winners are never perturbed.
- Duplicate values break ties toward ascending input position — the KVP
  first-minimum rule (smallest index among equal values wins).
- argmin treats NaN as minimal (numpy semantics: the NaN position is
  returned) — distances produced by the fused kernels are clamped ≥ 0 and
  cannot be NaN, so this only concerns direct primitive use.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from raft_tpu.matrix import SelectAlgo, argmin, select_k


def _np_select_min(x, k):
    part = np.argsort(x, axis=1, kind="stable")[:, :k]
    return np.take_along_axis(x, part, axis=1), part


class TestSelectKAdversarial:
    def test_inf_values_selected_correctly(self):
        x = np.array([[3., -np.inf, 1., np.inf, 2.]], np.float32)
        v, i = select_k(None, x, k=2, select_min=True)
        assert np.asarray(v).tolist() == [[-np.inf, 1.0]]
        assert np.asarray(i).tolist() == [[1, 2]]
        v, i = select_k(None, x, k=2, select_min=False)
        assert np.asarray(v).tolist() == [[np.inf, 3.0]]
        assert np.asarray(i).tolist() == [[3, 0]]

    def test_nan_total_order_and_non_nan_winners_stable(self):
        x = np.array([[4., np.nan, 1., 2., np.inf]], np.float32)
        # select_min: +NaN sorts above +inf -> last; first 3 unperturbed
        v, i = select_k(None, x, k=3, select_min=True)
        assert np.asarray(v).tolist() == [[1.0, 2.0, 4.0]]
        assert np.asarray(i).tolist() == [[2, 3, 0]]
        # select_max: +NaN above +inf -> selected first
        v, i = select_k(None, x, k=2, select_min=False)
        out = np.asarray(v)[0]
        assert np.isnan(out[0]) and out[1] == np.inf
        assert np.asarray(i).tolist()[0] == [1, 4]

    def test_duplicate_ties_ascending_position(self):
        """KVP first-minimum rule: equal values -> ascending indices."""
        x = np.array([[5., 1., 1., 1., 7., 1.]], np.float32)
        v, i = select_k(None, x, k=4, select_min=True)
        assert np.asarray(v).tolist() == [[1.0, 1.0, 1.0, 1.0]]
        assert np.asarray(i).tolist() == [[1, 2, 3, 5]]
        # the tiled path must agree on ties within a tile
        wide = np.full((1, 20_000), 3.0, np.float32)
        wide[0, 777] = 1.0
        wide[0, 778] = 1.0
        v, i = select_k(None, wide, k=3, select_min=True,
                        algo=SelectAlgo.RADIX_11BITS)
        assert np.asarray(i).tolist() == [[777, 778, 0]]

    @pytest.mark.parametrize("k_rel", ["k_eq_len", "k_eq_len_minus_1"])
    def test_k_equals_len(self, k_rel):
        rng = np.random.default_rng(3)
        x = rng.normal(size=(4, 33)).astype(np.float32)
        k = x.shape[1] - (0 if k_rel == "k_eq_len" else 1)
        v, i = select_k(None, x, k=k, select_min=True)
        ref_v, ref_i = _np_select_min(x, k)
        np.testing.assert_array_equal(np.asarray(v), ref_v)
        np.testing.assert_array_equal(np.asarray(i), ref_i)

    def test_single_element_rows(self):
        v, i = select_k(None, np.array([[7.]], np.float32), k=1)
        assert np.asarray(v).tolist() == [[7.0]]
        assert np.asarray(i).tolist() == [[0]]

    # slow: the 1M-column double-algorithm sweep is ~28s of CPU wall —
    # off the tier-1 budget; TestStreamSelect keeps the tiled path
    # covered there.
    @pytest.mark.slow
    def test_select_large_shapes_tiled_vs_direct(self):
        """MATRIX_SELECT_LARGE analogue (select_large_k.cu): 1M+odd-length
        rows, k=2048, both algorithms, against the numpy oracle."""
        rng = np.random.default_rng(11)
        n_cols = (1 << 20) + 17            # non-multiple of every tile
        x = rng.normal(size=(2, n_cols)).astype(np.float32)
        k = 2048
        ref_v, _ = _np_select_min(x, k)
        for algo in (SelectAlgo.RADIX_11BITS,
                     SelectAlgo.WARPSORT_IMMEDIATE):
            v, i = select_k(None, x, k=k, select_min=True, algo=algo)
            np.testing.assert_array_equal(np.asarray(v), ref_v)
            # indices must address the claimed values
            np.testing.assert_array_equal(
                np.take_along_axis(x, np.asarray(i), axis=1), ref_v)

    @pytest.mark.parametrize("dtype", [np.float16, np.int8, np.uint8,
                                       np.int32])
    def test_low_precision_dtypes(self, dtype):
        rng = np.random.default_rng(5)
        if np.issubdtype(dtype, np.floating):
            x = rng.normal(size=(3, 50)).astype(dtype)
        else:
            info = np.iinfo(dtype)
            x = rng.integers(info.min, info.max + 1, size=(3, 50),
                             endpoint=False).astype(dtype)
        for select_min in (True, False):
            v, i = select_k(None, x, k=5, select_min=select_min)
            assert np.asarray(v).dtype == dtype
            xs = np.sort(x, axis=1)
            ref = xs[:, :5] if select_min else xs[:, ::-1][:, :5]
            np.testing.assert_array_equal(np.asarray(v), ref)

    def test_int_extremes_no_negation_overflow(self):
        """-INT_MIN overflows; the bitwise-NOT order flip must not."""
        x = np.array([[np.iinfo(np.int32).min, 0,
                       np.iinfo(np.int32).max]], np.int32)
        v, _ = select_k(None, x, k=3, select_min=True)
        assert np.asarray(v).tolist() == [
            [np.iinfo(np.int32).min, 0, np.iinfo(np.int32).max]]


class TestArgminAdversarial:
    def test_nan_is_minimal(self):
        a = np.array([[3., np.nan, 1.], [2., 5., 2.]], np.float32)
        out = np.asarray(argmin(None, a))
        assert out.tolist() == [1, 0]      # NaN position; tie -> first

    def test_all_equal_rows_first_index(self):
        a = np.zeros((5, 7), np.float32)
        assert np.asarray(argmin(None, a)).tolist() == [0] * 5


class TestKMeansAdversarial:
    def _fit(self, x, k, **kw):
        from raft_tpu.cluster.kmeans import KMeansParams, kmeans_fit

        params = KMeansParams(n_clusters=k, max_iter=20, seed=0, **kw)
        return kmeans_fit(None, params, jnp.asarray(x))

    def test_k_equals_n_samples(self):
        """Every point becomes its own centroid. The expanded-L2 form
        gives d(x, x) a cancellation error ~|x|^2 * tier_eps rather than
        exact 0, so the inertia bound scales with the squared norms."""
        rng = np.random.default_rng(21)
        x = rng.normal(size=(16, 8)).astype(np.float32) * 10
        c, inertia, labels, _ = self._fit(x, k=16)
        scale = float((x.astype(np.float64) ** 2).sum())
        assert float(inertia) < scale * 1e-5
        assert len(set(np.asarray(labels).tolist())) == 16

    def test_empty_clusters_keep_centroid_finite(self):
        """k far above the number of distinct points: empty clusters must
        not produce NaN/inf centroids (the 0/0 update), and occupied
        clusters must sit on the duplicated points."""
        x = np.repeat(np.array([[0., 0.], [10., 10.]], np.float32),
                      8, axis=0)
        c, inertia, labels, _ = self._fit(x, k=6)
        c = np.asarray(c)
        assert np.all(np.isfinite(c))
        assert float(inertia) < 1e-6
        # both distinct locations are represented
        d0 = np.abs(c - np.array([0., 0.])).sum(1).min()
        d1 = np.abs(c - np.array([10., 10.])).sum(1).min()
        assert d0 < 1e-4 and d1 < 1e-4

    def test_single_cluster(self):
        rng = np.random.default_rng(23)
        x = rng.normal(size=(64, 4)).astype(np.float32)
        c, inertia, labels, _ = self._fit(x, k=1)
        np.testing.assert_allclose(np.asarray(c)[0], x.mean(0), rtol=1e-4,
                                   atol=1e-4)
        assert set(np.asarray(labels).tolist()) == {0}


class TestStreamSelect:
    """The streaming running-top-k contender (SelectAlgo.WARPSORT_FILTERED
    → _stream_select) must match the other algorithms on every case class
    the tournament covers."""

    @pytest.mark.parametrize("length,k", [(20_000, 16), (100_000, 512),
                                          (65_537, 100)])
    def test_matches_direct(self, length, k):
        rng = np.random.default_rng(length % 97)
        x = rng.normal(size=(4, length)).astype(np.float32)
        vd, idd = select_k(None, x, k=k, select_min=True,
                           algo=SelectAlgo.WARPSORT_IMMEDIATE)
        vs, ids = select_k(None, x, k=k, select_min=True,
                           algo=SelectAlgo.WARPSORT_FILTERED)
        np.testing.assert_array_equal(np.asarray(vs), np.asarray(vd))
        np.testing.assert_array_equal(np.asarray(ids), np.asarray(idd))

    def test_stream_duplicate_ties(self):
        wide = np.full((2, 30_000), 3.0, np.float32)
        wide[:, 12345] = 1.0
        wide[:, 12346] = 1.0
        v, i = select_k(None, wide, k=3, select_min=True,
                        algo=SelectAlgo.WARPSORT_FILTERED)
        assert np.asarray(i).tolist() == [[12345, 12346, 0]] * 2

    def test_stream_neg_inf_rows(self):
        x = np.full((1, 20_000), -np.inf, np.float32)
        v, i = select_k(None, x, k=4, select_min=False,
                        algo=SelectAlgo.WARPSORT_FILTERED)
        iv = np.asarray(i)[0]
        assert np.all(np.asarray(v) == -np.inf)
        # indices must be real, distinct positions — not a seed artifact
        assert len(set(iv.tolist())) == 4 and iv.max() < 20_000

    def test_stream_select_max(self):
        rng = np.random.default_rng(12)
        x = rng.normal(size=(3, 40_000)).astype(np.float32)
        v, i = select_k(None, x, k=9, select_min=False,
                        algo=SelectAlgo.WARPSORT_FILTERED)
        ref = np.sort(x, 1)[:, ::-1][:, :9]
        np.testing.assert_array_equal(np.asarray(v), ref)


class TestEigSelDegenerateSpectrum:
    """eig_sel on clustered / repeated eigenvalues (VERDICT item 9).

    The reference's syevdx is an exact subset solver, so multiplicity is
    free there; the TPU iterative path resolves one Krylov direction per
    DISTINCT eigenvalue and relies on locking + verification-with-
    fallback to surface degenerate copies. These tests pin the user-
    visible contract on the hardest spectra: the returned pairs must be
    the true extremal ones, with orthonormal vectors and small
    residuals, whether the iterative path resolved the cluster itself
    or verification routed it to the exact slice."""

    def _spd_with_spectrum(self, w, seed, dtype=np.float32):
        """Symmetric matrix with EXACTLY the eigenvalues ``w`` (built as
        Q diag(w) Q^T with Q orthogonal from a QR of Gaussian noise)."""
        n = len(w)
        rng = np.random.default_rng(seed)
        q, _ = np.linalg.qr(rng.standard_normal((n, n)))
        a = (q * np.asarray(w)) @ q.T
        return ((a + a.T) / 2).astype(dtype)

    def _check(self, a, w_got, v_got, w_want, *, tol):
        w_got = np.asarray(w_got, np.float64)
        v_got = np.asarray(v_got, np.float64)
        # values: ascending within the selection, equal to the designed
        # extremal set (multiplicity included)
        assert np.all(np.diff(w_got) >= -tol)
        np.testing.assert_allclose(w_got, np.sort(w_want),
                                   rtol=tol, atol=tol)
        # vectors: orthonormal even within a degenerate cluster (near-
        # parallel copies of one eigvec would pass a residual check but
        # not this one)
        gram = v_got.T @ v_got
        np.testing.assert_allclose(gram, np.eye(gram.shape[0]), atol=tol)
        # residuals: every returned pair really is an eigenpair
        res = np.abs(a.astype(np.float64) @ v_got - v_got * w_got)
        scale = np.abs(np.asarray(a)).max()
        assert res.max() <= tol * max(scale, 1.0), \
            f"residual {res.max():.3e} vs tol {tol * scale:.3e}"

    def test_repeated_top_eigenvalue_exact_path(self):
        # n=64 is far below the iterative envelope: exercises the exact
        # slice on a 4-fold degenerate dominant eigenvalue
        from raft_tpu.linalg import eig_sel

        w = np.concatenate([np.linspace(0.1, 1.0, 60), [5.0] * 4])
        a = self._spd_with_spectrum(w, seed=0)
        vals, vecs = eig_sel(None, a, 6, largest=True)
        self._check(a, vals, vecs, np.sort(w)[-6:], tol=5e-4)

    def test_clustered_spectrum_iterative_path(self):
        # n=512 f32, k<=n/3: inside the Lanczos envelope. The top of the
        # spectrum is a tight cluster (gap 1e-4) PLUS an exact 3-fold
        # multiplicity — the worst case for Krylov separation. Forcing
        # exact=False means any success here is either the iterative
        # solver resolving the cluster or its verifier correctly
        # refusing and falling back — both are the documented contract.
        from raft_tpu.linalg import eig_sel

        n = 512
        bulk = np.linspace(0.01, 1.0, n - 8)
        cluster = 2.0 + 1e-4 * np.arange(5)          # 5 nearly-equal
        triple = [3.0] * 3                           # exact multiplicity
        w = np.concatenate([bulk, cluster, triple])
        a = self._spd_with_spectrum(w, seed=1)
        vals, vecs = eig_sel(None, a, 8, largest=True, exact=False)
        self._check(a, vals, vecs, np.sort(w)[-8:], tol=2e-3)

    def test_flat_spectrum_smallest_end(self):
        # repeated eigenvalues at the SMALL end with largest=False, on
        # the exact path: the selection must return the full degenerate
        # block, not k copies of one direction
        from raft_tpu.linalg import eig_sel

        w = np.concatenate([[0.5] * 5, np.linspace(1.0, 4.0, 59)])
        a = self._spd_with_spectrum(w, seed=2)
        vals, vecs = eig_sel(None, a, 5, largest=False)
        self._check(a, vals, vecs, np.array([0.5] * 5), tol=5e-4)
