"""Chaos suite for the comms resilience layer (ISSUE 1).

Exercises the fault-injection hooks (drop / delay / duplicate / corrupt /
disconnect) against BOTH transports — the in-process ``_Mailbox`` and the
cross-process ``TcpMailbox`` — and asserts the typed error taxonomy
surfaces with the correct rank attribution:

* ``CommsTimeoutError`` when a message never arrives (dropped, corrupted
  on the wire) but the peer is not proven dead;
* ``PeerFailedError`` (dead rank attached) when the failure detector
  fires — connection lost, heartbeat silence, or a real peer process
  killed mid-exchange (< 5 s detection, the acceptance bar);
* ``CommsAbortedError`` when ``interruptible.cancel()`` is aimed at a
  thread blocked in a mailbox ``get``.

Everything here must stay inside the tier-1 ``not slow`` budget: each
case uses sub-second timeouts; the single subprocess test is bounded by
worker startup (one jax import), in line with test_multiprocess.py.
"""

import os
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from raft_tpu.comms.comms import _Mailbox, MeshComms
from raft_tpu.comms.errors import (
    CommsAbortedError,
    CommsError,
    CommsTimeoutError,
    PeerFailedError,
)
from raft_tpu.comms.faults import FaultInjector
from raft_tpu.comms.resilience import RetryPolicy, TagStore
from raft_tpu.comms.tcp_mailbox import TcpMailbox
from raft_tpu.core import interruptible, trace

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- helpers ----------------------------------------------------------------


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


@pytest.fixture
def tcp_pair():
    """Two live TcpMailbox ranks on localhost; closed at teardown."""
    boxes = []

    def make(rank1_kwargs=None, **kwargs):
        addrs = [f"127.0.0.1:{p}" for p in _free_ports(2)]
        b0 = TcpMailbox(0, addrs, **kwargs)
        b1 = TcpMailbox(1, addrs, **(rank1_kwargs if rank1_kwargs is not None
                                     else kwargs))
        boxes.extend([b0, b1])
        return b0, b1

    yield make
    for b in boxes:
        b.close()


def _run_to_completion(th, timeout=5.0):
    th.join(timeout=timeout)
    assert not th.is_alive(), "blocked thread never woke"


# -- error taxonomy ---------------------------------------------------------


def test_taxonomy_shape():
    """The typed hierarchy mirrors the status_t contract (ISSUE tentpole
    part 1): every comms failure isinstance-checks as CommsError; the
    timeout doubles as a stdlib TimeoutError and the abort as an
    InterruptedException."""
    assert issubclass(CommsTimeoutError, CommsError)
    assert issubclass(CommsTimeoutError, TimeoutError)
    assert issubclass(PeerFailedError, CommsError)
    assert issubclass(CommsAbortedError, CommsError)
    assert issubclass(CommsAbortedError, interruptible.InterruptedException)
    e = PeerFailedError("x", rank=3, endpoint=(3, 0, 7))
    assert e.rank == 3 and e.endpoint == (3, 0, 7)


# -- RetryPolicy ------------------------------------------------------------


def test_retry_policy_succeeds_after_transients():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return "ok"

    policy = RetryPolicy(max_attempts=5, base_delay=0.005, max_delay=0.01)
    assert policy.call(flaky, describe="flaky", seed=0) == "ok"
    assert len(calls) == 3


def test_retry_policy_exhaustion_reraises_last():
    def always():
        raise OSError("nope")

    policy = RetryPolicy(max_attempts=3, base_delay=0.001, max_delay=0.002)
    with pytest.raises(OSError, match="nope"):
        policy.call(always, describe="always", seed=0)


def test_retry_policy_deadline_raises_timeout():
    def always():
        raise OSError("nope")

    policy = RetryPolicy(max_attempts=100, base_delay=0.05, max_delay=0.05,
                         jitter=0.0, deadline=0.12)
    t0 = time.monotonic()
    with pytest.raises(CommsTimeoutError):
        policy.call(always, describe="deadline", seed=0)
    assert time.monotonic() - t0 < 1.0


def test_retry_policy_backoff_deterministic_and_capped():
    import random

    policy = RetryPolicy(max_attempts=8, base_delay=0.1, max_delay=0.4,
                         multiplier=2.0, jitter=0.5)
    a = [policy.delay(i, random.Random(42)) for i in range(6)]
    b = [policy.delay(i, random.Random(42)) for i in range(6)]
    assert a == b                       # seeded jitter replays
    assert max(a) <= 0.4                # cap holds under jitter
    nojit = RetryPolicy(max_attempts=8, base_delay=0.1, max_delay=0.4,
                        jitter=0.0)
    assert [nojit.delay(i) for i in range(4)] == [0.1, 0.2, 0.4, 0.4]


def test_retry_budget_exhaustion_fails_fast_and_is_metered():
    """ISSUE 16 satellite: a scope-wide retry budget converts the
    (N callers x full backoff) storm into a metered fast-fail once the
    window is spent — shared across every policy naming the scope."""
    from raft_tpu import obs
    from raft_tpu.comms import resilience
    from raft_tpu.obs import metrics as obs_metrics

    resilience.reset_retry_budgets()
    was_enabled = obs.enabled()
    old_reg = obs_metrics.set_registry(obs.MetricsRegistry())
    obs.set_enabled(True)
    calls = []

    def always():
        calls.append(1)
        raise OSError("nope")

    try:
        policy = RetryPolicy(max_attempts=10, base_delay=0.001,
                             max_delay=0.002,
                             budget_scope="test.retry_budget",
                             budget_max=2, budget_window_s=60.0)
        with pytest.raises(OSError, match="nope"):
            policy.call(always, describe="budgeted")
        # 1 try + 2 budgeted retries + the blocked third = 3 calls
        assert len(calls) == 3
        # the budget is the SCOPE's, not the policy instance's
        other = RetryPolicy(max_attempts=10, base_delay=0.001,
                            budget_scope="test.retry_budget",
                            budget_max=2)
        calls.clear()
        with pytest.raises(OSError):
            other.call(always, describe="second caller")
        assert len(calls) == 1          # window spent: zero retries
        snap = obs_metrics.get_registry().snapshot()
        rej = snap["limits_rejected_total"]["series"]
        assert any(s["labels"] == {"op": "test.retry_budget",
                                   "reason": "retry_budget"}
                   and s["value"] == 2.0 for s in rej), rej
        budgeted = [s for s in snap["comms_retries_total"]["series"]
                    if s["labels"] == {"outcome": "budget"}]
        assert budgeted and budgeted[0]["value"] == 2.0
    finally:
        obs.set_enabled(was_enabled)
        obs_metrics.set_registry(old_reg)
        resilience.reset_retry_budgets()


def test_retry_jitter_deterministic_from_describe(monkeypatch):
    """With no explicit seed, the jitter schedule derives from the
    describe string: same call site -> identical backoffs run-to-run,
    different call sites -> decorrelated."""
    from raft_tpu.runtime import limits as rt_limits

    waits = []
    monkeypatch.setattr(rt_limits, "sleep_within_deadline",
                        lambda w, op=None: waits.append(round(w, 9)))

    def always():
        raise OSError("x")

    policy = RetryPolicy(max_attempts=4, base_delay=0.05, jitter=0.5)
    schedules = []
    for describe in ("link rank0->rank1", "link rank0->rank1",
                     "link rank0->rank2"):
        waits.clear()
        with pytest.raises(OSError):
            policy.call(always, describe=describe)
        schedules.append(tuple(waits))
    assert len(schedules[0]) == 3       # max_attempts - 1 backoffs
    assert schedules[0] == schedules[1], "same call site must replay"
    assert schedules[0] != schedules[2], "distinct links decorrelate"


def test_retry_events_land_in_active_trace_range():
    """Tentpole part 5: retry observability rides core.trace — events
    carry the active range of the emitting thread."""
    trace.clear_events()

    def flaky(state=[]):
        state.append(1)
        if len(state) < 2:
            raise OSError("transient")

    policy = RetryPolicy(max_attempts=3, base_delay=0.001)
    with trace.push_range("chaos-test-range"):
        policy.call(flaky, describe="traced", seed=0)
    evs = trace.events("comms.retry")
    assert evs, "no retry event recorded"
    assert evs[-1]["range"] == "chaos-test-range"
    assert evs[-1]["what"] == "traced"


# -- cancellation integration ----------------------------------------------


def test_cancel_unblocks_pending_recv_inprocess():
    """Tentpole part 5: interruptible.cancel() wakes a blocked mailbox
    get promptly (not at the timeout) with CommsAbortedError."""
    mb = _Mailbox()
    caught = {}

    def blocked():
        try:
            mb.get(0, 1, 0, timeout=30.0)
        except CommsAbortedError as e:
            caught["err"] = e
            caught["t"] = time.monotonic()

    th = threading.Thread(target=blocked)
    th.start()
    time.sleep(0.15)                      # let it block
    t0 = time.monotonic()
    interruptible.cancel(th.ident)
    _run_to_completion(th)
    interruptible.get_token(th.ident).clear()   # don't poison reused idents
    assert isinstance(caught["err"], CommsAbortedError)
    assert caught["t"] - t0 < 1.0, "cancel did not wake the get promptly"


def test_cancel_unblocks_pending_recv_tcp(tcp_pair):
    b0, b1 = tcp_pair()
    caught = {}

    def blocked():
        try:
            b0.get(1, 0, 5, timeout=30.0)
        except CommsAbortedError as e:
            caught["err"] = e

    th = threading.Thread(target=blocked)
    th.start()
    time.sleep(0.15)
    interruptible.cancel(th.ident)
    _run_to_completion(th)
    interruptible.get_token(th.ident).clear()
    assert isinstance(caught["err"], CommsAbortedError)


def test_cancel_token_wakers_fire_once_registered():
    token = interruptible.CancelToken()
    fired = []
    token.add_waker(lambda: fired.append(1))
    token.cancel()
    assert fired == [1]
    token.clear()
    token.remove_waker(token.remove_waker)  # unknown waker: benign


# -- fault injection: in-process _Mailbox -----------------------------------


def test_inprocess_drop_surfaces_timeout():
    inj = FaultInjector(seed=1, drop=1.0)
    mb = _Mailbox(faults=inj)
    mb.put(0, 1, 0, np.int32(5))
    with pytest.raises(CommsTimeoutError) as ei:
        mb.get(0, 1, 0, timeout=0.2)
    assert ei.value.endpoint == (0, 1, 0)
    assert inj.counts["drop"] == 1


def test_inprocess_duplicate_delivers_twice():
    inj = FaultInjector(seed=2, duplicate=1.0)
    mb = _Mailbox(faults=inj)
    mb.put(0, 1, 0, np.int32(7))
    assert int(mb.get(0, 1, 0, timeout=1.0)) == 7
    assert int(mb.get(0, 1, 0, timeout=1.0)) == 7
    assert inj.counts["duplicate"] == 1


def test_inprocess_delay_applies_on_send_path():
    inj = FaultInjector(seed=3, delay=1.0, delay_s=0.05)
    mb = _Mailbox(faults=inj)
    t0 = time.monotonic()
    mb.put(0, 1, 0, np.int32(1))
    assert time.monotonic() - t0 >= 0.04
    assert int(mb.get(0, 1, 0, timeout=1.0)) == 1


def test_inprocess_disconnect_fails_peer_with_rank():
    inj = FaultInjector(seed=4, disconnect=1.0)
    mb = _Mailbox(faults=inj)
    mb.put(0, 1, 0, np.int32(1))
    # parting message drains before the failure is consulted
    assert int(mb.get(0, 1, 0, timeout=1.0)) == 1
    t0 = time.monotonic()
    with pytest.raises(PeerFailedError) as ei:
        mb.get(0, 1, 1, timeout=30.0)
    assert time.monotonic() - t0 < 1.0, "failure did not fail fast"
    assert ei.value.rank == 0


def test_inprocess_corrupt_delivers_damaged_payload():
    """In-process corruption models memory damage: delivered, not
    detected (the wire transport is the one with a CRC — see
    test_tcp_corrupt_detected_and_dropped)."""
    inj = FaultInjector(seed=5, corrupt=1.0)
    mb = _Mailbox(faults=inj)
    sent = np.arange(4, dtype=np.float32)
    mb.put(0, 1, 0, sent)
    got = mb.get(0, 1, 0, timeout=1.0)
    assert got.shape == sent.shape and not np.array_equal(got, sent)


def test_rank_scoping_confines_faults():
    inj = FaultInjector(seed=6, drop=1.0, source_ranks={2})
    mb = _Mailbox(faults=inj)
    mb.put(0, 1, 0, np.int32(1))          # out of scope: delivered
    mb.put(2, 1, 0, np.int32(2))          # in scope: dropped
    assert int(mb.get(0, 1, 0, timeout=1.0)) == 1
    with pytest.raises(CommsTimeoutError):
        mb.get(2, 1, 0, timeout=0.2)
    assert inj.counts["drop"] == 1 and inj.counts["sends"] == 1


def _chaos_sequence(mailbox, n=24):
    """Fixed send sequence; returns which tags arrived (None = error)."""
    arrived = []
    for tag in range(n):
        mailbox.put(0, 1, tag, np.int32(tag))
    for tag in range(n):
        try:
            arrived.append(int(mailbox.get(0, 1, tag, timeout=0.15)))
        except CommsError:
            arrived.append(None)
    return arrived


def test_inprocess_chaos_deterministic_under_fixed_seed():
    """Acceptance bar: the chaos suite replays identically under a fixed
    fault seed (same drops, same survivors, same counters)."""
    runs = []
    for _ in range(2):
        inj = FaultInjector(seed=1234, drop=0.4, duplicate=0.2)
        runs.append((_chaos_sequence(_Mailbox(faults=inj)),
                     dict(inj.counts)))
    assert runs[0] == runs[1]
    assert runs[0][1]["drop"] > 0          # the plan actually fired


def test_tcp_chaos_deterministic_under_fixed_seed(tcp_pair):
    runs = []
    for _ in range(2):
        b0, b1 = tcp_pair()
        b0.faults = FaultInjector(seed=1234, drop=0.4, duplicate=0.2)
        arrived = []
        for tag in range(16):
            b0.put(0, 1, tag, np.int32(tag))
        for tag in range(16):
            try:
                arrived.append(int(b1.get(0, 1, tag, timeout=0.3)))
            except CommsError:
                arrived.append(None)
        runs.append((arrived, dict(b0.faults.counts)))
    assert runs[0] == runs[1]
    assert runs[0][1]["drop"] > 0


# -- fault injection: TcpMailbox --------------------------------------------


def test_tcp_drop_surfaces_timeout(tcp_pair):
    b0, b1 = tcp_pair()
    b0.faults = FaultInjector(seed=1, drop=1.0)
    b0.put(0, 1, 0, np.int32(5))
    with pytest.raises(CommsTimeoutError) as ei:
        b1.get(0, 1, 0, timeout=0.3)
    assert ei.value.endpoint == (0, 1, 0)


def test_tcp_duplicate_delivers_twice(tcp_pair):
    b0, b1 = tcp_pair()
    b0.faults = FaultInjector(seed=2, duplicate=1.0)
    b0.put(0, 1, 0, np.int32(9))
    assert int(b1.get(0, 1, 0, timeout=2.0)) == 9
    assert int(b1.get(0, 1, 0, timeout=2.0)) == 9


def test_tcp_delay_applies(tcp_pair):
    b0, b1 = tcp_pair()
    b0.faults = FaultInjector(seed=3, delay=1.0, delay_s=0.05)
    t0 = time.monotonic()
    b0.put(0, 1, 0, np.int32(1))
    assert time.monotonic() - t0 >= 0.04
    assert int(b1.get(0, 1, 0, timeout=2.0)) == 1


def test_tcp_corrupt_detected_and_dropped(tcp_pair):
    """Wire corruption model: the CRC32 frame check detects the damage,
    drops the frame (counted on the receiver), and the recv times out —
    corrupted data is never delivered."""
    b0, b1 = tcp_pair()
    b0.faults = FaultInjector(seed=4, corrupt=1.0)
    b0.put(0, 1, 0, np.arange(8, dtype=np.float32))
    with pytest.raises(CommsTimeoutError):
        b1.get(0, 1, 0, timeout=0.5)
    deadline = time.monotonic() + 2.0
    while b1.corrupt_frames == 0 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert b1.corrupt_frames == 1


def test_tcp_disconnect_fails_peer_fast_with_rank(tcp_pair):
    b0, b1 = tcp_pair()
    b0.faults = FaultInjector(seed=5, disconnect=1.0)
    b0.put(0, 1, 0, np.int32(1))
    assert int(b1.get(0, 1, 0, timeout=2.0)) == 1   # parting message drains
    t0 = time.monotonic()
    with pytest.raises(PeerFailedError) as ei:
        b1.get(0, 1, 1, timeout=30.0)
    assert time.monotonic() - t0 < 5.0
    assert ei.value.rank == 0
    # fresh traffic revives the peer (transient suspicion, not a
    # tombstone) — but fail-fast means a get can race the revive frame,
    # so poll briefly
    b0.faults = None
    b0.put(0, 1, 2, np.int32(2))
    deadline = time.monotonic() + 5.0
    while True:
        try:
            assert int(b1.get(0, 1, 2, timeout=1.0)) == 2
            break
        except PeerFailedError:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.02)


def test_tcp_heartbeat_silence_detected(tcp_pair):
    """A peer that goes silent (no frames, no heartbeats) without closing
    its socket is declared dead by the heartbeat failure detector."""
    b0, b1 = tcp_pair(heartbeat_interval=0.05, heartbeat_timeout=0.3,
                      rank1_kwargs=dict(heartbeat_interval=100.0))
    b1.put(1, 0, 0, np.int32(1))          # attributes the stream to rank 1
    assert int(b0.get(1, 0, 0, timeout=5.0)) == 1
    t0 = time.monotonic()
    with pytest.raises(PeerFailedError) as ei:
        b0.get(1, 0, 1, timeout=30.0)
    assert time.monotonic() - t0 < 5.0
    assert ei.value.rank == 1


def test_tcp_graceful_close_is_attributed(tcp_pair):
    b0, b1 = tcp_pair()
    b0.put(0, 1, 0, np.int32(1))
    assert int(b1.get(0, 1, 0, timeout=2.0)) == 1
    b0.close()
    with pytest.raises(PeerFailedError) as ei:
        b1.get(0, 1, 1, timeout=30.0)
    assert ei.value.rank == 0
    assert "departed" in str(ei.value)


# -- the acceptance scenario: a peer killed mid-exchange --------------------


def test_killed_peer_produces_peerfailederror_under_5s():
    """ISSUE acceptance: a TcpMailbox peer killed mid-exchange produces a
    PeerFailedError naming the dead rank in < 5 s — not a 120 s timeout."""
    addrs = [f"127.0.0.1:{p}" for p in _free_ports(2)]
    b0 = TcpMailbox(0, addrs)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    worker = os.path.join(_REPO, "tests", "_fault_worker.py")
    proc = subprocess.Popen([sys.executable, worker, "1"] + addrs,
                            cwd=_REPO, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    try:
        # worker up + stream attributed (ready frame arrives)
        assert int(b0.get(1, 0, 0, timeout=60.0)) == 1
        proc.kill()
        proc.wait(timeout=10)
        t0 = time.monotonic()
        with pytest.raises(PeerFailedError) as ei:
            b0.get(1, 0, 1, timeout=120.0)
        detection = time.monotonic() - t0
        assert detection < 5.0, f"took {detection:.1f}s to detect the kill"
        assert ei.value.rank == 1
    finally:
        if proc.poll() is None:
            proc.kill()
        b0.close()


# -- typed errors through the MeshComms façade ------------------------------


def test_meshcomms_typed_errors_and_rank(mesh8):
    """The taxonomy surfaces through isend/irecv rank views exactly as it
    does on the raw mailboxes (tentpole: one contract, both layers)."""
    inj = FaultInjector(seed=7, disconnect=1.0)
    comm = MeshComms(mesh8, rank=0, _mailbox=_Mailbox(faults=inj))
    v1 = comm.rank_view(1)
    comm.isend(np.int32(1), dest=1, tag=0)
    assert int(v1.irecv(source=0, tag=0).wait()) == 1
    with pytest.raises(PeerFailedError) as ei:
        v1.irecv(source=0, tag=1, timeout=30.0).wait()
    assert ei.value.rank == 0

    clean = MeshComms(mesh8, rank=0, _mailbox=_Mailbox())
    with pytest.raises(CommsTimeoutError):
        clean.rank_view(1).irecv(source=0, tag=9, timeout=0.2).wait()


def test_tagstore_peer_failed_then_revived():
    st = TagStore(name="unit")
    st.fail_peer(3, "test")
    assert st.peer_failed(3) == "test"
    with pytest.raises(PeerFailedError):
        st.get(3, 0, 0, timeout=5.0)
    st.revive_peer(3)
    assert st.peer_failed(3) is None
    st.deliver(3, 0, 0, "x")
    assert st.get(3, 0, 0, timeout=1.0) == "x"


def test_comm_split_failed_peer_in_color_group_fast_fails(mesh8):
    """A dead rank inside the caller's color group fails the split
    immediately with the dead rank attached — not after the first child
    collective hangs out its deadline (ISSUE 2 satellite)."""
    box = _Mailbox()
    box.fail_peer(1, "heartbeat silence")
    comm = MeshComms(mesh8, rank=0, _mailbox=box)
    color = [0, 0, 0, 0, 1, 1, 1, 1]
    key = list(range(8))
    t0 = time.monotonic()
    with pytest.raises(PeerFailedError) as ei:
        comm.comm_split(color, key)
    assert time.monotonic() - t0 < 1.0      # fast-fail, no deadline wait
    assert ei.value.rank == 1
    assert "color group 0" in str(ei.value)


def test_comm_split_failed_peer_in_other_color_is_ignored(mesh8):
    """shrink() carves survivors AROUND the dead: a failure in the
    discarded color group must not poison the surviving sub-clique."""
    box = _Mailbox()
    box.fail_peer(5, "connection reset")
    comm = MeshComms(mesh8, rank=0, _mailbox=box)
    sub = comm.comm_split([0, 0, 0, 0, 1, 1, 1, 1], list(range(8)))
    assert sub.get_size() == 4
    assert sub.get_rank() == 0
