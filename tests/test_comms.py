"""Comms layer tests on the 8-virtual-device CPU mesh.

Mirrors the reference's MNMG comms validation strategy (SURVEY.md §4):
correctness of every collective/p2p op is verified by device-side self-test
functions (ref: comms/detail/test.hpp:31-513) invoked through
``perform_test_comms_*`` wrappers (ref: raft-dask comms_utils.pyx:68-218,
test_comms.py:254-293); here the LocalCUDACluster is replaced by the
8-device virtual CPU mesh.
"""

import numpy as np
import pytest

import raft_tpu
from raft_tpu import comms as rc
from raft_tpu.comms import device as rcd
from raft_tpu.core import resources as core_res


@pytest.fixture(scope="module")
def comm(mesh8):
    return rc.build_mesh_comms(mesh=mesh8)


@pytest.fixture(scope="module")
def handle(mesh8):
    res = raft_tpu.device_resources(mesh=mesh8)
    rc.build_mesh_comms(res)
    return res


SELF_TESTS = [
    rc.perform_test_comms_allreduce,
    rc.perform_test_comms_bcast,
    rc.perform_test_comms_reduce,
    rc.perform_test_comms_allgather,
    rc.perform_test_comms_allgatherv,
    rc.perform_test_comms_gather,
    rc.perform_test_comms_gatherv,
    rc.perform_test_comms_reducescatter,
    rc.perform_test_comms_send_recv,
    rc.perform_test_comms_device_send_recv,
    rc.perform_test_comms_device_sendrecv,
    rc.perform_test_comms_device_multicast_sendrecv,
]


@pytest.mark.parametrize("fn", SELF_TESTS, ids=lambda f: f.__name__)
def test_self_tests(handle, fn):
    assert fn(handle)


def test_comm_split(handle):
    assert rc.perform_test_comm_split(handle, n_colors=2)
    assert rc.perform_test_comm_split(handle, n_colors=4)


def test_handle_injection(mesh8):
    res = raft_tpu.device_resources(mesh=mesh8)
    with pytest.raises(RuntimeError):
        core_res.get_comms(res)
    c = rc.build_mesh_comms(res)
    assert core_res.get_comms(res) is c
    assert c.get_size() == 8


def test_allreduce_float_ops(comm):
    n = comm.get_size()
    x = np.arange(n, dtype=np.float32).reshape(n, 1) + 1.0
    assert np.allclose(np.asarray(comm.allreduce(x, op=rc.Op.SUM)),
                       x.sum())
    assert np.allclose(np.asarray(comm.allreduce(x, op=rc.Op.MIN)), 1.0)
    assert np.allclose(np.asarray(comm.allreduce(x, op=rc.Op.MAX)),
                       float(n))
    assert np.allclose(np.asarray(comm.allreduce(x, op=rc.Op.PROD)),
                       np.prod(x))


def test_reducescatter_blocks(comm):
    n = comm.get_size()
    x = np.tile(np.arange(n * 2, dtype=np.float32), (n, 1))  # [n, 2n]
    out = np.asarray(comm.reducescatter(x))  # [n, 2]
    for r in range(n):
        assert np.allclose(out[r], n * np.arange(2 * r, 2 * r + 2))


def test_in_jit_collectives(mesh8):
    """Device-side API inside an explicit shard_map (the MNMG algorithm
    pattern: ref docs/source/using_raft_comms.rst)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    def step(x):
        total = rcd.allreduce(jnp.sum(x), axis_name="data")
        r = rcd.rank("data")
        return x + total + r.astype(x.dtype)

    f = jax.jit(jax.shard_map(step, mesh=mesh8, in_specs=P("data"),
                              out_specs=P("data")))
    x = np.ones((16, 3), np.float32)
    out = np.asarray(f(x))
    # each shard: 2 rows; total = 48; shard r adds 48 + r
    for r in range(8):
        assert np.allclose(out[2 * r: 2 * r + 2], 1.0 + 48.0 + r)


def test_grouped_allreduce(mesh8, comm):
    """axis_index_groups == in-jit comm_split (ref: subcomm tests,
    raft-dask test_comms.py:429)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    groups = comm.axis_index_groups([r % 2 for r in range(8)])

    def step(x):
        return rcd.allreduce(x, axis_name="data",
                             axis_index_groups=groups)

    f = jax.jit(jax.shard_map(step, mesh=mesh8, in_specs=P("data"),
                              out_specs=P("data")))
    x = np.arange(8, dtype=np.float32).reshape(8, 1)
    out = np.asarray(f(x))
    even = sum(range(0, 8, 2))
    odd = sum(range(1, 8, 2))
    for r in range(8):
        assert out[r, 0] == (even if r % 2 == 0 else odd)


def test_ring_shift(mesh8, comm):
    n = comm.get_size()
    x = np.arange(n, dtype=np.int32).reshape(n, 1)
    perm = [(i, (i + 1) % n) for i in range(n)]
    out = np.asarray(comm.device_sendrecv(x, perm))
    assert np.array_equal(out[:, 0], np.roll(np.arange(n), 1))


def test_mailbox_tags(comm):
    v0 = comm.rank_view(0)
    v1 = comm.rank_view(1)
    v0.isend(np.float32(1.5), dest=1, tag=7)
    v0.isend(np.float32(2.5), dest=1, tag=9)
    r9 = v1.irecv(source=0, tag=9)
    r7 = v1.irecv(source=0, tag=7)
    assert float(r9.wait()) == 2.5
    assert float(r7.wait()) == 1.5


def test_get_type():
    assert rc.Datatype("float32") == rc.comms.get_type(np.float32(1)) \
        if hasattr(rc, "comms") else True
    from raft_tpu.comms.comms import get_type, Datatype

    assert get_type(np.zeros(3, np.float64)) == Datatype.FLOAT64
    assert get_type(np.zeros(3, np.int32)) == Datatype.INT32


def test_split_mailbox_shared(comm):
    """Regression: sub-communicators built from different rank views must
    share a mailbox per color group for host p2p to match."""
    color = [r % 2 for r in range(8)]
    key = list(range(8))
    sub0 = comm.rank_view(0).comm_split(color, key)  # color 0, sub-rank 0
    sub2 = comm.rank_view(2).comm_split(color, key)  # color 0, sub-rank 1
    sub0.isend(np.int32(99), dest=1, tag=0)
    got = sub2.irecv(source=0, tag=0).wait()
    assert int(got) == 99


def test_eager_collective_cached(comm):
    """Regression: repeated eager collectives reuse the compiled shard_map."""
    x = np.ones((8, 4), np.float32)
    comm.allreduce(x)
    n_entries = len(comm._shared["jit"])
    for _ in range(5):
        comm.allreduce(x)
    assert len(comm._shared["jit"]) == n_entries


def test_raft_dask_symbol_parity():
    """Every comms name raft_dask.common exports must exist here (ref:
    python/raft-dask/raft_dask/common/__init__.py:5-21; UCX's role is
    TcpMailbox, comms/tcp_mailbox.py)."""
    import raft_tpu.comms as c

    for name in ("Comms", "local_handle", "inject_comms_on_handle",
                 "inject_comms_on_handle_coll_only",
                 "perform_test_comm_split",
                 "perform_test_comms_allgather",
                 "perform_test_comms_allreduce",
                 "perform_test_comms_bcast",
                 "perform_test_comms_device_multicast_sendrecv",
                 "perform_test_comms_device_send_or_recv",
                 "perform_test_comms_device_sendrecv",
                 "perform_test_comms_gather",
                 "perform_test_comms_gatherv",
                 "perform_test_comms_reduce",
                 "perform_test_comms_reducescatter",
                 "perform_test_comms_send_recv"):
        assert hasattr(c, name), name
