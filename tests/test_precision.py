"""Matmul precision policy (util/precision.py).

The reference computes every matmul in f32 FMA through cuBLAS
(linalg/detail/cublas_wrappers.hpp); on TPU the equivalent accuracy
contract requires pinning dot_general precision above the single-bf16-pass
default. These tests assert the policy is actually reaching the traced
dots — the failure mode round 2's hardware smoke tier caught (knn index
agreement 95% vs 99%) regresses silently otherwise.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.util import precision as prec


def _dot_precisions(fn, *args):
    """Collect the precision attribute of every dot_general in fn's jaxpr."""
    jaxpr = jax.make_jaxpr(fn)(*args)
    out = []

    def walk(jx):
        for eqn in jx.eqns:
            if eqn.primitive.name == "dot_general":
                out.append(eqn.params.get("precision"))
            for v in eqn.params.values():
                for item in v if isinstance(v, (list, tuple)) else (v,):
                    if hasattr(item, "eqns"):          # raw Jaxpr
                        walk(item)
                    elif hasattr(item, "jaxpr"):       # ClosedJaxpr
                        walk(item.jaxpr)

    walk(jaxpr.jaxpr)
    return out


@pytest.fixture
def restore_policy():
    old = prec.get_matmul_precision()
    yield
    prec.set_matmul_precision(old)
    jax.config.update("jax_default_matmul_precision", None)


def test_default_policy_is_high():
    assert prec.get_matmul_precision() == "high"


def test_scope_pins_dots_in_pairwise(restore_policy):
    from raft_tpu.distance import DistanceType, pairwise_distance

    x = jnp.ones((8, 4), jnp.float32)
    prec.set_matmul_precision("highest")
    ps = _dot_precisions(
        lambda a: pairwise_distance(None, a, a, DistanceType.L2Expanded), x)
    assert ps, "expected at least one dot_general in the L2Expanded path"
    assert all(p == (jax.lax.Precision.HIGHEST,) * 2 for p in ps), ps


def test_user_global_config_wins(restore_policy):
    from raft_tpu.distance import DistanceType, pairwise_distance

    x = jnp.ones((8, 4), jnp.float32)
    with jax.default_matmul_precision("bfloat16"):
        ps = _dot_precisions(
            lambda a: pairwise_distance(None, a, a, DistanceType.L2Expanded),
            x)
    assert all(p == (jax.lax.Precision.DEFAULT,) * 2 for p in ps), ps


def test_set_matmul_precision_roundtrip(restore_policy):
    prec.set_matmul_precision("high")
    assert prec.get_matmul_precision() == "high"
    assert jax.config.jax_default_matmul_precision == "high"
    with pytest.raises(ValueError):
        prec.set_matmul_precision("quantum")


def test_gemm_precision_arg(restore_policy):
    from raft_tpu.linalg.blas import gemm

    a = np.arange(12, dtype=np.float32).reshape(3, 4)
    b = np.arange(8, dtype=np.float32).reshape(4, 2)
    want = a @ b
    for p in ("default", "high", "highest", None,
              jax.lax.Precision.HIGHEST):
        np.testing.assert_allclose(
            np.asarray(gemm(None, a, b, precision=p)), want, rtol=1e-6)
    ps = _dot_precisions(lambda x, y: gemm(None, x, y, precision="high"),
                         a, b)
    assert ps == [(jax.lax.Precision.HIGH,) * 2]


def test_knn_traced_at_policy(restore_policy):
    from raft_tpu.neighbors import knn

    db = jnp.asarray(np.random.default_rng(0).normal(size=(64, 8)),
                     jnp.float32)
    q = db[:4]
    prec.set_matmul_precision("highest")
    ps = _dot_precisions(lambda d, qq: knn(None, d, qq, k=3)[0], db, q)
    assert ps and all(p == (jax.lax.Precision.HIGHEST,) * 2 for p in ps), ps


def test_bf16_inputs_skip_split(restore_policy):
    """bf16 operands take the single-pass non-split kernels at every tier
    (splitting a bf16 value is meaningless) and still produce bf16-grade
    results."""
    from raft_tpu.linalg.contractions import pairwise_l2_pallas

    rng = np.random.default_rng(5)
    x16 = rng.normal(size=(64, 32)).astype(np.float32)
    y16 = rng.normal(size=(48, 32)).astype(np.float32)
    ref = ((x16[:, None, :] - y16[None, :, :]) ** 2).sum(-1)
    for tier in ("default", "high", "highest"):
        prec.set_matmul_precision(tier)
        d = np.asarray(pairwise_l2_pallas(jnp.asarray(x16, jnp.bfloat16),
                                          jnp.asarray(y16, jnp.bfloat16)))
        np.testing.assert_allclose(d, ref, rtol=0.1, atol=0.3)


def test_high_tier_split_accuracy(restore_policy):
    """The manual bf16 hi/lo split ('high' inside kernels) must land within
    ~2^-17 of the f64 oracle — far tighter than one bf16 pass."""
    from raft_tpu.linalg.contractions import pairwise_l2_pallas

    rng = np.random.default_rng(1)
    x = rng.normal(size=(96, 40)).astype(np.float32)
    y = rng.normal(size=(48, 40)).astype(np.float32)
    ref = ((x[:, None, :].astype(np.float64)
            - y[None, :, :].astype(np.float64)) ** 2).sum(-1)
    prec.set_matmul_precision("high")
    d = np.asarray(pairwise_l2_pallas(x, y)).astype(np.float64)
    rel = np.abs(d - ref) / np.maximum(np.abs(ref), 1e-9)
    assert rel.max() < 1e-4, rel.max()


def test_mixed_dtype_keeps_f32_operand_precision(restore_policy):
    """A mixed f32/bf16 dot must not silently truncate the f32 operand to
    one bf16 pass at tiers 'high'/'highest' (round-2 advisor finding):
    both are promoted to f32 and run through the tier's decomposition.
    The f32 operand carries sub-bf16 mantissa structure that one bf16
    pass destroys; the tiered result must preserve it."""
    from raft_tpu.linalg.contractions import _kernel_dot

    rng = np.random.default_rng(9)
    # values needing >8 mantissa bits: 1 + tiny perturbations
    a = (1.0 + rng.normal(size=(32, 64)) * 1e-4).astype(np.float32)
    b16 = jnp.asarray(rng.normal(size=(64, 16)).astype(np.float32),
                      jnp.bfloat16)
    ref = np.asarray(a, np.float64) @ np.asarray(
        b16.astype(jnp.float32), np.float64)
    for tier in ("high", "highest"):
        prec.set_matmul_precision(tier)
        out = np.asarray(_kernel_dot(jnp.asarray(a), b16), np.float64)
        rel = np.abs(out - ref) / np.maximum(np.abs(ref), 1e-9)
        assert rel.max() < 1e-4, (tier, rel.max())
    # the numeric check alone can't fail on CPU (XLA:CPU computes DEFAULT
    # dots in f32), so also pin the LOWERING: the old bug emitted ONE
    # DEFAULT-precision dot for the mixed case at every tier
    prec.set_matmul_precision("high")
    ps = _dot_precisions(_kernel_dot, jnp.asarray(a), b16)
    # a is f32 (needs its lo pass), b is bf16-exact (lo pass skipped) -> 2
    assert len(ps) == 2, ps
    prec.set_matmul_precision("highest")
    ps = _dot_precisions(_kernel_dot, jnp.asarray(a), b16)
    assert ps == [(jax.lax.Precision.HIGHEST,) * 2], ps


def test_packed_split_exact_equivalence(restore_policy):
    """The depth-packed bf16x3 spelling must be numerically IDENTICAL to
    the 3-dot spelling (same products, same f32 accumulation targets) —
    it is a scheduling variant, not an accuracy tier."""
    from raft_tpu.linalg.contractions import fused_lloyd_pallas

    rng = np.random.default_rng(31)
    x = jnp.asarray(rng.normal(size=(96, 40)).astype(np.float32))
    c = jnp.asarray(rng.normal(size=(16, 40)).astype(np.float32))
    prec.set_matmul_precision("high")
    ref = fused_lloyd_pallas(x, c, packed=False)
    got = fused_lloyd_pallas(x, c, packed=True)
    for a, b, name in zip(ref, got, ("sums", "counts", "dist", "labels")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-6, err_msg=name)


def test_split_rounding_survives_xla_simplifier():
    """The bf16 hi/lo split must be spelled so XLA cannot fold the lo
    half away: under --xla_allow_excess_precision (default-on on TPU)
    the simplifier deletes f32->bf16->f32 convert PAIRS, turning the
    astype-based residual ``a - f32(bf16(a))`` into ``a - a = 0`` and
    silently degrading tier 'high' to one bf16 pass (caught on-chip by
    the round-3 smoke tier; invisible to CPU numerics). Pin (a) the
    bitcast rounding is bit-identical to astype's round-half-to-even,
    including negatives, boundaries, and specials, and (b) the compiled
    HLO of _split_hi_lo retains the opaque bitcast arithmetic."""
    import jax

    from raft_tpu.linalg.contractions import (_round_to_bf16_f32,
                                              _split_hi_lo)

    rng = np.random.default_rng(77)
    vals = np.concatenate([
        rng.normal(size=4096).astype(np.float32),
        np.float32([0.0, -0.0, 1.0, -1.0, np.inf, -np.inf,
                    3.0e38, -3.0e38, 1e-38, -1e-38]),
        # exact rounding-boundary halves: 1 + (2n+1) * 2^-8 sits exactly
        # between two bf16 neighbours -> ties to even
        (1.0 + (2 * np.arange(64, dtype=np.float32) + 1) * 2.0 ** -8),
    ])
    got = np.asarray(_round_to_bf16_f32(jnp.asarray(vals)))
    want = np.asarray(jnp.asarray(vals).astype(jnp.bfloat16)
                      .astype(jnp.float32))
    np.testing.assert_array_equal(got, want)
    # NaN: the hi half is documented GARBAGE (payload-dependent — the
    # rounding carry can walk through the exponent: quiet 0x7FC00000
    # rounds to inf, full-payload 0x7FFFFFFF wraps to -0.0); the
    # CONTRACT is that the lo half is NaN for every NaN payload, so any
    # split dot that includes the lo pass propagates NaN
    nan_bits = np.uint32([0x7FC00000, 0x7FFFFFFF, 0xFFFFFFFF,
                          0x7F800001, 0xFFC00001])
    hi, lo = _split_hi_lo(jnp.asarray(nan_bits.view(np.float32)))
    assert np.isnan(np.asarray(lo.astype(jnp.float32))).all()

    hlo = jax.jit(_split_hi_lo).lower(
        jax.ShapeDtypeStruct((128, 64), jnp.float32)).compile().as_text()
    assert "bitcast-convert" in hlo, (
        "_split_hi_lo no longer goes through the integer rounding; the "
        "XLA excess-precision simplifier can fold its lo half to zero")
