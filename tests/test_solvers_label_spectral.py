"""Tests for label utilities, LAP solver, and spectral analyzers
(ref test models: cpp/tests/label/*, cpp/tests/lap/lap.cu,
cpp/tests/linalg/eigen_solvers.cu karate-club fixture)."""

import itertools

import numpy as np
import pytest

from raft_tpu import label as rlabel
from raft_tpu import spectral
from raft_tpu.solver import LinearAssignmentProblem, solve_linear_assignment
from raft_tpu.sparse import convert


# Zachary karate club edges (public domain fixture; the reference embeds the
# same graph in tests/linalg/eigen_solvers.cu:50-67).
_KARATE_EDGES = [
    (0, 1), (0, 2), (0, 3), (0, 4), (0, 5), (0, 6), (0, 7), (0, 8), (0, 10),
    (0, 11), (0, 12), (0, 13), (0, 17), (0, 19), (0, 21), (0, 31), (1, 2),
    (1, 3), (1, 7), (1, 13), (1, 17), (1, 19), (1, 21), (1, 30), (2, 3),
    (2, 7), (2, 8), (2, 9), (2, 13), (2, 27), (2, 28), (2, 32), (3, 7),
    (3, 12), (3, 13), (4, 6), (4, 10), (5, 6), (5, 10), (5, 16), (6, 16),
    (8, 30), (8, 32), (8, 33), (9, 33), (13, 33), (14, 32), (14, 33),
    (15, 32), (15, 33), (18, 32), (18, 33), (19, 33), (20, 32), (20, 33),
    (22, 32), (22, 33), (23, 25), (23, 27), (23, 29), (23, 32), (23, 33),
    (24, 25), (24, 27), (24, 31), (25, 31), (26, 29), (26, 33), (27, 33),
    (28, 31), (28, 33), (29, 32), (29, 33), (30, 32), (30, 33), (31, 32),
    (31, 33), (32, 33),
]


def karate_csr():
    n = 34
    a = np.zeros((n, n), np.float32)
    for i, j in _KARATE_EDGES:
        a[i, j] = a[j, i] = 1.0
    return convert.dense_to_csr(a), a


class TestLabel:
    def test_get_unique(self):
        y = np.array([5, 1, 5, 3, 1, 9])
        u = np.asarray(rlabel.get_unique_labels(y))
        np.testing.assert_array_equal(u, [1, 3, 5, 9])

    def test_ovr(self):
        y = np.array([1, 3, 5, 3, 1])
        u = rlabel.get_unique_labels(y)
        out = np.asarray(rlabel.get_ovr_labels(y, u, 1))   # class 3
        np.testing.assert_array_equal(out, [-1, 1, -1, 1, -1])
        with pytest.raises(ValueError):
            rlabel.get_ovr_labels(y, u, 5)

    @pytest.mark.parametrize("zero_based,base", [(False, 1), (True, 0)])
    def test_make_monotonic(self, zero_based, base):
        y = np.array([10, 30, 10, 50, 30])
        out = np.asarray(rlabel.make_monotonic(y, zero_based=zero_based))
        np.testing.assert_array_equal(out, np.array([0, 1, 0, 2, 1]) + base)

    def test_make_monotonic_filtered(self):
        y = np.array([-1, 10, 30, -1, 10])
        out = np.asarray(rlabel.make_monotonic(
            y, filter_op=lambda v: v < 0, zero_based=True))
        # -1 passes through; unique set is {-1,10,30} so 10->1, 30->2
        np.testing.assert_array_equal(out, [-1, 1, 2, -1, 1])

    def test_merge_labels_connected_components(self):
        # two labelings of 6 points; groups (by label value, 1-based):
        # A: {0,1}=1, {2,3}=3, {4,5}=5 ; B: {1,2}=2, {3}=4, {0}=1,{4}=5,{5}=6
        a = np.array([1, 1, 3, 3, 5, 5], np.int32)
        b = np.array([1, 2, 2, 4, 5, 6], np.int32)
        mask = np.ones(6, bool)
        out = np.asarray(rlabel.merge_labels(a, b, mask))
        # point1/point2 bridge groups 1 and 3 -> all of {0,1,2,3} get label 1
        assert out[0] == out[1] == out[2] == out[3] == 1
        assert out[4] == out[5] == 5

    def test_merge_labels_masked(self):
        a = np.array([1, 1, 3, 3], np.int32)
        b = np.array([1, 2, 2, 4], np.int32)
        mask = np.array([True, False, False, True])  # no bridge via point 1/2
        out = np.asarray(rlabel.merge_labels(a, b, mask))
        assert out[0] == out[1] == 1
        assert out[2] == out[3] == 3


def _brute_force_lap(cost):
    n = cost.shape[0]
    best, best_perm = np.inf, None
    for perm in itertools.permutations(range(n)):
        v = cost[np.arange(n), perm].sum()
        if v < best:
            best, best_perm = v, perm
    return best, np.asarray(best_perm)


class TestLAP:
    def test_small_exact(self, res):
        rng = np.random.default_rng(3)
        for _ in range(5):
            cost = rng.integers(0, 20, size=(6, 6)).astype(np.float32)
            row, total = solve_linear_assignment(res, cost, epsilon=0.01)
            expect, _ = _brute_force_lap(cost)
            assert float(total) == pytest.approx(expect)
            # assignment is a permutation
            assert sorted(np.asarray(row).tolist()) == list(range(6))

    def test_batched_class_api(self, res):
        rng = np.random.default_rng(11)
        batch, n = 4, 8
        costs = rng.integers(0, 50, size=(batch, n, n)).astype(np.float32)
        lap = LinearAssignmentProblem(res, n, batch, epsilon=0.01)
        rows, cols = lap.solve(costs)
        for b in range(batch):
            expect, _ = _brute_force_lap(costs[b])
            got = float(lap.get_primal_objective_value(b))
            assert got == pytest.approx(expect)
            # row/col assignments are inverse permutations
            r = np.asarray(rows[b])
            c = np.asarray(cols[b])
            np.testing.assert_array_equal(c[r], np.arange(n))
            # duality gap within n*eps
            dual = float(lap.get_dual_objective_value(b))
            assert abs(dual - got) <= n * 0.01 + 1e-3

    # n=300 is ~35s of CPU wall on its own — slow tier; n=100/200 keep
    # the exact-Hungarian comparison on the tier-1 budget.
    @pytest.mark.parametrize(
        "n,seed", [(100, 0), (200, 1),
                   pytest.param(300, 2, marks=pytest.mark.slow)])
    def test_vs_scipy_hungarian_float(self, res, n, seed):
        """Adversarial float costs at n in the hundreds vs scipy's EXACT
        Hungarian (VERDICT weak #7): the auction's n·eps bound must land
        within rtol of the true optimum, and tight eps should reach it."""
        from scipy.optimize import linear_sum_assignment

        rng = np.random.default_rng(seed)
        # adversarial: near-duplicate rows + tiny perturbations → many
        # near-ties, the auction's hardest regime
        base = rng.random((n // 2, n))
        cost = np.vstack([base, base + rng.normal(0, 1e-4,
                                                  base.shape)])[:n]
        cost = cost.astype(np.float32)
        ri, ci = linear_sum_assignment(cost.astype(np.float64))
        exact = float(cost.astype(np.float64)[ri, ci].sum())

        eps = 1e-5
        row, total = solve_linear_assignment(res, cost, epsilon=eps)
        row = np.asarray(row)
        assert sorted(row.tolist()) == list(range(n))   # a permutation
        got = float(cost.astype(np.float64)[np.arange(n), row].sum())
        # auction guarantee: within n*eps of optimal
        assert got <= exact + n * eps + 1e-4, (got, exact)

    def test_large_magnitude_f32_costs(self, res):
        # regression: costs at 1e5 magnitude with epsilon below f32 ulp
        # used to stall the bidding and return -1 assignments
        rng = np.random.default_rng(0)
        cost = rng.integers(0, 10, (16, 16)).astype(np.float32) * 1e5
        row, total = solve_linear_assignment(res, cost, epsilon=1e-6)
        assert sorted(np.asarray(row).tolist()) == list(range(16))
        scipy_opt = pytest.importorskip("scipy.optimize")
        ri, ci = scipy_opt.linear_sum_assignment(cost)
        assert float(total) == pytest.approx(cost[ri, ci].sum())

    def test_size_one(self, res):
        row, total = solve_linear_assignment(res, np.array([[3.0]]))
        assert int(row[0]) == 0 and float(total) == 3.0

    def test_identity_like(self, res):
        # strongly diagonal-dominant cost -> identity assignment
        n = 10
        cost = np.full((n, n), 100.0, np.float32)
        np.fill_diagonal(cost, 1.0)
        row, total = solve_linear_assignment(res, cost)
        np.testing.assert_array_equal(np.asarray(row), np.arange(n))
        assert float(total) == pytest.approx(n * 1.0)

    @pytest.mark.parametrize("n,seed", [(32, 5), (64, 6), (96, 7)])
    def test_exact_agreement_vs_scipy(self, res, n, seed):
        """VERDICT r4 #9: exact agreement with scipy's Hungarian on float
        costs when eps < spread/n^2 (the n*eps suboptimality bound then
        falls below any realistic assignment gap)."""
        from scipy.optimize import linear_sum_assignment

        rng = np.random.default_rng(seed)
        cost = rng.random((n, n)).astype(np.float32)
        spread = float(cost.max() - cost.min())
        eps = 0.5 * spread / n**2
        row, total = solve_linear_assignment(res, cost, epsilon=eps)
        ri, ci = linear_sum_assignment(cost.astype(np.float64))
        exact = float(cost.astype(np.float64)[ri, ci].sum())
        got = float(cost.astype(np.float64)[np.arange(n),
                                            np.asarray(row)].sum())
        assert got == pytest.approx(exact, abs=5e-5), (got, exact)

    def test_batch_is_one_compiled_program(self, res):
        """The batched solve must not retrace per element: one _solve_batch
        trace covers the whole batch (VERDICT r4 weak #8: the old per-
        element host loop serialized large batches)."""
        import jax

        from raft_tpu.solver import linear_assignment as la

        rng = np.random.default_rng(9)
        costs = rng.random((6, 12, 12)).astype(np.float32)
        traces = []
        orig = la._solve_batch.__wrapped__

        def counting(cost, eps_final, n_phases):
            traces.append(cost.shape)
            return orig(cost, eps_final, n_phases)

        counted = jax.jit(counting, static_argnums=(2,))
        old = la._solve_batch
        la._solve_batch = counted
        try:
            lap = LinearAssignmentProblem(res, 12, 6, epsilon=1e-4)
            rows, cols = lap.solve(costs)
        finally:
            la._solve_batch = old
        assert traces == [(6, 12, 12)]        # one trace, full batch
        from scipy.optimize import linear_sum_assignment
        for b in range(6):
            ri, ci = linear_sum_assignment(costs[b])
            got = float(lap.get_primal_objective_value(b))
            assert got == pytest.approx(float(costs[b][ri, ci].sum()),
                                        abs=1e-3)

    def test_batch_mixed_spreads(self, res):
        """Lanes with wildly different cost scales (and one constant lane)
        share the static epsilon schedule via per-lane clamping."""
        rng = np.random.default_rng(13)
        n = 10
        costs = np.stack([
            rng.random((n, n)).astype(np.float32),          # spread ~1
            rng.random((n, n)).astype(np.float32) * 1e6,    # huge spread
            np.full((n, n), 7.0, np.float32),               # zero spread
            rng.random((n, n)).astype(np.float32) * 1e-4,   # tiny spread
        ])
        lap = LinearAssignmentProblem(res, n, 4, epsilon=1e-6)
        rows, cols = lap.solve(costs)
        from scipy.optimize import linear_sum_assignment
        for b in (0, 1, 3):
            ri, ci = linear_sum_assignment(costs[b].astype(np.float64))
            exact = float(costs[b].astype(np.float64)[ri, ci].sum())
            got = float(lap.get_primal_objective_value(b))
            assert got == pytest.approx(exact, rel=1e-5), b
        # constant lane: identity assignment by convention
        np.testing.assert_array_equal(np.asarray(rows[2]), np.arange(n))

    def test_nan_costs_raise(self, res):
        """A NaN cost lane must raise (not silently return identity), and
        must not stall the program for max_rounds on an all-NaN benefit."""
        rng = np.random.default_rng(17)
        costs = rng.random((3, 8, 8)).astype(np.float32)
        costs[1, 2, 3] = np.nan
        lap = LinearAssignmentProblem(res, 8, 3, epsilon=1e-4)
        with pytest.raises(RuntimeError, match="NaN/inf"):
            lap.solve(costs)


class TestSpectral:
    def test_partition_two_cliques(self, res):
        # two 4-cliques joined by one edge; the natural partition cuts 1 edge
        n = 8
        a = np.zeros((n, n), np.float32)
        for grp in (range(0, 4), range(4, 8)):
            for i in grp:
                for j in grp:
                    if i != j:
                        a[i, j] = 1.0
        a[3, 4] = a[4, 3] = 1.0
        csr = convert.dense_to_csr(a)
        clusters = np.repeat([0, 1], 4)
        edge_cut, cost = spectral.analyze_partition(res, csr, 2, clusters)
        assert float(edge_cut) == pytest.approx(1.0)
        # ratio cut: each side has cut weight 1, size 4 -> 1/4 + 1/4
        assert float(cost) == pytest.approx(0.5)

    def test_modularity_karate(self, res):
        csr, a = karate_csr()
        # ground-truth two-faction split of the karate club
        faction2 = {8, 9, 14, 15, 18, 20, 22, 23, 24, 25, 26, 27, 28, 29,
                    30, 31, 32, 33}
        clusters = np.array([1 if i in faction2 else 0 for i in range(34)])
        q = float(spectral.analyze_modularity(res, csr, 2, clusters))
        # the true faction split has strong positive modularity
        assert 0.3 < q < 0.45
        # reference numpy computation
        deg = a.sum(1)
        two_m = deg.sum()
        b = a - np.outer(deg, deg) / two_m
        h = np.eye(2)[clusters]
        expect = np.trace(h.T @ b @ h) / two_m
        assert q == pytest.approx(expect, rel=1e-5)

    def test_modularity_single_cluster_zero(self, res):
        csr, _ = karate_csr()
        q = float(spectral.analyze_modularity(res, csr, 1,
                                              np.zeros(34, np.int32)))
        assert q == pytest.approx(0.0, abs=1e-6)

    def test_partition_matches_numpy_laplacian(self, res):
        csr, a = karate_csr()
        rng = np.random.default_rng(0)
        clusters = rng.integers(0, 3, size=34)
        edge_cut, cost = spectral.analyze_partition(res, csr, 3, clusters)
        lap = np.diag(a.sum(1)) - a
        h = np.eye(3)[clusters]
        quad = np.diag(h.T @ lap @ h)
        sizes = h.sum(0)
        np.testing.assert_allclose(float(edge_cut), quad.sum() / 2,
                                   rtol=1e-5)
        np.testing.assert_allclose(float(cost), (quad / sizes).sum(),
                                   rtol=1e-5)
