"""Native C++ host runtime tests (ref models: cpp/tests/core/
allocation_tracking.cpp, monitor_resources.cu, numpy_serializer.cu,
interruptible.cu)."""

import os
import time

import numpy as np
import pytest

from raft_tpu.core import native_runtime as nr

pytestmark = pytest.mark.skipif(
    not nr.native_available(),
    reason="no C++ toolchain for the native runtime")


class TestTrackedHostPool:
    def test_alloc_stats_release(self):
        pool = nr.TrackedHostPool()
        try:
            a = pool.allocate((100, 10), np.float32)
            a[:] = 2.0
            s = pool.stats()
            assert s["bytes_allocated"] == 4000
            assert s["n_allocations"] == 1
            b = pool.allocate((50,), np.float64)
            assert pool.stats()["bytes_allocated"] == 4400
            assert pool.stats()["peak_bytes"] == 4400
            pool.release(a)
            pool.release(b)
            s = pool.stats()
            assert s["bytes_allocated"] == 0
            assert s["n_deallocations"] == 2
            assert s["peak_bytes"] == 4400
        finally:
            pool.close()

    def test_mmap_pool(self):
        pool = nr.TrackedHostPool(use_mmap=True)
        try:
            a = pool.allocate((1 << 16,), np.uint8)
            a[:] = 7
            assert int(a.sum()) == 7 * (1 << 16)
            pool.release(a)
        finally:
            pool.close()

    def test_notify_hook(self):
        pool = nr.TrackedHostPool()
        try:
            events = []
            pool.set_notify(lambda is_alloc, n: events.append((is_alloc, n)))
            a = pool.allocate((10,), np.int32)
            pool.release(a)
            assert events == [(True, 40), (False, 40)]
        finally:
            pool.close()


class TestResourceMonitor:
    def test_csv_sampling_with_tags(self, tmp_path):
        pool = nr.TrackedHostPool()
        csv = str(tmp_path / "mon.csv")
        try:
            mon = nr.NativeResourceMonitor(pool, csv, interval_ms=5)
            mon.set_tag("warmup")
            a = pool.allocate((1024,), np.float32)
            time.sleep(0.03)
            mon.set_tag("steady")
            time.sleep(0.03)
            mon.stop()
            lines = open(csv).read().strip().split("\n")
            assert lines[0].startswith("timestamp_us,tag")
            assert any(",warmup," in ln for ln in lines[1:])
            assert any(",steady," in ln for ln in lines[1:])
            pool.release(a)
        finally:
            pool.close()


class TestNpySerializer:
    @pytest.mark.parametrize("dtype", [np.float32, np.float64, np.int32,
                                       np.int64, np.uint8, np.bool_])
    def test_roundtrip_vs_numpy(self, tmp_path, dtype):
        rng = np.random.default_rng(0)
        x = (rng.normal(size=(5, 3, 2)) * 10).astype(dtype)
        p = str(tmp_path / "x.npy")
        nr.npy_save(p, x)
        np.testing.assert_array_equal(np.load(p), x)       # numpy reads ours
        np.testing.assert_array_equal(nr.npy_load(p), x)   # we read ours
        p2 = str(tmp_path / "y.npy")
        np.save(p2, x)
        np.testing.assert_array_equal(nr.npy_load(p2), x)  # we read numpy's

    def test_scalar_and_1d(self, tmp_path):
        p = str(tmp_path / "v.npy")
        v = np.arange(7, dtype=np.int32)
        nr.npy_save(p, v)
        np.testing.assert_array_equal(np.load(p), v)


class TestThreadPool:
    def test_parallel_copy(self):
        tp = nr.NativeThreadPool(4)
        try:
            src = np.random.default_rng(1).normal(
                size=(1 << 18,)).astype(np.float32)
            dst = np.empty_like(src)
            tp.parallel_copy(dst, src, chunk_bytes=1 << 15)
            np.testing.assert_array_equal(dst, src)
        finally:
            tp.close()


class TestNativeInterruptible:
    def test_cancel_check_consumes(self):
        assert not nr.native_check_cancelled()
        nr.native_cancel()
        assert nr.native_check_cancelled()
        assert not nr.native_check_cancelled()
