"""Checkpoint container tests (ISSUE 2 tentpole part 3).

Covers the v1 binary format (round-trip of every entry kind, CRC
corruption detection, version gating), the atomic-save contract, the
retention manager, and the COMMITTED format fixture
(tests/data/checkpoint_v1.ckpt): readers must keep loading v1 bytes
produced before any future change — the format is frozen, changes bump
the version.
"""

import io
import os
import struct

import numpy as np
import pytest

from raft_tpu.core import checkpoint as ckpt
from raft_tpu.core.checkpoint import (
    CheckpointCorruptError,
    CheckpointManager,
    CheckpointVersionError,
    dump_checkpoint,
    load_checkpoint,
    restore_checkpoint,
    save_checkpoint,
)
from raft_tpu.random.rng_state import GeneratorType, RngState

_FIXTURE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "data", "checkpoint_v1.ckpt")


def _sample_entries():
    import ml_dtypes

    return {
        "centroids": np.arange(12, dtype=np.float32).reshape(3, 4) / 7.0,
        "t": np.linspace(-1.0, 1.0, 9, dtype=np.float64).reshape(3, 3),
        "mask": np.array([True, False, True]),
        "soft": np.arange(8, dtype=np.float32).astype(ml_dtypes.bfloat16),
        "n_iter": 17,
        "prev_inertia": 123.4375,
        "label": np.int64(-5),
        "rng": RngState(seed=99, base_subsequence=3,
                        type=GeneratorType.RBG),
    }


class TestContainer:
    def test_round_trip_all_kinds(self):
        buf = io.BytesIO()
        entries = _sample_entries()
        dump_checkpoint(entries, buf)
        buf.seek(0)
        out = load_checkpoint(buf)
        assert set(out) == set(entries)
        np.testing.assert_array_equal(out["centroids"],
                                      entries["centroids"])
        assert out["t"].dtype == np.float64
        np.testing.assert_array_equal(out["t"], entries["t"])
        np.testing.assert_array_equal(out["mask"], entries["mask"])
        assert out["soft"].dtype.name == "bfloat16"
        np.testing.assert_array_equal(
            out["soft"].astype(np.float32),
            entries["soft"].astype(np.float32))
        # scalars come back as NATIVE python values (serialize satellite)
        assert out["n_iter"] == 17 and type(out["n_iter"]) is int
        assert out["prev_inertia"] == 123.4375
        assert type(out["prev_inertia"]) is float
        assert out["label"] == -5 and type(out["label"]) is int
        rng = out["rng"]
        assert isinstance(rng, RngState)
        assert (rng.seed, rng.base_subsequence, rng.type) == (
            99, 3, GeneratorType.RBG)

    def test_bad_magic_rejected(self):
        with pytest.raises(CheckpointCorruptError, match="magic"):
            load_checkpoint(io.BytesIO(b"NOTRAFT1" + b"\0" * 8))

    def test_future_version_rejected(self):
        buf = io.BytesIO()
        buf.write(struct.pack("<8sII", ckpt.MAGIC, ckpt.VERSION + 1, 0))
        buf.seek(0)
        with pytest.raises(CheckpointVersionError):
            load_checkpoint(buf)

    def test_truncation_detected(self):
        buf = io.BytesIO()
        dump_checkpoint({"a": np.arange(100.0)}, buf)
        raw = buf.getvalue()
        with pytest.raises(CheckpointCorruptError, match="truncated"):
            load_checkpoint(io.BytesIO(raw[:-10]))

    def test_bitflip_detected_by_crc(self):
        buf = io.BytesIO()
        dump_checkpoint({"a": np.arange(100.0)}, buf)
        raw = bytearray(buf.getvalue())
        raw[len(raw) // 2] ^= 0x01          # damage the payload
        with pytest.raises(CheckpointCorruptError, match="crc"):
            load_checkpoint(io.BytesIO(bytes(raw)))


class TestSaveRestore:
    def test_atomic_save_leaves_no_tmp(self, tmp_path):
        path = tmp_path / "state.ckpt"
        save_checkpoint(path, {"x": np.ones(4)})
        assert sorted(os.listdir(tmp_path)) == ["state.ckpt"]
        out = restore_checkpoint(path)
        np.testing.assert_array_equal(out["x"], np.ones(4))

    def test_overwrite_replaces_whole_file(self, tmp_path):
        path = tmp_path / "state.ckpt"
        save_checkpoint(path, {"x": np.ones(1000)})
        save_checkpoint(path, {"x": np.zeros(2)})
        out = restore_checkpoint(path)
        np.testing.assert_array_equal(out["x"], np.zeros(2))


class TestManager:
    def test_latest_and_retention(self, tmp_path):
        mgr = CheckpointManager(tmp_path, prefix="km", keep=2)
        for step in (2, 4, 6):
            mgr.save(step, {"s": float(step)})
        assert mgr.steps() == [4, 6]        # keep=2 pruned step 2
        step, entries = mgr.restore_latest()
        assert step == 6 and entries["s"] == 6.0

    def test_empty_dir_latest_is_none(self, tmp_path):
        mgr = CheckpointManager(tmp_path, prefix="km")
        assert mgr.latest() is None
        assert mgr.restore_latest() is None

    def test_foreign_files_ignored(self, tmp_path):
        (tmp_path / "km-notastep.ckpt").write_bytes(b"junk")
        (tmp_path / "other-00000001.ckpt").write_bytes(b"junk")
        mgr = CheckpointManager(tmp_path, prefix="km", keep=1)
        mgr.save(3, {"s": 3.0})
        assert mgr.steps() == [3]

    def test_keep_validated(self, tmp_path):
        with pytest.raises(ValueError):
            CheckpointManager(tmp_path, keep=0)


class TestFrozenFixture:
    """The committed v1 artifact must load forever (ci/smoke.sh checks
    this too); regenerating it instead of bumping VERSION is a format
    break."""

    def test_fixture_loads(self):
        out = restore_checkpoint(_FIXTURE)
        ref = _sample_entries()
        assert set(out) == set(ref)
        np.testing.assert_array_equal(out["centroids"], ref["centroids"])
        np.testing.assert_array_equal(out["t"], ref["t"])
        np.testing.assert_array_equal(
            out["soft"].astype(np.float32),
            ref["soft"].astype(np.float32))
        assert out["n_iter"] == 17
        assert out["rng"].type == GeneratorType.RBG
