"""raftlint: per-rule positive/negative fixtures, baseline semantics,
and the shipped-baseline-matches-tree self-check.

Fixture trees are written under tmp_path as a package named
``raft_tpu`` because most rules scope themselves to the real package
name (R4's taxonomy, R5's helper table, R6's obs boundary, R7's env
registry, R8's numeric scopes).
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import textwrap
from pathlib import Path

import pytest

from tools.raftlint import cli
from tools.raftlint.core import Project

REPO_ROOT = Path(__file__).resolve().parents[1]


def write_tree(root: Path, files: dict) -> None:
    for rel, src in files.items():
        path = root / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(src), encoding="utf-8")
        # every package dir needs an __init__ for dotted modnames
        d = path.parent
        while d != root:
            init = d / "__init__.py"
            if not init.exists():
                init.write_text("", encoding="utf-8")
            d = d.parent


def lint(root: Path, files: dict, *, rules=None) -> list:
    """Scan a fixture tree and return findings (optionally one rule)."""
    write_tree(root, files)
    project = Project(str(root))
    project.scan(["raft_tpu"])
    assert not project.errors, project.errors
    return cli.run_rules(project, {rules} if isinstance(rules, str)
                         else rules)


def rule_ids(findings) -> set:
    return {f.rule for f in findings}


def run_cli(root: Path, *argv) -> tuple:
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf), \
            contextlib.redirect_stderr(buf):
        code = cli.main(["raft_tpu", "--root", str(root), *argv])
    return code, buf.getvalue()


# ---------------------------------------------------------------------------
# R1: jit purity


def test_r1_flags_numpy_in_jit_body(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/a.py": """
        import jax
        import numpy as np

        @jax.jit
        def f(x):
            return np.sin(x)
    """}, rules="R1")
    assert rule_ids(findings) == {"R1"}
    assert findings[0].symbol == "raft_tpu.a:f"


def test_r1_follows_the_call_graph(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/a.py": """
        import jax
        import numpy as np

        def helper(x):
            return float(np.sum(x))

        @jax.jit
        def f(x):
            return helper(x)
    """}, rules="R1")
    assert any(f.symbol == "raft_tpu.a:helper" for f in findings)


def test_r1_clean_jnp_and_static_branching_pass(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/a.py": """
        from functools import partial

        import jax
        import jax.numpy as jnp

        @partial(jax.jit, static_argnames=("n",))
        def f(x, n):
            if n > 4:          # static arg: host branching is fine
                return jnp.sin(x)
            return jnp.cos(x)
    """}, rules="R1")
    assert findings == []


def test_r1_host_branch_on_traced_param(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/a.py": """
        import jax

        @jax.jit
        def f(x):
            if x:
                return x
            return -x
    """}, rules="R1")
    assert rule_ids(findings) == {"R1"}


# ---------------------------------------------------------------------------
# R2: recompile hazards


def test_r2_flags_jit_of_local_def(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/a.py": """
        import jax

        def call(x):
            def inner(y):
                return y * 2
            return jax.jit(inner)(x)
    """}, rules="R2")
    assert rule_ids(findings) == {"R2"}


def test_r2_module_level_jit_and_lru_cache_pass(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/a.py": """
        import functools

        import jax

        def _impl(y):
            return y * 2

        g = jax.jit(_impl)

        @functools.lru_cache(maxsize=None)
        def build(n):
            def inner(y):
                return y * n
            return jax.jit(inner)
    """}, rules="R2")
    assert findings == []


# ---------------------------------------------------------------------------
# R3: lock discipline


LOCKED_CLASS = """
    import threading

    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self.count = 0

        def bump(self):
            %s
"""


def test_r3_flags_unlocked_field_write(tmp_path):
    findings = lint(tmp_path, {
        "raft_tpu/a.py": LOCKED_CLASS % "self.count += 1"},
        rules="R3")
    assert rule_ids(findings) == {"R3"}
    assert findings[0].symbol == "raft_tpu.a:Box.bump"


def test_r3_locked_write_passes(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/a.py": LOCKED_CLASS % (
        "with self._lock:\n                self.count += 1")},
        rules="R3")
    assert findings == []


def test_r3_private_helper_called_only_under_lock_passes(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/a.py": """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0

            def bump(self):
                with self._lock:
                    self._inc()

            def _inc(self):
                self.count += 1
    """}, rules="R3")
    assert findings == []


def test_r3_lock_order_cycle(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/a.py": """
        import threading

        class Two:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    with self._b:
                        pass

            def two(self):
                with self._b:
                    with self._a:
                        pass
    """}, rules="R3")
    assert any("order cycle" in f.message for f in findings)


# ---------------------------------------------------------------------------
# R4: typed-error taxonomy


def test_r4_flags_untyped_raise_and_broad_except(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/a.py": """
        def f():
            raise RuntimeError("boom")

        def g():
            try:
                f()
            except Exception:
                return None

        def h():
            try:
                f()
            except ValueError:
                pass
    """}, rules="R4")
    assert len(findings) == 3
    assert rule_ids(findings) == {"R4"}


def test_r4_typed_raise_and_narrow_except_pass(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/a.py": """
        import contextlib

        class CommsError(RuntimeError):
            pass

        def f():
            raise CommsError("peer died")

        def g():
            try:
                f()
            except CommsError:
                return None
            with contextlib.suppress(ValueError):
                f()
    """}, rules="R4")
    assert findings == []


# ---------------------------------------------------------------------------
# R5: off-path purity of the obs emit helpers


def test_r5_flags_helper_without_leading_gate(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/obs/metrics.py": """
        _enabled = False

        def inc(name, value=1, **labels):
            key = (name, tuple(sorted(labels.items())))
            if not _enabled:
                return
    """}, rules="R5")
    assert any(f.symbol == "raft_tpu.obs.metrics:inc" for f in findings)


def test_r5_leading_gate_passes(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/obs/metrics.py": """
        _enabled = False

        def inc(name, value=1, **labels):
            if not _enabled:
                return
            key = (name, tuple(sorted(labels.items())))
    """}, rules="R5")
    assert not any(f.symbol == "raft_tpu.obs.metrics:inc"
                   for f in findings)


# ---------------------------------------------------------------------------
# R6: obs API boundary


def test_r6_flags_submodule_import_outside_obs(tmp_path):
    findings = lint(tmp_path, {
        "raft_tpu/obs/metrics.py": "def inc(*a, **k):\n    pass\n",
        "raft_tpu/solver.py": """
            from raft_tpu.obs.metrics import inc

            def f():
                inc("x")
        """}, rules="R6")
    assert rule_ids(findings) == {"R6"}
    assert findings[0].path.endswith("solver.py")


def test_r6_facade_import_and_intra_obs_pass(tmp_path):
    findings = lint(tmp_path, {
        "raft_tpu/obs/metrics.py": "def inc(*a, **k):\n    pass\n",
        "raft_tpu/obs/export.py": (
            "from raft_tpu.obs import metrics\n"),
        "raft_tpu/solver.py": """
            from raft_tpu import obs

            def f():
                obs.inc("x")
        """}, rules="R6")
    assert findings == []


# ---------------------------------------------------------------------------
# R7: env knobs go through the registry


def test_r7_flags_direct_env_read(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/a.py": """
        import os

        FLAG = os.getenv("RAFT_TPU_FLAG", "0")
        OTHER = os.environ.get("RAFT_TPU_OTHER")
        THIRD = os.environ["RAFT_TPU_THIRD"]
    """}, rules="R7")
    assert len(findings) == 3
    assert rule_ids(findings) == {"R7"}


def test_r7_registry_module_and_foreign_vars_pass(tmp_path):
    findings = lint(tmp_path, {
        "raft_tpu/core/env.py": """
            import os

            def read(name):
                return os.environ.get(name)
        """,
        "raft_tpu/a.py": """
            import os

            HOME = os.environ.get("HOME")
        """}, rules="R7")
    assert findings == []


# ---------------------------------------------------------------------------
# R8: annotated numerical breakdown sites


def test_r8_flags_unguarded_sqrt_in_numeric_scope(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/linalg/a.py": """
        import jax.numpy as jnp

        def f(x):
            return jnp.sqrt(x)
    """}, rules="R8")
    assert rule_ids(findings) == {"R8"}


def test_r8_guard_token_on_line_passes(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/linalg/a.py": """
        import jax.numpy as jnp

        def f(x):
            return jnp.sqrt(jnp.maximum(x, 0.0))
    """}, rules="R8")
    assert findings == []


# ---------------------------------------------------------------------------
# baseline semantics (via the CLI)


VIOLATION = {"raft_tpu/a.py": "def f():\n    raise RuntimeError('x')\n"}


def test_cli_exit_codes_and_baseline_waiver(tmp_path):
    write_tree(tmp_path, VIOLATION)
    bl = tmp_path / "bl.json"

    code, out = run_cli(tmp_path, "--baseline", str(bl))
    assert code == 1 and "R4" in out

    bl.write_text(json.dumps({"version": 1, "entries": [{
        "rule": "R4", "file": "raft_tpu/a.py",
        "symbol": "raft_tpu.a:f", "why": "fixture"}]}))
    code, out = run_cli(tmp_path, "--baseline", str(bl))
    assert code == 0 and "1 waived" in out

    # --no-baseline reports the full debt regardless
    code, out = run_cli(tmp_path, "--baseline", str(bl),
                        "--no-baseline")
    assert code == 1 and "R4" in out


def test_stale_baseline_entry_fails(tmp_path):
    write_tree(tmp_path, {"raft_tpu/a.py": "def f():\n    return 1\n"})
    bl = tmp_path / "bl.json"
    bl.write_text(json.dumps({"version": 1, "entries": [{
        "rule": "R4", "file": "raft_tpu/a.py",
        "symbol": "raft_tpu.a:f", "why": "paid off"}]}))
    code, out = run_cli(tmp_path, "--baseline", str(bl))
    assert code == 1 and "stale" in out


def test_baseline_rejects_per_line_waivers(tmp_path):
    write_tree(tmp_path, VIOLATION)
    bl = tmp_path / "bl.json"
    bl.write_text(json.dumps({"version": 1, "entries": [{
        "rule": "R4", "file": "raft_tpu/a.py",
        "symbol": "raft_tpu.a:f", "why": "x", "line": 2}]}))
    code, out = run_cli(tmp_path, "--baseline", str(bl))
    assert code == 2 and "never per line" in out


def test_write_baseline_emits_todo_whys(tmp_path):
    write_tree(tmp_path, VIOLATION)
    bl = tmp_path / "bl.json"
    code, _ = run_cli(tmp_path, "--write-baseline", str(bl))
    assert code == 0
    doc = json.loads(bl.read_text())
    assert doc["entries"][0]["symbol"] == "raft_tpu.a:f"
    assert "TODO" in doc["entries"][0]["why"]


def test_unknown_rule_id_is_a_usage_error(tmp_path):
    write_tree(tmp_path, VIOLATION)
    code, out = run_cli(tmp_path, "--rules", "R99")
    assert code == 2 and "unknown rule" in out


# ---------------------------------------------------------------------------
# the shipped tree and baseline agree exactly


def test_shipped_tree_is_clean_under_shipped_baseline():
    """No new findings AND no stale entries: the checked-in baseline is
    an exact inventory of the tree's remaining debt."""
    code, out = run_cli(REPO_ROOT)
    assert code == 0, out
    assert "0 new finding(s)" in out
    assert "0 stale" in out


def test_shipped_baseline_entries_all_carry_real_whys():
    doc = json.loads(
        (REPO_ROOT / "tools/raftlint/baseline.json").read_text())
    for e in doc["entries"]:
        assert e["why"] and "TODO" not in e["why"], e
        assert "line" not in e, e
