"""raftlint: per-rule positive/negative fixtures, baseline semantics,
and the shipped-baseline-matches-tree self-check.

Fixture trees are written under tmp_path as a package named
``raft_tpu`` because most rules scope themselves to the real package
name (R4's taxonomy, R5's helper table, R6's obs boundary, R7's env
registry, R8's numeric scopes).
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import textwrap
from pathlib import Path

import pytest

from tools.raftlint import cli
from tools.raftlint.core import Project

REPO_ROOT = Path(__file__).resolve().parents[1]


def write_tree(root: Path, files: dict) -> None:
    for rel, src in files.items():
        path = root / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(src), encoding="utf-8")
        # every package dir needs an __init__ for dotted modnames
        d = path.parent
        while d != root:
            init = d / "__init__.py"
            if not init.exists():
                init.write_text("", encoding="utf-8")
            d = d.parent


def lint(root: Path, files: dict, *, rules=None) -> list:
    """Scan a fixture tree and return findings (optionally one rule)."""
    write_tree(root, files)
    project = Project(str(root))
    project.scan(["raft_tpu"])
    assert not project.errors, project.errors
    return cli.run_rules(project, {rules} if isinstance(rules, str)
                         else rules)


def rule_ids(findings) -> set:
    return {f.rule for f in findings}


def run_cli(root: Path, *argv) -> tuple:
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf), \
            contextlib.redirect_stderr(buf):
        code = cli.main(["raft_tpu", "--root", str(root), *argv])
    return code, buf.getvalue()


# ---------------------------------------------------------------------------
# R1: jit purity


def test_r1_flags_numpy_in_jit_body(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/a.py": """
        import jax
        import numpy as np

        @jax.jit
        def f(x):
            return np.sin(x)
    """}, rules="R1")
    assert rule_ids(findings) == {"R1"}
    assert findings[0].symbol == "raft_tpu.a:f"


def test_r1_follows_the_call_graph(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/a.py": """
        import jax
        import numpy as np

        def helper(x):
            return float(np.sum(x))

        @jax.jit
        def f(x):
            return helper(x)
    """}, rules="R1")
    assert any(f.symbol == "raft_tpu.a:helper" for f in findings)


def test_r1_clean_jnp_and_static_branching_pass(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/a.py": """
        from functools import partial

        import jax
        import jax.numpy as jnp

        @partial(jax.jit, static_argnames=("n",))
        def f(x, n):
            if n > 4:          # static arg: host branching is fine
                return jnp.sin(x)
            return jnp.cos(x)
    """}, rules="R1")
    assert findings == []


def test_r1_host_branch_on_traced_param(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/a.py": """
        import jax

        @jax.jit
        def f(x):
            if x:
                return x
            return -x
    """}, rules="R1")
    assert rule_ids(findings) == {"R1"}


# ---------------------------------------------------------------------------
# R2: recompile hazards


def test_r2_flags_jit_of_local_def(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/a.py": """
        import jax

        def call(x):
            def inner(y):
                return y * 2
            return jax.jit(inner)(x)
    """}, rules="R2")
    assert rule_ids(findings) == {"R2"}


def test_r2_module_level_jit_and_lru_cache_pass(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/a.py": """
        import functools

        import jax

        def _impl(y):
            return y * 2

        g = jax.jit(_impl)

        @functools.lru_cache(maxsize=None)
        def build(n):
            def inner(y):
                return y * n
            return jax.jit(inner)
    """}, rules="R2")
    assert findings == []


# ---------------------------------------------------------------------------
# R3: lock discipline


LOCKED_CLASS = """
    import threading

    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self.count = 0

        def bump(self):
            %s
"""


def test_r3_flags_unlocked_field_write(tmp_path):
    findings = lint(tmp_path, {
        "raft_tpu/a.py": LOCKED_CLASS % "self.count += 1"},
        rules="R3")
    assert rule_ids(findings) == {"R3"}
    assert findings[0].symbol == "raft_tpu.a:Box.bump"


def test_r3_locked_write_passes(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/a.py": LOCKED_CLASS % (
        "with self._lock:\n                self.count += 1")},
        rules="R3")
    assert findings == []


def test_r3_private_helper_called_only_under_lock_passes(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/a.py": """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0

            def bump(self):
                with self._lock:
                    self._inc()

            def _inc(self):
                self.count += 1
    """}, rules="R3")
    assert findings == []


def test_r3_lock_order_cycle(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/a.py": """
        import threading

        class Two:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    with self._b:
                        pass

            def two(self):
                with self._b:
                    with self._a:
                        pass
    """}, rules="R3")
    assert any("order cycle" in f.message for f in findings)


# ---------------------------------------------------------------------------
# R4: typed-error taxonomy


def test_r4_flags_untyped_raise_and_broad_except(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/a.py": """
        def f():
            raise RuntimeError("boom")

        def g():
            try:
                f()
            except Exception:
                return None

        def h():
            try:
                f()
            except ValueError:
                pass
    """}, rules="R4")
    assert len(findings) == 3
    assert rule_ids(findings) == {"R4"}


def test_r4_typed_raise_and_narrow_except_pass(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/a.py": """
        import contextlib

        class CommsError(RuntimeError):
            pass

        def f():
            raise CommsError("peer died")

        def g():
            try:
                f()
            except CommsError:
                return None
            with contextlib.suppress(ValueError):
                f()
    """}, rules="R4")
    assert findings == []


# ---------------------------------------------------------------------------
# R5: off-path purity of the obs emit helpers


def test_r5_flags_helper_without_leading_gate(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/obs/metrics.py": """
        _enabled = False

        def inc(name, value=1, **labels):
            key = (name, tuple(sorted(labels.items())))
            if not _enabled:
                return
    """}, rules="R5")
    assert any(f.symbol == "raft_tpu.obs.metrics:inc" for f in findings)


def test_r5_leading_gate_passes(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/obs/metrics.py": """
        _enabled = False

        def inc(name, value=1, **labels):
            if not _enabled:
                return
            key = (name, tuple(sorted(labels.items())))
    """}, rules="R5")
    assert not any(f.symbol == "raft_tpu.obs.metrics:inc"
                   for f in findings)


# ---------------------------------------------------------------------------
# R6: obs API boundary


def test_r6_flags_submodule_import_outside_obs(tmp_path):
    findings = lint(tmp_path, {
        "raft_tpu/obs/metrics.py": "def inc(*a, **k):\n    pass\n",
        "raft_tpu/solver.py": """
            from raft_tpu.obs.metrics import inc

            def f():
                inc("x")
        """}, rules="R6")
    assert rule_ids(findings) == {"R6"}
    assert findings[0].path.endswith("solver.py")


def test_r6_facade_import_and_intra_obs_pass(tmp_path):
    findings = lint(tmp_path, {
        "raft_tpu/obs/metrics.py": "def inc(*a, **k):\n    pass\n",
        "raft_tpu/obs/export.py": (
            "from raft_tpu.obs import metrics\n"),
        "raft_tpu/solver.py": """
            from raft_tpu import obs

            def f():
                obs.inc("x")
        """}, rules="R6")
    assert findings == []


# ---------------------------------------------------------------------------
# R7: env knobs go through the registry


def test_r7_flags_direct_env_read(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/a.py": """
        import os

        FLAG = os.getenv("RAFT_TPU_FLAG", "0")
        OTHER = os.environ.get("RAFT_TPU_OTHER")
        THIRD = os.environ["RAFT_TPU_THIRD"]
    """}, rules="R7")
    assert len(findings) == 3
    assert rule_ids(findings) == {"R7"}


def test_r7_registry_module_and_foreign_vars_pass(tmp_path):
    findings = lint(tmp_path, {
        "raft_tpu/core/env.py": """
            import os

            def read(name):
                return os.environ.get(name)
        """,
        "raft_tpu/a.py": """
            import os

            HOME = os.environ.get("HOME")
        """}, rules="R7")
    assert findings == []


# ---------------------------------------------------------------------------
# R8: annotated numerical breakdown sites


def test_r8_flags_unguarded_sqrt_in_numeric_scope(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/linalg/a.py": """
        import jax.numpy as jnp

        def f(x):
            return jnp.sqrt(x)
    """}, rules="R8")
    assert rule_ids(findings) == {"R8"}


def test_r8_guard_token_on_line_passes(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/linalg/a.py": """
        import jax.numpy as jnp

        def f(x):
            return jnp.sqrt(jnp.maximum(x, 0.0))
    """}, rules="R8")
    assert findings == []


# ---------------------------------------------------------------------------
# baseline semantics (via the CLI)


VIOLATION = {"raft_tpu/a.py": "def f():\n    raise RuntimeError('x')\n"}


def test_cli_exit_codes_and_baseline_waiver(tmp_path):
    write_tree(tmp_path, VIOLATION)
    bl = tmp_path / "bl.json"

    code, out = run_cli(tmp_path, "--baseline", str(bl))
    assert code == 1 and "R4" in out

    bl.write_text(json.dumps({"version": 1, "entries": [{
        "rule": "R4", "file": "raft_tpu/a.py",
        "symbol": "raft_tpu.a:f", "why": "fixture"}]}))
    code, out = run_cli(tmp_path, "--baseline", str(bl))
    assert code == 0 and "1 waived" in out

    # --no-baseline reports the full debt regardless
    code, out = run_cli(tmp_path, "--baseline", str(bl),
                        "--no-baseline")
    assert code == 1 and "R4" in out


def test_stale_baseline_entry_fails(tmp_path):
    write_tree(tmp_path, {"raft_tpu/a.py": "def f():\n    return 1\n"})
    bl = tmp_path / "bl.json"
    bl.write_text(json.dumps({"version": 1, "entries": [{
        "rule": "R4", "file": "raft_tpu/a.py",
        "symbol": "raft_tpu.a:f", "why": "paid off"}]}))
    code, out = run_cli(tmp_path, "--baseline", str(bl))
    assert code == 1 and "stale" in out


def test_baseline_rejects_per_line_waivers(tmp_path):
    write_tree(tmp_path, VIOLATION)
    bl = tmp_path / "bl.json"
    bl.write_text(json.dumps({"version": 1, "entries": [{
        "rule": "R4", "file": "raft_tpu/a.py",
        "symbol": "raft_tpu.a:f", "why": "x", "line": 2}]}))
    code, out = run_cli(tmp_path, "--baseline", str(bl))
    assert code == 2 and "never per line" in out


def test_write_baseline_emits_todo_whys(tmp_path):
    write_tree(tmp_path, VIOLATION)
    bl = tmp_path / "bl.json"
    code, _ = run_cli(tmp_path, "--write-baseline", str(bl))
    assert code == 0
    doc = json.loads(bl.read_text())
    assert doc["entries"][0]["symbol"] == "raft_tpu.a:f"
    assert "TODO" in doc["entries"][0]["why"]


def test_unknown_rule_id_is_a_usage_error(tmp_path):
    write_tree(tmp_path, VIOLATION)
    code, out = run_cli(tmp_path, "--rules", "R99")
    assert code == 2 and "unknown rule" in out


# ---------------------------------------------------------------------------
# the shipped tree and baseline agree exactly


def test_shipped_tree_is_clean_under_shipped_baseline():
    """No new findings AND no stale entries: the checked-in baseline is
    an exact inventory of the tree's remaining debt."""
    code, out = run_cli(REPO_ROOT)
    assert code == 0, out
    assert "0 new finding(s)" in out
    assert "0 stale" in out


def test_shipped_baseline_entries_all_carry_real_whys():
    doc = json.loads(
        (REPO_ROOT / "tools/raftlint/baseline.json").read_text())
    for e in doc["entries"]:
        assert e["why"] and "TODO" not in e["why"], e
        assert "line" not in e, e


# ---------------------------------------------------------------------------
# R10: donation safety (dataflow engine)


def test_r10_flags_use_after_donate(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/a.py": """
        import functools

        import jax

        @functools.partial(jax.jit, donate_argnums=(0,))
        def consume(buf, delta):
            return buf + delta

        def step(buf, delta):
            out = consume(buf, delta)
            return out + buf.sum()
    """}, rules="R10")
    assert rule_ids(findings) == {"R10"}
    assert "read after being donated" in findings[0].message
    assert findings[0].symbol == "raft_tpu.a:step"


def test_r10_rebound_result_is_clean(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/a.py": """
        import functools

        import jax

        @functools.partial(jax.jit, donate_argnums=(0,))
        def consume(buf, delta):
            return buf + delta

        def step(buf, delta):
            buf = consume(buf, delta)
            return buf.sum()
    """}, rules="R10")
    assert findings == []


def test_r10_resolves_jit_wrap_through_a_variable(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/a.py": """
        import jax

        def body(buf, d):
            return buf + d

        run = jax.jit(body, donate_argnums=(0,))

        def step(buf, d):
            out = run(buf, d)
            return out + buf.sum()
    """}, rules="R10")
    assert rule_ids(findings) == {"R10"}


def test_r10_flags_stale_loop_carry(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/a.py": """
        import functools

        import jax

        @functools.partial(jax.jit, donate_argnums=(0,))
        def consume(buf, delta):
            return buf + delta

        def steps(buf, deltas):
            acc = 0.0
            for d in deltas:
                acc = acc + consume(buf, d)
            return acc
    """}, rules="R10")
    assert any("inside a loop" in f.message for f in findings)


def test_r10_per_iteration_buffer_in_loop_is_clean(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/a.py": """
        import functools

        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit, donate_argnums=(0,))
        def consume(buf, delta):
            return buf + delta

        def steps(deltas):
            acc = 0.0
            for d in deltas:
                buf = jnp.zeros((8,))
                acc = acc + consume(buf, d)
            return acc
    """}, rules="R10")
    assert findings == []


def test_r10_flags_vacuous_donation(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/a.py": """
        import functools

        import jax

        @functools.partial(jax.jit, donate_argnums=(1,))
        def f(x, scratch):
            return x * 2
    """}, rules="R10")
    assert any("never consumes" in f.message for f in findings)


def test_r10_variable_donate_position_stays_silent(tmp_path):
    # a branch-dependent donate position is unknowable statically; the
    # rule must not guess (kmeans' weighted/unweighted chunk builder)
    findings = lint(tmp_path, {"raft_tpu/a.py": """
        import jax

        def body(a, b, c):
            return a + b + c

        def build(weighted):
            donate = 2 if weighted else 1
            run = jax.jit(body, donate_argnums=(donate,))
            def step(a, b, c):
                out = run(a, b, c)
                return out + b.sum() + c.sum()
            return step
    """}, rules="R10")
    assert findings == []


# ---------------------------------------------------------------------------
# R11: collective discipline


def test_r11_flags_axis_outside_mesh_scope(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/a.py": """
        import jax

        def body(x):
            return jax.lax.psum(x, "rows")

        def run(x, devs):
            mesh = jax.sharding.Mesh(devs, axis_names=("data",))
            mapped = jax.shard_map(body, mesh=mesh, in_specs=None,
                                   out_specs=None)
            return mapped(x)
    """}, rules="R11")
    assert rule_ids(findings) == {"R11"}
    assert "'rows'" in findings[0].message


def test_r11_bound_axis_and_nested_meshes_are_clean(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/a.py": """
        import jax

        def inner(x):
            return jax.lax.psum(x, "model")

        def outer(x, devs):
            sub = jax.sharding.Mesh(devs, axis_names=("model",))
            return jax.shard_map(inner, mesh=sub, in_specs=None,
                                 out_specs=None)(x)

        def body(x, devs):
            y = jax.lax.psum(x, "data")
            return outer(y, devs)

        def run(x, devs):
            mesh = jax.sharding.Mesh(devs, axis_names=("data",))
            mapped = jax.shard_map(body, mesh=mesh, in_specs=None,
                                   out_specs=None)
            return mapped(x, devs)
    """}, rules="R11")
    assert findings == []


def test_r11_inner_body_using_outer_axis_is_clean(tmp_path):
    # `inner` reduces over the OUTER mesh's axis from inside a nested
    # shard_map; the standalone pass of `body` only sees the inner
    # mesh, so the rule must honor the widest observed scope, not the
    # narrowest
    findings = lint(tmp_path, {"raft_tpu/a.py": """
        import jax

        def inner(x):
            return jax.lax.psum(x, "data")

        def body(x, devs):
            sub = jax.sharding.Mesh(devs, axis_names=("model",))
            return jax.shard_map(inner, mesh=sub, in_specs=None,
                                 out_specs=None)(x)

        def run(x, devs):
            mesh = jax.sharding.Mesh(devs, axis_names=("data",))
            return jax.shard_map(body, mesh=mesh, in_specs=None,
                                 out_specs=None)(x, devs)
    """}, rules="R11")
    assert findings == []


def test_r11_unknown_scope_stays_silent(tmp_path):
    # no shard_map context resolvable: the axis may be bound by a
    # caller outside the scan — conservative silence
    findings = lint(tmp_path, {"raft_tpu/a.py": """
        import jax

        def body(x):
            return jax.lax.psum(x, "data")
    """}, rules="R11")
    assert findings == []


def test_r11_flags_rank_divergent_cond_arm(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/a.py": """
        import jax

        def with_collective(x):
            return jax.lax.psum(x, "data")

        def without(x):
            return x

        def body(x):
            is_root = jax.lax.axis_index("data") == 0
            return jax.lax.cond(is_root, with_collective, without, x)
    """}, rules="R11")
    assert any("axis_index" in f.message for f in findings)


def test_r11_rank_uniform_cond_is_clean(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/a.py": """
        import jax

        def with_collective(x):
            return jax.lax.psum(x, "data")

        def without(x):
            return x

        def body(x, flag):
            return jax.lax.cond(flag, with_collective, without, x)
    """}, rules="R11")
    assert findings == []


def test_r11_flags_unmatched_mailbox_tag(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/a.py": """
        def push(view, payload, dst):
            view.isend(payload, dst, tag=7)

        def pull(view, src):
            return view.irecv(src, tag=9)
    """}, rules="R11")
    msgs = " ".join(f.message for f in findings)
    assert "tag 7" in msgs and "tag 9" in msgs


def test_r11_paired_and_computed_tags_are_clean(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/a.py": """
        def push(view, payload, dst, base):
            view.isend(payload, dst, tag=7)
            view.isend(payload, dst, tag=base + 1)

        def pull(view, src):
            return view.irecv(src, tag=7)
    """}, rules="R11")
    assert findings == []


# ---------------------------------------------------------------------------
# R12: layout & promotion hazards


def test_r12_flags_unaligned_lane_tile(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/a.py": """
        from raft_tpu.matrix.epilogue import insert_drain

        def drain(dist, val_ref, idx_ref, j):
            return insert_drain(dist, val_ref, idx_ref, j, tn=100,
                                k=64, n_valid=10)
    """}, rules="R12")
    assert rule_ids(findings) == {"R12"}
    assert "tn=100" in findings[0].message


def test_r12_padding_helper_output_is_clean(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/a.py": """
        from raft_tpu.matrix.epilogue import insert_drain, \\
            resolve_tn_sw

        def drain(dist, val_ref, idx_ref, j, n):
            tn, sw = resolve_tn_sw(100, None, n)
            return insert_drain(dist, val_ref, idx_ref, j, tn=tn,
                                k=64, n_valid=10, sw=sw)
    """}, rules="R12")
    assert findings == []


def test_r12_aligned_literal_and_unknown_are_clean(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/a.py": """
        from raft_tpu.matrix.epilogue import insert_drain

        def drain(dist, val_ref, idx_ref, j, tn):
            a = insert_drain(dist, val_ref, idx_ref, j, tn=256,
                             k=64, n_valid=10)
            return insert_drain(a, val_ref, idx_ref, j, tn=tn,
                                k=64, n_valid=10)
    """}, rules="R12")
    assert findings == []


def test_r12_shape_const_propagates_through_locals(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/a.py": """
        from raft_tpu.matrix.epilogue import insert_drain

        def drain(dist, val_ref, idx_ref, j):
            width = 64 + 36            # folds to 100
            return insert_drain(dist, val_ref, idx_ref, j, tn=width,
                                k=64, n_valid=10)
    """}, rules="R12")
    assert rule_ids(findings) == {"R12"}


def test_r12_flags_silent_f64_promotion(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/a.py": """
        import jax.numpy as jnp
        import numpy as np

        def mix(n):
            a = jnp.zeros((n,), dtype=jnp.float32)
            b = np.zeros((4,), dtype=np.float64)
            return a * b
    """}, rules="R12")
    assert any("float64" in f.message for f in findings)


def test_r12_matching_dtypes_are_clean(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/a.py": """
        import jax.numpy as jnp

        def same(n):
            a = jnp.zeros((n,), dtype=jnp.float32)
            b = jnp.ones((4,), dtype=jnp.float32)
            return a * b + 2.0
    """}, rules="R12")
    assert findings == []


# ---------------------------------------------------------------------------
# R13: cost-model coverage


def test_r13_flags_missing_flops_bytes_twin(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/runtime/limits.py": """
        def _est_toy(*, m, n, itemsize):
            return m * n * itemsize

        _ESTIMATORS = {
            "toy.op": _est_toy,
        }

        _SECONDS_ESTIMATORS = {}
    """}, rules="R13")
    assert rule_ids(findings) == {"R13"}
    assert "no _SECONDS_ESTIMATORS entry" in findings[0].message


def test_r13_flags_dim_signature_drift(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/runtime/limits.py": """
        def _est_toy(*, m, n, itemsize):
            return m * n * itemsize

        def _sec_toy(*, rows, cols):
            return 1.0, 2.0

        _ESTIMATORS = {
            "toy.op": _est_toy,
        }

        _SECONDS_ESTIMATORS = {
            "toy.op": _sec_toy,
        }
    """}, rules="R13")
    assert any("drift" in f.message for f in findings)


def test_r13_flags_call_site_off_the_table(tmp_path):
    findings = lint(tmp_path, {
        "raft_tpu/runtime/limits.py": """
            def _est_toy(*, m, n, itemsize):
                return m * n * itemsize

            def _sec_toy(*, m, n, itemsize):
                return 1.0, 2.0

            _ESTIMATORS = {
                "toy.op": _est_toy,
            }

            _SECONDS_ESTIMATORS = {
                "toy.op": _sec_toy,
            }

            def estimate_bytes(op, **dims):
                return _ESTIMATORS[op](**dims)
        """,
        "raft_tpu/serve/a.py": """
            from raft_tpu.runtime import limits

            def quote(rows):
                bad = limits.estimate_bytes("toy.gone", m=rows, n=1,
                                            itemsize=4)
                thin = limits.estimate_bytes("toy.op", m=rows)
                return bad + thin
        """}, rules="R13")
    msgs = " ".join(f.message for f in findings)
    assert "no such op" in msgs
    assert "missing dims" in msgs


def test_r13_matched_tables_and_call_sites_are_clean(tmp_path):
    findings = lint(tmp_path, {
        "raft_tpu/runtime/limits.py": """
            def _est_toy(*, m, n, itemsize):
                return m * n * itemsize

            def _sec_toy(*, m, n, itemsize):
                return 1.0, 2.0

            _ESTIMATORS = {
                "toy.op": _est_toy,
            }

            _SECONDS_ESTIMATORS = {
                "toy.op": _sec_toy,
            }

            def estimate_bytes(op, **dims):
                return _ESTIMATORS[op](**dims)
        """,
        "raft_tpu/serve/a.py": """
            from raft_tpu.runtime import limits

            def quote(rows):
                return limits.estimate_bytes("toy.op", m=rows, n=8,
                                             itemsize=4)
        """}, rules="R13")
    assert findings == []


def test_r13_shipped_tables_cover_every_bytes_op():
    """The real limits.py: every admission-priced op must carry its
    flops/bytes twin with the same required dims (keeps the roofline
    denominators honest)."""
    from raft_tpu.runtime import limits as L
    for op in L._ESTIMATORS:
        assert op in L._SECONDS_ESTIMATORS, op
        flops, bytes_ = L.estimate_flops_bytes(
            op, **_SMOKE_DIMS[op])
        assert flops > 0 and bytes_ > 0, op
        assert bytes_ == L.estimate_bytes(op, **_SMOKE_DIMS[op]), op


_SMOKE_DIMS = {
    "distance.pairwise_distance": dict(m=64, n=32, k=16, itemsize=4),
    "neighbors.brute_force_knn": dict(n_queries=8, n_db=128,
                                      n_dims=16, k=4, itemsize=4),
    "neighbors.ivf_search": dict(n_queries=8, probe_rows=64,
                                 n_dims=16, k=4, itemsize=4,
                                 packed_rows=256),
    "neighbors.ivf_mnmg_search": dict(n_queries=8, probe_rows=64,
                                      n_dims=16, k=4, n_ranks=2,
                                      itemsize=4, packed_rows=256),
    "neighbors.ivf_pq_search": dict(n_queries=8, nprobe=4,
                                    probe_rows=64, n_dims=16, k=4,
                                    m=4, n_codes=16, itemsize=4,
                                    refine=8, packed_rows=256),
    "neighbors.streaming_compact": dict(packed_rows=256, n_dims=16,
                                        itemsize=4),
    "linalg.gemm": dict(m=32, n=32, k=32, itemsize=4),
    "sparse.spmv": dict(n_rows=64, n_cols=64, nnz=512, itemsize=4),
}


# ---------------------------------------------------------------------------
# R14: import resolution


def test_r14_flags_import_of_missing_module(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/a.py": """
        from raft_tpu.gone_module import something
    """}, rules="R14")
    assert rule_ids(findings) == {"R14"}
    assert "no such module" in findings[0].message


def test_r14_flags_import_of_missing_name(tmp_path):
    findings = lint(tmp_path, {
        "raft_tpu/b.py": """
            def real():
                return 1
        """,
        "raft_tpu/a.py": """
            from raft_tpu.b import real, imaginary
        """}, rules="R14")
    assert any("'imaginary' is not defined" in f.message
               for f in findings)


def test_r14_relative_package_init_imports_resolve(tmp_path):
    # for an __init__.py the modname IS the package: `from . import x`
    # anchors at the package itself, not its parent
    findings = lint(tmp_path, {
        "raft_tpu/sub/x.py": "def f():\n    return 1\n",
        "raft_tpu/sub/__init__.py": """
            from . import x
            from .x import f
        """}, rules="R14")
    assert findings == []


def test_r14_star_and_getattr_exports_stay_silent(tmp_path):
    findings = lint(tmp_path, {
        "raft_tpu/lazy.py": """
            def __getattr__(name):
                raise AttributeError(name)
        """,
        "raft_tpu/a.py": """
            from raft_tpu.lazy import anything
        """}, rules="R14")
    assert findings == []


def test_r14_external_roots_are_out_of_scope(tmp_path):
    findings = lint(tmp_path, {"raft_tpu/a.py": """
        from not_a_local_package.sub import thing
    """}, rules="R14")
    assert findings == []


# ---------------------------------------------------------------------------
# baseline loader: shipped TODO whys are a hard failure


def test_baseline_rejects_todo_placeholder_why(tmp_path):
    write_tree(tmp_path, VIOLATION)
    bl = tmp_path / "bl.json"
    bl.write_text(json.dumps({"version": 1, "entries": [{
        "rule": "R4", "file": "raft_tpu/a.py",
        "symbol": "raft_tpu.a:f",
        "why": "TODO: justify this waiver"}]}))
    code, out = run_cli(tmp_path, "--baseline", str(bl))
    assert code == 2 and "placeholder" in out


def test_baseline_rejects_empty_why(tmp_path):
    write_tree(tmp_path, VIOLATION)
    bl = tmp_path / "bl.json"
    bl.write_text(json.dumps({"version": 1, "entries": [{
        "rule": "R4", "file": "raft_tpu/a.py",
        "symbol": "raft_tpu.a:f", "why": "  "}]}))
    code, out = run_cli(tmp_path, "--baseline", str(bl))
    assert code == 2


def test_write_baseline_roundtrip_needs_real_whys(tmp_path):
    # --write-baseline emits TODOs by design; feeding them back in
    # unedited must fail, closing the copy-paste loophole
    write_tree(tmp_path, VIOLATION)
    bl = tmp_path / "bl.json"
    code, _ = run_cli(tmp_path, "--write-baseline", str(bl))
    assert code == 0
    code, out = run_cli(tmp_path, "--baseline", str(bl))
    assert code == 2 and "placeholder" in out


# ---------------------------------------------------------------------------
# the .raftlint_cache/ fast path


def test_cache_warm_run_matches_cold_run(tmp_path):
    write_tree(tmp_path, VIOLATION)
    code_cold, out_cold = run_cli(tmp_path, "--no-baseline",
                                  "--no-cache")
    code1, out1 = run_cli(tmp_path, "--no-baseline")   # fills cache
    code2, out2 = run_cli(tmp_path, "--no-baseline")   # replays memo
    assert (tmp_path / ".raftlint_cache").is_dir()
    assert code_cold == code1 == code2 == 1
    assert out_cold == out1 == out2


def test_cache_invalidates_on_edit(tmp_path):
    write_tree(tmp_path, {"raft_tpu/a.py": "def f():\n    return 1\n"})
    code, _ = run_cli(tmp_path)
    assert code == 0
    # introduce a violation: the content-hash key must miss and the
    # new finding must surface despite the warm cache
    (tmp_path / "raft_tpu/a.py").write_text(
        "def f():\n    raise RuntimeError('boom')\n")
    code, out = run_cli(tmp_path, "--no-baseline")
    assert code == 1 and "R4" in out


def test_no_cache_flag_writes_nothing(tmp_path):
    write_tree(tmp_path, {"raft_tpu/a.py": "def f():\n    return 1\n"})
    code, _ = run_cli(tmp_path, "--no-cache")
    assert code == 0
    assert not (tmp_path / ".raftlint_cache").exists()
