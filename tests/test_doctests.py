"""Doctest harvest (ref test model:
python/pylibraft/pylibraft/tests/test_doctests.py — walks the package,
collects docstring examples and runs each as a test case)."""

import doctest
import importlib
import pkgutil

import pytest

import raft_tpu

# Modules whose import or examples need hardware/toolchain are skipped the
# same way the reference skips GPU-less doctests.
_SKIP_PREFIXES = ("raft_tpu._native",)


def _iter_modules():
    for info in pkgutil.walk_packages(raft_tpu.__path__,
                                      prefix="raft_tpu."):
        if info.name.startswith(_SKIP_PREFIXES):
            continue
        yield info.name


def _collect():
    finder = doctest.DocTestFinder(recurse=True)
    cases = []
    for name in _iter_modules():
        mod = importlib.import_module(name)
        for test in finder.find(mod, module=mod):
            if test.examples:
                cases.append(pytest.param(test, id=test.name))
    return cases


_CASES = _collect()


def test_doctests_found():
    # guards against the harvest silently collecting nothing
    assert len(_CASES) >= 6


@pytest.mark.parametrize("dt", _CASES)
def test_docstring_example(dt):
    runner = doctest.DocTestRunner(
        optionflags=doctest.NORMALIZE_WHITESPACE | doctest.ELLIPSIS)
    result = runner.run(dt)
    assert result.failed == 0, f"{dt.name}: {result.failed} failed examples"
