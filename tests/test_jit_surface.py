"""jit-surface tier: every major primitive must trace and compile under
jax.jit with no concrete-value leaks (ref test model: the EXT_HEADERS
compile-surface tests, cpp/tests/CMakeLists.txt:128-138 — 'does every
public entry compile in isolation' — translated to XLA tracing).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest


@pytest.fixture(scope="module")
def x64():
    return np.random.default_rng(0).normal(size=(64, 16)).astype(np.float32)


def _compiles(fn, *args):
    """Assert fn jits end-to-end: trace, lower, compile, run."""
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    return out


class TestLinalgJit:
    def test_elementwise_and_reduce(self, x64):
        from raft_tpu import linalg

        _compiles(lambda a: linalg.add(None, a, a), x64)
        _compiles(lambda a: linalg.reduce(None, a), x64)
        _compiles(lambda a: linalg.row_norm(None, a, norm_type="l2"), x64)
        _compiles(lambda a: linalg.normalize(None, a), x64)
        _compiles(lambda a: linalg.map_then_reduce(None, jnp.abs, a), x64)

    def test_decompositions(self, x64):
        from raft_tpu import linalg

        _compiles(lambda a: linalg.qr_get_qr(None, a), x64)
        _compiles(lambda a: linalg.svd_qr(None, a), x64)
        cov = x64.T @ x64
        _compiles(lambda a: linalg.eig_dc(None, a), cov)

    def test_knn(self, x64):
        from raft_tpu.neighbors import knn

        _compiles(functools.partial(knn, None, k=5), x64, x64[:8])

    def test_eig_jacobi(self, x64):
        from raft_tpu import linalg

        cov = (x64.T @ x64).astype(np.float32)
        _compiles(lambda a: linalg.eig_jacobi(None, a, sweeps=4), cov)

    def test_contraction_metric_epilogues(self, x64):
        from raft_tpu.linalg.contractions import (fused_argmin_pallas,
                                                  pairwise_pallas)

        y = x64[:16]
        for metric in ("l2", "cosine", "inner"):
            _compiles(functools.partial(pairwise_pallas, metric=metric),
                      x64, y)
            _compiles(functools.partial(fused_argmin_pallas, metric=metric),
                      x64, y)

    def test_gemm_dtypes(self, x64):
        from raft_tpu.linalg import gemm

        for dt in (jnp.float32, jnp.bfloat16):
            a = x64.astype(dt)
            _compiles(lambda p, q: gemm(None, p, q), a, a.T)


class TestMatrixJit:
    def test_select_k_static_k(self, x64):
        from raft_tpu.matrix import select_k

        f = functools.partial(select_k, None, k=4, select_min=True)
        _compiles(f, x64)

    def test_argminmax_gather(self, x64):
        from raft_tpu.matrix import argmax, argmin, gather

        _compiles(functools.partial(argmin, None), x64)
        _compiles(functools.partial(argmax, None), x64)
        idx = jnp.asarray([0, 5, 9], jnp.int32)
        _compiles(functools.partial(gather, None), x64, idx)


class TestStatsJit:
    def test_moments_and_metrics(self, x64):
        from raft_tpu import stats

        _compiles(lambda a: stats.meanvar(a), x64)
        _compiles(lambda a: stats.cov(a), x64)
        _compiles(lambda a: stats.minmax(a), x64)
        labels = jnp.asarray(np.random.default_rng(1).integers(
            0, 4, 64).astype(np.int32))
        _compiles(lambda p, q: stats.adjusted_rand_index(p, q, n_classes=4),
                  labels, labels)
        _compiles(lambda p, q: stats.v_measure(p, q, n_classes=4),
                  labels, labels)

    def test_histogram_static_bins(self, x64):
        from raft_tpu.stats import histogram

        data = jnp.asarray((np.abs(x64) * 3).astype(np.int32))
        _compiles(functools.partial(histogram, n_bins=8), data)


class TestClusterDistanceJit:
    def test_lloyd_step(self, x64):
        from raft_tpu.cluster.kmeans import lloyd_step

        c = x64[:8]
        _compiles(functools.partial(lloyd_step, n_clusters=8), x64, c)

    def test_pairwise_metrics(self, x64):
        from raft_tpu.distance.pairwise import (DistanceType,
                                                pairwise_distance)

        for metric in (DistanceType.L2Expanded, DistanceType.L1,
                       DistanceType.CosineExpanded):
            _compiles(functools.partial(pairwise_distance, None,
                                        metric=metric), x64, x64[:16])


class TestSparseJit:
    def test_spmv_spmm(self, x64):
        from raft_tpu.sparse.convert import dense_to_csr
        from raft_tpu.sparse.linalg import spmm, spmv

        d = np.array(x64)
        d[np.abs(d) < 0.5] = 0.0
        csr = dense_to_csr(d)
        v = jnp.asarray(np.ones(16, np.float32))
        _compiles(lambda vv: spmv(csr, vv), v)
        b = jnp.asarray(np.ones((16, 4), np.float32))
        _compiles(lambda bb: spmm(csr, bb), b)


class TestRandomJit:
    def test_distributions(self):
        from raft_tpu.random import RngState, normal, uniform

        # RngState is host state; the jit boundary takes the raw key
        key = RngState(0).next_key()

        def gen(k):
            import jax.random as jr
            k1, k2 = jr.split(k)
            return jr.uniform(k1, (32,)), jr.normal(k2, (32,))

        _compiles(gen, key)
        # and the wrapper API executes eagerly without tracer leaks
        uniform(None, RngState(1), (8,))
        normal(None, RngState(2), (8,))


class TestMultichipJit:
    def test_sharded_lloyd_compiles(self, mesh8):
        """The full MNMG step lowers under shard_map on the 8-device mesh
        (same path as __graft_entry__.dryrun_multichip)."""
        import functools as ft

        from jax.sharding import NamedSharding, PartitionSpec as P

        from raft_tpu.cluster.kmeans import mnmg_lloyd_step

        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(128, 16)).astype(np.float32))
        c = jnp.asarray(rng.normal(size=(8, 16)).astype(np.float32))
        step = jax.jit(jax.shard_map(
            ft.partial(mnmg_lloyd_step, n_clusters=8, data_axis="data"),
            mesh=mesh8,
            in_specs=(P("data", None), P(None, None)),
            out_specs=(P(None, None), P(), P("data")),
        ))
        with jax.sharding.use_mesh(mesh8) if hasattr(
                jax.sharding, "use_mesh") else _nullcontext():
            out = step(x, c)
            jax.block_until_ready(out)


def _nullcontext():
    import contextlib

    return contextlib.nullcontext()


class TestSparsePaddingJit:
    """Round-3 API additions must hold the jit surface: padded CSR
    matrices as pytree args, the tm override on the fused kernels."""

    def test_padded_csr_ops_compile(self):
        import scipy.sparse as sp

        from raft_tpu.core.sparse_types import CSRMatrix
        from raft_tpu.sparse.linalg import csr_row_norm, spmm, spmv

        a = sp.random(64, 64, density=0.1, random_state=3,
                      format="csr").astype(np.float32)
        csr = CSRMatrix.from_scipy(a)          # padded by default
        x = jnp.asarray(np.random.default_rng(4).normal(size=64)
                        .astype(np.float32))
        b = jnp.asarray(np.random.default_rng(5).normal(size=(64, 4))
                        .astype(np.float32))
        _compiles(spmv, csr, x)
        _compiles(spmm, csr, b)
        _compiles(csr_row_norm, csr)

    def test_fused_kernels_tm_override_compile(self, x64):
        from raft_tpu.linalg.contractions import (fused_l2_argmin_pallas,
                                                  fused_lloyd_pallas)

        y = jnp.asarray(np.random.default_rng(6).normal(size=(8, 16))
                        .astype(np.float32))
        for tm in (None, 16, 1 << 20):      # oversized falls back to auto
            _compiles(functools.partial(fused_lloyd_pallas, tm=tm),
                      jnp.asarray(x64), y)
            _compiles(functools.partial(fused_l2_argmin_pallas, tm=tm),
                      jnp.asarray(x64), y)


class TestSolverLabelSpectralJit:
    """jit-surface for the solver/label/spectral layer (absent from this
    tier until round 3): LAP, weak_cc, label relabeling — each must trace
    with no concrete-value leaks."""

    def test_linear_assignment_compiles(self):
        from raft_tpu.solver.linear_assignment import (
            LinearAssignmentProblem)

        rng = np.random.default_rng(2)
        costs = jnp.asarray(rng.uniform(1, 9, (8, 8)).astype(np.float32))
        lap = LinearAssignmentProblem(None, 8, epsilon=1e-3)
        # solve dispatches jitted auction rounds internally
        rows = np.asarray(lap.solve(costs)[0]).reshape(-1)
        assert sorted(rows.tolist()) == list(range(8))

    def test_weak_cc_compiles_with_padded_csr(self):
        import scipy.sparse as sp

        from raft_tpu.core.sparse_types import CSRMatrix
        from raft_tpu.sparse.csr import weak_cc

        a = sp.random(32, 32, density=0.08, random_state=5,
                      format="csr").astype(np.float32)
        a = a + a.T
        csr = CSRMatrix.from_scipy(sp.csr_matrix(a))
        _compiles(lambda c: weak_cc(None, c), csr)

    def test_label_relabel_eager_contract(self):
        """make_monotonic is EAGER-ONLY by design — its output values
        depend on np.unique of the data (dynamic), exactly like the
        reference's getUniquelabels+host path. The jit-surface fact to
        pin: it works on device arrays eagerly and refuses tracers with
        jax's standard error (not a hang or silent wrong result)."""
        import jax.errors

        from raft_tpu.label import make_monotonic

        labels = jnp.asarray(np.array([7, 7, 3, 9, 3], np.int32))
        got = np.asarray(make_monotonic(labels))
        assert got.tolist() == [2, 2, 1, 3, 1]
        with pytest.raises(jax.errors.TracerArrayConversionError):
            jax.jit(make_monotonic)(labels)
