"""Sharded IVF serving index tests (ISSUE 11 tentpole, index half).

Acceptance criteria exercised here:

* the merge epilogue is bit-identical at the exactness boundary:
  ``search_mnmg`` at ``nprobe = n_lists`` on 1, 2, and 4 ranks returns
  the same ids AND distances — including tie and NaN handling — as
  single-rank ``ivf_flat.search`` and as ``brute_force.knn`` on the
  reconstructed database;
* partial probes return per-element identical distances at every rank
  count (independent dot products over identical static tiles);
* :func:`partition_lists` is deterministic and balanced, and
  ``shrink_mnmg`` produces shards bit-for-bit equal to a fresh
  ``build_mnmg`` at the survivor count — the chaos-repack witness;
* the cross-process halves (``search_local`` + ``merge_pool``) agree
  with the one-program ``shard_map`` path;
* :class:`~raft_tpu.serve.IvfMnmgKnnService` warms to zero post-warm
  retraces and serves results equal to the eager search.
"""

import numpy as np
import pytest

from raft_tpu.neighbors import ivf_flat, ivf_mnmg
from raft_tpu.neighbors.brute_force import knn
from raft_tpu.neighbors.ivf_mnmg import (build_mnmg, merge_pool,
                                         partition_lists, search_local,
                                         search_mnmg, shrink_mnmg)

RANK_COUNTS = (1, 2, 4)


@pytest.fixture(scope="module")
def blob_db(res):
    from raft_tpu.random import RngState, make_blobs

    X, _, _ = make_blobs(res, RngState(5), 1536, 24, n_clusters=16)
    X = np.asarray(X)
    return X, ivf_flat.build(res, X, 16, seed=0, max_iter=4)


def _np(x):
    return np.asarray(x)


class TestPartition:
    def test_deterministic_and_total(self):
        caps = np.array([64, 8, 32, 8, 16, 128, 8, 24])
        a = partition_lists(caps, 3)
        b = partition_lists(caps, 3)
        assert np.array_equal(a, b)
        assert a.shape == (8,)
        assert set(a.tolist()) <= {0, 1, 2}
        # every rank owns something when there are enough lists
        assert len(set(a.tolist())) == 3

    def test_lpt_balance(self):
        # LPT greedy keeps max rank load within (max cap) of the mean
        rng = np.random.default_rng(0)
        caps = rng.integers(8, 256, size=64)
        owner = partition_lists(caps, 4)
        loads = np.array([caps[owner == r].sum() for r in range(4)])
        assert loads.max() - loads.min() <= caps.max()

    def test_bad_args(self):
        with pytest.raises(ValueError, match="n_ranks"):
            partition_lists(np.array([8, 8]), 0)


class TestBuild:
    def test_shards_are_a_partition(self, blob_db):
        X, flat = blob_db
        idx = build_mnmg(None, X, 16, 2, flat=flat)
        assert idx.n_ranks == 2
        ids = _np(idx.packed_ids_sh)
        real = ids[ids >= 0]
        assert sorted(real.tolist()) == list(range(len(X)))
        # sizes split exactly: each list owned by exactly one rank
        sizes = _np(idx.sizes_sh)
        assert np.array_equal(sizes.sum(axis=0), _np(flat.sizes))
        assert ((sizes > 0).sum(axis=0) <= 1).all()

    def test_same_flat_same_shards(self, blob_db):
        X, flat = blob_db
        a = build_mnmg(None, X, 16, 2, flat=flat)
        b = build_mnmg(None, X, 16, 2, flat=flat)
        for fa, fb in ((a.packed_db_sh, b.packed_db_sh),
                       (a.packed_ids_sh, b.packed_ids_sh),
                       (a.starts_sh, b.starts_sh),
                       (a.sizes_sh, b.sizes_sh)):
            assert np.array_equal(_np(fa), _np(fb))

    def test_reconstruct_exact(self, blob_db):
        X, flat = blob_db
        idx = build_mnmg(None, X, 16, 4, flat=flat)
        assert np.array_equal(_np(idx.reconstruct()), X)

    def test_mesh_rank_mismatch(self, blob_db):
        X, flat = blob_db
        import jax
        from jax.sharding import Mesh

        mesh = Mesh(np.asarray(jax.devices()[:2]), ("shard",))
        with pytest.raises(ValueError, match="need n_ranks"):
            build_mnmg(None, X, 16, 4, flat=flat, mesh=mesh)


class TestFullProbeBitIdentity:
    @pytest.mark.parametrize("n_ranks", RANK_COUNTS)
    def test_identical_to_single_rank_and_brute(self, res, blob_db,
                                                n_ranks):
        X, flat = blob_db
        q = X[100:116] + 0.01
        bd, bi = knn(res, X, q, k=12)
        sd, si = ivf_flat.search(res, flat, q, k=12,
                                 nprobe=flat.n_lists)
        idx = build_mnmg(res, X, 16, n_ranks, flat=flat)
        md, mi = search_mnmg(res, idx, q, k=12, nprobe=idx.n_lists)
        assert np.array_equal(_np(md), _np(bd))
        assert np.array_equal(_np(mi), _np(bi))
        assert np.array_equal(_np(md), _np(sd))
        assert np.array_equal(_np(mi), _np(si))

    @pytest.mark.parametrize("n_ranks", RANK_COUNTS)
    def test_ties_and_nan_identical(self, res, n_ranks):
        # duplicate rows (exact ties) + a NaN row: the pathological
        # inputs where "equal up to tie order" would hide a divergence
        rng = np.random.default_rng(9)
        X = rng.standard_normal((64, 8)).astype(np.float32)
        X[32:] = X[:32]                       # every row twice
        X[7] = np.nan
        q = np.concatenate([X[:4], X[40:42]])
        flat = ivf_flat.build(res, X, 8, centroids=X[:8])
        idx = build_mnmg(res, X, 8, n_ranks, flat=flat)
        bd, bi = knn(res, X, q, k=8)
        md, mi = search_mnmg(res, idx, q, k=8, nprobe=8)
        assert np.array_equal(_np(md), _np(bd), equal_nan=True)
        assert np.array_equal(_np(mi), _np(bi))

    def test_overprobe_clamps(self, res, blob_db):
        X, flat = blob_db
        idx = build_mnmg(res, X, 16, 2, flat=flat)
        d1, i1 = search_mnmg(res, idx, X[:8], k=4, nprobe=idx.n_lists)
        d2, i2 = search_mnmg(res, idx, X[:8], k=4,
                             nprobe=idx.n_lists + 7)
        assert np.array_equal(_np(d1), _np(d2))
        assert np.array_equal(_np(i1), _np(i2))


class TestPartialProbe:
    @pytest.mark.parametrize("n_ranks", (2, 4))
    def test_matches_single_rank(self, res, blob_db, n_ranks):
        X, flat = blob_db
        q = X[:32] + 0.02
        sd, si = ivf_flat.search(res, flat, q, k=10, nprobe=5)
        idx = build_mnmg(res, X, 16, n_ranks, flat=flat)
        md, mi = search_mnmg(res, idx, q, k=10, nprobe=5)
        assert np.array_equal(_np(md), _np(sd))
        assert np.array_equal(_np(mi), _np(si))

    @pytest.mark.parametrize("metric", ("euclidean", "inner"))
    def test_metric_finalize_applied_once(self, res, blob_db, metric):
        # "inner" negates and "euclidean" sqrts in the finalize — a
        # merge over finalized values would mis-order both
        X, _ = blob_db
        flat = ivf_flat.build(res, X, 16, metric, seed=0, max_iter=6)
        q = X[:16] + 0.05
        sd, si = ivf_flat.search(res, flat, q, k=8, nprobe=6)
        idx = build_mnmg(res, X, 16, 2, metric, flat=flat)
        md, mi = search_mnmg(res, idx, q, k=8, nprobe=6)
        assert np.array_equal(_np(md), _np(sd))
        assert np.array_equal(_np(mi), _np(si))

    def test_bad_args(self, res, blob_db):
        X, flat = blob_db
        idx = build_mnmg(res, X, 16, 2, flat=flat)
        with pytest.raises(ValueError, match="queries"):
            search_mnmg(res, idx, X[:2, :5], k=4, nprobe=2)
        with pytest.raises(ValueError, match="nprobe"):
            search_mnmg(res, idx, X[:2], k=4, nprobe=0)
        with pytest.raises(ValueError, match="k="):
            search_mnmg(res, idx, X[:2], k=0, nprobe=2)

    def test_budget_degrades_bit_identical(self, res, blob_db):
        from raft_tpu.runtime import limits

        X, flat = blob_db
        idx = build_mnmg(res, X, 16, 2, flat=flat)
        q = X[:16] + 0.02
        full_d, full_i = search_mnmg(res, idx, q, k=8, nprobe=4)
        est = limits.estimate_bytes(
            "neighbors.ivf_mnmg_search", n_queries=16,
            probe_rows=4 * idx.cap_max, n_dims=idx.dim, k=8,
            n_ranks=2, itemsize=4, packed_rows=idx.cap_rank_max)
        with limits.budget_scope(est // 2):
            cd, ci = search_mnmg(res, idx, q, k=8, nprobe=4)
        assert np.array_equal(_np(cd), _np(full_d))
        assert np.array_equal(_np(ci), _np(full_i))


class TestShrinkRepack:
    def test_shrink_equals_fresh_build(self, blob_db):
        X, flat = blob_db
        idx4 = build_mnmg(None, X, 16, 4, flat=flat)
        for survivors in ((0, 1, 3), (1, 2), (0,)):
            shrunk = shrink_mnmg(idx4, survivors)
            fresh = build_mnmg(None, X, 16, len(survivors), flat=flat)
            for a, b in ((shrunk.packed_db_sh, fresh.packed_db_sh),
                         (shrunk.packed_ids_sh, fresh.packed_ids_sh),
                         (shrunk.starts_sh, fresh.starts_sh),
                         (shrunk.sizes_sh, fresh.sizes_sh)):
                assert np.array_equal(_np(a), _np(b))
            assert np.array_equal(shrunk.owner, fresh.owner)

    def test_shrunk_index_answers_identically(self, res, blob_db):
        X, flat = blob_db
        idx4 = build_mnmg(res, X, 16, 4, flat=flat)
        shrunk = shrink_mnmg(idx4, (0, 2))
        q = X[:8] + 0.01
        sd, si = ivf_flat.search(res, flat, q, k=6, nprobe=4)
        md, mi = search_mnmg(res, shrunk, q, k=6, nprobe=4)
        assert np.array_equal(_np(md), _np(sd))
        assert np.array_equal(_np(mi), _np(si))

    def test_no_survivors(self, blob_db):
        X, flat = blob_db
        idx = build_mnmg(None, X, 16, 2, flat=flat)
        with pytest.raises(ValueError, match="survivor"):
            shrink_mnmg(idx, ())


class TestCrossProcessHalves:
    def test_local_plus_merge_equals_one_program(self, res, blob_db):
        # the cross-process serving clique path: per-rank raw pools
        # merged on the host transport must agree with the in-graph
        # all-gather merge bit-for-bit
        X, flat = blob_db
        idx = build_mnmg(res, X, 16, 2, flat=flat)
        q = X[:12] + 0.03
        md, mi = search_mnmg(res, idx, q, k=8, nprobe=5)
        pools = [search_local(idx, r, q, k=8, nprobe=5)
                 for r in range(2)]
        vals = np.stack([_np(v) for v, _ in pools])
        ids = np.stack([_np(i) for _, i in pools])
        hd, hi = merge_pool(vals, ids, k=8, metric=idx.metric)
        assert np.array_equal(_np(hd), _np(md))
        assert np.array_equal(_np(hi), _np(mi))


class TestIvfMnmgService:
    def test_warm_zero_retrace_equals_eager(self, res, blob_db):
        from raft_tpu.serve import (BatchPolicy, Executor,
                                    IvfMnmgKnnService)

        X, flat = blob_db
        idx = build_mnmg(res, X, 16, 2, flat=flat)
        svc = IvfMnmgKnnService(idx, k=6, nprobe=4)
        ex = Executor([svc], policy=BatchPolicy(max_batch=32,
                                                max_wait_ms=1.0))
        ex.warm([8, 32])
        traces0 = ex.stats.traces
        q = X[:8].astype(np.float32) + 0.01
        with ex:
            d, i = ex.submit(svc.name, q).result(timeout=60.0)
        assert ex.stats.traces == traces0      # zero post-warm retraces
        ed, ei = search_mnmg(res, idx, q, k=6, nprobe=4)
        assert np.array_equal(_np(d), _np(ed))
        assert np.array_equal(_np(i), _np(ei))

    def test_rejects_degenerate_nprobe(self, res, blob_db):
        from raft_tpu.serve import IvfMnmgKnnService

        X, flat = blob_db
        idx = build_mnmg(res, X, 16, 2, flat=flat)
        with pytest.raises(ValueError):
            IvfMnmgKnnService(idx, k=6, nprobe=0)
        with pytest.raises(ValueError):
            IvfMnmgKnnService(idx, k=6, nprobe=16)
