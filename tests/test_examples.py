"""The examples/ scripts must actually run (docs that execute are the
only docs that stay true; ref model: pylibraft's doctested quick starts)."""

import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# kmeans_quickstart's best-of-seeds restart sweep is ~18s of CPU wall
# — slow tier; the other three examples keep the example gate on the
# tier-1 budget.
@pytest.mark.parametrize("script", [
    pytest.param("kmeans_quickstart.py", marks=pytest.mark.slow),
    "knn_quickstart.py",
    "select_k_quickstart.py",
    "spectral_eigsh.py"])
def test_example_runs(script):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, os.path.join(_REPO, "examples", script)],
        cwd=_REPO, env=env, capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, f"{script}:\n{r.stdout}\n{r.stderr}"
