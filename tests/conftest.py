"""Test configuration: force an 8-device virtual CPU mesh.

Mirrors the reference's test strategy of exercising multi-GPU paths on a
single host (LocalCUDACluster, SURVEY.md §4): we run the whole suite on CPU
with 8 virtual devices so comms/mesh code paths execute for real, and Pallas
kernels run in interpreter mode (see raft_tpu.util.pallas_utils).
"""

import os

# Force CPU (the ambient environment may point JAX_PLATFORMS at real TPU
# hardware, but the unit suite runs on an 8-device virtual CPU mesh).  Set
# both the env var and — because pytest plugins (jaxtyping) import jax
# before this file runs, baking the env-derived default in — the live jax
# config, which is honored as long as no backend has initialized yet.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402
import numpy as np  # noqa: E402
import pytest  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)


@pytest.fixture(scope="session")
def res():
    import raft_tpu

    return raft_tpu.device_resources(seed=42)


@pytest.fixture(scope="session")
def mesh8():
    from jax.sharding import Mesh

    devs = np.asarray(jax.devices())
    assert len(devs) >= 8, "conftest expects 8 virtual devices"
    return Mesh(devs[:8], axis_names=("data",))


@pytest.fixture
def rng_state():
    from raft_tpu.random import RngState

    return RngState(seed=1234)


def ring_of_cliques(n_cliques=4, size=8):
    """Shared graph fixture: n cliques joined in a ring by single bridge
    edges — highly symmetric (few distinct eigenvalues), the Lanczos
    invariant-subspace stress case and the spectral-partition oracle."""
    import scipy.sparse as sp

    from raft_tpu.core.sparse_types import CSRMatrix

    blocks = [np.ones((size, size)) - np.eye(size)] * n_cliques
    a = sp.block_diag(blocks).tolil()
    for i in range(n_cliques):
        u = i * size
        v = ((i + 1) % n_cliques) * size + 1
        a[u, v] = a[v, u] = 1.0
    return CSRMatrix.from_scipy(sp.csr_matrix(a).astype(np.float32))


@pytest.fixture(scope="module", autouse=True)
def _clear_jax_caches_per_module():
    """Round-5 regression guard: with the suite at ~690 tests, the CPU
    PJRT client segfaults DETERMINISTICALLY inside an XLA compile near
    test #669 (jax compiler.py backend_compile_and_load — reproduced 3x
    at the same test, never in any subset; the accumulated live-
    executable state is the only full-suite-scale variable). Dropping
    the jit caches at module boundaries keeps the executable population
    bounded; per-module recompiles cost seconds against a ~30-minute
    suite."""
    yield
    jax.clear_caches()
