"""Python parity layer tests (ref test models:
python/pylibraft/pylibraft/tests/, python/raft-dask/raft_dask/tests/)."""

import numpy as np
import pytest

from raft_tpu import compat
from raft_tpu.comms import (
    Comms,
    get_raft_comm_state,
    local_handle,
    perform_test_comms_allreduce,
)


class TestDeviceNdarray:
    def test_roundtrip(self):
        host = np.arange(12, dtype=np.float32).reshape(3, 4)
        arr = compat.device_ndarray(host)
        assert arr.shape == (3, 4)
        assert arr.dtype == np.float32
        np.testing.assert_array_equal(arr.copy_to_host(), host)
        np.testing.assert_array_equal(np.asarray(arr), host)

    def test_empty_and_getitem(self):
        arr = compat.device_ndarray.empty((5, 2))
        assert arr.shape == (5, 2)
        row = arr[0]
        assert isinstance(row, compat.device_ndarray)
        assert row.shape == (2,)

    def test_dlpack_to_torch(self):
        torch = pytest.importorskip("torch")
        arr = compat.device_ndarray(np.ones((4,), np.float32))
        t = torch.from_dlpack(arr)
        assert t.shape == (4,)
        assert float(t.sum()) == 4.0

    def test_ai_wrapper(self):
        w = compat.ai_wrapper(np.zeros((2, 3), np.float64))
        assert w.shape == (2, 3)
        assert w.c_contiguous
        with pytest.raises(TypeError):
            compat.ai_wrapper(object())


class TestOutputConversion:
    def teardown_method(self):
        compat.set_output_as("raft")

    def test_set_output_as(self):
        from raft_tpu.compat.outputs import _conv

        arr = compat.device_ndarray(np.ones(3, np.float32))
        compat.set_output_as("numpy")
        assert isinstance(_conv(arr), np.ndarray)
        compat.set_output_as("jax")
        import jax
        assert isinstance(_conv(arr), jax.Array)
        compat.set_output_as(lambda a: "custom")
        assert _conv(arr) == "custom"
        with pytest.raises(ValueError):
            compat.set_output_as("cudf")

    def test_auto_convert_decorator(self):
        compat.set_output_as("numpy")

        @compat.auto_convert_output
        def f():
            return (compat.device_ndarray(np.ones(2)), 5)

        out, five = f()
        assert isinstance(out, np.ndarray)
        assert five == 5


class TestCompatAPIs:
    def test_rmat(self):
        theta = np.tile(np.array([0.55, 0.2, 0.2, 0.05], np.float32), (8, 1))
        edges = compat.rmat(theta=theta, r_scale=8, c_scale=8,
                            n_edges=1000, seed=7)
        e = np.asarray(edges)
        assert e.shape == (1000, 2)
        assert e.min() >= 0 and e.max() < 256

    def test_rmat_out_param(self):
        out = compat.device_ndarray.empty((500, 2), np.int32)
        theta = np.tile(np.array([0.6, 0.15, 0.15, 0.1], np.float32),
                        (6, 1))
        compat.rmat(out=out, theta=theta, r_scale=6, c_scale=6)
        e = np.asarray(out)
        assert e.shape == (500, 2) and e.max() < 64

    def test_eigsh_scipy_duck(self):
        scipy_sparse = pytest.importorskip("scipy.sparse")
        rng = np.random.default_rng(0)
        n = 60
        dense = rng.normal(size=(n, n)).astype(np.float32)
        dense = (dense + dense.T) / 2
        dense[np.abs(dense) < 0.8] = 0.0
        np.fill_diagonal(dense, np.arange(1.0, n + 1.0))
        a = scipy_sparse.csr_matrix(dense)
        w, v = compat.eigsh(a, k=4, which="SA", tol=1e-6)
        w = np.asarray(w)
        expect = np.linalg.eigvalsh(dense)[:4]
        np.testing.assert_allclose(w, expect, rtol=1e-3, atol=1e-3)

    def test_interruptible_context(self):
        with compat.interruptible():
            x = 1 + 1
        assert x == 2


class TestCommsBootstrap:
    def test_init_and_collective(self, mesh8):
        comms = Comms(devices=list(mesh8.devices.ravel()))
        comms.init()
        state = get_raft_comm_state(comms.sessionId)
        assert state["nranks"] == 8
        handle = local_handle(comms.sessionId, rank=0)
        assert handle is not None
        from raft_tpu.core.resources import get_comms

        view = get_comms(handle)
        assert view.get_size() == 8
        assert view.get_rank() == 0
        # the reference's perform_test_comms_* self-test path
        assert perform_test_comms_allreduce(view)
        comms.destroy()
        assert get_raft_comm_state(comms.sessionId) == {}

    def test_double_init_warns_not_raises(self, mesh8):
        comms = Comms(devices=list(mesh8.devices.ravel()))
        comms.init()
        comms.init()   # idempotent
        comms.destroy()


def test_common_symbol_parity():
    """Every name pylibraft.common exports must exist here (ref:
    python/pylibraft/pylibraft/common/__init__.py:5-10)."""
    from raft_tpu import compat

    for name in ("ai_wrapper", "cai_wrapper", "Stream", "device_ndarray",
                 "DeviceResources", "DeviceResourcesSNMG", "Handle",
                 "auto_convert_output", "auto_sync_handle"):
        assert hasattr(compat, name), name
    compat.Stream().sync()            # no-op barrier must not raise
    w = compat.cai_wrapper(np.arange(4, dtype=np.float32))
    assert w.shape == (4,) and w.dtype == np.float32


def test_eigsh_positional_order_matches_reference():
    """pylibraft calls eigsh positionally as (A, k, which, ...) —
    lanczos.pyx:100. A ported eigsh(A, 2, "SA") must keep working."""
    import scipy.sparse as sp

    from raft_tpu.compat import eigsh
    from raft_tpu.core.sparse_types import CSRMatrix

    a = CSRMatrix.from_scipy(
        sp.diags([1., 2., 3., 4., 10.]).tocsr().astype(np.float32))
    w, _ = eigsh(a, 2, "SA")            # positional which
    np.testing.assert_allclose(np.asarray(w.values), [1.0, 2.0],
                               atol=1e-3)
    w, _ = eigsh(a, 2, "LM", None, None, None, 0.0, None)  # full ref order
    np.testing.assert_allclose(sorted(np.asarray(w.values)), [4.0, 10.0],
                               atol=1e-3)


def test_rmat_positional_order_matches_reference():
    """pylibraft calls rmat positionally as (out, theta, r_scale, c_scale,
    seed, handle) — rmat_rectangular_generator.pyx:69. seed must land in
    the seed slot (our n_edges extension is keyword-only)."""
    theta = [0.55, 0.25, 0.15, 0.05] * 8
    out = np.zeros((64, 2), np.int32)
    compat.rmat(out, theta, 8, 8, 999, None)
    assert out.max() < (1 << 8) and out.min() >= 0
    # different seeds -> different edges (seed really is the 5th arg)
    out2 = np.zeros((64, 2), np.int32)
    compat.rmat(out2, theta, 8, 8, 1000, None)
    assert not np.array_equal(out, out2)


def test_sparse_linalg_import_path_parity():
    """pylibraft.sparse.linalg.eigsh import shape (sparse/__init__.py:5)."""
    from raft_tpu.compat.sparse.linalg import eigsh as e2
    from raft_tpu.compat import eigsh as e1

    assert e1 is e2


def test_input_validation_parity():
    """pylibraft.common.input_validation predicate names work on jax
    arrays and device_ndarray (ref: common/input_validation.py:13-60)."""
    import numpy as np

    from raft_tpu.compat import (device_ndarray, do_cols_match,
                                 do_dtypes_match, do_rows_match,
                                 do_shapes_match, is_c_contiguous)

    a = np.zeros((3, 4), np.float32)
    b = np.zeros((3, 5), np.float32)
    c = device_ndarray(np.zeros((3, 4), np.float32))
    assert do_dtypes_match(a, b, c)
    assert not do_dtypes_match(a, b.astype(np.float64))
    assert do_rows_match(a, b, c)
    assert do_cols_match(a, c) and not do_cols_match(a, b)
    assert do_shapes_match(a, c) and not do_shapes_match(a, b)
    assert is_c_contiguous(a) and is_c_contiguous(c)
    assert not is_c_contiguous(np.asfortranarray(np.zeros((3, 4))))
    # torch interop: stride-based contiguity + dtype normalization
    import torch

    t = torch.zeros(3, 4)
    assert is_c_contiguous(t) and not is_c_contiguous(t.T)
    assert do_dtypes_match(t, a)
    assert not do_dtypes_match(t, t.to(torch.float64))
