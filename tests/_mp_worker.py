"""Worker process for the multi-process comms test tier (run by
test_multiprocess.py; the analogue of the code raft-dask ships to each dask
worker in _func_init_all, ref comms.py:414-505).

Usage: python _mp_worker.py <pid> <nproc> <coord_port> <p2p_port0> <p2p_port1>
"""

import os
import sys


def main():
    pid, nproc, coord_port = (int(a) for a in sys.argv[1:4])
    p2p_ports = [int(a) for a in sys.argv[4:4 + nproc]]

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax

    jax.config.update("jax_platforms", "cpu")

    from raft_tpu.comms.bootstrap import initialize_distributed

    initialize_distributed(f"localhost:{coord_port}", nproc, pid)

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    # --- device-side collective across processes (XLA/Gloo path) ---------
    devs = jax.devices()
    assert len(devs) == 2 * nproc, f"global devices {len(devs)}"
    mesh = Mesh(np.asarray(devs), ("data",))
    local = np.full((2, 4), float(pid + 1), np.float32)
    arr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("data")), local)
    total = jax.jit(lambda a: jnp.sum(a),
                    out_shardings=NamedSharding(mesh, P()))(arr)
    expect = sum(2 * 4 * (r + 1) for r in range(nproc))
    assert float(total) == expect, (float(total), expect)

    # --- device collective self-tests over the global mesh ---------------
    # (the reference's perform_test_comms_* battery, comms/detail/test.hpp,
    # run multi-process: each collective is verified numerically on every
    # rank's shard)
    from raft_tpu.comms import device as cdev

    world = 2 * nproc                     # 2 local devices per process

    def selftests(xs):
        r = cdev.rank("data").astype(jnp.float32)
        ok = jnp.bool_(True)
        ok &= cdev.allreduce((r + 1.0)[None])[0] == world * (world + 1) / 2
        ok &= cdev.bcast((r * 3.0)[None], root=1)[0] == 3.0
        g = cdev.allgather(r[None])                      # [world, 1]
        ok &= jnp.all(g[:, 0] == jnp.arange(world, dtype=jnp.float32))
        rs = cdev.reducescatter(jnp.arange(world, dtype=jnp.float32)
                                + 0.0 * r)               # shard gets [1]
        ok &= rs[0] == world * r
        ring = cdev.ring_shift(r[None], 1)[0]            # from rank r-1
        ok &= ring == (r - 1) % world
        return ok[None]

    xs = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("data")), np.zeros((2, 1), np.float32))
    oks = jax.jit(jax.shard_map(
        selftests, mesh=mesh, in_specs=(P("data"),),
        out_specs=P("data")))(xs)
    for shard in oks.addressable_shards:
        assert bool(np.asarray(shard.data)[0]), \
            f"collective self-test failed on shard {shard.index}"

    # --- host p2p across processes (TcpMailbox through MeshComms) --------
    from raft_tpu.comms.comms import MeshComms, Op
    from raft_tpu.comms.tcp_mailbox import TcpMailbox

    addrs = [f"127.0.0.1:{p}" for p in p2p_ports]
    box = TcpMailbox(pid, addrs)
    comms = MeshComms(mesh, axis_name="data", rank=pid, _mailbox=box)
    payload = np.arange(8, dtype=np.float32) + 100 * pid
    comms.isend(payload, dest=(pid + 1) % nproc, tag=7)
    req = comms.irecv(source=(pid - 1) % nproc, tag=7)
    (got,) = comms.waitall([req])
    src = (pid - 1) % nproc
    np.testing.assert_array_equal(got, np.arange(8, dtype=np.float32)
                                  + 100 * src)

    # --- FULL eager-collective self-test battery over the global mesh ----
    # (the reference runs its whole perform_test_comms_* battery on an
    # N-worker cluster, raft-dask test_comms.py:254-293; the stacked-
    # buffer tests below go through MeshComms._run's multi-controller
    # path — every process executes the identical sequence, SPMD)
    from raft_tpu.comms import test_suite as ts

    for fn in (ts.perform_test_comms_allreduce,
               ts.perform_test_comms_bcast,
               ts.perform_test_comms_reduce,
               ts.perform_test_comms_allgather,
               ts.perform_test_comms_allgatherv,
               ts.perform_test_comms_gather,
               ts.perform_test_comms_gatherv,
               ts.perform_test_comms_reducescatter,
               ts.perform_test_comms_device_send_recv,
               ts.perform_test_comms_device_sendrecv,
               ts.perform_test_comms_device_multicast_sendrecv):
        assert fn(comms), f"{fn.__name__} failed on process {pid}"

    # comm_split at 2 colors: the global device axis splits into two
    # sub-cliques, each spanning every process (devices alternate
    # colors); eager allreduce inside each verifies the sub-mesh wiring
    # (ref: test_comms.py:429 subcomm subsets).
    world = 2 * nproc
    color = [r % 2 for r in range(world)]
    key = list(range(world))
    for view_rank in range(world):
        sub = comms.rank_view(view_rank).comm_split(color, key)
        m = sub.get_size()
        out = np.asarray(sub.allreduce(np.ones((m, 1), np.int32),
                                       op=Op.SUM))
        assert np.all(out == m), (view_rank, out)
        expect = sum(1 for q in range(view_rank)
                     if color[q] == color[view_rank])
        assert sub.get_rank() == expect

    # all-pairs tag-matched host p2p, 2 trials (ref: test.hpp:362-418 —
    # each rank sends its id to every other; here each PROCESS does its
    # own rank's sends/recvs through the cross-process mailbox)
    for _ in range(2):
        for dst in range(nproc):
            if dst != pid:
                comms.isend(np.int32(pid), dest=dst, tag=pid)
        recs = [(s, comms.irecv(source=s, tag=s))
                for s in range(nproc) if s != pid]
        for s, rq in recs:
            assert int(rq.wait()) == s

    # --- 2-D (data × model) mesh k-means step ACROSS processes ----------
    # (round-3: the model-axis sharding — cluster blocks over 'model',
    # paired-pmin global argmin, per-block one-hot update psum'd over
    # 'data' — with a device layout TRANSPOSED so the model axis itself
    # spans process boundaries: model partner of devs[i] is devs[dp+i],
    # owned by a different process. jax.devices() orders by process.)
    if (2 * nproc) % 4 == 0:
        import functools

        from raft_tpu.cluster.kmeans import mnmg_lloyd_step

        mp_ = 2
        dp = (2 * nproc) // mp_
        mesh2 = Mesh(np.asarray(devs).reshape(mp_, dp).T,
                     axis_names=("data", "model"))
        n_clusters, dim = 8, 16
        rows = 4 * dp
        rng = np.random.default_rng(41)     # same data on every process
        x_host = rng.normal(size=(rows, dim)).astype(np.float32)
        c_host = rng.normal(size=(n_clusters, dim)).astype(np.float32)
        step2 = jax.jit(jax.shard_map(
            functools.partial(mnmg_lloyd_step,
                              n_clusters=n_clusters // mp_,
                              data_axis="data", model_axis="model"),
            mesh=mesh2,
            in_specs=(P("data"), P("model")),
            out_specs=(P("model"), P(), P("data"))),
            out_shardings=(NamedSharding(mesh2, P()),
                           NamedSharding(mesh2, P()),
                           NamedSharding(mesh2, P())))
        x2 = jax.make_array_from_callback(
            x_host.shape, NamedSharding(mesh2, P("data")),
            lambda idx: x_host[idx])
        c2 = jax.make_array_from_callback(
            c_host.shape, NamedSharding(mesh2, P("model")),
            lambda idx: c_host[idx])
        new_c, inertia, labels = step2(x2, c2)
        new_c_h = np.asarray(new_c)
        labels_h = np.asarray(labels)
        # full numpy oracle for one Lloyd step on the replicated data
        d = ((x_host[:, None] - c_host[None]) ** 2).sum(-1)
        want_labels = d.argmin(1)
        np.testing.assert_array_equal(labels_h, want_labels)
        np.testing.assert_allclose(float(inertia), d.min(1).sum(),
                                   rtol=1e-4)
        want_c = c_host.copy()              # empty clusters keep old rows
        for cl in range(n_clusters):
            members = x_host[want_labels == cl]
            if members.shape[0]:
                want_c[cl] = members.mean(0)
        np.testing.assert_allclose(new_c_h, want_c, rtol=1e-3, atol=1e-3)

    box.close()
    print(f"MP_WORKER_OK {pid}", flush=True)


if __name__ == "__main__":
    main()
