"""Worker process for the multi-process comms test tier (run by
test_multiprocess.py; the analogue of the code raft-dask ships to each dask
worker in _func_init_all, ref comms.py:414-505).

Usage: python _mp_worker.py <pid> <nproc> <coord_port> <p2p_port0> <p2p_port1>
"""

import os
import sys


def main():
    pid, nproc, coord_port = (int(a) for a in sys.argv[1:4])
    p2p_ports = [int(a) for a in sys.argv[4:4 + nproc]]

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax

    jax.config.update("jax_platforms", "cpu")

    from raft_tpu.comms.bootstrap import initialize_distributed

    initialize_distributed(f"localhost:{coord_port}", nproc, pid)

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    # --- device-side collective across processes (XLA/Gloo path) ---------
    devs = jax.devices()
    assert len(devs) == 2 * nproc, f"global devices {len(devs)}"
    mesh = Mesh(np.asarray(devs), ("data",))
    local = np.full((2, 4), float(pid + 1), np.float32)
    arr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("data")), local)
    total = jax.jit(lambda a: jnp.sum(a),
                    out_shardings=NamedSharding(mesh, P()))(arr)
    expect = sum(2 * 4 * (r + 1) for r in range(nproc))
    assert float(total) == expect, (float(total), expect)

    # --- host p2p across processes (TcpMailbox through MeshComms) --------
    from raft_tpu.comms.comms import MeshComms
    from raft_tpu.comms.tcp_mailbox import TcpMailbox

    addrs = [f"127.0.0.1:{p}" for p in p2p_ports]
    box = TcpMailbox(pid, addrs)
    comms = MeshComms(mesh, axis_name="data", rank=pid, _mailbox=box)
    payload = np.arange(8, dtype=np.float32) + 100 * pid
    comms.isend(payload, dest=(pid + 1) % nproc, tag=7)
    req = comms.irecv(source=(pid - 1) % nproc, tag=7)
    (got,) = comms.waitall([req])
    src = (pid - 1) % nproc
    np.testing.assert_array_equal(got, np.arange(8, dtype=np.float32)
                                  + 100 * src)
    box.close()
    print(f"MP_WORKER_OK {pid}", flush=True)


if __name__ == "__main__":
    main()
