"""ISSUE 14 bit-identity matrix for the unified epilogue layer.

Two tiers:

* primitive oracles — each epilogue primitive against the exact
  pre-refactor spelling it replaced (jax.lax.argmin, jax.nn.one_hot,
  the inline iota-compare one-hots, the elastic fit's numpy body),
  bitwise where the refactor claims expression identity;
* consumer witnesses — each rewired consumer (kmeans single / mnmg,
  fused + chunked-radix kNN, IVF full-probe, dense + CSR select_k)
  against an independent oracle, including tie and NaN rows, plus the
  strip-width invariance contract (any ``sw`` is output-identical).

Wired into ci/smoke.sh as the refactor's regression gate.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_tpu.matrix import epilogue
from raft_tpu.matrix.epilogue import (argmin_ref, assign_onehot,
                                      host_assign_update, insert_drain_ref,
                                      iota_argmin, label_onehot,
                                      masked_fold_ref, masked_topk,
                                      onehot_histogram, onehot_histogram_ref,
                                      onehot_pair, resolve_tn_sw,
                                      row_min_arg, slot_onehot)


def _tie_nan_block(m=16, n=96, seed=0, with_nan=True):
    """Distance-like block with exact-tie rows and (optionally) NaN."""
    rng = np.random.default_rng(seed)
    d = rng.normal(size=(m, n)).astype(np.float32)
    d[1, 10] = d[1, 70] = d[1].min() - 1.0    # exact tie, two columns
    d[2, :] = 3.25                            # whole row tied
    if with_nan:
        d[3, 5] = np.nan                      # NaN among finite
        d[4, :4] = np.nan                     # NaNs then finite
    return d


class TestPrimitiveOracles:
    def test_iota_argmin_matches_lax_argmin(self):
        d = jnp.asarray(_tie_nan_block())
        ref_val, ref_arg = argmin_ref(d)
        col, minval, arg = iota_argmin(d, d.shape[1])
        assert col.shape == d.shape
        np.testing.assert_array_equal(np.asarray(minval[:, 0]),
                                      np.asarray(ref_val))
        np.testing.assert_array_equal(np.asarray(arg[:, 0]),
                                      np.asarray(ref_arg))

    def test_iota_argmin_traced_n_valid(self):
        d = jnp.asarray(_tie_nan_block(with_nan=False))
        n_valid = jnp.int32(d.shape[1] - 7)
        _, minval, arg = iota_argmin(d, n_valid)
        masked = jnp.where(jnp.arange(d.shape[1])[None, :] < n_valid,
                           d, jnp.inf)
        ref_val, ref_arg = argmin_ref(masked)
        np.testing.assert_array_equal(np.asarray(minval[:, 0]),
                                      np.asarray(ref_val))
        np.testing.assert_array_equal(np.asarray(arg[:, 0]),
                                      np.asarray(ref_arg))

    def test_iota_argmin_finite_flag_identical_on_finite(self):
        d = jnp.asarray(_tie_nan_block(with_nan=False))
        _, mv0, a0 = iota_argmin(d, d.shape[1], finite=False)
        _, mv1, a1 = iota_argmin(d, d.shape[1], finite=True)
        np.testing.assert_array_equal(np.asarray(mv0), np.asarray(mv1))
        np.testing.assert_array_equal(np.asarray(a0), np.asarray(a1))

    def test_row_min_arg_first_min_ties(self):
        pool = jnp.asarray(_tie_nan_block(with_nan=False))
        col = jax.lax.broadcasted_iota(jnp.int32, pool.shape, 1)
        pm, pidx = row_min_arg(pool, col)
        ref_val, ref_arg = argmin_ref(pool)
        np.testing.assert_array_equal(np.asarray(pm[:, 0]),
                                      np.asarray(ref_val))
        np.testing.assert_array_equal(np.asarray(pidx[:, 0]),
                                      np.asarray(ref_arg))

    def test_label_onehot_matches_jax_nn_one_hot(self):
        rng = np.random.default_rng(1)
        labels = jnp.asarray(rng.integers(0, 9, size=64), jnp.int32)
        # out-of-range sentinel (the padded-row convention): zero row
        labels = labels.at[5].set(8)
        for dtype in (jnp.float32, jnp.bfloat16):
            got = label_onehot(labels, 8, dtype=dtype)
            want = jax.nn.one_hot(labels, 8, dtype=dtype)
            np.testing.assert_array_equal(np.asarray(got),
                                          np.asarray(want))
        mask = jnp.asarray(rng.integers(0, 2, size=64), bool)
        got = label_onehot(labels, 8, mask=mask)
        want = jax.nn.one_hot(labels, 8) * mask[:, None]
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_assign_onehot_shared_iota_vs_one_hot(self):
        d = jnp.asarray(_tie_nan_block(with_nan=False))
        col, _, arg = iota_argmin(d, d.shape[1])
        got = assign_onehot(col, arg).astype(jnp.float32)
        want = jax.nn.one_hot(jax.lax.argmin(d, 1, jnp.int32),
                              d.shape[1])
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        row_mask = (jnp.arange(d.shape[0]) < 10)[:, None]
        got = assign_onehot(col, arg, row_mask).astype(jnp.float32)
        np.testing.assert_array_equal(
            np.asarray(got), np.asarray(want * row_mask))

    def test_onehot_histogram_matches_ref_and_bincount(self):
        rng = np.random.default_rng(2)
        tm, tl = 8, 256
        hi = jnp.asarray(rng.integers(0, 16, size=(tm, tl)), jnp.int32)
        lo = jnp.asarray(rng.integers(0, 16, size=(tm, tl)), jnp.int32)
        active = jnp.asarray(rng.integers(0, 2, size=(tm, tl)), bool)
        got = onehot_histogram(hi, lo, active)
        ref = onehot_histogram_ref(hi, lo, active)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
        digit = (np.asarray(hi) * 16 + np.asarray(lo))
        act = np.asarray(active)
        for r in range(tm):
            want = np.bincount(digit[r][act[r]], minlength=256)
            np.testing.assert_array_equal(
                np.asarray(got)[r].reshape(-1), want.astype(np.float32))

    def test_onehot_pair_sentinel_matches_no_row(self):
        hi = jnp.asarray([[-1, 3]], jnp.int32)     # -1: emitted-slot mark
        lo = jnp.asarray([[0, 5]], jnp.int32)
        ohhi, ohlo = onehot_pair(hi, lo, 16, 16)
        assert float(jnp.sum(ohhi[:, :, 0])) == 0.0
        assert float(jnp.sum(ohhi[:, :, 1])) == 1.0
        assert float(jnp.sum(ohlo)) == 2.0

    def test_slot_onehot(self):
        idx = jnp.asarray([[3], [0]], jnp.int32)
        oh = slot_onehot(idx, 16)
        assert oh.shape == (2, 16, 1)
        np.testing.assert_array_equal(
            np.asarray(oh[:, :, 0]),
            np.asarray(jax.nn.one_hot(idx[:, 0], 16)))

    def test_masked_fold_ref_tie_keeps_earlier(self):
        bv = jnp.asarray([1.0, 5.0], jnp.float32)
        bi = jnp.asarray([7, 7], jnp.int32)
        nv, ni = masked_fold_ref(bv, bi, jnp.asarray([1.0, 4.0]),
                                 jnp.asarray([2, 2], jnp.int32), 100)
        # strict <: the tied newcomer (val 1.0, idx 102) loses to idx 7
        np.testing.assert_array_equal(np.asarray(nv), [1.0, 4.0])
        np.testing.assert_array_equal(np.asarray(ni), [7, 102])

    def test_insert_drain_ref_ties_and_nan(self):
        v = _tie_nan_block()
        vals, idx = insert_drain_ref(v, 4)
        clean = np.where(np.isnan(v), np.inf, v)
        order = np.argsort(clean, axis=1, kind="stable")[:, :4]
        np.testing.assert_array_equal(np.asarray(idx), order)
        np.testing.assert_array_equal(
            np.asarray(vals), np.take_along_axis(clean, order, axis=1))

    def test_host_assign_update_matches_inline_spelling(self):
        rng = np.random.default_rng(3)
        xs = rng.normal(size=(64, 8))
        ws = rng.uniform(0.5, 2.0, size=64)
        c = rng.normal(size=(5, 8))
        labels, sums, counts, best = host_assign_update(xs, ws, c)
        # the exact pre-refactor elastic body
        d2 = ((xs * xs).sum(1)[:, None] - 2.0 * (xs @ c.T)
              + (c * c).sum(1)[None, :])
        want_labels = np.argmin(d2, axis=1)
        want_sums = np.zeros((5, 8), np.float64)
        np.add.at(want_sums, want_labels, xs * ws[:, None])
        want_counts = np.zeros(5, np.float64)
        np.add.at(want_counts, want_labels, ws)
        np.testing.assert_array_equal(labels, want_labels)
        np.testing.assert_array_equal(sums, want_sums)
        np.testing.assert_array_equal(counts, want_counts)
        np.testing.assert_array_equal(
            best, np.maximum(d2[np.arange(64), want_labels], 0.0))

    def test_resolve_tn_sw_contract(self):
        # sw=None picks the spent lever when it divides the request
        assert resolve_tn_sw(1024, None, 10_000) == (1024, epilogue.DRAIN_SW)
        assert resolve_tn_sw(2048, None, 10_000) == (2048, epilogue.DRAIN_SW)
        # ... and degrades to whole-tile when it cannot strip the ask
        assert resolve_tn_sw(128, None, 10_000) == (128, 0)
        # clamp-induced indivisibility degrades instead of erroring
        assert resolve_tn_sw(2048, None, 384) == (384, 0)
        assert resolve_tn_sw(2048, 256, 384) == (384, 0)
        # an sw that never divided the caller's ask is an error
        with pytest.raises(ValueError):
            resolve_tn_sw(128, 256, 10_000)
        with pytest.raises(ValueError):
            resolve_tn_sw(1024, 100, 10_000)

    def test_argminmax_shim_reexports(self):
        from raft_tpu.matrix import argminmax

        assert argminmax.argmin is epilogue.argmin
        assert argminmax.argmax is epilogue.argmax
        m = jnp.asarray([[3.0, 1.0, 1.0], [0.0, 2.0, -5.0]])
        np.testing.assert_array_equal(
            np.asarray(argminmax.argmin(None, m)), [1, 2])
        np.testing.assert_array_equal(
            np.asarray(argminmax.argmax(None, m)), [0, 1])


class TestConsumerBitIdentity:
    def test_insert_select_sw_invariance_and_ref(self):
        """Dense select_k drain path: any strip width is bit-identical,
        and matches the first-index-tie / NaN-sorts-last oracle."""
        from raft_tpu.matrix.topk_insert import insert_select

        rng = np.random.default_rng(4)
        v = rng.normal(size=(16, 512)).astype(np.float32)
        v[0, 100] = v[0, 400] = v[0].min() - 1.0     # cross-strip tie
        v[1, 7] = np.nan                              # NaN never inserts
        v[2, :] = 1.5                                 # fully tied row
        ref_v, ref_i = insert_drain_ref(v, 5)
        outs = [insert_select(jnp.asarray(v), 5, tn=512, sw=sw)
                for sw in (0, 128, 256)]
        for vals, idx in outs:
            np.testing.assert_array_equal(np.asarray(vals),
                                          np.asarray(ref_v))
            np.testing.assert_array_equal(np.asarray(idx),
                                          np.asarray(ref_i))

    def test_knn_fused_sw_invariance(self):
        """The spent drain lever (sw=None -> DRAIN_SW) is output-
        identical to the whole-tile drain, duplicates and all."""
        from raft_tpu.neighbors.fused_topk import knn_fused

        rng = np.random.default_rng(5)
        q = rng.normal(size=(8, 16)).astype(np.float32)
        db = rng.normal(size=(300, 16)).astype(np.float32)
        db[250] = db[3]                  # duplicate: smallest index wins
        v0, i0 = knn_fused(q, db, 4, tn=256, sw=0)
        v1, i1 = knn_fused(q, db, 4, tn=256, sw=128)
        vd, idd = knn_fused(q, db, 4, tn=256)        # sw=None -> 256
        np.testing.assert_array_equal(np.asarray(v0), np.asarray(v1))
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        np.testing.assert_array_equal(np.asarray(v0), np.asarray(vd))
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(idd))
        assert not np.any(np.asarray(i0) == 250)     # 3 wins the tie

    def test_knn_chunked_matches_scan_indices(self):
        """masked_topk rewire: the chunked-radix and scan formulations
        agree with the numpy oracle on the same inputs."""
        from raft_tpu.neighbors.brute_force import _knn_chunked, _knn_scan

        rng = np.random.default_rng(6)
        q = rng.normal(size=(4, 12)).astype(np.float32)
        db = rng.normal(size=(700, 12)).astype(np.float32)
        d2 = ((q ** 2).sum(1)[:, None] - 2.0 * q @ db.T
              + (db ** 2).sum(1)[None, :])
        want = np.argsort(d2, axis=1, kind="stable")[:, :5]
        _, i_scan = _knn_scan(jnp.asarray(q), jnp.asarray(db), 5, 256,
                              "l2")
        _, i_chunk = _knn_chunked(jnp.asarray(q), jnp.asarray(db), 5,
                                  256, "l2")
        np.testing.assert_array_equal(np.asarray(i_scan), want)
        np.testing.assert_array_equal(np.asarray(i_chunk), want)

    def test_ivf_full_probe_matches_brute_force(self):
        """IVF-Flat probe epilogue (masked_topk): full probe == exact."""
        import raft_tpu
        from raft_tpu.neighbors import brute_force, ivf_flat

        res = raft_tpu.device_resources(seed=0)
        rng = np.random.default_rng(7)
        X = rng.normal(size=(512, 16)).astype(np.float32)
        idx = ivf_flat.build(res, X, 8, seed=0, max_iter=4)
        _, ivf_i = ivf_flat.search(res, idx, X[:16], k=5, nprobe=8)
        _, bf_i = brute_force.knn(res, X, X[:16], k=5)
        for r in range(16):
            assert set(np.asarray(ivf_i)[r]) == set(np.asarray(bf_i)[r])

    def test_select_k_csr_matches_dense_rows(self):
        """CSR select_k rides the same dense epilogue: bit-identical to
        dense select_k over the materialized padded rows."""
        import scipy.sparse as sp

        import raft_tpu
        from raft_tpu.core.sparse_types import CSRMatrix
        from raft_tpu.matrix import select_k as dense_select_k
        from raft_tpu.sparse.matrix import select_k as csr_select_k

        res = raft_tpu.device_resources(seed=0)
        rng = np.random.default_rng(8)
        dense = rng.normal(size=(32, 64)).astype(np.float32)
        dense[dense > 0.4] = 0.0                     # sparsify
        dense[3, 10] = dense[3, 50] = dense[3].min() - 1.0   # tie row
        dense[5, :] = 0.0
        dense[5, 2] = -1.0                           # short row (1 nnz)
        csr = CSRMatrix.from_scipy(sp.csr_matrix(dense))
        vals, idx = csr_select_k(res, csr, 3)
        # materialize exactly what the CSR path scatters, run dense
        padded = np.full((32, max(int(np.diff(csr.indptr).max()), 3)),
                         np.inf, np.float32)
        cols = np.full_like(padded, -1, dtype=np.int64)
        for r in range(32):
            nz = np.flatnonzero(dense[r])
            padded[r, :len(nz)] = dense[r, nz]
            cols[r, :len(nz)] = nz
        dv, dp = dense_select_k(res, jnp.asarray(padded), 3)
        np.testing.assert_array_equal(np.asarray(vals), np.asarray(dv))
        want_idx = np.take_along_axis(cols, np.asarray(dp), axis=1)
        want_idx[np.asarray(vals) == np.inf] = -1
        np.testing.assert_array_equal(np.asarray(idx), want_idx)

    def test_kmeans_fit_matches_numpy_lloyd(self):
        """Single-rank consumer: the shared-iota assignment + one-hot
        update reproduces the numpy Lloyd iteration label-for-label."""
        import raft_tpu
        from raft_tpu.cluster.kmeans import (KMeansInit, KMeansParams,
                                             kmeans_fit)

        res = raft_tpu.device_resources(seed=0)
        rng = np.random.default_rng(9)
        X = np.concatenate([rng.normal(loc=4 * i, size=(64, 8))
                            for i in range(3)]).astype(np.float32)
        init = X[[0, 64, 128]]
        params = KMeansParams(n_clusters=3, init=KMeansInit.ARRAY,
                              max_iter=5, tol=0.0, seed=0)
        c, inertia, labels, _ = kmeans_fit(res, params, X,
                                           centroids=init)
        cn = init.astype(np.float64)
        for _ in range(5):
            d2 = ((X ** 2).sum(1)[:, None] - 2.0 * X @ cn.T
                  + (cn ** 2).sum(1)[None, :])
            want_labels = d2.argmin(1)
            cn = np.stack([X[want_labels == i].mean(0)
                           for i in range(3)])
        np.testing.assert_array_equal(np.asarray(labels), want_labels)
        np.testing.assert_allclose(np.asarray(c), cn, atol=1e-3)

    def test_mnmg_block_onehot_spelling(self):
        """The mnmg model-axis block update's label_onehot call is the
        exact pre-refactor inline spelling, bit for bit."""
        rng = np.random.default_rng(10)
        kb = 8
        local = jnp.asarray(rng.integers(0, 2 * kb, size=128), jnp.int32)
        in_block = (local >= 0) & (local < kb)
        got = label_onehot(local, kb, mask=in_block,
                           dtype=jnp.float32)
        col = jax.lax.broadcasted_iota(jnp.int32, (128, kb), 1)
        want = ((col == local[:, None])
                & in_block[:, None]).astype(jnp.float32)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_kmeans_mnmg_step_matches_oracle(self, mesh8):
        """mnmg consumer: shard_map Lloyd step over the 2-D mesh lands
        the numpy labels exactly (the label_onehot rewire)."""
        from jax.sharding import Mesh, PartitionSpec as P

        from raft_tpu.cluster.kmeans import mnmg_lloyd_step

        devs = np.asarray(jax.devices()[:8]).reshape(4, 2)
        mesh = Mesh(devs, axis_names=("data", "model"))
        rng = np.random.default_rng(11)
        X = rng.normal(size=(256, 16)).astype(np.float32)
        C = rng.normal(size=(8, 16)).astype(np.float32)

        def step(x, cblk):
            return mnmg_lloyd_step(x, cblk, n_clusters=8,
                                   data_axis="data", model_axis="model")

        f = jax.jit(jax.shard_map(
            step, mesh=mesh, in_specs=(P("data"), P("model")),
            out_specs=(P("model"), P(), P("data")), check_vma=False))
        _, _, labels = f(X, C)
        d2 = ((X ** 2).sum(1)[:, None] - 2.0 * X @ C.T
              + (C ** 2).sum(1)[None, :])
        np.testing.assert_array_equal(np.asarray(labels), d2.argmin(1))

    def test_masked_topk_radix_parity(self):
        """use_radix routing: both spellings select the same elements
        under a validity mask (value parity; radix emits its own
        tie order within equal values)."""
        rng = np.random.default_rng(12)
        d = jnp.asarray(rng.normal(size=(8, 1024)).astype(np.float32))
        valid = jax.lax.broadcasted_iota(jnp.int32, d.shape, 1) < 1000
        v_top, i_top = masked_topk(d, valid, 6, use_radix=False)
        v_rad, i_rad = masked_topk(d, valid, 6, use_radix=True)
        np.testing.assert_allclose(np.asarray(v_top), np.asarray(v_rad),
                                   rtol=0, atol=0)
        np.testing.assert_array_equal(np.sort(np.asarray(i_top), 1),
                                      np.sort(np.asarray(i_rad), 1))
        assert int(jnp.max(i_rad)) < 1000
