"""Slot-grid SpMV/SpMM vs scipy oracles.

The grid formulation (sparse/grid_spmv.py) re-packs the pattern host-side
and reduces with a segmented scan, so beyond value agreement these tests
pin the STRUCTURAL contracts: packer rules (run contiguity, cross-sub-row
chaining, tile span), C++/Python packer equivalence, pad-slot isolation
(inf/nan x never contaminates other rows), and the jit/pytree surface.
"""

import numpy as np
import pytest
import scipy.sparse as sp

import jax
import jax.numpy as jnp

from raft_tpu.core.sparse_types import CSRMatrix
from raft_tpu.sparse import grid_spmv
from raft_tpu.sparse.grid_spmv import (GridSpMV, _pack, _pack_python,
                                       prepare, spmm, spmv)


def _random_csr(rng, n_rows, n_cols, density):
    dense = rng.normal(size=(n_rows, n_cols)).astype(np.float32)
    dense[rng.uniform(size=(n_rows, n_cols)) > density] = 0.0
    return sp.csr_matrix(dense)


def _check(A, x=None, shard_w=None, rtol=2e-5, atol=2e-5):
    rng = np.random.default_rng(99)
    if x is None:
        x = rng.normal(size=A.shape[1]).astype(np.float32)
    kw = {} if shard_w is None else {"shard_w": shard_w}
    fmt = prepare(CSRMatrix.from_scipy(A), **kw)
    y = np.asarray(spmv(fmt, jnp.asarray(x)))
    ref = A @ x
    np.testing.assert_allclose(y, ref, rtol=rtol, atol=atol)
    return fmt


class TestGridSpMV:
    def test_random(self):
        rng = np.random.default_rng(0)
        _check(_random_csr(rng, 500, 700, 0.05))

    def test_multi_shard(self):
        rng = np.random.default_rng(1)
        fmt = _check(_random_csr(rng, 300, 900, 0.04), shard_w=256)
        assert fmt.n_shards == 4

    def test_skewed_hub_rows_and_cols(self):
        # power-law-ish: a hub row (long runs chaining across sub-rows
        # and tiles) and a hub column (gather index repetition)
        rng = np.random.default_rng(2)
        n = 600
        r = np.concatenate([np.full(400, 37), rng.integers(0, n, 2000),
                            np.full(300, 599)])
        c = np.concatenate([rng.integers(0, n, 400), np.full(2000, 11),
                            rng.integers(0, n, 300)])
        d = rng.normal(size=r.size).astype(np.float32)
        A = sp.csr_matrix((d, (r, c)), shape=(n, n))
        A.sum_duplicates()
        _check(A, shard_w=256)

    def test_sparse_tail_rows(self):
        # mostly-empty matrix: tiles close on the 8-window span rule
        rng = np.random.default_rng(3)
        n = 4000
        r = np.sort(rng.choice(n, 60, replace=False)).astype(np.int32)
        c = rng.integers(0, n, 60).astype(np.int32)
        d = rng.normal(size=60).astype(np.float32)
        _check(sp.csr_matrix((d, (r, c)), shape=(n, n)), shard_w=512)

    def test_empty_rows_and_empty_matrix(self):
        rng = np.random.default_rng(4)
        A = _random_csr(rng, 200, 200, 0.02)
        A[50:150] = 0
        A.eliminate_zeros()
        _check(A)
        Z = sp.csr_matrix((64, 64), dtype=np.float32)
        fmt = prepare(CSRMatrix.from_scipy(Z))
        y = np.asarray(spmv(fmt, jnp.ones(64, jnp.float32)))
        np.testing.assert_array_equal(y, np.zeros(64))

    def test_single_dense_row(self):
        # one row owning every column: maximal cross-sub-row chaining
        n = 700
        rng = np.random.default_rng(5)
        d = rng.normal(size=n).astype(np.float32)
        A = sp.csr_matrix((d, (np.zeros(n, np.int64), np.arange(n))),
                          shape=(4, n))
        _check(A, shard_w=256)

    def test_stored_zero_propagates_inf_pad_does_not(self):
        # A stored zero at (0, 1) must see x[1] = inf (0 * inf = nan per
        # IEEE, matching cuSPARSE); pad slots gather arbitrary x but are
        # masked BEFORE the multiply, so row 1 stays finite.
        A = sp.csr_matrix(np.array([[2.0, 0.0], [3.0, 0.0]], np.float32))
        A[0, 1] = 0.0   # explicit stored zero
        x = np.array([1.0, np.inf], np.float32)
        fmt = prepare(CSRMatrix.from_scipy(sp.csr_matrix(A)))
        y = np.asarray(spmv(fmt, jnp.asarray(x)))
        assert np.isnan(y[0])
        assert y[1] == 3.0

    def test_inf_x_with_padding_isolated(self):
        rng = np.random.default_rng(6)
        A = _random_csr(rng, 100, 300, 0.05)
        x = rng.normal(size=300).astype(np.float32)
        x[7] = np.inf
        fmt = prepare(CSRMatrix.from_scipy(A), shard_w=256)
        y = np.asarray(spmv(fmt, jnp.asarray(x)))
        ref = A @ x
        finite = np.isfinite(ref)
        np.testing.assert_allclose(y[finite], ref[finite], rtol=1e-4,
                                   atol=1e-4)
        np.testing.assert_array_equal(np.isfinite(y), finite)

    def test_wide_matrix_shard_boundary_columns(self):
        # entries sitting exactly at shard edges
        n_cols = 1024
        r = np.arange(8, dtype=np.int64) % 4
        c = np.array([0, 255, 256, 511, 512, 767, 768, 1023])
        order = np.argsort(r, kind="stable")
        A = sp.csr_matrix((np.ones(8, np.float32), (r[order], c[order])),
                          shape=(4, n_cols))
        x = np.arange(n_cols, dtype=np.float32)
        _check(A, x=x, shard_w=256)

    def test_spmm(self):
        rng = np.random.default_rng(7)
        A = _random_csr(rng, 300, 400, 0.05)
        B = rng.normal(size=(400, 5)).astype(np.float32)
        fmt = prepare(CSRMatrix.from_scipy(A))
        C = np.asarray(spmm(fmt, jnp.asarray(B)))
        np.testing.assert_allclose(C, A @ B, rtol=2e-5, atol=2e-5)

    @pytest.mark.parametrize("k", [1, 8, 13, 20])
    def test_spmm_k_batched(self, k):
        # the fused KT-group kernels across k < KT, k == KT, k spanning
        # groups with a ragged tail, and the k == 1 SpMV fall-through;
        # multi-shard so the chunk->tile 5-D view gets a tpc > 1 case
        rng = np.random.default_rng(17)
        A = _random_csr(rng, 350, 900, 0.04)
        B = rng.normal(size=(900, k)).astype(np.float32)
        fmt = prepare(CSRMatrix.from_scipy(A), shard_w=256)
        assert fmt.n_shards == 4
        C = np.asarray(spmm(fmt, jnp.asarray(B)))
        np.testing.assert_allclose(C, A @ B, rtol=2e-5, atol=2e-5)

    def test_spmm_k_batched_hub_pattern(self):
        # hub rows/cols: long runs chain across sub-rows and tiles in
        # every column of the group (the carry path per q)
        rng = np.random.default_rng(18)
        n = 600
        r = np.concatenate([np.full(400, 37), rng.integers(0, n, 2000),
                            np.full(300, 599)])
        c = np.concatenate([rng.integers(0, n, 400), np.full(2000, 11),
                            rng.integers(0, n, 300)])
        d = rng.normal(size=r.size).astype(np.float32)
        A = sp.csr_matrix((d, (r, c)), shape=(n, n))
        A.sum_duplicates()
        B = rng.normal(size=(n, 9)).astype(np.float32)
        fmt = prepare(CSRMatrix.from_scipy(A))
        C = np.asarray(spmm(fmt, jnp.asarray(B)))
        np.testing.assert_allclose(C, A @ B, rtol=2e-4, atol=2e-4)

    def test_jit_and_pytree_surface(self):
        rng = np.random.default_rng(8)
        A = _random_csr(rng, 200, 200, 0.05)
        fmt = prepare(CSRMatrix.from_scipy(A))

        @jax.jit
        def f(fmt, x):
            return spmv(fmt, x)

        x = rng.normal(size=200).astype(np.float32)
        y = np.asarray(f(fmt, jnp.asarray(x)))
        np.testing.assert_allclose(y, A @ x, rtol=2e-5, atol=2e-5)
        leaves, treedef = jax.tree_util.tree_flatten(fmt)
        fmt2 = jax.tree_util.tree_unflatten(treedef, leaves)
        assert isinstance(fmt2, GridSpMV)
        y2 = np.asarray(spmv(fmt2, jnp.asarray(x)))
        np.testing.assert_array_equal(y, y2)

    def test_padded_bucketed_csr_input(self):
        # CSRMatrix nnz-bucket padding: pad entries (data 0, col 0) must
        # be excluded by the logical-nnz slice in prepare()
        rng = np.random.default_rng(9)
        A = _random_csr(rng, 100, 100, 0.05)
        csr = CSRMatrix.from_scipy(A, pad=True)
        assert csr.nnz > int(np.asarray(csr.indptr)[-1])
        fmt = prepare(csr)
        assert fmt.nnz == int(np.asarray(csr.indptr)[-1])
        x = rng.normal(size=100).astype(np.float32)
        np.testing.assert_allclose(np.asarray(spmv(fmt, jnp.asarray(x))),
                                   A @ x, rtol=2e-5, atol=2e-5)


class TestIntegration:
    def test_linalg_spmv_dispatch(self, monkeypatch):
        rng = np.random.default_rng(20)
        A = _random_csr(rng, 150, 150, 0.05)
        csr = CSRMatrix.from_scipy(A)
        fmt = prepare(csr)
        x = rng.normal(size=150).astype(np.float32)
        from raft_tpu.sparse import linalg as slinalg

        y_grid = np.asarray(slinalg.spmv(fmt, jnp.asarray(x)))
        y_seg = np.asarray(slinalg.spmv(csr, jnp.asarray(x)))
        np.testing.assert_allclose(y_grid, y_seg, rtol=2e-5, atol=2e-5)
        B = rng.normal(size=(150, 4)).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(slinalg.spmm(fmt, jnp.asarray(B))),
            np.asarray(slinalg.spmm(csr, jnp.asarray(B))),
            rtol=2e-5, atol=2e-5)
        # env force knob validation
        monkeypatch.setenv("RAFT_TPU_SPMV", "bogus")
        with pytest.raises(ValueError):
            slinalg.spmv_method(csr)

    def test_spmm_honors_forced_ell(self, monkeypatch):
        # ADVICE r4: a forced RAFT_TPU_SPMV=ell must route spmm through
        # the ELL slab formulation, not silently fall to segment —
        # env-forced A/B comparisons must measure the path they name
        monkeypatch.setenv("RAFT_TPU_SPMV", "ell")
        rng = np.random.default_rng(22)
        A = _random_csr(rng, 120, 90, 0.06)
        csr = CSRMatrix.from_scipy(A)
        from raft_tpu.sparse import linalg as slinalg

        B = rng.normal(size=(90, 5)).astype(np.float32)
        called = {}
        from raft_tpu.sparse import ell as ell_mod

        real_spmm = ell_mod.spmm

        def spy(a, b):
            called["ell"] = True
            return real_spmm(a, b)

        monkeypatch.setattr(ell_mod, "spmm", spy)
        out = np.asarray(slinalg.spmm(csr, jnp.asarray(B)))
        assert called.get("ell")
        np.testing.assert_allclose(out, A @ B, rtol=2e-5, atol=2e-5)

    def test_auto_grid_pad_ratio_gate(self, monkeypatch, request):
        # ADVICE r4: the auto upgrade must reject a plan whose slot grid
        # blows past the pad-ratio bound (scattered rows >8 windows apart
        # pad a full 1024-slot tile per entry) and fall back to segment
        from raft_tpu.sparse import linalg as slinalg
        from raft_tpu.util.pallas_utils import use_interpret

        monkeypatch.setattr(slinalg, "_GRID_MIN_NNZ", 32)
        monkeypatch.setenv("RAFT_TPU_PALLAS_INTERPRET", "0")
        use_interpret.cache_clear()          # env change must be seen
        request.addfinalizer(use_interpret.cache_clear)
        n_rows = 200_000
        rows = np.arange(64) * 3000          # 23 windows apart each
        cols = np.arange(64) % 128
        A = sp.csr_matrix((np.ones(64, np.float32), (rows, cols)),
                          shape=(n_rows, 128))
        csr = CSRMatrix.from_scipy(A)
        assert slinalg.spmv_method(csr) == "auto"
        assert getattr(csr, "_grid_plan", None) is None  # rejected → freed
        # dense consecutive pattern: accepted, plan memoized
        B = _random_csr(np.random.default_rng(5), 64, 128, 0.5)
        csr2 = CSRMatrix.from_scipy(B)
        assert slinalg.spmv_method(csr2) == "grid"
        assert csr2._grid_plan is not None
        assert csr2._grid_plan.pad_ratio <= slinalg._GRID_MAX_PAD_RATIO

    def test_auto_grid_keeps_x64_promotion(self, monkeypatch, request):
        # ADVICE r4: with f32 data and f64 x under x64, the result dtype
        # must not flip to f32 because nnz crossed the grid threshold —
        # the auto path requires f32 on both sides
        from raft_tpu.sparse import linalg as slinalg
        from raft_tpu.util.pallas_utils import use_interpret

        monkeypatch.setattr(slinalg, "_GRID_MIN_NNZ", 16)
        monkeypatch.setenv("RAFT_TPU_PALLAS_INTERPRET", "0")
        use_interpret.cache_clear()          # env change must be seen
        request.addfinalizer(use_interpret.cache_clear)
        rng = np.random.default_rng(23)
        A = _random_csr(rng, 100, 100, 0.08)
        csr = CSRMatrix.from_scipy(A)
        x64 = rng.normal(size=100)            # float64
        prev = jax.config.jax_enable_x64
        jax.config.update("jax_enable_x64", True)
        try:
            y = slinalg.spmv(csr, jnp.asarray(x64))
            assert y.dtype == jnp.float64     # segment path, promoted
            np.testing.assert_allclose(np.asarray(y), A @ x64,
                                       rtol=1e-6, atol=1e-6)
        finally:
            jax.config.update("jax_enable_x64", prev)

    def test_eigsh_on_grid_matches_scipy(self, monkeypatch):
        import scipy.sparse.linalg as spla

        from raft_tpu.sparse.solver.lanczos import eigsh

        monkeypatch.setenv("RAFT_TPU_SPMV", "grid")
        rng = np.random.default_rng(21)
        n = 150   # small: every restart re-runs 3 interpreted kernels
        dense = rng.normal(size=(n, n)).astype(np.float32)
        dense[rng.uniform(size=(n, n)) > 0.06] = 0.0
        A = sp.csr_matrix(dense + dense.T)
        ref = np.sort(spla.eigsh(A.astype(np.float64), k=2, which="SA",
                                 return_eigenvectors=False))
        vals, _ = eigsh(CSRMatrix.from_scipy(A), k=2, which="SA",
                        maxiter=60)
        np.testing.assert_allclose(np.sort(np.asarray(vals)), ref,
                                   rtol=2e-4, atol=2e-4)


class TestMNMGLanczos:
    def test_eigsh_mnmg_matches_scipy(self, mesh8):
        import scipy.sparse.linalg as spla

        from raft_tpu.sparse.solver import eigsh_mnmg

        rng = np.random.default_rng(30)
        n = 500   # NOT a multiple of 8: exercises the row-band padding
        dense = rng.normal(size=(n, n)).astype(np.float32)
        dense[rng.uniform(size=(n, n)) > 0.04] = 0.0
        A = sp.csr_matrix(dense + dense.T)
        vals, vecs = eigsh_mnmg(CSRMatrix.from_scipy(A), k=4, mesh=mesh8,
                                which="SA")
        ref = np.sort(spla.eigsh(A.astype(np.float64), k=4, which="SA",
                                 return_eigenvectors=False))
        np.testing.assert_allclose(np.sort(np.asarray(vals)), ref,
                                   rtol=3e-4, atol=3e-4)
        res = np.abs(A @ np.asarray(vecs)
                     - np.asarray(vecs) * np.asarray(vals)).max()
        assert res < 1e-2

    def test_eigsh_mnmg_agrees_with_single_device(self, mesh8):
        from raft_tpu.sparse.solver import eigsh, eigsh_mnmg

        rng = np.random.default_rng(31)
        n = 256
        dense = rng.normal(size=(n, n)).astype(np.float32)
        dense[rng.uniform(size=(n, n)) > 0.05] = 0.0
        A = sp.csr_matrix(dense + dense.T)
        csr = CSRMatrix.from_scipy(A)
        v1, _ = eigsh(csr, k=3, which="LA")
        v2, _ = eigsh_mnmg(csr, k=3, mesh=mesh8, which="LA")
        np.testing.assert_allclose(np.sort(np.asarray(v1)),
                                   np.sort(np.asarray(v2)),
                                   rtol=5e-4, atol=5e-4)

    def test_eigsh_mnmg_segment_gate_on_hub_row(self, mesh8):
        # a hub row blows the ELL width gate: the band formulation falls
        # back to segment sums and must still match scipy
        import scipy.sparse.linalg as spla

        from raft_tpu.sparse.solver import eigsh_mnmg

        rng = np.random.default_rng(9)
        n = 400
        dense = rng.normal(size=(n, n)).astype(np.float32)
        dense[rng.uniform(size=(n, n)) > 0.03] = 0.0
        dense[5, :] = rng.normal(size=n)
        A = sp.csr_matrix(dense + dense.T)
        vals, _ = eigsh_mnmg(CSRMatrix.from_scipy(A), k=3, mesh=mesh8,
                             which="LA")
        ref = np.sort(spla.eigsh(A.astype(np.float64), k=3, which="LA",
                                 return_eigenvectors=False))
        np.testing.assert_allclose(np.sort(np.asarray(vals)), ref,
                                   rtol=3e-4, atol=3e-4)

    def test_eigsh_mnmg_requires_mesh(self):
        from raft_tpu.sparse.solver import eigsh_mnmg

        with pytest.raises(ValueError):
            eigsh_mnmg(CSRMatrix.from_scipy(
                sp.eye(32, format="csr", dtype=np.float32)), k=2)


class TestMNMGWeakCC:
    def test_matches_single_device_and_scipy(self, mesh8):
        from scipy.sparse.csgraph import connected_components

        from raft_tpu.sparse import weak_cc, weak_cc_mnmg

        rng = np.random.default_rng(33)
        n = 700   # not a multiple of 8: exercises edge-band padding
        A = sp.csr_matrix(
            (rng.uniform(size=(n, n)) < 0.002).astype(np.float32))
        csr = CSRMatrix.from_scipy(A)
        l1 = np.asarray(weak_cc(None, csr))
        l2 = np.asarray(weak_cc_mnmg(None, csr, mesh8))
        np.testing.assert_array_equal(l1, l2)
        _, ref = connected_components(A, directed=False)
        fwd, bwd = {}, {}
        for a, b in zip(l2, ref):     # bijection = identical partitions
            assert fwd.setdefault(a, b) == b
            assert bwd.setdefault(b, a) == a
        # mask barriers agree too
        mask = rng.uniform(size=n) > 0.15
        np.testing.assert_array_equal(
            np.asarray(weak_cc(None, csr, mask=mask)),
            np.asarray(weak_cc_mnmg(None, csr, mesh8, mask=mask)))


class TestPacker:
    @pytest.mark.parametrize("seed", range(4))
    def test_native_matches_python(self, seed):
        rng = np.random.default_rng(seed)
        parts = [rng.integers(0, 2000, rng.integers(1, 4000))]
        if seed % 2:
            parts.append(np.full(rng.integers(1, 900), 777))
        rows = np.sort(np.concatenate(parts)).astype(np.int32)
        s_n, b_n = _pack(rows, 8)
        s_p, b_p = _pack_python(rows, 8)
        from raft_tpu import _native
        if _native.get_lib() is None:
            pytest.skip("no native toolchain")
        np.testing.assert_array_equal(s_n, s_p)
        np.testing.assert_array_equal(b_n, b_p)

    def test_packing_invariants(self):
        rng = np.random.default_rng(42)
        rows = np.sort(rng.integers(0, 3000, 5000)).astype(np.int32)
        slots, bases = _pack_python(rows, 8)
        assert len(slots) % grid_spmv.TILE_SLOTS == 0
        grid = slots.reshape(-1, grid_spmv.SUBROWS, grid_spmv.LANES)
        rgrid = np.where(grid >= 0, rows[np.maximum(grid, 0)], -1)
        for t in range(grid.shape[0]):
            tile_rows = rgrid[t][rgrid[t] >= 0]
            if tile_rows.size == 0:
                continue
            # span rule: all rows within 8 windows of the base
            assert (tile_rows >> 7).min() == bases[t]
            assert (tile_rows >> 7).max() - bases[t] < 8
            for s in range(grid_spmv.SUBROWS):
                r = rgrid[t, s]
                real = r >= 0
                # runs contiguous within a sub-row: each row id appears
                # in one consecutive stretch
                vals = r[real]
                changes = np.count_nonzero(np.diff(vals) != 0)
                assert changes == len(np.unique(vals)) - 1
                # crossing rule: a run continues to the next sub-row only
                # if it fills to lane 127
                if s + 1 < grid_spmv.SUBROWS and rgrid[t, s + 1, 0] >= 0:
                    if rgrid[t, s + 1, 0] in vals:
                        assert r[127] == rgrid[t, s + 1, 0]
        # every entry placed exactly once
        placed = np.sort(slots[slots >= 0])
        np.testing.assert_array_equal(placed, np.arange(len(rows)))


class TestGridSpMVFuzz:
    @pytest.mark.parametrize("seed", range(12))
    def test_random_patterns_vs_scipy(self, seed):
        """Seeded fuzz over pattern shapes the packer must survive:
        skewed degrees, empty rows/cols bands, duplicate-free random,
        tiny shards, non-square."""
        rng = np.random.default_rng(100 + seed)
        n_rows = int(rng.integers(1, 900))
        n_cols = int(rng.integers(1, 900))
        nnz = int(rng.integers(0, max(1, n_rows * n_cols // 20)))
        r = rng.integers(0, n_rows, nnz)
        c = rng.integers(0, n_cols, nnz)
        if seed % 3 == 0 and nnz > 10:     # hub row + hub col
            r[: nnz // 3] = int(rng.integers(0, n_rows))
            c[nnz // 3: 2 * nnz // 3] = int(rng.integers(0, n_cols))
        d = rng.normal(size=nnz).astype(np.float32)
        A = sp.csr_matrix((d, (r, c)), shape=(n_rows, n_cols))
        A.sum_duplicates()
        shard_w = int(rng.choice([128, 256, 65536]))
        x = rng.normal(size=n_cols).astype(np.float32)
        fmt = prepare(CSRMatrix.from_scipy(A), shard_w=shard_w)
        y = np.asarray(spmv(fmt, jnp.asarray(x)))
        np.testing.assert_allclose(y, A @ x, rtol=5e-5, atol=5e-5)
