"""IVF-PQ index: encode/decode round-trip error bound, the exactness
boundary (full probe + full refine bit-identical to brute force,
ties/NaN included), recall floor with refine at partial probes, the
memory contract (PQ index bytes <= 1/8 of IVF-Flat at d=128 m=16
nbits=8, asserted from the packed arrays), extend == rebuild,
admission degrade/reject, the ivf_pq.search trace event, the knn_plan
ivf_pq band, and the serving IvfPqKnnService (batched == eager bits,
zero post-warm recompiles)."""

import numpy as np
import pytest

from raft_tpu.core import trace
from raft_tpu.neighbors import ivf_flat, ivf_pq, knn
from raft_tpu.neighbors.brute_force import knn_plan
from raft_tpu.random import RngState, make_blobs
from raft_tpu.runtime import limits


@pytest.fixture(scope="module")
def blob_pq(res):
    X, _, _ = make_blobs(res, RngState(3), 4096, 32, n_clusters=32)
    return np.asarray(X), ivf_pq.build(res, X, 32, m=8, nbits=8,
                                       seed=0, max_iter=6,
                                       pq_max_iter=4)


def _recall(gt_ids, ids, k):
    gt_ids, ids = np.asarray(gt_ids), np.asarray(ids)
    return np.mean([len(set(a) & set(b)) / k
                    for a, b in zip(gt_ids, ids)])


class TestBuildLayout:
    def test_packed_is_a_permutation(self, res, blob_pq):
        X, idx = blob_pq
        ids = np.asarray(idx.packed_ids)
        live = ids[ids >= 0]
        assert sorted(live.tolist()) == list(range(len(X)))
        assert idx.packed_codes.dtype == np.uint8
        assert idx.packed_codes.shape[1] == idx.m
        # raw rows ride host-side, bit-exact
        np.testing.assert_array_equal(np.asarray(idx.raw()), X)

    def test_spans_aligned_and_consistent(self, res, blob_pq):
        _, idx = blob_pq
        caps = idx.caps
        assert (caps % ivf_pq.SLOT_ALIGN == 0).all()
        sizes = np.asarray(idx.sizes)
        assert (sizes <= caps).all()
        starts = np.asarray(idx.starts)
        np.testing.assert_array_equal(
            starts, np.concatenate([[0], np.cumsum(caps)[:-1]]))
        assert int(sizes.sum()) == idx.n_db

    def test_decode_round_trip_bound(self, res, blob_pq):
        # PQ reconstruction must beat the coarse-only quantizer by a
        # wide margin: that is the whole point of spending m bytes/row
        X, idx = blob_pq
        dec = idx.decode()
        assert dec.shape == X.shape
        coarse = np.asarray(idx.centroids)[
            np.argmin(((X[:, None] - np.asarray(idx.centroids)[None])
                       ** 2).sum(-1), axis=1)]
        pq_mse = float(np.mean((dec - X) ** 2))
        coarse_mse = float(np.mean((coarse - X) ** 2))
        assert pq_mse < 0.75 * coarse_mse, (pq_mse, coarse_mse)

    def test_bad_args(self, res, blob_pq):
        X, idx = blob_pq
        with pytest.raises(ValueError, match="n_lists"):
            ivf_pq.build(res, X[:4], 8)
        with pytest.raises(ValueError, match="metric"):
            ivf_pq.build(res, X[:64], 4, metric="canberra")
        with pytest.raises(ValueError, match="divide"):
            ivf_pq.build(res, X[:64], 4, m=5)
        with pytest.raises(ValueError, match="nbits"):
            ivf_pq.build(res, X[:64], 4, nbits=9)
        with pytest.raises(ValueError, match="queries"):
            ivf_pq.search(res, idx, X[:2, :5], k=4, nprobe=2)
        with pytest.raises(ValueError, match="nprobe"):
            ivf_pq.search(res, idx, X[:2], k=4, nprobe=0)
        with pytest.raises(ValueError, match="n_db"):
            ivf_pq.search(res, idx, X[:2], k=0, nprobe=2)
        with pytest.raises(ValueError, match="refine"):
            ivf_pq.search(res, idx, X[:2], k=4, nprobe=2, refine=-1)
        with pytest.raises(ValueError, match="candidates"):
            ivf_pq.search(res, idx, X[:2], k=4, nprobe=1,
                          refine=idx.cap_max + 1)


class TestMemoryContract:
    def test_pq_bytes_at_most_eighth_of_flat(self, res):
        # the ISSUE-19 acceptance shape: d=128, m=16, nbits=8 — one
        # uint8 code byte per 8 float32 dims. Asserted from the packed
        # arrays actually resident, not estimated.
        rng = np.random.default_rng(29)
        X = rng.normal(size=(8192, 128)).astype(np.float32)
        flat = ivf_flat.build(res, X, 32, seed=0, max_iter=2)
        pq = ivf_pq.build(res, X, 32, m=16, nbits=8, seed=0,
                          max_iter=2, pq_max_iter=2)
        flat_bytes = int(flat.packed_db.nbytes + flat.packed_ids.nbytes
                         + flat.centroids.nbytes + flat.starts.nbytes
                         + flat.sizes.nbytes)
        pq_bytes = int(pq.device_bytes())
        assert pq.packed_codes.nbytes == pq.packed_codes.shape[0] * 16
        assert pq_bytes * 8 <= flat_bytes, (pq_bytes, flat_bytes)


class TestExactnessBoundary:
    def test_full_probe_bit_identical_to_brute(self, res, blob_pq):
        X, idx = blob_pq
        q = X[:96]
        bd, bi = knn(res, X, q, k=12)
        for refine in (0, 50):
            ad, ai = ivf_pq.search(res, idx, q, k=12,
                                   nprobe=idx.n_lists, refine=refine)
            np.testing.assert_array_equal(np.asarray(bd),
                                          np.asarray(ad))
            np.testing.assert_array_equal(np.asarray(bi),
                                          np.asarray(ai))

    def test_full_probe_ties_and_nan_identical(self, res):
        # adversarial db: exact duplicate rows (ties) and NaN rows —
        # quantizer training validates finiteness, so build against
        # supplied centroids AND codebooks; full probe + full refine
        # must reproduce brute force's tie ordering and NaN bits
        rng = np.random.default_rng(5)
        X = rng.normal(size=(512, 8)).astype(np.float32)
        X[100] = X[7]
        X[200] = X[7]
        X[300] = np.nan
        cb = rng.normal(size=(2, 16, 4)).astype(np.float32)
        idx = ivf_pq.build(res, X, 8, m=2, nbits=4, centroids=X[:8],
                           codebooks=cb)
        q = np.concatenate([X[7:8], X[300:301], X[40:44]])
        bd, bi = knn(res, X, q, k=8)
        ad, ai = ivf_pq.search(res, idx, q, k=8, nprobe=8, refine=100)
        np.testing.assert_array_equal(np.asarray(bd), np.asarray(ad))
        np.testing.assert_array_equal(np.asarray(bi), np.asarray(ai))

    def test_overprobe_clamps_to_full_scan(self, res, blob_pq):
        X, idx = blob_pq
        d1 = ivf_pq.search(res, idx, X[:8], k=4, nprobe=idx.n_lists)
        d2 = ivf_pq.search(res, idx, X[:8], k=4,
                           nprobe=idx.n_lists + 7)
        np.testing.assert_array_equal(np.asarray(d1[1]),
                                      np.asarray(d2[1]))

    def test_onehot_and_gather_lut_sum_bit_identical(self, res,
                                                     blob_pq,
                                                     monkeypatch):
        # the TPU one-hot contraction and the CPU advanced-indexing
        # gather are two spellings of the SAME sum — both accumulate
        # subspaces sequentially, so the f32 rounding matches bit-wise
        X, idx = blob_pq
        q = X[:32]
        monkeypatch.setattr(ivf_pq, "_use_onehot_lut", lambda: True)
        d1, i1 = ivf_pq.search(res, idx, q, k=10, nprobe=8)
        monkeypatch.setattr(ivf_pq, "_use_onehot_lut", lambda: False)
        d0, i0 = ivf_pq.search(res, idx, q, k=10, nprobe=8)
        np.testing.assert_array_equal(np.asarray(d1), np.asarray(d0))
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i0))


class TestRecall:
    @pytest.mark.slow  # also gated in ci/smoke.sh at the same shape
    def test_recall_floor_nprobe16_with_refine(self, res):
        X, _, _ = make_blobs(res, RngState(9), 8192, 32, n_clusters=64)
        idx = ivf_pq.build(res, X, 64, m=8, nbits=8, seed=0)
        q = np.asarray(X[:128])
        _, gi = knn(res, X, q, k=10)
        _, ai = ivf_pq.search(res, idx, q, k=10, nprobe=16, refine=40)
        recall = _recall(gi, ai, 10)
        assert recall >= 0.9, recall
        # refine must not LOSE recall vs the raw ADC ranking
        _, ri = ivf_pq.search(res, idx, q, k=10, nprobe=16)
        assert recall >= _recall(gi, ri, 10) - 1e-9

    @pytest.mark.slow
    def test_inner_metric_full_probe_matches_brute(self, res):
        rng = np.random.default_rng(11)
        X = rng.normal(size=(1024, 16)).astype(np.float32)
        idx = ivf_pq.build(res, X, 16, metric="inner", m=4, nbits=6,
                           seed=0)
        q = X[:32]
        bd, bi = knn(res, X, q, k=5, metric="inner")
        ad, ai = ivf_pq.search(res, idx, q, k=5, nprobe=16)
        np.testing.assert_array_equal(np.asarray(bi), np.asarray(ai))
        np.testing.assert_array_equal(np.asarray(bd), np.asarray(ad))


class TestExtend:
    @pytest.mark.slow
    def test_extend_fitting_tail_equals_rebuild(self, res):
        rng = np.random.default_rng(17)
        X = rng.normal(size=(1003, 12)).astype(np.float32)
        idx = ivf_pq.build(res, X, 8, m=4, nbits=6, seed=0)
        head = idx.caps - np.asarray(idx.sizes)
        li = int(np.argmax(head))
        assert head[li] >= 2, "all tails full; pick another seed"
        c = np.asarray(idx.centroids)[li]
        Y = (c + 0.01 * rng.normal(size=(2, 12))).astype(np.float32)
        ext = ivf_pq.extend(res, idx, Y)
        reb = ivf_pq.build(res, np.concatenate([X, Y]), 8, m=4,
                           nbits=6, centroids=idx.centroids,
                           codebooks=idx.codebooks)
        assert np.array_equal(ext.caps, idx.caps)   # append, no repack
        np.testing.assert_array_equal(np.asarray(ext.packed_ids),
                                      np.asarray(reb.packed_ids))
        np.testing.assert_array_equal(np.asarray(ext.packed_codes),
                                      np.asarray(reb.packed_codes))
        q = X[:40]
        ed, ei = ivf_pq.search(res, ext, q, k=8, nprobe=3, refine=20)
        rd, ri = ivf_pq.search(res, reb, q, k=8, nprobe=3, refine=20)
        np.testing.assert_array_equal(np.asarray(ei), np.asarray(ri))
        np.testing.assert_array_equal(np.asarray(ed), np.asarray(rd))

    @pytest.mark.slow
    def test_extend_overflow_repacks_and_equals_rebuild(self, res):
        rng = np.random.default_rng(19)
        X = rng.normal(size=(512, 12)).astype(np.float32)
        Y = rng.normal(size=(300, 12)).astype(np.float32)  # overflows
        idx = ivf_pq.build(res, X, 8, m=4, nbits=6, seed=0)
        ext = ivf_pq.extend(res, idx, Y)
        reb = ivf_pq.build(res, np.concatenate([X, Y]), 8, m=4,
                           nbits=6, centroids=idx.centroids,
                           codebooks=idx.codebooks)
        np.testing.assert_array_equal(np.asarray(ext.packed_ids),
                                      np.asarray(reb.packed_ids))
        np.testing.assert_array_equal(np.asarray(ext.packed_codes),
                                      np.asarray(reb.packed_codes))

    def test_extend_full_probe_still_exact(self, res, blob_pq):
        X, idx = blob_pq
        rng = np.random.default_rng(23)
        Y = rng.normal(size=(50, X.shape[1])).astype(np.float32)
        ext = ivf_pq.extend(res, idx, Y)
        assert ext.n_db == len(X) + 50
        full = np.concatenate([X, Y])
        np.testing.assert_array_equal(np.asarray(ext.raw()), full)
        q = full[-8:]
        bd, bi = knn(res, full, q, k=6)
        ad, ai = ivf_pq.search(res, ext, q, k=6, nprobe=ext.n_lists)
        np.testing.assert_array_equal(np.asarray(bi), np.asarray(ai))


class TestAdmissionAndObs:
    def test_degraded_bit_identical(self, res, blob_pq):
        X, idx = blob_pq
        q = X[:64]
        bd, bi = ivf_pq.search(res, idx, q, k=8, nprobe=4, refine=32)
        est = limits.estimate_bytes(
            "neighbors.ivf_pq_search", n_queries=64, nprobe=4,
            probe_rows=4 * idx.cap_max, n_dims=idx.dim, k=32, m=idx.m,
            n_codes=idx.n_codes, refine=32, itemsize=4,
            packed_rows=int(idx.packed_codes.shape[0]))
        with limits.budget_scope(est // 2 + int(idx.device_bytes())):
            dd, di = ivf_pq.search(res, idx, q, k=8, nprobe=4,
                                   refine=32)
        np.testing.assert_array_equal(np.asarray(bd), np.asarray(dd))
        np.testing.assert_array_equal(np.asarray(bi), np.asarray(di))

    def test_unfittable_rejected(self, res, blob_pq):
        X, idx = blob_pq
        with limits.budget_scope(1024):
            with pytest.raises(limits.RejectedError):
                ivf_pq.search(res, idx, X[:4], k=8, nprobe=4)

    def test_seconds_estimator_twin(self):
        dims = dict(n_queries=64, nprobe=4, probe_rows=512, n_dims=32,
                    k=10, m=8, n_codes=256)
        assert limits.estimate_seconds("neighbors.ivf_pq_search",
                                       **dims) > 0
        assert limits.estimate_bytes("neighbors.ivf_pq_search",
                                     **dims) > 0

    def test_trace_event_carries_probe_plan(self, res, blob_pq):
        X, idx = blob_pq
        trace.clear_events()
        ivf_pq.search(res, idx, X[:4], k=8, nprobe=4, refine=16)
        ev = trace.events("ivf_pq.search")
        assert len(ev) == 1
        assert ev[0]["nprobe"] == 4 and ev[0]["path"] == "ivf_pq"
        assert ev[0]["refine"] == 16
        assert ev[0]["scanned_frac"] == pytest.approx(
            idx.scanned_fraction(4), abs=1e-4)
        trace.clear_events()
        ivf_pq.search(res, idx, X[:4], k=8, nprobe=idx.n_lists)
        ev = trace.events("ivf_pq.search")
        assert ev[0]["path"] == "exact"
        assert ev[0]["scanned_frac"] == 1.0

    def test_knn_plan_ivf_pq_band(self):
        assert knn_plan(64, 4096, 10, n_lists=64, nprobe=8,
                        pq=True) == ("ivf_pq", 0)
        assert knn_plan(64, 4096, 10, n_lists=64, nprobe=8) == \
            ("ivf", 0)
        # full scan is not a pq plan — it IS the brute-force plan
        path, _ = knn_plan(64, 4096, 10, n_lists=64, nprobe=64,
                           pq=True)
        assert path != "ivf_pq"


class TestIvfPqServe:
    def test_batched_bits_and_zero_recompiles(self, res, blob_pq):
        from raft_tpu import serve

        X, idx = blob_pq
        svc = serve.IvfPqKnnService(idx, k=10, nprobe=8)
        assert svc.epilogue() == "ivf_pq"
        ex = serve.Executor(
            [svc], policy=serve.BatchPolicy(max_batch=64,
                                            max_wait_ms=2.0))
        ex.warm()
        traces_after_warm = ex.stats.traces
        q = X[:48]
        with ex:
            fut = ex.submit(svc.name, q)
            d, i = fut.result(timeout=60.0)
        assert ex.stats.traces == traces_after_warm
        ed, ei = ivf_pq.search(res, idx, q, k=10, nprobe=8)
        np.testing.assert_array_equal(np.asarray(i), np.asarray(ei))
        np.testing.assert_array_equal(np.asarray(d), np.asarray(ed))

    def test_full_scan_service_rejected(self, res, blob_pq):
        from raft_tpu import serve

        _, idx = blob_pq
        with pytest.raises(ValueError, match="KnnService"):
            serve.IvfPqKnnService(idx, k=4, nprobe=idx.n_lists)
