"""Leader-failover tests (ISSUE 20): term-fenced election, quorum-acked
writes, zero-loss promotion for the durable streaming fleet.

Acceptance claims gated here:

- the survivor clique deterministically promotes the most-caught-up
  follower — max ``(term, applied_seq)``, lowest rank on an exact tie —
  and every survivor records the identical :class:`ElectionRecord`;
- a minority clique NEVER elects (the split-brain guard): a follower
  that merely lost the leader's pulse refuses to crown itself;
- a stale-term record reaching a fenced replica raises the typed
  :class:`TermFencedError` carrying the divergence sequence; records
  BELOW the term boundary (legitimately stamped with the old term)
  still replay;
- a deposed leader that rejoins truncates its unreplicated WAL suffix
  at the carried divergence, demotes, and heals bit-equal
  (``content_crc``) to the fleet via the existing catch-up ladder;
- quorum-ack mode blocks ``insert()/delete()`` until ⌈(n+1)/2⌉
  followers confirm, raises the typed indeterminate
  :class:`WalQuorumError` on timeout, and feeds the per-follower
  ``wal_replication_lag_seconds`` gauge; ``write_id`` replay is
  idempotent and the dedup map replicates;
- frame damage — bit-flip, truncation, wrong ``_frame`` tag — is the
  typed :class:`WalFrameError`, never the raw pickle taxonomy;
- ``MutationLog`` fans appends out to MANY subscribers in order while
  the one-shipper-per-journal exclusivity stays enforced;
- malformed ``RAFT_TPU_ELECTION_TIMEOUT`` / ``RAFT_TPU_WAL_QUORUM``
  kill the IMPORT of the election module loudly (subprocess-tested);
- the serve tier redirects follower writes with the typed
  :class:`NotLeaderError` and ``ReplicaGroup.promote`` re-points write
  routing with zero post-promotion recompiles;
- the three-process SIGKILL witness (tests/_failover_worker.py): the
  leader dies mid-stream, the quorum elects, writes resume, and every
  client-acked sequence survives bit-equal to a clean twin.
"""

import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from raft_tpu import obs
from raft_tpu.comms.comms import _Mailbox
from raft_tpu.core import env
from raft_tpu.neighbors.election import (ElectionError, ElectionNode,
                                         TAG_HEARTBEAT)
from raft_tpu.neighbors.streaming import (MutationLog, StreamingError,
                                          StreamingIndex,
                                          TermFencedError, stream_build)
from raft_tpu.neighbors.wal_ship import (FRAME_SNAPSHOT, FRAME_WAL,
                                         TAG_WAL, WalFollower,
                                         WalFrameError, WalQuorumError,
                                         WalShipper, bootstrap_follower,
                                         decode_frame, encode_frame,
                                         frame_kind)
from raft_tpu.obs import metrics as obs_metrics
from raft_tpu.serve.ingest import (IngestController, NotLeaderError,
                                   StreamingKnnService)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

N, D, L = 160, 8, 8


def _mk_leader(tmp_path, seed=3, name="n0"):
    rng = np.random.default_rng(seed)
    db = rng.normal(size=(N, D)).astype(np.float32)
    idx = stream_build(None, db, L, seed=0, max_iter=4,
                       directory=str(tmp_path / name))
    return idx, rng


def _rows(rng, m=6):
    return rng.normal(size=(m, D)).astype(np.float32)


@pytest.fixture
def live_obs():
    """Metrics on with a private registry (the test_obs pattern)."""
    was_enabled = obs.enabled()
    old_reg = obs_metrics.set_registry(obs.MetricsRegistry())
    obs.set_enabled(True)
    try:
        yield obs_metrics.get_registry()
    finally:
        obs.set_enabled(was_enabled)
        obs_metrics.set_registry(old_reg)


def _counter(reg, name):
    fam = reg.snapshot().get(name)
    if not fam:
        return 0.0
    return sum(s["value"] for s in fam["series"])


# ---------------------------------------------------------------------------
# election (tentpole): deterministic promotion of the survivor clique
# ---------------------------------------------------------------------------


class TestElection:
    """In-proc three-node fleet. No vigilance threads — tests drive
    ``run_election()``/``tick()`` directly (the documented
    deterministic-test entrypoints); only the shippers' serve threads
    run, because catch-up needs a live responder."""

    def _trio(self, tmp_path, *, catch_up=(1, 2)):
        idx0, rng = _mk_leader(tmp_path)
        mbx = _Mailbox()
        n0 = ElectionNode(idx0, mbx, 0, [0, 1, 2], role="leader",
                          leader=0, acks="async", election_timeout=2.0,
                          heartbeat_interval=0.05, ack_timeout=30.0)
        n0.shipper.attach()
        n0.shipper.start()
        nodes = {0: n0}
        for r in (1, 2):
            fidx = bootstrap_follower(None, dim=D, n_lists=L,
                                      directory=str(tmp_path / f"n{r}"))
            wf = WalFollower(fidx, mbx, r, 0)
            if r in catch_up:
                wf.catch_up(timeout=60.0)
            nodes[r] = ElectionNode(fidx, mbx, r, [0, 1, 2],
                                    role="follower", leader=0,
                                    acks="async", election_timeout=2.0,
                                    ack_timeout=30.0, follower=wf)
        return idx0, rng, mbx, nodes[0], nodes[1], nodes[2]

    @staticmethod
    def _teardown(*nodes):
        for n in nodes:
            if n.role == "leader" and n.shipper is not None:
                if n.shipper._thread is not None:
                    n.shipper.stop()
                n.shipper.detach()

    @staticmethod
    def _elect(*nodes):
        """Run the all-to-all election concurrently (each survivor's
        ballot exchange needs the others' answers in flight)."""
        recs, errs = {}, {}

        def run(n):
            try:
                recs[n.rank] = n.run_election()
            except BaseException as exc:  # noqa: BLE001 — re-raised
                errs[n.rank] = exc

        threads = [threading.Thread(target=run, args=(n,))
                   for n in nodes]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60.0)
        assert not errs, errs
        return recs

    def test_most_caught_up_follower_wins(self, tmp_path):
        idx0, rng, mbx, n0, n1, n2 = self._trio(tmp_path)
        try:
            for _ in range(2):
                idx0.insert(_rows(rng))
            n1.follower.drain()
            n2.follower.drain()
            for _ in range(2):
                idx0.insert(_rows(rng))
            n1.follower.drain()                 # rank 1 pulls ahead
            assert n1.index.applied_seq > n2.index.applied_seq
            horizon = n1.index.applied_seq

            n0.shipper.stop()
            n0.shipper.detach()
            mbx.fail_peer(0, "killed")
            recs = self._elect(n1, n2)

            # every survivor decided the SAME election
            assert recs[1].winner == recs[2].winner == 1
            assert recs[1].term == recs[2].term == 1
            assert recs[1].votes == recs[2].votes
            assert recs[1].promoted and not recs[2].promoted
            assert n1.role == "leader" and n1.leader == 1
            assert n2.role == "follower" and n2.leader == 1
            # the loser armed its fence at the winner's ballot
            # horizon + 1 — exactly where KIND_TERM lands
            assert n2.index._term_start == horizon + 1
            assert n2.index.term == 1

            # the lagging loser heals from the NEW leader and writes
            # resume: converged bit-equal, zero rows lost
            n1.index.insert(_rows(rng))
            n2.follower.drain()
            assert n2.index.applied_seq == n1.index.applied_seq
            assert n2.index.content_crc() == n1.index.content_crc()
        finally:
            self._teardown(n0, n1, n2)

    def test_equal_applied_rank_tiebreak(self, tmp_path):
        """Split vote on identical ``(term, applied_seq)`` ballots:
        the lowest surviving rank wins, on every survivor."""
        idx0, rng, mbx, n0, n1, n2 = self._trio(tmp_path)
        try:
            idx0.insert(_rows(rng))
            n1.follower.drain()
            n2.follower.drain()
            assert n1.index.applied_seq == n2.index.applied_seq

            n0.shipper.stop()
            n0.shipper.detach()
            mbx.fail_peer(0, "killed")
            recs = self._elect(n1, n2)
            assert recs[1].votes[1] == recs[1].votes[2]
            assert recs[1].winner == recs[2].winner == 1
            assert n1.role == "leader" and n2.leader == 1
        finally:
            self._teardown(n0, n1, n2)

    def test_minority_clique_refuses_election(self, tmp_path):
        """The split-brain guard: one survivor out of three must NOT
        crown itself — the election raises, the node stays follower,
        and the term never moves."""
        idx0, rng, mbx, n0, n1, n2 = self._trio(tmp_path)
        try:
            mbx.fail_peer(0, "killed")
            mbx.fail_peer(2, "killed")
            with pytest.raises(ElectionError, match="quorum"):
                n1.run_election()
            assert n1.role == "follower"
            assert n1.index.term == 0
        finally:
            self._teardown(n0, n1, n2)

    def test_election_during_inflight_catchup(self, tmp_path):
        """A follower whose bootstrap catch-up never completed when
        the leader died: its near-empty ballot loses, and it heals
        from the NEW leader's snapshot afterwards."""
        idx0, rng, mbx, n0, n1, n2 = self._trio(tmp_path,
                                                catch_up=(1,))
        try:
            idx0.insert(_rows(rng))
            n1.follower.drain()
            assert n2.index.applied_seq < n1.index.applied_seq

            n0.shipper.stop()
            n0.shipper.detach()
            mbx.fail_peer(0, "killed")
            recs = self._elect(n1, n2)
            assert recs[2].winner == 1 and not recs[2].promoted

            # post-election: the interrupted catch-up re-targets the
            # new leader and converges bit-equal
            n2.follower.catch_up(timeout=60.0)
            assert n2.index.term == 1
            assert n2.index.content_crc() == n1.index.content_crc()
        finally:
            self._teardown(n0, n1, n2)

    def test_deposed_leader_truncates_and_heals(self, tmp_path):
        """The rejoin ladder, end to end: the old leader keeps
        appending a suffix the quorum never saw, hears the new term's
        pulse, records the typed fence, truncates FROM the divergence
        sequence, demotes, and converges ``content_crc`` bit-equal."""
        idx0, rng, mbx, n0, n1, n2 = self._trio(tmp_path)
        try:
            idx0.insert(_rows(rng))
            n1.follower.drain()
            n2.follower.drain()
            divergence = idx0.applied_seq + 1   # first un-shipped seq

            recs = self._elect(n1, n2)          # old leader silent
            assert recs[1].promoted
            n2.follower.drain()

            # the deposed leader, unaware, appends a 2-record suffix
            idx0.insert(_rows(rng, 4))
            idx0.insert(_rows(rng, 3))
            stale_applied = idx0.applied_seq
            assert stale_applied >= divergence

            # heal dance. The deposed leader pulses its stale term
            # FIRST so the new leader re-admits it to the shipping
            # set (in the threaded fleet the vigilance threads
            # interleave; driving tick() by hand we must order it) —
            # then its own next tick hears term 1 and demotes.
            n0.broadcast_heartbeat()
            n1.tick()
            assert 0 in n1.shipper.followers
            assert n1.fences_sent >= 1
            n0.tick()
            assert n0.role == "follower" and n0.leader == 1
            fence = n0.last_fence
            assert isinstance(fence, TermFencedError)
            assert fence.stale_term == 0 and fence.current_term == 1
            assert fence.divergence == divergence
            # the suffix is gone from journal AND content
            assert n0.index.applied_seq == n1.index.applied_seq
            assert n0.index.content_crc() == n1.index.content_crc()
            assert n0.index.term == 1

            # writes now replicate to BOTH followers
            n1.index.insert(_rows(rng))
            n0.follower.drain()
            n2.follower.drain()
            assert n0.index.content_crc() == n1.index.content_crc() \
                == n2.index.content_crc()
        finally:
            self._teardown(n0, n1, n2)


# ---------------------------------------------------------------------------
# term fencing at the record level
# ---------------------------------------------------------------------------


class TestFencing:
    def _pair(self, tmp_path):
        idx0, rng = _mk_leader(tmp_path)
        mbx = _Mailbox()
        sh = WalShipper(idx0, mbx, 0, [1], poll_interval=0.01).attach()
        sh.start()
        fidx = bootstrap_follower(None, dim=D, n_lists=L,
                                  directory=str(tmp_path / "n1"))
        wf = WalFollower(fidx, mbx, 1, 0)
        wf.catch_up(timeout=60.0)
        return idx0, rng, mbx, sh, fidx, wf

    def test_stale_record_raises_typed_fence(self, tmp_path):
        idx0, rng, mbx, sh, fidx, wf = self._pair(tmp_path)
        try:
            idx0.insert(_rows(rng))
            wf.drain()
            # the follower moves to term 3 with the boundary at the
            # next sequence — as a real election's repoint would
            boundary = fidx.applied_seq + 1
            fidx.adopt_term(3)
            fidx._term_start = boundary

            idx0.insert(_rows(rng))             # still stamped term 0
            with pytest.raises(TermFencedError) as ei:
                wf.drain()
            assert ei.value.stale_term == 0
            assert ei.value.current_term == 3
            assert ei.value.divergence == boundary
            assert fidx.applied_seq == boundary - 1   # never applied
        finally:
            sh.stop()
            sh.detach()

    def test_records_below_boundary_still_replay(self, tmp_path):
        """The fence predicate is ``term < cur AND seq >= boundary`` —
        old-term records BELOW the boundary are the legitimate history
        and must keep replaying after a term adoption."""
        idx0, rng, mbx, sh, fidx, wf = self._pair(tmp_path)
        try:
            idx0.insert(_rows(rng))             # seq s, term 0
            # follower adopts the new term BEFORE draining, boundary
            # one past the in-flight record
            fidx.adopt_term(2)
            fidx._term_start = idx0.applied_seq + 1
            wf.drain()                          # applies, no fence
            assert fidx.applied_seq == idx0.applied_seq
            assert fidx.content_crc() == idx0.content_crc()

            idx0.insert(_rows(rng))             # seq >= boundary: fenced
            with pytest.raises(TermFencedError):
                wf.drain()
        finally:
            sh.stop()
            sh.detach()

    def test_truncate_from_rewinds_journal(self, tmp_path):
        log = MutationLog(str(tmp_path / "j"))
        for i in range(5):
            log.append({"kind": 1, "i": i})
        assert log.truncate_from(3) == 2
        assert [int(r["seq"]) for r in log.wal_records()] == [0, 1, 2]
        # the issue cursor rewound: the next append reuses seq 3
        assert log.append({"kind": 1, "i": 99}) == 3
        assert log.truncate_from(100) == 0      # nothing past the end


# ---------------------------------------------------------------------------
# quorum-acked writes
# ---------------------------------------------------------------------------


class TestQuorumAcks:
    def _fleet(self, tmp_path, *, acks="majority", ack_timeout=30.0):
        idx0, rng = _mk_leader(tmp_path)
        mbx = _Mailbox()
        sh = WalShipper(idx0, mbx, 0, [1, 2], acks=acks,
                        ack_timeout=ack_timeout,
                        poll_interval=0.01).attach()
        sh.start()
        wfs = []
        for r in (1, 2):
            fidx = bootstrap_follower(None, dim=D, n_lists=L,
                                      directory=str(tmp_path / f"n{r}"))
            wf = WalFollower(fidx, mbx, r, 0)
            wf.catch_up(timeout=60.0)
            wfs.append(wf)
        return idx0, rng, mbx, sh, wfs

    @staticmethod
    def _pump(wf, stop):
        while not stop.is_set():
            wf.drain()
            time.sleep(0.005)

    def test_acks_needed_ladder(self, tmp_path):
        idx0, rng = _mk_leader(tmp_path)
        mbx = _Mailbox()
        mk = lambda a: WalShipper(idx0, mbx, 0, [1, 2], acks=a)
        assert mk("async").acks_needed() == 0
        assert mk("majority").acks_needed() == 1   # ⌈(3+1)/2⌉−1
        assert mk("all").acks_needed() == 2
        assert mk(2).acks_needed() == 2
        assert mk(5).acks_needed() == 2            # clamped to fleet

    def test_majority_blocks_until_follower_confirms(self, tmp_path):
        idx0, rng, mbx, sh, (wf1, wf2) = self._fleet(tmp_path)
        stop = threading.Event()
        t = threading.Thread(target=self._pump, args=(wf1, stop),
                             daemon=True)
        t.start()
        try:
            # one live follower satisfies majority; wf2 stays idle
            idx0.insert(_rows(rng))
            assert sh.quorum_waits == 1
            assert sh.acked_seq(1) >= idx0.applied_seq
            assert wf1.index.applied_seq == idx0.applied_seq
        finally:
            stop.set()
            t.join(timeout=10.0)
            sh.stop()
            sh.detach()

    def test_quorum_timeout_typed_indeterminate(self, tmp_path):
        idx0, rng, mbx, sh, wfs = self._fleet(tmp_path, acks="all",
                                              ack_timeout=0.5)
        try:
            before = idx0.applied_seq
            with pytest.raises(WalQuorumError) as ei:
                idx0.insert(_rows(rng))
            assert ei.value.acked == 0 and ei.value.needed == 2
            # indeterminate, NOT rolled back: durable locally, the
            # caller retries idempotently with the same write_id
            assert idx0.applied_seq == before + 1
            assert "idempotent" in str(ei.value).lower() or \
                "retry" in str(ei.value).lower()
        finally:
            sh.stop()
            sh.detach()

    def test_replication_lag_gauge(self, tmp_path, live_obs):
        idx0, rng, mbx, sh, (wf1, wf2) = self._fleet(tmp_path)
        stop = threading.Event()
        threads = [threading.Thread(target=self._pump, args=(wf, stop),
                                    daemon=True) for wf in (wf1, wf2)]
        for t in threads:
            t.start()
        try:
            idx0.insert(_rows(rng))
            deadline = time.monotonic() + 10.0
            fam = None
            while time.monotonic() < deadline:
                sh.drain_acks()
                fam = live_obs.snapshot().get(
                    "wal_replication_lag_seconds")
                if fam and len(fam["series"]) >= 1:
                    break
                time.sleep(0.01)
            assert fam, "lag gauge never exported"
            # labelled per follower (which rank's ack lands the stamp
            # first is a benign race — the label taxonomy is not)
            assert all(s["labels"].get("follower") in ("1", "2")
                       for s in fam["series"])
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=10.0)
            sh.stop()
            sh.detach()

    def test_write_id_replay_idempotent_and_replicated(self, tmp_path):
        idx0, rng, mbx, sh, (wf1, wf2) = self._fleet(tmp_path)
        stop = threading.Event()
        threads = [threading.Thread(target=self._pump, args=(wf, stop),
                                    daemon=True) for wf in (wf1, wf2)]
        for t in threads:
            t.start()
        try:
            ids_a = idx0.insert(_rows(rng, 2), write_id=77)
            seq = idx0.applied_seq
            ids_b = idx0.insert(_rows(rng, 2), write_id=77)
            assert np.array_equal(ids_a, ids_b)
            assert idx0.applied_seq == seq      # no second record
            deadline = time.monotonic() + 10.0
            while wf1.index.applied_seq < seq and \
                    time.monotonic() < deadline:
                time.sleep(0.01)
            assert np.array_equal(wf1.index.seen_write_id(77), ids_a)
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=10.0)
            sh.stop()
            sh.detach()


# ---------------------------------------------------------------------------
# frame integrity (satellite 2)
# ---------------------------------------------------------------------------


class TestFrameFuzz:
    REC = {"_frame": FRAME_WAL, "kind": 1, "seq": 4,
           "rows": np.arange(12, dtype=np.float32).reshape(3, 4)}

    def test_roundtrip(self):
        out = decode_frame(encode_frame(self.REC))
        assert frame_kind(out) == FRAME_WAL
        assert int(out["seq"]) == 4
        np.testing.assert_array_equal(out["rows"], self.REC["rows"])

    def test_bit_flip_fuzz(self):
        """Random single-bit damage anywhere in the container either
        raises the typed WalFrameError or decodes with every VALUE
        bit-intact (entry payloads are CRC-covered; a flip in an entry
        NAME can only rename a key, which the apply layer rejects on
        the missing field) — NEVER a silently-corrupted value or a raw
        pickle/struct error escaping untyped."""
        payload = encode_frame(self.REC)
        rng = np.random.default_rng(0)
        detected = 0
        for _ in range(64):
            bad = payload.copy()
            pos = int(rng.integers(len(bad)))
            bad[pos] ^= np.uint8(1 << int(rng.integers(8)))
            try:
                out = decode_frame(bad)
            except WalFrameError:
                detected += 1
                continue
            for k, v in self.REC.items():
                if k in out:
                    np.testing.assert_array_equal(out[k], v)
        assert detected > 0

    def test_truncation_detected(self):
        payload = encode_frame(self.REC)
        for frac in (0.1, 0.5, 0.9):
            with pytest.raises(WalFrameError):
                decode_frame(payload[:int(len(payload) * frac)])
        with pytest.raises(WalFrameError):
            decode_frame(payload[:0])

    def test_wrong_frame_tag(self):
        with pytest.raises(WalFrameError, match="unknown"):
            frame_kind({"_frame": 99})
        with pytest.raises(WalFrameError, match="_frame"):
            frame_kind({"seq": 1})

    def test_wrong_kind_on_wal_channel(self, tmp_path):
        """A snapshot frame smuggled onto the live TAG_WAL channel is
        rejected typed, not applied."""
        idx0, rng = _mk_leader(tmp_path)
        mbx = _Mailbox()
        sh = WalShipper(idx0, mbx, 0, [1], poll_interval=0.01).attach()
        sh.start()
        fidx = bootstrap_follower(None, dim=D, n_lists=L,
                                  directory=str(tmp_path / "n1"))
        wf = WalFollower(fidx, mbx, 1, 0)
        wf.catch_up(timeout=60.0)
        try:
            mbx.put(0, 1, TAG_WAL,
                    encode_frame({"_frame": FRAME_SNAPSHOT}))
            with pytest.raises(WalFrameError, match="FRAME_WAL"):
                wf.drain()
        finally:
            sh.stop()
            sh.detach()


# ---------------------------------------------------------------------------
# MutationLog append fan-out (satellite 1)
# ---------------------------------------------------------------------------


class TestOnAppendSubscribers:
    def test_multi_subscriber_order_and_removal(self, tmp_path):
        log = MutationLog(str(tmp_path / "j"))
        calls = []
        a = lambda rec: calls.append(("a", int(rec["seq"])))
        b = lambda rec: calls.append(("b", int(rec["seq"])))
        log.add_on_append(a)
        log.add_on_append(b)
        log.add_on_append(a)                    # idempotent
        log.append({"kind": 1})
        assert calls == [("a", 0), ("b", 0)]    # registration order
        log.remove_on_append(a)
        log.append({"kind": 1})
        assert calls[-1] == ("b", 1)
        log.remove_on_append(a)                 # absent: no raise

    def test_legacy_single_slot_shim(self, tmp_path):
        log = MutationLog(str(tmp_path / "j"))
        assert log.on_append is None
        a = lambda rec: None
        b = lambda rec: None
        log.on_append = a
        assert log.on_append is a               # single → the callable
        log.add_on_append(b)
        assert log.on_append == (a, b)          # several → the tuple
        log.on_append = b                       # setter REPLACES all
        assert log.on_append is b
        log.on_append = None
        assert log.on_append is None

    def test_shipper_exclusive_but_observers_coexist(self, tmp_path):
        """Exactly one shipper per journal (two would double-ship),
        but plain observers ride along freely."""
        idx0, rng = _mk_leader(tmp_path)
        mbx = _Mailbox()
        seen = []
        idx0.log.add_on_append(lambda rec: seen.append(int(rec["seq"])))
        sh = WalShipper(idx0, mbx, 0, [1]).attach()
        assert sh.attach() is sh                # same instance: ok
        with pytest.raises(StreamingError, match="on_append"):
            WalShipper(idx0, mbx, 0, [2]).attach()
        idx0.insert(_rows(rng))
        assert seen                              # observer still fired
        sh.detach()
        WalShipper(idx0, mbx, 0, [2]).attach().detach()


# ---------------------------------------------------------------------------
# env knobs (satellite 3): fail-loud, at import
# ---------------------------------------------------------------------------


class TestEnvKnobs:
    @pytest.mark.parametrize("name,bad,good,parsed", [
        ("RAFT_TPU_ELECTION_TIMEOUT", "0", "2.5", 2.5),
        ("RAFT_TPU_ELECTION_TIMEOUT", "fast", "0.5", 0.5),
        ("RAFT_TPU_WAL_QUORUM", "0", "majority", "majority"),
        ("RAFT_TPU_WAL_QUORUM", "sometimes", "3", 3),
    ])
    def test_registered_fail_loud(self, monkeypatch, name, bad, good,
                                  parsed):
        monkeypatch.setenv(name, bad)
        with pytest.raises(ValueError, match=name):
            env.read(name)
        monkeypatch.setenv(name, good)
        assert env.read(name) == parsed

    @pytest.mark.parametrize("name,bad", [
        ("RAFT_TPU_ELECTION_TIMEOUT", "-1"),
        ("RAFT_TPU_WAL_QUORUM", "most"),
    ])
    def test_malformed_knob_fails_at_import(self, name, bad):
        """Both failover knobs are validated when the election module
        imports — a fleet must never come up with a silently-wrong
        succession config."""
        code = "import raft_tpu.neighbors.election\n"
        env2 = dict(os.environ)
        env2[name] = bad
        env2["JAX_PLATFORMS"] = "cpu"
        env2["PYTHONPATH"] = _REPO + os.pathsep + env2.get(
            "PYTHONPATH", "")
        p = subprocess.run([sys.executable, "-c", code], env=env2,
                           capture_output=True, text=True, timeout=120)
        assert p.returncode != 0
        assert name in p.stderr


# ---------------------------------------------------------------------------
# serve tier: leader-aware ingest + routing promotion
# ---------------------------------------------------------------------------


class TestServeFailover:
    def _ctl(self, idx, election=None):
        from raft_tpu.serve import BatchPolicy
        return IngestController(
            idx, [StreamingKnnService(idx, k=4, nprobe=3)],
            policy=BatchPolicy(max_batch=16, max_wait_ms=2.0),
            compact_interval=30.0, refit=False, warm_buckets=[4],
            election=election)

    def test_follower_write_redirects_typed(self, tmp_path):
        idx, rng = _mk_leader(tmp_path, name="n1")
        mbx = _Mailbox()
        node = ElectionNode(idx, mbx, 1, [0, 1], role="follower",
                            leader=0, acks="async",
                            election_timeout=60.0)
        ctl = self._ctl(idx, election=node)
        with ctl:
            assert not ctl.is_leader() and ctl.leader == 0
            with pytest.raises(NotLeaderError) as ei:
                ctl.insert(_rows(rng))
            assert ei.value.leader == 0 and ei.value.rank == 1
            with pytest.raises(NotLeaderError):
                ctl.delete(np.array([0, 1]))
            # queries keep serving on followers — only writes redirect
            q = _rows(rng, 4)
            svc = ctl.streaming_services[0].name
            out = ctl.submit(svc, q).result(timeout=60.0)
            assert out[0].shape == (4, 4)

    def test_leader_controller_write_id_dedup(self, tmp_path):
        idx, rng = _mk_leader(tmp_path)
        ctl = self._ctl(idx)
        with ctl:
            assert ctl.is_leader()              # no election wired
            ids_a = ctl.insert(_rows(rng, 3), write_id=5)
            seq = idx.applied_seq
            ids_b = ctl.insert(_rows(rng, 3), write_id=5)
            assert np.array_equal(ids_a, ids_b)
            assert idx.applied_seq == seq

    @staticmethod
    def _mnmg_fleet(res, n=3):
        from raft_tpu.neighbors import ivf_flat
        from raft_tpu.neighbors.ivf_mnmg import build_mnmg
        from raft_tpu.serve import (BatchPolicy, Executor,
                                    IvfMnmgKnnService, ReplicaGroup)
        rng = np.random.default_rng(2)
        X = rng.standard_normal((256, 12)).astype(np.float32)
        flat = ivf_flat.build(res, X, 8, seed=0, max_iter=4)
        idx = build_mnmg(res, X, 8, 2, flat=flat)

        def make_ex():
            ex = Executor([IvfMnmgKnnService(idx, k=4, nprobe=3)],
                          policy=BatchPolicy(max_batch=32,
                                             max_wait_ms=1.0))
            ex.warm([8])
            return ex

        op = f"ivf_mnmg_k4_np3_r{idx.n_ranks}_{idx.metric}"
        return X, ReplicaGroup([make_ex() for _ in range(n)]), op

    def test_replica_group_promote_zero_recompiles(self, res,
                                                   live_obs):
        X, group, op = self._mnmg_fleet(res)
        with group:
            for _ in range(4):
                group.route(op, X[:8])[1].result(timeout=60.0)
            assert group.leader is None
            traces0 = [r.executor.stats.traces for r in group.replicas]
            rep = group.promote("replica1")
            assert group.leader is rep and rep.name == "replica1"
            # promotion moved the leader MARKER, not data: the warmed
            # executables survive verbatim
            for _ in range(4):
                group.route(op, X[:8])[1].result(timeout=60.0)
            assert [r.executor.stats.traces
                    for r in group.replicas] == traces0
            assert _counter(live_obs,
                            "serve_replica_promotions_total") == 1.0
            # a dead replica can never take writes
            group.fail_replica("replica2")
            with pytest.raises(ValueError, match="promote"):
                group.promote("replica2")

    def test_chaos_kill_leader_scenario(self, res):
        """The loadgen failover scenario: the write leader dies at
        the spike peak, a survivor is promoted, and both failover
        clocks are stamped for the CI gate."""
        from raft_tpu.serve.loadgen import run_chaos
        X, group, op = self._mnmg_fleet(res)
        with group:
            rep = run_chaos("kill_leader", group, op, clients=4,
                            phase_s=1.0)
        notes = rep.notes
        assert notes["killed_leader"] == notes["old_leader"]
        assert notes["new_leader"] is not None
        assert notes["new_leader"] != notes["old_leader"]
        assert notes["time_to_new_leader_s"] is not None
        assert notes["recovery_time_to_slo_s"] is not None


# ---------------------------------------------------------------------------
# the three-process SIGKILL witness (slow tier — smoke.sh gates it too)
# ---------------------------------------------------------------------------


class TestFailoverChaos:
    @pytest.mark.slow
    def test_kill_leader_quorum_promotes_zero_loss(self):
        """Real-TCP 3-node fleet, SIGKILL the leader mid-stream: the
        survivor quorum elects the most-caught-up follower, writes
        resume, every client-acked seq survives bit-equal to a clean
        twin, and the rejoining stale leader truncates its suffix via
        the typed fence and converges."""
        worker = os.path.join(_REPO, "tests", "_failover_worker.py")
        env2 = dict(os.environ)
        env2["JAX_PLATFORMS"] = "cpu"
        env2["PYTHONPATH"] = _REPO + os.pathsep + env2.get(
            "PYTHONPATH", "")
        p = subprocess.run([sys.executable, worker, "orchestrate"],
                           cwd=_REPO, env=env2, capture_output=True,
                           text=True, timeout=480)
        assert p.returncode == 0, p.stdout + p.stderr
        assert "FAILOVER_CHAOS_OK" in p.stdout, p.stdout
