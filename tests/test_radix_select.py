"""Radix-rank select kernel vs a numpy total-order oracle.

The oracle sorts by the same sortable-key map the kernel uses (IEEE
total order for floats), stably — so expected indices pin BOTH the
selected set and the reference tie rule (lowest column index wins among
equal values; ref: select_radix.cuh's in-order last-pass writes).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_tpu.matrix import SelectAlgo, select_k
from raft_tpu.matrix.radix_select import radix_select_k, supports


def _oracle(v, k, select_min=True):
    v = np.asarray(v)
    if v.dtype.kind == "f":
        b = v.astype(np.float32).view(np.int32)
        key = (b ^ ((b >> 31) & 0x7FFFFFFF)).astype(np.int64)
    else:
        key = v.astype(np.int64)
    if not select_min:
        key = -key - 1
    order = np.argsort(key, axis=1, kind="stable")
    idx = order[:, :k]
    return np.take_along_axis(v, idx, 1), idx


def _check(v, k, select_min=True):
    ov, oi = _oracle(v, k, select_min)
    gv, gi = radix_select_k(jnp.asarray(v), k, select_min)
    np.testing.assert_array_equal(np.asarray(gi), oi)
    np.testing.assert_array_equal(
        np.asarray(gv).astype(np.float64),
        ov.astype(np.float64))


class TestRadixSelect:
    def test_random_f32(self):
        rng = np.random.default_rng(0)
        _check(rng.normal(size=(13, 1000)).astype(np.float32), 7)

    @pytest.mark.parametrize("k", [1, 2, 127, 128, 129, 255])
    def test_k_boundaries(self, k):
        rng = np.random.default_rng(k)
        _check(rng.normal(size=(5, 777)).astype(np.float32), k)

    @pytest.mark.parametrize("n_cols", [511, 512, 513, 1000, 4096])
    def test_len_boundaries(self, n_cols):
        rng = np.random.default_rng(n_cols)
        _check(rng.normal(size=(4, n_cols)).astype(np.float32),
               min(31, n_cols))

    def test_k_equals_len(self):
        rng = np.random.default_rng(3)
        _check(rng.normal(size=(2, 256)).astype(np.float32), 256)

    def test_select_max(self):
        rng = np.random.default_rng(4)
        _check(rng.normal(size=(6, 900)).astype(np.float32), 33,
               select_min=False)

    def test_large_k_above_preferred_band(self):
        # kh = 32 leaves the (16, 1024) emission tile for (8, 1024)
        # (advisor finding, round 3: large-k live-set gating)
        rng = np.random.default_rng(41)
        _check(rng.normal(size=(2, 8192)).astype(np.float32), 4096)

    def test_emit_tiles_fit_budget_up_to_max_k(self):
        from raft_tpu.linalg.contractions import _VMEM_BUDGET
        from raft_tpu.matrix.radix_select import (MAX_K,
                                                  _emit_live_set_bytes,
                                                  _emit_tiles)

        assert MAX_K == 128 * 128   # kh sample below covers the envelope
        for kh in (1, 4, 16, 17, 32, 64, 128):
            tm, tl = _emit_tiles(kh)
            assert _emit_live_set_bytes(tm, tl, kh) <= _VMEM_BUDGET
            # tm = 16 is the hardware-validated band only
            assert kh <= 16 or tm == 8
        assert _emit_tiles(16) == (16, 1024)   # preferred band unchanged

    def test_all_equal_rows_tie_to_first_indices(self):
        v = np.zeros((3, 600), np.float32)
        _, gi = radix_select_k(v, 5)
        np.testing.assert_array_equal(np.asarray(gi),
                                      np.tile(np.arange(5), (3, 1)))

    def test_duplicate_blocks_first_come(self):
        v = np.array([[5., 7., 5., 7., 5.]], np.float32)
        _, gi = radix_select_k(v, 3, select_min=False)
        assert np.asarray(gi).tolist() == [[1, 3, 0]]
        _, gi = radix_select_k(v, 3)
        assert np.asarray(gi).tolist() == [[0, 2, 4]]

    def test_nan_inf_total_order(self):
        v = np.array([[4., np.nan, 1., 2., np.inf, -np.inf, -np.nan]],
                     np.float32)
        gv, gi = radix_select_k(v, 3)
        assert np.isnan(np.asarray(gv)[0, 0]) and np.asarray(gi)[0, 0] == 6
        assert np.asarray(gv)[0, 1] == -np.inf
        assert np.asarray(gv)[0, 2] == 1.0
        gv, gi = radix_select_k(v, 3, select_min=False)
        assert np.isnan(np.asarray(gv)[0, 0]) and np.asarray(gi)[0, 0] == 1
        assert np.asarray(gv)[0, 1] == np.inf
        assert np.asarray(gv)[0, 2] == 4.0

    def test_threshold_straddles_tie_run(self):
        # exactly the radix hard case: the k-th value sits inside a run
        # of equal values; only the earliest columns of the run belong
        v = np.full((1, 300), 2.0, np.float32)
        v[0, 250:] = 1.0                      # 50 strictly-smaller at the end
        gv, gi = radix_select_k(v, 60)
        # 50 ones (cols 250..299) then the first 10 twos (cols 0..9)
        assert np.asarray(gv)[0].tolist() == [1.0] * 50 + [2.0] * 10
        assert np.asarray(gi)[0, :50].tolist() == list(range(250, 300))
        assert np.asarray(gi)[0, 50:].tolist() == list(range(10))

    @pytest.mark.parametrize("dt", [np.int8, np.int16, np.int32,
                                    np.uint8, np.uint16, np.uint32])
    def test_int_dtypes(self, dt):
        rng = np.random.default_rng(11)
        info = np.iinfo(dt)
        v = rng.integers(info.min, int(info.max) + 1,
                         size=(5, 700)).astype(dt)
        _check(v, 9)
        _check(v, 9, select_min=False)

    @pytest.mark.parametrize("dt", [np.float16, jnp.bfloat16])
    def test_small_floats(self, dt):
        rng = np.random.default_rng(12)
        v = jnp.asarray(rng.normal(size=(4, 500)).astype(np.float32), dt)
        gv, gi = radix_select_k(v, 11)
        ov, oi = _oracle(np.asarray(v, np.float32), 11)
        np.testing.assert_array_equal(np.asarray(gi), oi)
        assert gv.dtype == jnp.asarray(v).dtype

    def test_int_extremes(self):
        v = np.array([[np.iinfo(np.int32).min, np.iinfo(np.int32).max,
                       0, -1, 1]], np.int32)
        _check(v, 3)
        _check(v, 3, select_min=False)

    def test_supports_envelope(self):
        assert supports(np.float32, 1 << 20, 16384)
        # past the VMEM-resident chunk bound: the two-level scheme
        # (VERDICT r4 #7) supports up to the 2^24 index-encoding cap
        assert supports(np.float32, (1 << 20) + 1, 16)
        assert supports(np.float32, 1 << 24, 256)
        assert not supports(np.float32, (1 << 24) + 1, 16)
        # merge pool always fits one chunk at the real constants
        # (16 chunks * MAX_K = 2^18 <= 2^20), so MAX_K holds at 2^24 too
        assert supports(np.float32, 1 << 24, 16384)
        assert not supports(np.float32, 32768, 16385)
        assert not supports(np.float32, 1024, 2048)   # k > n_cols
        assert not supports(np.float64, 1024, 16)
        assert not supports(np.int64, 1024, 16)
        with pytest.raises(ValueError):
            radix_select_k(np.zeros((2, 100), np.float32), 200)

    def test_two_level_past_chunk_bound(self):
        """Rows past CHUNK_LEN run per-chunk select + one merge select;
        exact agreement with the oracle incl. cross-chunk ties."""
        from raft_tpu.matrix import radix_select as rs

        old = rs.CHUNK_LEN
        rs.CHUNK_LEN = 4096          # force the two-level path cheaply
        try:
            rng = np.random.default_rng(31)
            v = rng.normal(size=(3, 10000)).astype(np.float32)
            # inject cross-chunk duplicates so the merge tie rule is load-
            # bearing: the winner set must take the LOWEST column ids
            v[0, 17] = v[0, 4500] = v[0, 9999] = v[0].min() - 1.0
            v[1, 5000:5008] = -100.0
            gv, gi = rs.radix_select_k(v, 12)
            ov, oi = _oracle(v, 12)
            np.testing.assert_array_equal(np.asarray(gi), oi)
            np.testing.assert_array_equal(np.asarray(gv), ov)
            # non-divisible length + k ties straddling the pad boundary
            v2 = np.full((2, 9001), 7.0, np.float32)
            gv2, gi2 = rs.radix_select_k(v2, 20)
            np.testing.assert_array_equal(np.asarray(gi2),
                                          np.tile(np.arange(20), (2, 1)))
            np.testing.assert_array_equal(np.asarray(gv2),
                                          np.full((2, 20), 7.0))
        finally:
            rs.CHUNK_LEN = old

    def test_jit_surface(self):
        rng = np.random.default_rng(13)
        v = rng.normal(size=(4, 600)).astype(np.float32)
        f = jax.jit(lambda a: radix_select_k(a, 9))
        gv, gi = f(v)
        ov, oi = _oracle(v, 9)
        np.testing.assert_array_equal(np.asarray(gi), oi)


class TestSelectKDispatch:
    def test_radix_enum_routes_to_radix_kernel(self):
        rng = np.random.default_rng(14)
        v = rng.normal(size=(3, 9000)).astype(np.float32)
        for algo in (SelectAlgo.RADIX_8BITS, SelectAlgo.RADIX_11BITS,
                     SelectAlgo.RADIX_11BITS_EXTRA_PASS):
            gv, gi = select_k(None, v, 20, algo=algo)
            ov, oi = _oracle(v, 20)
            np.testing.assert_array_equal(np.asarray(gi), oi)
            np.testing.assert_allclose(np.asarray(gv), ov)

    def test_auto_agrees_with_direct_everywhere(self):
        rng = np.random.default_rng(15)
        for n_cols, k in [(8192, 17), (9000, 64), (4096, 32), (700, 8)]:
            v = rng.normal(size=(2, n_cols)).astype(np.float32)
            av, ai = select_k(None, v, k)
            dv, di = select_k(None, v, k,
                              algo=SelectAlgo.WARPSORT_IMMEDIATE)
            np.testing.assert_array_equal(np.asarray(ai), np.asarray(di))

    def test_in_idx_passthrough_on_radix(self):
        rng = np.random.default_rng(16)
        v = rng.normal(size=(2, 8500)).astype(np.float32)
        payload = jnp.asarray(
            rng.integers(0, 1 << 30, size=(2, 8500)), jnp.int32)
        _, gi = select_k(None, v, 20, algo=SelectAlgo.RADIX_11BITS)
        _, pi = select_k(None, v, 20, in_idx=payload,
                         algo=SelectAlgo.RADIX_11BITS)
        np.testing.assert_array_equal(
            np.asarray(pi),
            np.take_along_axis(np.asarray(payload), np.asarray(gi), 1))


class TestRadixFuzz:
    """Randomized shape/k/distribution fuzz vs the stable-argsort oracle
    — 40 drawn cases per run (fixed seed: reproducible), covering
    duplicate-heavy, constant, bimodal, subnormal-range, and integer-
    valued float distributions across both tm regimes."""

    # slow: ~50s of CPU wall for the 40-trial sweep — off the tier-1
    # budget; the deterministic single-case oracle tests above keep the
    # kernel covered there.
    @pytest.mark.slow
    def test_fuzz_against_oracle(self):
        rng = np.random.default_rng(2024)
        for trial in range(40):
            n_rows = int(rng.integers(1, 40))
            n_cols = int(rng.integers(2, 3000))
            k = int(rng.integers(1, n_cols + 1))
            style = trial % 5
            if style == 0:
                v = rng.normal(size=(n_rows, n_cols))
            elif style == 1:      # duplicate-heavy
                v = rng.integers(0, 7, size=(n_rows, n_cols))
            elif style == 2:      # constant rows
                v = np.tile(rng.normal(size=(n_rows, 1)), (1, n_cols))
            elif style == 3:      # bimodal with inf spikes
                v = np.where(rng.random((n_rows, n_cols)) < 0.1,
                             np.inf, rng.normal(size=(n_rows, n_cols)))
            else:                 # tiny magnitudes (subnormal-range)
                v = rng.normal(size=(n_rows, n_cols)) * 1e-40
            v = v.astype(np.float32)
            sm = bool(trial % 2)
            gv, gi = radix_select_k(jnp.asarray(v), k, select_min=sm)
            ov, oi = _oracle(v, k, sm)
            np.testing.assert_array_equal(
                np.asarray(gi), oi,
                err_msg=f"trial={trial} shape={(n_rows, n_cols)} "
                        f"k={k} sm={sm}")


class TestDigitHistogramThreshold:
    """Era-7 digit-histogram threshold stage: pass-count provenance,
    lax.top_k parity across dtypes, and envelope fallbacks."""

    def test_trace_event_pass_count(self):
        from raft_tpu.core import trace
        from raft_tpu.matrix import radix_select as rs
        rng = np.random.default_rng(70)
        v = rng.normal(size=(3, 1000)).astype(np.float32)
        trace.clear_events()
        radix_select_k(jnp.asarray(v), 20)
        evs = trace.events("radix.select")
        assert evs, "radix_select_k must record its dispatch event"
        ev = evs[-1]
        # acceptance bar: the selected set is identified in <= 5 full-
        # row passes (NPASS digit passes; emission adds one more read)
        assert ev["threshold_passes"] == rs.NPASS
        assert ev["threshold_passes"] + 1 <= 5
        assert ev["path"] == "single"
        assert (ev["rows"], ev["cols"], ev["k"]) == (3, 1000, 20)

    def test_trace_event_two_level_path(self):
        from raft_tpu.core import trace
        from raft_tpu.matrix import radix_select as rs
        old = rs.CHUNK_LEN
        rs.CHUNK_LEN = 1024
        try:
            rng = np.random.default_rng(71)
            v = rng.normal(size=(2, 3000)).astype(np.float32)
            trace.clear_events()
            radix_select_k(jnp.asarray(v), 8)
            assert trace.events("radix.select")[-1]["path"] == "two_level"
        finally:
            rs.CHUNK_LEN = old

    @pytest.mark.parametrize("dt", [np.float32, jnp.bfloat16, np.int32])
    def test_lax_top_k_value_parity(self, dt):
        """Selected VALUES match lax.top_k bit-for-bit per dtype (index
        tie rules differ: top_k has no documented tie order, so parity
        is on the sorted value multiset)."""
        rng = np.random.default_rng(72)
        v = rng.integers(-50, 50, size=(5, 2000)) if dt == np.int32 \
            else rng.normal(size=(5, 2000))
        x = jnp.asarray(v).astype(dt)
        gv, _ = radix_select_k(x, 37, select_min=False)
        tv, _ = jax.lax.top_k(x, 37)
        np.testing.assert_array_equal(
            np.asarray(gv).astype(np.float64),
            np.asarray(tv).astype(np.float64))

    def test_tie_count_is_exact(self):
        """Heavy-tie input where the threshold digit is shared by most
        of the row: exactly k columns come back, ties resolved
        first-come (the ntie quota cannot over- or under-emit)."""
        v = np.zeros((4, 1024), np.float32)
        v[:, ::3] = -1.0          # below-threshold mass
        gv, gi = radix_select_k(jnp.asarray(v), 400)
        below = (np.asarray(gv) == -1.0).sum(axis=1)
        np.testing.assert_array_equal(below, np.full(4, 342))
        # tie quota filled strictly first-come among the zeros
        zero_cols = np.setdiff1d(np.arange(1024), np.arange(0, 1024, 3))
        for r in range(4):
            got_zero = np.sort(np.asarray(gi)[r][np.asarray(gv)[r] == 0.0])
            np.testing.assert_array_equal(got_zero, zero_cols[:400 - 342])

    def test_envelope_k_above_max_falls_back(self):
        """k > MAX_K: supports() refuses, and the explicit radix enum
        falls back to a tournament path that still answers correctly."""
        from raft_tpu.matrix import radix_select as rs
        assert not supports(np.float32, 1 << 15, rs.MAX_K + 1)
        rng = np.random.default_rng(73)
        v = rng.normal(size=(2, 1 << 15)).astype(np.float32)
        k = rs.MAX_K + 1
        gv, gi = select_k(None, v, k, algo=SelectAlgo.RADIX_8BITS)
        ov, oi = _oracle(v, k)
        np.testing.assert_array_equal(np.asarray(gi), oi)

    def test_envelope_cols_above_max_len(self):
        from raft_tpu.matrix import radix_select as rs
        assert not supports(np.float32, rs.MAX_LEN + 1, 512)
        assert not rs.preferred(rs.MAX_LEN + 1, 512)

    def test_preferred_band_extends_to_max_k(self):
        """Era-7 band: short rows (>= MIN_COLS) prefer radix for the
        whole 16 < k <= MAX_K band; long rows keep the k > 256 gate."""
        from raft_tpu.matrix import radix_select as rs
        assert rs.preferred(rs.MIN_COLS, rs.MAX_K)
        assert rs.preferred(rs.MIN_COLS, 17)
        assert not rs.preferred(rs.MIN_COLS, 16)
        assert not rs.preferred(rs.MIN_COLS - 1, 512)
        assert rs.preferred(1 << 20, 257)
        assert rs.preferred(1 << 20, rs.MAX_K)
        assert not rs.preferred(1 << 20, 16)

    def test_hist_tiles_fit_budget(self):
        """Every (tm, tl) the threshold sizer can pick stays inside the
        shared VMEM budget."""
        from raft_tpu.matrix import radix_select as rs
        from raft_tpu.linalg.contractions import _VMEM_BUDGET
        for lp in (1024, 2048, 4096, 8192, 1 << 20):
            for n_rows in (1, 7, 8, 64, 1000):
                tm, tl = rs._hist_tiles(n_rows, lp, 8)
                assert lp % tl == 0
                assert rs._hist_live_set_bytes(tm, tl) <= _VMEM_BUDGET


class TestSelectionCostModel:
    def test_traffic_ratio_meets_bar(self):
        """Acceptance bar: the digit-histogram walk moves >= 4x fewer
        selection-stage bytes than the binary-search threshold."""
        from benches import select_model
        assert select_model.traffic_ratio() >= 4.0

    def test_bytes_scale_with_shape(self):
        from benches import select_model
        b = select_model.selection_bytes(64, 1 << 20)
        assert b == select_model.DIGIT_HIST_PASSES * 64 * (1 << 20) * 4
        assert select_model.selection_bytes(64, 1 << 20, algo="binary") \
            == select_model.BINARY_SEARCH_PASSES * 64 * (1 << 20) * 4
