"""Core runtime tests (ref test models: cpp/tests/core/*)."""

import io
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import raft_tpu
from raft_tpu.core import (
    Bitmap,
    Bitset,
    CSRMatrix,
    COOMatrix,
    InterruptedException,
    MdBuffer,
    MemoryType,
    ResourceType,
    copy,
    make_device_matrix,
    make_device_vector,
    make_host_matrix,
    serialize,
)
from raft_tpu.core import interruptible, memory, trace
from raft_tpu.core.resources import (
    ResourceFactory,
    Resources,
    get_device_resources,
    get_mesh,
    get_rng_state,
    get_workspace_limit,
    set_workspace_limit,
)


class TestResources:
    def test_lazy_construction(self):
        res = Resources()
        calls = []

        def make():
            calls.append(1)
            return "the-resource"

        res.add_resource_factory(ResourceFactory(ResourceType.LOGGER, make))
        assert calls == []
        assert res.get_resource(ResourceType.LOGGER) == "the-resource"
        assert res.get_resource(ResourceType.LOGGER) == "the-resource"
        assert calls == [1]  # constructed exactly once

    def test_missing_factory_raises(self):
        res = Resources()
        with pytest.raises(KeyError):
            res.get_resource(ResourceType.COMMS)

    def test_shallow_copy_shares_state(self):
        res = Resources()
        res.set_resource(ResourceType.WORKSPACE, 123)
        clone = Resources(res)
        assert clone.get_resource(ResourceType.WORKSPACE) == 123
        clone.set_resource(ResourceType.WORKSPACE, 456)
        assert res.get_resource(ResourceType.WORKSPACE) == 456

    def test_device_resources_defaults(self, res):
        assert res.device in jax.devices()
        assert get_mesh(res) is not None
        assert get_rng_state(res).seed == 42

    def test_workspace_limit(self, res):
        set_workspace_limit(res, 1 << 20)
        assert get_workspace_limit(res) == 1 << 20

    def test_manager_caches_per_thread(self):
        h1 = get_device_resources()
        h2 = get_device_resources()
        assert h1 is h2
        results = []

        def worker():
            results.append(get_device_resources())

        t = threading.Thread(target=worker)
        t.start()
        t.join()
        assert results[0] is not h1

    def test_sync(self, res):
        x = jnp.ones((8, 8)) * 2
        res.sync_stream(x)


class TestMdArray:
    def test_factories(self, res):
        m = make_device_matrix(res, 4, 5)
        assert m.shape == (4, 5)
        assert m.memory_type == MemoryType.DEVICE
        v = make_device_vector(res, 7, dtype=jnp.int32)
        assert v.dtype == jnp.int32
        h = make_host_matrix(3, 3)
        assert isinstance(h.view(), np.ndarray)

    def test_copy_host_device_roundtrip(self, res):
        h = make_host_matrix(4, 4, dtype=np.float64)
        h.data[:] = np.arange(16, dtype=np.float64).reshape(4, 4)
        d = make_device_matrix(res, 4, 4, dtype=jnp.float32)
        copy(res, d, h)
        back = make_host_matrix(4, 4, dtype=np.float64)
        copy(res, back, d)
        np.testing.assert_allclose(np.asarray(back.view()),
                                   np.asarray(h.view()))

    def test_mdbuffer_lazy_copy(self):
        src = np.arange(12, dtype=np.float32).reshape(3, 4)
        buf = MdBuffer(src)
        dview = buf.view(MemoryType.DEVICE)
        assert isinstance(dview, jax.Array)
        # cached: same object on second call
        assert buf.view(MemoryType.DEVICE) is dview
        np.testing.assert_array_equal(np.asarray(dview), src)


class TestBitset:
    def test_roundtrip(self):
        bools = np.array([True, False, True, True] * 17 + [False])
        bs = Bitset.from_bools(bools)
        np.testing.assert_array_equal(np.asarray(bs.to_bools()), bools)
        assert int(bs.count()) == int(bools.sum())

    def test_set_and_test(self):
        bs = Bitset(70, default_value=False)
        bs = bs.set(jnp.array([0, 33, 69]))
        assert bool(bs.test(33))
        assert not bool(bs.test(34))
        assert int(bs.count()) == 3
        bs = bs.set(jnp.array([33]), value=False)
        assert int(bs.count()) == 2

    @pytest.mark.parametrize("extra", [0, 2000])
    def test_set_paths_agree(self, extra):
        # extra=0 stays under _SORT_THRESHOLD (plane scatter); extra=2000
        # crosses it (sort+cumsum). Same semantics on both: duplicates
        # combine; negatives, >= n_bits, and the packed tail of the last
        # word all drop; clears (value=False) mirror sets.
        from raft_tpu.core.bitset import _SORT_THRESHOLD

        rng = np.random.default_rng(3)
        n = 40_007                                  # n % 32 != 0: tail bits
        count = _SORT_THRESHOLD - 1000 + extra
        ids = rng.integers(0, n, size=count)
        ids = np.concatenate([ids, ids[:500],       # duplicates
                              [-3, -1, n, n + 17,   # out of range
                               n + (32 - n % 32) - 1]])   # tail of last word
        bs = Bitset(n, default_value=False).set(jnp.asarray(ids))
        want = np.zeros(n, dtype=bool)
        valid = ids[(ids >= 0) & (ids < n)]
        want[valid] = True
        np.testing.assert_array_equal(np.asarray(bs.to_bools()), want)
        assert int(bs.count()) == int(want.sum())
        clear = np.concatenate([valid[:1000], [-3, n]])
        bs2 = bs.set(jnp.asarray(clear), value=False)
        want[valid[:1000]] = False
        np.testing.assert_array_equal(np.asarray(bs2.to_bools()), want)

    def test_flip_all_none(self):
        bs = Bitset(10, default_value=False)
        assert bool(bs.none())
        flipped = bs.flip()
        assert bool(flipped.all())
        assert int(flipped.count()) == 10

    def test_bitmap(self):
        mat = np.zeros((5, 9), dtype=bool)
        mat[2, 3] = True
        mat[4, 8] = True
        bm = Bitmap.from_bool_matrix(mat)
        assert bool(bm.test_rc(2, 3))
        assert not bool(bm.test_rc(2, 4))
        np.testing.assert_array_equal(np.asarray(bm.to_bool_matrix()), mat)


class TestSparseTypes:
    def test_csr_scipy_roundtrip(self):
        import scipy.sparse as sp

        rng = np.random.default_rng(0)
        m = sp.random(20, 30, density=0.2, random_state=rng, format="csr")
        ours = CSRMatrix.from_scipy(m)
        # nnz bucketing (default on): physical nnz is the size class,
        # indptr[-1] keeps the logical count; scipy roundtrip is exact
        assert ours.logical_nnz() == m.nnz
        from raft_tpu.core.sparse_types import nnz_bucket
        assert ours.nnz == nnz_bucket(m.nnz)
        back = ours.to_scipy()
        assert back.nnz == m.nnz
        assert (abs(back - m)).max() < 1e-12
        unpadded = CSRMatrix.from_scipy(m, pad=False)
        assert unpadded.nnz == m.nnz

    def test_coo_roundtrip_and_pytree(self):
        coo = COOMatrix(jnp.array([0, 1]), jnp.array([2, 0]),
                        jnp.array([1.0, 2.0]), (3, 4))
        leaves = jax.tree_util.tree_leaves(coo)
        assert len(leaves) == 3

        @jax.jit
        def scale(c):
            return COOMatrix(c.rows, c.cols, c.data * 2, c.shape)

        out = scale(coo)
        np.testing.assert_allclose(np.asarray(out.data), [2.0, 4.0])

    def test_csr_row_ids(self):
        indptr = jnp.array([0, 2, 2, 5])
        csr = CSRMatrix(indptr, jnp.array([0, 1, 0, 1, 2]),
                        jnp.ones(5), (3, 3))
        np.testing.assert_array_equal(np.asarray(csr.row_ids()),
                                      [0, 0, 2, 2, 2])


class TestSerialize:
    def test_npy_roundtrip_device(self, res):
        x = jnp.arange(24, dtype=jnp.float32).reshape(4, 6)
        buf = io.BytesIO()
        serialize.serialize_mdspan(res, buf, x)
        buf.seek(0)
        y = serialize.deserialize_mdspan(res, buf)
        np.testing.assert_array_equal(np.asarray(y), np.asarray(x))
        # also numpy-compatible
        buf.seek(0)
        z = np.load(buf)
        np.testing.assert_array_equal(z, np.asarray(x))

    def test_dumps_loads(self):
        x = np.random.default_rng(0).normal(size=(5, 5))
        data = serialize.dumps(x)
        y = serialize.loads(data, to_device=False)
        np.testing.assert_array_equal(x, y)

    @pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
    def test_dumps_loads_low_precision(self, dtype):
        """bf16 rides the wire as a named one-field structured dtype
        (np.save would otherwise degrade it to typeless '|V2' bytes) and
        round-trips exactly; f32 stays a plain .npy."""
        import ml_dtypes

        dt = np.float32 if dtype == "float32" else ml_dtypes.bfloat16
        x = (np.arange(20, dtype=np.float32).reshape(4, 5) / 3.0).astype(dt)
        y = serialize.loads(serialize.dumps(x), to_device=False)
        assert y.dtype == x.dtype
        np.testing.assert_array_equal(y.astype(np.float32),
                                      x.astype(np.float32))

    def test_scalar_roundtrip_native_types(self, res):
        """deserialize_scalar returns NATIVE python values (the ref's
        deserialize_scalar<T> returns T): np.float64/np.int64 leaking
        into params structs broke ==/is comparisons downstream."""
        for val, want in ((3, int), (2.5, float), (True, bool),
                         (np.int64(-7), int), (np.float32(1.5), float)):
            buf = io.BytesIO()
            serialize.serialize_scalar(res, buf, val)
            buf.seek(0)
            out = serialize.deserialize_scalar(res, buf)
            assert out == val
            assert type(out) is want, (val, type(out))


class TestInterruptible:
    def test_cancel_raises_on_next_check(self):
        token = interruptible.get_token()
        token.cancel()
        with pytest.raises(InterruptedException):
            interruptible.yield_now()
        # flag consumed: next check passes
        interruptible.yield_now()

    def test_cross_thread_cancel(self):
        errors = []
        started = threading.Event()
        tid_holder = []

        def worker():
            tid_holder.append(threading.get_ident())
            started.set()
            try:
                for _ in range(2000):
                    interruptible.synchronize(jnp.ones(4))
            except InterruptedException:
                errors.append("interrupted")

        t = threading.Thread(target=worker)
        t.start()
        started.wait()
        interruptible.cancel(tid_holder[0])
        t.join(timeout=30)
        assert errors == ["interrupted"]


class TestTrace:
    def test_range_stack(self):
        assert trace.current_range() is None
        with trace.push_range("outer"):
            with trace.push_range("inner"):
                assert trace.current_range() == "inner"
                assert trace.range_stack() == ["outer", "inner"]
            assert trace.current_range() == "outer"
        assert trace.current_range() is None

    def test_annotate_decorator(self):
        @trace.annotate("my_op")
        def fn(x):
            assert trace.current_range() == "my_op"
            return x + 1

        assert fn(1) == 2


class TestMemory:
    def test_statistics_tracker(self):
        tr = memory.StatisticsTracker()
        tr.on_alloc(100)
        tr.on_alloc(50)
        tr.on_dealloc(100)
        b, peak, na, nd = tr.snapshot()
        assert (b, peak, na, nd) == (50, 150, 2, 1)

    def test_notifying_tracker(self):
        tr = memory.NotifyingTracker()
        events = []
        tr.subscribe(lambda kind, n: events.append((kind, n)))
        tr.on_alloc(10)
        tr.on_dealloc(10)
        assert events == [("alloc", 10), ("dealloc", 10)]

    def test_resource_monitor_writes_csv(self, tmp_path):
        path = tmp_path / "monitor.csv"
        tr = memory.StatisticsTracker()
        with memory.ResourceMonitor(str(path), tracker=tr, interval_s=0.01):
            tr.on_alloc(1000)
            import time

            time.sleep(0.05)
        lines = path.read_text().strip().splitlines()
        assert lines[0].startswith("time_s,range")
        assert len(lines) >= 2

    def test_mmap_buffer(self):
        with memory.mmap_buffer(4096) as buf:
            arr = buf.as_array(np.float32, (32, 32))
            arr[:] = 7.0
            assert arr.sum() == 7.0 * 1024


class TestOperators:
    def test_compose_and_plug(self):
        from raft_tpu.core import operators as ops

        f = ops.compose_op(ops.sqrt_op, ops.abs_op)
        assert float(f(jnp.asarray(-4.0))) == 2.0
        add3 = ops.plug_const_op(ops.add_op, 3.0)
        assert float(add3(jnp.asarray(1.0))) == 4.0

    def test_argmin_op(self):
        from raft_tpu.core import operators as ops

        k, v = ops.argmin_op((jnp.asarray(5), jnp.asarray(2.0)),
                             (jnp.asarray(3), jnp.asarray(2.0)))
        assert int(k) == 3  # tie → smaller key


class TestVectorCache:
    """ref test model: cpp/tests/util (cache tests)."""

    def test_miss_then_hit(self):
        import numpy as np
        from raft_tpu.util import VectorCache

        cache = VectorCache(n_vec=4, capacity=8, associativity=4)
        keys = np.array([3, 9, 3])
        idx = np.asarray(cache.get_cache_idx(keys))
        assert (idx == -1).all()
        slots = np.asarray(cache.assign_cache_idx(np.array([3, 9])))
        assert (slots >= 0).all() and slots[0] != slots[1]
        vecs = np.arange(8, dtype=np.float32).reshape(2, 4)
        cache.store_vecs(vecs, slots)
        idx = np.asarray(cache.get_cache_idx(keys))
        assert (idx >= 0).all()
        got = np.asarray(cache.get_vecs(idx))
        np.testing.assert_array_equal(got[0], vecs[0])
        np.testing.assert_array_equal(got[1], vecs[1])
        np.testing.assert_array_equal(got[2], vecs[0])

    def test_lru_eviction_and_get_or_compute(self):
        import numpy as np
        from raft_tpu.util import VectorCache

        cache = VectorCache(n_vec=2, capacity=4, associativity=4)
        calls = []

        def compute(keys):
            k = np.asarray(keys)
            calls.append(k.tolist())
            return np.stack([k.astype(np.float32)] * 2, axis=1)

        out = np.asarray(cache.get_or_compute(np.array([0, 1, 2, 3]),
                                              compute))
        np.testing.assert_array_equal(out[:, 0], [0, 1, 2, 3])
        # all cached now: no new compute
        cache.get_or_compute(np.array([1, 2]), compute)
        assert len(calls) == 1
        # 5th key evicts the LRU slot; cache stays consistent
        out = np.asarray(cache.get_or_compute(np.array([4, 1]), compute))
        np.testing.assert_array_equal(out[:, 0], [4, 1])


class TestBitsetProperty:
    """Property sweep: Bitset ops vs a numpy bool-array oracle across
    random index streams, duplicate-heavy sets, word-boundary sizes, and
    full clear/set cycles (ref model: cpp/tests/core/bitset.cu's
    parameterized grids)."""

    @pytest.mark.parametrize("n_bits", [1, 31, 32, 33, 64, 1000, 4097])
    def test_random_op_stream_matches_oracle(self, n_bits):
        from raft_tpu.core.bitset import Bitset

        rng = np.random.default_rng(n_bits)
        oracle = np.zeros(n_bits, bool)
        bs = Bitset(n_bits, default_value=False)
        for _ in range(4):
            ids = rng.integers(0, n_bits, size=max(1, n_bits // 3))
            val = bool(rng.integers(0, 2))
            bs = bs.set(jnp.asarray(ids.astype(np.int32)), val)
            oracle[ids] = val
            np.testing.assert_array_equal(np.asarray(bs.to_bools()),
                                          oracle)
            assert int(bs.count()) == int(oracle.sum())
        flipped = bs.flip()
        np.testing.assert_array_equal(np.asarray(flipped.to_bools()),
                                      ~oracle)
        # tail bits beyond n_bits must not leak into count after flip
        assert int(flipped.count()) == int((~oracle).sum())

    def test_duplicate_indices_last_write_semantics(self):
        from raft_tpu.core.bitset import Bitset

        bs = Bitset(64, default_value=False)
        ids = jnp.asarray(np.array([5, 5, 5, 9], np.int32))
        bs = bs.set(ids, True)
        assert int(bs.count()) == 2
        assert bool(bs.test(jnp.asarray([5]))[0])

    def test_popc_matches_bit_count(self):
        """popc totals the set bits of the whole word span (the
        reference's detail::popc reduction, not a per-word map)."""
        from raft_tpu.core.bitset import popc

        rng = np.random.default_rng(3)
        words = rng.integers(0, 2 ** 31, size=257, dtype=np.int64)
        got = int(popc(jnp.asarray(words.astype(np.int32))))
        want = sum(bin(int(w)).count("1") for w in words)
        assert got == want


class TestSerializeDtypeGrid:
    """.npy serialization roundtrip across the dtype/order grid (ref:
    detail/mdspan_numpy_serializer.hpp + tests/core/numpy_serializer.cu's
    typed instantiations)."""

    @pytest.mark.parametrize("dtype", [np.float32, np.float64, np.int32,
                                       np.int64, np.uint8, np.bool_,
                                       np.float16])
    def test_dumps_loads_roundtrip(self, dtype):
        rng = np.random.default_rng(5)
        if dtype == np.bool_:
            a = rng.uniform(size=(6, 7)) < 0.5
        elif np.issubdtype(dtype, np.floating):
            a = rng.normal(size=(6, 7)).astype(dtype)
        else:
            a = rng.integers(0, 100, size=(6, 7)).astype(dtype)
        blob = serialize.dumps(a)
        back = np.asarray(serialize.loads(blob, to_device=False))
        assert back.dtype == a.dtype
        np.testing.assert_array_equal(back, a)
        # the wire format IS .npy: numpy itself must read it
        np.testing.assert_array_equal(np.load(io.BytesIO(blob)), a)

    def test_fortran_order_input_roundtrips(self):
        a = np.asfortranarray(np.arange(12, dtype=np.float32)
                              .reshape(3, 4))
        back = np.asarray(serialize.loads(serialize.dumps(a),
                                          to_device=False))
        np.testing.assert_array_equal(back, a)

    def test_numpy_written_npy_loads(self):
        """Interop the other way: a numpy-written .npy must deserialize
        (the reference reads numpy files through the same header)."""
        a = np.arange(20, dtype=np.int32).reshape(4, 5)
        buf = io.BytesIO()
        np.save(buf, a)
        back = np.asarray(serialize.loads(buf.getvalue(), to_device=False))
        np.testing.assert_array_equal(back, a)


class TestDeviceCache:
    """Jit-usable functional cache (ref device primitive:
    util/cache_util.cuh in-kernel lookup/assign)."""

    def test_insert_lookup_roundtrip(self):
        from raft_tpu.util import (device_cache_init, device_cache_insert,
                                   device_cache_lookup)

        st = device_cache_init(n_vec=4, capacity=32, associativity=4)
        keys = jnp.asarray([3, 7, 100], jnp.int32)
        vecs = jnp.arange(12, dtype=jnp.float32).reshape(3, 4)
        st = device_cache_insert(st, keys, vecs)
        out, hit, st = device_cache_lookup(st, jnp.asarray([7, 3, 5]))
        np.testing.assert_array_equal(np.asarray(hit), [True, True, False])
        np.testing.assert_array_equal(np.asarray(out[0]), vecs[1])
        np.testing.assert_array_equal(np.asarray(out[1]), vecs[0])
        np.testing.assert_array_equal(np.asarray(out[2]), np.zeros(4))

    def test_lru_eviction_respects_touch(self):
        from raft_tpu.util import (device_cache_init, device_cache_insert,
                                   device_cache_lookup)

        # one set, two ways: insert a,b; touch a; insert c -> b evicted
        st = device_cache_init(n_vec=2, capacity=2, associativity=2)
        st = device_cache_insert(st, jnp.asarray([10]),
                                 jnp.asarray([[1.0, 1.0]]))
        st = device_cache_insert(st, jnp.asarray([20]),
                                 jnp.asarray([[2.0, 2.0]]))
        _, hit, st = device_cache_lookup(st, jnp.asarray([10]))  # touch a
        assert bool(hit[0])
        st = device_cache_insert(st, jnp.asarray([30]),
                                 jnp.asarray([[3.0, 3.0]]))
        _, hit, st = device_cache_lookup(st, jnp.asarray([10, 20, 30]))
        np.testing.assert_array_equal(np.asarray(hit),
                                      [True, False, True])

    def test_overwrite_existing_key(self):
        from raft_tpu.util import (device_cache_init, device_cache_insert,
                                   device_cache_lookup)

        st = device_cache_init(n_vec=2, capacity=8, associativity=2)
        st = device_cache_insert(st, jnp.asarray([5]),
                                 jnp.asarray([[1.0, 1.0]]))
        st = device_cache_insert(st, jnp.asarray([5]),
                                 jnp.asarray([[9.0, 9.0]]))
        out, hit, _ = device_cache_lookup(st, jnp.asarray([5]))
        assert bool(hit[0])
        np.testing.assert_array_equal(np.asarray(out[0]), [9.0, 9.0])
        # overwrote in place: no second copy of the key in its set
        assert int((np.asarray(st.keys) == 5).sum()) == 1

    def test_scan_carry_inside_jit(self):
        """The property the host-driven VectorCache cannot offer: the
        cache state rides a lax.scan carry with zero host syncs."""
        from raft_tpu.util import (device_cache_init, device_cache_insert,
                                   device_cache_lookup)

        st = device_cache_init(n_vec=2, capacity=16, associativity=4)

        @jax.jit
        def run(st, keys):
            def step(carry, k):
                kb = k[None]
                out, hit, carry = device_cache_lookup(carry, kb)
                vec = jnp.where(hit[0], out[0],
                                jnp.stack([k, k]).astype(jnp.float32))
                carry = device_cache_insert(carry, kb, vec[None])
                return carry, hit[0]
            return jax.lax.scan(step, st, keys)

        keys = jnp.asarray([1, 2, 1, 3, 2, 1], jnp.int32)
        st, hits = run(st, keys)
        np.testing.assert_array_equal(
            np.asarray(hits), [False, False, True, False, True, True])

    def test_negative_keys_are_inert(self):
        """-1 is the empty-slot sentinel: lookups of negative keys always
        miss (a fresh cache must not 'hit' its own empty markers) and
        inserts of them are dropped."""
        from raft_tpu.util import (device_cache_init, device_cache_insert,
                                   device_cache_lookup)

        st = device_cache_init(n_vec=2, capacity=4, associativity=2)
        out, hit, st = device_cache_lookup(st, jnp.asarray([-1, -5]))
        assert not bool(hit[0]) and not bool(hit[1])
        st = device_cache_insert(st, jnp.asarray([-1]),
                                 jnp.asarray([[9.0, 9.0]]))
        assert int((np.asarray(st.keys) >= 0).sum()) == 0  # still empty

    def test_capacity_rounds_up(self):
        from raft_tpu.util import device_cache_init

        st = device_cache_init(n_vec=1, capacity=48, associativity=32)
        assert st.keys.size >= 48


class TestTpuArch:
    """Generation dispatch (ref: util/arch.cuh SM_compute_arch/SM_range)."""

    def test_parse_kinds(self):
        from raft_tpu.util import TpuArch

        a = TpuArch("TPU v5 lite")
        assert a.gen == 5 and a.lite
        b = TpuArch("TPU v4")
        assert b.gen == 4 and not b.lite
        c = TpuArch("TPU v5p")
        assert c.gen == 5 and not c.lite
        d = TpuArch("cpu")
        assert d.gen == 0
        assert TpuArch("TPU v6e") .lite

    def test_range_gate(self):
        from raft_tpu.util import ArchRange, TpuArch

        r = ArchRange(min_gen=5)
        assert r.contains(TpuArch("TPU v5 lite"))
        assert not r.contains(TpuArch("TPU v4"))
        assert r.contains(TpuArch("cpu"))            # unknown passes
        assert not ArchRange(min_gen=5, allow_unknown=False).contains(
            TpuArch("cpu"))
        assert not ArchRange(min_gen=4, max_gen=4).contains(
            TpuArch("TPU v5p"))

    def test_capabilities(self):
        from raft_tpu.util import (TpuArch, mxu_dim, runtime_arch,
                                   vmem_bytes, vreg_shape)

        assert vmem_bytes(TpuArch("TPU v5 lite")) == 128 * 1024 * 1024
        assert mxu_dim() == 128
        assert vreg_shape() == (8, 128)
        ra = runtime_arch()
        assert isinstance(ra, TpuArch)
        assert ra.gen == 0          # this suite pins the CPU backend
        assert TpuArch("TPU7x").gen == 7
