"""Durable streaming fleet tests (ISSUE 18): replicated WAL shipping,
checkpointed mid-stream replica restart, scrub + read-repair.

Acceptance claims gated here:

- a follower converges to the leader's ``content_crc`` bit-for-bit
  through every path: live record shipping, snapshot resync (blank
  bootstrap AND pruned-WAL gap), a forced refit's KIND_CENTROIDS
  record, and a mirror-journal restart;
- gaps are a typed :class:`WalGapError` and drain() auto-heals them
  with a catch-up round; duplicates are idempotent;
- catch-up under live query load never drops below the recall floor
  (:func:`~raft_tpu.serve.loadgen.catchup_under_load`);
- the scrubber detects seeded bit-flips (``corrupt_bytes``),
  quarantines the damaged container, and repairs up the ladder —
  unrepairable damage raises the typed :class:`ShardCorruptError`;
  the memory sidecar catches RAM damage (same version, changed bytes);
- the two-process SIGKILL witness (tests/_durability_worker.py): a
  follower killed mid-stream restarts from its mirrored journal and
  converges, CRC-equal to a clean never-killed twin;
- ``kmeans_partial_fit`` checkpoint/resume is bit-equal to an
  uninterrupted run; ``ReplicaGroup.spawn`` joins routing at the
  vtime floor with zero post-warm recompiles; the frozen epoch
  fixture (tests/data/streaming_epoch_v1.ckpt) loads forever.
"""

import os
import shutil
import subprocess
import sys
import time

import numpy as np
import pytest

from raft_tpu import obs
from raft_tpu.comms.comms import _Mailbox
from raft_tpu.comms.faults import FaultInjector
from raft_tpu.core import env
from raft_tpu.core.checkpoint import restore_checkpoint
from raft_tpu.neighbors.scrub import Scrubber
from raft_tpu.neighbors.streaming import (MutationLog, ShardCorruptError,
                                          StreamingError, StreamingIndex,
                                          WalGapError, _epoch_entries,
                                          stream_build)
from raft_tpu.neighbors.wal_ship import (TAG_WAL, CatchupReport,
                                         WalFollower, WalShipper,
                                         bootstrap_follower)
from raft_tpu.obs import metrics as obs_metrics
from raft_tpu.serve.loadgen import catchup_under_load

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_FIXTURE = os.path.join(_REPO, "tests", "data",
                        "streaming_epoch_v1.ckpt")

N, D, L = 160, 8, 8


def _leader(tmp_path, n=N, seed=3):
    rng = np.random.default_rng(seed)
    db = rng.normal(size=(n, D)).astype(np.float32)
    idx = stream_build(None, db, L, seed=0, max_iter=4,
                       directory=str(tmp_path / "leader"))
    return idx, rng


def _rows(rng, m=12):
    return rng.normal(size=(m, D)).astype(np.float32)


@pytest.fixture
def live_obs():
    """Metrics on with a private registry (the test_obs pattern)."""
    was_enabled = obs.enabled()
    old_reg = obs_metrics.set_registry(obs.MetricsRegistry())
    obs.set_enabled(True)
    try:
        yield obs_metrics.get_registry()
    finally:
        obs.set_enabled(was_enabled)
        obs_metrics.set_registry(old_reg)


def _counter(reg, name):
    fam = reg.snapshot().get(name)
    if not fam:
        return 0.0
    return sum(s["value"] for s in fam["series"])


# ---------------------------------------------------------------------------
# WAL shipping (tentpole part 2)
# ---------------------------------------------------------------------------


class TestWalShipping:
    def _pair(self, tmp_path, *, follower_dir=True, serve=True):
        leader, rng = _leader(tmp_path)
        mbx = _Mailbox()
        shipper = WalShipper(leader, mbx, 0, [1],
                             poll_interval=0.01).attach()
        if serve:
            shipper.start()                     # answers catch-up reqs
        fdir = str(tmp_path / "follower") if follower_dir else None
        fidx = bootstrap_follower(None, dim=D, n_lists=L,
                                  directory=fdir)
        wf = WalFollower(fidx, mbx, 1, 0)
        return leader, rng, mbx, shipper, fidx, wf

    @staticmethod
    def _down(shipper):
        if shipper._thread is not None:
            shipper.stop()
        shipper.detach()

    def test_live_shipping_converges_bit_equal(self, tmp_path):
        leader, rng, mbx, shipper, fidx, wf = self._pair(tmp_path)
        rpt = wf.catch_up(timeout=30.0)         # blank cursor → snapshot
        assert rpt.snapshot and wf.resyncs == 1
        assert fidx.content_crc() == leader.content_crc()
        ids = leader.insert(_rows(rng))
        leader.delete(ids[::3])
        assert wf.drain() == 2
        assert fidx.content_crc() == leader.content_crc()
        assert wf.applied_seq == leader._applied_seq
        self._down(shipper)

    def test_refit_ships_centroids(self, tmp_path):
        leader, rng, mbx, shipper, fidx, wf = self._pair(tmp_path)
        wf.catch_up(timeout=30.0)
        leader.insert(_rows(rng, 24))
        assert leader.maybe_refit(force=True)   # KIND_CENTROIDS record
        wf.drain()
        # content_crc covers centroids: equality proves the refit's
        # quantizer change crossed the wire
        assert fidx.content_crc() == leader.content_crc()
        self._down(shipper)

    def test_gap_is_typed_and_drain_heals_it(self, tmp_path):
        leader, rng, mbx, shipper, fidx, wf = self._pair(tmp_path)
        wf.catch_up(timeout=30.0)
        leader.insert(_rows(rng))
        assert wf.drain() == 1
        leader.insert(_rows(rng))               # shipped...
        assert mbx.get_nowait(0, 1, TAG_WAL) is not None  # ...stolen
        leader.insert(_rows(rng))
        # resync=False surfaces the typed error with the cursor facts
        with pytest.raises(WalGapError) as ei:
            wf.drain(resync=False)
        assert ei.value.expected == wf.applied_seq + 1
        assert ei.value.got == ei.value.expected + 1
        # steady-state drain turns the same gap into a catch-up round
        leader.insert(_rows(rng))
        wf.drain()
        assert fidx.content_crc() == leader.content_crc()
        assert wf.applied_seq == leader._applied_seq
        self._down(shipper)

    def test_pruned_wal_gap_resyncs_via_snapshot(self, tmp_path):
        leader, rng, mbx, shipper, fidx, wf = self._pair(tmp_path)
        wf.catch_up(timeout=30.0)
        resyncs0 = wf.resyncs
        # records shipped while the follower sleeps, then folded into
        # an epoch and pruned — catch-up MUST fall back to a snapshot
        while mbx.get_nowait(0, 1, TAG_WAL) is not None:
            pass
        ids = leader.insert(_rows(rng))
        leader.delete(ids[:4])
        while mbx.get_nowait(0, 1, TAG_WAL) is not None:
            pass
        leader.compact(reason="prune")          # WAL pruned to horizon
        rpt = wf.catch_up(timeout=30.0)
        assert rpt.snapshot and wf.resyncs == resyncs0 + 1
        assert fidx.content_crc() == leader.content_crc()
        self._down(shipper)

    def test_duplicates_are_idempotent(self, tmp_path):
        leader, rng, mbx, shipper, fidx, wf = self._pair(tmp_path)
        wf.catch_up(timeout=30.0)
        leader.insert(_rows(rng))
        payload = mbx.get_nowait(0, 1, TAG_WAL)
        mbx.put(0, 1, TAG_WAL, payload)         # deliver once...
        mbx.put(0, 1, TAG_WAL, payload)         # ...and once again
        assert wf.drain() == 1
        assert wf.dups == 1
        assert fidx.content_crc() == leader.content_crc()
        self._down(shipper)

    def test_mirror_restart_resumes_cursor(self, tmp_path):
        leader, rng, mbx, shipper, fidx, wf = self._pair(tmp_path)
        wf.catch_up(timeout=30.0)
        ids = leader.insert(_rows(rng))
        leader.delete(ids[::2])
        wf.drain()
        cursor = wf.applied_seq
        crc = fidx.content_crc()
        # "SIGKILL": drop the in-memory follower, recover from its
        # mirrored journal — state AND cursor survive
        del fidx, wf
        fidx2 = StreamingIndex.recover(None, str(tmp_path / "follower"))
        assert fidx2._applied_seq == cursor
        assert fidx2.content_crc() == crc
        wf2 = WalFollower(fidx2, mbx, 1, 0)
        leader.insert(_rows(rng))               # stream continues
        wf2.drain()
        assert fidx2.content_crc() == leader.content_crc()
        self._down(shipper)

    def test_catchup_under_load_holds_recall_floor(self, tmp_path,
                                                   live_obs):
        leader, rng, mbx, shipper, fidx, wf = self._pair(
            tmp_path, follower_dir=False)
        for _ in range(4):
            ids = leader.insert(_rows(rng))
            leader.delete(ids[::4])
        rep = catchup_under_load(wf, k=5, nprobe=L,
                                 target_seq=leader._applied_seq,
                                 rows=4, seed=1)
        self._down(shipper)
        assert rep.applied_seq >= rep.target_seq
        assert rep.queries >= 1
        assert rep.min_recall >= 0.99, rep.as_dict()
        assert rep.resyncs == 1                 # blank cursor
        assert fidx.content_crc() == leader.content_crc()
        assert _counter(live_obs, "replica_catchups_total") >= 1
        snap = live_obs.snapshot().get("replica_catchup_seconds")
        assert snap and snap["series"][0]["count"] >= 1

    def test_shipper_validation(self, tmp_path, res):
        rng = np.random.default_rng(0)
        db = rng.normal(size=(96, D)).astype(np.float32)
        bare = stream_build(None, db, 4, seed=0, max_iter=4)
        mbx = _Mailbox()
        with pytest.raises(StreamingError, match="journaled"):
            WalShipper(bare, mbx, 0, [1])
        leader, _ = _leader(tmp_path)
        with pytest.raises(ValueError, match="follow itself"):
            WalShipper(leader, mbx, 0, [0, 1])
        with pytest.raises(ValueError, match="follow itself"):
            WalFollower(leader, mbx, 2, 2)
        s = WalShipper(leader, mbx, 0, [1]).attach()
        with pytest.raises(StreamingError, match="on_append"):
            WalShipper(leader, mbx, 0, [1]).attach()
        s.detach()


# ---------------------------------------------------------------------------
# scrub + read-repair (tentpole part 3)
# ---------------------------------------------------------------------------


class TestScrub:
    def test_clean_pass_counts_files(self, tmp_path, live_obs):
        leader, rng = _leader(tmp_path)
        leader.insert(_rows(rng))
        sc = Scrubber(leader, interval=10.0)
        rep = sc.run_once()
        assert rep.files_checked >= 2           # epoch(s) + WAL record
        assert not rep.corrupt and not rep.quarantined
        assert _counter(live_obs, "scrub_passes_total") == 1

    def test_corrupt_epoch_quarantined_and_repaired(self, tmp_path,
                                                    live_obs):
        leader, rng = _leader(tmp_path)
        leader.insert(_rows(rng))
        crc = leader.content_crc()
        faults = FaultInjector()
        newest = leader.log.epoch_path(max(leader.log.epoch_steps()))
        faults.corrupt_bytes(newest)
        sc = Scrubber(leader, interval=10.0)
        rep = sc.run_once()
        name = os.path.basename(newest)
        assert rep.corrupt == [name]
        assert rep.quarantined == [name]
        assert rep.repaired == [name]           # rewritten from memory
        assert os.path.exists(newest + ".quarantined")
        # redundancy restored: the next pass is clean AND a cold
        # recover reproduces the live content exactly
        rep2 = sc.run_once()
        assert not rep2.corrupt
        recovered = StreamingIndex.recover(None, leader.log.directory)
        assert recovered.content_crc() == crc
        fam = live_obs.snapshot()["scrub_corruptions_total"]
        outcomes = {s["labels"]["outcome"]: s["value"]
                    for s in fam["series"]}
        assert outcomes == {"repaired": 1.0}

    def test_corrupt_wal_superseded_by_epoch_rewrite(self, tmp_path):
        leader, rng = _leader(tmp_path)
        ids = leader.insert(_rows(rng))
        leader.delete(ids[:3])                  # in-place → WAL record
        crc = leader.content_crc()
        wal = [os.path.join(leader.log.directory, f)
               for f in sorted(os.listdir(leader.log.directory))
               if f.startswith("wal-")]
        assert wal
        FaultInjector().corrupt_bytes(wal[-1])
        rep = Scrubber(leader, interval=10.0).run_once()
        assert rep.repaired
        recovered = StreamingIndex.recover(None, leader.log.directory)
        assert recovered.content_crc() == crc

    def test_cold_directory_repairs_from_source(self, tmp_path):
        leader, rng = _leader(tmp_path)
        leader.insert(_rows(rng))
        crc = leader.content_crc()
        # clone the journal to a "dead replica" directory, damage every
        # epoch, and repair from the healthy peer's entries
        cold = str(tmp_path / "cold")
        shutil.copytree(leader.log.directory, cold)
        log = MutationLog(cold)
        faults = FaultInjector()
        for step in log.epoch_steps():
            faults.corrupt_bytes(log.epoch_path(step))
        for f in sorted(os.listdir(cold)):      # and the WAL suffix
            if f.startswith("wal-"):
                faults.corrupt_bytes(os.path.join(cold, f))
        sc = Scrubber(log=log,
                      repair_source=lambda: _epoch_entries(leader),
                      interval=10.0)
        rep = sc.run_once()
        assert rep.quarantined and rep.repaired
        recovered = StreamingIndex.recover(None, cold)
        assert recovered.content_crc() == crc

    def test_cold_directory_unrepairable_raises_typed(self, tmp_path):
        leader, rng = _leader(tmp_path)
        log = MutationLog(str(tmp_path / "dead"))
        entries = _epoch_entries(leader)
        log.write_epoch(0, entries)
        FaultInjector().corrupt_bytes(log.epoch_path(0))
        sc = Scrubber(log=log, interval=10.0)
        with pytest.raises(ShardCorruptError) as ei:
            sc.run_once()
        assert "epoch-00000000" in ei.value.shard
        assert os.path.exists(log.epoch_path(0) + ".quarantined")

    def test_memory_sidecar_detects_ram_damage(self, tmp_path):
        leader, rng = _leader(tmp_path)
        sc = Scrubber(leader, interval=10.0)
        sc.run_once()                           # baseline sidecar
        # flip a tombstone bit behind the index's back: same snapshot
        # version, different bytes — the RAM-damage signature
        leader._tomb_host[0] ^= np.uint32(1)
        with pytest.raises(ShardCorruptError, match="memory"):
            sc.run_once()

    def test_memory_sidecar_repairs_from_source(self, tmp_path,
                                                live_obs):
        leader, rng = _leader(tmp_path)
        healthy = _epoch_entries(leader)
        crc = leader.content_crc()
        sc = Scrubber(leader, repair_source=lambda: dict(healthy),
                      interval=10.0)
        sc.run_once()
        leader._tomb_host[0] ^= np.uint32(1)
        rep = sc.run_once()
        assert rep.memory_repaired
        assert leader.content_crc() == crc
        assert _counter(live_obs, "scrub_memory_repairs_total") == 1

    def test_background_thread_scrubs_on_interval(self, tmp_path):
        leader, rng = _leader(tmp_path)
        sc = Scrubber(leader, interval=0.02)
        with sc:
            deadline = time.monotonic() + 5.0
            while sc.passes < 2 and time.monotonic() < deadline:
                time.sleep(0.01)
        assert sc.passes >= 2

    def test_validation(self, tmp_path):
        leader, rng = _leader(tmp_path)
        with pytest.raises(ValueError, match="journal"):
            Scrubber()
        with pytest.raises(ValueError, match="not both"):
            Scrubber(leader, log=MutationLog(str(tmp_path / "other")))
        with pytest.raises(ValueError, match="interval"):
            Scrubber(leader, interval=0.0)


# ---------------------------------------------------------------------------
# env knobs (satellite: fail-loud configuration)
# ---------------------------------------------------------------------------


class TestEnvKnobs:
    @pytest.mark.parametrize("name,bad,good,parsed", [
        ("RAFT_TPU_WAL_RETAIN", "0", "3", 3),
        ("RAFT_TPU_WAL_RETAIN", "two", "2", 2),
        ("RAFT_TPU_SCRUB_INTERVAL", "-1", "0.5", 0.5),
        ("RAFT_TPU_SCRUB_INTERVAL", "fast", "2.0", 2.0),
    ])
    def test_registered_fail_loud(self, monkeypatch, name, bad, good,
                                  parsed):
        monkeypatch.setenv(name, bad)
        with pytest.raises(ValueError, match=name):
            env.read(name)
        monkeypatch.setenv(name, good)
        assert env.read(name) == parsed

    def test_malformed_knob_fails_in_subprocess(self, tmp_path):
        """The knob is read at MutationLog construction — a malformed
        value must kill the process loudly, not default silently."""
        code = ("from raft_tpu.neighbors.streaming import MutationLog\n"
                f"MutationLog({str(tmp_path / 'j')!r})\n")
        env2 = dict(os.environ)
        env2["RAFT_TPU_WAL_RETAIN"] = "-2"
        env2["JAX_PLATFORMS"] = "cpu"
        env2["PYTHONPATH"] = _REPO + os.pathsep + env2.get(
            "PYTHONPATH", "")
        p = subprocess.run([sys.executable, "-c", code], env=env2,
                           capture_output=True, text=True, timeout=120)
        assert p.returncode != 0
        assert "RAFT_TPU_WAL_RETAIN" in p.stderr

    def test_retain_knob_drives_wal_pruning(self, tmp_path,
                                            monkeypatch):
        monkeypatch.setenv("RAFT_TPU_WAL_RETAIN", "4")
        log = MutationLog(str(tmp_path / "j"))
        assert log.retain == 4
        assert MutationLog(str(tmp_path / "k"), retain=1).retain == 1

    def test_scrub_interval_knob(self, tmp_path, monkeypatch):
        leader, rng = _leader(tmp_path)
        monkeypatch.setenv("RAFT_TPU_SCRUB_INTERVAL", "7.5")
        assert Scrubber(leader).interval == 7.5


# ---------------------------------------------------------------------------
# kmeans_partial_fit checkpointing (satellite: PR-8 boundary pattern)
# ---------------------------------------------------------------------------


class TestPartialFitCheckpoint:
    def test_resume_is_bit_equal_to_uninterrupted(self, res, tmp_path):
        from raft_tpu.cluster.kmeans import kmeans_partial_fit

        rng = np.random.default_rng(5)
        c0 = rng.normal(size=(4, 6)).astype(np.float32)
        batch = rng.normal(size=(64, 6)).astype(np.float32)
        ref_c, ref_n = kmeans_partial_fit(res, c0, batch, chunk_rows=8)

        ck = str(tmp_path / "pf")
        kmeans_partial_fit(res, c0, batch, chunk_rows=8,
                           checkpoint_dir=ck, checkpoint_every=1)
        saved = sorted(f for f in os.listdir(ck)
                       if f.startswith("kmeans_pf-"))
        assert saved, "boundary hook never saved"
        # resume from a MID-batch checkpoint (not the final one): the
        # remaining chunks replay to the exact uninterrupted result
        mid = os.path.join(ck, saved[0])
        chunk = int(restore_checkpoint(mid)["chunk"])
        assert 0 < chunk < 8
        out_c, out_n = kmeans_partial_fit(res, c0, batch, chunk_rows=8,
                                          resume_from=mid)
        np.testing.assert_array_equal(np.asarray(out_c),
                                      np.asarray(ref_c))
        np.testing.assert_array_equal(np.asarray(out_n),
                                      np.asarray(ref_n))

    def test_resume_beyond_batch_raises(self, res, tmp_path):
        from raft_tpu.cluster.kmeans import kmeans_partial_fit

        rng = np.random.default_rng(5)
        c0 = rng.normal(size=(4, 6)).astype(np.float32)
        batch = rng.normal(size=(64, 6)).astype(np.float32)
        ck = str(tmp_path / "pf")
        kmeans_partial_fit(res, c0, batch, chunk_rows=8,
                           checkpoint_dir=ck, checkpoint_every=1)
        newest = sorted(f for f in os.listdir(ck)
                        if f.startswith("kmeans_pf-"))[-1]
        short = rng.normal(size=(8, 6)).astype(np.float32)
        with pytest.raises(ValueError, match="SAME batch"):
            kmeans_partial_fit(res, c0, short, chunk_rows=8,
                               resume_from=os.path.join(ck, newest))

    def test_checkpoint_every_requires_dir(self, res):
        from raft_tpu.cluster.kmeans import kmeans_partial_fit

        rng = np.random.default_rng(5)
        with pytest.raises(ValueError, match="checkpoint_dir"):
            kmeans_partial_fit(res,
                               rng.normal(size=(4, 6)).astype("float32"),
                               rng.normal(size=(16, 6)).astype("float32"),
                               checkpoint_every=1)


# ---------------------------------------------------------------------------
# frozen on-disk format (satellite: compat fixture)
# ---------------------------------------------------------------------------


class TestFrozenEpochFixture:
    # cut by this PR from a deterministic build+insert+delete+compact;
    # the constants are the fixture's frozen identity — readers must
    # load these exact bytes forever (format changes bump the version)
    CRC = 1456153610
    N_LIVE = 108
    HORIZON = 1

    def test_fixture_recovers_forever(self, tmp_path):
        shutil.copyfile(_FIXTURE,
                        str(tmp_path / "epoch-00000000.ckpt"))
        idx = StreamingIndex.recover(None, str(tmp_path))
        assert idx.content_crc() == self.CRC
        assert idx.n_live == self.N_LIVE
        assert idx._applied_seq == self.HORIZON

    def test_fixture_entries_schema(self):
        ent = restore_checkpoint(_FIXTURE)
        for key in ("epoch", "next_id", "n_live", "n_db", "metric",
                    "centroids", "packed_db", "packed_ids", "starts",
                    "sizes", "caps", "tomb_words", "wal_horizon"):
            assert key in ent, key


# ---------------------------------------------------------------------------
# fleet rejoin (satellite: ReplicaGroup.spawn)
# ---------------------------------------------------------------------------


class TestReplicaSpawn:
    def _fleet(self, res, n=2):
        from raft_tpu.neighbors import ivf_flat
        from raft_tpu.neighbors.ivf_mnmg import build_mnmg
        from raft_tpu.serve import (BatchPolicy, Executor,
                                    IvfMnmgKnnService, ReplicaGroup)

        rng = np.random.default_rng(2)
        X = rng.standard_normal((256, 12)).astype(np.float32)
        flat = ivf_flat.build(res, X, 8, seed=0, max_iter=4)
        idx = build_mnmg(res, X, 8, 2, flat=flat)

        def make_ex():
            ex = Executor([IvfMnmgKnnService(idx, k=4, nprobe=3)],
                          policy=BatchPolicy(max_batch=32,
                                             max_wait_ms=1.0))
            ex.warm([8])
            return ex

        op = f"ivf_mnmg_k4_np3_r{idx.n_ranks}_{idx.metric}"
        return X, ReplicaGroup([make_ex() for _ in range(n)]), make_ex, op

    def test_spawn_joins_at_vtime_floor(self, res):
        X, group, make_ex, op = self._fleet(res)
        with group:
            for _ in range(10):
                group.route(op, X[:8])[1].result(timeout=60.0)
            floor = min(r.vtime for r in group.replicas)
            assert floor > 0.0
            rep = group.spawn("joiner", make_ex())
            assert rep.vtime == 0.0 and rep.healthy
            # the joiner is the fair-queue minimum, so it serves next —
            # and its clock snaps to the fleet floor, never a flood
            served, fut = group.route(op, X[:8])
            fut.result(timeout=60.0)
            assert served.name == "joiner"
            assert served.vtime >= floor
        assert len(group.replicas) == 3

    def test_spawn_zero_post_warm_recompiles(self, res):
        X, group, make_ex, op = self._fleet(res)
        with group:
            group.route(op, X[:8])[1].result(timeout=60.0)
            ex = make_ex()                      # warmed BEFORE routable
            group.spawn("joiner", ex, warm=False)  # already warm
            traces0 = ex.stats.traces
            misses0 = ex.stats.exec_misses
            for _ in range(6):
                group.route(op, X[:8])[1].result(timeout=60.0)
            assert ex.stats.requests > 0        # the joiner did serve
            assert ex.stats.traces == traces0
            assert ex.stats.exec_misses == misses0

    def test_spawn_validation(self, res):
        X, group, make_ex, op = self._fleet(res)
        with pytest.raises(ValueError, match="weight"):
            group.spawn("w", make_ex(), weight=0.0)
        with pytest.raises(ValueError, match="rejoin"):
            group.spawn("replica0", make_ex())


# ---------------------------------------------------------------------------
# the two-process SIGKILL witness (slow tier — smoke.sh runs it too)
# ---------------------------------------------------------------------------


class TestDurabilityChaos:
    @pytest.mark.slow
    def test_sigkill_restart_catchup_bit_equal(self):
        """Follower SIGKILL'd mid-stream, restarted from its mirrored
        journal, catches up under query load: CRC equal to the leader
        AND a clean never-killed twin; recall floor held throughout."""
        worker = os.path.join(_REPO, "tests", "_durability_worker.py")
        env2 = dict(os.environ)
        env2["JAX_PLATFORMS"] = "cpu"
        env2["PYTHONPATH"] = _REPO + os.pathsep + env2.get(
            "PYTHONPATH", "")
        p = subprocess.run([sys.executable, worker, "orchestrate"],
                           cwd=_REPO, env=env2, capture_output=True,
                           text=True, timeout=480)
        assert p.returncode == 0, p.stdout + p.stderr
        assert "DURABILITY_CHAOS_OK" in p.stdout, p.stdout
