"""Runtime instantiation layer tests (ref model: the runtime APIs are what
pylibraft links against — cpp/include/raft_runtime/, SURVEY.md §2.11; the
AOT tier is the explicit-instantiation discipline's analogue)."""

import os

import numpy as np
import pytest
import scipy.sparse as sp
import scipy.sparse.linalg as spla

from raft_tpu.runtime import (aot_export, deserialize_computation,
                              load_computation, save_computation,
                              serialize_computation)
from raft_tpu.runtime.random_gen import rmat_rectangular_gen
from raft_tpu.runtime.solver import lanczos_solver
from raft_tpu.sparse.solver.lanczos import LanczosConfig


class TestAotExport:
    def test_roundtrip_bytes(self):
        import jax.numpy as jnp

        def f(x, y):
            return (x @ y).sum(axis=1)

        a = np.arange(32, dtype=np.float32).reshape(8, 4)
        b = np.ones((4, 8), np.float32)
        exp = aot_export(f, a, b)
        blob = serialize_computation(exp)
        assert isinstance(blob, bytes) and len(blob) > 0
        call = deserialize_computation(blob)
        np.testing.assert_allclose(np.asarray(call(a, b)), (a @ b).sum(1))

    def test_roundtrip_file(self, tmp_path):
        def f(x):
            return x * 2.0 + 1.0

        x = np.linspace(0, 1, 7, dtype=np.float32)
        p = str(tmp_path / "double_plus_one.stablehlo")
        save_computation(aot_export(f, x), p)
        call = load_computation(p)
        np.testing.assert_allclose(np.asarray(call(x)), x * 2 + 1)

    def test_sha256_sidecar_written(self, tmp_path):
        import hashlib

        def f(x):
            return x - 3.0

        x = np.ones((5,), np.float32)
        p = str(tmp_path / "artifact.stablehlo")
        save_computation(aot_export(f, x), p)
        sidecar = p + ".sha256"
        assert os.path.exists(sidecar)
        with open(p, "rb") as fh:
            blob = fh.read()
        with open(sidecar) as fh:
            assert fh.read().strip() == hashlib.sha256(blob).hexdigest()

    def test_bit_flip_raises_typed_corrupt_error(self, tmp_path):
        from raft_tpu.core.guards import ArtifactCorruptError

        def f(x):
            return x * x

        x = np.ones((3,), np.float32)
        p = str(tmp_path / "artifact.stablehlo")
        save_computation(aot_export(f, x), p)
        with open(p, "rb") as fh:
            blob = bytearray(fh.read())
        blob[len(blob) // 2] ^= 0xFF          # flip one byte mid-artifact
        with open(p, "wb") as fh:
            fh.write(blob)
        with pytest.raises(ArtifactCorruptError) as ei:
            load_computation(p)
        assert ei.value.path == p
        assert p in str(ei.value)

    def test_truncation_raises_typed_corrupt_error(self, tmp_path):
        from raft_tpu.core.guards import ArtifactCorruptError

        def f(x):
            return x + 7.0

        x = np.ones((3,), np.float32)
        p = str(tmp_path / "artifact.stablehlo")
        save_computation(aot_export(f, x), p)
        with open(p, "rb") as fh:
            blob = fh.read()
        with open(p, "wb") as fh:
            fh.write(blob[: len(blob) // 2])  # torn write / partial copy
        with pytest.raises(ArtifactCorruptError):
            load_computation(p)

    def test_truncation_without_sidecar_still_typed(self, tmp_path):
        """Pre-guardrails artifacts have no sidecar: the deserialize
        failure itself must still surface as ArtifactCorruptError."""
        from raft_tpu.core.guards import ArtifactCorruptError

        def f(x):
            return x + 7.0

        x = np.ones((3,), np.float32)
        p = str(tmp_path / "artifact.stablehlo")
        save_computation(aot_export(f, x), p)
        os.remove(p + ".sha256")
        with open(p, "rb") as fh:
            blob = fh.read()
        with open(p, "wb") as fh:
            fh.write(blob[: len(blob) // 3])
        with pytest.raises(ArtifactCorruptError) as ei:
            load_computation(p)
        assert ei.value.path == p

    def test_shape_signature_enforced(self):
        def f(x):
            return x + 1

        call = deserialize_computation(serialize_computation(
            aot_export(f, np.zeros((4,), np.float32))))
        with pytest.raises(Exception):
            call(np.zeros((5,), np.float32))    # wrong shape must reject

    def test_flagship_lloyd_step_exports(self):
        """The driver's flagship step survives AOT roundtrip."""
        import functools

        from raft_tpu.cluster.kmeans import lloyd_step

        rng = np.random.default_rng(0)
        x = rng.normal(size=(256, 16)).astype(np.float32)
        c = rng.normal(size=(8, 16)).astype(np.float32)
        fn = functools.partial(lloyd_step, n_clusters=8)
        ref = [np.asarray(o) for o in fn(x, c)]
        call = deserialize_computation(serialize_computation(
            aot_export(fn, x, c)))
        out = [np.asarray(o) for o in call(x, c)]
        for got, want in zip(out, ref):
            np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


class TestRuntimeEntryPoints:
    def test_lanczos_solver_raw_buffers(self, res):
        n = 200
        A = sp.diags([np.full(n, 3.0), np.full(n - 1, -1.0)], [0, 1])
        A = (A + A.T).tocsr().astype(np.float32)
        cfg = LanczosConfig(n_components=3, which="SA", seed=0)
        vals, vecs = lanczos_solver(res, cfg, A.indptr.astype(np.int32),
                                    A.indices.astype(np.int32), A.data)
        ref = spla.eigsh(A.astype(np.float64), k=3, which="SA")[0]
        np.testing.assert_allclose(np.sort(np.asarray(vals)),
                                   np.sort(ref), rtol=1e-3, atol=1e-4)

    def test_lanczos_solver_rejects_foreign_dtypes(self, res):
        cfg = LanczosConfig(n_components=2)
        with pytest.raises(TypeError):
            lanczos_solver(res, cfg, np.zeros(5, np.int16),
                           np.zeros(4, np.int32), np.zeros(4, np.float32))
        with pytest.raises(TypeError):
            lanczos_solver(res, cfg, np.zeros(5, np.int32),
                           np.zeros(4, np.int32), np.zeros(4, np.float16))

    def test_rmat_entry(self, res):
        from raft_tpu.random.rng_state import RngState

        src, dst = rmat_rectangular_gen(res, RngState(5), None, 8, 8,
                                        1000)
        src, dst = np.asarray(src), np.asarray(dst)
        assert src.shape == dst.shape == (1000,)
        assert src.max() < 256 and dst.max() < 256
        with pytest.raises(TypeError):
            rmat_rectangular_gen(res, RngState(5), None, 8, 8, 10,
                                 out_dtype=np.int8)


def test_lloyd_packed_spelling_exports(tmp_path):
    """The depth-packed kernel spelling must survive the AOT path too:
    export → serialize → reload → run gives the 3-dot spelling's numbers
    (the artifact story must not constrain kernel-variant choices)."""
    import functools

    import jax.numpy as jnp

    from raft_tpu import set_matmul_precision, get_matmul_precision
    from raft_tpu.linalg.contractions import fused_lloyd_pallas

    old = get_matmul_precision()
    try:
        set_matmul_precision("high")
        rng = np.random.default_rng(13)
        x = jnp.asarray(rng.normal(size=(128, 32)).astype(np.float32))
        c = jnp.asarray(rng.normal(size=(16, 32)).astype(np.float32))
        exp = aot_export(functools.partial(fused_lloyd_pallas, packed=True),
                         x, c)
        fn = deserialize_computation(serialize_computation(exp))
        got = fn(x, c)
        want = fused_lloyd_pallas(x, c, packed=False)
        for a, b in zip(got, want):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-5)
    finally:
        set_matmul_precision(old)


def test_radix_select_exports(tmp_path):
    """The radix-select kernels (grid-axis digit-histogram threshold +
    batched-dot emission + scratch carry) survive the AOT
    serialize/reload boundary with
    identical results — the runtime layer's contract for every shipped
    kernel family."""
    import numpy as np

    from raft_tpu.matrix.radix_select import radix_select_k

    rng = np.random.default_rng(0)
    v = rng.normal(size=(12, 2000)).astype(np.float32)
    ref_v, ref_i = radix_select_k(v, 25)
    exp = aot_export(lambda a: radix_select_k(a, 25), v)
    p = str(tmp_path / "radix_select.stablehlo")
    save_computation(exp, p)
    got_v, got_i = load_computation(p)(v)
    np.testing.assert_array_equal(np.asarray(got_i), np.asarray(ref_i))
    np.testing.assert_array_equal(np.asarray(got_v), np.asarray(ref_v))
