"""Serving runtime tests (ISSUE 6): shape buckets, coalesced-batch
bit-identity, batching-policy timing, weighted fairness, typed QoS
failures, the zero-recompile-after-warmup contract, and the bench
provenance (era / superseded_by) satellite."""

import importlib.util
import json
import pathlib
import threading
import time

import numpy as np
import pytest

from raft_tpu import obs, serve
from raft_tpu.obs import metrics as obs_metrics
from raft_tpu.runtime import limits

DIM = 16


@pytest.fixture
def live_obs():
    """Metrics on with a fresh private registry; restored afterwards."""
    was_enabled = obs.enabled()
    old_reg = obs_metrics.set_registry(obs.MetricsRegistry())
    old_sink = obs.set_sink(None)
    obs.set_enabled(True)
    try:
        yield obs_metrics.get_registry()
    finally:
        obs.set_enabled(was_enabled)
        obs_metrics.set_registry(old_reg)
        obs.set_sink(old_sink)


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(7)
    return {
        "db": rng.standard_normal((128, DIM)).astype(np.float32),
        "centroids": rng.standard_normal((6, DIM)).astype(np.float32),
        "rng": rng,
    }


def _queries(rng, rows):
    return rng.standard_normal((rows, DIM)).astype(np.float32)


def _counter_value(reg, name, **labels):
    fam = reg.snapshot().get(name)
    if not fam:
        return 0.0
    total = 0.0
    for s in fam["series"]:
        if all(s["labels"].get(k) == v for k, v in labels.items()):
            total += s["value"]
    return total


class TestBuckets:
    def test_ladder_values(self):
        got = [serve.bucket_rows(n) for n in (1, 8, 9, 12, 13, 17, 25, 100)]
        assert got == [8, 8, 12, 12, 16, 24, 32, 128]

    def test_idempotent_and_monotone(self):
        prev = 0
        for n in range(1, 300):
            b = serve.bucket_rows(n)
            assert b >= n
            assert b >= prev - 0  # monotone in n
            assert serve.bucket_rows(b) == b
            prev = b

    def test_pad_waste_bounded(self):
        # the x1.5 / x1.33 ladder bounds pad waste at 50% of rows
        for n in range(1, 2000):
            assert serve.bucket_rows(n) <= max(8, int(np.ceil(n * 1.5)))

    def test_ladder_covers_max(self):
        ladder = serve.bucket_ladder(200)
        assert ladder[0] == serve.BUCKET_FLOOR
        assert ladder[-1] >= 200
        assert ladder == sorted(set(ladder))
        # every bucket_rows() answer for n <= 200 is on the ladder
        assert {serve.bucket_rows(n) for n in range(1, 201)} <= set(ladder)

    def test_nonpositive_rejected(self):
        with pytest.raises(ValueError):
            serve.bucket_rows(0)


class TestBitIdentity:
    """Coalesced+padded serving returns the same bits as one
    unbatched call per request, for every served op."""

    def _run(self, services, ops, data, rows_list):
        rng = np.random.default_rng(3)
        ex = serve.Executor(
            services,
            policy=serve.BatchPolicy(max_batch=64, max_wait_ms=5.0))
        ex.warm()
        with ex:
            subs = []
            for i, rows in enumerate(rows_list):
                q = _queries(rng, rows)
                op = ops[i % len(ops)]
                subs.append((op, q, ex.submit(op, q)))
            outs = [(op, q, f.result(timeout=60)) for op, q, f in subs]
        for op, q, got in outs:
            want = ex.services[op].eager(q)
            got_l = [np.asarray(x) for x in np.atleast_1d(got)] \
                if not isinstance(got, tuple) else [np.asarray(x) for x in got]
            want_l = [np.asarray(x) for x in np.atleast_1d(want)] \
                if not isinstance(want, tuple) else [np.asarray(x) for x in want]
            assert len(got_l) == len(want_l)
            for g, w in zip(got_l, want_l):
                np.testing.assert_array_equal(g, w)

    def test_knn_bit_identical(self, data):
        self._run([serve.KnnService(data["db"], k=4)], ["knn_k4_l2"],
                  data, [1, 3, 5, 8, 2, 7, 11, 4])

    def test_pairwise_bit_identical(self, data):
        self._run([serve.PairwiseService(data["db"])],
                  ["pairwise_l2_expanded"], data, [2, 6, 1, 9, 3])

    def test_kmeans_predict_bit_identical(self, data):
        self._run([serve.KMeansPredictService(data["centroids"])],
                  ["kmeans_predict_k6"], data, [4, 1, 7, 2, 5])

    def test_mixed_ops_route_correctly(self, data):
        self._run([serve.KnnService(data["db"], k=4),
                   serve.PairwiseService(data["db"])],
                  ["knn_k4_l2", "pairwise_l2_expanded"],
                  data, [3, 3, 5, 5, 2, 2])


class TestBatchingPolicy:
    def test_max_wait_flushes_partial_batch(self, data):
        """A lone small request must NOT wait for max_batch — it ships
        once its age reaches max_wait_ms."""
        ex = serve.Executor(
            [serve.KnnService(data["db"], k=4)],
            policy=serve.BatchPolicy(max_batch=10_000, max_wait_ms=60.0))
        ex.warm([8])
        with ex:
            t0 = time.monotonic()
            fut = ex.submit("knn_k4_l2", _queries(np.random.default_rng(0), 3))
            fut.result(timeout=30)
            dt = time.monotonic() - t0
        assert 0.05 <= dt < 10.0
        assert ex.stats.batches == 1

    def test_full_batch_dispatches_before_wait(self, data):
        """Once queued rows reach max_batch the batch goes immediately,
        long before a generous max_wait_ms."""
        ex = serve.Executor(
            [serve.KnnService(data["db"], k=4)],
            policy=serve.BatchPolicy(max_batch=16, max_wait_ms=5_000.0))
        ex.warm([16])
        rng = np.random.default_rng(1)
        with ex:
            t0 = time.monotonic()
            futs = [ex.submit("knn_k4_l2", _queries(rng, 8))
                    for _ in range(2)]
            for f in futs:
                f.result(timeout=30)
            dt = time.monotonic() - t0
        assert dt < 4.0
        assert ex.stats.batches == 1
        assert ex.stats.coalescing_factor() == 16.0


class TestFairness:
    def test_hog_tenant_cannot_starve_light_tenant(self):
        """40 hog requests queued ahead of 4 light ones: weighted-fair
        dequeue interleaves the light tenant instead of serving it
        dead last (FIFO would put it at positions 41-44)."""
        qos = serve.QosPolicy({"hog": serve.TenantPolicy(weight=1.0),
                               "light": serve.TenantPolicy(weight=1.0)})
        q = serve.RequestQueue(
            serve.BatchPolicy(max_batch=16, max_wait_ms=0.0,
                              max_queue=10_000), qos=qos)
        rng = np.random.default_rng(2)
        for _ in range(40):
            q.submit("knn", _queries(rng, 8), tenant="hog")
        for _ in range(4):
            q.submit("knn", _queries(rng, 8), tenant="light")
        order = []
        while q.pending():
            batch = q.next_batch(timeout=1.0)
            assert batch is not None
            order.extend(r.tenant for r in batch.requests)
        assert len(order) == 44
        light_pos = [i for i, t in enumerate(order) if t == "light"]
        assert len(light_pos) == 4
        assert max(light_pos) < 12, (
            f"light tenant starved: served at positions {light_pos}")

    def test_weights_shift_share(self):
        """A weight-3 tenant gets ~3x the rows of a weight-1 tenant in
        any drain prefix while both are backlogged."""
        qos = serve.QosPolicy({"gold": serve.TenantPolicy(weight=3.0),
                               "bronze": serve.TenantPolicy(weight=1.0)})
        q = serve.RequestQueue(
            serve.BatchPolicy(max_batch=8, max_wait_ms=0.0,
                              max_queue=10_000), qos=qos)
        rng = np.random.default_rng(3)
        for _ in range(30):
            q.submit("op", _queries(rng, 8), tenant="gold")
            q.submit("op", _queries(rng, 8), tenant="bronze")
        first = []
        for _ in range(12):            # 12 single-request batches
            first.extend(r.tenant for r in q.next_batch(timeout=1.0).requests)
        gold = first.count("gold")
        assert gold >= 8, f"expected ~3:1 split, got {first}"


class TestQos:
    def test_deadline_expired_in_queue_fast_fails(self, data, live_obs):
        ex = serve.Executor(
            [serve.KnnService(data["db"], k=4)],
            policy=serve.BatchPolicy(max_batch=8, max_wait_ms=0.0))
        ex.warm([8])
        fut = ex.submit("knn_k4_l2",
                        _queries(np.random.default_rng(0), 2),
                        deadline_s=0.005)
        time.sleep(0.05)               # expire while queued
        batch = ex.queue.next_batch(timeout=1.0)
        launches_before = ex.stats.batches
        ex.dispatch(batch)
        with pytest.raises(limits.DeadlineExceededError) as ei:
            fut.result(timeout=1.0)
        assert ei.value.op == "serve.knn_k4_l2"
        assert ex.stats.batches == launches_before, \
            "expired request must not burn a device launch"
        assert _counter_value(live_obs, "limits_deadline_exceeded_total",
                              op="serve.knn_k4_l2") == 1.0

    def test_tenant_default_deadline_applies(self, data):
        qos = serve.QosPolicy(
            {"slo": serve.TenantPolicy(deadline_s=0.004)})
        ex = serve.Executor(
            [serve.KnnService(data["db"], k=4)],
            policy=serve.BatchPolicy(max_batch=8, max_wait_ms=0.0),
            qos=qos)
        ex.warm([8])
        fut = ex.submit("knn_k4_l2",
                        _queries(np.random.default_rng(0), 2),
                        tenant="slo")
        time.sleep(0.05)
        ex.dispatch(ex.queue.next_batch(timeout=1.0))
        with pytest.raises(limits.DeadlineExceededError):
            fut.result(timeout=1.0)

    def test_queue_full_typed_rejection(self, data, live_obs):
        ex = serve.Executor(
            [serve.KnnService(data["db"], k=4)],
            policy=serve.BatchPolicy(max_batch=8, max_wait_ms=1_000.0,
                                     max_queue=2))
        rng = np.random.default_rng(0)
        ex.submit("knn_k4_l2", _queries(rng, 1))
        ex.submit("knn_k4_l2", _queries(rng, 1))
        with pytest.raises(limits.RejectedError) as ei:
            ex.submit("knn_k4_l2", _queries(rng, 1))
        assert ei.value.reason == "queue_full"
        assert ei.value.op == "serve.knn_k4_l2"
        assert _counter_value(live_obs, "limits_rejected_total",
                              reason="queue_full") == 1.0

    def test_tenant_share_cap_rejects(self, data):
        qos = serve.QosPolicy(
            {"capped": serve.TenantPolicy(max_queued=1)})
        ex = serve.Executor(
            [serve.KnnService(data["db"], k=4)],
            policy=serve.BatchPolicy(max_batch=8, max_wait_ms=1_000.0),
            qos=qos)
        rng = np.random.default_rng(0)
        ex.submit("knn_k4_l2", _queries(rng, 1), tenant="capped")
        with pytest.raises(limits.RejectedError) as ei:
            ex.submit("knn_k4_l2", _queries(rng, 1), tenant="capped")
        assert ei.value.reason == "queue_full"
        # other tenants are unaffected by the capped tenant's share
        ex.submit("knn_k4_l2", _queries(rng, 1), tenant="other")

    def test_expired_head_swept_at_enqueue(self, data, live_obs):
        """ISSUE 16 satellite: a dead request must not hold its queue
        slot. With max_queue=2 and an expired head, the NEXT submit
        sweeps the corpse and is admitted instead of queue_full-failing
        behind it."""
        ex = serve.Executor(
            [serve.KnnService(data["db"], k=4)],
            policy=serve.BatchPolicy(max_batch=8, max_wait_ms=1_000.0,
                                     max_queue=2))
        rng = np.random.default_rng(0)
        dead = ex.submit("knn_k4_l2", _queries(rng, 1),
                         deadline_s=0.005)
        live1 = ex.submit("knn_k4_l2", _queries(rng, 1))
        time.sleep(0.05)               # head expires while queued
        live2 = ex.submit("knn_k4_l2", _queries(rng, 1))
        assert ex.queue.pending() == 2
        with pytest.raises(limits.DeadlineExceededError,
                           match="swept"):
            dead.result(timeout=1.0)
        assert _counter_value(live_obs, "limits_deadline_exceeded_total",
                              op="serve.knn_k4_l2") == 1.0
        ex.warm([8])
        with ex:
            for f in (live1, live2):
                f.result(timeout=30.0)

    def test_cancelled_head_swept_without_double_resolution(self, data):
        ex = serve.Executor(
            [serve.KnnService(data["db"], k=4)],
            policy=serve.BatchPolicy(max_batch=8, max_wait_ms=1_000.0,
                                     max_queue=2))
        rng = np.random.default_rng(1)
        r1 = ex.submit_request("knn_k4_l2", _queries(rng, 1))
        r1.cancel("hedge_lost")
        with pytest.raises(limits.RejectedError) as ei:
            r1.future.result(timeout=1.0)
        assert ei.value.reason == "cancelled"
        # the sweep drops it from the queue; its already-resolved
        # future is left alone (first fulfillment won)
        ex.submit("knn_k4_l2", _queries(rng, 1))
        ex.submit("knn_k4_l2", _queries(rng, 1))
        assert ex.queue.pending() == 2
        with pytest.raises(limits.RejectedError):
            r1.future.result(timeout=0.1)   # still the cancel, stable

    def test_over_budget_batch_splits_and_stays_bit_identical(self, data):
        """A coalesced batch whose footprint exceeds the serving budget
        splits into smaller warmed buckets; results unchanged."""
        svc = serve.KnnService(data["db"], k=4)
        # budget fits a 16-row launch but not the 64-row coalesced one
        budget = limits.WorkBudget(svc.estimate_bytes(16) + 1)
        assert svc.estimate_bytes(64) > budget.limit_bytes
        qos = serve.QosPolicy(budget=budget)
        ex = serve.Executor(
            [svc], policy=serve.BatchPolicy(max_batch=64,
                                            max_wait_ms=20.0),
            qos=qos)
        ex.warm()
        rng = np.random.default_rng(5)
        with ex:
            subs = [(q := _queries(rng, 8), ex.submit("knn_k4_l2", q))
                    for _ in range(8)]
            outs = [(q, f.result(timeout=60)) for q, f in subs]
        assert ex.stats.splits >= 1
        for q, (d, i) in outs:
            wd, wi = svc.eager(q)
            np.testing.assert_array_equal(np.asarray(d), np.asarray(wd))
            np.testing.assert_array_equal(np.asarray(i), np.asarray(wi))


class TestAotWarm:
    def test_zero_compiles_after_warmup(self, data):
        """Steady-state serving must never recompile: the trace-time
        hook (which ticks exactly on jit cache misses) stays flat over
        requests of every size after warm()."""
        ex = serve.Executor(
            [serve.KnnService(data["db"], k=4)],
            policy=serve.BatchPolicy(max_batch=32, max_wait_ms=1.0))
        warmed = ex.warm()
        assert warmed == len(serve.bucket_ladder(32))
        traces_at_warm = ex.stats.traces
        misses_at_warm = ex.stats.exec_misses
        rng = np.random.default_rng(9)
        with ex:
            futs = [ex.submit("knn_k4_l2", _queries(rng, rows))
                    for rows in (1, 3, 8, 13, 2, 30, 5, 17, 9, 21)]
            for f in futs:
                f.result(timeout=60)
        assert ex.stats.traces == traces_at_warm, (
            f"{ex.stats.traces - traces_at_warm} recompiles after warmup")
        assert ex.stats.exec_misses == misses_at_warm
        assert ex.stats.exec_hits > 0
        assert ex.stats.batches >= 1

    def test_compile_cache_metrics(self, data, live_obs):
        ex = serve.Executor(
            [serve.KnnService(data["db"], k=4)],
            policy=serve.BatchPolicy(max_batch=8, max_wait_ms=1.0))
        ex.warm([8])
        assert _counter_value(live_obs, "runtime_compile_cache_total",
                              cache="serve", outcome="miss") == 1.0
        ex._get_executable(ex.services["knn_k4_l2"], 8)
        assert _counter_value(live_obs, "runtime_compile_cache_total",
                              cache="serve", outcome="hit") >= 1.0


class TestLoadgen:
    def test_closed_loop_reports(self, data):
        ex = serve.Executor(
            [serve.KnnService(data["db"], k=4)],
            policy=serve.BatchPolicy(max_batch=32, max_wait_ms=2.0))
        ex.warm()
        with ex:
            rep = serve.closed_loop(ex, "knn_k4_l2", clients=4, rows=4,
                                    duration_s=0.5)
        assert rep.completed > 0
        assert rep.qps > 0
        assert np.isfinite(rep.p50_ms) and np.isfinite(rep.p99_ms)
        assert rep.p99_ms >= rep.p50_ms
        d = rep.as_dict()
        assert d["mode"] == "closed"
        json.dumps(d)                  # bench-line serializable


class TestRadixEpilogueServePath:
    """Era-7 serve wiring: a k > 256 KnnService warms onto the radix
    epilogue (trace-visible), its launches set the
    select_k_bytes_per_s gauge, and the loadgen report carries it."""

    @pytest.fixture
    def big_db(self):
        rng = np.random.default_rng(77)
        return rng.standard_normal((16384, DIM)).astype(np.float32)

    def test_epilogue_and_selection_bytes(self, big_db, data):
        from raft_tpu.matrix.radix_select import NPASS

        svc = serve.KnnService(big_db, k=512)
        assert svc.epilogue() == "radix"
        assert svc.selection_bytes(8) == (NPASS + 2) * 8 * 16384 * 4
        small = serve.KnnService(data["db"], k=4)
        assert small.epilogue() != "radix"
        assert small.selection_bytes(8) == 0

    def test_warm_event_and_launch_gauge(self, big_db, live_obs):
        from raft_tpu.core import trace

        ex = serve.Executor(
            [serve.KnnService(big_db, k=512)],
            policy=serve.BatchPolicy(max_batch=8, max_wait_ms=1.0))
        trace.clear_events()
        ex.warm(buckets=(8,))
        warmed = trace.events("serve.warmed")
        assert warmed and warmed[-1]["epilogue"] == "radix"
        rng = np.random.default_rng(78)
        with ex:
            fut = ex.submit("knn_k512_l2", _queries(rng, 4))
            fut.result(timeout=120)
        fam = live_obs.snapshot().get("select_k_bytes_per_s")
        assert fam and fam["series"], \
            "radix-epilogue launch must set the bandwidth gauge"
        assert fam["series"][0]["value"] > 0
        assert fam["series"][0]["labels"]["op"] == "knn_k512_l2"
        rep = serve.LoadReport(mode="x", duration_s=1.0)
        from raft_tpu.serve.loadgen import _finalize
        rep = _finalize(rep, ex, (0, 0, 0), 0.0)
        assert rep.as_dict()["select_k_bytes_per_s"] > 0


ROOT = pathlib.Path(__file__).resolve().parent.parent


def _load(name, relpath):
    import sys

    spec = importlib.util.spec_from_file_location(name, ROOT / relpath)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod            # dataclasses resolve via sys.modules
    try:
        spec.loader.exec_module(mod)
    except BaseException:
        sys.modules.pop(name, None)
        raise
    return mod


class TestBenchProvenance:
    """Era / superseded_by stamping satellite: stale rows cannot be
    read as current by any BENCH_r0*.json reader."""

    def _tpu_line(self, **over):
        line = {"metric": "kmeans_lloyd", "backend": "tpu",
                "mxu_util_4mnk": 0.5, "value": 100.0, "era": 6}
        line.update(over)
        return line

    def test_superseded_rows_are_invalid(self):
        bench = _load("_bench_prov", "bench.py")
        assert bench.is_valid_northstar_line(self._tpu_line())
        assert not bench.is_valid_northstar_line(
            self._tpu_line(superseded_by="era 7 remeasure"))

    def test_relay_prefers_newest_era(self, tmp_path, monkeypatch):
        bench = _load("_bench_prov2", "bench.py")
        art_dir = tmp_path / "tpu_battery_out"
        art_dir.mkdir()
        lines = [self._tpu_line(era=0, value=1.0),
                 self._tpu_line(era=6, value=6.0),
                 self._tpu_line(era=3, value=3.0),
                 self._tpu_line(era=9, value=9.0,
                                superseded_by="bad apparatus")]
        (art_dir / "bench_northstar.json").write_text(
            "\n".join(json.dumps(d) for d in lines) + "\n")
        monkeypatch.setattr(bench, "__file__",
                            str(tmp_path / "bench.py"))
        got = bench._relay_battery_artifact()
        assert got is not None
        assert got["value"] == 6.0 and got["era"] == 6
        assert got["relay"]

    def test_harness_stamps_era(self):
        harness = _load("_harness_prov", "benches/harness.py")
        row = json.loads(harness.BenchResult(
            name="x", median_ms=1.0, best_ms=1.0, repeats=1).json_line())
        assert row["era"] == harness.BENCH_ERA >= 6
        assert harness.is_current_row(row, harness.BENCH_ERA)
        assert not harness.is_current_row(
            dict(row, superseded_by="retired"), harness.BENCH_ERA)
        assert not harness.is_current_row({"bench": "x"},
                                          harness.BENCH_ERA)

    def test_render_bench_filters_stale_rows(self):
        rb = _load("_render_bench", "ci/render_bench.py")
        rows = [{"bench": "a", "era": 6, "median_ms": 1.0},
                {"bench": "a", "era": 0, "median_ms": 9.0},
                {"bench": "a", "era": 6, "median_ms": 2.0,
                 "superseded_by": "x"},
                {"bench": "b", "median_ms": 3.0}]   # pre-era family: kept
        got = rb.current_rows(rows)
        assert got == [{"bench": "a", "era": 6, "median_ms": 1.0},
                       {"bench": "b", "median_ms": 3.0}]


class TestJsonlSinkShutdown:
    """atexit-flush satellite: the sink closes idempotently and the
    shutdown hook flushes whatever sink is still attached."""

    def test_close_is_idempotent_and_write_after_close_is_noop(self, tmp_path):
        from raft_tpu.obs import export

        path = tmp_path / "events.jsonl"
        sink = export.JsonlSink(str(path))
        sink.write({"kind": "event", "name": "a"})
        sink.close()
        sink.close()                    # second close: no error
        sink.write({"kind": "event", "name": "dropped"})
        sink.flush()                    # flush after close: no error
        lines = path.read_text().strip().splitlines()
        assert len(lines) == 1

    def test_atexit_hook_closes_attached_sink(self, tmp_path):
        from raft_tpu.obs import export

        path = tmp_path / "events.jsonl"
        sink = export.JsonlSink(str(path))
        old = export.set_sink(sink)
        try:
            sink.write({"kind": "event", "name": "final"})
            export._atexit_close_sink()
            assert sink._closed
            assert json.loads(path.read_text().splitlines()[-1])[
                "name"] == "final"
        finally:
            export.set_sink(old)
