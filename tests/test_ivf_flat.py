"""IVF-Flat index: the exactness boundary (nprobe = n_lists bit-identical
to brute force, ties/NaN included), recall floor at partial probes,
extend == rebuild (both the tail-append and the repack branch),
admission degrade/reject, the ivf.search trace event, the knn_plan ivf
band, and the serving IvfKnnService (batched == eager bits, zero
post-warm recompiles)."""

import numpy as np
import pytest

import jax.numpy as jnp

from raft_tpu.core import trace
from raft_tpu.neighbors import ivf_flat, knn
from raft_tpu.neighbors.brute_force import knn_plan
from raft_tpu.random import RngState, make_blobs
from raft_tpu.runtime import limits


@pytest.fixture(scope="module")
def blob_index(res):
    X, _, _ = make_blobs(res, RngState(3), 4096, 24, n_clusters=32)
    return np.asarray(X), ivf_flat.build(res, X, 32, seed=0, max_iter=6)


class TestBuildLayout:
    def test_packed_is_a_permutation(self, res, blob_index):
        X, idx = blob_index
        ids = np.asarray(idx.packed_ids)
        live = ids[ids >= 0]
        assert sorted(live.tolist()) == list(range(len(X)))
        # packed rows are the ORIGINAL rows, bit-exact
        np.testing.assert_array_equal(np.asarray(idx.reconstruct()), X)

    def test_spans_aligned_and_consistent(self, res, blob_index):
        _, idx = blob_index
        caps = idx.caps
        assert (caps % ivf_flat.SLOT_ALIGN == 0).all()
        sizes = np.asarray(idx.sizes)
        assert (sizes <= caps).all()
        starts = np.asarray(idx.starts)
        np.testing.assert_array_equal(
            starts, np.concatenate([[0], np.cumsum(caps)[:-1]]))
        assert int(sizes.sum()) == idx.n_db
        # within each list, ascending original id (the stable pack
        # order extend's tail appends rely on)
        ids = np.asarray(idx.packed_ids)
        for li in range(idx.n_lists):
            span = ids[starts[li]:starts[li] + sizes[li]]
            assert (np.diff(span) > 0).all()

    def test_bad_args(self, res, blob_index):
        X, idx = blob_index
        with pytest.raises(ValueError, match="n_lists"):
            ivf_flat.build(res, X[:4], 8)
        with pytest.raises(ValueError, match="metric"):
            ivf_flat.build(res, X[:64], 4, metric="canberra")
        with pytest.raises(ValueError, match="queries"):
            ivf_flat.search(res, idx, X[:2, :5], k=4, nprobe=2)
        with pytest.raises(ValueError, match="nprobe"):
            ivf_flat.search(res, idx, X[:2], k=4, nprobe=0)
        with pytest.raises(ValueError, match="n_db"):
            ivf_flat.search(res, idx, X[:2], k=0, nprobe=2)


class TestExactnessBoundary:
    def test_full_probe_bit_identical_to_brute(self, res, blob_index):
        X, idx = blob_index
        q = X[:96]
        bd, bi = knn(res, X, q, k=12)
        ad, ai = ivf_flat.search(res, idx, q, k=12, nprobe=idx.n_lists)
        np.testing.assert_array_equal(np.asarray(bd), np.asarray(ad))
        np.testing.assert_array_equal(np.asarray(bi), np.asarray(ai))

    def test_full_probe_ties_and_nan_identical(self, res):
        # adversarial db: exact duplicate rows (ties) and NaN rows —
        # the delegation to brute force on the reconstructed db must
        # reproduce its tie ordering and NaN behavior bit-for-bit
        rng = np.random.default_rng(5)
        X = rng.normal(size=(512, 8)).astype(np.float32)
        X[100] = X[7]                     # exact tie pair
        X[200] = X[7]
        X[300] = np.nan                   # NaN row
        # quantizer training validates finiteness (kmeans_fit contract)
        # — a dirty database builds against supplied centroids; the NaN
        # row still lands in SOME list deterministically and survives
        # reconstruction bit-for-bit
        idx = ivf_flat.build(res, X, 8, centroids=X[:8])
        q = np.concatenate([X[7:8], X[300:301], X[40:44]])
        bd, bi = knn(res, X, q, k=8)
        ad, ai = ivf_flat.search(res, idx, q, k=8, nprobe=8)
        np.testing.assert_array_equal(np.asarray(bd), np.asarray(ad))
        np.testing.assert_array_equal(np.asarray(bi), np.asarray(ai))

    def test_overprobe_clamps_to_full_scan(self, res, blob_index):
        X, idx = blob_index
        d1 = ivf_flat.search(res, idx, X[:8], k=4, nprobe=idx.n_lists)
        d2 = ivf_flat.search(res, idx, X[:8], k=4,
                             nprobe=idx.n_lists + 7)
        np.testing.assert_array_equal(np.asarray(d1[1]),
                                      np.asarray(d2[1]))


class TestRecall:
    @pytest.mark.slow  # also gated in ci/smoke.sh at the same shape
    def test_recall_floor_nprobe16(self, res):
        X, _, _ = make_blobs(res, RngState(9), 8192, 32, n_clusters=64)
        idx = ivf_flat.build(res, X, 64, seed=0)
        q = np.asarray(X[:128])
        _, gi = knn(res, X, q, k=10)
        _, ai = ivf_flat.search(res, idx, q, k=10, nprobe=16)
        gi, ai = np.asarray(gi), np.asarray(ai)
        recall = np.mean([len(set(a) & set(b)) / 10
                          for a, b in zip(gi, ai)])
        assert recall >= 0.95

    @pytest.mark.slow
    def test_inner_metric_full_probe_matches_brute(self, res):
        rng = np.random.default_rng(11)
        X = rng.normal(size=(1024, 16)).astype(np.float32)
        idx = ivf_flat.build(res, X, 16, metric="inner", seed=0)
        q = X[:32]
        bd, bi = knn(res, X, q, k=5, metric="inner")
        ad, ai = ivf_flat.search(res, idx, q, k=5, nprobe=16)
        np.testing.assert_array_equal(np.asarray(bi), np.asarray(ai))
        np.testing.assert_array_equal(np.asarray(bd), np.asarray(ad))

    def test_underfull_candidates_pad(self, res):
        # k reaches past one probed list's capacity: require the
        # explicit error, not silent truncation
        rng = np.random.default_rng(13)
        X = rng.normal(size=(256, 8)).astype(np.float32)
        idx = ivf_flat.build(res, X, 32, seed=0)
        with pytest.raises(ValueError, match="candidates"):
            ivf_flat.search(res, idx, X[:4], k=idx.cap_max + 1,
                            nprobe=1)
        # a sparse query row that probes a short list still returns k
        # columns, padded with id -1 / +inf
        d, i = ivf_flat.search(res, idx, X[:4], k=idx.cap_max, nprobe=1)
        i = np.asarray(i)
        d = np.asarray(d)
        pad = i == -1
        assert np.isinf(d[pad]).all()
        assert (i[~pad] >= 0).all()


class TestExtend:
    @pytest.mark.slow
    def test_extend_fitting_tail_equals_rebuild(self, res):
        # craft new rows next to the centroid whose padded tail has the
        # most headroom, so the append branch is exercised
        # deterministically (no repack)
        rng = np.random.default_rng(17)
        X = rng.normal(size=(1003, 12)).astype(np.float32)
        idx = ivf_flat.build(res, X, 8, seed=0)
        head = idx.caps - np.asarray(idx.sizes)
        li = int(np.argmax(head))
        assert head[li] >= 2, "all tails full; pick another seed"
        c = np.asarray(idx.centroids)[li]
        Y = (c + 0.01 * rng.normal(size=(2, 12))).astype(np.float32)
        ext = ivf_flat.extend(res, idx, Y)
        reb = ivf_flat.build(res, np.concatenate([X, Y]), 8,
                             centroids=idx.centroids)
        assert np.array_equal(ext.caps, idx.caps)   # append, no repack
        np.testing.assert_array_equal(np.asarray(ext.packed_ids),
                                      np.asarray(reb.packed_ids))
        q = X[:40]
        ed, ei = ivf_flat.search(res, ext, q, k=8, nprobe=3)
        rd, ri = ivf_flat.search(res, reb, q, k=8, nprobe=3)
        np.testing.assert_array_equal(np.asarray(ei), np.asarray(ri))
        np.testing.assert_array_equal(np.asarray(ed), np.asarray(rd))

    @pytest.mark.slow
    def test_extend_overflow_repacks_and_equals_rebuild(self, res):
        rng = np.random.default_rng(19)
        X = rng.normal(size=(512, 12)).astype(np.float32)
        Y = rng.normal(size=(300, 12)).astype(np.float32)  # overflows
        idx = ivf_flat.build(res, X, 8, seed=0)
        ext = ivf_flat.extend(res, idx, Y)
        reb = ivf_flat.build(res, np.concatenate([X, Y]), 8,
                             centroids=idx.centroids)
        np.testing.assert_array_equal(np.asarray(ext.packed_ids),
                                      np.asarray(reb.packed_ids))
        q = X[:40]
        ed, ei = ivf_flat.search(res, ext, q, k=8, nprobe=3)
        rd, ri = ivf_flat.search(res, reb, q, k=8, nprobe=3)
        np.testing.assert_array_equal(np.asarray(ei), np.asarray(ri))
        np.testing.assert_array_equal(np.asarray(ed), np.asarray(rd))

    def test_extend_full_probe_still_exact(self, res, blob_index):
        X, idx = blob_index
        rng = np.random.default_rng(23)
        Y = rng.normal(size=(50, X.shape[1])).astype(np.float32)
        ext = ivf_flat.extend(res, idx, Y)
        assert ext.n_db == len(X) + 50
        full = np.concatenate([X, Y])
        np.testing.assert_array_equal(np.asarray(ext.reconstruct()),
                                      full)
        q = full[-8:]
        bd, bi = knn(res, full, q, k=6)
        ad, ai = ivf_flat.search(res, ext, q, k=6, nprobe=ext.n_lists)
        np.testing.assert_array_equal(np.asarray(bi), np.asarray(ai))


class TestAdmissionAndObs:
    def test_degraded_bit_identical(self, res, blob_index):
        X, idx = blob_index
        q = X[:64]
        bd, bi = ivf_flat.search(res, idx, q, k=8, nprobe=4)
        est = limits.estimate_bytes(
            "neighbors.ivf_search", n_queries=64,
            probe_rows=4 * idx.cap_max, n_dims=idx.dim, k=8,
            itemsize=4, packed_rows=int(idx.packed_db.shape[0]))
        with limits.budget_scope(est // 2 + int(idx.packed_db.nbytes)):
            dd, di = ivf_flat.search(res, idx, q, k=8, nprobe=4)
        np.testing.assert_array_equal(np.asarray(bd), np.asarray(dd))
        np.testing.assert_array_equal(np.asarray(bi), np.asarray(di))

    def test_unfittable_rejected(self, res, blob_index):
        X, idx = blob_index
        with limits.budget_scope(1024):
            with pytest.raises(limits.RejectedError):
                ivf_flat.search(res, idx, X[:4], k=8, nprobe=4)

    def test_trace_event_carries_probe_plan(self, res, blob_index):
        X, idx = blob_index
        trace.clear_events()
        ivf_flat.search(res, idx, X[:4], k=8, nprobe=4)
        ev = trace.events("ivf.search")
        assert len(ev) == 1
        assert ev[0]["nprobe"] == 4 and ev[0]["path"] == "ivf"
        assert ev[0]["scanned_frac"] == pytest.approx(4 / idx.n_lists)
        trace.clear_events()
        ivf_flat.search(res, idx, X[:4], k=8, nprobe=idx.n_lists)
        ev = trace.events("ivf.search")
        assert ev[0]["path"] == "exact"
        assert ev[0]["scanned_frac"] == 1.0

    def test_knn_plan_ivf_band(self):
        assert knn_plan(64, 4096, 10, n_lists=64, nprobe=8) == ("ivf", 0)
        # full scan is not an ivf plan — it IS the brute-force plan
        path, _ = knn_plan(64, 4096, 10, n_lists=64, nprobe=64)
        assert path != "ivf"
        assert knn_plan(64, 4096, 10)[0] != "ivf"


class TestIvfServe:
    def test_batched_bits_and_zero_recompiles(self, res, blob_index):
        from raft_tpu import serve

        X, idx = blob_index
        svc = serve.IvfKnnService(idx, k=10, nprobe=8)
        assert svc.epilogue() == "ivf"
        ex = serve.Executor(
            [svc], policy=serve.BatchPolicy(max_batch=64,
                                            max_wait_ms=2.0))
        ex.warm()
        traces_after_warm = ex.stats.traces
        q = X[:48]
        with ex:
            fut = ex.submit(svc.name, q)
            d, i = fut.result(timeout=60.0)
        assert ex.stats.traces == traces_after_warm
        ed, ei = ivf_flat.search(res, idx, q, k=10, nprobe=8)
        np.testing.assert_array_equal(np.asarray(i), np.asarray(ei))
        np.testing.assert_array_equal(np.asarray(d), np.asarray(ed))

    def test_full_scan_service_rejected(self, res, blob_index):
        from raft_tpu import serve

        _, idx = blob_index
        with pytest.raises(ValueError, match="KnnService"):
            serve.IvfKnnService(idx, k=4, nprobe=idx.n_lists)
