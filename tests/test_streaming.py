"""Streaming index lifecycle (ISSUE 17): crash-safe online mutation,
zero-pause compaction, drift-aware refit.

Exactness claims gated here:

- tombstoned-id exclusion is bit-identical to a rebuild WITHOUT the
  deleted rows — under exact duplicates (ties) and NaN rows, on BOTH
  fine-select epilogues (fused merge and radix), forced explicitly
  through ``_search_jit(use_radix=...)``;
- a delete/fitting-insert never retraces the compiled search (the
  same-shape swap contract), pinned via ``_cache_size``;
- recovery replays a journaled mutation history to the exact pre-crash
  content CRC — a raise-mode sweep over every named crash point
  in-process, plus a real-SIGKILL subprocess witness
  (tests/_streaming_chaos_worker.py) whose reference CRCs come from a
  twin subprocess so jax config can never skew the comparison.
"""

import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

import _streaming_chaos_worker as chaos
from raft_tpu.comms.faults import CrashPointError, FaultInjector
from raft_tpu.core import env
from raft_tpu.neighbors import ivf_flat
from raft_tpu.neighbors.ivf_flat import _search_jit
from raft_tpu.neighbors.streaming import (Compactor, DriftGauge,
                                          MutationLog, RecoveryError,
                                          StreamingError,
                                          StreamingIndex, StreamingMnmg,
                                          _flat_from_live, stream_build)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CRASH_POINTS = ["ingest.pre_journal", "ingest.post_journal",
                "compact.pre_pack", "compact.pre_commit",
                "compact.mid_write", "compact.post_commit",
                "compact.post_swap"]


def _mk(res=None, n=160, d=8, n_lists=8, seed=3, **kw):
    rng = np.random.default_rng(seed)
    db = rng.normal(size=(n, d)).astype(np.float32)
    return db, stream_build(res, db, n_lists, seed=0, max_iter=4, **kw)


def _rows(m, d=8, seed=11):
    return np.random.default_rng(seed).normal(size=(m, d)).astype(
        np.float32)


# ---------------------------------------------------------------------------
# mutation basics
# ---------------------------------------------------------------------------


class TestMutation:
    def test_insert_assigns_sequential_ids_and_serves(self, res):
        db, idx = _mk(res)
        new = _rows(12)
        ids = idx.insert(new)
        np.testing.assert_array_equal(ids, np.arange(160, 172))
        assert idx.n_live == 172 and idx.next_id == 172
        # every inserted row is its own nearest live neighbor at full
        # probe (exact path over live rows)
        _, got = idx.search(new, k=1, nprobe=idx.flat.n_lists)
        np.testing.assert_array_equal(np.asarray(got)[:, 0], ids)

    def test_fitting_insert_and_delete_never_retrace(self, res):
        db, idx = _mk(res)
        idx.compact(reason="provision")        # tails get repack_slack
        q = db[:16]
        idx.search(q, k=4, nprobe=7)
        before = _search_jit._cache_size()
        assert idx.delete([3, 5]) == 2
        idx.search(q, k=4, nprobe=7)
        epoch0 = idx.epoch
        idx.insert(_rows(4))                   # fits the provisioned tails
        assert idx.epoch == epoch0, "fitting insert must not repack"
        idx.search(q, k=4, nprobe=7)
        assert _search_jit._cache_size() == before, \
            "delete / fitting insert changed a compiled-search shape"

    def test_delete_excludes_and_is_idempotent(self, res):
        db, idx = _mk(res)
        assert idx.delete([7, 7, 9]) == 2
        assert idx.delete([7, 9]) == 0
        assert idx.n_live == 158
        _, got = idx.search(db[7:8], k=4, nprobe=idx.flat.n_lists)
        assert 7 not in np.asarray(got)
        rows, ids = idx.live_rows()
        assert 7 not in ids and 9 not in ids
        assert rows.shape[0] == 158

    def test_overflow_insert_repacks_under_new_epoch(self, res):
        db, idx = _mk(res)
        epoch0 = idx.epoch
        big = _rows(200, seed=13)
        ids = idx.insert(big)
        assert idx.epoch > epoch0
        assert idx.n_live == 360 and idx.next_id == 360
        rows, live = idx.live_rows()
        np.testing.assert_array_equal(live, np.arange(360))
        np.testing.assert_array_equal(rows[ids], big)

    def test_validation(self, res):
        db, idx = _mk(res)
        with pytest.raises(ValueError, match=r"rows must be"):
            idx.insert(np.zeros((3, 5), np.float32))
        with pytest.raises(ValueError, match=r"labels must be"):
            idx.insert(_rows(2), labels=np.asarray([0, 99]))
        with pytest.raises(ValueError, match=r"ids must be in"):
            idx.delete([700])
        with pytest.raises(ValueError, match=r"n_live"):
            idx.search(db[:2], k=200, nprobe=8)
        with pytest.raises(ValueError, match=r"nprobe"):
            idx.search(db[:2], k=2, nprobe=0)
        assert idx.insert(np.zeros((0, 8), np.float32)).size == 0
        assert idx.delete(np.zeros((0,), np.int64)) == 0


# ---------------------------------------------------------------------------
# tombstone exactness: ties + NaN, both epilogues (satellite d)
# ---------------------------------------------------------------------------


def _dirty_stream(res):
    """The adversarial db from test_ivf_flat: an exact duplicate pair
    and a NaN row, built against supplied centroids so the quantizer
    never sees the NaN."""
    rng = np.random.default_rng(5)
    X = rng.normal(size=(512, 8)).astype(np.float32)
    X[100] = X[7]
    X[200] = X[7]
    X[300] = np.nan
    flat = ivf_flat.build(res, X, 8, centroids=X[:8])
    return X, StreamingIndex(flat)


def _force_search(flat, tomb_words, q, k, nprobe, use_radix):
    return _search_jit(jnp.asarray(q), flat.centroids, flat.packed_db,
                       flat.packed_ids, flat.starts, flat.sizes,
                       tomb_words, k=k, nprobe=nprobe,
                       cap_max=flat.cap_max, metric=flat.metric,
                       use_radix=use_radix)


class TestTombstoneExactness:
    def _radix_ok(self, k, *flats):
        from raft_tpu.matrix import radix_select
        from raft_tpu.util.pallas_utils import interpret_needs_ref

        return all(radix_select.supports(jnp.float32,
                                         7 * f.cap_max, k)
                   and not interpret_needs_ref(f.packed_db)
                   for f in flats)

    @pytest.mark.parametrize("use_radix", [False, True])
    def test_delete_bit_identical_to_rebuild(self, res, use_radix):
        X, idx = _dirty_stream(res)
        # kill one of the tie pair, the NaN row, and a bystander
        idx.delete([100, 300, 20])
        snap = idx.snapshot
        rows, ids = idx.live_rows()
        rebuilt = _flat_from_live(rows, ids, snap.flat.centroids,
                                  snap.flat.metric)
        if use_radix and not self._radix_ok(8, snap.flat, rebuilt):
            pytest.skip("radix epilogue unsupported at this shape")
        q = np.concatenate([X[7:8], X[100:101], X[40:44]])
        md, mi = _force_search(snap.flat, snap.tomb_words, q, 8, 7,
                               use_radix)
        rd, ri = _force_search(rebuilt, None, q, 8, 7, use_radix)
        np.testing.assert_array_equal(np.asarray(md), np.asarray(rd))
        np.testing.assert_array_equal(np.asarray(mi), np.asarray(ri))
        assert not np.isin(np.asarray(mi), [100, 300, 20]).any()

    @pytest.mark.parametrize("use_radix", [False, True])
    def test_zero_bitset_is_value_level_noop(self, res, use_radix):
        X, idx = _dirty_stream(res)
        snap = idx.snapshot
        if use_radix and not self._radix_ok(8, snap.flat):
            pytest.skip("radix epilogue unsupported at this shape")
        q = np.concatenate([X[7:8], X[300:301], X[40:44]])
        zd, zi = _force_search(snap.flat, snap.tomb_words, q, 8, 7,
                               use_radix)
        nd, ni = _force_search(snap.flat, None, q, 8, 7, use_radix)
        np.testing.assert_array_equal(np.asarray(zd), np.asarray(nd))
        np.testing.assert_array_equal(np.asarray(zi), np.asarray(ni))

    def test_unrelated_delete_leaves_results_bit_identical(self, res):
        X, idx = _dirty_stream(res)
        q = np.concatenate([X[7:8], X[40:44]])
        d0, i0 = idx.search(q, k=4, nprobe=7)
        victims = sorted(set(range(450, 470))
                         - set(np.asarray(i0).ravel().tolist()))
        idx.delete(victims)
        d1, i1 = idx.search(q, k=4, nprobe=7)
        np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))

    def test_exact_path_matches_brute_force_on_live(self, res):
        from raft_tpu.neighbors.brute_force import knn

        X, idx = _dirty_stream(res)
        idx.delete([100, 300])
        rows, ids = idx.live_rows()
        bd, bi = knn(res, rows, np.concatenate([X[7:8], X[40:44]]), k=8)
        ad, ai = idx.search(np.concatenate([X[7:8], X[40:44]]), k=8,
                            nprobe=idx.flat.n_lists)
        np.testing.assert_array_equal(np.asarray(bd), np.asarray(ad))
        np.testing.assert_array_equal(ids[np.asarray(bi)],
                                      np.asarray(ai))


# ---------------------------------------------------------------------------
# WAL + recovery
# ---------------------------------------------------------------------------


class TestRecovery:
    def test_replay_is_bit_identical(self, res, tmp_path):
        db, idx = _mk(res, directory=str(tmp_path))
        idx.insert(_rows(24))
        idx.delete(np.arange(0, 30, 3))
        rec = StreamingIndex.recover(res, str(tmp_path))
        assert rec.content_crc() == idx.content_crc()
        assert rec.next_id == idx.next_id
        assert rec.n_live == idx.n_live
        q = db[:8]
        np.testing.assert_array_equal(
            np.asarray(idx.search(q, k=4, nprobe=7)[1]),
            np.asarray(rec.search(q, k=4, nprobe=7)[1]))

    def test_recover_after_compaction_prunes_wal(self, res, tmp_path):
        db, idx = _mk(res, directory=str(tmp_path))
        idx.insert(_rows(24))
        idx.delete(np.arange(10))
        idx.compact(reason="test")
        names = os.listdir(tmp_path)
        assert not [n for n in names if n.startswith("wal-")], \
            "commit must prune the WAL records the snapshot folded in"
        assert len([n for n in names if n.startswith("epoch-")]) <= 2
        rec = StreamingIndex.recover(res, str(tmp_path))
        assert rec.content_crc() == idx.content_crc()

    @pytest.mark.parametrize("point", CRASH_POINTS)
    def test_crash_sweep_recovers_consistent(self, tmp_path, point):
        """Raise-mode crash at every named protocol point: recovery
        lands on the exact pre- or post-mutation content (pre_journal
        is the only point where the mutation is not yet durable), and
        replay is deterministic (two recoveries agree)."""
        ref = str(tmp_path / "ref")
        crc_del, crc_ins2, crc_fin = chaos._sequence(ref)
        assert crc_fin == crc_ins2, "compaction must preserve the CRC"
        want = crc_del if point == "ingest.pre_journal" else crc_ins2
        d = str(tmp_path / "crash")
        with pytest.raises(CrashPointError):
            chaos._sequence(d, crash=point, mode="raise")
        assert StreamingIndex.recover(None, d).content_crc() == want
        assert StreamingIndex.recover(None, d).content_crc() == want

    def test_sigkill_witness(self, tmp_path):
        """The real-SIGKILL half: the worker dies at
        compact.mid_write (the torn-file window) under SIGKILL — no
        atexit, no finally — and two independent recoveries in a fresh
        process land bit-equal on the post-mutation epoch."""
        env_ = dict(os.environ, JAX_PLATFORMS="cpu")
        worker = os.path.join(_REPO, "tests",
                              "_streaming_chaos_worker.py")

        def run(*args, rc=0):
            p = subprocess.run([sys.executable, worker, *args],
                               cwd=_REPO, env=env_, timeout=300,
                               capture_output=True, text=True)
            assert p.returncode == rc, p.stderr[-2000:]
            return p.stdout.split()

        ref = run("--dir", str(tmp_path / "ref"))
        _, after_insert2, final = (int(c) for c in ref)
        assert final == after_insert2
        kill_dir = str(tmp_path / "kill")
        run("--dir", kill_dir, "--crash", "compact.mid_write",
            "--mode", "kill", rc=-9)
        first, second = (int(c) for c in
                         run("--dir", kill_dir, "--recover"))
        assert first == second == after_insert2

    def test_corrupt_epoch_falls_back_to_previous(self, res, tmp_path):
        db, idx = _mk(res, directory=str(tmp_path))
        idx.insert(_rows(200))            # overflow: folds into epoch 1
        e1, crc1 = idx.epoch, idx.content_crc()
        assert e1 >= 1
        idx.delete(np.arange(0, 80))
        idx.compact(reason="test")
        e2 = idx.epoch
        assert e2 > e1
        # at-rest damage to the newest epoch: recovery skips it and
        # serves the previous one (whose WAL was pruned at commit, so
        # the fallback is that epoch's folded content)
        path = idx.log.epoch_path(e2)
        with open(path, "r+b") as f:
            f.seek(os.path.getsize(path) // 2)
            f.write(b"\xde\xad\xbe\xef" * 8)
        rec = StreamingIndex.recover(res, str(tmp_path))
        assert rec.epoch == e1 and rec.content_crc() == crc1
        with open(idx.log.epoch_path(e1), "r+b") as f:
            f.seek(16)
            f.write(b"\xde\xad\xbe\xef" * 8)
        with pytest.raises(RecoveryError):
            StreamingIndex.recover(res, str(tmp_path))

    def test_mutation_log_seq_and_prune(self, tmp_path):
        log = MutationLog(str(tmp_path))
        assert log.append({"epoch": 0, "kind": 0,
                           "data": np.arange(3)}) == 0
        assert log.append({"epoch": 1, "kind": 1,
                           "data": np.arange(2)}) == 1
        # a reopened log continues the sequence
        assert MutationLog(str(tmp_path)).append(
            {"epoch": 1, "kind": 0, "data": np.arange(1)}) == 2
        recs = log.wal_records()
        assert [int(r["seq"]) for r in recs] == [0, 1, 2]
        assert log.prune_wal(before_epoch=1) == 1
        assert [int(r["epoch"]) for r in log.wal_records()] == [1, 1]
        with pytest.raises(RecoveryError):
            log.load_latest_epoch()


# ---------------------------------------------------------------------------
# compaction
# ---------------------------------------------------------------------------


class TestCompaction:
    def test_compact_preserves_content_and_search_bits(self, res):
        db, idx = _mk(res, n=256)
        idx.insert(_rows(24))
        idx.delete(np.arange(0, 60, 2))
        crc = idx.content_crc()
        q = db[:16]
        d0, i0 = idx.search(q, k=4, nprobe=7)
        frac0 = idx.tombstone_fraction()
        assert frac0 > 0
        idx.compact(reason="test")
        assert idx.content_crc() == crc
        assert idx.tombstone_fraction() == 0.0
        d1, i1 = idx.search(q, k=4, nprobe=7)
        np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))

    def test_compactor_triggers_then_settles(self, res):
        db, idx = _mk(res)
        c = Compactor(idx, interval=0.01, tombstone_frac=0.2,
                      refit=False)
        # a fresh build packs aligned-full tails, so the tail-overflow
        # criterion is due until a provisioning repack reserves slack
        assert c.should_compact()
        idx.compact(reason="provision")
        assert not c.should_compact()
        idx.delete(np.arange(0, 80))
        assert c.should_compact()
        assert c.run_once() is True
        assert c.compactions == 1
        assert c.run_once() is False, \
            "a repack with slack must clear both trigger fractions"

    def test_background_compactor_runs_and_stops(self, res):
        db, idx = _mk(res)
        swapped = threading.Event()
        with Compactor(idx, interval=0.01, tombstone_frac=0.2,
                       refit=False, on_change=swapped.set):
            idx.delete(np.arange(0, 80))
            assert swapped.wait(10.0), "compactor never fired"
        assert idx.tombstone_fraction() == 0.0

    def test_compactor_error_surfaces_at_stop(self, res, monkeypatch):
        db, idx = _mk(res)
        monkeypatch.setattr(idx, "compact",
                            lambda **kw: (_ for _ in ()).throw(
                                ValueError("boom")))
        c = Compactor(idx, interval=0.01, tombstone_frac=0.2,
                      refit=False)
        idx.delete(np.arange(0, 80))
        c.start()
        deadline = time.monotonic() + 10.0
        while c._error is None and time.monotonic() < deadline:
            time.sleep(0.01)
        with pytest.raises(StreamingError, match="compactor failed"):
            c.stop()

    def test_double_start_raises(self, res):
        db, idx = _mk(res)
        c = Compactor(idx, interval=60.0, refit=False)
        try:
            c.start()
            with pytest.raises(StreamingError, match="already started"):
                c.start()
        finally:
            c.stop()


# ---------------------------------------------------------------------------
# drift + refit
# ---------------------------------------------------------------------------


class TestDrift:
    def test_gauge_ratio_and_trigger(self):
        g = DriftGauge(threshold=1.5, alpha=1.0)
        assert g.ratio == 1.0 and not g.triggered
        g.set_baseline(2.0)
        assert g.observe_batch(2.0) == pytest.approx(1.0)
        assert not g.triggered
        assert g.observe_batch(4.0) == pytest.approx(2.0)
        assert g.triggered

    def test_refit_moves_centroids_and_keeps_ids(self, res):
        db, idx = _mk(res)
        before = np.asarray(idx.flat.centroids).copy()
        shifted = _rows(96, seed=21) + 4.0
        idx.insert(shifted)
        epoch0 = idx.epoch
        assert idx.maybe_refit(force=True) is True
        assert idx.epoch > epoch0
        assert not np.array_equal(before,
                                  np.asarray(idx.flat.centroids))
        rows, ids = idx.live_rows()
        assert rows.shape[0] == idx.n_live == 256
        np.testing.assert_array_equal(ids, np.arange(256))
        # the refitted quantizer still serves every live row exactly
        _, got = idx.search(shifted[:8], k=1,
                            nprobe=idx.flat.n_lists)

    def test_drift_triggered_refit_resets_baseline(self, res):
        db, idx = _mk(res, drift=DriftGauge(threshold=1.5, alpha=1.0))
        assert idx.maybe_refit() is False
        for s in range(4):
            idx.insert(_rows(48, seed=30 + s) + 6.0)
        assert idx.drift.triggered
        assert idx.maybe_refit() is True
        assert not idx.drift.triggered, \
            "refit must reset the drift baseline"


# ---------------------------------------------------------------------------
# MNMG: routed ingest + rebalance
# ---------------------------------------------------------------------------


class TestMnmg:
    def test_nearest_route_matches_single_rank_bits(self, res):
        db, idx = _mk(res, n=256)
        sm = StreamingMnmg(idx, n_ranks=2)
        sm.insert(_rows(24))
        sm.delete(np.arange(0, 40, 5))
        q = db[:12]
        sd, si = idx.search(q, k=6, nprobe=7)
        md, mi = sm.search(res, q, k=6, nprobe=7)
        np.testing.assert_array_equal(np.asarray(sd), np.asarray(md))
        np.testing.assert_array_equal(np.asarray(si), np.asarray(mi))
        # exact path delegates to the streaming live-row brute force
        sd, si = idx.search(q, k=6, nprobe=8)
        md, mi = sm.search(res, q, k=6, nprobe=8)
        np.testing.assert_array_equal(np.asarray(si), np.asarray(mi))

    def test_load_route_placement_is_journaled(self, res, tmp_path):
        db, idx = _mk(res, n=256, directory=str(tmp_path))
        sm = StreamingMnmg(idx, n_ranks=2, route="load", slack=2.0)
        for s in range(3):
            sm.insert(_rows(32, seed=40 + s))
        sizes = np.asarray(idx.flat.sizes, np.int64)
        rec = StreamingIndex.recover(res, str(tmp_path))
        assert rec.content_crc() == idx.content_crc()
        np.testing.assert_array_equal(
            sizes, np.asarray(rec.flat.sizes, np.int64))
        # exact search is placement-independent: every row it inserted
        # is its own nearest neighbor regardless of the routed list
        probe = _rows(32, seed=40)
        _, got = sm.search(res, probe, k=1, nprobe=idx.flat.n_lists)
        np.testing.assert_array_equal(np.asarray(got)[:, 0],
                                      np.arange(256, 288))

    def test_invalid_route_rejected(self, res):
        db, idx = _mk(res)
        with pytest.raises(ValueError, match="route"):
            StreamingMnmg(idx, n_ranks=2, route="random")

    def test_rebalance_compacts_and_reshards(self, res):
        db, idx = _mk(res, n=256)
        sm = StreamingMnmg(idx, n_ranks=2)
        sm.insert(_rows(24))
        sm.delete(np.arange(0, 80))
        crc = idx.content_crc()
        epoch0 = idx.epoch
        sm.rebalance()
        assert idx.epoch > epoch0
        assert idx.content_crc() == crc
        assert int(sm.rank_loads().sum()) == idx.flat.n_db
        q = db[100:108]
        sd, si = idx.search(q, k=6, nprobe=7)
        md, mi = sm.search(res, q, k=6, nprobe=7)
        np.testing.assert_array_equal(np.asarray(si), np.asarray(mi))


# ---------------------------------------------------------------------------
# serving: StreamingKnnService + IngestController
# ---------------------------------------------------------------------------


class TestServe:
    @pytest.fixture
    def controller(self, res):
        from raft_tpu import serve

        db, idx = _mk(res, n=256, repack_slack=64)
        idx.compact(reason="provision")
        svc = serve.StreamingKnnService(idx, k=5, nprobe=7)
        ctl = serve.IngestController(
            idx, [svc],
            policy=serve.BatchPolicy(max_batch=8, max_wait_ms=2.0),
            compact_interval=30.0, refit=False, warm_buckets=[8])
        with ctl:
            yield db, idx, svc, ctl

    def test_batched_serve_matches_direct_search_bits(self, controller):
        db, idx, svc, ctl = controller
        q = db[:4]
        d, i = ctl.submit(svc.name, q).result(timeout=30.0)
        ed, ei = idx.search(q, k=5, nprobe=7)
        np.testing.assert_array_equal(np.asarray(d), np.asarray(ed))
        np.testing.assert_array_equal(np.asarray(i), np.asarray(ei))

    def test_same_shape_swap_serves_immediately(self, controller):
        db, idx, svc, ctl = controller
        q = db[7:11]
        _, i0 = ctl.submit(svc.name, q).result(timeout=30.0)
        assert 7 in np.asarray(i0)
        swaps0, epoch0 = ctl.swaps, svc.serve_epoch
        ctl.delete([7])
        assert ctl.swaps == swaps0 and svc.serve_epoch == epoch0, \
            "a delete is a same-shape swap — no epoch bump"
        assert ctl.refreshes >= 1
        _, i1 = ctl.submit(svc.name, q).result(timeout=30.0)
        assert 7 not in np.asarray(i1)

    def test_shape_changing_swap_prewarms_then_serves(self, controller):
        db, idx, svc, ctl = controller
        swaps0 = ctl.swaps
        new = _rows(700, seed=51)
        ids = ctl.insert(new)
        assert ctl.swaps > swaps0, "an overflow repack must bump the epoch"
        q = new[:4]
        d, i = ctl.submit(svc.name, q).result(timeout=30.0)
        ed, ei = idx.search(q, k=5, nprobe=7)
        np.testing.assert_array_equal(np.asarray(d), np.asarray(ed))
        np.testing.assert_array_equal(np.asarray(i), np.asarray(ei))
        # a post-swap build evicts executables stranded on dead epochs
        ctl.executor._get_executable(svc, 16)
        stale = [key for key in ctl.executor._executables
                 if key[0] == svc.name and key[1] < svc.serve_epoch]
        assert not stale

    def test_prepare_publish_protocol(self, controller):
        db, idx, svc, ctl = controller
        assert svc.prepare() is None, "serving snapshot already current"
        idx.delete([3])                       # direct: bypass controller
        pending, version = svc.prepare()
        assert pending[0] == svc.serve_epoch  # same shapes, same epoch
        assert svc.publish(pending, version) is False
        idx.insert(_rows(700, seed=52))       # overflow: shapes change
        pending, version = svc.prepare()
        assert pending[0] == svc.serve_epoch + 1
        assert svc.publish(pending, version) is True

    def test_validation(self, res):
        from raft_tpu import serve

        db, idx = _mk(res)
        with pytest.raises(ValueError, match="nprobe"):
            serve.StreamingKnnService(idx, k=4, nprobe=8)
        db2, idx2 = _mk(res, seed=9)
        svc = serve.StreamingKnnService(idx2, k=4, nprobe=7)
        with pytest.raises(ValueError, match="different"):
            serve.IngestController(idx, [svc])

    def test_streaming_loop_recall_floor_across_swaps(self, res):
        """The CI gate's witness in miniature: sustained ingest +
        deletes racing concurrent queries through at least one
        shape-changing swap, recall scored per query against an exact
        reference over the snapshot window it was served from."""
        from raft_tpu import serve

        db, idx = _mk(res, n=256, repack_slack=48)
        idx.compact(reason="provision")
        svc = serve.StreamingKnnService(idx, k=5, nprobe=7)
        ctl = serve.IngestController(
            idx, [svc],
            policy=serve.BatchPolicy(max_batch=8, max_wait_ms=2.0),
            compact_interval=0.05, refit=False, warm_buckets=[8])
        with ctl:
            rep = serve.streaming_loop(
                ctl, svc.name, clients=3, rows=4, duration_s=2.0,
                ingest_rows=48, ingest_interval_s=0.02,
                delete_frac=0.3, seed=1)
        assert rep.failed == 0
        assert rep.queries > 0 and rep.ingest_batches >= 2
        assert rep.swaps >= 1, "the run must cross a shape swap"
        assert rep.min_recall >= 0.5, rep.as_dict()
        assert rep.mean_recall >= 0.85, rep.as_dict()
        assert rep.n_live_final == idx.n_live


# ---------------------------------------------------------------------------
# env knobs (satellite b)
# ---------------------------------------------------------------------------


class TestEnvKnobs:
    @pytest.mark.parametrize("name,bad,good,parsed", [
        ("RAFT_TPU_COMPACT_TOMBSTONE_FRAC", "1.5", "0.4", 0.4),
        ("RAFT_TPU_COMPACT_INTERVAL", "-1", "0.5", 0.5),
        ("RAFT_TPU_DRIFT_THRESHOLD", "0.5", "3.0", 3.0),
    ])
    def test_registered_fail_loud(self, monkeypatch, name, bad, good,
                                  parsed):
        monkeypatch.setenv(name, bad)
        with pytest.raises(ValueError, match=name):
            env.read(name)
        monkeypatch.setenv(name, good)
        assert env.read(name) == parsed

    def test_compactor_and_gauge_read_knobs(self, res, monkeypatch):
        monkeypatch.setenv("RAFT_TPU_COMPACT_INTERVAL", "7.5")
        monkeypatch.setenv("RAFT_TPU_COMPACT_TOMBSTONE_FRAC", "0.45")
        monkeypatch.setenv("RAFT_TPU_DRIFT_THRESHOLD", "4.0")
        db, idx = _mk(res)
        c = Compactor(idx)
        assert c.interval == 7.5 and c.tombstone_frac == 0.45
        assert DriftGauge().threshold == 4.0
