"""Stats layer tests vs NumPy/SciPy-style references
(ref test models: cpp/tests/stats/*)."""

import numpy as np
import pytest

from raft_tpu import stats


@pytest.fixture
def rng():
    return np.random.default_rng(7)


class TestMoments:
    def test_mean_sum_stddev(self, rng):
        x = rng.normal(size=(200, 8)).astype(np.float64)
        np.testing.assert_allclose(np.asarray(stats.mean(x)), x.mean(0),
                                   rtol=1e-12)
        np.testing.assert_allclose(np.asarray(stats.sum_(x, axis=1)),
                                   x.sum(1), rtol=1e-12)
        np.testing.assert_allclose(np.asarray(stats.stddev(x)),
                                   x.std(0, ddof=1), rtol=1e-10)
        np.testing.assert_allclose(
            np.asarray(stats.stddev(x, sample=False)), x.std(0), rtol=1e-10)

    def test_meanvar_center_add(self, rng):
        x = rng.normal(size=(64, 5))
        mu, var = stats.meanvar(x)
        np.testing.assert_allclose(np.asarray(mu), x.mean(0), rtol=1e-12)
        np.testing.assert_allclose(np.asarray(var), x.var(0, ddof=1),
                                   rtol=1e-10)
        c = stats.mean_center(x)
        np.testing.assert_allclose(np.asarray(c), x - x.mean(0), rtol=1e-12)
        back = stats.mean_add(c, mu)
        np.testing.assert_allclose(np.asarray(back), x, rtol=1e-12)

    def test_minmax(self, rng):
        x = rng.normal(size=(100, 4))
        lo, hi = stats.minmax(x)
        np.testing.assert_allclose(np.asarray(lo), x.min(0))
        np.testing.assert_allclose(np.asarray(hi), x.max(0))
        ids = np.array([0, 5, 9])
        lo, hi = stats.minmax(x, row_ids=ids)
        np.testing.assert_allclose(np.asarray(lo), x[ids].min(0))

    def test_cov(self, rng):
        x = rng.normal(size=(300, 6))
        np.testing.assert_allclose(np.asarray(stats.cov(x)),
                                   np.cov(x, rowvar=False), rtol=1e-10)

    def test_weighted_mean(self, rng):
        x = rng.normal(size=(50, 7))
        w_rows = rng.uniform(0.1, 1.0, size=50)
        w_cols = rng.uniform(0.1, 1.0, size=7)
        np.testing.assert_allclose(
            np.asarray(stats.col_weighted_mean(x, w_rows)),
            (x * w_rows[:, None]).sum(0) / w_rows.sum(), rtol=1e-12)
        np.testing.assert_allclose(
            np.asarray(stats.row_weighted_mean(x, w_cols)),
            (x * w_cols[None, :]).sum(1) / w_cols.sum(), rtol=1e-12)


class TestHistogram:
    @pytest.mark.parametrize("hist_type", [stats.HistType.Auto,
                                           stats.HistType.Gmem])
    def test_identity_binner(self, rng, hist_type):
        data = rng.integers(0, 16, size=(500, 3))
        h = np.asarray(stats.histogram(data, 16, hist_type=hist_type))
        expect = np.stack([np.bincount(data[:, c], minlength=16)
                           for c in range(3)], axis=1)
        np.testing.assert_array_equal(h, expect)

    def test_out_of_range_dropped(self):
        data = np.array([[-1], [0], [1], [99]])
        h = np.asarray(stats.histogram(data, 2))
        np.testing.assert_array_equal(h[:, 0], [1, 1])

    def test_custom_binner(self, rng):
        data = rng.uniform(0.0, 1.0, size=(400, 2))
        h = np.asarray(stats.histogram(
            data, 10, binner=lambda v, r, c: (v * 10).astype(np.int32)))
        expect = np.stack([np.histogram(data[:, c], bins=10,
                                        range=(0, 1))[0]
                           for c in range(2)], axis=1)
        np.testing.assert_array_equal(h, expect)

    @pytest.mark.parametrize("n_bins", [513, 777, 2048, 4096])
    def test_factored_path_matches_scatter(self, rng, n_bins):
        """Mid/large bin counts ride the factored hi/lo one-hot matmul
        (the scatter measured 1.4e8 items/s on chip); results must be
        bit-identical to the Gmem scatter path, incl. out-of-range
        drops."""
        data = rng.integers(-10, n_bins + 10, size=(3000, 3)).astype(
            np.float32)
        h_fac = np.asarray(stats.histogram(data, n_bins))
        h_sct = np.asarray(stats.histogram(data, n_bins,
                                           hist_type=stats.HistType.Gmem))
        np.testing.assert_array_equal(h_fac, h_sct)
        assert h_fac.shape == (n_bins, 3)

    # slow: the 257-row × 16384-col × 4096-bin sweep is ~30s of CPU
    # wall — off the tier-1 budget; the one-chunk factored tests above
    # cover the kernel there.
    @pytest.mark.slow
    def test_factored_multi_chunk_and_padding(self, rng):
        """The scan accumulation across row chunks INCLUDING a padded
        tail — the branch a one-chunk test never reaches. The chunk
        budget is (32<<20) // (n_cols * (128 + n_hi)): n_cols=16384 with
        n_bins=4096 (n_hi=32) gives chunk=12, so 257 rows span 22
        chunks with a 7-row pad."""
        data = rng.integers(-5, 4101, size=(257, 16384)).astype(
            np.float32)
        h_fac = np.asarray(stats.histogram(data, 4096))
        h_sct = np.asarray(stats.histogram(data, 4096,
                                           hist_type=stats.HistType.Gmem))
        np.testing.assert_array_equal(h_fac, h_sct)

    def test_factored_empty_input(self):
        h = np.asarray(stats.histogram(np.zeros((0, 3), np.float32),
                                       1000))
        assert h.shape == (1000, 3) and h.sum() == 0


class TestInformation:
    def test_entropy(self, rng):
        labels = rng.integers(0, 5, size=1000)
        p = np.bincount(labels, minlength=5) / 1000
        expect = -np.sum(p[p > 0] * np.log(p[p > 0]))
        got = float(stats.entropy(labels, lower=0, upper=5))
        np.testing.assert_allclose(got, expect, rtol=1e-10)

    def test_kl_divergence(self, rng):
        p = rng.uniform(0.1, 1.0, size=50)
        p /= p.sum()
        q = rng.uniform(0.1, 1.0, size=50)
        q /= q.sum()
        got = float(stats.kl_divergence(p, q))
        np.testing.assert_allclose(got, np.sum(p * np.log(p / q)),
                                   rtol=1e-10)

    @pytest.mark.parametrize("ic,expect_penalty", [
        (stats.IC_Type.AIC, 2.0 * 3),
        (stats.IC_Type.BIC, np.log(100) * 3),
        (stats.IC_Type.AICc, 2.0 * 3 + (2.0 * 3 * 4) / (100 - 3 - 1)),
    ])
    def test_information_criterion(self, ic, expect_penalty):
        ll = np.array([-50.0, -42.0])
        got = np.asarray(stats.information_criterion_batched(ll, ic, 3, 100))
        np.testing.assert_allclose(got, -2 * ll + expect_penalty, rtol=1e-12)

    def test_cluster_dispersion(self, rng):
        k, d = 8, 4
        centroids = rng.normal(size=(k, d))
        sizes = rng.integers(10, 100, size=k)
        n = sizes.sum()
        mu = (centroids * sizes[:, None]).sum(0) / n
        expect = np.sqrt(np.sum(sizes * ((centroids - mu) ** 2).sum(1)))
        got = float(stats.cluster_dispersion(centroids, sizes))
        np.testing.assert_allclose(got, expect, rtol=1e-10)


class TestClusteringMetrics:
    def test_contingency(self):
        a = np.array([0, 0, 1, 1, 2])
        b = np.array([1, 1, 0, 0, 0])
        c = np.asarray(stats.contingency_matrix(a, b))
        np.testing.assert_array_equal(c, [[0, 2], [2, 0], [1, 0]])

    def test_rand_index_perfect_and_known(self):
        a = np.array([0, 0, 1, 1])
        assert float(stats.rand_index(a, a)) == pytest.approx(1.0)
        b = np.array([0, 1, 0, 1])
        # pairs: C(4,2)=6; agreements = both-diff pairs = 4 -> RI = 1/3...
        # compute directly: disagree pairs are (0,1),(2,3) same-in-a diff-in-b
        # and (0,2),(1,3) diff-in-a same-in-b -> 4 disagreements, RI = 2/6.
        assert float(stats.rand_index(a, b)) == pytest.approx(2.0 / 6.0)

    def test_ari_matches_sklearn_formula(self, rng):
        a = rng.integers(0, 4, size=500)
        b = rng.integers(0, 3, size=500)
        got = float(stats.adjusted_rand_index(a, b))
        # independent labelings -> ARI near 0
        assert abs(got) < 0.05
        assert float(stats.adjusted_rand_index(a, a)) == pytest.approx(1.0)
        # label-permutation invariance
        perm = np.array([2, 0, 3, 1])
        assert float(stats.adjusted_rand_index(a, perm[a])) == pytest.approx(
            1.0)

    def test_mutual_info_and_vmeasure(self, rng):
        a = rng.integers(0, 4, size=400)
        # identical labelings: MI == H, h = c = v = 1
        mi = float(stats.mutual_info_score(a, a))
        h_a = float(stats.entropy(a, lower=0, upper=4))
        np.testing.assert_allclose(mi, h_a, rtol=1e-8)
        assert float(stats.homogeneity_score(a, a)) == pytest.approx(1.0)
        assert float(stats.completeness_score(a, a)) == pytest.approx(1.0)
        assert float(stats.v_measure(a, a)) == pytest.approx(1.0)
        # singleton clusters: perfectly homogeneous, poorly complete
        singletons = np.arange(400)
        assert float(stats.homogeneity_score(
            a, singletons, n_classes=400)) == pytest.approx(1.0)
        assert float(stats.completeness_score(
            a, singletons, n_classes=400)) < 0.6

    def test_silhouette(self, res):
        # two well-separated blobs -> silhouette near 1
        rng = np.random.default_rng(0)
        x0 = rng.normal(size=(50, 2)) * 0.1
        x1 = rng.normal(size=(50, 2)) * 0.1 + 10.0
        x = np.vstack([x0, x1]).astype(np.float32)
        labels = np.repeat([0, 1], 50)
        s = float(stats.silhouette_score(res, x, labels, n_clusters=2))
        assert s > 0.95
        # random labels -> near 0
        s_bad = float(stats.silhouette_score(
            res, x, rng.integers(0, 2, size=100), n_clusters=2))
        assert s_bad < 0.2


class TestRegressionMetrics:
    def test_accuracy(self):
        p = np.array([1, 2, 3, 4])
        r = np.array([1, 2, 0, 4])
        assert float(stats.accuracy(p, r)) == pytest.approx(0.75)

    def test_r2(self, rng):
        y = rng.normal(size=100)
        noise = rng.normal(size=100) * 0.1
        yh = y + noise
        expect = 1 - np.sum((y - yh) ** 2) / np.sum((y - y.mean()) ** 2)
        np.testing.assert_allclose(float(stats.r2_score(y, yh)), expect,
                                   rtol=1e-10)

    @pytest.mark.parametrize("n", [99, 100])
    def test_regression_metrics(self, rng, n):
        p = rng.normal(size=n)
        r = rng.normal(size=n)
        mae, mse, medae = stats.regression_metrics(p, r)
        np.testing.assert_allclose(float(mae), np.abs(p - r).mean(),
                                   rtol=1e-10)
        np.testing.assert_allclose(float(mse), ((p - r) ** 2).mean(),
                                   rtol=1e-10)
        np.testing.assert_allclose(float(medae), np.median(np.abs(p - r)),
                                   rtol=1e-10)


class TestNeighborhood:
    def test_recall_perfect_and_partial(self):
        idx = np.array([[0, 1, 2], [3, 4, 5]])
        assert float(stats.neighborhood_recall(idx, idx)) == pytest.approx(
            1.0)
        ref = np.array([[0, 1, 9], [9, 9, 9]])
        assert float(stats.neighborhood_recall(idx, ref)) == pytest.approx(
            2.0 / 6.0)

    def test_recall_distance_ties(self):
        idx = np.array([[0, 1]])
        ref = np.array([[0, 7]])  # index mismatch at slot 1
        d = np.array([[0.0, 1.0]])
        rd = np.array([[0.0, 1.0]])  # but identical distance -> tie counts
        assert float(stats.neighborhood_recall(
            idx, ref, distances=d, ref_distances=rd)) == pytest.approx(1.0)

    def test_trustworthiness_identity_embedding(self, res, rng):
        x = rng.normal(size=(120, 5)).astype(np.float32)
        t = float(stats.trustworthiness_score(res, x, x, n_neighbors=7))
        assert t == pytest.approx(1.0, abs=1e-6)

    def test_trustworthiness_vs_sklearn_formula(self, res, rng):
        # reference implementation in numpy
        x = rng.normal(size=(80, 6))
        emb = rng.normal(size=(80, 2))
        k = 5
        n = 80

        def knn_ranks(data):
            d = np.sqrt(((data[:, None, :] - data[None, :, :]) ** 2).sum(-1))
            np.fill_diagonal(d, np.inf)
            order = np.argsort(d, axis=1)
            ranks = np.empty_like(order)
            rows = np.arange(n)[:, None]
            ranks[rows, order] = np.arange(n - 1 + 1)[None, :]
            return d, order, ranks

        _, _, ranks_orig = knn_ranks(x)
        d_emb, order_emb, _ = knn_ranks(emb)
        nn_emb = order_emb[:, :k]
        rank1 = ranks_orig[np.arange(n)[:, None], nn_emb] + 1
        penalty = np.maximum(rank1 - k, 0).sum()
        expect = 1 - penalty * 2.0 / (n * k * (2 * n - 3 * k - 1))

        got = float(stats.trustworthiness_score(res, x, emb, n_neighbors=k,
                                                batch_size=32))
        np.testing.assert_allclose(got, expect, rtol=1e-6)


class TestSklearnCrossValidation:
    """Direct numeric cross-checks against scikit-learn (available in this
    image) — stronger than formula-identity tests: two independent
    implementations agreeing on random inputs (ref model: pylibraft test
    suites compare against sklearn/scipy the same way)."""

    @pytest.fixture
    def labels_pair(self):
        rng = np.random.default_rng(77)
        a = rng.integers(0, 6, size=2000).astype(np.int32)
        # correlated second labeling: 70% copied, 30% random
        b = np.where(rng.uniform(size=2000) < 0.7, a,
                     rng.integers(0, 5, size=2000)).astype(np.int32)
        return a, b

    def test_pair_metrics_vs_sklearn(self, labels_pair):
        import sklearn.metrics as skm

        a, b = labels_pair
        checks = [
            (stats.adjusted_rand_index, skm.adjusted_rand_score, {}),
            (stats.rand_index, skm.rand_score, {}),
            (stats.mutual_info_score, skm.mutual_info_score, {}),
            (stats.homogeneity_score, skm.homogeneity_score, {}),
            (stats.completeness_score, skm.completeness_score, {}),
            (stats.v_measure, skm.v_measure_score, {}),
        ]
        for ours, theirs, kw in checks:
            got = float(ours(a, b, **kw))
            want = float(theirs(a, b))
            assert got == pytest.approx(want, rel=1e-5), \
                (ours.__name__, got, want)

    def test_silhouette_vs_sklearn(self):
        import sklearn.metrics as skm

        from raft_tpu.distance.pairwise import DistanceType

        rng = np.random.default_rng(78)
        x = np.concatenate([rng.normal(size=(60, 8)) + off
                            for off in (0.0, 4.0, -4.0)]).astype(np.float32)
        labels = np.repeat(np.arange(3), 60).astype(np.int32)
        # sklearn roots its euclidean distances; our DEFAULT is squared L2
        # (the reference's DistanceType default) — pass the rooted metric
        # for an apples-to-apples check
        got = float(stats.silhouette_score(
            None, x, labels, n_clusters=3,
            metric=DistanceType.L2SqrtUnexpanded))
        want = float(skm.silhouette_score(x.astype(np.float64), labels))
        assert got == pytest.approx(want, rel=1e-4, abs=1e-4)

    def test_entropy_vs_scipy(self):
        from scipy.stats import entropy as scipy_entropy

        rng = np.random.default_rng(79)
        labels = rng.integers(0, 10, size=3000).astype(np.int32)
        got = float(stats.entropy(labels, lower=0, upper=10))
        counts = np.bincount(labels, minlength=10)
        want = float(scipy_entropy(counts / counts.sum()))
        assert got == pytest.approx(want, rel=1e-5)

    def test_trustworthiness_vs_sklearn(self):
        import sklearn.manifold as skman

        rng = np.random.default_rng(80)
        x = rng.normal(size=(120, 16)).astype(np.float32)
        emb = x[:, :2] + 0.05 * rng.normal(size=(120, 2)).astype(np.float32)
        got = float(stats.trustworthiness_score(None, x, emb,
                                                n_neighbors=7))
        want = float(skman.trustworthiness(x.astype(np.float64),
                                           emb.astype(np.float64),
                                           n_neighbors=7))
        assert got == pytest.approx(want, rel=1e-3, abs=1e-3)
