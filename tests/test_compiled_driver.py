"""Compiled solver inner loops (runtime/compiled_driver, ISSUE 8).

Covers the chunk-runner contract (in-graph early exit, cost-model
defaults, chunk-budget admission) and the wiring into both solver
families: ``sync_every=1`` must be bit-identical to the host-driven
seed paths, ``sync_every=8`` must converge to the same state in the
same number of iterations, chunk-boundary checkpoints must resume
bit-for-bit, and deadline expiry mid-fit must leave a loadable
checkpoint behind.
"""

import os
import tempfile

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import obs
from raft_tpu.core import trace
from raft_tpu.obs import metrics as obs_metrics
from raft_tpu.runtime import compiled_driver, limits


@pytest.fixture
def clean_events():
    trace.clear_events()
    yield
    trace.clear_events()


@pytest.fixture
def live_obs():
    was_enabled = obs.enabled()
    old_reg = obs_metrics.set_registry(obs.MetricsRegistry())
    obs.set_enabled(True)
    try:
        yield obs_metrics.get_registry()
    finally:
        obs.set_enabled(was_enabled)
        obs_metrics.set_registry(old_reg)


def _blobs(m=320, k=8, seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.normal(scale=6.0, size=(5, k))
    x = (centers[rng.integers(0, 5, m)]
         + rng.normal(size=(m, k))).astype(np.float32)
    return x


def _sym_csr(n=150, seed=0):
    import scipy.sparse as sp

    from raft_tpu.core.sparse_types import CSRMatrix

    a = sp.random(n, n, density=0.06, random_state=seed, format="csr",
                  dtype=np.float32)
    a = (a + a.T) * 0.5
    return CSRMatrix.from_scipy(sp.csr_matrix(a))


# ---------------------------------------------------------------------------
# chunk-runner unit contract
# ---------------------------------------------------------------------------


class TestChunkWhile:
    def test_early_exit_counts_executions(self):
        def step(c):
            c = c + 1
            return c, c >= 3

        @jax.jit
        def chunk(c, steps):
            return compiled_driver.chunk_while(step, c, steps)

        c, ran, done = chunk(jnp.asarray(0), jnp.asarray(10, jnp.int32))
        assert int(c) == 3 and int(ran) == 3 and bool(done)

    def test_traced_steps_serves_tail_chunk(self):
        def step(c):
            return c + 1, jnp.asarray(False)

        @jax.jit
        def chunk(c, steps):
            return compiled_driver.chunk_while(step, c, steps)

        for n in (8, 3):          # same executable, full + tail chunk
            _, ran, done = chunk(jnp.asarray(0), jnp.asarray(n, jnp.int32))
            assert int(ran) == n and not bool(done)


class TestSyncEveryPolicy:
    def test_cpu_defaults_to_host_driven(self):
        assert compiled_driver.default_sync_every(backend="cpu") == 1
        assert compiled_driver.resolve_sync_every(None, backend="cpu") == 1

    def test_accelerator_clamped_8_16(self):
        assert compiled_driver.default_sync_every(backend="tpu") == 16
        # slow step: overhead amortizes fast, clamp floor binds
        assert compiled_driver.default_sync_every(
            backend="tpu", step_seconds=1.0) == 8
        # fast step: overhead dominates, clamp ceiling binds
        assert compiled_driver.default_sync_every(
            backend="tpu", step_seconds=1e-5) == 16

    def test_explicit_value_validated(self):
        assert compiled_driver.resolve_sync_every(4) == 4
        with pytest.raises(ValueError):
            compiled_driver.resolve_sync_every(0)


class TestChunkBudget:
    def test_estimate_seconds_known_ops(self):
        s = limits.estimate_seconds("cluster.lloyd_step", backend="cpu",
                                    m=1000, k=64, n_clusters=32)
        assert s > 0.0
        s2 = limits.estimate_seconds("sparse.lanczos_restart",
                                     backend="cpu", n=1000, ncv=20,
                                     nnz=5000, k=4)
        assert s2 > 0.0

    def test_estimate_seconds_unknown_op_raises(self):
        with pytest.raises(ValueError, match="no seconds estimator"):
            limits.estimate_seconds("nope.unknown", backend="cpu", m=1)

    def test_fast_fail_before_launch(self, clean_events):
        """A chunk whose cost estimate exceeds the remaining slack must
        fail BEFORE launching (no chunk trace event)."""
        def chunk_call(carry, steps):     # pragma: no cover - must not run
            raise AssertionError("chunk launched past its budget")

        with limits.deadline_scope(1.0):
            with pytest.raises(limits.DeadlineExceededError):
                compiled_driver.run_chunked(
                    chunk_call, jnp.zeros(()), max_steps=100,
                    sync_every=10, op="test.budget",
                    est_step_seconds=100.0)
        assert not [e for e in trace.events()
                    if e["name"] == "compiled_driver.chunk"]

    def test_slack_recorded_at_boundaries(self, clean_events, live_obs):
        def step(c):
            return c + 1, jnp.asarray(False)

        @jax.jit
        def chunk(c, steps):
            return compiled_driver.chunk_while(step, c, steps)

        with limits.deadline_scope(60.0):
            compiled_driver.run_chunked(chunk, jnp.asarray(0),
                                        max_steps=8, sync_every=4,
                                        op="test.slack")
        snap = live_obs.snapshot()
        assert "deadline_slack_seconds" in snap
        # one observation per chunk boundary (2 chunks of 4), plus the
        # deadline_scope exit's own slack observation
        assert snap["deadline_slack_seconds"]["series"][0]["count"] == 3


# ---------------------------------------------------------------------------
# kmeans_fit / kmeans_fit_mnmg
# ---------------------------------------------------------------------------


class TestKMeansChunked:
    def test_sync1_bit_identical_and_hostdriven(self, clean_events):
        from raft_tpu.cluster.kmeans import KMeansParams, kmeans_fit

        x = _blobs()
        p = KMeansParams(n_clusters=5, seed=0, max_iter=25)
        c0, i0, l0, n0 = kmeans_fit(None, p, x)     # default: cpu -> 1
        c1, i1, l1, n1 = kmeans_fit(None, p, x, sync_every=1)
        assert np.asarray(c0).tobytes() == np.asarray(c1).tobytes()
        assert float(i0) == float(i1) and n0 == n1
        assert np.array_equal(np.asarray(l0), np.asarray(l1))
        # sync_every=1 IS the host-driven path: no chunk events at all
        assert not [e for e in trace.events()
                    if e["name"] == "compiled_driver.chunk"]

    def test_sync8_same_niter_allclose(self):
        from raft_tpu.cluster.kmeans import KMeansParams, kmeans_fit

        x = _blobs()
        p = KMeansParams(n_clusters=5, seed=0, max_iter=25)
        c1, i1, _, n1 = kmeans_fit(None, p, x, sync_every=1)
        c8, i8, _, n8 = kmeans_fit(None, p, x, sync_every=8)
        assert n1 == n8
        np.testing.assert_allclose(np.asarray(c1), np.asarray(c8),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(float(i1), float(i8), rtol=1e-5)

    def test_weighted_chunked_allclose(self):
        from raft_tpu.cluster.kmeans import KMeansParams, kmeans_fit

        x = _blobs()
        w = np.random.default_rng(3).uniform(0.5, 2.0,
                                             x.shape[0]).astype(np.float32)
        p = KMeansParams(n_clusters=5, seed=0, max_iter=25)
        c1, _, _, n1 = kmeans_fit(None, p, x, sample_weights=w,
                                  sync_every=1)
        c8, _, _, n8 = kmeans_fit(None, p, x, sample_weights=w,
                                  sync_every=8)
        assert n1 == n8
        np.testing.assert_allclose(np.asarray(c1), np.asarray(c8),
                                   rtol=1e-5, atol=1e-6)

    def test_host_sync_count_is_chunk_count(self, clean_events, live_obs):
        """32 never-converging iterations at sync_every=8 must touch the
        host exactly ceil(32/8) = 4 times (the CI regression gate for a
        reintroduced per-iteration block_until_ready)."""
        from raft_tpu.cluster.kmeans import KMeansParams, kmeans_fit

        x = _blobs()
        p = KMeansParams(n_clusters=5, seed=0, max_iter=32, tol=-1.0)
        _, _, _, n_iter = kmeans_fit(None, p, x, sync_every=8)
        assert n_iter == 32
        ev = [e for e in trace.events()
              if e["name"] == "compiled_driver.chunk"]
        assert len(ev) == 4
        assert sum(e["steps"] for e in ev) == 32
        snap = live_obs.snapshot()["solver_host_syncs_total"]["series"]
        counts = {tuple(s["labels"].items()): s["value"] for s in snap}
        assert counts[(("op", "cluster.kmeans_fit"),)] == 4

    def test_mnmg_sync1_bit_identical(self, mesh8, clean_events):
        from raft_tpu.cluster.kmeans import KMeansParams, kmeans_fit_mnmg

        x = _blobs()
        p = KMeansParams(n_clusters=8, seed=0, max_iter=20)
        c0, i0, _, n0 = kmeans_fit_mnmg(None, p, x, mesh=mesh8)
        c1, i1, _, n1 = kmeans_fit_mnmg(None, p, x, mesh=mesh8,
                                        sync_every=1)
        assert np.asarray(c0).tobytes() == np.asarray(c1).tobytes()
        assert n0 == n1
        assert not [e for e in trace.events()
                    if e["name"] == "compiled_driver.chunk"]

    def test_mnmg_chunked_allclose(self, mesh8):
        from raft_tpu.cluster.kmeans import KMeansParams, kmeans_fit_mnmg

        x = _blobs()
        p = KMeansParams(n_clusters=8, seed=0, max_iter=20)
        c1, _, _, n1 = kmeans_fit_mnmg(None, p, x, mesh=mesh8,
                                       sync_every=1)
        c8, _, _, n8 = kmeans_fit_mnmg(None, p, x, mesh=mesh8,
                                       sync_every=8)
        assert n1 == n8
        np.testing.assert_allclose(np.asarray(c1), np.asarray(c8),
                                   rtol=1e-5, atol=1e-6)

    def test_mnmg_checkpoint_boundary_resumes_bits(self, mesh8):
        """A checkpoint written at a chunk boundary resumes bit-for-bit
        on the same mesh — same executable, same state."""
        from raft_tpu.cluster.kmeans import KMeansParams, kmeans_fit_mnmg
        from raft_tpu.core.checkpoint import CheckpointManager

        x = _blobs()
        p = KMeansParams(n_clusters=8, seed=0, max_iter=20)
        with tempfile.TemporaryDirectory() as d:
            full = kmeans_fit_mnmg(None, p, x, mesh=mesh8, sync_every=4,
                                   checkpoint_every=1, checkpoint_dir=d,
                                   checkpoint_keep=16)
            # resume from a MID-fit boundary (step 4), not the final
            # checkpoint: the replayed iterations must land on the same
            # bits and the same iteration count
            pth = CheckpointManager(d, prefix="kmeans").path_for(4)
            assert os.path.exists(pth)
            res = kmeans_fit_mnmg(None, p, x, mesh=mesh8, sync_every=4,
                                  resume_from=pth)
        assert np.asarray(full[0]).tobytes() == np.asarray(res[0]).tobytes()
        assert full[3] == res[3]

    def test_mnmg_deadline_expiry_leaves_checkpoint(self, mesh8):
        """Deadline expiry mid-fit must leave a loadable checkpoint: the
        boundary hook saves BEFORE the deadline poll raises."""
        from raft_tpu.cluster.kmeans import KMeansParams, kmeans_fit_mnmg
        from raft_tpu.core.checkpoint import CheckpointManager

        x = _blobs()
        p = KMeansParams(n_clusters=8, seed=0, max_iter=50_000, tol=-1.0)
        with tempfile.TemporaryDirectory() as d:
            with pytest.raises(limits.DeadlineExceededError):
                with limits.deadline_scope(0.5):
                    kmeans_fit_mnmg(None, p, x, mesh=mesh8,
                                    sync_every=25, checkpoint_every=1,
                                    checkpoint_dir=d)
            latest = CheckpointManager(d, prefix="kmeans").restore_latest()
            assert latest is not None
            step, entries = latest
            assert step > 0 and entries["n_iter"] == step
            # and it actually resumes
            res = kmeans_fit_mnmg(
                None, KMeansParams(n_clusters=8, seed=0,
                                   max_iter=step + 4, tol=-1.0),
                x, mesh=mesh8, sync_every=4,
                resume_from=CheckpointManager(
                    d, prefix="kmeans").path_for(step))
            assert res[3] == step + 4

    def test_lazy_host_mirror_not_built_on_plain_fit(self, mesh8,
                                                     monkeypatch):
        """The common single-process MNMG fit must never materialize the
        host dataset copy (the eager np.asarray(x) it used to pay)."""
        from raft_tpu.cluster import kmeans as km

        def boom(self):        # pragma: no cover - failure is the test
            raise AssertionError("host mirror materialized on plain fit")

        monkeypatch.setattr(km._LazyHostMirror, "get", boom)
        x = _blobs()
        p = km.KMeansParams(n_clusters=8, seed=0, max_iter=5)
        km.kmeans_fit_mnmg(None, p, x, mesh=mesh8, sync_every=1)
        km.kmeans_fit_mnmg(None, p, x, mesh=mesh8, sync_every=4)

    def test_lazy_host_mirror_unit(self):
        from raft_tpu.cluster.kmeans import _LazyHostMirror

        m = _LazyHostMirror(jnp.arange(4))
        assert not m.built
        got = m.get()
        assert m.built and isinstance(got, np.ndarray)
        assert m.get() is got


# ---------------------------------------------------------------------------
# eigsh / eigsh_mnmg
# ---------------------------------------------------------------------------


class TestEigshChunked:
    def test_sync1_bit_identical(self, clean_events):
        from raft_tpu.sparse.solver.lanczos import eigsh

        csr = _sym_csr()
        w0, v0, r0 = eigsh(csr, k=4, maxiter=60, return_report=True)
        w1, v1, r1 = eigsh(csr, k=4, maxiter=60, sync_every=1,
                           return_report=True)
        assert np.asarray(w0).tobytes() == np.asarray(w1).tobytes()
        assert np.asarray(v0).tobytes() == np.asarray(v1).tobytes()
        assert r0.n_iter == r1.n_iter
        assert not [e for e in trace.events()
                    if e["name"] == "compiled_driver.chunk"]

    def test_sync8_same_niter_allclose(self):
        from raft_tpu.sparse.solver.lanczos import eigsh

        csr = _sym_csr()
        w1, v1, r1 = eigsh(csr, k=4, maxiter=60, sync_every=1,
                           return_report=True)
        w8, v8, r8 = eigsh(csr, k=4, maxiter=60, sync_every=8,
                           return_report=True)
        assert r1.n_iter == r8.n_iter
        assert r1.converged and r8.converged
        np.testing.assert_allclose(np.asarray(w1), np.asarray(w8),
                                   rtol=1e-5, atol=1e-6)
        for i in range(4):        # eigenvectors match up to sign
            a, b = np.asarray(v1)[:, i], np.asarray(v8)[:, i]
            s = np.sign(np.dot(a, b))
            np.testing.assert_allclose(a, s * b, rtol=1e-3, atol=2e-3)

    def test_dense_operator_chunked(self):
        from raft_tpu.sparse.solver.lanczos import eigsh

        rng = np.random.default_rng(0)
        a = rng.normal(size=(80, 80)).astype(np.float32)
        a = (a + a.T) * 0.5
        w1, _, r1 = eigsh(a, k=3, maxiter=60, sync_every=1,
                          return_report=True)
        w8, _, r8 = eigsh(a, k=3, maxiter=60, sync_every=8,
                          return_report=True)
        assert r1.n_iter == r8.n_iter
        np.testing.assert_allclose(np.asarray(w1), np.asarray(w8),
                                   rtol=1e-5, atol=1e-6)

    def test_mnmg_chunked_allclose(self, mesh8):
        from raft_tpu.sparse.solver.lanczos import eigsh_mnmg

        csr = _sym_csr()
        w1, v1, r1 = eigsh_mnmg(csr, k=4, mesh=mesh8, maxiter=60,
                                sync_every=1, return_report=True)
        w8, v8, r8 = eigsh_mnmg(csr, k=4, mesh=mesh8, maxiter=60,
                                sync_every=8, return_report=True)
        assert r1.n_iter == r8.n_iter
        np.testing.assert_allclose(np.asarray(w1), np.asarray(w8),
                                   rtol=1e-4, atol=1e-5)
        for i in range(4):
            a, b = np.asarray(v1)[:, i], np.asarray(v8)[:, i]
            s = np.sign(np.dot(a, b))
            np.testing.assert_allclose(a, s * b, rtol=1e-3, atol=2e-3)

    def test_mnmg_checkpoint_boundary_resumes_bits(self, mesh8):
        from raft_tpu.core.checkpoint import CheckpointManager
        from raft_tpu.sparse.solver.lanczos import eigsh_mnmg

        csr = _sym_csr()
        with tempfile.TemporaryDirectory() as d:
            full = eigsh_mnmg(csr, k=4, mesh=mesh8, maxiter=60,
                              sync_every=2, checkpoint_every=1,
                              checkpoint_dir=d, checkpoint_keep=16,
                              return_report=True)
            pth = CheckpointManager(d, prefix="eigsh").path_for(4)
            assert os.path.exists(pth)
            res = eigsh_mnmg(csr, k=4, mesh=mesh8, maxiter=60,
                             sync_every=2, resume_from=pth,
                             return_report=True)
        assert np.asarray(full[0]).tobytes() == np.asarray(res[0]).tobytes()
        assert np.asarray(full[1]).tobytes() == np.asarray(res[1]).tobytes()
        assert full[2].n_iter == res[2].n_iter
