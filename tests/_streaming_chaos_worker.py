"""Subprocess worker for the streaming SIGKILL crash-consistency
witness (tests/test_streaming.py + ci/smoke.sh chaos smoke).

Runs a fixed, deterministic mutation sequence against a journaled
:class:`~raft_tpu.neighbors.streaming.StreamingIndex`:

    build(seed) → insert 24 → delete every 3rd of ids 0..39
    → [arm ingest.* here] → insert 16 → [arm compact.* here] → compact

Modes:

``--run`` (default)
    Execute the sequence. With ``--crash NAME`` the named
    :meth:`FaultInjector.crash_point` is armed (``--mode kill``
    delivers a real SIGKILL — no atexit, no finally, torn files are
    whatever the OS kept). Without a crash, prints the three content
    CRCs the parent scores recovery against:
    ``after_delete after_insert2 final``.

``--recover``
    Recover the index from ``--dir`` twice (two independent
    :meth:`StreamingIndex.recover` calls) and print both CRCs — the
    parent asserts the recovered CRC equals a consistent pre/post
    state AND that replay is deterministic.

All CRC printing happens in subprocesses launched from the same
environment, so jax config (x64, platform) can never skew the
reference against the witness.
"""

import argparse
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402

N_DB, DIM, N_LISTS = 160, 8, 8


def _sequence(directory, crash=None, mode="kill"):
    from raft_tpu.comms.faults import FaultInjector
    from raft_tpu.neighbors import streaming

    faults = FaultInjector()
    rng = np.random.default_rng(7)
    db = rng.normal(size=(N_DB, DIM)).astype(np.float32)
    idx = streaming.stream_build(None, db, N_LISTS, seed=0,
                                 max_iter=4, directory=directory,
                                 faults=faults)
    idx.insert(rng.normal(size=(24, DIM)).astype(np.float32))
    idx.delete(np.arange(0, 40, 3))
    crc_after_delete = idx.content_crc()
    if crash and crash.startswith("ingest."):
        faults.arm_crash(crash, mode=mode)
    idx.insert(rng.normal(size=(16, DIM)).astype(np.float32))
    crc_after_insert2 = idx.content_crc()
    if crash and crash.startswith("compact."):
        faults.arm_crash(crash, mode=mode)
    idx.compact(reason="chaos")
    return crc_after_delete, crc_after_insert2, idx.content_crc()


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--dir", required=True)
    p.add_argument("--crash", default=None)
    p.add_argument("--mode", default="kill")
    p.add_argument("--recover", action="store_true")
    a = p.parse_args(argv)
    if a.recover:
        from raft_tpu.neighbors.streaming import StreamingIndex

        first = StreamingIndex.recover(None, a.dir).content_crc()
        second = StreamingIndex.recover(None, a.dir).content_crc()
        print(f"{first} {second}")
        return 0
    crcs = _sequence(a.dir, a.crash, a.mode)
    print(" ".join(str(c) for c in crcs))
    return 0


if __name__ == "__main__":
    sys.exit(main())
