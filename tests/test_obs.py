"""Tests for the observability subsystem (ISSUE 4): metrics registry
semantics, label cardinality cap, histogram bucketing, span parenting,
Prometheus/JSONL export schema, thread-safety under concurrent emitters,
and the metrics-off no-op identity. Also covers the trace.record_event
shim over the unified obs event ring."""

from __future__ import annotations

import io
import json
import math
import threading

import pytest

from raft_tpu import obs
from raft_tpu.core import trace
from raft_tpu.obs import metrics as obs_metrics
from raft_tpu.obs import schema as obs_schema


@pytest.fixture
def live_obs():
    """Metrics on, a fresh private registry, clean span/event state;
    everything restored afterwards so other tests see the default
    (off, empty) world."""
    was_enabled = obs.enabled()
    old_reg = obs_metrics.set_registry(obs.MetricsRegistry())
    old_sink = obs.set_sink(None)
    obs.set_enabled(True)
    obs.clear_spans()
    obs.clear_events()
    obs.set_sample_rate(1.0)
    try:
        yield obs_metrics.get_registry()
    finally:
        obs.set_enabled(was_enabled)
        obs_metrics.set_registry(old_reg)
        obs.set_sink(old_sink)
        obs.clear_spans()
        obs.clear_events()
        obs.set_sample_rate(1.0)


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------

class TestRegistry:
    def test_counter_get_or_create_and_inc(self, live_obs):
        fam = live_obs.counter("c_total", "help text", ("op",))
        fam.labels(op="a").inc()
        fam.labels(op="a").inc(2.5)
        fam.labels(op="b").inc()
        snap = live_obs.snapshot()["c_total"]
        by_op = {s["labels"]["op"]: s["value"] for s in snap["series"]}
        assert by_op == {"a": 3.5, "b": 1.0}
        # same name returns the same family object
        assert live_obs.counter("c_total", "help text", ("op",)) is fam

    def test_counter_rejects_negative(self, live_obs):
        fam = live_obs.counter("c2_total")
        with pytest.raises(ValueError, match="increase"):
            fam.labels().inc(-1)

    def test_gauge_set_inc_dec(self, live_obs):
        g = live_obs.gauge("g").labels()
        g.set(5)
        g.inc(2)
        g.dec(3)
        assert live_obs.snapshot()["g"]["series"][0]["value"] == 4.0

    def test_reregistration_conflicts_raise(self, live_obs):
        live_obs.counter("name1", labelnames=("a",))
        with pytest.raises(ValueError, match="already registered"):
            live_obs.gauge("name1", labelnames=("a",))
        with pytest.raises(ValueError, match="labels"):
            live_obs.counter("name1", labelnames=("b",))
        live_obs.histogram("h1", buckets=(1.0, 2.0))
        with pytest.raises(ValueError, match="buckets"):
            live_obs.histogram("h1", buckets=(1.0, 3.0))

    def test_label_schema_enforced(self, live_obs):
        fam = live_obs.counter("c3_total", labelnames=("op", "stage"))
        with pytest.raises(ValueError, match="expects labels"):
            fam.labels(op="x")          # missing 'stage'
        with pytest.raises(ValueError, match="expects labels"):
            fam.labels(op="x", other="y")

    def test_emit_helpers_autocreate(self, live_obs):
        obs.inc("auto_total", 2, op="x")
        obs.set_gauge("auto_gauge", 7.0)
        obs.observe("auto_hist", 0.5)
        snap = live_obs.snapshot()
        assert snap["auto_total"]["series"][0]["value"] == 2.0
        assert snap["auto_gauge"]["series"][0]["value"] == 7.0
        assert snap["auto_hist"]["series"][0]["count"] == 1


# ---------------------------------------------------------------------------
# cardinality cap
# ---------------------------------------------------------------------------

class TestCardinality:
    def test_overflow_collapses(self, live_obs):
        reg = obs.MetricsRegistry(max_series_per_family=3)
        fam = reg.counter("peers_total", labelnames=("peer",))
        for i in range(10):
            fam.labels(peer=f"host{i}").inc()
        snap = reg.snapshot()["peers_total"]
        # 3 real series + the single <overflow> series
        assert len(snap["series"]) == 4
        assert snap["dropped_series"] == 7
        over = [s for s in snap["series"]
                if s["labels"]["peer"] == "<overflow>"]
        assert len(over) == 1 and over[0]["value"] == 7.0

    def test_existing_series_unaffected_by_cap(self, live_obs):
        reg = obs.MetricsRegistry(max_series_per_family=2)
        fam = reg.counter("x_total", labelnames=("k",))
        fam.labels(k="a").inc()
        fam.labels(k="b").inc()
        fam.labels(k="c").inc()        # rerouted
        fam.labels(k="a").inc()        # still lands on the real series
        snap = {s["labels"]["k"]: s["value"]
                for s in reg.snapshot()["x_total"]["series"]}
        assert snap["a"] == 2.0 and snap["b"] == 1.0


# ---------------------------------------------------------------------------
# histogram bucketing
# ---------------------------------------------------------------------------

class TestHistogram:
    def test_log_buckets_shape(self):
        b = obs.log_buckets(1e-3, 1e3, per_decade=1)
        assert b[0] == pytest.approx(1e-3)
        assert b[-1] == pytest.approx(1e3)
        assert len(b) == 7
        assert list(b) == sorted(b)
        with pytest.raises(ValueError):
            obs.log_buckets(0.0, 1.0)
        with pytest.raises(ValueError):
            obs.log_buckets(1.0, 1.0)

    def test_observation_lands_in_first_le_bucket(self, live_obs):
        fam = live_obs.histogram("h_test", buckets=(0.1, 1.0, 10.0))
        child = fam.labels()
        for v in (0.05, 0.5, 0.5, 5.0, 50.0):
            child.observe(v)
        # bucket_counts are per-slot (non-cumulative): (0.1, 1, 10, +Inf)
        assert child.bucket_counts == [1, 2, 1, 1]
        assert child.count == 5
        assert child.sum == pytest.approx(56.05)

    def test_boundary_goes_to_its_own_bucket(self, live_obs):
        # le semantics: an observation equal to a bound belongs to it
        child = live_obs.histogram("h_edge", buckets=(1.0, 2.0)).labels()
        child.observe(1.0)
        assert child.bucket_counts == [1, 0, 0]

    def test_nonfinite_counts_but_does_not_poison_sum(self, live_obs):
        child = live_obs.histogram("h_nan", buckets=(1.0,)).labels()
        child.observe(math.nan)
        child.observe(math.inf)
        child.observe(0.5)
        assert child.count == 3
        assert child.bucket_counts == [1, 2]   # both non-finite in +Inf
        assert math.isfinite(child.sum) and child.sum == pytest.approx(0.5)


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------

class TestSpans:
    def test_span_records_duration_and_attrs(self, live_obs):
        with obs.span("work", n=3) as sp:
            sp.set_attr(extra="yes")
        recs = obs.spans("work")
        assert len(recs) == 1
        rec = recs[0]
        assert rec["duration"] >= 0
        assert rec["attrs"] == {"n": 3, "extra": "yes"}
        assert rec["parent"] is None

    def test_span_parents_off_range_stack(self, live_obs):
        with trace.push_range("outer"):
            with obs.span("child"):
                pass
        assert obs.spans("child")[0]["parent"] == "outer"

    def test_nested_spans_parent_each_other(self, live_obs):
        with obs.span("outer"):
            # the enclosing span is on the range stack, so events and
            # inner spans attribute to it
            trace.record_event("tick")
            with obs.span("inner"):
                pass
        assert obs.spans("inner")[0]["parent"] == "outer"
        assert obs.spans("outer")[0]["parent"] is None
        ev = trace.events("tick")[-1]
        assert ev["range"] == "outer"
        assert "outer" in ev["range_stack"]

    def test_span_error_attr_on_exception(self, live_obs):
        with pytest.raises(RuntimeError):
            with obs.span("boom"):
                raise RuntimeError("x")
        assert obs.spans("boom")[0]["attrs"]["error"] == "RuntimeError"
        # the range stack is unwound despite the exception
        assert trace.current_range() is None

    def test_sampling_stride(self, live_obs):
        obs.set_sample_rate(0.5)      # keep every 2nd span per name
        for _ in range(10):
            with obs.span("sampled"):
                pass
        assert len(obs.spans("sampled")) == 5
        obs.set_sample_rate(0.0)      # drop everything
        for _ in range(5):
            with obs.span("dropped"):
                pass
        assert obs.spans("dropped") == []

    def test_retention_bound(self, live_obs):
        obs.set_retention(4)
        try:
            for i in range(10):
                with obs.span("ring", i=i):
                    pass
            recs = obs.spans("ring")
            assert len(recs) == 4
            assert [r["attrs"]["i"] for r in recs] == [6, 7, 8, 9]
        finally:
            obs.set_retention(2048)


# ---------------------------------------------------------------------------
# export: snapshot, Prometheus, JSONL
# ---------------------------------------------------------------------------

class TestExport:
    def test_snapshot_is_json_serializable(self, live_obs):
        obs.inc("snap_total", op="a")
        obs.observe("snap_seconds", 0.01)
        with obs.span("s"):
            pass
        snap = obs.snapshot()
        json.dumps(snap)    # must not raise
        assert snap["enabled"] is True
        assert snap["metrics"]["snap_total"]["type"] == "counter"
        assert snap["spans_retained"] == 1

    def test_prometheus_rendering(self, live_obs):
        obs.inc("req_total", 3, help="requests", op="get")
        obs.observe("lat_seconds", 0.5, buckets=(0.1, 1.0))
        text = obs.render_prometheus()
        assert "# HELP req_total requests\n" in text
        assert "# TYPE req_total counter\n" in text
        assert 'req_total{op="get"} 3\n' in text
        assert "# TYPE lat_seconds histogram\n" in text
        # cumulative le buckets + +Inf, then sum/count
        assert 'lat_seconds_bucket{le="0.1"} 0\n' in text
        assert 'lat_seconds_bucket{le="1.0"} 1\n' in text
        assert 'lat_seconds_bucket{le="+Inf"} 1\n' in text
        assert "lat_seconds_sum 0.5\n" in text
        assert "lat_seconds_count 1\n" in text

    def test_prometheus_label_escaping(self, live_obs):
        obs.inc("esc_total", 1, op='a"b\nc\\d')
        text = obs.render_prometheus()
        assert r'esc_total{op="a\"b\nc\\d"} 1' in text

    def test_jsonl_sink_stream_is_schema_valid(self, live_obs, tmp_path):
        path = tmp_path / "events.jsonl"
        old = obs.set_sink(obs.JsonlSink(str(path)))
        try:
            trace.record_event("comms.retry", attempt=1)
            with trace.push_range("solver"):
                with obs.span("iteration", k=2):
                    pass
        finally:
            sink = obs.set_sink(old)
            sink.close()
        n_ok, problems = obs_schema.validate_jsonl(str(path))
        assert problems == []
        assert n_ok == 2
        lines = [json.loads(l) for l in path.read_text().splitlines()]
        kinds = {l["kind"] for l in lines}
        assert kinds == {"event", "span"}
        span_rec = next(l for l in lines if l["kind"] == "span")
        assert span_rec["parent"] == "solver"

    def test_schema_rejects_malformed(self):
        assert obs_schema.validate_record([1, 2]) != []
        assert obs_schema.validate_record({"kind": "nope"}) != []
        assert obs_schema.validate_record(
            {"kind": "span", "name": "", "ts": 0, "t": 0,
             "duration": -1, "parent": None, "attrs": {}}) != []
        ok_event = {"kind": "event", "name": "e", "ts": 1.0, "t": 2.0,
                    "range": None, "range_stack": []}
        assert obs_schema.validate_record(ok_event) == []

    def test_jsonl_sink_json_safe_fallback(self, live_obs):
        buf = io.StringIO()
        sink = obs.JsonlSink(buf)
        sink.write({"name": "x", "obj": object(), "tup": (1, 2)})
        rec = json.loads(buf.getvalue())
        assert rec["tup"] == [1, 2]
        assert isinstance(rec["obj"], str)


# ---------------------------------------------------------------------------
# trace shim unification
# ---------------------------------------------------------------------------

class TestTraceShim:
    def test_trace_and_obs_share_one_ring(self):
        trace.clear_events()
        trace.record_event("via.trace", a=1)
        obs.emit_event("via.obs", b=2)
        names = [e["name"] for e in trace.events()]
        assert names == ["via.trace", "via.obs"]
        assert trace.events() == obs.events()
        obs.clear_events()
        assert trace.events() == []

    def test_event_record_shape_unchanged(self):
        trace.clear_events()
        with trace.push_range("r1"):
            trace.record_event("shaped", code=7)
        ev = trace.events("shaped")[-1]
        assert ev["range"] == "r1"
        assert ev["range_stack"] == ("r1",)
        assert ev["code"] == 7
        assert isinstance(ev["t"], float)
        trace.clear_events()

    def test_ring_lives_with_metrics_off(self):
        # error-path observability is not gated by RAFT_TPU_METRICS
        assert not obs.enabled()
        trace.clear_events()
        trace.record_event("always.on")
        assert len(trace.events("always.on")) == 1
        trace.clear_events()


# ---------------------------------------------------------------------------
# thread safety
# ---------------------------------------------------------------------------

class TestThreadSafety:
    def test_concurrent_counter_increments_are_exact(self, live_obs):
        n_threads, n_iter = 8, 500

        def work():
            for _ in range(n_iter):
                obs.inc("race_total", op="x")

        threads = [threading.Thread(target=work) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        snap = live_obs.snapshot()["race_total"]
        assert snap["series"][0]["value"] == n_threads * n_iter

    def test_concurrent_histograms_and_spans(self, live_obs):
        n_threads, n_iter = 4, 200
        errors = []

        def work(i):
            try:
                for k in range(n_iter):
                    obs.observe("h_race", 0.001 * (k + 1), op=str(i % 2))
                    with obs.span("t_span", worker=i):
                        pass
            except Exception as e:   # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=work, args=(i,))
                   for i in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert errors == []
        snap = live_obs.snapshot()["h_race"]
        assert sum(s["count"] for s in snap["series"]) \
            == n_threads * n_iter
        # spans survived concurrent recording (ring is bounded at 2048
        # >= 800 total, all retained at rate 1.0)
        assert len(obs.spans("t_span")) == n_threads * n_iter


# ---------------------------------------------------------------------------
# metrics-off identity
# ---------------------------------------------------------------------------

class TestOffIdentity:
    def test_off_is_default_and_emits_nothing(self):
        assert not obs.enabled()
        old = obs_metrics.set_registry(obs.MetricsRegistry())
        try:
            obs.inc("ghost_total")
            obs.set_gauge("ghost_gauge", 1.0)
            obs.observe("ghost_seconds", 0.1)
            obs.record_convergence("ghost", None)
            assert obs_metrics.get_registry().snapshot() == {}
        finally:
            obs_metrics.set_registry(old)

    def test_off_span_is_shared_null(self):
        # note: `from raft_tpu.obs import spans` would resolve to the
        # re-exported *function*, not the submodule
        import importlib
        spans_mod = importlib.import_module("raft_tpu.obs.spans")
        assert not obs.enabled()
        s1 = obs.span("a", k=1)
        s2 = obs.span("b")
        assert s1 is s2 is spans_mod._NULL
        with s1 as sp:
            sp.set_attr(x=1)       # accepted, discarded
        assert obs.spans() == []
        # and it never touches the range stack
        with obs.span("c"):
            assert trace.current_range() is None

    def test_cached_children_noop_after_disable(self, live_obs):
        fam = live_obs.counter("flip_total")
        child = fam.labels()
        child.inc()
        obs.set_enabled(False)
        child.inc(100)             # cached handle must go dead too
        obs.set_enabled(True)
        assert live_obs.snapshot()["flip_total"]["series"][0]["value"] == 1.0


# ---------------------------------------------------------------------------
# record_convergence
# ---------------------------------------------------------------------------

class TestRecordConvergence:
    def test_report_feeds_solver_families(self, live_obs):
        from raft_tpu.core.guards import ConvergenceReport
        rep = ConvergenceReport(converged=True, n_iter=12, residual=1e-9,
                                tol=1e-8)
        obs.record_convergence("test.solver", rep)
        snap = live_obs.snapshot()
        assert snap["solver_iterations_total"]["series"][0]["value"] == 12
        runs = snap["solver_runs_total"]["series"][0]
        assert runs["labels"] == {"converged": "true", "solver":
                                  "test.solver"}
        assert runs["value"] == 1.0
        res = snap["solver_residual"]["series"][0]
        assert res["count"] == 1 and res["sum"] == pytest.approx(1e-9)
