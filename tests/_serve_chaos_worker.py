"""Worker for the multiprocess sharded-serving chaos test (ISSUE 11
acceptance: a rank SIGKILL'd mid-query-stream leaves the survivors
answering, with the repacked index bit-equal to a fresh build on the
survivor count).

Each worker is one serving rank of a cross-process clique: it builds
the SAME flat IVF index deterministically, holds its shard of the
rank-count partition, and per query iteration runs ``search_local``,
exchanges the raw (keys, ids) candidate pools all-to-all over a
TcpMailbox — the transport that outlives a SIGKILL'd peer, unlike an
XLA collective — and merges with ``merge_pool``. Fast heartbeats keep
the detect → abort → consensus → shrink → repack round-trip inside the
test budget.

Usage: python _serve_chaos_worker.py <rank> <mode> <addr0> <addr1> ...

mode "faulted": the highest rank SIGKILLs itself at iteration KILL_AT
(after its local probe, before sending); survivors recover and redo
that iteration on the shrunken clique.
mode "clean": no failures — the reference run the survivors' results
must match bit-for-bit.
"""

import os
import signal
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["XLA_FLAGS"] = ("--xla_force_host_platform_device_count=8 "
                           + os.environ.get("XLA_FLAGS", ""))

KILL_AT = 4
N_ITER = 8
N_DB, DIM, N_LISTS, K, NPROBE, Q_ROWS = 512, 12, 8, 6, 3, 8
_TAG0 = 1000


def dataset():
    import numpy as np

    rng = np.random.default_rng(7)
    return rng.standard_normal((N_DB, DIM)).astype(np.float32)


def queries(it):
    import numpy as np

    rng = np.random.default_rng(100 + it)
    return rng.standard_normal((Q_ROWS, DIM)).astype(np.float32)


def main():
    rank = int(sys.argv[1])
    mode = sys.argv[2]
    addrs = sys.argv[3:]
    nranks = len(addrs)

    import numpy as np

    import raft_tpu
    from raft_tpu.comms.comms import MeshComms
    from raft_tpu.comms.errors import (CommsAbortedError,
                                       CommsTimeoutError,
                                       PeerFailedError)
    from raft_tpu.comms.tcp_mailbox import TcpMailbox
    from raft_tpu.neighbors import ivf_flat
    from raft_tpu.neighbors.ivf_mnmg import (build_mnmg, merge_pool,
                                             search_local, shrink_mnmg)

    import jax
    from jax.sharding import Mesh

    box = TcpMailbox(rank, addrs, heartbeat_interval=0.3,
                     heartbeat_timeout=1.5, default_recv_timeout=60.0)
    mesh = Mesh(np.asarray(jax.devices()[:nranks]), axis_names=("data",))
    comms = MeshComms(mesh, "data", rank, _mailbox=box)

    res = raft_tpu.device_resources(seed=42)
    db = dataset()
    # every rank trains the identical coarse quantizer (same inputs,
    # same seed, same platform) — the partition is then a pure function
    # of (caps, n_ranks), so all ranks agree on shard ownership without
    # exchanging a byte of index data
    flat = ivf_flat.build(res, db, N_LISTS, seed=0, max_iter=4)
    idx = build_mnmg(res, db, N_LISTS, nranks, flat=flat)

    import zlib

    res_crc = 0
    recovery_s = 0.0
    it = 0
    while it < N_ITER:
        q = queries(it)
        my = comms.get_rank()
        n = comms.get_size()
        vals, ids = search_local(idx, my, q, k=K, nprobe=NPROBE)
        vals = np.ascontiguousarray(vals)
        ids = np.ascontiguousarray(ids)
        if mode == "faulted" and rank == nranks - 1 and it == KILL_AT:
            print("SERVE_CHAOS_SUICIDE", flush=True)
            sys.stdout.flush()
            os.kill(os.getpid(), signal.SIGKILL)
        try:
            tag = _TAG0 + 4 * it
            for peer in range(n):
                if peer != my:
                    comms.isend(vals, peer, tag)
                    comms.isend(ids, peer, tag + 1)
            pool_v = [None] * n
            pool_i = [None] * n
            pool_v[my], pool_i[my] = vals, ids
            for peer in range(n):
                if peer == my:
                    continue
                pool_v[peer] = np.asarray(
                    comms.irecv(peer, tag).wait())
                pool_i[peer] = np.asarray(
                    comms.irecv(peer, tag + 1).wait())
        except (PeerFailedError, CommsTimeoutError,
                CommsAbortedError) as e:
            t0 = time.monotonic()
            if not isinstance(e, CommsAbortedError):
                # first detector poisons the clique so peers blocked in
                # their own recv wake NOW (kmeans_fit_elastic discipline)
                comms.abort(f"serve chaos: {e}")
            time.sleep(2.0 * comms.heartbeat_interval)
            comms.clear_abort()
            survivors = comms.agree_on_survivors()
            comms = comms.shrink(survivors)
            # repack: bit-equal to a fresh build at the survivor count
            idx = shrink_mnmg(idx, survivors)
            recovery_s = time.monotonic() - t0
            continue                        # redo this iteration
        d, i = merge_pool(np.stack(pool_v), np.stack(pool_i),
                          k=K, metric=idx.metric)
        res_crc = zlib.crc32(np.ascontiguousarray(d).tobytes(), res_crc)
        res_crc = zlib.crc32(np.ascontiguousarray(i).tobytes(), res_crc)
        it += 1

    idx_crc = 0
    for arr in (idx.packed_db_sh, idx.packed_ids_sh, idx.starts_sh,
                idx.sizes_sh):
        idx_crc = zlib.crc32(
            np.ascontiguousarray(np.asarray(arr)).tobytes(), idx_crc)
    print(f"SERVE_CHAOS_OK rank={rank} size={comms.get_size()} "
          f"n_iter={it} idx_crc={idx_crc} res_crc={res_crc} "
          f"recovery_s={recovery_s:.3f}", flush=True)
    box.close()


if __name__ == "__main__":
    main()
