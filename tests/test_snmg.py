"""SNMG handle tests (ref test model: the reference exercises
device_resources_snmg via its SNMG clique init,
core/device_resources_snmg.hpp:102-126)."""

import numpy as np
import pytest

from raft_tpu.core.resources import DeviceResourcesSNMG, get_comms


class TestSNMG:
    def test_rank_loop(self, mesh8):
        snmg = DeviceResourcesSNMG(devices=list(mesh8.devices.ravel()))
        assert snmg.n_ranks == 8
        for rank, child in enumerate(snmg):
            view = get_comms(child)
            assert view.get_rank() == rank
            assert view.get_size() == 8

    def test_root_comms_and_noop_pool(self, mesh8):
        snmg = DeviceResourcesSNMG(devices=list(mesh8.devices.ravel()))
        assert get_comms(snmg).get_rank() == 0
        snmg.set_memory_pool(80)   # parity no-op

    def test_collective_through_rank_views(self, mesh8):
        from raft_tpu.comms import perform_test_comms_allreduce

        snmg = DeviceResourcesSNMG(devices=list(mesh8.devices.ravel()))
        assert perform_test_comms_allreduce(get_comms(snmg.rank_resources(3)))

    def test_empty_devices_rejected(self):
        with pytest.raises(ValueError):
            DeviceResourcesSNMG(devices=[])
