"""End-to-end request tracing + flight recorder tests (ISSUE 10):
context propagation through a real coalesced batch, cross-rank trace_id
equality over the TCP transport, forced-fault flight dumps that
schema-validate, tracing-off bit-identity on the serve paths, concurrent
mint uniqueness, schema round-trips for the new record shapes, and the
fail-loud span env knobs."""

import json
import os
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from raft_tpu import obs, serve
from raft_tpu.obs import schema
from raft_tpu.obs import metrics as obs_metrics
from raft_tpu.obs import tracectx
from raft_tpu.comms.errors import PeerFailedError
from raft_tpu.comms.tcp_mailbox import TcpMailbox
from raft_tpu.runtime import limits

DIM = 16

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def live_tracing():
    """Metrics + tracing on with fresh private state; restored after."""
    was_enabled = obs.enabled()
    was_tracing = obs.tracing_enabled()
    old_reg = obs_metrics.set_registry(obs.MetricsRegistry())
    old_sink = obs.set_sink(None)
    old_dir = obs.set_flight_dir(None)
    obs.set_enabled(True)
    obs.set_tracing(True)
    obs.clear_spans()
    obs.clear_events()
    obs.clear_flight_bundles()
    prev_ctx = obs.adopt(None)
    try:
        yield obs_metrics.get_registry()
    finally:
        obs.adopt(prev_ctx)
        obs.set_enabled(was_enabled)
        obs.set_tracing(was_tracing)
        obs_metrics.set_registry(old_reg)
        obs.set_sink(old_sink)
        obs.set_flight_dir(old_dir)
        obs.clear_flight_bundles()
        obs.clear_spans()
        obs.clear_events()


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(11)
    return {
        "db": rng.standard_normal((96, DIM)).astype(np.float32),
        "centroids": rng.standard_normal((5, DIM)).astype(np.float32),
        "corpus": rng.standard_normal((48, DIM)).astype(np.float32),
    }


def _queries(seed, rows):
    return (np.random.default_rng(seed)
            .standard_normal((rows, DIM)).astype(np.float32))


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


# -- context primitives -----------------------------------------------------


class TestTraceContext:
    def test_mint_off_is_none(self):
        was = obs.tracing_enabled()
        obs.set_tracing(False)
        try:
            assert obs.mint() is None
            assert obs.current_context() is None
        finally:
            obs.set_tracing(was)

    def test_header_round_trip(self, live_tracing):
        c = obs.mint(tenant="a,b:\"c\"")     # delimiter-hostile tenant
        assert obs.TraceContext.from_header(c.to_header()) == c

    @pytest.mark.parametrize("bad", [
        "", "{", "[]", "[\"a\",\"b\"]", "[\"a\",\"b\",\"\"]",
        "[\"a\",\"b\",3]", "[\"a\",\"b\",\"c\",\"d\"]", "nope",
    ])
    def test_malformed_header_raises(self, bad):
        with pytest.raises(ValueError):
            obs.TraceContext.from_header(bad)

    def test_use_context_scoped_and_none_noop(self, live_tracing):
        outer = obs.mint()
        inner = obs.mint(trace_id=outer.trace_id)
        assert inner.trace_id == outer.trace_id
        assert inner.request_id != outer.request_id
        with obs.use_context(outer):
            assert obs.current_context() is outer
            with obs.use_context(inner):
                assert obs.current_context() is inner
            with obs.use_context(None):     # true no-op
                assert obs.current_context() is outer
            assert obs.current_context() is outer
        assert obs.current_context() is None

    def test_concurrent_mint_uniqueness(self, live_tracing):
        """8 threads x 200 mints: every trace_id / request_id distinct,
        and each thread's adopted context never leaks to another."""
        n_threads, n_each = 8, 200
        ids, errs = [], []
        lock = threading.Lock()

        def worker(i):
            try:
                mine = []
                for _ in range(n_each):
                    c = obs.mint(tenant=f"t{i}")
                    with obs.use_context(c):
                        cur = obs.current_context()
                        assert cur is c and cur.tenant == f"t{i}"
                        mine.append((c.trace_id, c.request_id))
                assert obs.current_context() is None
                with lock:
                    ids.extend(mine)
            except BaseException as e:  # noqa: BLE001 — surface in main
                with lock:
                    errs.append(e)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not errs, errs
        assert len(ids) == n_threads * n_each
        assert len({t for t, _ in ids}) == len(ids)
        assert len({r for _, r in ids}) == len(ids)


# -- serve propagation ------------------------------------------------------


class TestServePropagation:
    def test_coalesced_batch_links_every_request(self, live_tracing, data):
        """A real coalesced batch: the serve.batch span names every
        member request_id, and each request gets a consistent
        request/queue_wait/execute span family carrying its context."""
        ex = serve.Executor(
            [serve.KnnService(data["db"], k=4)],
            policy=serve.BatchPolicy(max_batch=64, max_wait_ms=20.0))
        ex.warm()
        obs.clear_spans()
        with ex:
            futs = [ex.submit("knn_k4_l2", _queries(s, 4),
                              tenant=f"tn{s % 2}") for s in range(5)]
            for f in futs:
                f.result(timeout=30)
        batch_spans = obs.spans("serve.batch")
        assert batch_spans, "no serve.batch span recorded"
        linked = [rid for b in batch_spans
                  for rid in b["attrs"]["request_ids"]]
        assert len(linked) == 5 and len(set(linked)) == 5

        req_spans = obs.spans("serve.request")
        assert len(req_spans) == 5
        by_rid = {s["request_id"]: s for s in req_spans}
        assert set(by_rid) == set(linked)
        waits = obs.spans("serve.queue_wait")
        execs = obs.spans("serve.execute")
        assert len(waits) == 5 and len(execs) == 5
        for fam in (waits, execs):
            for s in fam:
                assert s["parent"] == "serve.request"
                parent = by_rid[s["request_id"]]
                assert s["trace_id"] == parent["trace_id"]
                assert s["thread"] == parent["thread"]
        # the wait/execute split covers the request span
        for rid, parent in by_rid.items():
            w = next(s for s in waits if s["request_id"] == rid)
            e = next(s for s in execs if s["request_id"] == rid)
            assert w["duration"] + e["duration"] <= \
                parent["duration"] + 1e-6
        # and the histogram metered the queue side of the split
        fam = live_tracing.snapshot().get("serve_queue_wait_seconds")
        assert fam and fam["series"][0]["count"] == 5

    def test_tenant_rides_context(self, live_tracing, data):
        ex = serve.Executor([serve.KnnService(data["db"], k=4)])
        ex.warm()
        obs.clear_spans()
        with ex:
            ex.submit("knn_k4_l2", _queries(0, 4),
                      tenant="gold").result(timeout=30)
        (span,) = obs.spans("serve.request")
        assert span["tenant"] == "gold"

    def test_slo_outcomes_and_burn_rate(self, live_tracing, data):
        qos = serve.QosPolicy({"gold": serve.TenantPolicy(
            weight=2.0, slo_latency_s=1e-6, slo_target=0.9)})
        ex = serve.Executor([serve.KnnService(data["db"], k=4)], qos=qos)
        ex.warm()
        with ex:
            for s in range(3):
                ex.submit("knn_k4_l2", _queries(s, 4),
                          tenant="gold").result(timeout=30)
        # 1 microsecond objective: every completion is a violation
        snap = live_tracing.snapshot()
        fam = snap["slo_requests_total"]
        got = [s["value"] for s in fam["series"]
               if s["labels"] == {"tenant": "gold",
                                  "outcome": "violation"}]
        assert got == [3]
        burn = snap["slo_burn_rate"]["series"][0]["value"]
        assert burn == pytest.approx(1.0 / (1.0 - 0.9))
        slo = qos.slo_snapshot()["gold"]
        assert slo["window_requests"] == 3 and slo["window_bad"] == 3

    def test_loadgen_report_carries_slo_and_obs(self, live_tracing, data):
        qos = serve.QosPolicy(default=serve.TenantPolicy(
            slo_latency_s=10.0))
        ex = serve.Executor([serve.KnnService(data["db"], k=4)], qos=qos)
        ex.warm()
        with ex:
            rep = serve.closed_loop(ex, "knn_k4_l2", clients=2, rows=4,
                                    duration_s=0.3)
        assert rep.completed > 0
        d = rep.as_dict()
        assert "obs" in d and d["obs"]["enabled"]
        assert "serve_requests_total" in d["obs"]["metrics"]
        assert d["slo"]["default"]["window_requests"] >= rep.completed


# -- tracing-off bit-identity ------------------------------------------------


class TestTracingOffBitIdentity:
    def test_serve_outputs_bit_identical_and_ctx_free(self, data):
        """With metrics AND tracing off, served results equal the eager
        reference exactly and no context is ever minted."""
        assert not obs.enabled() and not obs.tracing_enabled()
        services = [serve.KnnService(data["db"], k=4),
                    serve.PairwiseService(data["corpus"]),
                    serve.KMeansPredictService(data["centroids"])]
        ex = serve.Executor(services)
        ex.warm()
        seen = []
        orig_dispatch = ex.dispatch

        def spy(batch):
            seen.extend(batch.requests)
            orig_dispatch(batch)

        ex.dispatch = spy
        q = _queries(3, 6)
        with ex:
            outs = {svc.name: ex.submit(svc.name, q).result(timeout=30)
                    for svc in services}
        assert seen and all(r.ctx is None for r in seen)
        for svc in services:
            ref = svc.eager(q)
            got = outs[svc.name]
            ref = ref if isinstance(ref, tuple) else (ref,)
            got = got if isinstance(got, tuple) else (got,)
            for r, g in zip(ref, got):
                np.testing.assert_array_equal(np.asarray(r),
                                              np.asarray(g))


# -- cross-rank propagation --------------------------------------------------


class TestCrossRank:
    def test_two_rank_trace_id_equality(self, live_tracing):
        """Rank 0 sends under a minted context; rank 1's blocked recv
        adopts the same trace_id from the wire context header."""
        addrs = [f"127.0.0.1:{p}" for p in _free_ports(2)]
        b0 = TcpMailbox(0, addrs)
        b1 = TcpMailbox(1, addrs)
        got = {}

        def rank1():
            got["msg"] = b1.get(0, 1, tag=7, timeout=10)
            got["ctx"] = obs.current_context()

        try:
            th = threading.Thread(target=rank1, daemon=True)
            th.start()
            ctx = obs.mint(tenant="mnmg")
            with obs.use_context(ctx):
                b0.put(0, 1, 7, np.arange(8, dtype=np.float32))
            th.join(timeout=10)
            assert not th.is_alive()
            np.testing.assert_array_equal(
                got["msg"], np.arange(8, dtype=np.float32))
            assert got["ctx"] is not None
            assert got["ctx"].trace_id == ctx.trace_id
            assert got["ctx"].tenant == "mnmg"
        finally:
            b0.close()
            b1.close()

    def test_inproc_mailbox_propagates(self, live_tracing):
        from raft_tpu.comms.comms import _Mailbox

        box = _Mailbox()
        got = {}

        def receiver():
            got["msg"] = box.get(0, 1, tag=3, timeout=10)
            got["ctx"] = obs.current_context()

        th = threading.Thread(target=receiver, daemon=True)
        th.start()
        ctx = obs.mint()
        with obs.use_context(ctx):
            box.put(0, 1, 3, np.ones(4))
        th.join(timeout=10)
        assert not th.is_alive()
        assert got["ctx"] is not None
        assert got["ctx"].trace_id == ctx.trace_id

    def test_dead_peer_error_names_trace(self, live_tracing):
        """A dead-peer failure while a traced recv is pending names the
        trace it killed, and flight-records the PeerFailedError."""
        addrs = [f"127.0.0.1:{p}" for p in _free_ports(2)]
        b0 = TcpMailbox(0, addrs)
        b1 = TcpMailbox(1, addrs)
        errs = {}
        ctx = obs.mint(tenant="mnmg")

        def rank1():
            obs.adopt(ctx)
            try:
                b1.get(0, 1, tag=9, timeout=10)
            except PeerFailedError as e:
                errs["exc"] = e
            finally:
                obs.adopt(None)

        try:
            th = threading.Thread(target=rank1, daemon=True)
            th.start()
            time.sleep(0.2)
            b1.fail_peer(0, "test-induced death")
            th.join(timeout=10)
            assert not th.is_alive()
            exc = errs["exc"]
            assert f"[trace {ctx.trace_id}]" in str(exc)
            bundles = obs.flight_bundles("PeerFailedError")
            assert bundles
            assert bundles[-1]["header"]["trace_id"] == ctx.trace_id
            assert bundles[-1]["header"]["op"] == "comms.recv"
        finally:
            b0.close()
            b1.close()


# -- flight recorder ---------------------------------------------------------


class TestFlightRecorder:
    def test_forced_fault_dump_validates(self, live_tracing, tmp_path,
                                         data):
        """A deadline fault during traced serving dumps a bundle that
        schema-validates, names the failing trace, and contains spans
        recorded before the failure."""
        obs.set_flight_dir(str(tmp_path))
        ex = serve.Executor([serve.KnnService(data["db"], k=4)])
        ex.warm()
        with ex:
            ex.submit("knn_k4_l2", _queries(0, 4)).result(timeout=30)
            fut = ex.submit("knn_k4_l2", _queries(1, 4),
                            deadline_s=1e-4)   # expires in queue
            with pytest.raises(limits.DeadlineExceededError):
                fut.result(timeout=30)
        bundles = obs.flight_bundles("DeadlineExceededError")
        assert bundles
        header = bundles[-1]["header"]
        assert header["trace_id"].startswith("t-")
        assert header["op"].startswith("serve.")
        path = header["path"]
        n_ok, problems = schema.validate_flight_bundle(path)
        assert not problems, problems
        assert n_ok == 2 + header["n_spans"] + header["n_events"]
        # the pre-failure serving spans are inside the snapshot
        assert any(s["name"] == "serve.batch"
                   for s in bundles[-1]["spans"])
        with open(path, encoding="utf-8") as f:
            first = json.loads(f.readline())
        assert first["kind"] == "flight"
        assert first["trace_id"] == header["trace_id"]

    def test_breaker_open_records_flight(self, live_tracing):
        limits.reset_breakers()
        try:
            br = limits.get_breaker("trace.test.op")
            for _ in range(br.threshold):
                br.record_failure()
            with limits.deadline_scope(10.0):
                with pytest.raises(limits.RejectedError):
                    limits.check_deadline("trace.test.op")
            assert obs.flight_bundles("RejectedError")
        finally:
            limits.reset_breakers()

    def test_nonfinite_guard_records_flight(self, live_tracing):
        from raft_tpu.core import guards

        with pytest.raises(guards.NonFiniteError):
            guards.check_finite("trace.guard.op",
                                np.array([1.0, np.nan]), mode="check")
        bundles = obs.flight_bundles("NonFiniteError")
        assert bundles and bundles[-1]["header"]["op"] == \
            "trace.guard.op"

    def test_recorder_is_bounded_and_never_raises(self, live_tracing,
                                                  tmp_path):
        obs.set_flight_dir(str(tmp_path))
        for i in range(40):
            assert obs.record_failure(ValueError(f"boom {i}")) is not None
        assert len(obs.flight_bundles()) == 16           # memory ring
        files = [f for f in os.listdir(tmp_path)
                 if f.startswith("flight-")]
        assert len(files) == 32                          # disk cap
        # an unwritable dir must not raise into the failure path;
        # the in-memory ring still records the bundle
        obs.set_flight_dir("/dev/null/not-a-dir")
        obs.clear_flight_bundles()
        obs.record_failure(ValueError("still fine"))
        assert len(obs.flight_bundles()) == 1


# -- chrome trace + schema round-trips ---------------------------------------


class TestChromeTrace:
    def test_span_ring_renders_valid_perfetto(self, live_tracing,
                                              tmp_path):
        ctx = obs.mint(tenant="t")
        with obs.use_context(ctx):
            with obs.span("outer", x=1):
                with obs.span("solver.chunk", steps=4):
                    time.sleep(0.001)
        path = tmp_path / "trace.json"
        doc = obs.render_chrome_trace(str(path))
        assert not schema.validate_chrome_trace(doc)
        on_disk = json.loads(path.read_text())
        assert on_disk == json.loads(json.dumps(doc))
        evs = doc["traceEvents"]
        x = {e["name"]: e for e in evs if e["ph"] == "X"}
        assert set(x) == {"outer", "solver.chunk"}
        # nesting: child wholly inside parent on the same tid
        assert x["solver.chunk"]["tid"] == x["outer"]["tid"]
        assert x["solver.chunk"]["ts"] >= x["outer"]["ts"]
        assert (x["solver.chunk"]["ts"] + x["solver.chunk"]["dur"]
                <= x["outer"]["ts"] + x["outer"]["dur"] + 1e-3)
        assert x["solver.chunk"]["args"]["parent"] == "outer"
        assert x["outer"]["args"]["trace_id"] == ctx.trace_id
        # *.chunk spans also get the async device lane
        bs = [e for e in evs if e["ph"] == "b"]
        es = [e for e in evs if e["ph"] == "e"]
        assert len(bs) == 1 and len(es) == 1
        assert bs[0]["cat"] == "device" and bs[0]["id"] == es[0]["id"]

    def test_compiled_driver_chunks_render_async(self, live_tracing):
        from raft_tpu.runtime import compiled_driver
        import jax
        import jax.numpy as jnp

        def step(c):
            return c + 1.0, jnp.zeros((), jnp.bool_)

        chunk = jax.jit(
            lambda c, s: compiled_driver.chunk_while(step, c, s))
        compiled_driver.run_chunked(chunk, jnp.zeros(()), max_steps=8,
                                    sync_every=4, op="trace.solver")
        chunk = obs.spans("trace.solver.chunk")
        assert len(chunk) == 2
        assert all(s["attrs"]["ran"] == 4 for s in chunk)
        doc = obs.render_chrome_trace()
        assert sum(1 for e in doc["traceEvents"] if e["ph"] == "b") == 2

    def test_validator_rejects_garbage(self):
        assert schema.validate_chrome_trace([])
        assert schema.validate_chrome_trace({"traceEvents": "nope"})
        probs = schema.validate_chrome_trace({"traceEvents": [
            {"name": "a", "ph": "X", "ts": 0, "pid": 1, "tid": 1},
        ]})
        assert any("dur" in p for p in probs)
        probs = schema.validate_chrome_trace({"traceEvents": [
            {"name": "a", "ph": "b", "ts": 0, "pid": 1, "tid": 1},
        ]})
        assert any("id" in p for p in probs)


class TestSchemaRoundTrip:
    def test_ctx_fields_on_span_and_event_records(self, live_tracing,
                                                  tmp_path):
        """Every record the sink writes under tracing round-trips
        through the validator, context fields included."""
        path = tmp_path / "stream.jsonl"
        sink = obs.JsonlSink(str(path))
        old = obs.set_sink(sink)
        try:
            with obs.use_context(obs.mint(tenant="rt")):
                with obs.span("rt.span"):
                    pass
                obs.emit_event("rt.event")
        finally:
            obs.set_sink(old)
            sink.close()
        n_ok, problems = schema.validate_jsonl(str(path))
        assert not problems, problems
        assert n_ok == 2
        recs = [json.loads(line) for line in path.read_text().splitlines()]
        for rec in recs:
            assert rec["trace_id"].startswith("t-")
            assert rec["tenant"] == "rt"

    def test_bad_ctx_fields_rejected(self):
        base = {"kind": "event", "name": "e", "ts": 1.0, "t": 1.0,
                "range": None, "range_stack": []}
        assert not schema.validate_record(base)
        assert schema.validate_record({**base, "trace_id": ""})
        assert schema.validate_record({**base, "request_id": 7})

    def test_flight_and_metrics_records(self):
        flight = {"kind": "flight", "ts": 1.0, "t": 1.0,
                  "error_type": "ValueError", "error": "boom",
                  "op": None, "n_spans": 0, "n_events": 2,
                  "trace_id": "t-x", "request_id": "r-x",
                  "tenant": "d"}
        assert not schema.validate_record(flight)
        assert schema.validate_record({**flight, "error_type": ""})
        assert schema.validate_record({**flight, "n_spans": -1})
        assert schema.validate_record({**flight, "n_events": True})
        metrics = {"kind": "metrics", "ts": 1.0, "t": 1.0, "metrics": {}}
        assert not schema.validate_record(metrics)
        assert schema.validate_record({**metrics, "metrics": []})

    def test_bundle_structure_enforced(self, tmp_path):
        p = tmp_path / "bad.jsonl"
        ev = {"kind": "event", "name": "e", "ts": 1.0, "t": 1.0,
              "range": None, "range_stack": []}
        p.write_text(json.dumps(ev) + "\n")
        _, problems = schema.validate_flight_bundle(str(p))
        assert any("kind='flight'" in q for q in problems)
        assert any("metrics" in q for q in problems)
        empty = tmp_path / "empty.jsonl"
        empty.write_text("")
        _, problems = schema.validate_flight_bundle(str(empty))
        assert any("empty" in q for q in problems)


# -- fail-loud env knobs -----------------------------------------------------


class TestFailLoudEnv:
    @staticmethod
    def _import_obs(env):
        full = dict(os.environ, JAX_PLATFORMS="cpu", **env)
        return subprocess.run(
            [sys.executable, "-c", "import raft_tpu.obs"],
            env=full, cwd=_REPO, capture_output=True, text=True,
            timeout=120)

    @pytest.mark.parametrize("env", [
        {"RAFT_TPU_SPAN_RETAIN": "lots"},
        {"RAFT_TPU_SPAN_RETAIN": "0"},
        {"RAFT_TPU_SPAN_RETAIN": "-5"},
        {"RAFT_TPU_SPAN_SAMPLE": "often"},
        {"RAFT_TPU_SPAN_SAMPLE": "1.5"},
        {"RAFT_TPU_SPAN_SAMPLE": "-0.1"},
    ])
    def test_malformed_values_fail_import(self, env):
        res = self._import_obs(env)
        assert res.returncode != 0
        name = next(iter(env))
        assert name in res.stderr       # the error names the knob

    @pytest.mark.parametrize("env", [
        {"RAFT_TPU_SPAN_RETAIN": "512"},
        {"RAFT_TPU_SPAN_SAMPLE": "0.25"},
        {"RAFT_TPU_SPAN_SAMPLE": "0"},
        {"RAFT_TPU_SPAN_RETAIN": "", "RAFT_TPU_SPAN_SAMPLE": ""},
    ])
    def test_valid_values_import(self, env):
        res = self._import_obs(env)
        assert res.returncode == 0, res.stderr
