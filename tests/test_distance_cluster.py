"""Distance + k-means tests vs scipy/numpy references (the reference's
devArrMatch-vs-host pattern, SURVEY.md §4)."""

import numpy as np
import pytest
import scipy.spatial.distance as sdist

import raft_tpu
from raft_tpu.distance import DistanceType, pairwise_distance, \
    fused_l2_nn_argmin
from raft_tpu.cluster import (KMeansParams, KMeansInit, kmeans_fit,
                              kmeans_predict, kmeans_transform,
                              kmeans_fit_mnmg, lloyd_step)


@pytest.fixture(scope="module")
def xy():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(83, 17)).astype(np.float32)
    y = rng.normal(size=(41, 17)).astype(np.float32)
    return x, y


@pytest.fixture(scope="module")
def xy_pos(xy):
    x, y = xy
    xp = np.abs(x) + 0.01
    yp = np.abs(y) + 0.01
    xp /= xp.sum(1, keepdims=True)
    yp /= yp.sum(1, keepdims=True)
    return xp.astype(np.float32), yp.astype(np.float32)


CDIST_CASES = [
    (DistanceType.L2SqrtExpanded, "euclidean", 2e-3),
    (DistanceType.L2SqrtUnexpanded, "euclidean", 1e-4),
    (DistanceType.L2Expanded, "sqeuclidean", 2e-3),
    (DistanceType.L2Unexpanded, "sqeuclidean", 1e-4),
    (DistanceType.L1, "cityblock", 1e-4),
    (DistanceType.Linf, "chebyshev", 1e-5),
    (DistanceType.Canberra, "canberra", 1e-4),
    (DistanceType.CosineExpanded, "cosine", 1e-5),
    (DistanceType.CorrelationExpanded, "correlation", 1e-5),
]


class TestPairwiseDistance:
    @pytest.mark.parametrize("metric,scipy_name,tol", CDIST_CASES,
                             ids=lambda c: str(c))
    def test_vs_scipy(self, res, xy, metric, scipy_name, tol):
        x, y = xy
        got = np.asarray(pairwise_distance(res, x, y, metric=metric))
        want = sdist.cdist(x.astype(np.float64), y.astype(np.float64),
                           scipy_name)
        np.testing.assert_allclose(got, want, atol=tol, rtol=tol)

    def test_minkowski(self, res, xy):
        x, y = xy
        got = np.asarray(pairwise_distance(
            res, x, y, metric=DistanceType.LpUnexpanded, p=3.0))
        want = sdist.cdist(x.astype(np.float64), y.astype(np.float64),
                           "minkowski", p=3.0)
        np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-4)

    def test_inner_product(self, res, xy):
        x, y = xy
        got = np.asarray(pairwise_distance(res, x, y,
                                           metric=DistanceType.InnerProduct))
        np.testing.assert_allclose(got, x @ y.T, atol=1e-4)

    def test_hellinger(self, res, xy_pos):
        x, y = xy_pos
        got = np.asarray(pairwise_distance(
            res, x, y, metric=DistanceType.HellingerExpanded))
        bc = np.sqrt(x)[:, None, :] * np.sqrt(y)[None, :, :]
        want = np.sqrt(np.maximum(1.0 - bc.sum(-1), 0.0))
        np.testing.assert_allclose(got, want, atol=1e-4)

    def test_jensen_shannon(self, res, xy_pos):
        x, y = xy_pos
        got = np.asarray(pairwise_distance(
            res, x, y, metric=DistanceType.JensenShannon))
        want = sdist.cdist(x.astype(np.float64), y.astype(np.float64),
                           "jensenshannon")
        np.testing.assert_allclose(got, want, atol=1e-3)

    def test_kl(self, res, xy_pos):
        x, y = xy_pos
        got = np.asarray(pairwise_distance(
            res, x, y, metric=DistanceType.KLDivergence))
        xd, yd = x.astype(np.float64), y.astype(np.float64)
        want = (xd[:, None, :] * np.log(xd[:, None, :] / yd[None, :, :])
                ).sum(-1)
        np.testing.assert_allclose(got, want, atol=1e-3)

    def test_boolean_metrics(self, res):
        rng = np.random.default_rng(5)
        x = (rng.random((30, 24)) > 0.5)
        y = (rng.random((20, 24)) > 0.5)
        for metric, name in [(DistanceType.JaccardExpanded, "jaccard"),
                             (DistanceType.HammingUnexpanded, "hamming"),
                             (DistanceType.RusselRaoExpanded, "russellrao"),
                             (DistanceType.DiceExpanded, "dice")]:
            got = np.asarray(pairwise_distance(
                res, x.astype(np.float32), y.astype(np.float32),
                metric=metric))
            want = sdist.cdist(x, y, name)
            np.testing.assert_allclose(got, want, atol=1e-5, err_msg=name)

    def test_self_distance(self, res, xy):
        x, _ = xy
        d = np.asarray(pairwise_distance(res, x,
                                         metric=DistanceType.L2SqrtExpanded))
        assert d.shape == (83, 83)
        assert np.allclose(np.diag(d), 0.0, atol=1e-2)

    def test_fused_l2_nn(self, res, xy):
        x, y = xy
        val, idx = fused_l2_nn_argmin(res, x, y)
        d = sdist.cdist(x, y, "sqeuclidean")
        np.testing.assert_array_equal(np.asarray(idx), d.argmin(1))
        np.testing.assert_allclose(np.asarray(val), d.min(1), rtol=1e-3,
                                   atol=1e-3)


class TestKMeans:
    @pytest.fixture(scope="class")
    def blobs(self, res):
        from raft_tpu.random import make_blobs, RngState

        X, labels, centers = make_blobs(res, RngState(3), 3000, 8,
                                        n_clusters=5, cluster_std=0.3)
        return np.asarray(X), np.asarray(labels), np.asarray(centers)

    def test_lloyd_converges(self, res, blobs):
        X, true_labels, centers = blobs
        params = KMeansParams(n_clusters=5, max_iter=50, seed=1)
        c, inertia, labels, n_iter = kmeans_fit(res, params, X)
        assert n_iter < 50
        # every true cluster is recovered: centroid within 3·std of a center
        d = sdist.cdist(np.asarray(c), centers)
        assert d.min(axis=0).max() < 1.0
        # labels consistent with true clustering (perfect up to permutation)
        from scipy.stats import mode
        for t in range(5):
            assert mode(np.asarray(labels)[true_labels == t]).count > \
                0.95 * (true_labels == t).sum()

    def test_random_init(self, res, blobs):
        # Random init has no spreading guarantee: a single draw can put
        # two centroids in one blob and strand a cluster (seed 4 does,
        # deterministically — inertia ~70k vs the ~6.5k bound). Random
        # restarts are the contract under which RANDOM init is usable;
        # the best of a few seeded draws must recover the blobs.
        X, _, centers = blobs
        best = np.inf
        for seed in (0, 2, 5):
            params = KMeansParams(n_clusters=5, init=KMeansInit.RANDOM,
                                  max_iter=100, seed=seed)
            c, inertia, _, _ = kmeans_fit(res, params, X)
            best = min(best, float(inertia))
        assert best < X.shape[0] * 0.3 ** 2 * 8 * 3

    def test_predict_transform(self, res, blobs):
        X, _, _ = blobs
        params = KMeansParams(n_clusters=5, seed=1, max_iter=20)
        c, _, labels, _ = kmeans_fit(res, params, X)
        pred, _ = kmeans_predict(res, X, c)
        np.testing.assert_array_equal(np.asarray(pred), np.asarray(labels))
        t = np.asarray(kmeans_transform(res, X[:10], c))
        want = sdist.cdist(X[:10], np.asarray(c))
        np.testing.assert_allclose(t, want, atol=1e-2)

    def test_lloyd_step_jit(self, blobs):
        X, _, _ = blobs
        c0 = X[:5]
        c1, inertia, labels = lloyd_step(X, c0, 5)
        assert c1.shape == c0.shape and labels.shape == (X.shape[0],)

    def test_mnmg_matches_single(self, res, blobs, mesh8):
        """MNMG result == single-chip result for identical init (the
        allreduce makes the math bitwise-equivalent up to reduction order)."""
        X, _, _ = blobs
        X = X[:2048]  # divisible by 8
        init = X[7 * np.arange(5)]
        params = KMeansParams(n_clusters=5, init=KMeansInit.ARRAY,
                              max_iter=10, tol=0.0, seed=1)
        c_single, in_single, _, _ = kmeans_fit(res, params, X,
                                               centroids=init)
        c_mnmg, in_mnmg, labels, _ = kmeans_fit_mnmg(
            res, params, X, centroids=init, mesh=mesh8)
        np.testing.assert_allclose(np.asarray(c_single), np.asarray(c_mnmg),
                                   rtol=1e-4, atol=1e-4)
        assert abs(float(in_single) - float(in_mnmg)) < 1e-1
        assert labels.shape == (2048,)

    def test_mnmg_model_axis(self, mesh8):
        """2-D mesh: rows over 'data', centroids over 'model'."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, PartitionSpec as P

        from raft_tpu.cluster.kmeans import mnmg_lloyd_step

        devs = np.asarray(jax.devices()[:8]).reshape(4, 2)
        mesh = Mesh(devs, axis_names=("data", "model"))
        rng = np.random.default_rng(0)
        X = rng.normal(size=(256, 16)).astype(np.float32)
        C = rng.normal(size=(8, 16)).astype(np.float32)

        def step(x, cblk):
            return mnmg_lloyd_step(x, cblk, n_clusters=8, data_axis="data",
                                   model_axis="model")

        f = jax.jit(jax.shard_map(
            step, mesh=mesh,
            in_specs=(P("data"), P("model")),
            out_specs=(P("model"), P(), P("data")),
            check_vma=False))
        new_c, inertia, labels = f(X, C)
        # reference single-device Lloyd step
        d = sdist.cdist(X, C, "sqeuclidean")
        want_labels = d.argmin(1)
        np.testing.assert_array_equal(np.asarray(labels), want_labels)
        want_c = np.stack([
            X[want_labels == i].mean(0) if (want_labels == i).any() else C[i]
            for i in range(8)])
        np.testing.assert_allclose(np.asarray(new_c), want_c, atol=1e-4)
        assert abs(float(inertia) - d.min(1).sum()) < 1.0


class TestExtraMetrics:
    def test_haversine(self, res):
        import numpy as np
        from raft_tpu.distance import DistanceType, pairwise_distance

        rng = np.random.default_rng(0)
        pts = np.stack([rng.uniform(-np.pi / 2, np.pi / 2, 20),
                        rng.uniform(-np.pi, np.pi, 20)], axis=1)
        d = np.asarray(pairwise_distance(res, pts.astype(np.float32),
                                         metric=DistanceType.Haversine))
        lat1, lon1 = pts[:, None, 0], pts[:, None, 1]
        lat2, lon2 = pts[None, :, 0], pts[None, :, 1]
        a = (np.sin((lat2 - lat1) / 2) ** 2
             + np.cos(lat1) * np.cos(lat2) * np.sin((lon2 - lon1) / 2) ** 2)
        expect = 2 * np.arcsin(np.sqrt(np.clip(a, 0, 1)))
        np.testing.assert_allclose(d, expect, atol=1e-5)
        with __import__("pytest").raises(ValueError):
            pairwise_distance(res, np.zeros((3, 4), np.float32),
                              metric=DistanceType.Haversine)

    def test_braycurtis(self, res):
        import numpy as np
        from raft_tpu.distance import DistanceType, pairwise_distance

        rng = np.random.default_rng(1)
        x = rng.uniform(0, 1, (15, 6)).astype(np.float32)
        d = np.asarray(pairwise_distance(res, x,
                                         metric=DistanceType.BrayCurtis))
        num = np.abs(x[:, None, :] - x[None, :, :]).sum(-1)
        den = np.abs(x[:, None, :] + x[None, :, :]).sum(-1)
        np.testing.assert_allclose(d, num / den, rtol=1e-5)


def test_kmeans_check_every_same_result(res):
    """Batched convergence polling must land on the same clustering as
    per-iteration polling (at most check_every-1 extra iterations)."""
    import numpy as np

    from raft_tpu.cluster.kmeans import KMeansParams, kmeans_fit
    from raft_tpu.random import RngState, make_blobs

    x, labels, _ = make_blobs(res, RngState(3), 3000, 12, n_clusters=6)
    x = np.asarray(x)
    c1, i1, l1, n1 = kmeans_fit(res, KMeansParams(n_clusters=6, seed=1), x)
    c2, i2, l2, n2 = kmeans_fit(
        res, KMeansParams(n_clusters=6, seed=1, check_every=5), x)
    np.testing.assert_allclose(float(i1), float(i2), rtol=1e-4)
    assert (np.asarray(l1) == np.asarray(l2)).mean() > 0.999
    # convergence needs two poll values: bound is next-multiple + one window
    assert n2 <= -(-n1 // 5) * 5 + 5


class TestKmeansFit2D:
    def test_fit_mnmg_model_axis_matches_1d(self, mesh8):
        """The PUBLIC 2-D fit path (round-3: kmeans_fit_mnmg grew
        model_axis) must match the 1-D fit exactly — same init seed, same
        math, only the sharding differs."""
        import jax
        from jax.sharding import Mesh

        from raft_tpu.cluster.kmeans import KMeansParams, kmeans_fit_mnmg

        rng = np.random.default_rng(5)
        x = rng.normal(size=(512, 16)).astype(np.float32)
        params = KMeansParams(n_clusters=8, max_iter=8, tol=0.0, seed=3)

        c1, in1, l1, n1 = kmeans_fit_mnmg(None, params, x, mesh=mesh8,
                                          data_axis="data")
        devs = np.asarray(jax.devices()[:8]).reshape(4, 2)
        mesh2 = Mesh(devs, axis_names=("data", "model"))
        c2, in2, l2, n2 = kmeans_fit_mnmg(None, params, x, mesh=mesh2,
                                          data_axis="data",
                                          model_axis="model")
        assert n1 == n2
        np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2))
        np.testing.assert_allclose(float(in1), float(in2), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(c1), np.asarray(c2),
                                   rtol=1e-4, atol=1e-4)

    def test_fit_mnmg_model_axis_divisibility_error(self, mesh8):
        import jax
        from jax.sharding import Mesh

        from raft_tpu.cluster.kmeans import KMeansParams, kmeans_fit_mnmg

        devs = np.asarray(jax.devices()[:8]).reshape(4, 2)
        mesh2 = Mesh(devs, axis_names=("data", "model"))
        params = KMeansParams(n_clusters=7, max_iter=2, seed=0)
        x = np.zeros((64, 4), np.float32)
        with pytest.raises(ValueError, match="divisible"):
            kmeans_fit_mnmg(None, params, x, mesh=mesh2,
                            data_axis="data", model_axis="model")


def test_cluster_cost_matches_predict_inertia():
    from raft_tpu.cluster.kmeans import cluster_cost, kmeans_predict

    rng = np.random.default_rng(9)
    x = rng.normal(size=(300, 8)).astype(np.float32)
    c = rng.normal(size=(5, 8)).astype(np.float32)
    _, inertia = kmeans_predict(None, x, c)
    cost = cluster_cost(None, x, c)
    np.testing.assert_allclose(float(cost), float(inertia), rtol=1e-6)
    ref = ((x[:, None] - c[None]) ** 2).sum(-1).min(1).sum()
    np.testing.assert_allclose(float(cost), ref, rtol=1e-3)


class TestWeightedKMeans:
    def test_uniform_weights_match_unweighted(self):
        """With the SAME init (pinned centroids — the weighted init uses
        a different RNG draw, so seeding-level equality is not the
        contract), w == ones must reproduce the unweighted iteration
        math exactly."""
        from raft_tpu.cluster.kmeans import KMeansParams, kmeans_fit

        rng = np.random.default_rng(15)
        x = rng.normal(size=(400, 8)).astype(np.float32)
        init_c = x[:6].copy()
        params = KMeansParams(n_clusters=6, max_iter=12, tol=0.0, seed=1)
        c1, in1, l1, n1 = kmeans_fit(None, params, x, centroids=init_c)
        w = np.ones(400, np.float32)
        c2, in2, l2, n2 = kmeans_fit(None, params, x, centroids=init_c,
                                     sample_weights=w)
        np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2))
        np.testing.assert_allclose(np.asarray(c1), np.asarray(c2),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(float(in1), float(in2), rtol=1e-4)

    def test_weights_equal_duplication(self):
        """Weighting a point by k must equal duplicating it k times (the
        defining property of sample weights; sklearn pins the same)."""
        from raft_tpu.cluster.kmeans import KMeansParams, kmeans_fit

        rng = np.random.default_rng(16)
        base = rng.normal(size=(60, 4)).astype(np.float32)
        reps = rng.integers(1, 4, size=60)
        dup = np.repeat(base, reps, axis=0)
        params = KMeansParams(n_clusters=4, max_iter=15, tol=0.0, seed=2,
                              init=KMeansInit.ARRAY)
        init_c = base[:4].copy()
        cw, iw, _, _ = kmeans_fit(None, params, base, centroids=init_c,
                                  sample_weights=reps.astype(np.float32))
        cd, idp, _, _ = kmeans_fit(None, params, dup, centroids=init_c)
        np.testing.assert_allclose(np.asarray(cw), np.asarray(cd),
                                   rtol=1e-3, atol=1e-3)
        np.testing.assert_allclose(float(iw), float(idp), rtol=1e-3)

    def test_zero_weight_points_ignored(self):
        from raft_tpu.cluster.kmeans import KMeansParams, kmeans_fit

        rng = np.random.default_rng(17)
        x = np.concatenate([rng.normal(size=(100, 2)).astype(np.float32),
                            np.full((5, 2), 100.0, np.float32)])
        w = np.concatenate([np.ones(100), np.zeros(5)]).astype(np.float32)
        params = KMeansParams(n_clusters=3, max_iter=20, seed=3)
        c, inertia, labels, _ = kmeans_fit(None, params, x,
                                           sample_weights=w)
        # no centroid gets dragged to the zero-weight outliers
        assert np.abs(np.asarray(c)).max() < 50.0

    def test_bad_weight_shape_raises(self):
        from raft_tpu.cluster.kmeans import KMeansParams, kmeans_fit

        with pytest.raises(ValueError, match="sample_weights"):
            kmeans_fit(None, KMeansParams(n_clusters=2, seed=0),
                       np.zeros((10, 2), np.float32),
                       sample_weights=np.ones(9, np.float32))

    def test_invalid_weights_raise(self):
        from raft_tpu.cluster.kmeans import KMeansParams, kmeans_fit

        x = np.zeros((10, 2), np.float32)
        p = KMeansParams(n_clusters=2, seed=0)
        for bad in (np.full(10, -1.0, np.float32),
                    np.zeros(10, np.float32),
                    np.full(10, np.nan, np.float32)):
            with pytest.raises(ValueError):
                kmeans_fit(None, p, x, sample_weights=bad)

    def test_mnmg_weighted_matches_single(self, mesh8):
        """Weighted MNMG fit (1-D and 2-D mesh) == weighted single-device
        fit for identical init — weights shard with the rows and the
        psums aggregate the same weighted mass."""
        import jax
        from jax.sharding import Mesh

        from raft_tpu.cluster.kmeans import (KMeansParams, kmeans_fit,
                                             kmeans_fit_mnmg)

        rng = np.random.default_rng(19)
        x = rng.normal(size=(512, 8)).astype(np.float32)
        w = rng.uniform(0.1, 3.0, size=512).astype(np.float32)
        init_c = x[11 * np.arange(4)].copy()
        params = KMeansParams(n_clusters=4, init=KMeansInit.ARRAY,
                              max_iter=8, tol=0.0, seed=5)
        c0, in0, l0, _ = kmeans_fit(None, params, x, centroids=init_c,
                                    sample_weights=w)
        c1, in1, l1, _ = kmeans_fit_mnmg(None, params, x,
                                         centroids=init_c, mesh=mesh8,
                                         data_axis="data",
                                         sample_weights=w)
        np.testing.assert_array_equal(np.asarray(l0), np.asarray(l1))
        np.testing.assert_allclose(float(in0), float(in1), rtol=1e-4)
        np.testing.assert_allclose(np.asarray(c0), np.asarray(c1),
                                   rtol=1e-3, atol=1e-3)
        devs = np.asarray(jax.devices()[:8]).reshape(4, 2)
        mesh2 = Mesh(devs, axis_names=("data", "model"))
        c2, in2, l2, _ = kmeans_fit_mnmg(None, params, x,
                                         centroids=init_c, mesh=mesh2,
                                         data_axis="data",
                                         model_axis="model",
                                         sample_weights=w)
        np.testing.assert_array_equal(np.asarray(l0), np.asarray(l2))
        np.testing.assert_allclose(float(in0), float(in2), rtol=1e-4)
        np.testing.assert_allclose(np.asarray(c0), np.asarray(c2),
                                   rtol=1e-3, atol=1e-3)

    def test_small_scale_weights_are_scale_invariant(self):
        """Weights are a relative measure: scaling all weights by 0.01
        must not change the fit (regression: max(counts, 1) in the update
        collapsed clusters whose total weighted mass fell below 1)."""
        from raft_tpu.cluster.kmeans import KMeansParams, kmeans_fit

        rng = np.random.default_rng(23)
        x = rng.normal(size=(200, 4)).astype(np.float32)
        init_c = x[:5].copy()
        params = KMeansParams(n_clusters=5, init=KMeansInit.ARRAY,
                              max_iter=10, tol=0.0, seed=6)
        c1, in1, l1, _ = kmeans_fit(None, params, x, centroids=init_c,
                                    sample_weights=np.ones(200, np.float32))
        c2, in2, l2, _ = kmeans_fit(
            None, params, x, centroids=init_c,
            sample_weights=np.full(200, 0.01, np.float32))
        np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2))
        np.testing.assert_allclose(np.asarray(c1), np.asarray(c2),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(float(in2), 0.01 * float(in1),
                                   rtol=1e-4)


def test_lloyd_prepared_bit_identical():
    """The hoisted-operand Lloyd path (lloyd_prepare +
    lloyd_step_prepared) must be BIT-identical to lloyd_step at tier
    'high' — same kernel, same operand bytes, only their production is
    hoisted out of the loop — and must decline (None) when the prepared
    path doesn't apply (non-'high' tier, non-f32 dtype)."""
    import jax.numpy as jnp
    import raft_tpu
    from raft_tpu.cluster.kmeans import lloyd_step, lloyd_step_prepared
    from raft_tpu.linalg.contractions import lloyd_prepare

    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.normal(size=(700, 33)).astype(np.float32))
    c = jnp.asarray(rng.normal(size=(37, 33)).astype(np.float32))
    old = raft_tpu.get_matmul_precision()
    try:
        raft_tpu.set_matmul_precision("high")
        ops, meta = lloyd_prepare(x, 37)
        assert ops is not None
        ref = lloyd_step(x, c, 37)
        got = lloyd_step_prepared(ops, c, **meta)
        for a, b, name in zip(ref, got, ("centroids", "inertia", "labels")):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                          err_msg=name)
        # two chained iterations stay identical (the prepared ops are
        # reused across steps; centroids evolve)
        ref2 = lloyd_step(x, ref[0], 37)
        got2 = lloyd_step_prepared(ops, got[0], **meta)
        np.testing.assert_array_equal(np.asarray(ref2[0]),
                                      np.asarray(got2[0]))

        raft_tpu.set_matmul_precision("highest")
        assert lloyd_prepare(x, 37) == (None, None)
        raft_tpu.set_matmul_precision("high")
        assert lloyd_prepare(x.astype(jnp.bfloat16), 37) == (None, None)
        # VMEM-fallback shapes decline too (Y + sums beyond residency)
        big = jnp.zeros((64, 40000), jnp.float32)
        assert lloyd_prepare(big, 20000) == (None, None)
    finally:
        raft_tpu.set_matmul_precision(old)


def test_lloyd_iterate_prepared_matches_stepped():
    """The scanned iteration block (lloyd_iterate_prepared) must end at
    the SAME (centroids, inertia, labels) as the same number of chained
    lloyd_step_prepared calls, bit-identically — it is the one-launch
    spelling of the between-polls loop, not a different algorithm."""
    import jax.numpy as jnp
    import raft_tpu
    from raft_tpu.cluster.kmeans import (lloyd_iterate_prepared,
                                         lloyd_step_prepared)
    from raft_tpu.linalg.contractions import lloyd_prepare

    rng = np.random.default_rng(6)
    x = jnp.asarray(rng.normal(size=(700, 33)).astype(np.float32))
    c0 = jnp.asarray(rng.normal(size=(37, 33)).astype(np.float32))
    old = raft_tpu.get_matmul_precision()
    try:
        raft_tpu.set_matmul_precision("high")
        ops, meta = lloyd_prepare(x, 37)
        assert ops is not None
        c = c0
        for _ in range(3):
            c, inertia, labels = lloyd_step_prepared(ops, c, **meta)
        got = lloyd_iterate_prepared(ops, c0, 3, **meta)
        for a, b, name in zip((c, inertia, labels), got,
                              ("centroids", "inertia", "labels")):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                          err_msg=name)
        with pytest.raises(ValueError):
            lloyd_iterate_prepared(ops, c0, 0, **meta)
    finally:
        raft_tpu.set_matmul_precision(old)


def test_kmeans_fit_block_size_invariant():
    """kmeans_fit's scanned between-polls blocks must not change the
    result: check_every=7 (blocks of 7 + remainder) and check_every=1
    run the same iteration sequence bit-identically at tol=0."""
    import jax.numpy as jnp
    import raft_tpu
    from raft_tpu.cluster.kmeans import KMeansParams, kmeans_fit

    rng = np.random.default_rng(13)
    x = jnp.asarray(rng.normal(size=(600, 17)).astype(np.float32))
    old = raft_tpu.get_matmul_precision()
    try:
        raft_tpu.set_matmul_precision("high")
        res = []
        for ce in (1, 7):
            p = KMeansParams(n_clusters=23, max_iter=10, tol=0.0,
                             seed=3, check_every=ce)
            c, inertia, labels, n_iter = kmeans_fit(None, p, x)
            assert n_iter == 10
            res.append((np.asarray(c), float(inertia),
                        np.asarray(labels)))
        np.testing.assert_array_equal(res[0][0], res[1][0])
        assert res[0][1] == res[1][1]
        np.testing.assert_array_equal(res[0][2], res[1][2])
    finally:
        raft_tpu.set_matmul_precision(old)
