"""Elastic MNMG tests (ISSUE 2 tentpole): abort propagation, survivor
consensus + shrink, and checkpoint/resume wired through the iterative
solvers.

Acceptance criteria exercised here:

* a 4-rank ``kmeans_fit_mnmg`` with one fault-injected disconnected rank
  completes on the 3 survivors from the last checkpoint, centroids
  bit-for-bit equal to a fault-free run resumed from the same
  checkpoint on a fresh 3-device mesh;
* the same for ``eigsh_mnmg`` (bands REBUILT for the smaller device
  count — n_local changes with the divisor);
* a 4-process ``kmeans_fit_elastic`` clique with one rank SIGKILL'd
  mid-iteration finishes on the 3 survivors, bit-for-bit equal to a
  clean 3-process run resumed from the kill-boundary checkpoint
  (tests/_elastic_worker.py);
* ``abort()`` wakes a blocked peer recv well inside 2x the heartbeat
  interval (the propagation contract: poison frames, not staggered
  timeouts).
"""

import os
import re
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from raft_tpu.cluster.kmeans import (KMeansParams, kmeans_fit_elastic,
                                     kmeans_fit_mnmg)
from raft_tpu.comms.comms import MeshComms, _Mailbox
from raft_tpu.comms.errors import CommsAbortedError, PeerFailedError
from raft_tpu.comms.faults import FaultInjector
from raft_tpu.core import resources as core_res

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _submesh(n):
    import jax
    from jax.sharding import Mesh

    return Mesh(np.asarray(jax.devices()[:n]), axis_names=("data",))


def _blobs(seed=0, per=300, k=4, d=5):
    rng = np.random.default_rng(seed)
    return np.concatenate(
        [rng.normal(c, 0.3, (per, d)) for c in range(k)]).astype(np.float32)


class TestElasticKMeansMnmg:
    def test_disconnect_recovers_bit_for_bit(self, tmp_path):
        """Rank 2 disconnects at the first health probe; survivors agree,
        shrink to 3 devices, reload the checkpoint and finish — equal to
        a clean run resumed from that same checkpoint on a fresh
        3-device mesh (device prefix == survivor mesh determinism)."""
        x = _blobs()
        params = KMeansParams(n_clusters=4, max_iter=30, tol=1e-6, seed=3,
                              check_every=2)
        d = str(tmp_path)

        res = core_res.Resources()
        core_res.set_mesh(res, _submesh(4))
        inj = FaultInjector(seed=0, disconnect=1.0, source_ranks={2})
        comms = MeshComms(_submesh(4), "data", 0,
                          _mailbox=_Mailbox(faults=inj))
        core_res.set_comms(res, comms)
        c_f, in_f, _, it_f = kmeans_fit_mnmg(
            res, params, x, mesh=_submesh(4), checkpoint_every=1,
            checkpoint_dir=d, checkpoint_keep=50)
        # the fit recovered: its handle now carries the survivor clique
        assert core_res.get_comms(res).get_size() == 3
        assert inj.counts["disconnect"] >= 1

        first = sorted(f for f in os.listdir(d) if f.endswith(".ckpt"))[0]
        res2 = core_res.Resources()
        c_c, in_c, _, it_c = kmeans_fit_mnmg(
            res2, params, x, mesh=_submesh(3),
            resume_from=os.path.join(d, first))
        assert np.array_equal(np.asarray(c_f), np.asarray(c_c))
        assert it_f == it_c
        assert float(in_f) == float(in_c)

    def test_resume_requires_checkpoint(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            kmeans_fit_mnmg(core_res.Resources(),
                            KMeansParams(n_clusters=2, max_iter=2),
                            _blobs(per=20, k=2), mesh=_submesh(2),
                            resume_from=str(tmp_path))

    def test_checkpoint_every_requires_dir(self):
        with pytest.raises(ValueError, match="checkpoint_dir"):
            kmeans_fit_mnmg(core_res.Resources(),
                            KMeansParams(n_clusters=2, max_iter=2),
                            _blobs(per=20, k=2), mesh=_submesh(2),
                            checkpoint_every=1)


class TestElasticEigsh:
    def test_disconnect_recovers_bit_for_bit(self, tmp_path):
        """The eigsh recovery additionally re-pads: n_local = ceil(n/3)
        differs from ceil(n/4), so the row bands and basis placement are
        rebuilt from the unpadded checkpoint state."""
        import scipy.sparse as sp

        from raft_tpu.core.sparse_types import CSRMatrix
        from raft_tpu.sparse.solver import eigsh_mnmg

        n = 96
        A = sp.random(n, n, density=0.08, random_state=2, format="csr",
                      dtype=np.float64)
        A = ((A + A.T) * 0.5).astype(np.float32)
        csr = CSRMatrix.from_scipy(A)
        d = str(tmp_path)

        inj = FaultInjector(seed=0, disconnect=1.0, source_ranks={2})
        comms = MeshComms(_submesh(4), "data", 0,
                          _mailbox=_Mailbox(faults=inj))
        w_f, v_f = eigsh_mnmg(csr, k=4, mesh=_submesh(4), which="SA",
                              maxiter=50, tol=1e-6, comms=comms,
                              checkpoint_every=1, checkpoint_dir=d,
                              checkpoint_keep=50)
        assert inj.counts["disconnect"] >= 1

        first = sorted(f for f in os.listdir(d) if f.endswith(".ckpt"))[0]
        w_c, v_c = eigsh_mnmg(csr, k=4, mesh=_submesh(3), which="SA",
                              maxiter=50, tol=1e-6,
                              resume_from=os.path.join(d, first))
        assert np.array_equal(np.asarray(w_f), np.asarray(w_c))
        assert np.array_equal(np.asarray(v_f), np.asarray(v_c))

        from scipy.sparse.linalg import eigsh as scipy_eigsh

        ws = scipy_eigsh(A.astype(np.float64), k=4, which="SA")[0]
        np.testing.assert_allclose(np.sort(np.asarray(w_f)), np.sort(ws),
                                   atol=1e-4)


class TestAbortPropagation:
    def test_abort_wakes_blocked_recv_within_two_heartbeats(self):
        """A rank blocked in a long recv learns of a remote abort within
        2x the heartbeat interval — propagation, not timeout expiry."""
        from raft_tpu.comms.tcp_mailbox import TcpMailbox

        hb = 0.5
        addrs = [f"127.0.0.1:{p}" for p in _free_ports(2)]
        b0 = TcpMailbox(0, addrs, heartbeat_interval=hb)
        b1 = TcpMailbox(1, addrs, heartbeat_interval=hb)
        try:
            woke = {}

            def blocked():
                t0 = time.monotonic()
                try:
                    b0.get(1, 0, 7, timeout=30.0)
                except CommsAbortedError as e:
                    woke["dt"] = time.monotonic() - t0
                    woke["err"] = e

            th = threading.Thread(target=blocked)
            th.start()
            time.sleep(0.2)                  # let the recv block
            t_abort = time.monotonic()
            b1.abort("solver rank died")
            th.join(timeout=5.0)
            assert not th.is_alive()
            assert "solver rank died" in str(woke["err"])
            assert time.monotonic() - t_abort < 2 * hb
        finally:
            b0.close()
            b1.close()


class TestHostElasticKMeans:
    def test_threaded_ranks_agree(self):
        """Three in-process rank views over one shared mailbox run the
        host-driven Lloyd in lock step and return identical results
        (the deterministic-reduction contract host_allreduce makes)."""
        x = _blobs(seed=7, per=200, k=5, d=6)
        params = KMeansParams(n_clusters=5, max_iter=12, tol=1e-12,
                              seed=11)
        mesh = _submesh(3)
        box = _Mailbox()
        results = {}

        def run(r):
            comms = MeshComms(mesh, "data", r, _mailbox=box)
            results[r] = kmeans_fit_elastic(comms, params, x)

        ths = [threading.Thread(target=run, args=(r,)) for r in range(3)]
        for t in ths:
            t.start()
        for t in ths:
            t.join(timeout=60)
        assert len(results) == 3
        c0, i0, n0, _ = results[0]
        for r in (1, 2):
            cr, ir, nr, _ = results[r]
            assert np.array_equal(c0, cr)
            assert (i0, n0) == (ir, nr)
        assert 0 < n0 <= params.max_iter

    def test_checkpoint_every_requires_dir(self):
        comms = MeshComms(_submesh(1), "data", 0, _mailbox=_Mailbox())
        with pytest.raises(ValueError, match="checkpoint_dir"):
            kmeans_fit_elastic(comms, KMeansParams(n_clusters=2),
                               _blobs(per=10, k=2), checkpoint_every=1)


_OK_RE = (r"ELASTIC_WORKER_OK rank=\d+ size=(\d+) n_iter=(\d+) "
          r"inertia=(\S+) crc=(\d+)")


class TestMultiprocessSigkill:
    def test_sigkilled_rank_survived_bit_for_bit(self, tmp_path):
        """The headline acceptance run: 4 real processes, rank 2
        SIGKILLs itself mid-iteration, the 3 survivors detect → abort →
        agree → shrink → resume from the kill-boundary checkpoint and
        finish; a clean 3-process run resumed from that same checkpoint
        reproduces the centroids bit-for-bit."""
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
        worker = os.path.join(_REPO, "tests", "_elastic_worker.py")
        d = str(tmp_path)

        def launch(nproc, mode):
            addrs = [f"127.0.0.1:{p}" for p in _free_ports(nproc)]
            procs = [subprocess.Popen(
                [sys.executable, worker, str(r), d, mode] + addrs,
                cwd=_REPO, env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True)
                for r in range(nproc)]
            outs = []
            try:
                for p in procs:
                    outs.append(p.communicate(timeout=180)[0])
            finally:
                for p in procs:
                    if p.poll() is None:
                        p.kill()
            return procs, outs

        procs, outs = launch(4, "faulted")
        assert procs[2].returncode == -9, outs[2]   # actually SIGKILLed
        assert "ELASTIC_WORKER_SUICIDE" in outs[2]
        results = set()
        for r in (0, 1, 3):
            assert procs[r].returncode == 0, \
                f"survivor {r} failed:\n{outs[r]}"
            m = re.search(_OK_RE, outs[r])
            assert m, outs[r]
            assert m.group(1) == "3"                # finished on 3 ranks
            results.add(m.groups()[1:])
        assert len(results) == 1                    # survivors agree

        # the kill fires at iteration 4 of the faulted run, AFTER the
        # update but before rank 0's boundary save/probe — so the newest
        # complete checkpoint every survivor resumed from is step 4
        from tests._elastic_worker import KILL_AT

        resume = os.path.join(d, f"kmeans_host-{KILL_AT:08d}.ckpt")
        assert os.path.exists(resume), sorted(os.listdir(d))

        procs, outs = launch(3, f"clean:{resume}")
        clean = set()
        for r in range(3):
            assert procs[r].returncode == 0, outs[r]
            m = re.search(_OK_RE, outs[r])
            assert m, outs[r]
            clean.add(m.groups()[1:])
        assert clean == results                     # bit-for-bit
