"""Dynamic-nnz bucketing (SURVEY §7 hard part; round-2 verdict item 6).

Every distinct nnz is a distinct static shape under jit, so a stream of
graphs with varying nnz would retrace every sparse kernel. The bucketing
policy pads indices/data to quarter-octave size classes at construction
(``CSRMatrix.from_scipy`` default; opt out with ``pad=False`` or
``RAFT_TPU_SPARSE_PAD=0``) while ``indptr[-1]`` keeps the logical nnz.

These tests pin BOTH halves of the contract:
- executable reuse: a varying-nnz stream inside one size class compiles
  exactly once (ref contrast: sparse/detail/coo.cuh:38 setSize realloc —
  CUDA kernels are nnz-agnostic, XLA programs are not, so the framework
  must engineer the reuse explicitly);
- numerics: padded and unpadded matrices agree on every consumer family
  (linear ops, selection ops, conversions, solvers).
"""

import numpy as np
import pytest
import scipy.sparse as sp

import jax
import jax.numpy as jnp

from raft_tpu.core.sparse_types import CSRMatrix, nnz_bucket


def _random_csr(n, nnz, seed, pad=None):
    rng = np.random.default_rng(seed)
    rows = rng.integers(0, n, nnz)
    cols = rng.integers(0, n, nnz)
    vals = rng.normal(size=nnz).astype(np.float32)
    a = sp.coo_matrix((vals, (rows, cols)), shape=(n, n)).tocsr()
    a.sum_duplicates()
    return a, CSRMatrix.from_scipy(a, pad=pad)


def test_bucket_classes():
    assert nnz_bucket(0) == 256
    assert nnz_bucket(256) == 256
    assert nnz_bucket(257) == 320          # 256 * 1.25
    for n in (300, 1000, 5000, 123_457, 10_000_000):
        b = nnz_bucket(n)
        assert b >= n
        assert b <= n * 1.25 + 256, (n, b)            # ≤25% overhead
        assert nnz_bucket(b) == b                     # classes are stable


def test_padding_flag_and_roundtrip():
    a, csr = _random_csr(128, 1000, 0)
    assert csr.nnz == nnz_bucket(a.nnz) and csr.nnz > a.nnz
    assert csr.logical_nnz() == a.nnz
    # scipy roundtrip sees only the logical structure
    back = csr.to_scipy()
    assert back.nnz == a.nnz
    assert np.allclose((back - a).toarray(), 0)
    # opt-out
    _, raw = _random_csr(128, 1000, 0, pad=False)
    assert raw.nnz == a.nnz


def test_executable_reuse_across_nnz_stream():
    """10 graphs with nnz spread inside one size class → ONE compile of
    the segment-spmv executable (the verdict's bounded-trace criterion)."""
    from raft_tpu.sparse.linalg import _segment_spmv, spmv

    n = 256
    x = jnp.asarray(np.random.default_rng(9).normal(size=n)
                    .astype(np.float32))
    nnzs = list(range(2100, 2560, 50))   # all bucket to 2560
    before = _segment_spmv._cache_size()
    for i, nnz in enumerate(nnzs):
        a, csr = _random_csr(n, nnz, seed=100 + i)
        assert csr.nnz == nnz_bucket(csr.logical_nnz())
        y = np.asarray(spmv(csr, x))
        np.testing.assert_allclose(y, a @ np.asarray(x), rtol=2e-4,
                                   atol=2e-4)
    added = _segment_spmv._cache_size() - before
    assert added <= 1, f"expected one executable for the stream, got {added}"


def test_unpadded_stream_retraces():
    """Sanity counterpoint: with pad=False every distinct nnz retraces —
    the exact cost the bucketing policy removes."""
    from raft_tpu.sparse.linalg import _segment_spmv, spmv

    n = 256
    x = jnp.zeros((n,), jnp.float32)
    before = _segment_spmv._cache_size()
    for i, nnz in enumerate((3100, 3150, 3200)):
        _, csr = _random_csr(n, nnz, seed=200 + i, pad=False)
        spmv(csr, x)
    assert _segment_spmv._cache_size() - before == 3


@pytest.mark.parametrize("nnz", [700, 2000])
def test_padded_numerics_linear_ops(nnz):
    from raft_tpu.sparse import linalg as sl

    n = 96
    a, padded = _random_csr(n, nnz, seed=3)
    _, raw = _random_csr(n, nnz, seed=3, pad=False)
    x = jnp.asarray(np.random.default_rng(4).normal(size=n)
                    .astype(np.float32))
    b = jnp.asarray(np.random.default_rng(5).normal(size=(n, 8))
                    .astype(np.float32))
    np.testing.assert_allclose(np.asarray(sl.spmv(padded, x)),
                               np.asarray(sl.spmv(raw, x)), rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(sl.spmm(padded, b)),
                               np.asarray(sl.spmm(raw, b)), rtol=1e-5,
                               atol=1e-5)
    for nt in ("l1", "l2", "linf"):
        np.testing.assert_allclose(
            np.asarray(sl.csr_row_norm(padded, nt)),
            np.asarray(sl.csr_row_norm(raw, nt)), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(sl.rows_sum(padded)),
                               np.asarray(sl.rows_sum(raw)), rtol=1e-6)


def test_padded_numerics_selection_and_structure():
    from raft_tpu.sparse import linalg as sl
    from raft_tpu.sparse.convert import csr_to_dense
    from raft_tpu.sparse.matrix import diagonal, select_k, set_diagonal

    n = 64
    # all-NEGATIVE values: a zero pad entry leaking into selection or the
    # dense form would win/show immediately
    rng = np.random.default_rng(7)
    a = sp.random(n, n, density=0.2, random_state=11, format="csr",
                  data_rvs=lambda k: -1.0 - rng.random(k))
    a = a.astype(np.float32)
    padded = CSRMatrix.from_scipy(a, pad=True)
    raw = CSRMatrix.from_scipy(a, pad=False)
    assert padded.nnz > raw.nnz

    vp, ip = select_k(None, padded, k=4, select_min=False)
    vr, ir = select_k(None, raw, k=4, select_min=False)
    np.testing.assert_array_equal(np.asarray(vp), np.asarray(vr))
    np.testing.assert_array_equal(np.asarray(ip), np.asarray(ir))

    np.testing.assert_array_equal(np.asarray(csr_to_dense(padded)),
                                  a.toarray())
    np.testing.assert_array_equal(np.asarray(diagonal(padded)),
                                  np.asarray(diagonal(raw)))
    sd = set_diagonal(padded, -9.0)
    np.testing.assert_array_equal(np.asarray(csr_to_dense(sd)),
                                  np.asarray(csr_to_dense(
                                      set_diagonal(raw, -9.0))))
    # transpose / laplacian ride csr_to_coo, which must depad
    tp = sl.transpose(padded)
    np.testing.assert_array_equal(np.asarray(csr_to_dense(tp)),
                                  a.toarray().T)


def test_padded_sddmm_keeps_invariant():
    """sddmm over a padded pattern must re-zero pad slots — otherwise a
    later spmv over its output sums real dot products into the last row."""
    from raft_tpu.sparse.linalg import sddmm, spmv

    n, k = 48, 16
    rng = np.random.default_rng(8)
    pat = sp.random(n, n, density=0.15, random_state=12,
                    format="csr").astype(np.float32)
    pat.data[:] = 1.0
    padded = CSRMatrix.from_scipy(pat, pad=True)
    raw = CSRMatrix.from_scipy(pat, pad=False)
    a = jnp.asarray(rng.normal(size=(n, k)).astype(np.float32))
    b = jnp.asarray(rng.normal(size=(k, n)).astype(np.float32))
    out_p = sddmm(a, b, padded)
    out_r = sddmm(a, b, raw)
    x = jnp.asarray(rng.normal(size=n).astype(np.float32))
    np.testing.assert_allclose(np.asarray(spmv(out_p, x)),
                               np.asarray(spmv(out_r, x)), rtol=1e-4,
                               atol=1e-4)


def test_padded_solvers_and_graph_ops():
    from raft_tpu.sparse.csr import weak_cc
    from raft_tpu.sparse.ell import from_csr
    from raft_tpu.sparse.ell import spmv as ell_spmv
    from raft_tpu.sparse.solver.mst import mst

    # two disconnected cliques: a phantom pad edge (last row → vertex 0)
    # would merge them in weak_cc and bridge them in the MSF
    n = 40
    half = n // 2
    rng = np.random.default_rng(13)
    dense = np.zeros((n, n), np.float32)
    for blk in (slice(0, half), slice(half, n)):
        w = rng.random((half, half)).astype(np.float32) + 0.5
        dense[blk, blk] = np.triu(w, 1)
    dense = dense + dense.T
    a = sp.csr_matrix(dense)
    padded = CSRMatrix.from_scipy(a, pad=True)
    raw = CSRMatrix.from_scipy(a, pad=False)
    assert padded.nnz > raw.nnz

    labels = np.asarray(weak_cc(None, padded))
    assert len(set(labels.tolist())) == 2
    assert set(labels[:half]) != set(labels[half:])

    fp = mst(None, padded)
    fr = mst(None, raw)
    assert fp.n_edges == fr.n_edges          # 2 trees: 2*(half-1) dir edges
    np.testing.assert_allclose(float(np.sum(np.asarray(fp.weights))),
                               float(np.sum(np.asarray(fr.weights))),
                               rtol=1e-6)

    ell = from_csr(padded)
    x = jnp.asarray(rng.normal(size=n).astype(np.float32))
    np.testing.assert_allclose(np.asarray(ell_spmv(ell, x)),
                               dense @ np.asarray(x), rtol=1e-4, atol=1e-4)


def test_padded_spmv_with_inf_vector():
    """x[0] = inf: pad slots gather x[0], and 0 * inf = nan — the product
    mask must keep padded and unpadded results identical (including the
    ELL slab, whose padded lanes have the same hazard)."""
    from raft_tpu.sparse.ell import from_csr
    from raft_tpu.sparse.ell import spmm as ell_spmm
    from raft_tpu.sparse.ell import spmv as ell_spmv
    from raft_tpu.sparse.linalg import spmm, spmv

    n = 64
    a, padded = _random_csr(n, 900, seed=31)
    _, raw = _random_csr(n, 900, seed=31, pad=False)
    x = np.random.default_rng(32).normal(size=n).astype(np.float32)
    x[0] = np.inf
    xj = jnp.asarray(x)
    yp, yr = np.asarray(spmv(padded, xj)), np.asarray(spmv(raw, xj))
    np.testing.assert_array_equal(np.isnan(yp), np.isnan(yr))
    np.testing.assert_allclose(yp[~np.isnan(yp)], yr[~np.isnan(yr)],
                               rtol=1e-5)
    b = np.random.default_rng(33).normal(size=(n, 4)).astype(np.float32)
    b[0, 0] = np.inf
    bp = np.asarray(spmm(padded, jnp.asarray(b)))
    br = np.asarray(spmm(raw, jnp.asarray(b)))
    np.testing.assert_array_equal(np.isnan(bp), np.isnan(br))
    ell = from_csr(padded)
    ep = np.asarray(ell_spmv(ell, xj))
    np.testing.assert_array_equal(np.isnan(ep), np.isnan(yr))
    em = np.asarray(ell_spmm(ell, jnp.asarray(b)))
    np.testing.assert_array_equal(np.isnan(em), np.isnan(br))


def test_padded_csr_jit_boundary():
    """A padded CSRMatrix must cross jax.jit as a pytree: consumers build
    pad masks from the DEVICE scalar indptr[-1], never a host sync (the
    round-3 review found logical_nnz() raised under tracing)."""
    from raft_tpu.sparse.csr import weak_cc
    from raft_tpu.sparse.linalg import sddmm, spmv
    from raft_tpu.sparse.matrix import set_diagonal

    n = 48
    a, padded = _random_csr(n, 700, seed=41)
    x = jnp.asarray(np.random.default_rng(42).normal(size=n)
                    .astype(np.float32))
    y = jax.jit(spmv)(padded, x)
    np.testing.assert_allclose(np.asarray(y), a @ np.asarray(x),
                               rtol=2e-4, atol=2e-4)
    jax.jit(lambda c: set_diagonal(c, 2.0).data)(padded)
    jax.jit(lambda c: weak_cc(None, c))(padded)
    dm = jnp.asarray(np.random.default_rng(43).normal(size=(n, 8))
                     .astype(np.float32))
    jax.jit(lambda aa, c: sddmm(aa, dm.T, c).data)(dm, padded)
